class Input {
    int[] values;

    int sumValues() {
        int acc = 0;
        for (int v : this.values) {
            acc += v;
        }
        return acc;
    }
}
