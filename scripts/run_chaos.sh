#!/usr/bin/env bash
# Run the chaos + multi-process protocol suites with hard timeouts and
# crash diagnostics.
#
# The multi-host tests drive real jax.distributed process pairs; a
# protocol bug tends to surface as a HANG (a host waiting on a dead
# peer's collective), so every layer here is timeout-bounded:
# - each child pair has an in-test subprocess timeout (~150s);
# - each pytest invocation below gets a wall-clock `timeout` as backstop;
# - on failure, any heartbeat/metrics snapshot files the children left
#   under the run dir are dumped so "where was each host when it stopped"
#   is answerable from CI logs alone.
#
# Usage: scripts/run_chaos.sh [extra pytest args...]
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-chaos.XXXXXX")"
LOG="$RUN_DIR/pytest.log"
# Children inherit this: tests that export heartbeats/metrics land them
# where the failure dump below can find them.
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Per-suite wall-clock backstops (seconds). The suites' own subprocess
# timeouts fire first; these catch a hang in pytest/collection itself.
SINGLE_HOST_BUDGET=600
MULTI_HOST_BUDGET=900
# Elastic N->M resume: three phase-1 training pods + per-scenario resume
# children, each a full facade run — the longest suite of the three.
ELASTIC_BUDGET=1200
# Serving resilience: in-process admission/breaker/swap drills plus the
# supervised-replica SIGKILL / stale-heartbeat subprocess drills (fake
# model children — fast to spawn, so the budget covers hangs, not work).
SERVING_BUDGET=600
# Retrieval stack: store/index round-trips, embed-job resume, and the
# /neighbors + hot-swap embedding-space drills (tiny in-process models
# + the scripted fake extractor).
RETRIEVAL_BUDGET=600
# Cross-host fleet: the host-SIGKILL-under-load convergence drill, the
# canary swap commit/rollback drill and the multi-model/scale e2e —
# each fleet is 2 host supervisors x fake-model replicas, so the
# budget covers hangs, not work.
FLEET_BUDGET=600
# Continuous-training pipeline: the SIGKILL-at-every-stage-boundary
# matrix on the real supervisor (scripted stage bodies — milliseconds
# per attempt) plus the end-to-end promotion/refusal/rollback drill on
# a real 2-host fake-model fleet under client load.
PIPELINE_BUDGET=600
# Horizontally-scaled edge: the router-SIGKILL-under-4-client-load
# zero-failure drill and the N-routers-live coordinated swap +
# host-respawn (artifact, retrieval_index) reconciliation drill — each
# a 2-router x 2-host fake-model fleet, so the budget covers hangs.
EDGE_BUDGET=600
# Tenant-fair serving: the hot-tenant-overload drill — one tenant at a
# multiple of its share against a real server while in-share tenants
# keep serving — plus the in-process fairness-law matrix (fake-model
# servers, so the budget covers hangs, not work).
TENANCY_BUDGET=600

rc=0

run_suite() {
    local budget="$1"; shift
    echo "=== $* (budget ${budget}s) ==="
    timeout -k 20 "$budget" \
        env JAX_PLATFORMS=cpu python -m pytest -q -p no:cacheprovider \
        -p no:xdist -p no:randomly "$@" 2>&1 | tee -a "$LOG"
    local suite_rc=${PIPESTATUS[0]}
    if [ "$suite_rc" -eq 124 ] || [ "$suite_rc" -eq 137 ]; then
        echo "SUITE TIMED OUT (rc=$suite_rc): likely a protocol hang" \
            | tee -a "$LOG"
    fi
    [ "$suite_rc" -ne 0 ] && rc=$suite_rc
    return 0
}

run_suite "$SINGLE_HOST_BUDGET" tests/test_chaos.py "$@"
run_suite "$MULTI_HOST_BUDGET" tests/test_multihost_chaos.py \
    tests/test_multiprocess.py "$@"
run_suite "$ELASTIC_BUDGET" tests/test_elastic_resume.py "$@"
run_suite "$SERVING_BUDGET" tests/test_serving_chaos.py "$@"
run_suite "$RETRIEVAL_BUDGET" tests/test_retrieval.py "$@"
run_suite "$FLEET_BUDGET" tests/test_fleet.py "$@"
run_suite "$PIPELINE_BUDGET" tests/test_pipeline.py "$@"
run_suite "$EDGE_BUDGET" tests/test_edge.py "$@"
run_suite "$TENANCY_BUDGET" tests/test_tenancy.py "$@"

if [ "$rc" -ne 0 ]; then
    echo "=== chaos run FAILED (rc=$rc): dumping diagnostics ==="
    # heartbeat/metrics snapshots the children left behind: each says
    # status + step + epoch at the moment its writer stopped
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
