#!/usr/bin/env bash
# Run the serving load-generator bench with a hard timeout and crash
# diagnostics, matching scripts/run_chaos.sh conventions.
#
# The bench drives a real HTTP server + warm extractor pool + batcher;
# a serving bug tends to surface as a HANG (a request waiting on a dead
# worker or a stuck batcher dispatch), so the run is wall-clock bounded
# and, on failure, any metrics/heartbeat snapshots the bench left under
# the run dir are dumped so "where was the server when it stopped" is
# answerable from CI logs alone.
#
# Usage: scripts/run_serving_bench.sh [extra args passed to the bench]
#        scripts/run_serving_bench.sh resilience   # PR-9 overload +
#        kill-replica scenarios -> results/serving_resilience.json
#        scripts/run_serving_bench.sh mixed        # PR-18 continuous-
#        batching + head-dispatch paired A/B -> results/serving_mixed.json
#        scripts/run_serving_bench.sh tenants      # PR-20 tenancy
#        overhead + hot-tenant fairness drill -> results/serving_tenants.json
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-serving.XXXXXX")"
LOG="$RUN_DIR/bench.log"
# The bench exports a Prometheus snapshot here at exit; on failure the
# dump below surfaces it (SLO histograms, pool/cache/batcher counters).
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Wall-clock backstop: the bench itself finishes in ~2 minutes on a
# laptop CPU; 600s catches a pool/batcher/drain hang, not a slow run.
BUDGET=600

echo "=== serving bench (budget ${BUDGET}s) ==="
timeout -k 20 "$BUDGET" \
    env JAX_PLATFORMS=cpu python experiments/serving_bench.py "$@" \
    2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "BENCH TIMED OUT (rc=$rc): likely a serving hang" | tee -a "$LOG"
fi

if [ "$rc" -ne 0 ]; then
    echo "=== serving bench FAILED (rc=$rc): dumping diagnostics ==="
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
