#!/usr/bin/env python3
"""Static SLO documentation check (tier-1 via tests/test_slo_doc.py) —
the sibling of check_metrics_doc.py / check_knobs_doc.py for the SLO
surface.

Every objective the engine can declare (obs/slo.py
`objectives_from_config` — `SloObjective(name="...")` with a literal
name) must have a row in the README's SLO reference (the table between
the `<!-- slo-table:begin -->` / `<!-- slo-table:end -->` markers in
the "SLO & history" section), and every SLO named in that table must
still be declared — a new objective cannot ship undocumented, and the
table cannot rot as objectives are renamed away.

The walk also cross-checks the alert severities: every severity in
`BURN_WINDOWS` must appear (backticked) inside the marked section, so
the burn-rate windows table cannot silently drift from the engine.

Names are extracted by AST walk; a non-literal `name=` in an
`SloObjective(...)` call is an ERROR — a dynamically-named objective
cannot be statically checked.

Usage: python scripts/check_slo_doc.py  (exit 0 = consistent)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import List, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SLO_PATH = os.path.join(REPO_ROOT, "code2vec_tpu", "obs", "slo.py")
README = os.path.join(REPO_ROOT, "README.md")

BEGIN_MARKER = "<!-- slo-table:begin -->"
END_MARKER = "<!-- slo-table:end -->"

# the SLO name is the FIRST cell of a table row — backticked names
# elsewhere in a row are cross-references, not declarations
_TABLE_SLO_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`", re.MULTILINE)


def _literal(node) -> object:
    return node.value if isinstance(node, ast.Constant) else None


def declared_slos() -> Set[str]:
    """Literal `name=` values of every SloObjective(...) call in
    obs/slo.py. Raises SystemExit on a non-literal name."""
    with open(SLO_PATH) as f:
        tree = ast.parse(f.read(), filename=SLO_PATH)
    names: Set[str] = set()
    errors: List[str] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "SloObjective"):
            continue
        name = None
        for kw in node.keywords:
            if kw.arg == "name":
                name = _literal(kw.value)
        if node.args:  # positional name
            name = _literal(node.args[0])
        if not isinstance(name, str):
            errors.append(
                f"obs/slo.py:{node.lineno}: non-literal name in "
                f"SloObjective(...) — objective names must be string "
                f"literals for the doc check to see them")
            continue
        names.add(name)
    if errors:
        raise SystemExit("\n".join(errors))
    if not names:
        raise SystemExit(
            "obs/slo.py: no SloObjective(name=...) declarations found "
            "— did the construction site move out of AST reach?")
    return names


def declared_severities() -> Set[str]:
    """First element of every BURN_WINDOWS tuple, by AST."""
    with open(SLO_PATH) as f:
        tree = ast.parse(f.read(), filename=SLO_PATH)
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Assign) and node.targets
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "BURN_WINDOWS"):
            continue
        severities: Set[str] = set()
        if isinstance(node.value, (ast.Tuple, ast.List)):
            for elt in node.value.elts:
                if (isinstance(elt, (ast.Tuple, ast.List)) and elt.elts
                        and isinstance(_literal(elt.elts[0]), str)):
                    severities.add(_literal(elt.elts[0]))
        if severities:
            return severities
    raise SystemExit("obs/slo.py: no literal BURN_WINDOWS tuple found")


def _marked_section() -> str:
    with open(README) as f:
        text = f.read()
    try:
        begin = text.index(BEGIN_MARKER) + len(BEGIN_MARKER)
        end = text.index(END_MARKER, begin)
    except ValueError:
        raise SystemExit(
            f"README.md is missing the {BEGIN_MARKER} / {END_MARKER} "
            f"markers around the SLO reference table (README "
            f"'SLO & history')")
    return text[begin:end]


def documented_slos() -> Set[str]:
    return set(_TABLE_SLO_RE.findall(_marked_section()))


def check() -> List[str]:
    """Returns a list of problems (empty = consistent)."""
    declared = declared_slos()
    severities = declared_severities()
    # the burn-windows table lives inside the same markers and its
    # first cell is the severity — not a stale objective
    documented = documented_slos() - severities
    section = _marked_section()
    problems: List[str] = []
    for name in sorted(declared - documented):
        problems.append(
            f"UNDOCUMENTED: SLO {name!r} (obs/slo.py "
            f"objectives_from_config) is missing from the README SLO "
            f"reference table")
    for name in sorted(documented - declared):
        problems.append(
            f"STALE DOC: SLO {name!r} appears in the README SLO "
            f"reference table but is not declared in obs/slo.py")
    for severity in sorted(severities):
        if f"`{severity}`" not in section:
            problems.append(
                f"UNDOCUMENTED: burn-rate severity {severity!r} "
                f"(obs/slo.py BURN_WINDOWS) is not mentioned in the "
                f"README SLO section")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} SLO-documentation problem(s). "
              f"Update the README 'SLO & history' table (between the "
              f"slo-table markers).")
        return 1
    print(f"OK: {len(declared_slos())} SLO objective(s) and "
          f"{len(declared_severities())} severity(ies) all documented, "
          f"no stale table entries.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
