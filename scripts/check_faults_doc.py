#!/usr/bin/env python3
"""Static fault-point documentation check (tier-1 via
tests/test_faults_doc.py) — check_metrics_doc.py's sibling for the
chaos registry.

Every `fault_point("name")` call site under `code2vec_tpu/` must be
documented in the registry docstring of `utils/faults.py` (the
`- \\`name\\`` bullets), and every documented name must still be
crossed somewhere in the code — a new fault point cannot ship
undocumented (the chaos suite arms points BY NAME from that registry),
and the registry cannot keep names the code dropped (an armed typo'd/
stale point silently injects nothing, invalidating the drill).

Call sites are extracted by AST walk: any call whose callee is named
`fault_point` (bare or attribute) with a literal first argument — the
repo convention. A non-literal first argument is an ERROR: a
dynamically-named fault point cannot be statically checked or armed
from the registry.

Usage: python scripts/check_faults_doc.py  (exit 0 = consistent)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "code2vec_tpu")
REGISTRY = os.path.join(PACKAGE_DIR, "utils", "faults.py")

# the registry module itself defines fault_point; its docstring is the
# documentation side, so its code is not a call-site source
_IGNORED_FILES = {os.path.join("utils", "faults.py")}

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# a registry entry is a bullet whose FIRST token is a backticked name
# (prose mentions elsewhere in the docstring — spec grammar, examples —
# are not declarations)
_DOC_NAME_RE = re.compile(r"^- `([a-z][a-z0-9_]*)`", re.MULTILINE)


def crossed_fault_points() -> Dict[str, List[str]]:
    """{fault-point name: [files crossing it]} from an AST walk of the
    package. Raises SystemExit on a dynamic (non-literal) name."""
    names: Dict[str, List[str]] = {}
    errors: List[str] = []
    for root, _dirs, files in os.walk(PACKAGE_DIR):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PACKAGE_DIR)
            if rel in _IGNORED_FILES:
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call) and node.args):
                    continue
                func = node.func
                callee = (func.id if isinstance(func, ast.Name)
                          else func.attr if isinstance(func,
                                                       ast.Attribute)
                          else None)
                if callee != "fault_point":
                    continue
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)
                        and _NAME_RE.match(arg.value)):
                    names.setdefault(arg.value, []).append(rel)
                    continue
                errors.append(
                    f"{rel}:{node.lineno}: non-literal fault-point "
                    f"name in fault_point(...) — the chaos suite arms "
                    f"points by name from the utils/faults.py "
                    f"registry, so the name must be a string literal")
    if errors:
        raise SystemExit("\n".join(errors))
    return names


def documented_fault_points() -> Set[str]:
    """Backticked bullet names in the utils/faults.py registry
    docstring."""
    with open(REGISTRY) as f:
        tree = ast.parse(f.read(), filename=REGISTRY)
    doc = ast.get_docstring(tree)
    if not doc:
        raise SystemExit(f"{REGISTRY} has no module docstring — the "
                         f"fault-point registry lives there")
    return set(_DOC_NAME_RE.findall(doc))


def check() -> List[str]:
    """Returns a list of problems (empty = consistent)."""
    crossed = crossed_fault_points()
    documented = documented_fault_points()
    problems: List[str] = []
    for name in sorted(set(crossed) - documented):
        problems.append(
            f"UNDOCUMENTED: fault point {name} (crossed in "
            f"{', '.join(sorted(set(crossed[name])))}) is missing from "
            f"the utils/faults.py registry docstring")
    for name in sorted(documented - set(crossed)):
        problems.append(
            f"STALE DOC: fault point {name} appears in the "
            f"utils/faults.py registry docstring but no fault_point() "
            f"call site crosses it under code2vec_tpu/")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} fault-point documentation "
              f"problem(s). Update the registry docstring in "
              f"code2vec_tpu/utils/faults.py.")
        return 1
    print(f"OK: {len(crossed_fault_points())} crossed fault points "
          f"all documented, no stale registry entries.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
