#!/usr/bin/env bash
# Run the retrieval-stack bench with a hard timeout and crash
# diagnostics, matching scripts/run_chaos.sh conventions.
#
# The bench extracts a generated-Java corpus with the real native
# extractor, runs the batch embedding job, builds the IVF index,
# measures recall@10 across the nprobe sweep against the brute-force
# ground truth, and drives POST /neighbors over real HTTP — a hang
# usually means a wedged extractor child or a stuck serving dispatch,
# so the run is wall-clock bounded and, on failure, any metrics
# snapshots the bench left under the run dir are dumped.
#
# Usage: scripts/run_retrieval_bench.sh [extra args passed to the bench]
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-retrieval.XXXXXX")"
LOG="$RUN_DIR/bench.log"
# The bench exports a Prometheus snapshot here at exit; on failure the
# dump below surfaces it (embed phase histograms, search latency,
# serving SLO histograms).
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Wall-clock backstop: extraction + embed + index + recall sweep +
# serving load finish in a few minutes on a dev CPU; the timeout
# catches an extractor/serving hang, not a slow run.
BUDGET=1800

echo "=== retrieval bench (budget ${BUDGET}s) ==="
timeout -k 20 "$BUDGET" \
    env JAX_PLATFORMS=cpu python experiments/retrieval_bench.py "$@" \
    2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "BENCH TIMED OUT (rc=$rc): likely an extractor/serving hang" \
        | tee -a "$LOG"
fi

if [ "$rc" -ne 0 ]; then
    echo "=== retrieval bench FAILED (rc=$rc): dumping diagnostics ==="
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
