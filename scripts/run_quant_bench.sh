#!/usr/bin/env bash
# Run the quantized-release-artifact bench with a hard timeout and crash
# diagnostics, matching scripts/run_chaos.sh conventions.
#
# The bench trains (or reuses) the accuracy-corpus model, evaluates the
# fp32/blockwise/int8 arms, measures the AOT cold start, and drives the
# PR-7 serving harness before/after — a hang usually means a wedged
# serving dispatch or a stuck eval batch, so the run is wall-clock
# bounded and, on failure, any metrics snapshots the bench left under
# the run dir are dumped.
#
# Usage: scripts/run_quant_bench.sh [extra args passed to the bench]
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-quant.XXXXXX")"
LOG="$RUN_DIR/bench.log"
# The bench exports a Prometheus snapshot here at exit; on failure the
# dump below surfaces it (eval counters, serving SLO histograms).
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Wall-clock backstop: a cold run (corpus build + ~10-epoch training +
# four eval arms + serving load) finishes well inside 3600s on a dev
# CPU; the timeout catches a serving/eval hang, not a slow run. Cached
# reruns (--root kept) finish in minutes.
BUDGET=3600

echo "=== quant bench (budget ${BUDGET}s) ==="
timeout -k 20 "$BUDGET" \
    env JAX_PLATFORMS=cpu python experiments/quant_bench.py "$@" \
    2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "BENCH TIMED OUT (rc=$rc): likely an eval/serving hang" | tee -a "$LOG"
fi

if [ "$rc" -ne 0 ]; then
    echo "=== quant bench FAILED (rc=$rc): dumping diagnostics ==="
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
