#!/usr/bin/env bash
# Run the roofline-PR benches with a hard timeout and crash
# diagnostics, matching scripts/run_chaos.sh conventions:
#
#   1. the 2-host bucketed all-reduce overlap A/B
#      (experiments/overlap_bench.py -> experiments/results/overlap.json
#       + the BENCH_ROOFLINE.md overlap section);
#   2. the `roofline` pytest marker (overlap parity, bucket-planner
#      laws, fp8/int4 round-trip bounds, MIPS-head agreement pins).
#
# The overlap bench drives a real 2-process jax.distributed pair —
# a collectives bug tends to surface as a HANG (one host waiting on a
# dead peer's all-reduce), so the run is wall-clock bounded and, on
# failure, any metrics snapshots left under the run dir are dumped.
#
# Usage: scripts/run_roofline_bench.sh [extra args passed to the bench]
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-roofline.XXXXXX")"
LOG="$RUN_DIR/bench.log"
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Wall-clock backstops: the 2-host A/B finishes in ~2 min on a dev CPU
# (two arms x compile + 20 steps each, per process); the marker suite
# in ~1 min. The timeouts catch a gloo hang, not a slow run.
BENCH_BUDGET=900
TEST_BUDGET=600
rc=0

echo "=== overlap A/B bench (budget ${BENCH_BUDGET}s) ==="
timeout -k 20 "$BENCH_BUDGET" \
    env JAX_PLATFORMS=cpu python experiments/overlap_bench.py "$@" \
    2>&1 | tee "$LOG"
bench_rc=${PIPESTATUS[0]}
if [ "$bench_rc" -eq 124 ] || [ "$bench_rc" -eq 137 ]; then
    echo "BENCH TIMED OUT (rc=$bench_rc): likely a collective hang" \
        | tee -a "$LOG"
fi
[ "$bench_rc" -ne 0 ] && rc=$bench_rc

echo "=== roofline marker suite (budget ${TEST_BUDGET}s) ==="
timeout -k 20 "$TEST_BUDGET" \
    env JAX_PLATFORMS=cpu python -m pytest -q -m roofline \
    -p no:cacheprovider -p no:xdist -p no:randomly tests/ \
    2>&1 | tee -a "$LOG"
test_rc=${PIPESTATUS[0]}
[ "$test_rc" -ne 0 ] && rc=$test_rc

if [ "$rc" -ne 0 ]; then
    echo "=== roofline run FAILED (rc=$rc): dumping diagnostics ==="
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
