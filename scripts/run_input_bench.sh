#!/usr/bin/env bash
# Run the pod-scale input-pipeline benches with a hard timeout and
# crash diagnostics, matching scripts/run_roofline_bench.sh:
#
#   1. the input grid (simulated hosts x shards x double-buffer) plus
#      the 2-host in-backward overlap A/B
#      (experiments/input_bench.py -> experiments/results/input.json
#       + the BENCH_INPUT.md sections);
#   2. the fast multi-shard reader suite (tests/test_sharded_corpus.py
#      — the cursor-law pins the bench numbers rest on).
#
# The in-backward A/B drives a real 2-process jax.distributed pair —
# a collectives bug tends to surface as a HANG, so the run is
# wall-clock bounded and failures dump any metrics snapshots.
#
# Usage: scripts/run_input_bench.sh [extra args passed to the bench]
set -u -o pipefail

cd "$(dirname "$0")/.."

RUN_DIR="$(mktemp -d "${TMPDIR:-/tmp}/c2v-input.XXXXXX")"
LOG="$RUN_DIR/bench.log"
export C2V_CHAOS_DIAG_DIR="$RUN_DIR"

# Wall-clock backstops: the grid is 18 arms x best-of-3 short runs
# (~3 min on a dev CPU); the 2-process A/B compiles four overlap
# programs (~3 min). The timeouts catch a gloo hang, not a slow run.
BENCH_BUDGET=900
TEST_BUDGET=300
rc=0

echo "=== input grid + in-backward A/B (budget ${BENCH_BUDGET}s) ==="
timeout -k 20 "$BENCH_BUDGET" \
    env JAX_PLATFORMS=cpu python experiments/input_bench.py "$@" \
    2>&1 | tee "$LOG"
bench_rc=${PIPESTATUS[0]}
if [ "$bench_rc" -eq 124 ] || [ "$bench_rc" -eq 137 ]; then
    echo "BENCH TIMED OUT (rc=$bench_rc): likely a collective hang" \
        | tee -a "$LOG"
fi
[ "$bench_rc" -ne 0 ] && rc=$bench_rc

echo "=== multi-shard reader suite (budget ${TEST_BUDGET}s) ==="
timeout -k 20 "$TEST_BUDGET" \
    env JAX_PLATFORMS=cpu python -m pytest -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    tests/test_sharded_corpus.py 2>&1 | tee -a "$LOG"
test_rc=${PIPESTATUS[0]}
[ "$test_rc" -ne 0 ] && rc=$test_rc

if [ "$rc" -ne 0 ]; then
    echo "=== input bench FAILED (rc=$rc): dumping diagnostics ==="
    find "$RUN_DIR" -maxdepth 4 -type f \
        \( -name '*heartbeat*.json' -o -name 'hb*.json' \
           -o -name '*.prom' -o -name '*metrics*' \) 2>/dev/null \
        | while read -r f; do
        echo "--- $f ---"
        cat "$f"
        echo
    done
    echo "full log: $LOG"
else
    rm -rf "$RUN_DIR"
fi
exit "$rc"
