#!/usr/bin/env python3
"""Static CLI-knob documentation check (tier-1 via
tests/test_knobs_doc.py) — the sibling of check_metrics_doc.py /
check_faults_doc.py for the operator knob surface.

Every long flag registered in code2vec_tpu/cli.py must appear in the
README's canonical knob reference (the table between the
`<!-- knobs-table:begin -->` / `<!-- knobs-table:end -->` markers in
the "CLI knob reference" section), and every flag in that table must
still be registered — a new knob cannot ship undocumented, and the
table cannot rot as knobs are renamed away.

Registered flags are extracted by AST walk: any
`<parser>.add_argument("--name", ...)` call with literal option
strings. A non-literal option string is an ERROR: a dynamically-named
flag cannot be statically checked.

The walk also checks the CLI -> Config WIRING: every flag's argparse
dest (explicit `dest=` literal, else the long option name) must be a
Config field (config.py) or appear in the closed `_ARGS_ONLY`
allowlist of args config_from_args consumes by hand — so a new flag
whose value silently never lands anywhere fails here, not in
production.

Usage: python scripts/check_knobs_doc.py  (exit 0 = consistent)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set, Tuple

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLI_PATH = os.path.join(REPO_ROOT, "code2vec_tpu", "cli.py")
CONFIG_PATH = os.path.join(REPO_ROOT, "code2vec_tpu", "config.py")
README = os.path.join(REPO_ROOT, "README.md")

BEGIN_MARKER = "<!-- knobs-table:begin -->"
END_MARKER = "<!-- knobs-table:end -->"

_FLAG_RE = re.compile(r"^--[a-z][a-z0-9_-]*$")  # dash: reference
# compat (--logs-path); new knobs use lower_snake_case
# the flag is the FIRST cell of a table row — backticked flags
# elsewhere in a row are cross-references, not declarations
_TABLE_FLAG_RE = re.compile(r"^\|\s*`(--[a-z][a-z0-9_-]*)`",
                            re.MULTILINE)

# argparse dests config_from_args consumes by HAND instead of piping
# into a same-named Config field (renames, derived fields, pure-CLI
# switches). Closed set: a new flag must either match a Config field
# by dest or be deliberately added here.
_ARGS_ONLY = {
    # renamed on the way into Config (reference-CLI compat)
    "load_path",              # -> Config.model_load_path
    "save_path",              # -> Config.model_save_path
    "data_path",              # -> Config.train_data_path_prefix
    "test_path",              # -> Config.test_data_path
    "batch_size",             # -> train_batch_size AND test_batch_size
    "epochs",                 # -> Config.num_train_epochs
    "sparse_embedding_update",  # -> use_sparse_embedding_update
    # negative flags flipping a default-on Config field (argparse
    # cannot express that as a same-named dest)
    "no_quantize",            # -> release_quantize = False
    "no_aot",                 # -> release_aot = False
    "no_cursor_resume",       # -> cursor_resume = False
    "no_packed_data",         # -> use_packed_data = False
    "gspmd",                  # -> use_manual_tp_kernels = False
    "fleet_no_affinity",      # -> fleet_cache_affinity = False
    # reference-CLI compat no-op (the reference picked keras/tf here;
    # this framework is jax-only and accepts-and-ignores the flag)
    "dl_framework",
}


def _literal(node) -> object:
    return node.value if isinstance(node, ast.Constant) else None


def registered_flags() -> Dict[str, List[Tuple[int, str]]]:
    """{long flag: [(lineno, dest)]} from an AST walk of cli.py.
    Raises SystemExit on a non-literal option string."""
    with open(CLI_PATH) as f:
        tree = ast.parse(f.read(), filename=CLI_PATH)
    flags: Dict[str, List[Tuple[int, str]]] = {}
    errors: List[str] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
                and node.args):
            continue
        options: List[str] = []
        for arg in node.args:
            value = _literal(arg)
            if not isinstance(value, str):
                errors.append(
                    f"cli.py:{node.lineno}: non-literal option string "
                    f"in add_argument(...) — flags must be string "
                    f"literals for the doc check to see them")
                options = []
                break
            if value.startswith("-"):
                options.append(value)
            else:
                break  # positional argument: not a knob
        longs = [o for o in options if o.startswith("--")]
        if not longs:
            continue
        dest = None
        for kw in node.keywords:
            if kw.arg == "dest":
                dest = _literal(kw.value)
        if dest is None:
            dest = longs[0].lstrip("-").replace("-", "_")
        for flag in longs:
            if not _FLAG_RE.match(flag):
                errors.append(
                    f"cli.py:{node.lineno}: flag {flag!r} does not "
                    f"match the --lower_snake_case convention")
                continue
            flags.setdefault(flag, []).append((node.lineno, dest))
    if errors:
        raise SystemExit("\n".join(errors))
    return flags


def config_fields() -> Set[str]:
    """Annotated field names of the Config dataclass, by AST (no
    package import — the checker must run anywhere)."""
    with open(CONFIG_PATH) as f:
        tree = ast.parse(f.read(), filename=CONFIG_PATH)
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return {stmt.target.id for stmt in node.body
                    if isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)}
    raise SystemExit("config.py: no `class Config` found")


def documented_flags() -> Set[str]:
    """Backticked flags inside the README's marked knobs table."""
    with open(README) as f:
        text = f.read()
    try:
        begin = text.index(BEGIN_MARKER) + len(BEGIN_MARKER)
        end = text.index(END_MARKER, begin)
    except ValueError:
        raise SystemExit(
            f"README.md is missing the {BEGIN_MARKER} / {END_MARKER} "
            f"markers around the knob reference table "
            f"(README 'CLI knob reference')")
    return set(_TABLE_FLAG_RE.findall(text[begin:end]))


def check() -> List[str]:
    """Returns a list of problems (empty = consistent)."""
    registered = registered_flags()
    documented = documented_flags()
    fields = config_fields()
    problems: List[str] = []
    for flag in sorted(set(registered) - documented):
        lines = ", ".join(str(ln) for ln, _ in registered[flag])
        problems.append(
            f"UNDOCUMENTED: {flag} (cli.py:{lines}) is missing from "
            f"the README knob reference table")
    for flag in sorted(documented - set(registered)):
        problems.append(
            f"STALE DOC: {flag} appears in the README knob reference "
            f"table but is not registered in cli.py")
    for flag in sorted(registered):
        for lineno, dest in registered[flag]:
            if dest not in fields and dest not in _ARGS_ONLY:
                problems.append(
                    f"UNWIRED: {flag} (cli.py:{lineno}) has dest "
                    f"{dest!r} which is neither a Config field nor in "
                    f"check_knobs_doc._ARGS_ONLY — its value would "
                    f"silently go nowhere")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} knob-documentation problem(s). "
              f"Update the README 'CLI knob reference' table "
              f"(between the knobs-table markers).")
        return 1
    print(f"OK: {len(registered_flags())} CLI flags all documented, "
          f"wired to Config, no stale table entries.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
