#!/usr/bin/env python3
"""Static metric-name documentation check (tier-1 via
tests/test_metrics_doc.py).

Every metric registered under `code2vec_tpu/` must appear in the
README's canonical metrics reference (the table between the
`<!-- metrics-table:begin -->` / `<!-- metrics-table:end -->` markers
in the "Telemetry" section), and every name in that table must still be
registered somewhere in the code — new metrics cannot ship
undocumented, and the table cannot rot as metrics are renamed away.

Registered names are extracted by AST walk: any call
`<something>.counter("name", ...)` / `.gauge(...)` / `.histogram(...)`
with a literal first argument (the repo convention — obs module
helpers, MetricsRegistry methods and the tracer's internal handles all
match). A non-literal first argument is an ERROR: a dynamically-named
metric cannot be statically checked, so the name must be lifted into a
literal (labels are the supported dynamic dimension).

Usage: python scripts/check_metrics_doc.py  (exit 0 = consistent)
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, Set

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE_DIR = os.path.join(REPO_ROOT, "code2vec_tpu")
README = os.path.join(REPO_ROOT, "README.md")

BEGIN_MARKER = "<!-- metrics-table:begin -->"
END_MARKER = "<!-- metrics-table:end -->"

_REGISTER_METHODS = {"counter", "gauge", "histogram"}
# registry-internal plumbing whose first positional arg is a metric
# name but which is always reached through the public helpers above
_IGNORED_FILES = {os.path.join("obs", "metrics.py"),
                  os.path.join("obs", "__init__.py")}

# Dynamically-named registrations the AST walk cannot see through,
# declared here as the closed set of names they produce (the evaluator
# turns every ModelEvaluationResults.tb_scalars() tag into an
# `eval_<tag>` gauge). A file listed here may use non-literal names;
# the names still participate in BOTH check directions, so this list
# rots loudly (a vanished gauge becomes a STALE DOC error once dropped
# from the README, and an undeclared new tag shows up UNDOCUMENTED in
# any scrape-diff review).
_DYNAMIC_REGISTRATIONS = {
    os.path.join("evaluation", "evaluator.py"): (
        "eval_top1_acc", "eval_topk_acc", "eval_subtoken_precision",
        "eval_subtoken_recall", "eval_subtoken_f1", "eval_loss"),
    # tenant_metric() registers the three tenant-labeled families with
    # the name as a variable behind a ValueError guard that pins this
    # exact closed set (serving/tenancy.py _TENANT_METRICS; the guard
    # is itself asserted in tests/test_tenancy.py)
    os.path.join("serving", "tenancy.py"): (
        "serving_requests_total", "serving_requests_shed_total",
        "serving_request_seconds"),
}

_METRIC_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")
# the metric name is the FIRST cell of a table row — backticked names
# elsewhere in the row are label keys / prose, not declarations
_TABLE_NAME_RE = re.compile(r"^\|\s*`([a-z][a-z0-9_]*)`\s*\|",
                            re.MULTILINE)


def registered_metric_names() -> Dict[str, List[str]]:
    """{metric name: [files registering it]} from an AST walk of the
    package. Raises SystemExit on a dynamic (non-literal) name."""
    names: Dict[str, List[str]] = {}
    errors: List[str] = []
    for root, _dirs, files in os.walk(PACKAGE_DIR):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(root, fn)
            rel = os.path.relpath(path, PACKAGE_DIR)
            if rel in _IGNORED_FILES:
                continue
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in _REGISTER_METHODS
                        and node.args):
                    continue
                # skip x.method() calls that are clearly not metric
                # registration: first arg must be a string literal or
                # it is an error
                arg = node.args[0]
                if (isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    if _METRIC_NAME_RE.match(arg.value):
                        names.setdefault(arg.value, []).append(rel)
                    continue
                if rel in _DYNAMIC_REGISTRATIONS:
                    continue  # declared below, names added after walk
                errors.append(
                    f"{rel}:{node.lineno}: non-literal metric name in "
                    f".{node.func.attr}(...) — lift the name into a "
                    f"string literal (labels are the dynamic "
                    f"dimension), or declare the closed name set in "
                    f"check_metrics_doc._DYNAMIC_REGISTRATIONS")
    if errors:
        raise SystemExit("\n".join(errors))
    for rel, declared in _DYNAMIC_REGISTRATIONS.items():
        for name in declared:
            names.setdefault(name, []).append(rel)
    return names


def documented_metric_names() -> Set[str]:
    """Backticked names inside the README's marked metrics table."""
    with open(README) as f:
        text = f.read()
    try:
        begin = text.index(BEGIN_MARKER) + len(BEGIN_MARKER)
        end = text.index(END_MARKER, begin)
    except ValueError:
        raise SystemExit(
            f"README.md is missing the {BEGIN_MARKER} / {END_MARKER} "
            f"markers around the metrics reference table "
            f"(README 'Telemetry')")
    return set(_TABLE_NAME_RE.findall(text[begin:end]))


def check() -> List[str]:
    """Returns a list of problems (empty = consistent)."""
    registered = registered_metric_names()
    documented = documented_metric_names()
    problems: List[str] = []
    for name in sorted(set(registered) - documented):
        problems.append(
            f"UNDOCUMENTED: {name} (registered in "
            f"{', '.join(sorted(set(registered[name])))}) is missing "
            f"from the README metrics table")
    for name in sorted(documented - set(registered)):
        problems.append(
            f"STALE DOC: {name} appears in the README metrics table "
            f"but is not registered anywhere under code2vec_tpu/")
    return problems


def main() -> int:
    problems = check()
    if problems:
        print("\n".join(problems))
        print(f"\n{len(problems)} metric-documentation problem(s). "
              f"Update the README 'Telemetry' metrics table "
              f"(between the metrics-table markers).")
        return 1
    print(f"OK: {len(registered_metric_names())} registered metric "
          f"names all documented, no stale table entries.")
    return 0


if __name__ == "__main__":
    sys.exit(main())
