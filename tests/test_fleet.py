"""Cross-host serving fleet suite (code2vec_tpu/serving/fleet/):
health-gated router (weighted routing, deadline-bounded retry, trace
propagation, multi-model isolation), control-plane scaling policy
(hysteresis, bounds, cooldown), canary-first coordinated hot-swap
(commit / halt / rollback), plus the satellite pins — jittered
Retry-After, flight-dump retention, telemetry admin verbs.

Fast tests run in tier-1 on stubs; the multi-host chaos drills (real
ControlPlane + router over real Supervisor subprocesses running
fake-model replicas) are marked `slow` and run via scripts/run_chaos.sh
with their own budget.
"""

import http.server
import json
import os
import signal
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from code2vec_tpu import obs
from code2vec_tpu.config import Config

from test_serving import FAKE_EXTRACTOR, _counter_value

pytestmark = pytest.mark.fleet

HERE = os.path.dirname(os.path.abspath(__file__))
FLEET_HOST = os.path.join(HERE, "chaos_fleet_host.py")


def _post(port, path, body, headers=None, timeout=60):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=body.encode(),
        method="POST", headers=dict({"Content-Type": "text/plain"},
                                    **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _get(port, path, timeout=30):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ------------------------------------------------- satellite: jitter


def test_retry_after_jitter_bounds_and_varies():
    """503 Retry-After carries jitter so a fleet-wide shed does not
    teach every client the same retry instant (satellite pin)."""
    from code2vec_tpu.serving.admission import retry_after_seconds

    values = {retry_after_seconds(4.0) for _ in range(200)}
    assert all(4 <= v <= 6 for v in values), values  # ceil(4..6)
    assert len(values) >= 2, "no jitter: every client retries at once"
    # floor: never below 1 second, even for tiny bases
    assert all(retry_after_seconds(0.0) >= 1 for _ in range(20))
    # jitter disabled -> exact ceil of the base
    assert retry_after_seconds(2.5, jitter_frac=0.0) == 3


# --------------------------------------- satellite: flight retention


def test_flight_dump_retention_deletes_oldest_past_cap(tmp_path):
    from code2vec_tpu.obs.flight import FlightRecorder

    rec = FlightRecorder(capacity=8)
    rec.configure(dump_dir=str(tmp_path), max_dumps=3)
    rec.event("x")
    paths = []
    for i in range(5):
        p = rec.dump(reason=f"r{i}",
                     path=str(tmp_path / f"flight-0000{i}-r{i}.json"))
        os.utime(p, (i, i))  # deterministic mtime order
        paths.append(p)
    left = sorted(f.name for f in tmp_path.glob("flight-*.json"))
    assert len(left) == 3
    # newest kept, oldest deleted
    assert os.path.basename(paths[-1]) in left
    assert os.path.basename(paths[0]) not in left
    # cap 0 = unbounded (the pre-knob behavior)
    rec.configure(max_dumps=0)
    for i in range(5, 8):
        rec.dump(reason=f"r{i}",
                 path=str(tmp_path / f"flight-0000{i}-r{i}.json"))
    assert len(list(tmp_path.glob("flight-*.json"))) == 6


# ------------------------------------------------- quantile helpers


def test_quantile_from_buckets_window_and_edges():
    from code2vec_tpu.serving.telemetry import quantile_from_buckets

    cur = {"0.1": 10.0, "0.5": 90.0, "1": 100.0, "+Inf": 100.0}
    # p95 rank 95 lands in the (0.5, 1] bucket: 0.5 + 0.5 * 5/10
    assert quantile_from_buckets(cur, None, 0.95) == pytest.approx(0.75)
    # windowed: identical prev snapshot -> empty window -> None
    assert quantile_from_buckets(cur, cur, 0.95) is None
    # window with only fast samples since prev
    nxt = {"0.1": 30.0, "0.5": 110.0, "1": 120.0, "+Inf": 120.0}
    assert quantile_from_buckets(nxt, cur, 0.5) <= 0.5
    # quantile in +Inf -> largest finite bound (conservative floor)
    assert quantile_from_buckets(
        {"0.1": 0.0, "+Inf": 10.0}, None, 0.5) == 0.1
    assert quantile_from_buckets({}, None, 0.5) is None


# ------------------------------------------------------ router units


def test_weighted_order_prefers_heavy_drops_zero():
    from code2vec_tpu.serving.fleet.router import weighted_order

    firsts = [weighted_order([(1.0, "a"), (0.05, "b"), (0.0, "c")])[0]
              for _ in range(500)]
    assert firsts.count("a") > 400
    assert "c" not in {x for order in (
        weighted_order([(1.0, "a"), (0.0, "c")]) for _ in range(50))
        for x in order}
    assert weighted_order([]) == []
    assert weighted_order([(0.0, "c")]) == []


class _StubBackendHandler(http.server.BaseHTTPRequestHandler):
    fingerprint = "fp-stub"
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        self.rfile.read(length)
        body = json.dumps({
            "model_fingerprint": self.fingerprint,
            "seen_model": self.headers.get("X-Model"),
            "seen_deadline": self.headers.get("X-Deadline-Ms"),
            "methods": []}).encode() + b"\n"
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _stub_backend(fingerprint):
    handler = type("H", (_StubBackendHandler,),
                   {"fingerprint": fingerprint})
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    srv.daemon_threads = True
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


class _StubControl:
    """The duck-typed surface FleetRouter consumes."""

    def __init__(self, candidates):
        self.candidates = candidates  # model -> list OR None

    def hosts_for(self, model):
        return self.candidates.get(model)

    def fleet_view(self):
        return {"hosts": [], "models": {m: {} for m in self.candidates}}

    def merged_fleet_metrics(self):
        return "# empty\n"

    def request_swap(self, payload):
        return 202, {"accepted": True, "payload": payload}

    def request_scale(self, host, n):
        return 200, {"host": host, "desired_replicas": n}

    def drain_host(self, host):
        return 202, {"host": host, "draining": True}


@pytest.fixture()
def router_config():
    return Config(serve=True, serve_host="127.0.0.1",
                  serve_deadline_ms=2000.0, verbose_mode=0)


def _make_router(config, control):
    from code2vec_tpu.serving.fleet.router import FleetRouter
    return FleetRouter(config, control, host="127.0.0.1", port=0,
                       log=lambda m: None)


def test_router_forwards_and_retries_past_dead_host(router_config):
    """A connection-refused candidate is retried on the next host; the
    client sees one healthy answer, trace headers included."""
    backend = _stub_backend("fp-live")
    dead_port = _free_port()
    control = _StubControl({"default": [
        (1.0, "dead", ("127.0.0.1", dead_port)),
        (1.0, "live", ("127.0.0.1", backend.server_address[1]))]})
    router = _make_router(router_config, control)
    try:
        for _ in range(6):  # weighted order is random: hit both orders
            status, body, headers = _post(router.port, "/predict",
                                          "class A { int a(){} }")
            assert status == 200
            payload = json.loads(body)
            assert payload["model_fingerprint"] == "fp-live"
            assert headers["X-Trace-Id"]
            assert headers["traceparent"].split("-")[1] \
                == headers["X-Trace-Id"]
    finally:
        router.close()
        backend.shutdown()


def test_router_retry_honors_remaining_deadline_budget(router_config):
    """Satellite pin: after a black-hole host consumes the budget, the
    retry is NOT dispatched — an honest, prompt 504 with a trace id
    (a retry past the budget can only produce a late 504)."""
    # accepts the TCP handshake, never answers: the first attempt
    # burns the whole X-Deadline-Ms budget
    hole = socket.socket()
    hole.bind(("127.0.0.1", 0))
    hole.listen(1)
    backend = _stub_backend("fp-after-hole")
    control = _StubControl({"default": [
        (1000.0, "hole", ("127.0.0.1", hole.getsockname()[1])),
        (0.001, "live", ("127.0.0.1", backend.server_address[1]))]})
    router = _make_router(router_config, control)
    try:
        t0 = time.perf_counter()
        status, body, headers = _post(
            router.port, "/predict", "class B { int b(){} }",
            headers={"X-Deadline-Ms": "300"})
        elapsed = time.perf_counter() - t0
        # the hole is weight-1000: first virtually always. Either the
        # budget died there (504, no retry) or the rare live-first
        # order answered 200 — never a LATE success and never a hang.
        assert status in (200, 504)
        assert elapsed < 2.0, f"blocked {elapsed:.2f}s on a 300ms budget"
        if status == 504:
            payload = json.loads(body)
            assert "deadline" in payload["error"]
            assert payload["trace_id"] == headers["X-Trace-Id"]
    finally:
        router.close()
        backend.shutdown()
        hole.close()


def test_router_unknown_model_404_no_host_503_with_trace(router_config):
    backend = _stub_backend("fp-m1")
    control = _StubControl({
        "m1": [(1.0, "h", ("127.0.0.1", backend.server_address[1]))],
        "empty": []})
    router = _make_router(router_config, control)
    try:
        status, body, headers = _post(router.port, "/predict", "x",
                                      headers={"X-Model": "nope"})
        assert status == 404
        assert json.loads(body)["trace_id"] == headers["X-Trace-Id"]
        status, body, headers = _post(router.port, "/predict", "x",
                                      headers={"X-Model": "empty"})
        assert status == 503
        assert json.loads(body)["trace_id"] == headers["X-Trace-Id"]
        assert int(headers["Retry-After"]) >= 1
        # default model group absent in this control -> 404 too
        status, _, _ = _post(router.port, "/predict", "x")
        assert status == 404
    finally:
        router.close()
        backend.shutdown()


def test_router_multi_model_isolation_and_inbound_trace(router_config):
    """X-Model keys the host group; a request can only reach a host
    mounting its model (structural cross-model isolation), and an
    inbound traceparent survives the hop."""
    b1, b2 = _stub_backend("fp-m1"), _stub_backend("fp-m2")
    control = _StubControl({
        "m1": [(1.0, "h1", ("127.0.0.1", b1.server_address[1]))],
        "m2": [(1.0, "h2", ("127.0.0.1", b2.server_address[1]))]})
    router = _make_router(router_config, control)
    try:
        for model, fp in (("m1", "fp-m1"), ("m2", "fp-m2")):
            inbound = "ab" * 16
            status, body, headers = _post(
                router.port, "/predict", "class C { int c(){} }",
                headers={"X-Model": model,
                         "traceparent": f"00-{inbound}-{'cd' * 8}-01"})
            assert status == 200
            payload = json.loads(body)
            assert payload["model_fingerprint"] == fp
            assert payload["seen_model"] == model
            assert headers["X-Trace-Id"] == inbound
        # admin verbs dispatch to the control plane, not a host
        status, body, _ = _post(
            router.port, "/admin/scale",
            json.dumps({"host": "h1", "replicas": 3}),
            headers={"Content-Type": "application/json"})
        assert status == 200
        assert json.loads(body)["desired_replicas"] == 3
        status, body, _ = _post(
            router.port, "/admin/drain", json.dumps({"host": "h2"}),
            headers={"Content-Type": "application/json"})
        assert status == 202
        status, _, _ = _post(router.port, "/admin/reload",
                             json.dumps({"artifact": "/a"}),
                             headers={"Content-Type":
                                      "application/json"})
        assert status == 202
        # /fleet + /healthz answered locally
        assert _get(router.port, "/fleet")[0] == 200
        hz = json.loads(_get(router.port, "/healthz")[1])
        assert hz["status"] == "routing"
    finally:
        router.close()
        b1.shutdown()
        b2.shutdown()


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ---------------------------------------------------- scaling policy


def _scale_config(**overrides):
    kwargs = dict(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_poll_interval_s=0.2, fleet_scale_min=1, fleet_scale_max=4,
        fleet_scale_up_shed_rate=0.05, fleet_scale_up_ticks=2,
        fleet_scale_down_ticks=3, fleet_scale_cooldown_s=0.0,
        fleet_models="default=/tmp/none")
    kwargs.update(overrides)
    return Config(**kwargs)


def _policy_control(tmp_path, config):
    from code2vec_tpu.serving.fleet.control import (
        ControlPlane, HostSpec,
    )
    config.heartbeat_file = str(tmp_path / "fleet.heartbeat.json")
    control = ControlPlane(
        config, [HostSpec("h0", ["true"])], log=lambda m: None)
    host = control.hosts[0]
    host.state, host.weight = "healthy", 1.0
    posts = []
    control._post = lambda h, path, payload, timeout=10.0: (
        posts.append((h.id, path, payload)) or (True, "{}"))
    return control, host, posts


def _view(requests, sheds, desired=2):
    return {"desired_replicas": desired,
            "replicas": [{"requests_total": requests,
                          "requests_shed_total": sheds}]}


def _scale_metrics(requests, sheds):
    """The host /metrics slice the autoscaler's tsdb window reads."""
    return (
        "# TYPE serving_requests_total counter\n"
        f'serving_requests_total{{endpoint="/predict",status="200"}}'
        f" {requests}\n"
        "# TYPE serving_requests_shed_total counter\n"
        f"serving_requests_shed_total {sheds}\n")


def _scale_ticker(control, host, now):
    def tick(requests, sheds):
        host.view = _view(requests, sheds)
        control.tsdb.append(
            {f"host:{host.id}": _scale_metrics(requests, sheds)},
            now=now[0])
        control._scale_tick(host, now[0])
        now[0] += 1.0
    return tick


def test_scale_up_needs_consecutive_ticks_and_respects_max(tmp_path):
    config = _scale_config()
    control, host, posts = _policy_control(tmp_path, config)
    tick = _scale_ticker(control, host, [100.0])

    tick(100, 0)        # seed the window
    tick(200, 50)       # shed_rate 0.5 -> up_tick 1: hysteresis holds
    assert posts == []
    tick(300, 100)      # up_tick 2 -> scale up 2 -> 3
    assert posts == [("h0", "/admin/scale", {"replicas": 3})]
    tick(400, 150)
    tick(500, 200)      # two more bad ticks -> 3 -> 4 (the max)
    assert posts[-1] == ("h0", "/admin/scale", {"replicas": 4})
    tick(600, 250)
    tick(700, 300)      # at fleet_scale_max: no further action
    assert len(posts) == 2


def test_scale_up_blocked_by_cooldown_then_idle_scales_down(tmp_path):
    config = _scale_config(fleet_scale_cooldown_s=3600.0)
    control, host, posts = _policy_control(tmp_path, config)
    tick = _scale_ticker(control, host, [100.0])

    tick(100, 0)
    tick(200, 50)
    tick(300, 100)      # action + cooldown armed
    assert len(posts) == 1
    tick(400, 150)
    tick(500, 200)      # over threshold again, but inside cooldown
    assert len(posts) == 1
    host.cooldown_until = 0.0
    # sustained idle (zero new requests) for fleet_scale_down_ticks
    tick(500, 200)
    tick(500, 200)
    assert len(posts) == 1  # hysteresis: 2 idle ticks < 3
    tick(500, 200)
    assert posts[-1] == ("h0", "/admin/scale", {"replicas": 2})
    # floor: drive down to min=1, then idle forever stays at 1
    host.cooldown_until = 0.0
    host.desired_replicas = 1
    for _ in range(5):
        tick(500, 200)
    assert posts[-1][2] == {"replicas": 2}  # no action below the floor


def test_scale_window_survives_replica_restart(tmp_path):
    """A replica restart zeroes its counters mid-window. The tsdb's
    reset-aware increase (telemetry.counter_delta) reads the
    post-restart values as growth IN FULL — never a negative delta,
    never a phantom idle tick, and no lost decision tick."""
    config = _scale_config()
    control, host, posts = _policy_control(tmp_path, config)
    tick = _scale_ticker(control, host, [100.0])
    tick(1000, 0)
    tick(50, 10)   # counters went BACKWARD (restart)
    # 50 post-restart requests, 10 shed -> a real over-threshold tick
    assert host.idle_ticks == 0 and host.up_ticks == 1
    assert posts == []  # hysteresis still holds at 1 tick
    # and the boot tick itself never reads as idle
    control2, host2, posts2 = _policy_control(tmp_path / "b", config)
    tick2 = _scale_ticker(control2, host2, [100.0])
    tick2(100, 0)
    assert host2.idle_ticks == 0 and posts2 == []


# ------------------------------------------------ swap driver (stub)


class _SwapHost:
    def __init__(self, host_id, fail_targets=()):
        self.id = host_id
        self.fail_targets = set(fail_targets)
        self.fingerprint = "fp-v1"
        self.swap_state = "idle"
        self.swap_target = None
        self.reloads = []

    def apply_reload(self, artifact):
        self.reloads.append(artifact)
        self.swap_target = artifact
        name = os.path.basename(artifact)
        if name in self.fail_targets:
            self.swap_state = "failed"
        else:
            self.fingerprint = f"fp-{name}"
            self.swap_state = "ready"


class _SwapControl:
    def __init__(self, hosts, rollback="v1"):
        class _Cfg:
            fleet_swap_timeout_s = 3.0
        self.config = _Cfg()
        self.hosts = hosts
        self._rollback = rollback
        self.committed_artifact = None
        self.flight = obs.default_flight_recorder()
        self.log = lambda m: None

    def swap_hosts(self, model):
        return list(self.hosts) if model == "default" else None

    def host_reload(self, host, artifact, retrieval_index=None,
                    traceparent=None):
        host.apply_reload(artifact)
        host.retrieval_index = retrieval_index
        host.reload_traceparent = traceparent
        return True, ""

    def host_fleet(self, host):
        return {"replicas": [
            {"model_fingerprint": host.fingerprint,
             "swap_state": host.swap_state,
             "swap_target": host.swap_target, "draining": False}
            for _ in range(2)]}

    def rollback_target(self, model):
        return self._rollback

    def set_artifact(self, model, artifact, retrieval_index=None):
        self.committed_artifact = artifact
        self.committed_retrieval_index = retrieval_index


def _run_swap(driver, artifact, **kw):
    driver.request(artifact, **kw)
    deadline = time.time() + 15
    while driver.status()["state"] in ("canary", "rolling",
                                       "rolling_back"):
        if time.time() > deadline:
            raise AssertionError(f"swap wedged: {driver.status()}")
        time.sleep(0.02)
    return driver.status()


def test_fleet_swap_canary_first_commit(tmp_path):
    from code2vec_tpu.serving.fleet.swap import (
        FleetSwapBusy, FleetSwapDriver,
    )

    h0, h1 = _SwapHost("h0"), _SwapHost("h1")
    control = _SwapControl([h0, h1])
    driver = FleetSwapDriver(control, poll_interval_s=0.01)
    status = _run_swap(driver, "/artifacts/v2")
    assert status["state"] == "committed"
    assert status["target_fingerprint"] == "fp-v2"
    assert [h["outcome"] for h in status["hosts"]] == ["committed"] * 2
    # canary-first: h0 swapped strictly before h1
    assert h0.reloads == ["/artifacts/v2"] and h1.reloads == \
        ["/artifacts/v2"]
    assert control.committed_artifact == "/artifacts/v2"
    assert h0.fingerprint == h1.fingerprint == "fp-v2"
    # busy conflict is a 409-shaped error
    driver._worker = threading.Thread(target=time.sleep, args=(0.3,))
    driver._worker.start()
    with pytest.raises(FleetSwapBusy, match="in flight"):
        driver.request("/artifacts/v3")


def test_fleet_swap_canary_failure_halts_untouched(tmp_path):
    from code2vec_tpu.serving.fleet.swap import FleetSwapDriver

    h0, h1 = _SwapHost("h0", fail_targets={"bad"}), _SwapHost("h1")
    control = _SwapControl([h0, h1])
    driver = FleetSwapDriver(control, poll_interval_s=0.01)
    status = _run_swap(driver, "/artifacts/bad")
    assert status["state"] == "failed"
    assert "canary" in status["error"]
    # halt-and-report: the non-canary host was NEVER touched
    assert h1.reloads == []
    assert h1.fingerprint == "fp-v1"
    assert control.committed_artifact is None


def test_fleet_swap_post_canary_failure_rolls_back_fleet(tmp_path):
    from code2vec_tpu.serving.fleet.swap import FleetSwapDriver

    h0, h1 = _SwapHost("h0"), _SwapHost("h1", fail_targets={"v2"})
    control = _SwapControl([h0, h1], rollback="/artifacts/v1")
    driver = FleetSwapDriver(control, poll_interval_s=0.01)
    status = _run_swap(driver, "/artifacts/v2")
    assert status["state"] == "rolled_back"
    # the canary committed v2, then was rolled back to v1 — the fleet
    # converges on ONE fingerprint instead of staying mixed
    assert h0.reloads == ["/artifacts/v2", "/artifacts/v1"]
    assert h1.reloads == ["/artifacts/v2", "/artifacts/v1"]
    assert h0.fingerprint == h1.fingerprint == "fp-v1"
    outcomes = {h["host"]: h["outcome"] for h in status["hosts"]
                if "rolled_back" in h["outcome"]}
    assert set(outcomes) == {"h0", "h1"}
    # no rollback target -> halt-and-report instead
    h0b, h1b = _SwapHost("h0"), _SwapHost("h1", fail_targets={"v2"})
    control2 = _SwapControl([h0b, h1b], rollback=None)
    driver2 = FleetSwapDriver(control2, poll_interval_s=0.01)
    status2 = _run_swap(driver2, "/artifacts/v2")
    assert status2["state"] == "failed"
    assert "rollback" in status2["error"]


# ------------------------------------------------- telemetry verbs


def test_telemetry_server_post_handlers_dispatch_and_400():
    from code2vec_tpu.serving.telemetry import TelemetryServer

    seen = []

    def scale(payload):
        if "replicas" not in payload:
            raise ValueError("missing replicas")
        seen.append(payload)
        return 200, {"ok": True}

    srv = TelemetryServer(lambda: "# m\n", lambda: {},
                          post_handlers={"/admin/scale": scale})
    try:
        status, body, _ = _post(srv.port, "/admin/scale",
                                json.dumps({"replicas": 3}),
                                headers={"Content-Type":
                                         "application/json"})
        assert status == 200 and json.loads(body)["ok"]
        assert seen == [{"replicas": 3}]
        assert _post(srv.port, "/admin/scale", "{}")[0] == 400
        assert _post(srv.port, "/admin/scale", "{nope")[0] == 400
        assert _post(srv.port, "/admin/nope", "{}")[0] == 404
        # GETs still serve
        assert _get(srv.port, "/metrics")[0] == 200
    finally:
        srv.close()


# --------------------------------------------------------- CLI seam


def test_fleet_cli_flags_parse_and_verify():
    from code2vec_tpu.cli import config_from_args

    config = config_from_args([
        "fleet", "--fleet_models", "stable=/a,canary=/b",
        "--fleet_hosts", "3", "--fleet_port", "0",
        "--fleet_poll_interval", "0.5",
        "--fleet_scale_min", "1", "--fleet_scale_max", "6",
        "--fleet_scale_up_shed_rate", "0.1",
        "--fleet_scale_up_p95_ms", "250",
        "--fleet_scale_up_ticks", "3", "--fleet_scale_down_ticks", "8",
        "--fleet_scale_cooldown", "30", "--fleet_swap_timeout", "90",
        "--fleet_max_host_restarts", "2",
        "--serve_flight_max_dumps", "16"])
    assert config.fleet and config.serve
    assert config.fleet_hosts == 3
    assert config.fleet_models == "stable=/a,canary=/b"
    assert config.fleet_scale_max == 6
    assert config.fleet_scale_up_p95_ms == 250
    assert config.fleet_swap_timeout_s == 90
    assert config.serve_flight_max_dumps == 16
    config.verify()  # fleet_models carries the models: no --load needed

    bad = config_from_args(["fleet", "--fleet_models", "oops"])
    with pytest.raises(ValueError, match="fleet_models"):
        bad.verify()
    inverted = config_from_args([
        "fleet", "--artifact", "/a", "--fleet_scale_min", "3",
        "--fleet_scale_max", "2"])
    with pytest.raises(ValueError, match="fleet_scale_max"):
        inverted.verify()


def test_host_base_command_strips_fleet_flags():
    from code2vec_tpu.serving.fleet.control import _host_base_command

    cmd = _host_base_command(
        ["fleet", "--artifact", "/a", "--fleet_hosts", "2",
         "--fleet_models", "m=/x", "--replicas", "2",
         "--serve_port", "9000", "--heartbeat_file", "/tmp/hb"],
        strip_artifact=True)
    tail = cmd[3:]
    assert tail[0] == "serve"
    assert "--fleet_hosts" not in tail and "--fleet_models" not in tail
    assert "--serve_port" not in tail and "--heartbeat_file" not in tail
    assert "--artifact" not in tail
    assert tail[tail.index("--replicas") + 1] == "2"


# ---------------------------------------------- chaos drills (slow)


@pytest.fixture()
def fake_extractor(tmp_path, monkeypatch):
    path = tmp_path / "fake-c2v-extract"
    path.write_text(FAKE_EXTRACTOR)
    path.chmod(0o755)
    monkeypatch.setenv("C2V_NATIVE_EXTRACTOR", str(path))
    monkeypatch.delenv("C2V_FAKE_NO_SERVER", raising=False)
    return str(path)


def _write_json(tmp_path, name, payload):
    path = tmp_path / name
    path.write_text(json.dumps(payload))
    return str(path)


def _replica_overrides(**extra):
    overrides = dict(
        serve_host="127.0.0.1", max_contexts=16, serve_batch_size=4,
        serve_buckets="4,8", serve_max_delay_ms=2.0,
        serve_cache_entries=0, extractor_pool_size=1,
        serve_drain_timeout_s=5.0, serve_heartbeat_interval_s=0.2,
        serve_deadline_ms=3000.0)
    overrides.update(extra)
    return overrides


def _host_overrides(**extra):
    overrides = dict(
        serve_host="127.0.0.1", serve_port=0, serve_telemetry_port=0,
        serve_replicas=2, serve_max_restarts=5,
        serve_heartbeat_interval_s=0.2, serve_drain_timeout_s=5.0)
    overrides.update(extra)
    return overrides


def _fleet_config(tmp_path, **overrides):
    kwargs = dict(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_hosts=2, fleet_poll_interval_s=0.25,
        fleet_max_host_restarts=5, fleet_swap_timeout_s=30.0,
        serve_drain_timeout_s=6.0,
        # the drills assert on deterministic replica sets: keep the
        # autoscaler from draining idle replicas mid-drill (the policy
        # has its own unit tests above)
        fleet_scale_down_ticks=1000000, fleet_scale_up_shed_rate=1.0,
        heartbeat_file=str(tmp_path / "fleet.heartbeat.json"))
    kwargs.update(overrides)
    return Config(**kwargs)


@pytest.fixture()
def run_fleet(tmp_path, fake_extractor):
    """Factory: ControlPlane + FleetRouter over real Supervisor host
    subprocesses running fake-model replicas; torn down at test end."""
    from code2vec_tpu.serving.fleet.control import (
        ControlPlane, HostSpec,
    )
    from code2vec_tpu.serving.fleet.router import FleetRouter

    running = []

    def start(config, host_specs, artifacts=None):
        control = ControlPlane(config, host_specs, log=lambda m: None)
        for model, artifact in (artifacts or {}).items():
            control.set_initial_artifact(model, artifact)
        control.router = FleetRouter(config, control, host="127.0.0.1",
                                     port=0, log=lambda m: None)
        rc_holder = {}
        thread = threading.Thread(
            target=lambda: rc_holder.update(rc=control.run()),
            daemon=True)
        thread.start()
        running.append((control, thread))
        return control, thread, rc_holder

    yield start
    for control, thread in running:
        control.stop()
        thread.join(timeout=60)


def _wait_fleet(control, predicate, timeout=45.0, what="condition"):
    deadline = time.time() + timeout
    view = None
    while time.time() < deadline:
        view = control.fleet_view()
        if predicate(view):
            return view
        time.sleep(0.1)
    raise AssertionError(f"fleet never reached {what}; last={view}")


def _all_routable(n):
    # readiness = every host routable AND at least one replica per
    # host has written a "serving" heartbeat (under SO_REUSEPORT a
    # replica's port is assigned at spawn, BEFORE the child binds)
    def ready(view):
        hosts = [h for h in view["hosts"] if h["weight"] > 0]
        if len(hosts) < n:
            return False
        for h in hosts:
            replicas = (h.get("replicas_serving") or 0)
            if replicas < 1:
                return False
        return True
    return ready


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_host_kill_under_load_converges_and_readmits(
        tmp_path, fake_extractor, run_fleet):
    """THE fleet chaos drill (ROADMAP acceptance): SIGKILL one entire
    host (supervisor + its replicas) under concurrent overload across
    2 hosts x 2 replicas. Every client failure is an honest shed
    (503/504, valid JSON, trace id in body and header), zero malformed
    or cross-fingerprint responses, the router converges onto the
    survivor, and the killed host's capacity is re-admitted after the
    control plane restarts it."""
    replica_cfg = _write_json(
        tmp_path, "replica.json",
        _replica_overrides(fingerprint="fp-drill",
                           serve_queue_depth=2))
    host_cmd = [sys.executable, FLEET_HOST,
                _write_json(tmp_path, "host.json", _host_overrides()),
                replica_cfg]
    from code2vec_tpu.serving.fleet.control import HostSpec
    config = _fleet_config(tmp_path)
    control, thread, rc_holder = run_fleet(
        config, [HostSpec("default-0", host_cmd),
                 HostSpec("default-1", host_cmd)])
    _wait_fleet(control, _all_routable(2), what="2 routable hosts")
    port = control.router.port

    malformed, responses = [], []
    lock = threading.Lock()
    stop_load = threading.Event()

    def load(ci):
        i = 0
        while not stop_load.is_set():
            try:
                status, body, headers = _post(
                    port, "/predict",
                    f"class K{ci}x{i} {{ int m{ci}x{i}() "
                    f"{{ return 1; }} }}", timeout=30)
            except Exception as e:  # noqa: BLE001 — a torn TCP conn is
                # a client-side retry, not a corrupt response
                with lock:
                    responses.append(("conn_error", str(e)))
                i += 1
                continue
            try:
                payload = json.loads(body)
                if status == 200:
                    ok = (payload.get("model_fingerprint") == "fp-drill"
                          and "methods" in payload)
                else:
                    ok = (status in (503, 504)
                          and payload.get("trace_id")
                          and payload["trace_id"]
                          == headers.get("X-Trace-Id"))
                if not ok:
                    raise ValueError(f"dishonest: {status} {payload}")
            except ValueError as e:
                with lock:
                    malformed.append((status, body[:200], str(e)))
            with lock:
                responses.append((status, None))
            i += 1

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(6)]
    for t in threads:
        t.start()
    try:
        time.sleep(1.0)
        # kill the WHOLE host: supervisor first, then its replicas
        victim = control.hosts[0]
        victim_pid = victim.proc.pid
        hb = victim.heartbeat()
        replica_pids = [r["pid"] for r in hb["replicas"] if r["pid"]]
        os.kill(victim_pid, signal.SIGKILL)
        for pid in replica_pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        # convergence: the control plane restarts the host (new pid)
        # and its capacity is re-admitted into routing
        _wait_fleet(
            control,
            lambda v: (v["hosts"][0]["pid"] not in (None, victim_pid)
                       and v["hosts"][0]["weight"] > 0
                       and v["hosts"][0]["restarts"] >= 1
                       and (v["hosts"][0]["replica_count"] or 0) >= 2),
            timeout=60, what="killed host restarted + re-admitted")
        time.sleep(1.0)  # post-recovery traffic through both hosts
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
    assert not malformed, f"dishonest responses: {malformed[:3]}"
    statuses = [s for s, _ in responses]
    assert statuses.count(200) > 0, "no successes at all"
    # a fresh request through the recovered fleet succeeds
    status, body, _ = _post(port, "/predict",
                            "class Z { int after() { return 1; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fp-drill"
    assert _counter_value("fleet_host_restarts_total") >= 1
    # coordinated shutdown: router drains, hosts drain, rc 0
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_canary_swap_commits_then_rolls_back_on_host_failure(
        tmp_path, fake_extractor, run_fleet):
    """Fleet-wide coordinated hot-swap drill (ROADMAP acceptance):
    (1) canary-first rollout lands ONE new fingerprint on every
    replica of every host; (2) a rollout where a non-canary host's
    replicas reject the candidate rolls the WHOLE fleet back to the
    previous artifact — never a permanently mixed fleet."""
    from code2vec_tpu.serving.fleet.control import HostSpec

    # host 1's replicas fail validation for artifact basename "v3"
    ok_replicas = _write_json(
        tmp_path, "replica-ok.json",
        _replica_overrides(fingerprint="fp-v1", fake_swap=True))
    failing_replicas = _write_json(
        tmp_path, "replica-fail-v3.json",
        _replica_overrides(fingerprint="fp-v1", fake_swap=True,
                           swap_fail_targets=["v3"]))
    host_json = _write_json(tmp_path, "host.json", _host_overrides())
    config = _fleet_config(tmp_path)
    control, thread, rc_holder = run_fleet(
        config,
        [HostSpec("default-0",
                  [sys.executable, FLEET_HOST, host_json, ok_replicas]),
         HostSpec("default-1",
                  [sys.executable, FLEET_HOST, host_json,
                   failing_replicas])],
        artifacts={"default": "/artifacts/v1"})
    _wait_fleet(control, _all_routable(2), what="2 routable hosts")
    port = control.router.port

    def fleet_fingerprints(view):
        return view["models"]["default"]["fingerprints"]

    # ---- rollout 1: clean canary-first commit to v2
    status, body, _ = _post(port, "/admin/reload",
                            json.dumps({"artifact": "/artifacts/v2"}),
                            headers={"Content-Type":
                                     "application/json"})
    assert status == 202
    view = _wait_fleet(
        control, lambda v: v["swap"]["state"] == "committed",
        what="swap committed")
    assert view["swap"]["target_fingerprint"] == "fp-v2"
    # canary strictly first in the outcome order
    assert [h["host"] for h in view["swap"]["hosts"]] == \
        ["default-0", "default-1"]
    view = _wait_fleet(
        control,
        lambda v: fleet_fingerprints(v) == ["fp-v2"]
        and not v["models"]["default"]["mixed_fingerprints"],
        what="every replica on fp-v2")
    # every replica of every host landed the new fingerprint
    for host in view["hosts"]:
        assert host["fingerprints"] == ["fp-v2"], host
    assert view["models"]["default"]["artifact"] == "/artifacts/v2"
    # a 409 while nothing is in flight would be a bug: re-assert idle
    # behavior via a second no-op check of status below

    # ---- rollout 2: host 1 rejects v3 -> fleet-wide rollback to v2
    status, _, _ = _post(port, "/admin/reload",
                         json.dumps({"artifact": "/artifacts/v3"}),
                         headers={"Content-Type": "application/json"})
    assert status == 202
    view = _wait_fleet(
        control, lambda v: v["swap"]["state"] == "rolled_back",
        timeout=90, what="swap rolled back")
    assert "default-1" in view["swap"]["error"]
    view = _wait_fleet(
        control, lambda v: fleet_fingerprints(v) == ["fp-v2"],
        what="fleet back on fp-v2 after rollback")
    assert not view["models"]["default"]["mixed_fingerprints"]
    assert view["models"]["default"]["artifact"] == "/artifacts/v2"
    # live traffic still serves the rolled-back weights, honestly
    status, body, _ = _post(port, "/predict",
                            "class R { int rb() { return 1; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fp-v2"
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_fleet_multi_model_groups_and_host_scale_e2e(
        tmp_path, fake_extractor, run_fleet):
    """Multi-model fleet: X-Model routes to the right group's weights
    (zero cross-model responses by construction, asserted on the
    fingerprint), unknown models 404, and a manual /admin/scale
    resizes one host's replica set live (up, then drained back
    down)."""
    from code2vec_tpu.serving.fleet.control import HostSpec

    host_json = _write_json(tmp_path, "host.json",
                            _host_overrides(serve_replicas=1))
    specs, artifacts = [], {}
    for model in ("stable", "exp"):
        replicas = _write_json(
            tmp_path, f"replica-{model}.json",
            _replica_overrides(fingerprint=f"fp-{model}"))
        specs.append(HostSpec(
            f"{model}-0",
            [sys.executable, FLEET_HOST, host_json, replicas],
            model=model))
        artifacts[model] = f"/artifacts/{model}"
    config = _fleet_config(tmp_path, fleet_hosts=1,
                           fleet_models="stable=/a,exp=/b")
    control, thread, rc_holder = run_fleet(config, specs,
                                           artifacts=artifacts)
    _wait_fleet(control, _all_routable(2), what="both model hosts up")
    port = control.router.port
    for model in ("stable", "exp"):
        for i in range(3):
            status, body, _ = _post(
                port, "/predict",
                f"class M{i} {{ int m{i}() {{ return 1; }} }}",
                headers={"X-Model": model})
            assert status == 200
            assert json.loads(body)["model_fingerprint"] == \
                f"fp-{model}", f"cross-model response for {model}"
    assert _post(port, "/predict", "x",
                 headers={"X-Model": "nope"})[0] == 404
    # manual scale override: 1 -> 2 replicas on the stable host
    status, _, _ = _post(port, "/admin/scale",
                         json.dumps({"host": "stable-0",
                                     "replicas": 2}),
                         headers={"Content-Type": "application/json"})
    assert status == 200
    _wait_fleet(
        control,
        lambda v: next(h for h in v["hosts"]
                       if h["host"] == "stable-0")["replica_count"]
        == 2,
        what="stable-0 scaled to 2 replicas")
    # and back down: the retired replica drains, count returns to 1
    status, _, _ = _post(port, "/admin/scale",
                         json.dumps({"host": "stable-0",
                                     "replicas": 1}),
                         headers={"Content-Type": "application/json"})
    assert status == 200
    _wait_fleet(
        control,
        lambda v: next(h for h in v["hosts"]
                       if h["host"] == "stable-0")["replica_count"]
        == 1,
        what="stable-0 drained back to 1 replica")
    # fleet-wide merged metrics include both hosts' counters
    status, body = _get(port, "/metrics")
    assert status == 200
    from code2vec_tpu.serving import telemetry
    assert telemetry.sum_family(body.decode(),
                                "serving_requests_total") >= 6
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0


# --------------------- shared forwarding core (serving/forwarding.py)


class _FakeDeadline:
    def __init__(self, remaining_values, bounded=True):
        self._vals = list(remaining_values)
        self.bounded = bounded

    def remaining(self):
        return self._vals.pop(0) if self._vals else 0.0


class _FakeSpan:
    def __init__(self, attrs):
        self.attrs = attrs

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class _FakeTrace:
    trace_id = "f" * 32

    def traceparent(self):
        return f"00-{self.trace_id}-{'b' * 16}-01"

    def span(self, name, **attrs):
        return _FakeSpan(attrs)


def _run_forward(targets, deadline=None, **kw):
    from code2vec_tpu.serving.forwarding import forward_with_retry
    replies = []
    outcomes = []
    forward_with_retry(
        method="POST", path="/predict", body=b"x",
        fwd_headers={}, targets=targets,
        deadline=deadline or _FakeDeadline([10.0] * 8),
        trace=_FakeTrace(),
        reply=lambda *a: replies.append(a),
        what="replicas", unreachable_error="all replicas unreachable",
        on_outcome=outcomes.append, **kw)
    assert len(replies) == 1, "reply must be called exactly once"
    return replies[0], outcomes


def test_forwarding_relays_backend_and_stamps_trace():
    srv = _stub_backend("fp-fwd")
    port = srv.server_address[1]
    try:
        (code, payload, headers, ctype), outcomes = _run_forward(
            [("b", "127.0.0.1", port)])
        assert code == 200 and outcomes == ["forwarded"]
        assert headers["X-Trace-Id"]  # stamped even when backend lacks it
        assert json.loads(payload)["model_fingerprint"] == "fp-fwd"
    finally:
        srv.shutdown()


def test_forwarding_retries_dead_then_succeeds_and_counts():
    srv = _stub_backend("fp-retry")
    port = srv.server_address[1]
    dead = _free_port()

    class _Ctr:
        n = 0

        def inc(self):
            self.n += 1

    ctr = _Ctr()
    try:
        (code, _, _, _), outcomes = _run_forward(
            [("dead", "127.0.0.1", dead), ("live", "127.0.0.1", port)],
            retry_counter=ctr)
        assert code == 200 and outcomes == ["forwarded"]
        assert ctr.n == 1
    finally:
        srv.shutdown()


def test_forwarding_expired_budget_is_honest_504():
    dead = _free_port()
    (code, payload, headers, _), outcomes = _run_forward(
        [("d1", "127.0.0.1", dead), ("d2", "127.0.0.1", dead)],
        deadline=_FakeDeadline([0.5, 0.0]))
    assert code == 504 and outcomes == ["expired"]
    body = json.loads(payload)
    assert "deadline exhausted retrying replicas" in body["error"]
    assert body["trace_id"] == _FakeTrace.trace_id
    assert headers["X-Trace-Id"] == _FakeTrace.trace_id


def test_forwarding_all_unreachable_503_with_retry_after():
    dead = _free_port()
    (code, payload, headers, _), outcomes = _run_forward(
        [("d1", "127.0.0.1", dead)], retry_after="1.2")
    assert code == 503 and outcomes == ["unreachable"]
    assert "all replicas unreachable" in json.loads(payload)["error"]
    assert headers["Retry-After"] == "1.2"
    assert headers["traceparent"].startswith("00-" + _FakeTrace.trace_id)


def test_handle_admin_post_error_mapping():
    from code2vec_tpu.serving.forwarding import handle_admin_post

    class _Handler:
        headers = {"Content-Length": "2"}

        class rfile:
            @staticmethod
            def read(n):
                return b"{}"

    out = []

    def run(dispatch, **kw):
        out.clear()
        handle_admin_post(_Handler(), dispatch,
                          lambda code, body: out.append((code, body)),
                          **kw)
        return out[0]

    assert run(lambda p: (200, {"ok": True})) == (200, {"ok": True})
    code, body = run(lambda p: (_ for _ in ()).throw(
        ValueError("bad knob")))
    assert code == 400 and "bad knob" in body["error"]
    # "in flight" ValueError -> 409 only when the caller opts in
    code, _ = run(lambda p: (_ for _ in ()).throw(
        ValueError("a swap is already in flight")), conflict_409=True)
    assert code == 409
    code, _ = run(lambda p: (_ for _ in ()).throw(
        ValueError("a swap is already in flight")))
    assert code == 400
    # KeyError -> 404 naming the host only when the caller opts in
    code, body = run(lambda p: (_ for _ in ()).throw(KeyError("h7")),
                     keyerror_is_missing_host=True)
    assert code == 404 and "h7" in body["error"]
    code, _ = run(lambda p: (_ for _ in ()).throw(KeyError("h7")))
    assert code == 500
    # anything else -> 500 as an HTTP error, never a torn connection
    code, body = run(lambda p: (_ for _ in ()).throw(
        RuntimeError("boom")))
    assert code == 500 and "RuntimeError" in body["error"]
