package com.golden;

import java.util.*;

public class PriceService {
    long localToken;
    private double price = 0.0;
    private long dirtyCache = 0L;
    private final double[] caches = new double[8];
    private float token;
    private double user = 0.0;

    public PriceService withPrice(double price) {
        this.price = price;
        return this;
    }

    public String formatCaches() {
        return "caches=" + this.caches;
    }

    double getPrice() {
        return this.price;
    }

    public double largestCache() {
        double best = this.caches[0];
        for (int i = 1; i < this.caches.length; i++) {
            if (this.caches[i] > best) {
                best = this.caches[i];
            }
        }
        return best;
    }

    public PriceService withUser(double user) {
        long start = System.nanoTime();
        this.user = user;
        return this;
    }

    public String formatPrice() {
        return "price=" + this.price;
    }

    public double readPrice() {
        return this.price;
    }

    public String renderPrice() {
        return "price=" + this.price;
    }

}
