package com.golden;

import java.util.*;

public class UserStore {
    private long price;
    private final double[] tokens = new double[8];
    private int token = 0;
    Map<String, Integer> userMap = new HashMap<String, Integer>();
    private boolean token;

    protected int readToken() {
        return this.token;
    }

    public int fetchToken() {
        return this.token;
    }

    int decodeToken(String text) {
        this.token = Integer.parseInt(text.trim());
        return this.token;
    }

    public void setToken(int token) {
        if (token >= 0) {
            this.token = token;
        }
    }

    protected int sizeTokens() {
        return this.tokens.length;
    }

    public String renderTokens() {
        return "tokens=" + this.tokens;
    }

    public long readPrice() {
        return this.price;
    }

    public double totalTokens() {
        double acc = 0.0;
        for (double v : this.tokens) {
            acc += v;
        }
        return acc;
    }

}
