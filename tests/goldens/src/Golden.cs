using System;
using System.Collections.Generic;
using System.Linq;

namespace Golden
{
    // Fixture exercising the C# extractor's main constructs: fields,
    // properties, variable pairing across statements, loops (foreach /
    // for / while), conditionals, ternaries, lambdas, LINQ-style calls,
    // arrays, string building and a nested type.
    public class InventoryTracker
    {
        private readonly List<int> quantities = new List<int>();
        private Dictionary<string, int> skuCounts = new Dictionary<string, int>();
        private double totalValue;
        private int[] reorderLevels = new int[16];
        private string label = "";

        public int CountQuantities()
        {
            return this.quantities.Count;
        }

        public void AddQuantity(int quantity)
        {
            if (quantity >= 0)
            {
                this.quantities.Add(quantity);
            }
        }

        public int SumQuantities()
        {
            int acc = 0;
            foreach (int q in this.quantities)
            {
                acc += q;
            }
            return acc;
        }

        public int LargestReorder()
        {
            int best = this.reorderLevels[0];
            for (int i = 1; i < this.reorderLevels.Length; i++)
            {
                if (this.reorderLevels[i] > best)
                {
                    best = this.reorderLevels[i];
                }
            }
            return best;
        }

        public bool HasSku(string sku)
        {
            return this.skuCounts.ContainsKey(sku);
        }

        public int ResolveSku(string sku)
        {
            int value;
            return this.skuCounts.TryGetValue(sku, out value) ? value : 0;
        }

        public void ScaleValue(double factor)
        {
            this.totalValue *= factor;
        }

        public string DescribeQuantities()
        {
            var sb = new System.Text.StringBuilder();
            foreach (var q in this.quantities)
            {
                sb.Append(q).Append(',');
            }
            return sb.ToString();
        }

        public List<int> FilterPositiveQuantities()
        {
            return this.quantities.Where(q => q > 0).ToList();
        }


        public List<string> TopSkuNames(int minCount)
        {
            var top = from pair in this.skuCounts
                      where pair.Value >= minCount
                      orderby pair.Value descending, pair.Key
                      select pair.Key;
            return top.ToList();
        }

        public void ResetAll()
        {
            while (this.quantities.Count > 0)
            {
                this.quantities.RemoveAt(this.quantities.Count - 1);
            }
            this.skuCounts.Clear();
            this.label = string.Empty;
        }

        private class Snapshot
        {
            public int Total;

            public int ReadTotal()
            {
                return this.Total;
            }
        }
    }
}
