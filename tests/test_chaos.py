"""Fault-injection chaos harness for the crash-atomic checkpoint
lifecycle (training/checkpoint.py commit protocol, utils/faults.py hook
points) and the failure-handling paths around it.

The contract under test: a save killed at ANY point — between any two
files, after Orbax flushed but before the manifest, staged but not yet
committed, or hard-killed by the OS — leaves the resume chain able to
load the newest VALID artifact with bit-equal params, and
`latest_valid_checkpoint` never returns a directory that fails its
manifest check. Plus: the SIGTERM preemption path end-to-end in a real
subprocess, the NaN/Inf loss sentinel, the profiler-trace leak fix, the
rotation safety rules, and the serving extractor timeout.

Most tests here are fast (in-process fault injection on tiny states) and
run in tier-1; everything carries the `chaos` marker so the kill tests
can be selected (`-m chaos`) or skipped (`-m 'not chaos'`) as a group.
"""

import glob
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import EpochEnd, RowBatch
from code2vec_tpu.training import checkpoint as ckpt_mod
from code2vec_tpu.training.loop import NonFiniteLossError, Trainer
from code2vec_tpu.utils import faults

HERE = os.path.dirname(os.path.abspath(__file__))
if HERE not in sys.path:
    sys.path.insert(0, HERE)

import chaos_child  # noqa: E402

CHILD = os.path.join(HERE, "chaos_child.py")

pytestmark = pytest.mark.chaos

# Number of `save` fault points save_model crosses per call (staging
# created / vocab written / meta written / Orbax flushed / fully staged).
SAVE_FAULT_POINTS = 5


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No test may leak an armed fault spec into the rest of the suite."""
    yield
    faults.reset(None)


@pytest.fixture(scope="module")
def tiny():
    return chaos_child.build_vocabs(), chaos_child.build_config()


def _save(base, epoch, tiny):
    vocabs, config = tiny
    return ckpt_mod.save_model(f"{base}_iter{epoch}",
                               chaos_child.build_state(epoch),
                               vocabs, config, epoch=epoch)


def _assert_restores_bit_equal(path, epoch):
    """The oracle: `path` must restore exactly the arrays `build_state`
    produced for `epoch` (save/restore is lossless, so any difference
    means the fallback chain landed on the wrong or a damaged artifact)."""
    expected = chaos_child.build_state(epoch)
    restored = ckpt_mod.load_model(path, chaos_child.build_state(0))
    assert int(np.asarray(restored.step)) == epoch * 10
    for name, arr in expected.params.items():
        np.testing.assert_array_equal(np.asarray(restored.params[name]), arr)


def _run_child(args, env=None, timeout=300):
    proc = subprocess.run([sys.executable, CHILD, *args],
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, env=env, timeout=timeout)
    return proc.returncode, proc.stdout


# ------------------------------------------------------------- faults.py

def test_fault_point_is_noop_when_unarmed():
    faults.reset(None)
    for _ in range(3):
        faults.fault_point("save")  # must not raise


def test_fault_hit_counting_fires_exactly_once():
    faults.reset("p@3=raise")
    faults.fault_point("p")
    faults.fault_point("p")
    with pytest.raises(faults.FaultInjected):
        faults.fault_point("p")
    faults.fault_point("p")  # hit 4 != 3: armed points fire exactly once
    faults.fault_point("other")  # unarmed point untouched


def test_fault_spec_errors_are_loud():
    # a typo'd spec silently injecting nothing would invalidate the test
    # that set it, so parsing fails loudly
    for bad in ("save@x=raise", "save=explode", "@2=raise", "save@0"):
        with pytest.raises(faults.FaultSpecError):
            faults.reset(bad)


# ---------------------------------------- crash-at-file-K during a save

@pytest.mark.parametrize("k", list(range(1, SAVE_FAULT_POINTS + 1)))
def test_crash_at_file_k_falls_back_to_previous_artifact(tmp_path, tiny, k):
    """A save interrupted at every file boundary: the final `_iter2` name
    must never exist half-written, and resume lands on `_iter1` with
    bit-equal params."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    faults.reset(f"save@{k}=raise")
    with pytest.raises(faults.FaultInjected):
        ckpt_mod.save_model(f"{base}_iter2", chaos_child.build_state(2),
                            vocabs, config, epoch=2)
    faults.reset(None)
    # the atomic commit never exposes a partial dir at the final name
    assert not os.path.exists(f"{base}_iter2")
    # only the staging dir is left behind, for the sweeper
    leftovers = [p for p in glob.glob(base + "_iter2*")]
    assert all(ckpt_mod.is_staging_path(p) for p in leftovers)
    found = ckpt_mod.latest_valid_checkpoint(base)
    assert found == f"{base}_iter1"
    _assert_restores_bit_equal(found, 1)


def test_crash_between_rename_and_cleanup(tmp_path, tiny):
    """Kill at the commit fault point itself (staged, rename pending):
    the new artifact is fully staged but not promoted — the previous one
    must still win."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    faults.reset("checkpoint_commit=raise")
    with pytest.raises(faults.FaultInjected):
        ckpt_mod.save_model(f"{base}_iter2", chaos_child.build_state(2),
                            vocabs, config, epoch=2)
    faults.reset(None)
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"
    _assert_restores_bit_equal(f"{base}_iter1", 1)


def test_kill_between_swap_renames_recovered_by_sweeper(tmp_path, tiny):
    """The one commit window where the final name is EMPTY: an overwrite
    save killed after `base -> .old` but before `.tmp -> base`. Both
    copies are intact; the sweeper must promote the newer (.tmp) one
    back instead of deleting two valid artifacts."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    faults.reset("checkpoint_swap=raise")
    with pytest.raises(faults.FaultInjected):
        ckpt_mod.save_model(f"{base}_iter1", chaos_child.build_state(5),
                            vocabs, config, epoch=1)
    faults.reset(None)
    assert not os.path.exists(f"{base}_iter1")  # the empty-slot window
    # the injected raise keeps THIS process alive, so hand the leftovers
    # to a dead pid — the on-disk state a real kill would leave
    for p in glob.glob(base + "_iter1.*"):
        os.rename(p, p.rsplit("-", 1)[0] + "-999999999")
    _facade_shim(Config(model_save_path=base, max_to_keep=5,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert os.path.exists(f"{base}_iter1")
    # the NEW (fully staged) state won the slot, not the .old backup
    _assert_restores_bit_equal(f"{base}_iter1", 5)
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"
    # and no commit-protocol leftovers remain
    assert not [p for p in glob.glob(base + "*")
                if ckpt_mod.is_staging_path(p)]


def test_interrupted_save_can_be_retried_in_same_process(tmp_path, tiny):
    """A failed save leaves its staging dir; the SAME process retrying
    the save (e.g. the next epoch boundary) must succeed, not trip over
    its own leftovers."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    faults.reset("save@2=raise")
    with pytest.raises(faults.FaultInjected):
        _save(base, 1, tiny)
    faults.reset(None)
    _save(base, 1, tiny)  # retry: must overwrite the stale staging dir
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"
    _assert_restores_bit_equal(f"{base}_iter1", 1)


def test_overwrite_commit_swaps_atomically(tmp_path, tiny):
    """Re-saving to an existing path goes through the backup swap; the
    committed artifact carries the NEW state and no `.old-` backup
    lingers."""
    base = str(tmp_path / "m")
    path = _save(base, 1, tiny)
    vocabs, config = tiny
    ckpt_mod.save_model(f"{base}_iter1", chaos_child.build_state(3),
                        vocabs, config, epoch=3)
    _assert_restores_bit_equal(path, 3)
    assert not [p for p in glob.glob(base + "*")
                if ckpt_mod.BACKUP_INFIX in os.path.basename(p)]


# -------------------------------------- hard kills (subprocess, os._exit)

@pytest.mark.parametrize("k", [2, 4, 5])
def test_hard_kill_during_save_subprocess(tmp_path, k):
    """os._exit at file boundary K of the second save — the closest
    in-process stand-in for SIGKILL/power loss (no unwinding, no cleanup
    handlers). The child's first save committed; resume must land on it
    bit-equal."""
    base = str(tmp_path / "m")
    rc, out = _run_child(["save-seq", base, "2", f"save@{k}=exit"])
    assert rc == faults.FAULT_EXIT_CODE, out
    assert "CHAOS_SAVED 1" in out
    assert "CHAOS_SAVED 2" not in out
    assert not os.path.exists(f"{base}_iter2")
    found = ckpt_mod.latest_valid_checkpoint(base)
    assert found == f"{base}_iter1"
    _assert_restores_bit_equal(found, 1)


def test_env_var_fault_kill_first_save_leaves_no_valid_artifact(tmp_path):
    """The env-var arming path (C2V_FAULTS set before the interpreter
    starts): the only save dies fully staged but uncommitted, so there is
    NO valid artifact — and latest_valid_checkpoint says so instead of
    returning the staging dir."""
    base = str(tmp_path / "m")
    env = {**os.environ, faults.FAULTS_ENV: "save@5=exit"}
    rc, out = _run_child(["save-seq", base, "1"], env=env)
    assert rc == faults.FAULT_EXIT_CODE, out
    staged = glob.glob(base + "_iter1*")
    assert staged and all(ckpt_mod.is_staging_path(p) for p in staged)
    assert ckpt_mod.latest_valid_checkpoint(base) is None


# ------------------------------- integrity verification + fallback chain

def _a_state_file(artifact):
    """Largest file under the artifact's Orbax state dir."""
    files = [p for p in glob.glob(os.path.join(artifact, "state", "**"),
                                  recursive=True) if os.path.isfile(p)]
    assert files
    return max(files, key=os.path.getsize)


def test_truncated_state_file_fails_fast_with_named_file(tmp_path, tiny):
    base = str(tmp_path / "m")
    path = _save(base, 1, tiny)
    victim = _a_state_file(path)
    with open(victim, "r+b") as f:
        f.truncate(max(os.path.getsize(victim) // 2, 1))
    with pytest.raises(ckpt_mod.CheckpointIntegrityError) as ei:
        ckpt_mod.load_model(path, chaos_child.build_state(0))
    # fails fast naming the truncated file, not an opaque pytree error
    assert os.path.basename(victim) in str(ei.value)
    assert "truncated" in str(ei.value)


def test_deleted_state_file_detected_and_skipped(tmp_path, tiny):
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    newest = _save(base, 2, tiny)
    os.remove(_a_state_file(newest))
    skips = []
    found = ckpt_mod.latest_valid_checkpoint(base, log=skips.append)
    assert found == f"{base}_iter1"
    assert any("Skipping corrupt/partial checkpoint" in m for m in skips)
    _assert_restores_bit_equal(found, 1)


def test_corrupt_manifest_skipped(tmp_path, tiny):
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    newest = _save(base, 2, tiny)
    with open(os.path.join(newest, ckpt_mod.MANIFEST_NAME), "w") as f:
        f.write("{ not json")
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"


def test_bitflip_in_dictionaries_caught_by_checksum(tmp_path, tiny):
    """Same-size corruption (a flipped byte) is invisible to size checks;
    the sha256 in the manifest catches it."""
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    newest = _save(base, 2, tiny)
    dict_path = os.path.join(newest, "dictionaries.bin")
    data = bytearray(open(dict_path, "rb").read())
    data[len(data) // 2] ^= 0xFF
    with open(dict_path, "wb") as f:
        f.write(bytes(data))
    with pytest.raises(ckpt_mod.CheckpointIntegrityError) as ei:
        ckpt_mod.verify_checkpoint(newest)
    assert "sha256 mismatch" in str(ei.value)
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"


def test_legacy_artifact_without_manifest_still_loads(tmp_path, tiny):
    """Pre-manifest artifacts (older saves) pass the structural probe and
    remain loadable; a half-written legacy dir does not."""
    base = str(tmp_path / "m")
    path = _save(base, 1, tiny)
    os.remove(os.path.join(path, ckpt_mod.MANIFEST_NAME))
    assert ckpt_mod.latest_valid_checkpoint(base) == path
    _assert_restores_bit_equal(path, 1)
    # gut it down to the half-write the old layout could leave
    os.remove(os.path.join(path, "code2vec_meta.json"))
    assert ckpt_mod.latest_valid_checkpoint(base) is None


def test_preempt_artifact_preferred_at_equal_epoch(tmp_path, tiny):
    """At equal N the `_preempt` artifact wins (mid-epoch-N+1 params are
    strictly more trained) — but only while it verifies."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    _save(base, 2, tiny)
    preempt = ckpt_mod.save_model(f"{base}_iter2_preempt",
                                  chaos_child.build_state(3),
                                  vocabs, config, epoch=2)
    assert ckpt_mod.latest_valid_checkpoint(base) == preempt
    os.remove(_a_state_file(preempt))
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter2"


def test_resolve_load_path(tmp_path, tiny):
    base = str(tmp_path / "m")
    art1 = _save(base, 1, tiny)
    art2 = _save(base, 2, tiny)
    # a concrete artifact dir resolves to itself
    assert ckpt_mod.resolve_load_path(art1) == art1
    # a save base resolves to the newest VALID artifact
    assert ckpt_mod.resolve_load_path(base) == art2
    os.remove(_a_state_file(art2))
    assert ckpt_mod.resolve_load_path(base) == art1


# ------------------------------------------------------ rotation safety

def _facade_shim(config):
    """A Code2VecModel with only the attributes rotation needs — building
    the full model (vocabs, mesh, jitted state) is irrelevant to the
    on-disk policy under test."""
    from code2vec_tpu.model_facade import Code2VecModel
    shim = Code2VecModel.__new__(Code2VecModel)
    shim.config = config
    shim.log = lambda *_: None
    return shim


def test_rotation_sweeps_orphaned_staging_dirs(tmp_path, tiny):
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    dead = f"{base}_iter9{ckpt_mod.STAGING_INFIX}999999999"
    live = f"{base}_iter9{ckpt_mod.STAGING_INFIX}{os.getpid()}"
    os.makedirs(dead)
    os.makedirs(live)
    _facade_shim(Config(model_save_path=base, max_to_keep=5,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert not os.path.exists(dead)    # orphan of a killed save: swept
    assert os.path.exists(live)        # live process's staging: untouched
    assert os.path.exists(f"{base}_iter1")


def test_rotation_never_deletes_the_only_valid_artifact(tmp_path, tiny):
    """max_to_keep=2 with the two newest artifacts corrupt: the oldest —
    the only one that verifies — must survive rotation."""
    base = str(tmp_path / "m")
    for e in (1, 2, 3):
        _save(base, e, tiny)
    for e in (2, 3):
        os.remove(_a_state_file(f"{base}_iter{e}"))
    _facade_shim(Config(model_save_path=base, max_to_keep=2,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert os.path.exists(f"{base}_iter1")
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"


def test_rotation_keeps_rotating_when_retained_are_valid(tmp_path, tiny):
    base = str(tmp_path / "m")
    for e in (1, 2, 3):
        _save(base, e, tiny)
    _facade_shim(Config(model_save_path=base, max_to_keep=2,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert not os.path.exists(f"{base}_iter1")
    assert os.path.exists(f"{base}_iter2")
    assert os.path.exists(f"{base}_iter3")


def test_corrupt_clean_save_does_not_supersede_preempt(tmp_path, tiny):
    """A preemption artifact is only deleted when a NEWER clean artifact
    actually verifies; a corrupt clean save must not take the only
    loadable state down with it."""
    vocabs, config = tiny
    base = str(tmp_path / "m")
    preempt = ckpt_mod.save_model(f"{base}_iter2_preempt",
                                  chaos_child.build_state(2),
                                  vocabs, config, epoch=2)
    corrupt = _save(base, 3, tiny)
    os.remove(_a_state_file(corrupt))
    _facade_shim(Config(model_save_path=base, max_to_keep=5,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert os.path.exists(preempt)
    assert ckpt_mod.latest_valid_checkpoint(base) == preempt
    # once a VALID newer clean artifact exists, the preempt is reclaimed
    _save(base, 4, tiny)
    _facade_shim(Config(model_save_path=base, max_to_keep=5,
                        train_data_path_prefix="x"))._rotate_epoch_checkpoints()
    assert not os.path.exists(preempt)


# ------------------------------------------------- NaN/Inf loss sentinel

def _fake_batch(n=2, m=4):
    return RowBatch(
        source_token_indices=np.ones((n, m), np.int32),
        path_indices=np.ones((n, m), np.int32),
        target_token_indices=np.ones((n, m), np.int32),
        context_valid_mask=np.ones((n, m), np.float32),
        target_index=np.ones((n,), np.int32),
        example_valid=np.ones((n,), bool))


class _State:
    step = np.zeros((), np.int32)


def _marker_stream(batches_per_epoch, epochs):
    for e in range(epochs):
        for _ in range(batches_per_epoch):
            yield _fake_batch()
        yield EpochEnd(e + 1)


def test_nonfinite_loss_halt_checkpoints_and_raises(tiny_config):
    """`halt` policy: the first NaN log-window average triggers a
    preemption-style checkpoint (suffix `_preempt`, never clobbering the
    clean artifact) and a nonzero exit via NonFiniteLossError."""
    tiny_config.num_train_epochs = 2
    tiny_config.num_batches_to_log_progress = 2
    tiny_config.verbose_mode = 0
    tiny_config.on_nonfinite_loss = "halt"
    saves, steps = [], []

    def train_step(state, *args):
        steps.append(1)
        return state, (np.float32("nan") if len(steps) >= 3
                       else np.float32(1.0))

    def save_fn(state, epoch, suffix=""):
        saves.append((epoch, suffix))

    trainer = Trainer(tiny_config, train_step, save_fn=save_fn)
    with pytest.raises(NonFiniteLossError, match="nan"):
        trainer.train(_State(), _marker_stream(8, 2),
                      rng=np.zeros((2,), np.uint32))
    assert len(steps) == 4          # stopped at the first NaN log window
    # `_nanhalt`, not `_preempt`: the poisoned state must never be the
    # artifact an auto-restarted `--load <base>` resolves to (that would
    # be an infinite NaN crash loop)
    assert saves == [(0, "_nanhalt")]
    assert trainer.preempted
    assert ckpt_mod.parse_iter_name("m_iter0_nanhalt") is None


def test_nonfinite_loss_warn_continues(tiny_config):
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 2
    tiny_config.on_nonfinite_loss = "warn"
    logs = []
    tiny_config.log = logs.append
    steps = []

    def train_step(state, *args):
        steps.append(1)
        return state, (np.float32("inf") if len(steps) == 3
                       else np.float32(1.0))

    saves = []
    trainer = Trainer(tiny_config, train_step,
                      save_fn=lambda s, e, suffix="": saves.append(e))
    trainer.train(_State(), _marker_stream(6, 1),
                  rng=np.zeros((2,), np.uint32))
    assert len(steps) == 6          # ran the full epoch
    assert saves == [1]             # normal end-of-epoch save, no preempt
    assert any("Non-finite average loss" in m for m in logs)


def test_nonfinite_policy_validated_by_config():
    with pytest.raises(ValueError, match="on_nonfinite_loss"):
        Config(train_data_path_prefix="x",
               on_nonfinite_loss="explode").verify()


# -------------------------------------------------- profiler trace leak

def test_exception_mid_trace_does_not_leak_open_trace(tiny_config, tmp_path):
    """A crash between start_trace (batch 10) and stop_trace (batch 20)
    must close the trace in the loop's finally block — a leaked trace
    poisons every later profiler use in the process."""
    import jax
    tiny_config.num_train_epochs = 1
    tiny_config.verbose_mode = 0
    steps = []

    def train_step(state, *args):
        steps.append(1)
        if len(steps) == 14:
            raise RuntimeError("boom mid-trace")
        return state, np.float32(1.0)

    trainer = Trainer(tiny_config, train_step,
                      profile_dir=str(tmp_path / "trace"))
    with pytest.raises(RuntimeError, match="boom mid-trace"):
        trainer.train(_State(), _marker_stream(25, 1),
                      rng=np.zeros((2,), np.uint32))
    # if the trace leaked, a fresh start_trace raises "already started"
    jax.profiler.start_trace(str(tmp_path / "trace2"))
    jax.profiler.stop_trace()


# ------------------------------------------- SIGTERM preemption, for real

def test_sigterm_mid_train_writes_preempt_artifact_and_resumes(tmp_path):
    """The whole preemption story in a real subprocess: SIGTERM lands
    mid-train, the watcher checkpoints `_iter<N>_preempt` within the
    grace window and exits 0; `--load <save_base>` then resolves to that
    preemption artifact and resumes its epoch numbering."""
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    base = str(run_dir / "model")
    proc = subprocess.Popen(
        [sys.executable, CHILD, "train", str(tmp_path), base],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        # wait for the first COMMITTED artifact, then preempt
        deadline = time.time() + 240
        while time.time() < deadline:
            if proc.poll() is not None:
                pytest.fail(f"train child died early:\n{proc.stdout.read()}")
            if ckpt_mod.latest_valid_checkpoint(base):
                break
            time.sleep(0.2)
        else:
            pytest.fail("no checkpoint appeared within the deadline")
        proc.send_signal(signal.SIGTERM)
        out, _ = proc.communicate(timeout=240)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out
    assert "CHAOS_TRAIN_DONE" in out

    preempts = glob.glob(base + "_iter*_preempt")
    assert preempts, f"no preemption artifact written:\n{out}"
    meta = ckpt_mod.verify_checkpoint(preempts[0])  # committed + intact
    assert meta["epoch"] >= 1

    # resume: the facade resolves --load <base> past nothing-in-particular
    # to the preemption artifact and continues its epoch numbering
    from code2vec_tpu.model_facade import Code2VecModel
    cfg = Config(model_load_path=base, max_contexts=8,
                 default_embeddings_size=16, compute_dtype="float32",
                 use_packed_data=False, verbose_mode=0)
    model = Code2VecModel(cfg)
    assert cfg.model_load_path.endswith("_preempt")
    assert model.initial_epoch == meta["epoch"]


# ------------------------------------------- serving extractor timeouts

def _extractor(tmp_path, timeout=None):
    from code2vec_tpu.serving.extractor_bridge import PathExtractor
    config = Config(max_contexts=4, train_data_path_prefix="x")
    return PathExtractor(config, timeout=timeout)


def test_extractor_timeout_kills_hung_child(tmp_path):
    ex = _extractor(tmp_path, timeout=1.0)
    ex._build_command = lambda path: [
        sys.executable, "-c",
        "import sys,time; print('hello'); sys.stdout.flush(); "
        "sys.stderr.write('still going'); sys.stderr.flush(); "
        "time.sleep(600)"]
    from code2vec_tpu.serving.extractor_bridge import ExtractionTimeout
    start = time.time()
    with pytest.raises(ExtractionTimeout) as ei:
        ex.extract_paths("whatever.java")
    assert time.time() - start < 30  # killed, not waited out
    assert "still going" in str(ei.value)
    # ValueError subclass: the interactive REPL's catch-print-continue
    # handles a timeout like any other failed extraction
    assert isinstance(ei.value, ValueError)


def test_extractor_nonzero_exit_surfaces_stderr_despite_stdout(tmp_path):
    """The old bridge trusted any non-empty stdout; a nonzero exit with
    partial output must raise and carry stderr."""
    ex = _extractor(tmp_path)
    ex._build_command = lambda path: [
        sys.executable, "-c",
        "import sys; print('target ctx,1,ctx'); "
        "sys.stderr.write('OutOfMemoryError mid-file'); sys.exit(3)"]
    with pytest.raises(ValueError) as ei:
        ex.extract_paths("whatever.java")
    assert "code 3" in str(ei.value)
    assert "OutOfMemoryError mid-file" in str(ei.value)


def test_cli_flags_roundtrip():
    from code2vec_tpu.cli import config_from_args
    cfg = config_from_args(["--data", "d", "--on_nonfinite_loss", "warn",
                            "--extractor_timeout", "9"])
    assert cfg.on_nonfinite_loss == "warn"
    assert cfg.extractor_timeout_s == 9.0
    cfg = config_from_args(["--data", "d"])
    assert cfg.on_nonfinite_loss == "halt"       # config.py default
    assert cfg.extractor_timeout_s == 120.0


def test_extractor_timeout_config_plumbing():
    from code2vec_tpu.serving.extractor_bridge import PathExtractor
    config = Config(max_contexts=4, train_data_path_prefix="x",
                    extractor_timeout_s=7.5)
    assert PathExtractor(config).timeout == 7.5
    assert PathExtractor(config, timeout=0).timeout is None  # 0 disables
    with pytest.raises(ValueError, match="extractor_timeout_s"):
        Config(train_data_path_prefix="x", extractor_timeout_s=-1).verify()


# ---------------------------------------------- post-commit content hashing


def test_content_hashing_catches_size_preserving_corruption(tmp_path, tiny):
    """`checkpoint_hash_content` records full-content sha256 for EVERY
    file (incl. the Orbax shards the commit-path manifest only
    size-checks) AFTER the atomic commit; resume's deep probe
    (`verify_checkpoint(check_content=True)`) must catch a
    size-preserving bitflip that the cheap probe cannot see."""
    import dataclasses
    import json as json_mod

    vocabs, config = tiny
    config = dataclasses.replace(config, checkpoint_hash_content=True)
    base = str(tmp_path / "model_iter1")
    out = ckpt_mod.save_model(base, chaos_child.build_state(1), vocabs,
                              config, epoch=1)
    with open(os.path.join(out, ckpt_mod.MANIFEST_NAME)) as f:
        manifest = json_mod.load(f)
    assert manifest["content_hashed"] is True
    state_files = [rel for rel in manifest["files"]
                   if rel.startswith("state" + os.sep)
                   or rel.startswith("state/")]
    assert state_files, "no Orbax state files in manifest"
    assert all("content_sha256" in entry
               for entry in manifest["files"].values())
    ckpt_mod.verify_checkpoint(out, check_content=True)

    # size-preserving bitflip in the largest state file
    big = max(state_files, key=lambda rel: manifest["files"][rel]["size"])
    victim = os.path.join(out, big)
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    with open(victim, "wb") as f:
        f.write(bytes(blob))
    ckpt_mod.verify_checkpoint(out)  # cheap probe: same sizes, passes
    with pytest.raises(ckpt_mod.CheckpointIntegrityError,
                       match="content sha256"):
        ckpt_mod.verify_checkpoint(out, check_content=True)


def test_content_hashing_off_by_default(tmp_path, tiny):
    """Without the flag the manifest carries no content hashes and the
    save path never pays the full-file hashing cost."""
    import json as json_mod

    vocabs, config = tiny
    out = ckpt_mod.save_model(str(tmp_path / "model_iter1"),
                              chaos_child.build_state(1), vocabs, config,
                              epoch=1)
    with open(os.path.join(out, ckpt_mod.MANIFEST_NAME)) as f:
        manifest = json_mod.load(f)
    assert "content_hashed" not in manifest
    state_entries = [entry for rel, entry in manifest["files"].items()
                     if rel.startswith("state")]
    assert state_entries
    assert all("content_sha256" not in entry for entry in state_entries)
    # and the deep probe is then simply a no-op extra check
    ckpt_mod.verify_checkpoint(out, check_content=True)


# --------------------------------------------- async commit pipeline

def _async_save(base, epoch, tiny, committer, config=None):
    vocabs, cfg = tiny
    return ckpt_mod.save_model(f"{base}_iter{epoch}",
                               chaos_child.build_state(epoch), vocabs,
                               config or cfg, epoch=epoch,
                               committer=committer)


def test_async_save_commits_and_restores_bit_equal(tmp_path, tiny):
    """The async pipeline must produce byte-for-byte the same artifact
    guarantees as the sync path: current-format manifest, verifiable,
    bit-equal restore — with the commit running on the background
    thread."""
    import json as json_mod
    base = str(tmp_path / "m")
    committer = ckpt_mod.AsyncCommitter(max_in_flight=2)
    _async_save(base, 1, tiny, committer)
    _async_save(base, 2, tiny, committer)
    committer.close()
    for epoch in (1, 2):
        ckpt_mod.verify_checkpoint(f"{base}_iter{epoch}")
        _assert_restores_bit_equal(f"{base}_iter{epoch}", epoch)
    with open(os.path.join(f"{base}_iter2", ckpt_mod.MANIFEST_NAME)) as f:
        manifest = json_mod.load(f)
    assert manifest["format"] == ckpt_mod.MANIFEST_FORMAT
    assert manifest["process_count"] == 1
    assert manifest["commit_acks"] == [0]


@pytest.mark.parametrize("k", list(range(1, SAVE_FAULT_POINTS + 1)))
def test_async_crash_at_file_k_falls_back(tmp_path, tiny, k):
    """The kill-at-every-file-boundary matrix with async commits on:
    points 1-3 fire in the synchronous staging half (submit-time raise),
    4-5 on the commit thread (surfaced by drain). Either way the final
    name never exists half-written and resume lands on `_iter1`."""
    base = str(tmp_path / "m")
    committer = ckpt_mod.AsyncCommitter(max_in_flight=2)
    _async_save(base, 1, tiny, committer)
    committer.drain()
    faults.reset(f"save@{k}=raise")
    with pytest.raises(faults.FaultInjected):
        _async_save(base, 2, tiny, committer)
        committer.drain()
    faults.reset(None)
    assert not os.path.exists(f"{base}_iter2")
    leftovers = [p for p in glob.glob(base + "_iter2*")]
    assert all(ckpt_mod.is_staging_path(p) for p in leftovers)
    found = ckpt_mod.latest_valid_checkpoint(base)
    assert found == f"{base}_iter1"
    _assert_restores_bit_equal(found, 1)
    committer._executor.shutdown(wait=True)


def test_async_commit_error_resurfaces_on_next_submit(tmp_path, tiny):
    """A commit that failed in the background must fail the NEXT save
    too (not only the final drain) — the trainer dies at the next epoch
    boundary instead of silently losing every checkpoint after the
    first failure."""
    base = str(tmp_path / "m")
    committer = ckpt_mod.AsyncCommitter(max_in_flight=2)
    faults.reset("async_commit=raise")
    _async_save(base, 1, tiny, committer)
    deadline = time.time() + 30
    while committer.in_flight and time.time() < deadline:
        time.sleep(0.01)   # let the background failure land, unconsumed
    faults.reset(None)
    with pytest.raises(faults.FaultInjected):
        _async_save(base, 2, tiny, committer)  # submit-time resurface
    # error was consumed; the pipeline is usable again
    _async_save(base, 2, tiny, committer)
    committer.close()
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter2"


def test_async_committer_backpressure_bounds_inflight():
    """submit() must block once max_in_flight commits are pending — a
    slow filesystem cannot queue unbounded half-finished saves."""
    import threading as th
    gate = th.Event()
    started = th.Event()
    committer = ckpt_mod.AsyncCommitter(max_in_flight=1)

    def slow_job():
        started.set()
        gate.wait(30)

    committer.submit(slow_job, "slow")
    started.wait(5)
    second_done = th.Event()

    def submit_second():
        committer.submit(lambda: None, "second")
        second_done.set()

    t = th.Thread(target=submit_second, daemon=True)
    t.start()
    # back-pressure: the second submit must NOT complete while the
    # first commit still occupies the only slot
    assert not second_done.wait(0.3)
    assert committer.in_flight == 1
    gate.set()
    assert second_done.wait(10)
    committer.close()
    assert committer.in_flight == 0


def test_trainer_drains_commits_before_preempt_save(tiny_config):
    """Preemption with async checkpointing: the in-flight commit is
    COMPLETED before the grace-window artifact is written (never
    interleaved, never abandoned)."""
    tiny_config.num_train_epochs = 1
    tiny_config.verbose_mode = 0
    events = []

    def train_step(state, *args):
        if len([e for e in events if e == "step"]) == 2:
            os.kill(os.getpid(), signal.SIGTERM)
        events.append("step")
        return state, np.float32(1.0)

    def save_fn(state, epoch, suffix=""):
        events.append(("save", suffix))

    trainer = Trainer(tiny_config, train_step, save_fn=save_fn,
                      commit_drain_fn=lambda: events.append("drain"))
    trainer.train(_State(), _marker_stream(20, 1),
                  rng=np.zeros((2,), np.uint32))
    assert trainer.preempted
    assert ("save", "_preempt") in events
    # the drain happened BEFORE the preemption save
    assert events.index("drain") < events.index(("save", "_preempt"))


def test_trainer_finally_drain_failure_fails_the_run(tiny_config):
    """A background commit failure with an otherwise-clean loop exit
    must fail the run (exit nonzero), not evaporate with the commit
    thread — and the heartbeat must say why."""
    tiny_config.num_train_epochs = 1
    tiny_config.verbose_mode = 0

    def drain():
        raise RuntimeError("orbax flush exploded in the background")

    trainer = Trainer(tiny_config, lambda s, *a: (s, np.float32(1.0)),
                      commit_drain_fn=drain)
    with pytest.raises(RuntimeError, match="exploded in the background"):
        trainer.train(_State(), _marker_stream(4, 1),
                      rng=np.zeros((2,), np.uint32))


def test_manifest_incomplete_participant_set_rejected(tmp_path, tiny):
    """An artifact whose recorded commit-ack set is short of its
    process_count (a host died between the barrier and the manifest)
    must fail verification and be walked past by resume."""
    import json as json_mod
    base = str(tmp_path / "m")
    _save(base, 1, tiny)
    newest = _save(base, 2, tiny)
    manifest_path = os.path.join(newest, ckpt_mod.MANIFEST_NAME)
    with open(manifest_path) as f:
        manifest = json_mod.load(f)
    manifest["process_count"] = 2          # pretends to be a pod save
    manifest["commit_acks"] = [0]          # ...with one ack missing
    with open(manifest_path, "w") as f:
        json_mod.dump(manifest, f)
    with pytest.raises(ckpt_mod.CheckpointIntegrityError,
                       match="participant set"):
        ckpt_mod.verify_checkpoint(newest)
    assert ckpt_mod.latest_valid_checkpoint(base) == f"{base}_iter1"


def test_format1_manifest_without_participant_fields_still_loads(
        tmp_path, tiny):
    """Pre-barrier (format 1) manifests carry no participant record;
    they must remain loadable, not rejected for missing acks."""
    import json as json_mod
    base = str(tmp_path / "m")
    path = _save(base, 1, tiny)
    manifest_path = os.path.join(path, ckpt_mod.MANIFEST_NAME)
    with open(manifest_path) as f:
        manifest = json_mod.load(f)
    manifest["format"] = 1
    del manifest["process_count"]
    del manifest["commit_acks"]
    with open(manifest_path, "w") as f:
        json_mod.dump(manifest, f)
    ckpt_mod.verify_checkpoint(path)
    _assert_restores_bit_equal(path, 1)


# -------------------------------- heartbeat terminal-state diagnostics

def test_heartbeat_records_error_class_on_unhandled_crash(tiny_config,
                                                          tmp_path):
    """An unhandled trainer crash must leave status=error WITH the
    exception class in the heartbeat — distinguishable from a hang
    (stale file), a preemption, and a clean exit without log parsing."""
    import json as json_mod
    hb = str(tmp_path / "hb.json")
    tiny_config.heartbeat_file = hb
    tiny_config.num_train_epochs = 1
    tiny_config.verbose_mode = 0

    def train_step(state, *args):
        raise KeyError("poisoned batch layout")

    trainer = Trainer(tiny_config, train_step)
    with pytest.raises(KeyError):
        trainer.train(_State(), _marker_stream(4, 1),
                      rng=np.zeros((2,), np.uint32))
    with open(hb) as f:
        beat = json_mod.load(f)
    assert beat["status"] == "error"
    assert beat["error_type"] == "KeyError"
    assert "poisoned batch layout" in beat["error_message"]


# ------------------------------- distributed.initialize retry/backoff

def _reset_distributed_initialized():
    from code2vec_tpu.parallel import distributed
    distributed._initialized = False


def test_initialize_retries_transient_connect_failures(monkeypatch):
    """A transient coordinator-connect failure must be retried with
    backoff, NOT silently degrade the host to single-process (which
    would deadlock its peers' collectives)."""
    import jax
    from code2vec_tpu.parallel import distributed
    _reset_distributed_initialized()
    attempts, sleeps = [], []

    def flaky_init(**kwargs):
        attempts.append(kwargs)
        if len(attempts) < 3:
            raise RuntimeError("connect refused (coordinator booting)")

    monkeypatch.setattr(jax.distributed, "initialize", flaky_init)
    monkeypatch.setattr(
        "code2vec_tpu.parallel.distributed.time.sleep", sleeps.append)
    try:
        distributed.initialize(coordinator_address="host:1234",
                               num_processes=2, process_id=1)
        assert len(attempts) == 3
        assert sleeps == [0.5, 1.0]  # bounded exponential backoff
        assert distributed._initialized
    finally:
        _reset_distributed_initialized()


def test_initialize_explicit_coordinator_raises_after_retries(monkeypatch):
    import jax
    from code2vec_tpu.parallel import distributed
    _reset_distributed_initialized()
    attempts, sleeps = [], []

    def dead_init(**kwargs):
        attempts.append(1)
        raise RuntimeError("coordinator is gone")

    monkeypatch.setattr(jax.distributed, "initialize", dead_init)
    monkeypatch.setattr(
        "code2vec_tpu.parallel.distributed.time.sleep", sleeps.append)
    try:
        with pytest.raises(RuntimeError, match="coordinator is gone"):
            distributed.initialize(coordinator_address="host:1234")
        assert len(attempts) == distributed._INIT_ATTEMPTS
        assert not distributed._initialized
    finally:
        _reset_distributed_initialized()


def test_initialize_auto_detect_degrades_only_after_retries(monkeypatch):
    """The TPU-pod auto-detection path keeps its single-process
    fallback, but only AFTER the bounded retries are exhausted."""
    import jax
    from code2vec_tpu.parallel import distributed
    _reset_distributed_initialized()
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "w0,w1")
    attempts = []

    def dead_init(**kwargs):
        attempts.append(1)
        raise RuntimeError("no coordinator here")

    monkeypatch.setattr(jax.distributed, "initialize", dead_init)
    monkeypatch.setattr(
        "code2vec_tpu.parallel.distributed.time.sleep", lambda s: None)
    try:
        distributed.initialize()  # must not raise: degrades
        assert len(attempts) == distributed._INIT_ATTEMPTS
        assert not distributed._initialized
    finally:
        _reset_distributed_initialized()


# -------------------------------------- extractor launch/crash retries

def test_extractor_retries_transient_crash_then_succeeds(tmp_path):
    """A crashed extractor child (transient OOM/fork pressure) is
    retried with backoff and the call succeeds; the failure counter
    records the retried attempts under retried=yes."""
    from code2vec_tpu import obs
    marker = tmp_path / "attempts"
    ex = _extractor(tmp_path)
    ex.retries = 3
    ex._RETRY_BACKOFF_BASE_S = 0.01
    ex._build_command = lambda path: [
        sys.executable, "-c",
        "import os, sys\n"
        f"p = {str(marker)!r}\n"
        "n = int(open(p).read()) if os.path.exists(p) else 0\n"
        "open(p, 'w').write(str(n + 1))\n"
        "if n < 2:\n"
        "    sys.stderr.write('transient OOM'); sys.exit(137)\n"
        "print('target ctx,1,ctx')"]
    before = _failure_count("yes")
    result, _hashes = ex.extract_paths("whatever.java")
    assert len(result) == 1 and result[0].startswith("target")
    assert int(marker.read_text()) == 3      # 2 failures + 1 success
    assert _failure_count("yes") - before == 2


def _failure_count(retried: str) -> float:
    from code2vec_tpu import obs
    metrics = obs.default_registry().collect()
    children = metrics.get("extractor_failures_total", {})
    child = children.get((("retried", retried),))
    return float(child.value) if child is not None else 0.0


def test_extractor_exhausted_retries_surface_final_failure(tmp_path):
    ex = _extractor(tmp_path)
    ex.retries = 1
    ex._RETRY_BACKOFF_BASE_S = 0.01
    ex._build_command = lambda path: [
        sys.executable, "-c",
        "import sys; sys.stderr.write('persistent crash'); sys.exit(139)"]
    before_no = _failure_count("no")
    before_yes = _failure_count("yes")
    with pytest.raises(ValueError, match="persistent crash"):
        ex.extract_paths("whatever.java")
    assert _failure_count("no") - before_no == 1    # the surfaced failure
    assert _failure_count("yes") - before_yes == 1  # the retried attempt


def test_extractor_deterministic_rejection_not_retried(tmp_path):
    """A plain nonzero diagnostic exit (the extractor REJECTING its
    input, e.g. unparseable Java) would fail identically on every
    retry: it must surface immediately, without the crash-retry
    latency, and count as a non-retried failure."""
    ex = _extractor(tmp_path)
    ex.retries = 5
    calls = []
    real_inner = ex._extract_paths_inner

    def counting_inner(path):
        calls.append(1)
        return real_inner(path)

    ex._extract_paths_inner = counting_inner
    ex._build_command = lambda path: [
        sys.executable, "-c",
        "import sys; sys.stderr.write('syntax error at line 3'); "
        "sys.exit(2)"]
    before_no = _failure_count("no")
    with pytest.raises(ValueError, match="syntax error") as ei:
        ex.extract_paths("bad.java")
    from code2vec_tpu.serving.extractor_bridge import ExtractorCrash
    assert not isinstance(ei.value, ExtractorCrash)
    assert len(calls) == 1                          # no retries
    assert _failure_count("no") - before_no == 1


def test_extractor_timeout_is_never_retried(tmp_path):
    """A hung child already cost a full timeout; retrying would likely
    hang again — the timeout path keeps its own policy."""
    ex = _extractor(tmp_path, timeout=0.5)
    ex.retries = 5
    calls = []
    real_inner = ex._extract_paths_inner

    def counting_inner(path):
        calls.append(1)
        return real_inner(path)

    ex._extract_paths_inner = counting_inner
    ex._build_command = lambda path: [
        sys.executable, "-c", "import time; time.sleep(600)"]
    from code2vec_tpu.serving.extractor_bridge import ExtractionTimeout
    with pytest.raises(ExtractionTimeout):
        ex.extract_paths("whatever.java")
    assert len(calls) == 1


def test_extractor_retries_config_plumbing():
    from code2vec_tpu.serving.extractor_bridge import PathExtractor
    config = Config(max_contexts=4, train_data_path_prefix="x",
                    extractor_retries=7)
    assert PathExtractor(config).retries == 7
    assert PathExtractor(config, retries=0).retries == 0
    with pytest.raises(ValueError, match="extractor_retries"):
        Config(train_data_path_prefix="x", extractor_retries=-1).verify()


def test_new_cli_flags_roundtrip():
    from code2vec_tpu.cli import config_from_args
    cfg = config_from_args(["--data", "d", "--async_checkpointing",
                            "--save_barrier_timeout", "33",
                            "--extractor_retries", "5"])
    assert cfg.async_checkpointing is True
    assert cfg.save_barrier_timeout_s == 33.0
    assert cfg.extractor_retries == 5
    cfg = config_from_args(["--data", "d"])
    assert cfg.async_checkpointing is False
    assert cfg.save_barrier_timeout_s == 600.0    # config.py default
    assert cfg.extractor_retries == 2
    with pytest.raises(ValueError, match="save_barrier_timeout_s"):
        Config(train_data_path_prefix="x",
               save_barrier_timeout_s=0).verify()


def test_verify_degrades_when_file_vanishes_mid_probe(tmp_path, tiny,
                                                      monkeypatch):
    """A manifest-listed file that disappears BETWEEN the isfile() check
    and the stat/hash (a peer host's commit swap on a multi-host pod, or
    concurrent rotation) must surface as CheckpointIntegrityError — which
    the fallback walks tolerate by design — never as a raw OSError that
    crashes the trainer."""
    vocabs, config = tiny
    out = ckpt_mod.save_model(str(tmp_path / "model_iter1"),
                              chaos_child.build_state(1), vocabs, config,
                              epoch=1)

    real_getsize = os.path.getsize

    def racy_getsize(path):
        if path.endswith("dictionaries.bin"):
            raise FileNotFoundError(2, "vanished mid-probe", path)
        return real_getsize(path)

    monkeypatch.setattr(os.path, "getsize", racy_getsize)
    with pytest.raises(ckpt_mod.CheckpointIntegrityError,
                       match="mid-probe"):
        ckpt_mod.verify_checkpoint(out)
    # and latest_valid_checkpoint just walks past it instead of crashing
    assert ckpt_mod.latest_valid_checkpoint(
        str(tmp_path / "model"), log=lambda *_: None) is None
