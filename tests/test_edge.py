"""Edge-tier suite (code2vec_tpu/serving/fleet/edge.py + the router's
consistent-hash cache affinity + the remote HostLauncher seam):

- affinity ring laws (determinism, balance, minimal disruption) and
  the cache INVARIANTS affinity must preserve — byte-equality of
  responses whichever host answers, and fingerprint-keying across a
  hot-swap (a stale-fingerprint cache entry can never serve) — pinned
  against scripted 2-host backends running the real cache_key;
- SharedFleetView: candidate derivation from a polled /fleet snapshot,
  honest no-view/unknown-model semantics, admin relay with status
  pass-through (including 409);
- RemoteHostLauncher: {address} substitution, env filtering + shell
  quoting, and launch failure mapping onto the EXISTING host_down ->
  backoff -> host_escalation incident path;
- the (artifact, retrieval_index) PAIR a (re)spawned host reconciles
  onto (PR-15 residue);
- slow chaos drills: SIGKILL one of 2 router processes under 4-client
  load (zero failed requests — survivors absorb, control plane
  respawns), and a fleet-wide coordinated swap with N routers live
  whose killed host converges back onto the committed pair.

Fast tests run in tier-1; the drills are `slow` + `chaos` and run via
scripts/run_chaos.sh under EDGE_BUDGET.
"""

import http.server
import json
import os
import signal
import sys
import threading
import time

import pytest

from code2vec_tpu.config import Config

from test_serving import _counter_value
from test_fleet import (  # noqa: F401 — fake_extractor is a fixture
    FLEET_HOST, _all_routable, _fleet_config, _free_port, _get,
    _host_overrides, _post, _replica_overrides, _wait_fleet,
    _write_json, fake_extractor,
)

pytestmark = pytest.mark.edge

HERE = os.path.dirname(os.path.abspath(__file__))


def _router_test_config(**overrides):
    kwargs = dict(serve=True, serve_host="127.0.0.1",
                  serve_deadline_ms=2000.0, verbose_mode=0)
    kwargs.update(overrides)
    return Config(**kwargs)


# ------------------------------------------------- affinity ring laws


def test_affinity_ring_deterministic_and_balanced():
    from code2vec_tpu.serving.fleet.router import (
        AFFINITY_VNODES, affinity_host, affinity_ring,
    )

    hosts = ["default-0", "default-1", "default-2"]
    ring = affinity_ring(hosts)
    # order-independent and deterministic (no per-process salt: every
    # router in the tier must agree on the preferred host)
    assert ring == affinity_ring(list(reversed(hosts)))
    assert len(ring) == len(hosts) * AFFINITY_VNODES
    counts = {h: 0 for h in hosts}
    for i in range(3000):
        counts[affinity_host(f"key-{i}".encode(), ring)] += 1
    # vnodes keep the split rough-thirds, not exact — assert no host
    # owns a pathological share
    assert min(counts.values()) > 3000 / len(hosts) * 0.5, counts
    assert max(counts.values()) < 3000 / len(hosts) * 1.5, counts
    # stable per key
    assert (affinity_host(b"class A {}", ring)
            == affinity_host(b"class A {}", ring))
    assert affinity_host(b"anything", []) is None


def test_affinity_ring_removal_remaps_only_the_lost_hosts_keys():
    from code2vec_tpu.serving.fleet.router import (
        affinity_host, affinity_ring,
    )

    full = affinity_ring(["h0", "h1", "h2", "h3"])
    reduced = affinity_ring(["h0", "h1", "h3"])
    moved = 0
    for i in range(2000):
        key = f"key-{i}".encode()
        before = affinity_host(key, full)
        after = affinity_host(key, reduced)
        if before == "h2":
            moved += 1
            assert after != "h2"
        else:
            # consistent hashing's whole point: survivors keep their
            # keys (and their warm cache entries)
            assert after == before, key
    assert moved > 0


def test_apply_affinity_prefers_healthy_ring_host():
    from code2vec_tpu.serving.cache import normalize_source
    from code2vec_tpu.serving.fleet.router import (
        FleetRouter, affinity_host, affinity_ring, weighted_order,
    )
    from test_fleet import _StubControl

    config = _router_test_config()
    router = FleetRouter(config, _StubControl({}), host="127.0.0.1",
                         port=0, log=lambda m: None)
    try:
        body = b"class A { int f() { return 1; } }"
        candidates = [(1.0, "h0", ("127.0.0.1", 1)),
                      (1.0, "h1", ("127.0.0.1", 2)),
                      (0.1, "h2", ("127.0.0.1", 3))]
        # the ring holds FULLY-healthy hosts only: h2 (degraded, 0.1)
        # must never be preferred
        expected = affinity_host(
            normalize_source(body.decode()), affinity_ring(("h0", "h1")))
        for _ in range(25):
            ordered = weighted_order([(w, (hid, addr))
                                      for w, hid, addr in candidates])
            router._apply_affinity(body, candidates, ordered)
            assert ordered[0][0] == expected
            # affinity reorders, never drops: every candidate still
            # reachable by the retry walk
            assert sorted(h for h, _ in ordered) == ["h0", "h1", "h2"]
        # the affinity key is the NORMALIZED source: a reformatted
        # variant lands on the same host (where its cache entry is)
        variant = b"class A {\n    int f() {\n        return 1; } }"
        ordered = weighted_order([(w, (hid, addr))
                                  for w, hid, addr in candidates])
        router._apply_affinity(variant, candidates, ordered)
        assert ordered[0][0] == expected
        # no fully-healthy host at all -> pure weighted fallback,
        # order untouched
        degraded = [(0.1, "h0", ("127.0.0.1", 1)),
                    (0.1, "h1", ("127.0.0.1", 2))]
        ordered = weighted_order([(w, (hid, addr))
                                  for w, hid, addr in degraded])
        before = list(ordered)
        router._apply_affinity(body, degraded, ordered)
        assert ordered == before
        assert _counter_value("fleet_router_affinity_total",
                              outcome="fallback") >= 1
        assert _counter_value("fleet_router_affinity_total",
                              outcome="preferred") >= 25
    finally:
        router.close()


# --------------------------- cache invariants vs scripted 2-host fleet


class _CachingBackend(http.server.ThreadingHTTPServer):
    """Scripted host backend running the REAL cache keying
    (serving/cache.py cache_key, fingerprint-as-knob): response bytes
    are a deterministic function of (normalized source, fingerprint),
    cached exactly as a replica caches them."""

    daemon_threads = True

    def __init__(self):
        import hashlib

        from code2vec_tpu.serving.cache import (
            cache_key, normalize_source,
        )

        backend = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def do_POST(self):  # noqa: N802 (stdlib API name)
                length = int(self.headers.get("Content-Length", 0))
                code = self.rfile.read(length).decode()
                with backend.lock:
                    fp = backend.fingerprint
                    key = cache_key(code, endpoint="predict", topk=3,
                                    model=fp)
                    cached = backend.cache.get(key)
                    if cached is not None:
                        backend.hits += 1
                        body = cached
                    else:
                        backend.misses += 1
                        digest = hashlib.blake2b(
                            normalize_source(code),
                            digest_size=8).hexdigest()
                        body = json.dumps(
                            {"model_fingerprint": fp,
                             "methods": [{"digest": digest}]},
                            sort_keys=True).encode() + b"\n"
                        backend.cache[key] = body
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        super().__init__(("127.0.0.1", 0), Handler)
        self.lock = threading.Lock()
        self.fingerprint = "fp-v1"
        self.cache = {}
        self.hits = self.misses = 0
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]

    def swap_to(self, fingerprint):
        with self.lock:
            self.fingerprint = fingerprint


@pytest.fixture()
def two_host_backends():
    from test_fleet import _StubControl

    backends = {"h0": _CachingBackend(), "h1": _CachingBackend()}
    control = _StubControl({"default": [
        (1.0, hid, ("127.0.0.1", b.port))
        for hid, b in sorted(backends.items())]})
    yield backends, control
    for b in backends.values():
        b.shutdown()


def test_affinity_never_changes_response_bytes(two_host_backends):
    """The byte-equality invariant: affinity picks WHICH host answers;
    the response is a host-local function of (normalized source,
    knobs, fingerprint), so affinity-on and affinity-off responses are
    byte-identical — and repeats concentrate on ONE host's cache."""
    from code2vec_tpu.serving.fleet.router import FleetRouter

    backends, control = two_host_backends
    on = FleetRouter(_router_test_config(), control,
                     host="127.0.0.1", port=0, log=lambda m: None)
    off = FleetRouter(_router_test_config(fleet_cache_affinity=False),
                      control, host="127.0.0.1", port=0,
                      log=lambda m: None)
    try:
        assert on.affinity and not off.affinity
        sources = [f"class C{i} {{ int m{i}() {{ return {i}; }} }}"
                   for i in range(12)]
        for src in sources:
            first = _post(on.port, "/predict", src)[1]
            for _ in range(3):
                assert _post(on.port, "/predict", src)[1] == first
                assert _post(off.port, "/predict", src)[1] == first
            # a whitespace variant shares the cache entry AND the bytes
            variant = src.replace(" { ", " {\n    ")
            assert _post(on.port, "/predict", variant)[1] == first
        # with affinity on, each source warmed exactly ONE host: every
        # affinity-routed request either missed once or hit — no
        # double-warming across the fleet for affinity-routed traffic
        # (the off-router's sampled requests also hit: both routers
        # share the backends, and bytes are identical either way)
        hits = sum(b.hits for b in backends.values())
        misses = sum(b.misses for b in backends.values())
        assert misses >= len(sources)
        assert hits > misses  # repeats + variants overwhelmingly hit
        # both hosts took a share of the keyspace
        assert all(b.misses > 0 for b in backends.values()), \
            {h: b.misses for h, b in backends.items()}
    finally:
        on.close()
        off.close()


def test_hot_swap_mid_affinity_window_never_serves_stale_fingerprint(
        two_host_backends):
    """The fingerprint-keying invariant: affinity keeps routing a
    source to the same host across a hot-swap, and that host's cache
    still HOLDS the old-fingerprint entry — but the key includes the
    live fingerprint, so the stale bytes can never serve."""
    from code2vec_tpu.serving.fleet.router import FleetRouter

    backends, control = two_host_backends
    router = FleetRouter(_router_test_config(), control,
                         host="127.0.0.1", port=0, log=lambda m: None)
    try:
        src = "class Swap { int mid() { return 7; } }"
        before = json.loads(_post(router.port, "/predict", src)[1])
        assert before["model_fingerprint"] == "fp-v1"
        assert _post(router.port, "/predict", src)[1]  # warm the entry
        stale_entries = sum(len(b.cache) for b in backends.values())
        assert stale_entries >= 1
        for b in backends.values():
            b.swap_to("fp-v2")
        after = json.loads(_post(router.port, "/predict", src)[1])
        # same source, same preferred host, old entry still cached —
        # the response MUST carry the new fingerprint
        assert after["model_fingerprint"] == "fp-v2"
        assert after["methods"] == before["methods"]  # same content
        # the stale entry was never evicted, only out-keyed
        assert sum(len(b.cache) for b in backends.values()) \
            > stale_entries
    finally:
        router.close()


# ------------------------------------------------- shared fleet view


class _ControlListener(http.server.ThreadingHTTPServer):
    """Canned control-plane listener: /fleet JSON, /metrics text, and
    scripted admin status codes (409 pass-through is the interesting
    one)."""

    daemon_threads = True

    def __init__(self, view):
        listener = self

        class Handler(http.server.BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _reply(self, code, body, ctype="application/json"):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (stdlib API name)
                if self.path == "/fleet":
                    self._reply(200, json.dumps(listener.view).encode())
                elif self.path == "/metrics":
                    self._reply(
                        200,
                        b"# TYPE fleet_swap_total counter\n"
                        b'fleet_swap_total{outcome="committed"} 2\n',
                        ctype="text/plain")
                else:
                    self._reply(404, b"{}")

            def do_POST(self):  # noqa: N802 (stdlib API name)
                length = int(self.headers.get("Content-Length", 0))
                listener.admin_bodies.append(
                    (self.path, json.loads(self.rfile.read(length))))
                code, payload = listener.admin_replies.get(
                    self.path, (404, {"error": "no such endpoint"}))
                self._reply(code, json.dumps(payload).encode())

        super().__init__(("127.0.0.1", 0), Handler)
        self.view = view
        self.admin_bodies = []
        self.admin_replies = {}
        threading.Thread(target=self.serve_forever, daemon=True).start()

    @property
    def port(self):
        return self.server_address[1]


_CANNED_VIEW = {
    "role": "fleet-control",
    "models": {"default": {"routable": 2}},
    "hosts": [
        {"host": "default-0", "model": "default", "weight": 1.0,
         "address": "10.0.0.5", "port": 8101},
        {"host": "default-1", "model": "default", "weight": 0.1,
         "port": 8102},                       # no address -> loopback
        {"host": "default-2", "model": "default", "weight": 1.0,
         "address": "10.0.0.7", "port": None},  # no port -> dropped
    ],
}


def test_shared_fleet_view_derives_candidates_and_view():
    from code2vec_tpu.serving.fleet.edge import SharedFleetView

    listener = _ControlListener(_CANNED_VIEW)
    try:
        view = SharedFleetView(_router_test_config(),
                               f"127.0.0.1:{listener.port}",
                               "router-7", log=lambda m: None)
        # before the first successful poll: an EMPTY candidate list
        # (retryable 503), never a None (that would 404 a real model)
        assert view.hosts_for("default") == []
        assert view.view_age_s() is None
        assert view.refresh()
        assert view.hosts_for("default") == [
            (1.0, "default-0", ("10.0.0.5", 8101)),
            (0.1, "default-1", ("127.0.0.1", 8102)),
        ]
        assert view.hosts_for("nope") is None  # known models, not this
        fleet = view.fleet_view()
        assert fleet["role"] == "fleet-router"
        assert fleet["router"] == "router-7"
        assert fleet["view_age_s"] is not None
        # metrics re-merge: the listener's counter survives alongside
        # this process's own registry
        merged = view.merged_fleet_metrics()
        assert 'fleet_swap_total{outcome="committed"} 2' in merged
        with pytest.raises(ValueError):
            SharedFleetView(_router_test_config(), "no-port", "r",
                            log=lambda m: None)
    finally:
        listener.shutdown()


def test_router_forwards_x_tenant_to_backend():
    """The tenant identity pin (serving/tenancy.py): X-Tenant rides
    the shared forwarding contract router -> host, alongside X-Model
    and X-Deadline-Ms — a header in REQUEST_FORWARD_HEADERS can never
    silently stop at one hop."""
    from code2vec_tpu.serving.fleet.router import FleetRouter
    from code2vec_tpu.serving.forwarding import REQUEST_FORWARD_HEADERS
    from test_fleet import _StubControl

    assert "X-Tenant" in REQUEST_FORWARD_HEADERS

    captured = []

    class _Capture(http.server.ThreadingHTTPServer):
        daemon_threads = True

        def __init__(self):
            class Handler(http.server.BaseHTTPRequestHandler):
                protocol_version = "HTTP/1.1"

                def log_message(self, *args):
                    pass

                def do_POST(self):  # noqa: N802 (stdlib API name)
                    length = int(self.headers.get("Content-Length", 0))
                    self.rfile.read(length)
                    captured.append(dict(self.headers))
                    body = b'{"ok": true}\n'
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

            super().__init__(("127.0.0.1", 0), Handler)
            threading.Thread(target=self.serve_forever,
                             daemon=True).start()

    backend = _Capture()
    control = _StubControl({"default": [
        (1.0, "h0", ("127.0.0.1", backend.server_address[1]))]})
    router = FleetRouter(_router_test_config(), control,
                         host="127.0.0.1", port=0, log=lambda m: None)
    try:
        status, _, _ = _post(router.port, "/predict",
                             "class A { int f() { return 1; } }",
                             headers={"X-Tenant": "acme",
                                      "X-Deadline-Ms": "1500"})
        assert status == 200
        [headers] = captured
        assert headers.get("X-Tenant") == "acme"
        assert headers.get("X-Deadline-Ms") == "1500"
        # absent header stays absent: the backend sees exactly what
        # the client sent, never an injected default
        captured.clear()
        status, _, _ = _post(router.port, "/predict",
                             "class A { int g() { return 2; } }")
        assert status == 200
        [headers] = captured
        assert "X-Tenant" not in headers
    finally:
        router.close()
        backend.shutdown()


def test_shared_fleet_view_admin_relay_passes_status_through():
    from code2vec_tpu.serving.fleet.edge import SharedFleetView

    listener = _ControlListener(_CANNED_VIEW)
    listener.admin_replies = {
        "/admin/reload": (409, {"error": "a fleet swap is already in "
                                         "flight"}),
        "/admin/scale": (200, {"host": "default-0",
                               "desired_replicas": 3}),
        "/admin/drain": (202, {"host": "default-1", "draining": True}),
    }
    try:
        view = SharedFleetView(_router_test_config(),
                               f"127.0.0.1:{listener.port}",
                               "router-0", log=lambda m: None)
        assert view.refresh()
        code, body = view.request_swap({"artifact": "/a/v2"})
        assert (code, body["error"].startswith("a fleet swap")) \
            == (409, True)
        assert view.request_scale("default-0", 3) \
            == (200, {"host": "default-0", "desired_replicas": 3})
        assert view.drain_host("default-1")[0] == 202
        # the payload reached the listener verbatim
        assert ("/admin/reload", {"artifact": "/a/v2"}) \
            in listener.admin_bodies
    finally:
        listener.shutdown()
    # control plane gone: refresh fails but keeps the cached view;
    # admin relays answer an honest 503
    assert not view.refresh()
    assert view.hosts_for("default") != []
    code, body = view.request_swap({"artifact": "/a/v3"})
    assert code == 503 and "unreachable" in body["error"]


# --------------------------------------------- remote host launcher


def test_remote_launcher_substitutes_address_filters_env_and_quotes(
        tmp_path):
    from code2vec_tpu.serving.fleet.control import (
        FLEET_HOST_ADDRESS_ENV, RemoteHostLauncher,
    )

    recorder = tmp_path / "fakessh"
    args_out = tmp_path / "args.txt"
    recorder.write_text("#!/bin/sh\n"
                        f"printf '%s\\n' \"$@\" > {args_out}\n")
    recorder.chmod(0o755)
    launcher = RemoteHostLauncher(f"{recorder} {{address}}")
    env = dict(os.environ,
               **{FLEET_HOST_ADDRESS_ENV: "10.1.2.3",
                  "C2V_FLEET_HOST": "default-0",
                  "PYTHONPATH": "/repo path",        # space survives
                  "SECRET_TOKEN": "must-not-travel"})
    proc = launcher.launch(
        [sys.executable, "-m", "code2vec_tpu.cli", "serve",
         "--fleet_models", "default=/a b/v1"],
        env, str(tmp_path / "host.log"))
    assert proc.wait(timeout=30) == 0
    lines = args_out.read_text().splitlines()
    assert lines[0] == "10.1.2.3"  # {address} became the wrapper arg
    remote = lines[1]
    assert remote.startswith("env ")
    assert "C2V_FLEET_HOST=default-0" in remote
    assert f"{FLEET_HOST_ADDRESS_ENV}=10.1.2.3" in remote
    assert "'/repo path'" in remote          # quoted for the far shell
    assert "SECRET_TOKEN" not in remote      # filtered, not exported
    assert "'default=/a b/v1'" in remote     # command args quoted too
    with pytest.raises(ValueError):
        RemoteHostLauncher("   ")


def test_remote_launcher_command_survives_a_real_shell(tmp_path):
    # "sh -c" is the degenerate remote substrate: the flattened
    # `env K=V ... cmd` word must execute verbatim under a real shell
    from code2vec_tpu.serving.fleet.control import (
        FLEET_HOST_ADDRESS_ENV, RemoteHostLauncher,
    )

    launcher = RemoteHostLauncher("sh -c")
    log_path = str(tmp_path / "host.log")
    env = dict(os.environ, **{FLEET_HOST_ADDRESS_ENV: "10.9.9.9",
                              "C2V_MARKER": "it's \"quoted\""})
    proc = launcher.launch(
        [sys.executable, "-c",
         "import os; print(os.environ['C2V_MARKER'], "
         "os.environ['" + FLEET_HOST_ADDRESS_ENV + "'])"],
        env, log_path)
    assert proc.wait(timeout=30) == 0
    assert open(log_path).read().strip() \
        == "it's \"quoted\" 10.9.9.9"


def test_remote_launch_failure_rides_host_down_then_escalates(
        tmp_path):
    from code2vec_tpu.serving.fleet.control import (
        ControlPlane, HostSpec, RemoteHostLauncher,
    )

    config = Config(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_models="default=/a/v1", fleet_max_host_restarts=1,
        fleet_addresses="10.0.0.1",
        fleet_launcher="/nonexistent-wrapper-xyz {address}",
        heartbeat_file=str(tmp_path / "fleet.heartbeat.json"))
    config.verify()
    restarts_before = _counter_value("fleet_host_restarts_total")
    control = ControlPlane(
        config, [HostSpec("default-0", ["true"], address="10.0.0.1")],
        launcher=RemoteHostLauncher(config.fleet_launcher),
        log=lambda m: None)
    host = control.hosts[0]
    control._spawn(host)
    # the missing wrapper binary joined the ORDINARY death path:
    # host_down incident, backoff gate armed, restart budget ticking
    assert host.proc is None
    assert host.restarts == 1
    assert host.restart_at is not None
    assert not control._escalated
    assert _counter_value("fleet_host_restarts_total") \
        == restarts_before + 1
    # the retry fails the same way and exhausts the budget ->
    # host_escalation, fleet stop
    host.restart_at = 0.0
    control._check_host(host, time.monotonic())
    assert control._escalated
    assert control._stop.is_set()


# ------------------------- (artifact, retrieval_index) reconciliation


class _FakeProc:
    pid = 4242

    def poll(self):
        return None

    def wait(self, timeout=None):
        return 0

    def send_signal(self, sig):
        pass


class _RecordingLauncher:
    def __init__(self):
        self.launches = []

    def launch(self, command, env, log_path):
        self.launches.append((list(command), dict(env), log_path))
        return _FakeProc()


def test_respawned_host_reconciles_onto_artifact_index_pair(tmp_path):
    """PR-15 residue: a host (re)spawned after a retrieval_refresh must
    get the (artifact, retrieval_index) PAIR in its reload-target file
    — the artifact alone would revive the model with no/stale index."""
    from code2vec_tpu.serving.fleet.control import (
        FLEET_HOST_ADDRESS_ENV, ControlPlane, HostSpec,
    )
    from code2vec_tpu.serving.server import RELOAD_TARGET_FILENAME

    config = Config(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_models="default=/a/v1",
        heartbeat_file=str(tmp_path / "fleet.heartbeat.json"))
    launcher = _RecordingLauncher()
    control = ControlPlane(
        config,
        [HostSpec("default-0", ["host-cmd"], boot_artifact="/a/v1")],
        launcher=launcher, log=lambda m: None)
    control.set_initial_artifact("default", "/a/v1")
    host = control.hosts[0]
    target = os.path.join(host.host_dir, RELOAD_TARGET_FILENAME)

    control._spawn(host)                 # boot == current, no index
    assert not os.path.exists(target)
    assert launcher.launches[-1][1][FLEET_HOST_ADDRESS_ENV] \
        == "127.0.0.1"

    # a swap that rode an index: the pair, not the artifact alone
    control.set_artifact("default", "/a/v2", retrieval_index="/idx/r7")
    control._spawn(host)
    payload = json.load(open(target))
    assert (payload["artifact"], payload["retrieval_index"]) \
        == ("/a/v2", "/idx/r7")

    # an index refresh re-targeting the BOOT artifact still writes the
    # pair (the artifact matches the boot one, the index must ride)
    control.set_artifact("default", "/a/v1", retrieval_index="/idx/r8")
    control._spawn(host)
    payload = json.load(open(target))
    assert (payload["artifact"], payload["retrieval_index"]) \
        == ("/a/v1", "/idx/r8")

    # a plain promote clears the index: reviving the old one would
    # serve stale vectors against the new weights
    control.set_artifact("default", "/a/v3")
    control._spawn(host)
    payload = json.load(open(target))
    assert payload["artifact"] == "/a/v3"
    assert "retrieval_index" not in payload


def test_first_heartbeat_reconcile_reaches_remote_hosts(tmp_path):
    """The respawn reconcile must ride the host's own telemetry
    surface, not the control plane's local filesystem: a remote host
    (or a supervisor that restarted by itself) never reads the
    reload-target file, so the control plane compares the host's
    REPORTED reload state against the committed (artifact, index) pair
    at the first view after every spawn and re-issues /admin/reload on
    disagreement."""
    from code2vec_tpu.serving.fleet.control import ControlPlane, HostSpec

    config = Config(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_models="default=/a/v1",
        heartbeat_file=str(tmp_path / "fleet.heartbeat.json"))
    control = ControlPlane(
        config,
        [HostSpec("default-0", ["host-cmd"], boot_artifact="/a/v1")],
        launcher=_RecordingLauncher(), log=lambda m: None)
    control.set_initial_artifact("default", "/a/v1")
    host = control.hosts[0]
    posts = []
    control._post = lambda h, path, payload, timeout=10.0: (
        posts.append((h.id, path, dict(payload))) or (True, "{}"))

    control._spawn(host)
    assert host.needs_reconcile
    # boot pair == committed pair: no reload, flag cleared
    host.view = {"replicas": []}
    control._reconcile_host(host)
    assert not host.needs_reconcile and posts == []

    # the fleet commits a refreshed pair, then the host dies and comes
    # back reporting only its boot artifact (remote host: the
    # reload-target file never reached its filesystem)
    control.set_artifact("default", "/a/v2", retrieval_index="/idx/r9")
    control._spawn(host)
    host.view = {"replicas": []}
    control._reconcile_host(host)
    assert posts == [("default-0", "/admin/reload",
                      {"artifact": "/a/v2",
                       "retrieval_index": "/idx/r9"})]
    assert not host.needs_reconcile

    # a host that already processed the fan-out (its view reports the
    # committed pair) is left alone
    control._spawn(host)
    host.view = {"last_reload": {"artifact": "/a/v2",
                                 "retrieval_index": "/idx/r9"}}
    posts.clear()
    control._reconcile_host(host)
    assert posts == [] and not host.needs_reconcile

    # artifact matches but the index is missing from the report (the
    # residue this PR closes: supervisor status omitted it) -> the
    # FULL pair is re-issued
    control._spawn(host)
    host.view = {"last_reload": {"artifact": "/a/v2"}}
    control._reconcile_host(host)
    assert posts and posts[-1][2] == {"artifact": "/a/v2",
                                      "retrieval_index": "/idx/r9"}

    # an in-flight coordinated swap defers to the swap driver: no
    # competing reload, the flag stays set for the next tick
    control._spawn(host)
    host.view = {"last_reload": {"artifact": "/a/v1"}}
    control.swap._set(state="rolling")
    posts.clear()
    control._reconcile_host(host)
    assert posts == [] and host.needs_reconcile


def test_supervisor_last_reload_reports_index_pair(tmp_path):
    """fleet_view's last_reload must carry the retrieval_index it
    fanned out — the control plane's reconcile compares pairs, and an
    artifact-only report would read as 'index missing' forever."""
    from code2vec_tpu import obs
    from code2vec_tpu.serving.supervisor import Supervisor

    config = Config(serve=True, serve_host="127.0.0.1", verbose_mode=0,
                    heartbeat_file=str(tmp_path / "sup.heartbeat.json"))
    sup = Supervisor.__new__(Supervisor)
    sup.config = config
    sup.replicas = []
    sup.run_dir = str(tmp_path)
    sup.reuseport = False
    sup.log = lambda m: None
    sup.flight = obs.default_flight_recorder()
    status = sup.reload_all("/a/v2", retrieval_index="/idx/r9")
    sup._last_reload = status
    assert status["artifact"] == "/a/v2"
    assert status["retrieval_index"] == "/idx/r9"
    # and a plain reload omits the key (pair semantics: absent index
    # means none mounted, not unknown)
    assert "retrieval_index" not in sup.reload_all("/a/v3")


def test_fleet_view_carries_pair_and_router_tier(tmp_path):
    from code2vec_tpu.serving.fleet.control import (
        ControlPlane, HostSpec, RouterSpec,
    )

    config = Config(
        serve=True, fleet=True, serve_host="127.0.0.1", verbose_mode=0,
        fleet_models="default=/a/v1", fleet_routers=2,
        heartbeat_file=str(tmp_path / "fleet.heartbeat.json"))
    config.verify()
    control = ControlPlane(config, [HostSpec("default-0", ["cmd"])],
                           launcher=_RecordingLauncher(),
                           log=lambda m: None)
    control.set_initial_artifact("default", "/a/v1")
    control.set_artifact("default", "/a/v2", retrieval_index="/idx/r2")
    control.add_router(RouterSpec("router-0", ["cmd"]))
    view = control.fleet_view()
    assert view["models"]["default"]["artifact"] == "/a/v2"
    assert view["models"]["default"]["retrieval_index"] == "/idx/r2"
    assert [r["router"] for r in view["routers"]] == ["router-0"]
    assert view["hosts"][0]["address"] == "127.0.0.1"


# --------------------------------------------------- CLI / re-exec


def test_router_base_command_keeps_knobs_strips_topology():
    from code2vec_tpu.serving.fleet.control import _router_base_command

    argv = ["fleet", "--fleet_routers", "3",
            "--fleet_control", "127.0.0.1:9", "--fleet_port", "9100",
            "--serve_port", "9000", "--serve_telemetry_port", "9001",
            "--heartbeat_file", "/x/hb.json", "--fleet_no_affinity",
            "--serve_deadline_ms", "1500",
            "--fleet_poll_interval", "0.5",
            "--fleet_models", "default=/a"]
    cmd = _router_base_command(argv)
    assert cmd[:3] == [sys.executable, "-m", "code2vec_tpu.cli"]
    rest = cmd[3:]
    # keeps the `fleet` subcommand: dispatch keys on C2V_FLEET_ROUTER
    assert rest[0] == "fleet"
    for flag in ("--fleet_routers", "--fleet_control", "--fleet_port",
                 "--serve_port", "--serve_telemetry_port",
                 "--heartbeat_file"):
        assert flag not in rest, flag
    # operator knobs (including the affinity toggle) are inherited
    for flag in ("--fleet_no_affinity", "--serve_deadline_ms",
                 "--fleet_poll_interval", "--fleet_models"):
        assert flag in rest, flag


def test_cli_edge_flags_parse_and_config_verifies():
    from code2vec_tpu.cli import config_from_args

    cfg = config_from_args(
        ["fleet", "--fleet_models", "default=/a",
         "--fleet_routers", "2", "--fleet_control", "127.0.0.1:9901",
         "--fleet_no_affinity", "--fleet_launcher", "ssh {address}",
         "--fleet_addresses", "10.0.0.1,10.0.0.2"])
    assert cfg.fleet_routers == 2
    assert cfg.fleet_control == "127.0.0.1:9901"
    assert cfg.fleet_cache_affinity is False
    assert cfg.fleet_launcher == "ssh {address}"
    assert cfg.fleet_addresses == "10.0.0.1,10.0.0.2"
    cfg.verify()
    # defaults: one embedded router, affinity ON
    base = config_from_args(["fleet", "--fleet_models", "default=/a"])
    assert base.fleet_routers == 1
    assert base.fleet_cache_affinity is True

    def bad(**kw):
        cfg = Config(serve=True, fleet=True, serve_host="127.0.0.1",
                     fleet_models="default=/a", **kw)
        with pytest.raises(ValueError):
            cfg.verify()

    bad(fleet_routers=0)
    bad(fleet_control="no-port")
    bad(fleet_launcher="ssh {address}")   # {address}, no addresses


# ------------------------------------------------ chaos drills (slow)


def _run_edge_fleet(tmp_path, config, host_specs, artifacts=None,
                    router_ports=()):
    """ControlPlane + PRIVATE control listener + N router-agent
    subprocesses (the fleet_main n_routers>=2 topology, built by hand
    so the drill owns the ports and the teardown)."""
    from code2vec_tpu.serving.fleet.control import (
        ControlPlane, RouterSpec,
    )
    from code2vec_tpu.serving.fleet.router import FleetRouter

    control = ControlPlane(config, host_specs, log=lambda m: None)
    for model, artifact in (artifacts or {}).items():
        control.set_initial_artifact(model, artifact)
    control.router = FleetRouter(config, control, host="127.0.0.1",
                                 port=0, log=lambda m: None)
    for i, port in enumerate(router_ports):
        control.add_router(RouterSpec(
            f"router-{i}",
            [sys.executable, "-m", "code2vec_tpu.cli", "fleet",
             "--fleet_models", "default=/tmp/unused",
             "--serve_host", "127.0.0.1", "--serve_port", str(port),
             "--fleet_control", f"127.0.0.1:{control.router.port}",
             "--fleet_poll_interval", "0.25", "--verbose", "0"]))
    rc_holder = {}
    thread = threading.Thread(
        target=lambda: rc_holder.update(rc=control.run()), daemon=True)
    thread.start()
    return control, thread, rc_holder


@pytest.fixture()
def run_edge(tmp_path, fake_extractor):  # noqa: F811 — pytest fixture
    running = []

    def start(config, host_specs, artifacts=None, router_ports=()):
        out = _run_edge_fleet(tmp_path, config, host_specs,
                              artifacts=artifacts,
                              router_ports=router_ports)
        running.append(out)
        return out

    yield start
    for control, thread, _rc in running:
        control.stop()
        thread.join(timeout=60)


def _routers_routing(n):
    def ready(view):
        routing = [r for r in view.get("routers", [])
                   if r["state"] == "routing" and r["port"]]
        return len(routing) >= n
    return ready


@pytest.mark.slow
@pytest.mark.chaos
def test_edge_router_sigkill_under_load_zero_failed_requests(
        tmp_path, fake_extractor, run_edge):
    """THE edge chaos drill (ISSUE acceptance): SIGKILL one of 2
    router processes under 4-client load. Clients follow the VIP
    convention — fixed member ports, retry the next member on a
    refused/torn connection — and ZERO requests fail or come back
    malformed; the control plane respawns the router (same
    backoff/escalation policy as hosts) and the fleet exits rc 0."""
    replica_cfg = _write_json(
        tmp_path, "replica.json",
        _replica_overrides(fingerprint="fp-edge"))
    host_cmd = [sys.executable, FLEET_HOST,
                _write_json(tmp_path, "host.json", _host_overrides()),
                replica_cfg]
    from code2vec_tpu.serving.fleet.control import HostSpec
    ports = [_free_port(), _free_port()]
    config = _fleet_config(tmp_path)
    control, thread, rc_holder = run_edge(
        config, [HostSpec("default-0", host_cmd),
                 HostSpec("default-1", host_cmd)],
        router_ports=ports)
    _wait_fleet(control,
                lambda v: _all_routable(2)(v) and _routers_routing(2)(v),
                timeout=60, what="2 routable hosts + 2 routing routers")
    restarts_before = _counter_value("edge_router_restarts_total")

    failures, malformed = [], []
    lock = threading.Lock()
    stop_load = threading.Event()

    def load(ci):
        i = 0
        while not stop_load.is_set():
            src = (f"class K{ci}x{i} {{ int m{ci}x{i}() "
                   f"{{ return 1; }} }}")
            served = False
            deadline = time.time() + 30
            attempt = ci  # pin each client to a different start member
            last = None
            while time.time() < deadline:
                port = ports[attempt % len(ports)]
                attempt += 1
                try:
                    status, body, headers = _post(port, "/predict",
                                                  src, timeout=15)
                except Exception as e:  # noqa: BLE001 — refused/torn
                    # connection: the VIP retries the next member
                    last = ("conn_error", str(e))
                    time.sleep(0.05)
                    continue
                try:
                    payload = json.loads(body)
                except ValueError:
                    with lock:
                        malformed.append((status, body[:200]))
                    break
                if status == 200:
                    if (payload.get("model_fingerprint") != "fp-edge"
                            or "methods" not in payload):
                        with lock:
                            malformed.append((status, body[:200]))
                    served = True
                    break
                # an honest shed retries; anything else is malformed
                if status not in (503, 504) \
                        or not payload.get("trace_id"):
                    with lock:
                        malformed.append((status, body[:200]))
                    break
                last = (status, None)
                time.sleep(0.1)
            if not served and not stop_load.is_set():
                with lock:
                    failures.append((ci, i, last))
            i += 1
            time.sleep(0.02)

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(4)]
    for t in threads:
        t.start()
    try:
        time.sleep(1.0)
        view = control.fleet_view()
        victim = view["routers"][0]
        assert victim["pid"]
        os.kill(victim["pid"], signal.SIGKILL)
        _wait_fleet(
            control,
            lambda v: (v["routers"][0]["pid"] not in (None,
                                                      victim["pid"])
                       and v["routers"][0]["restarts"] >= 1
                       and v["routers"][0]["state"] == "routing"),
            timeout=60, what="killed router respawned + routing")
        time.sleep(1.0)  # post-recovery traffic through both members
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=60)
    assert not failures, f"failed client requests: {failures[:3]}"
    assert not malformed, f"malformed responses: {malformed[:3]}"
    assert _counter_value("edge_router_restarts_total") \
        >= restarts_before + 1
    # both members (including the respawned one, on its ORIGINAL port
    # — the VIP never re-learns addresses) serve a fresh request
    for port in ports:
        status, body, _ = _post(port, "/predict",
                                "class Z { int after() { return 1; } }")
        assert status == 200, (port, body[:200])
        assert json.loads(body)["model_fingerprint"] == "fp-edge"
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_edge_swap_commits_with_routers_live_and_respawn_gets_pair(
        tmp_path, fake_extractor, run_edge):
    """Coordinated hot-swap with N routers live: a reload POSTed to a
    PUBLIC router relays to the control plane, commits fleet-wide
    (every router's own /fleet converges on it), and a host SIGKILLed
    after the commit respawns onto the committed (artifact,
    retrieval_index) PAIR at its first heartbeat (PR-15 residue)."""
    replicas = _write_json(
        tmp_path, "replica.json",
        _replica_overrides(fingerprint="fp-v1", fake_swap=True,
                           fake_retrieval=True))
    host_json = _write_json(tmp_path, "host.json", _host_overrides())
    host_cmd = [sys.executable, FLEET_HOST, host_json, replicas]
    from code2vec_tpu.serving.fleet.control import HostSpec
    ports = [_free_port(), _free_port()]
    config = _fleet_config(tmp_path)
    control, thread, rc_holder = run_edge(
        config, [HostSpec("default-0", host_cmd,
                          boot_artifact="/artifacts/v1"),
                 HostSpec("default-1", host_cmd,
                          boot_artifact="/artifacts/v1")],
        artifacts={"default": "/artifacts/v1"}, router_ports=ports)
    _wait_fleet(control,
                lambda v: _all_routable(2)(v) and _routers_routing(2)(v),
                timeout=60, what="2 routable hosts + 2 routing routers")

    # the swap rides a retrieval index; POSTed to a PUBLIC router
    status, body, _ = _post(
        ports[1], "/admin/reload",
        json.dumps({"artifact": "/artifacts/v2",
                    "retrieval_index": "/indexes/r2"}),
        headers={"Content-Type": "application/json"})
    assert status == 202, body[:300]
    view = _wait_fleet(control,
                       lambda v: v["swap"]["state"] == "committed",
                       timeout=60, what="swap committed")
    assert view["swap"]["target_fingerprint"] == "fp-v2"
    assert view["models"]["default"]["artifact"] == "/artifacts/v2"
    assert view["models"]["default"]["retrieval_index"] == "/indexes/r2"

    # EVERY router's own /fleet (its polled shared view) converges
    for port in ports:
        deadline = time.time() + 15
        while True:
            rv = json.loads(_get(port, "/fleet")[1])
            if (rv.get("role") == "fleet-router"
                    and (rv.get("swap") or {}).get("state")
                    == "committed"
                    and rv["models"]["default"]["artifact"]
                    == "/artifacts/v2"):
                break
            assert time.time() < deadline, (port, rv.get("swap"))
            time.sleep(0.25)

    # SIGKILL one whole host (supervisor + replicas) AFTER the commit
    victim = control.hosts[0]
    victim_pid = victim.proc.pid
    hb = victim.heartbeat()
    replica_pids = [r["pid"] for r in hb["replicas"] if r["pid"]]
    os.kill(victim_pid, signal.SIGKILL)
    for pid in replica_pids:
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    _wait_fleet(
        control,
        lambda v: (v["hosts"][0]["pid"] not in (None, victim_pid)
                   and v["hosts"][0]["weight"] > 0
                   and v["hosts"][0]["restarts"] >= 1
                   and v["hosts"][0]["fingerprints"] == ["fp-v2"]),
        timeout=90, what="killed host respawned onto fp-v2")
    # the PAIR pin: every replica of the respawned host converged onto
    # (artifact, retrieval_index) — the first-heartbeat SIGHUP
    # delivered BOTH, not the artifact alone
    deadline = time.time() + 30
    while True:
        hv = control.host_fleet(control.hosts[0]) or {}
        live = [r for r in hv.get("replicas", [])
                if not r.get("draining")]
        if live and all(
                r.get("swap_target") == "/artifacts/v2"
                and r.get("swap_retrieval_index") == "/indexes/r2"
                and r.get("swap_state") == "ready"
                and r.get("model_fingerprint") == "fp-v2"
                for r in live):
            break
        assert time.time() < deadline, \
            [(r.get("swap_target"), r.get("swap_retrieval_index"),
              r.get("swap_state")) for r in live]
        time.sleep(0.25)

    # live traffic through a router serves the committed weights
    status, body, _ = _post(ports[0], "/predict",
                            "class P { int pair() { return 2; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fp-v2"
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0
