"""End-to-end offline preprocessing: Java sources -> native extraction ->
histograms/sampling -> `.c2v` + `.dict.c2v` -> loadable vocabularies.

Covers the preprocess.sh-equivalent CLI (data/preprocess.py main), which
chains the native extractor with the Python sampling/dict stage.
"""

import os
import pickle
import subprocess

import pytest

from code2vec_tpu.data import preprocess as pp

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JAVA_A = """
public class Calc {
    int add(int left, int right) { return left + right; }
    int twice(int value) { return add(value, value); }
}
"""
JAVA_B = """
public class Greeter {
    String greet(String name) {
        if (name == null) { return "hello"; }
        return "hello " + name;
    }
}
"""


@pytest.fixture(scope="module", autouse=True)
def built_extractor():
    binary = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract")
    if not os.path.exists(binary):
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr


@pytest.fixture()
def source_dirs(tmp_path):
    dirs = {}
    for role in ("train", "val", "test"):
        d = tmp_path / role / "proj"
        d.mkdir(parents=True)
        (d / "Calc.java").write_text(JAVA_A)
        (d / "Greeter.java").write_text(JAVA_B)
        dirs[role] = str(tmp_path / role)
    return dirs


def test_cli_end_to_end(tmp_path, source_dirs):
    name = str(tmp_path / "out" / "mini")
    pp.main(["--train_dir", source_dirs["train"],
             "--val_dir", source_dirs["val"],
             "--test_dir", source_dirs["test"],
             "--output_name", name, "--max_contexts", "16"])

    for role in ("train", "val", "test"):
        path = f"{name}.{role}.c2v"
        assert os.path.exists(path)
        lines = open(path).read().splitlines()
        assert len(lines) == 3  # add, twice, greet
        labels = sorted(line.split(" ")[0] for line in lines)
        assert labels == ["add", "greet", "twice"]
        # each line padded to exactly max_contexts fields
        for line in lines:
            assert len(line.split(" ")) == 1 + 16

    with open(f"{name}.dict.c2v", "rb") as f:
        word_to_count = pickle.load(f)
        path_to_count = pickle.load(f)
        target_to_count = pickle.load(f)
        n_train = pickle.load(f)
    assert n_train == 3
    assert "left" in word_to_count and "METHOD_NAME" in word_to_count
    assert set(target_to_count) == {"add", "twice", "greet"}
    assert all(p.lstrip("-").isdigit() for p in path_to_count)

    # the produced dataset trains end-to-end through the facade
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_facade import Code2VecModel
    config = Config(train_data_path_prefix=name,
                    test_data_path=f"{name}.val.c2v",
                    num_train_epochs=1, train_batch_size=3,
                    test_batch_size=3, max_contexts=16,
                    max_token_vocab_size=100, max_path_vocab_size=100,
                    max_target_vocab_size=100, compute_dtype="float32")
    model = Code2VecModel(config)
    model.train()
    results = model.evaluate()
    assert results is not None


def test_context_sampling_prefers_in_vocab(tmp_path):
    raw = tmp_path / "raw.txt"
    # 4 contexts, max 2: the in-vocab ones must survive
    raw.write_text("m known,1,known known,1,known oov1,9,oov1 oov2,9,oov2\n")
    word_to_count = {"known": 5}
    path_to_count = {"1": 5}
    n = pp.process_file(str(raw), "train", str(tmp_path / "d"),
                        word_to_count, path_to_count, max_contexts=2,
                        log=lambda *_: None)
    assert n == 1
    line = open(str(tmp_path / "d") + ".train.c2v").read().strip()
    assert line.count("known,1,known") == 2
    assert "oov" not in line


def test_main_arg_validation(tmp_path):
    with pytest.raises(SystemExit):
        pp.main(["--output_name", str(tmp_path / "x")])  # no inputs
    with pytest.raises(SystemExit):
        pp.main(["--output_name", str(tmp_path / "x"),
                 "--train_dir", "a", "--train_raw", "b",
                 "--val_dir", "c", "--test_dir", "d"])  # both modes


def test_extract_timeout_retries_per_child(tmp_path):
    """A hung whole-tree extraction is killed and retried per child; a
    single hanging file is skipped and logged (reference resilience
    semantics: JavaExtractor/extract.py:38-58)."""
    import os
    import stat

    # Fake extractor: hangs on --dir and on any file named Hang.java;
    # emits one line per other file.
    fake = tmp_path / "fake-extract"
    fake.write_text(
        "#!/bin/sh\n"
        "while [ $# -gt 0 ]; do\n"
        "  case $1 in\n"
        "    --dir) sleep 30;;\n"
        "    --file) case $2 in *Hang.java) sleep 30;; "
        "*) echo \"m a,$2,b\";; esac; shift;;\n"
        "  esac\n"
        "  shift\n"
        "done\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    tree = tmp_path / "tree"
    sub = tree / "proj"
    sub.mkdir(parents=True)
    (sub / "A.java").write_text("class A {}")
    (sub / "Hang.java").write_text("class H {}")
    (sub / "B.java").write_text("class B {}")

    logs = []
    out = tmp_path / "out.txt"
    with open(out, "wb") as f:
        skipped = pp._run_extractor_tree(
            f, str(fake), "java", str(tree), 8, 2, 1, timeout=1.0,
            log=logs.append)
    lines = out.read_text().splitlines()
    assert skipped == 1
    assert len(lines) == 2  # A.java and B.java extracted
    assert all("Hang" not in ln for ln in lines)
    assert any("TIMEOUT" in m and "Hang.java" in m for m in logs)


def test_extract_retry_skips_crashing_children(tmp_path):
    """During a retry descent, a child that crashes the extractor is
    skipped-and-logged, not fatal (the resilience path must survive
    pathological inputs)."""
    import stat

    fake = tmp_path / "fake-extract"
    fake.write_text(
        "#!/bin/sh\n"
        "while [ $# -gt 0 ]; do\n"
        "  case $1 in\n"
        "    --dir) sleep 30;;\n"
        "    --file) case $2 in *Crash.java) exit 9;; "
        "*) echo \"m a,$2,b\";; esac; shift;;\n"
        "  esac\n"
        "  shift\n"
        "done\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "A.java").write_text("class A {}")
    (tree / "Crash.java").write_text("class C {}")

    logs = []
    out = tmp_path / "out.txt"
    with open(out, "wb") as f:
        skipped = pp._run_extractor_tree(
            f, str(fake), "java", str(tree), 8, 2, 1, timeout=1.0,
            log=logs.append)
    assert skipped == 1
    assert out.read_text().count("\n") == 1
    assert any("failed on" in m and "Crash.java" in m for m in logs)


def test_external_shuffle_is_a_permutation(tmp_path):
    """The spill-bucket external shuffle (forced via a tiny memory budget)
    emits exactly the input lines, reordered, deterministically per seed —
    the `| shuf` contract (reference: preprocess.sh:44-48) in bounded RAM."""
    path = tmp_path / "raw.txt"
    lines = [f"method{i} " + "x" * 40 + "\n" for i in range(1000)]
    path.write_text("".join(lines))
    logs = []
    pp.external_shuffle(str(path), seed=1, mem_budget_bytes=4096,
                        log=logs.append)
    first = path.read_text().splitlines(keepends=True)
    assert sorted(first) == sorted(lines)
    assert first != lines  # vanishingly unlikely to be identity
    assert any("spill buckets" in m for m in logs), logs
    assert not list(tmp_path.glob("c2v_shuf_*")), "spill dir not cleaned"

    # deterministic: same seed reproduces the same permutation
    path.write_text("".join(lines))
    pp.external_shuffle(str(path), seed=1, mem_budget_bytes=4096,
                        log=lambda *_: None)
    assert path.read_text().splitlines(keepends=True) == first

    # a different seed produces a different permutation
    path.write_text("".join(lines))
    pp.external_shuffle(str(path), seed=2, mem_budget_bytes=4096,
                        log=lambda *_: None)
    assert path.read_text().splitlines(keepends=True) != first


def test_external_shuffle_small_file_in_memory(tmp_path):
    """Files within the budget take the direct in-memory path; an
    unterminated final line gains a newline (shuf semantics) instead of
    merging with its shuffled successor."""
    path = tmp_path / "raw.txt"
    path.write_text("a 1\nb 2\nc 3")  # no trailing newline
    pp.external_shuffle(str(path), seed=0, log=lambda *_: None)
    out = path.read_text()
    assert sorted(out.splitlines()) == ["a 1", "b 2", "c 3"]
    assert out.endswith("\n")


def test_external_shuffle_unterminated_last_line_external_path(tmp_path):
    path = tmp_path / "raw.txt"
    lines = [f"m{i} " + "y" * 30 for i in range(300)]
    path.write_text("\n".join(lines))  # last line unterminated
    pp.external_shuffle(str(path), seed=3, mem_budget_bytes=2048,
                        log=lambda *_: None)
    assert sorted(path.read_text().splitlines()) == sorted(lines)


def test_parallel_extraction_matches_sequential(tmp_path):
    """num_workers>1 extracts top-level projects concurrently (reference
    driver: multiprocessing.Pool(4), JavaExtractor/extract.py:61-76) and
    must produce the same multiset of context lines as one sequential
    whole-tree extraction."""
    tree = tmp_path / "tree"
    for proj in ("p1", "p2", "p3"):
        d = tree / proj
        d.mkdir(parents=True)
        (d / "Calc.java").write_text(JAVA_A)
        (d / "Greeter.java").write_text(JAVA_B)
    seq = tmp_path / "seq.txt"
    par = tmp_path / "par.txt"
    pp.extract_dir(str(tree), str(seq), num_threads=1, num_workers=1,
                   log=lambda *_: None)
    pp.extract_dir(str(tree), str(par), num_threads=1, num_workers=3,
                   log=lambda *_: None)
    seq_lines = sorted(seq.read_text().splitlines())
    par_lines = sorted(par.read_text().splitlines())
    assert seq_lines == par_lines
    assert len(seq_lines) >= 9  # 3 projects x 3 methods


def test_parallel_extraction_keeps_retry_protection(tmp_path):
    """Each parallel worker retains the kill-timer + per-child retry:
    a project with one hanging file still yields its other files."""
    import stat

    fake = tmp_path / "fake-extract"
    fake.write_text(
        "#!/bin/sh\n"
        "while [ $# -gt 0 ]; do\n"
        "  case $1 in\n"
        "    --dir) case $2 in *bad*) sleep 30;; *) echo \"m a,$2,b\";; "
        "esac; shift;;\n"
        "    --file) case $2 in *Hang.java) sleep 30;; "
        "*) echo \"m a,$2,b\";; esac; shift;;\n"
        "  esac\n"
        "  shift\n"
        "done\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    tree = tmp_path / "tree"
    good = tree / "good"
    bad = tree / "bad"
    good.mkdir(parents=True)
    bad.mkdir()
    (good / "A.java").write_text("class A {}")
    (bad / "Hang.java").write_text("class H {}")
    (bad / "B.java").write_text("class B {}")

    logs = []
    out = tmp_path / "out.txt"
    with open(out, "wb") as f:
        skipped = pp._extract_tree_parallel(
            f, str(fake), "java", str(tree), 8, 2, 1, timeout=1.0,
            num_workers=2, log=logs.append)
    lines = out.read_text().splitlines()
    assert skipped == 1  # Hang.java, after bad/'s dir-level timeout descent
    assert any("good" in ln for ln in lines)
    assert any("B.java" in ln for ln in lines)
    assert all("Hang" not in ln for ln in lines)


# ------------------------------------------------ fused parallel compiler


def _write_raw(path, n, seed, n_tokens=20, n_paths=9, n_names=12,
               widths=(1, 2, 3, 8, 12)):
    """Synthetic raw extractor output with repeated contexts, empty
    fields, blank lines and (given a small max_contexts) methods that
    overflow the sampling budget."""
    import random as random_mod
    r = random_mod.Random(seed)
    with open(path, "w") as f:
        for _ in range(n):
            k = r.choice(widths)
            ctxs = [f"t{r.randrange(n_tokens)},p{r.randrange(n_paths)},"
                    f"t{r.randrange(n_tokens)}" for _ in range(k)]
            if r.random() < 0.1:
                ctxs.append("")  # empty field (double space)
            f.write(f"m|{r.randrange(n_names)} " + " ".join(ctxs) + "\n")
            if r.random() < 0.05:
                f.write("\n")  # blank line


@pytest.fixture()
def raw_corpus(tmp_path):
    paths = {}
    for role, (n, seed) in {"train": (400, 1), "val": (60, 2),
                            "test": (60, 3)}.items():
        paths[role] = str(tmp_path / f"{role}.raw.txt")
        _write_raw(paths[role], n, seed)
    return paths


@pytest.mark.parametrize("force_python", [False, True])
def test_histogram_merge_matches_serial(raw_corpus, monkeypatch,
                                        force_python):
    """Map-reduce histograms over byte-range shards must merge to exactly
    the serial loop's Counters at any worker count (the tentpole's
    correctness contract for the map step) — on both the native
    (`c2v_histogram_range`) and pure-Python map steps."""
    serial = pp.build_histograms(raw_corpus["train"])
    if force_python:
        from code2vec_tpu.data import native
        monkeypatch.setattr(native, "_lib", None)
        monkeypatch.setattr(native, "_lib_checked", True)
    for workers in (1, 2, 4):
        tokens, paths, targets = pp.build_histograms(raw_corpus["train"],
                                                     num_workers=workers)
        assert tokens == serial[0], workers
        assert paths == serial[1], workers
        assert targets == serial[2], workers


def test_truncate_histogram_heapq_matches_sort():
    """The heapq.nlargest threshold must equal the old full-sort one."""
    import random as random_mod
    r = random_mod.Random(5)
    hist = {f"w{i}": r.randrange(1, 40) for i in range(500)}
    for max_size in (1, 7, 100, 499, 500, 900):
        got = pp.truncate_histogram(dict(hist), max_size)
        if len(hist) <= max_size:
            assert got == hist
            continue
        min_count = sorted(hist.values(), reverse=True)[max_size] + 1
        want = {w: c for w, c in hist.items() if c >= min_count}
        assert got == want, max_size


def _compile(raw_corpus, out_name, workers, emit_c2v=False):
    return pp.compile_corpus(
        raw_corpus["train"], raw_corpus["val"], raw_corpus["test"],
        out_name, max_contexts=6, word_vocab_size=15, path_vocab_size=8,
        target_vocab_size=10, seed=7, num_workers=workers,
        emit_c2v=emit_c2v, log=lambda *a: None)


def test_fused_compile_byte_identical_across_worker_counts(tmp_path,
                                                           raw_corpus):
    """The acceptance-bar determinism contract: `.c2vb` + `.targets`
    sidecar + `.dict.c2v` (and the compat `.c2v` text) are byte-identical
    at 1, 2 and 4 workers — per-method RNG seeded from (seed, global
    line ordinal) + canonicalized histograms + in-order segment
    stitching."""
    blobs = {}
    for workers in (1, 2, 4):
        name = str(tmp_path / f"w{workers}" / "data")
        os.makedirs(os.path.dirname(name))
        _compile(raw_corpus, name, workers, emit_c2v=True)
        out = {}
        for role in ("train", "val", "test"):
            for suffix in (".c2vb", ".c2vb.targets", ".c2v"):
                with open(f"{name}.{role}{suffix}", "rb") as f:
                    out[role + suffix] = f.read()
        with open(f"{name}.dict.c2v", "rb") as f:
            out["dict"] = f.read()
        blobs[workers] = out
    assert blobs[1] == blobs[2]
    assert blobs[1] == blobs[4]
    # sampling actually engaged (methods wider than max_contexts=6 exist)
    # and over-budget methods kept <= max_contexts
    lines = blobs[1]["train.c2v"].decode().splitlines()
    assert all(len(ln.split(" ")) == 1 + 6 for ln in lines)


def test_fused_compile_matches_legacy_text_path(tmp_path, raw_corpus):
    """With max_contexts wide enough that sampling never engages, the
    fused raw->`.c2vb` output must be byte-identical to the legacy
    process_file -> pack_c2v chain (same rows, same ids, same sidecar) —
    the fusion removes the text intermediate, not semantics."""
    from code2vec_tpu.data import packed
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

    name = str(tmp_path / "legacy" / "data")
    os.makedirs(os.path.dirname(name))
    pp.preprocess(raw_corpus["train"], raw_corpus["val"],
                  raw_corpus["test"], name, max_contexts=20,
                  word_vocab_size=15, path_vocab_size=8,
                  target_vocab_size=10, seed=7, log=lambda *a: None)
    tokens, paths, targets = pp.build_histograms(raw_corpus["train"])
    w2c = pp.canonical_freq_dict(pp.truncate_histogram(tokens, 15))
    p2c = pp.canonical_freq_dict(pp.truncate_histogram(paths, 8))
    t2c = pp.canonical_freq_dict(pp.truncate_histogram(targets, 10))
    vocabs = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts(w2c, p2c, t2c, 0), max_token_vocab_size=15,
        max_path_vocab_size=8, max_target_vocab_size=10)
    legacy = packed.pack_c2v(name + ".train.c2v", vocabs, 20)
    fused = str(tmp_path / "legacy" / "fused.train.c2vb")
    packed.pack_raw(raw_corpus["train"], fused, vocabs, w2c, p2c, 20,
                    seed=7, num_workers=2)
    with open(legacy, "rb") as a, open(fused, "rb") as b:
        assert a.read() == b.read()
    with open(legacy + ".targets", "rb") as a, \
            open(fused + ".targets", "rb") as b:
        assert a.read() == b.read()


def test_pack_c2v_parallel_matches_serial(tmp_path, raw_corpus,
                                          monkeypatch):
    """`pack_c2v(num_workers>1)` (compat repack of existing text) must be
    byte-identical to the serial Python loop. Native is monkeypatched
    away so the sharded Python stitcher itself is what's exercised."""
    from code2vec_tpu.data import native, packed
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

    name = str(tmp_path / "out" / "data")
    os.makedirs(os.path.dirname(name))
    pp.preprocess(raw_corpus["train"], raw_corpus["val"],
                  raw_corpus["test"], name, max_contexts=6,
                  word_vocab_size=15, path_vocab_size=8,
                  target_vocab_size=10, seed=7, log=lambda *a: None)
    tokens, paths, targets = pp.build_histograms(raw_corpus["train"])
    vocabs = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts(pp.truncate_histogram(tokens, 15),
                      pp.truncate_histogram(paths, 8),
                      pp.truncate_histogram(targets, 10), 0),
        max_token_vocab_size=15, max_path_vocab_size=8,
        max_target_vocab_size=10)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_lib_checked", True)
    serial = packed.pack_c2v(name + ".train.c2v", vocabs, 6,
                             out_path=str(tmp_path / "serial.c2vb"))
    parallel = packed.pack_c2v(name + ".train.c2v", vocabs, 6,
                               out_path=str(tmp_path / "parallel.c2vb"),
                               num_workers=3)
    with open(serial, "rb") as a, open(parallel, "rb") as b:
        assert a.read() == b.read()
    with open(serial + ".targets", "rb") as a, \
            open(parallel + ".targets", "rb") as b:
        assert a.read() == b.read()


def test_fused_cli_end_to_end(tmp_path, raw_corpus):
    """`--preprocess_workers` CLI path: raw files -> .c2vb + dict, then
    the packed dataset loads and round-trips against its vocab."""
    from code2vec_tpu.data.packed import PackedDataset
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts, \
        load_word_freq_dicts

    name = str(tmp_path / "out" / "mini")
    pp.main(["--train_raw", raw_corpus["train"],
             "--val_raw", raw_corpus["val"],
             "--test_raw", raw_corpus["test"],
             "--output_name", name, "--max_contexts", "8",
             "--word_vocab_size", "15", "--path_vocab_size", "8",
             "--target_vocab_size", "10",
             "--preprocess_workers", "2"])
    for role in ("train", "val", "test"):
        assert os.path.exists(f"{name}.{role}.c2vb")
        assert os.path.exists(f"{name}.{role}.c2vb.targets")
        # the compat text path is opt-in and was not requested
        assert not os.path.exists(f"{name}.{role}.c2v")
    freq = load_word_freq_dicts(f"{name}.dict.c2v")
    assert freq.num_train_examples > 0
    vocabs = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts(freq.token_to_count, freq.path_to_count,
                      freq.target_to_count, freq.num_train_examples),
        max_token_vocab_size=15, max_path_vocab_size=8,
        max_target_vocab_size=10)
    ds = PackedDataset(f"{name}.train.c2vb", vocabs)
    assert ds.num_rows_total == freq.num_train_examples
    assert len(ds.target_strings) == ds.num_rows_total


def test_external_shuffle_recursive_oversized_buckets(tmp_path):
    """When the input is so large relative to the budget that even capped
    buckets exceed it, buckets are shuffled recursively and streamed —
    the memory bound holds at any input size. Forced here with a tiny
    budget so every bucket overflows."""
    path = tmp_path / "raw.txt"
    lines = [f"m{i} " + "z" * 44 + "\n" for i in range(24000)]
    path.write_text("".join(lines))
    pp.external_shuffle(str(path), seed=7, mem_budget_bytes=2048,
                        log=lambda *_: None)
    out = path.read_text().splitlines(keepends=True)
    assert sorted(out) == sorted(lines)
    assert out != lines
    assert not list(tmp_path.glob("c2v_shuf_*"))
