"""End-to-end offline preprocessing: Java sources -> native extraction ->
histograms/sampling -> `.c2v` + `.dict.c2v` -> loadable vocabularies.

Covers the preprocess.sh-equivalent CLI (data/preprocess.py main), which
chains the native extractor with the Python sampling/dict stage.
"""

import os
import pickle
import subprocess

import pytest

from code2vec_tpu.data import preprocess as pp

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

JAVA_A = """
public class Calc {
    int add(int left, int right) { return left + right; }
    int twice(int value) { return add(value, value); }
}
"""
JAVA_B = """
public class Greeter {
    String greet(String name) {
        if (name == null) { return "hello"; }
        return "hello " + name;
    }
}
"""


@pytest.fixture(scope="module", autouse=True)
def built_extractor():
    binary = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract")
    if not os.path.exists(binary):
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr


@pytest.fixture()
def source_dirs(tmp_path):
    dirs = {}
    for role in ("train", "val", "test"):
        d = tmp_path / role / "proj"
        d.mkdir(parents=True)
        (d / "Calc.java").write_text(JAVA_A)
        (d / "Greeter.java").write_text(JAVA_B)
        dirs[role] = str(tmp_path / role)
    return dirs


def test_cli_end_to_end(tmp_path, source_dirs):
    name = str(tmp_path / "out" / "mini")
    pp.main(["--train_dir", source_dirs["train"],
             "--val_dir", source_dirs["val"],
             "--test_dir", source_dirs["test"],
             "--output_name", name, "--max_contexts", "16"])

    for role in ("train", "val", "test"):
        path = f"{name}.{role}.c2v"
        assert os.path.exists(path)
        lines = open(path).read().splitlines()
        assert len(lines) == 3  # add, twice, greet
        labels = sorted(line.split(" ")[0] for line in lines)
        assert labels == ["add", "greet", "twice"]
        # each line padded to exactly max_contexts fields
        for line in lines:
            assert len(line.split(" ")) == 1 + 16

    with open(f"{name}.dict.c2v", "rb") as f:
        word_to_count = pickle.load(f)
        path_to_count = pickle.load(f)
        target_to_count = pickle.load(f)
        n_train = pickle.load(f)
    assert n_train == 3
    assert "left" in word_to_count and "METHOD_NAME" in word_to_count
    assert set(target_to_count) == {"add", "twice", "greet"}
    assert all(p.lstrip("-").isdigit() for p in path_to_count)

    # the produced dataset trains end-to-end through the facade
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_facade import Code2VecModel
    config = Config(train_data_path_prefix=name,
                    test_data_path=f"{name}.val.c2v",
                    num_train_epochs=1, train_batch_size=3,
                    test_batch_size=3, max_contexts=16,
                    max_token_vocab_size=100, max_path_vocab_size=100,
                    max_target_vocab_size=100, compute_dtype="float32")
    model = Code2VecModel(config)
    model.train()
    results = model.evaluate()
    assert results is not None


def test_context_sampling_prefers_in_vocab(tmp_path):
    raw = tmp_path / "raw.txt"
    # 4 contexts, max 2: the in-vocab ones must survive
    raw.write_text("m known,1,known known,1,known oov1,9,oov1 oov2,9,oov2\n")
    word_to_count = {"known": 5}
    path_to_count = {"1": 5}
    n = pp.process_file(str(raw), "train", str(tmp_path / "d"),
                        word_to_count, path_to_count, max_contexts=2,
                        log=lambda *_: None)
    assert n == 1
    line = open(str(tmp_path / "d") + ".train.c2v").read().strip()
    assert line.count("known,1,known") == 2
    assert "oov" not in line


def test_main_arg_validation(tmp_path):
    with pytest.raises(SystemExit):
        pp.main(["--output_name", str(tmp_path / "x")])  # no inputs
    with pytest.raises(SystemExit):
        pp.main(["--output_name", str(tmp_path / "x"),
                 "--train_dir", "a", "--train_raw", "b",
                 "--val_dir", "c", "--test_dir", "d"])  # both modes


def test_extract_timeout_retries_per_child(tmp_path):
    """A hung whole-tree extraction is killed and retried per child; a
    single hanging file is skipped and logged (reference resilience
    semantics: JavaExtractor/extract.py:38-58)."""
    import os
    import stat

    # Fake extractor: hangs on --dir and on any file named Hang.java;
    # emits one line per other file.
    fake = tmp_path / "fake-extract"
    fake.write_text(
        "#!/bin/sh\n"
        "while [ $# -gt 0 ]; do\n"
        "  case $1 in\n"
        "    --dir) sleep 30;;\n"
        "    --file) case $2 in *Hang.java) sleep 30;; "
        "*) echo \"m a,$2,b\";; esac; shift;;\n"
        "  esac\n"
        "  shift\n"
        "done\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    tree = tmp_path / "tree"
    sub = tree / "proj"
    sub.mkdir(parents=True)
    (sub / "A.java").write_text("class A {}")
    (sub / "Hang.java").write_text("class H {}")
    (sub / "B.java").write_text("class B {}")

    logs = []
    out = tmp_path / "out.txt"
    with open(out, "wb") as f:
        skipped = pp._run_extractor_tree(
            f, str(fake), "java", str(tree), 8, 2, 1, timeout=1.0,
            log=logs.append)
    lines = out.read_text().splitlines()
    assert skipped == 1
    assert len(lines) == 2  # A.java and B.java extracted
    assert all("Hang" not in ln for ln in lines)
    assert any("TIMEOUT" in m and "Hang.java" in m for m in logs)


def test_extract_retry_skips_crashing_children(tmp_path):
    """During a retry descent, a child that crashes the extractor is
    skipped-and-logged, not fatal (the resilience path must survive
    pathological inputs)."""
    import stat

    fake = tmp_path / "fake-extract"
    fake.write_text(
        "#!/bin/sh\n"
        "while [ $# -gt 0 ]; do\n"
        "  case $1 in\n"
        "    --dir) sleep 30;;\n"
        "    --file) case $2 in *Crash.java) exit 9;; "
        "*) echo \"m a,$2,b\";; esac; shift;;\n"
        "  esac\n"
        "  shift\n"
        "done\n")
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)

    tree = tmp_path / "tree"
    tree.mkdir()
    (tree / "A.java").write_text("class A {}")
    (tree / "Crash.java").write_text("class C {}")

    logs = []
    out = tmp_path / "out.txt"
    with open(out, "wb") as f:
        skipped = pp._run_extractor_tree(
            f, str(fake), "java", str(tree), 8, 2, 1, timeout=1.0,
            log=logs.append)
    assert skipped == 1
    assert out.read_text().count("\n") == 1
    assert any("failed on" in m and "Crash.java" in m for m in logs)
