"""Test harness: run JAX on CPU with 8 virtual devices so DP/TP/CP sharding
is exercised without TPU hardware (SURVEY.md §4 implication)."""

import os

# Forced (not setdefault): the ambient environment points JAX at the real
# TPU (JAX_PLATFORMS=axon), but tests exercise sharding on 8 virtual CPU
# devices. In this image `import pytest` already imports jax, which
# snapshots env vars into its config at import time — so update the jax
# config directly as well (safe: the backend itself initializes lazily).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the XLA_FLAGS
    # --xla_force_host_platform_device_count fallback above covers it
    # (the CPU backend reads the flag at its lazy initialization, which
    # has not happened yet at conftest-import time).
    pass

import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import pytest  # noqa: E402

from code2vec_tpu.vocab import (  # noqa: E402
    Code2VecVocabs, WordFreqDicts,
)


@pytest.fixture
def tiny_vocabs() -> Code2VecVocabs:
    """Small deterministic vocabs used across tests."""
    freq = WordFreqDicts(
        token_to_count={"foo": 10, "bar": 8, "baz": 5, "qux": 2},
        path_to_count={"P1": 9, "P2": 7, "P3": 3},
        target_to_count={"get|name": 6, "set|value": 4, "run": 2},
        num_train_examples=100,
    )
    return Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=10, max_path_vocab_size=10,
        max_target_vocab_size=10)


@pytest.fixture
def tiny_config(tmp_path):
    from code2vec_tpu.config import Config
    return Config(
        train_data_path_prefix=str(tmp_path / "data"),
        max_contexts=4,
        train_batch_size=2,
        test_batch_size=2,
        num_train_epochs=1,
        shuffle_buffer_size=8,
        seed=0,
    )
