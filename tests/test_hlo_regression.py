"""HLO-level sharding regression tests.

The manual tensor-parallel kernels (ops/sharded.py, training/step.py)
exist to prevent two specific compiled-program failure modes; these
tests pin them by grepping the actual post-SPMD compiled HLO:

1. logits stay vocab-sharded: no collective ever materializes a full
   (B, target_vocab) logits tensor (ops/sharded.py tp_softmax_ce /
   tp_top_k rationale — at java14m scale that tensor is (B, 261K));
2. the touched-rows sparse optimizer replaces the table-shaped gradient
   all-reduce with a (ids, rows) all-gather exchange
   (training/sparse_adam.py; at java14m scale the dense exchange moves
   the full 1.3M x 128 table per step, the sparse one ~5x less).

Shapes at test scale: B=8, target vocab 32 (padded), token table shard
64/2 x 16 = (32, 16). The dense/sparse pair is differential: the same
table-shaped all-reduce the dense HLO must contain, the sparse HLO must
not — so a change that merely renames HLO ops can't silently pass.
"""

import re

import numpy as np
import jax
import jax.numpy as jnp

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
from code2vec_tpu.training.state import create_train_state, make_optimizer
from code2vec_tpu.training.step import TrainStepBuilder, device_put_batch

B, M = 8, 8
PLAN = MeshPlan(dp=2, tp=2, cp=2)
# token vocab 64 over tp=2 -> (32, 16) table shards; target vocab 32
# (already tp-divisible) -> full logits would be (8, 32)
DIMS = ModelDims(token_vocab_size=64, path_vocab_size=32,
                 target_vocab_size=32, token_dim=16, path_dim=16)
TOKEN_TABLE_SHARD = f"f32[{DIMS.token_vocab_size // PLAN.tp},{DIMS.token_dim}]"
FULL_LOGITS = f"f32[{B},{DIMS.target_vocab_size}]"

_COLLECTIVE_RE = re.compile(
    r"(all-gather|all-reduce|reduce-scatter|all-to-all)")


def _build(sparse: bool):
    config = Config(train_data_path_prefix="unused", compute_dtype="float32",
                    dp=PLAN.dp, tp=PLAN.tp, cp=PLAN.cp,
                    use_manual_tp_kernels=True,
                    train_batch_size=B, max_contexts=M,
                    use_sparse_embedding_update=sparse)
    mesh = make_mesh(PLAN)
    module = Code2VecModule(dims=DIMS, compute_dtype=jnp.float32)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               mesh=mesh, config=config)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)
    assert builder.manual
    rng = np.random.default_rng(0)
    batch = RowBatch(
        source_token_indices=rng.integers(0, 16, (B, M)).astype(np.int32),
        path_indices=rng.integers(0, 16, (B, M)).astype(np.int32),
        target_token_indices=rng.integers(0, 16, (B, M)).astype(np.int32),
        context_valid_mask=np.ones((B, M), np.float32),
        target_index=rng.integers(1, 16, (B,)).astype(np.int32),
        example_valid=np.ones((B,), bool))
    arrays = device_put_batch(batch, mesh)
    return builder, state, arrays


def _collective_lines(hlo_text: str):
    return [ln for ln in hlo_text.splitlines() if _COLLECTIVE_RE.search(ln)]


def _train_hlo(sparse: bool) -> str:
    builder, state, arrays = _build(sparse)
    step = builder.make_train_step(state)
    return step.lower(state, *arrays, jax.random.PRNGKey(1)).compile().as_text()


def test_no_full_logits_collective_in_tp_steps():
    """(i) Nothing in the compiled tp train/eval programs all-gathers a
    full (B, target_vocab) logits tensor."""
    builder, state, arrays = _build(sparse=False)
    eval_step = builder.make_eval_step(state, k=3)
    eval_text = eval_step.lower(state.params, *arrays).compile().as_text()
    train_text = _train_hlo(sparse=False)
    for label, text in (("eval", eval_text), ("train", train_text)):
        offending = [ln for ln in _collective_lines(text) if FULL_LOGITS in ln]
        assert not offending, (
            f"{label} step materializes full logits {FULL_LOGITS} in a "
            f"collective:\n" + "\n".join(offending[:4]))


def test_sparse_step_exchanges_rows_not_tables():
    """(ii) Differential: the dense step's table-shaped gradient
    all-reduce disappears under use_sparse_embedding_update, replaced by
    an integer ids all-gather (+ gathered rows)."""
    dense_text = _train_hlo(sparse=False)
    sparse_text = _train_hlo(sparse=True)

    # The op ITSELF must be a table-shaped all-reduce (`= f32[32,16]{...}
    # all-reduce(`): some XLA versions print fusion consumers that
    # mention an all-reduce operand on the same line as a table-shaped
    # output, which a bare substring test would miscount.
    table_ar = re.compile(
        rf"= {re.escape(TOKEN_TABLE_SHARD)}\S* all-reduce\(")

    def table_allreduces(text):
        return [ln for ln in _collective_lines(text)
                if table_ar.search(ln)]

    # the detector must actually detect: dense HAS the table exchange
    assert table_allreduces(dense_text), (
        "expected a table-shaped gradient all-reduce in the dense step; "
        "the test's shape pattern is stale")
    assert not table_allreduces(sparse_text), (
        "sparse step still all-reduces table-shaped gradients:\n"
        + "\n".join(table_allreduces(sparse_text)[:4]))

    # and the sparse exchange is the (ids, rows) all-gather
    id_gathers = [ln for ln in _collective_lines(sparse_text)
                  if "all-gather" in ln and re.search(r"s32\[\d+\]", ln)]
    assert id_gathers, "sparse step has no integer ids all-gather"
    # dense moves no ids at all
    assert not [ln for ln in _collective_lines(dense_text)
                if "all-gather" in ln and re.search(r"s32\[\d+\]", ln)]
