"""Pipelined vs serial evaluation must be bit-identical: the
DevicePrefetcher + overlapped-consume path (evaluator.py evaluate
prefetch=True) reorders WORK (host metrics for batch N run during batch
N+1's device step) but must not reorder RESULTS — metrics, the
per-example audit log, and the exported code vectors all match the
strictly serial path."""

import numpy as np
import jax
import jax.numpy as jnp

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch, _pad_rows, _select_rows
from code2vec_tpu.evaluation.evaluator import Evaluator
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.training.state import create_train_state, make_optimizer
from code2vec_tpu.training.step import TrainStepBuilder
from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

B, M, N_ROWS = 8, 6, 43  # deliberately not a batch multiple (padded tail)


def _vocabs():
    freq = WordFreqDicts(
        token_to_count={f"t{i}": 10 for i in range(8)},
        path_to_count={f"P{i}": 9 for i in range(5)},
        target_to_count={f"w{i}": 20 - i for i in range(12)},
        num_train_examples=100)
    return Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=30, max_path_vocab_size=20,
        max_target_vocab_size=20)


def _batches(dims):
    rng = np.random.default_rng(5)
    src = rng.integers(0, dims.token_vocab_size, (N_ROWS, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (N_ROWS, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (N_ROWS, M)).astype(np.int32)
    mask = (rng.random((N_ROWS, M)) > 0.4).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(
        0, dims.real_target_vocab_size, (N_ROWS,)).astype(np.int32)
    pool = ["w0", "w1", "w2|w3", "nosuchname", "w5|w1", "w7", "w9"]
    rows = RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels,
        example_valid=np.ones((N_ROWS,), bool),
        target_strings=[pool[i % len(pool)] for i in range(N_ROWS)])
    return [_pad_rows(_select_rows(rows, np.arange(s, min(s + B, N_ROWS))), B)
            for s in range(0, N_ROWS, B)]


def test_prefetched_eval_equals_serial(tmp_path):
    dims = ModelDims(token_vocab_size=16, path_vocab_size=12,
                     target_vocab_size=16, token_dim=4, path_dim=4)
    config = Config(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=B, test_batch_size=B, max_contexts=M,
                    dropout_keep_rate=1.0, verbose_mode=0)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=1.0)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(3))
    eval_step = TrainStepBuilder(module, opt, config, mesh=None
                                 ).make_eval_step(state, k=3)
    batches = _batches(dims)
    results = {}
    for mode in ("serial", "prefetch"):
        ev = Evaluator(config, _vocabs(), eval_step, mesh=None,
                       log_path=str(tmp_path / f"log_{mode}.txt"))
        results[mode] = ev.evaluate(
            state.params, list(batches),
            code_vectors_path=str(tmp_path / f"vec_{mode}.txt"),
            prefetch=(mode == "prefetch"))

    s, p = results["serial"], results["prefetch"]
    np.testing.assert_array_equal(s.topk_acc, p.topk_acc)
    assert s.subtoken_precision == p.subtoken_precision
    assert s.subtoken_recall == p.subtoken_recall
    assert s.subtoken_f1 == p.subtoken_f1
    np.testing.assert_allclose(s.loss, p.loss, rtol=1e-6)
    # audit log and exported vectors byte-identical, in order
    assert (tmp_path / "log_serial.txt").read_text() \
        == (tmp_path / "log_prefetch.txt").read_text()
    assert (tmp_path / "vec_serial.txt").read_text() \
        == (tmp_path / "vec_prefetch.txt").read_text()
