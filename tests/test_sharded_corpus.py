"""Cursor laws under multi-shard manifests (ShardedCorpus,
data/packed.py).

The PR-6 cursor laws must hold VERBATIM across shard counts, because
the per-epoch permutation is a pure function of (seed, epoch) over the
GLOBAL row space the manifest's shard order defines:

- the multi-shard stream is byte-for-byte the single-pack stream over
  the same rows,
- the per-step global batch SET is invariant across host counts,
- resuming mid-epoch — even at a DIFFERENT host count — reproduces the
  uninterrupted stream's remaining batch sets exactly,
- a delta shard appended mid-run is refused until the next epoch
  boundary, then joins the next epoch's permutation.

Doubles as the tier-1 fast smoke of the multi-shard reader (everything
here is tiny and CPU-only).
"""

import itertools
import os

import numpy as np
import pytest

from code2vec_tpu.data.packed import (
    PackedDataset, ShardedCorpus, append_manifest_shard, create_manifest,
    load_manifest, pack_c2v, validate_manifest,
)
from code2vec_tpu.data.reader import EpochEnd, EstimatorAction


def _distinct_lines(n, start=0):
    """n distinct trainable rows (known targets, known contexts)."""
    targets = ["get|name", "set|value", "run"]
    tokens = ["foo", "bar", "baz", "qux"]
    paths = ["P1", "P2", "P3"]
    combos = itertools.islice(
        itertools.product(targets, tokens, paths, tokens, paths), start,
        start + n)
    return [f"{t} {a},{p},{b} {b},{q},{a}" for t, a, p, b, q in combos]


def _pack_lines(tmp_path, vocabs, name, lines, max_contexts=4):
    c2v = str(tmp_path / f"{name}.train.c2v")
    with open(c2v, "w") as f:
        f.write("\n".join(lines) + "\n")
    return pack_c2v(c2v, vocabs, max_contexts)


def _make_manifest(tmp_path, vocabs, groups, name="corpus"):
    shards = [_pack_lines(tmp_path, vocabs, f"{name}-shard{i}", lines)
              for i, lines in enumerate(groups)]
    manifest = str(tmp_path / f"{name}.manifest.json")
    create_manifest(manifest, shards)
    return manifest


def _batch_sig(batch):
    """One hashable signature per row (all packed int fields)."""
    rec = np.concatenate(
        [np.asarray(batch.target_index)[:, None].astype(np.int32),
         np.asarray(batch.source_token_indices).astype(np.int32),
         np.asarray(batch.path_indices).astype(np.int32),
         np.asarray(batch.target_token_indices).astype(np.int32),
         np.asarray(batch.context_valid_mask).astype(np.int32)], axis=1)
    return [rec[i].tobytes() for i in range(rec.shape[0])]


def _train_batches(ds, batch_size, **kw):
    return [b for b in ds.iter_batches(batch_size, EstimatorAction.Train,
                                       **kw)
            if not isinstance(b, EpochEnd)]


# ------------------------------------------------------ smoke / basics


def test_multishard_stream_equals_single_pack(tmp_path, tiny_vocabs):
    """3-shard manifest vs ONE pack over the same rows in the same
    order: identical global row space -> byte-identical train stream."""
    lines = _distinct_lines(40)
    groups = [lines[:13], lines[13:26], lines[26:]]
    manifest = _make_manifest(tmp_path, tiny_vocabs, groups)
    single = _pack_lines(tmp_path, tiny_vocabs, "single", lines)

    corpus = ShardedCorpus(manifest, tiny_vocabs)
    packed = PackedDataset(single, tiny_vocabs)
    assert len(corpus) == packed.num_rows_total == 40
    assert corpus.num_shard_files == 3
    assert corpus.steps_per_epoch(8, EstimatorAction.Train) == \
        packed.steps_per_epoch(8, EstimatorAction.Train)

    for action in (EstimatorAction.Train, EstimatorAction.Evaluate):
        got = _train_batches(corpus, 8, num_epochs=2, seed=3) \
            if action.is_train else list(corpus.iter_batches(8, action))
        want = _train_batches(packed, 8, num_epochs=2, seed=3) \
            if action.is_train else list(packed.iter_batches(8, action))
        assert len(got) == len(want) and len(got) > 0
        for g, w in zip(got, want):
            assert _batch_sig(g) == _batch_sig(w)


def test_validate_manifest_counts_and_fingerprints(tmp_path, tiny_vocabs):
    manifest = _make_manifest(
        tmp_path, tiny_vocabs,
        [_distinct_lines(5), _distinct_lines(7, start=5)])
    entries = validate_manifest(manifest, vocabs=tiny_vocabs)
    assert [e["rows"] for e in entries] == [5, 7]
    assert len({e["vocab_fingerprint"] for e in entries}) == 1
    assert ShardedCorpus.read_manifest_rows(manifest) == 12


def test_mixed_vocab_append_refused(tmp_path, tiny_vocabs):
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts
    manifest = _make_manifest(tmp_path, tiny_vocabs,
                              [_distinct_lines(5)])
    other = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts(token_to_count={"foo": 3, "bar": 1},
                      path_to_count={"P1": 2},
                      target_to_count={"run": 2},
                      num_train_examples=4),
        max_token_vocab_size=5, max_path_vocab_size=5,
        max_target_vocab_size=5)
    alien = _pack_lines(tmp_path, other, "alien", ["run foo,P1,bar"])
    with pytest.raises(ValueError, match="mixed-vocab"):
        append_manifest_shard(manifest, alien)
    # the manifest is unchanged by the refused append
    assert len(load_manifest(manifest)["shards"]) == 1


# --------------------------------------------------------- cursor laws


def test_batch_sets_invariant_across_host_counts(tmp_path, tiny_vocabs):
    """4-shard manifest: per-step global batch SET identical at 1, 2
    and 4 hosts (truncate-before-stride, global permutation)."""
    manifest = _make_manifest(
        tmp_path, tiny_vocabs,
        [_distinct_lines(12, start=12 * i) for i in range(4)])
    ref = [_batch_sig(b) for b in _train_batches(
        ShardedCorpus(manifest, tiny_vocabs), 8, num_epochs=1, seed=5)]
    assert len(ref) == 6  # 48 rows / Bg=8
    for hosts in (2, 4):
        per_host = [
            [_batch_sig(b) for b in _train_batches(
                ShardedCorpus(manifest, tiny_vocabs, shard_index=h,
                              num_shards=hosts),
                8 // hosts, num_epochs=1, seed=5)]
            for h in range(hosts)]
        assert all(len(s) == len(ref) for s in per_host)
        for step, want in enumerate(ref):
            union = sorted(sum((s[step] for s in per_host), []))
            assert union == sorted(want), f"hosts={hosts} step={step}"


def test_resume_mid_epoch_at_different_host_count(tmp_path, tiny_vocabs):
    """THE pod-scale elastic-resume law: consume k steps at 1 host,
    resume at 2 hosts with the same cursor — the remaining steps (and
    the following epoch) reproduce the uninterrupted batch sets."""
    manifest = _make_manifest(
        tmp_path, tiny_vocabs,
        [_distinct_lines(12, start=12 * i) for i in range(4)])
    Bg, consumed_steps = 8, 3
    full = [_batch_sig(b) for b in _train_batches(
        ShardedCorpus(manifest, tiny_vocabs), Bg, num_epochs=2, seed=5)]
    assert len(full) == 12  # 6 steps/epoch x 2 epochs
    skip = consumed_steps * Bg
    resumed_hosts = [
        [_batch_sig(b) for b in _train_batches(
            ShardedCorpus(manifest, tiny_vocabs, shard_index=h,
                          num_shards=2),
            Bg // 2, num_epochs=2, seed=5, start_epoch=0,
            skip_rows=skip)]
        for h in range(2)]
    want = full[consumed_steps:]
    assert all(len(s) == len(want) for s in resumed_hosts)
    for step, ref in enumerate(want):
        union = sorted(resumed_hosts[0][step] + resumed_hosts[1][step])
        assert union == sorted(ref), f"resumed step {step}"


def test_resume_at_epoch_boundary_matches_uninterrupted(
        tmp_path, tiny_vocabs):
    """start_epoch=e with no cursor == the uninterrupted run's epoch e,
    regardless of shard count (the permutation keys on the absolute
    epoch index)."""
    manifest = _make_manifest(
        tmp_path, tiny_vocabs, [_distinct_lines(10),
                                _distinct_lines(10, start=10)])
    corpus = ShardedCorpus(manifest, tiny_vocabs)
    full = [_batch_sig(b) for b in _train_batches(
        corpus, 4, num_epochs=3, seed=9)]
    steps = len(full) // 3
    resumed = [_batch_sig(b) for b in _train_batches(
        corpus, 4, num_epochs=2, seed=9, start_epoch=1)]
    assert resumed == full[steps:]


def test_mid_epoch_append_refused_until_boundary(tmp_path, tiny_vocabs):
    """A delta shard appended mid-run: the manifest append itself is
    fine (pure file append), but the OPEN corpus refuses to adopt it
    while an epoch is in flight — adoption lands at the next epoch
    boundary and the new rows join the NEXT epoch's permutation."""
    manifest = _make_manifest(tmp_path, tiny_vocabs,
                              [_distinct_lines(8),
                               _distinct_lines(8, start=8)])
    corpus = ShardedCorpus(manifest, tiny_vocabs)
    gen = corpus.iter_batches(4, EstimatorAction.Train, num_epochs=2,
                              seed=1, yield_epoch_markers=True)
    first = next(gen)
    assert not isinstance(first, EpochEnd)

    delta = _pack_lines(tmp_path, tiny_vocabs, "delta",
                        _distinct_lines(6, start=16))
    append_manifest_shard(manifest, delta)
    with pytest.raises(RuntimeError, match="mid-epoch"):
        corpus.adopt_appended_shards()
    assert len(corpus) == 16  # refusal left the open view untouched

    # drain to the epoch boundary (the EpochEnd marker suspends the
    # generator BETWEEN epochs)
    item = next(gen)
    while not isinstance(item, EpochEnd):
        item = next(gen)
    adopted = corpus.adopt_appended_shards()
    assert adopted == 1 and len(corpus) == 22

    # epoch 2 draws over the grown global row space: some batch now
    # contains a delta row
    delta_sigs = set()
    ds_delta = PackedDataset(delta, tiny_vocabs)
    delta_sigs.update(_batch_sig(
        ds_delta.gather(np.arange(ds_delta.num_rows_total))))
    epoch2 = []
    for item in gen:
        if isinstance(item, EpochEnd):
            break
        epoch2.extend(_batch_sig(item))
    assert len(epoch2) == (22 // 4) * 4
    assert delta_sigs & set(epoch2), \
        "adopted delta rows never drawn in the next epoch"


def test_manifest_relative_paths_survive_move(tmp_path, tiny_vocabs):
    """Shard paths are stored relative to the manifest: moving the
    whole directory keeps the corpus openable (pod-scale corpora live
    on shared filesystems that mount at different roots)."""
    src = tmp_path / "a"
    src.mkdir()
    manifest = _make_manifest(src, tiny_vocabs, [_distinct_lines(5)])
    entry = load_manifest(manifest)["shards"][0]
    assert not os.path.isabs(entry["path"])
    dst = tmp_path / "b"
    os.rename(src, dst)
    moved = str(dst / os.path.basename(manifest))
    corpus = ShardedCorpus(moved, tiny_vocabs)
    assert len(corpus) == 5
