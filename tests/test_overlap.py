"""Bucketed async all-reduce overlap suite (parallel/overlap.py).

Pins the roofline PR's correctness contract: the overlapped composite
(backward + K bucket reduce+apply dispatches) computes the SAME step as
the unbucketed single-program GSPMD step — loss bit-equal, params
within a documented float tolerance (the program split changes XLA's
fusion/reduction order for the token table's two-gather gradient; the
mesh path additionally reorders the cross-shard sum) — plus the bucket
planner's size/order laws and the config guard rails.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.config import Config

pytestmark = pytest.mark.roofline

# Documented parity tolerance (see module docstring): everything
# observed is <= 2e-9 absolute on the tiny model; the bound leaves room
# for platform-dependent fusion without letting a real bug through.
PARITY_RTOL = 2e-6
PARITY_ATOL = 1e-7


def _build(overlap, mesh=None, *, dropout_keep=1.0, bucket_mb=0.003,
           nu_dtype="bfloat16", in_backward=False):
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.training.state import create_train_state, make_optimizer
    from code2vec_tpu.training.step import TrainStepBuilder
    config = Config(train_data_path_prefix="<t>", train_batch_size=8,
                    max_contexts=6, compute_dtype="float32",
                    dropout_keep_rate=dropout_keep,
                    dp=(2 if mesh is not None else 1),
                    adam_nu_dtype=nu_dtype,
                    overlap_grad_allreduce=overlap,
                    overlap_in_backward=in_backward,
                    overlap_bucket_mb=bucket_mb)
    dims = ModelDims(token_vocab_size=50, path_vocab_size=40,
                     target_vocab_size=30, token_dim=8, path_dim=8)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=dropout_keep)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               mesh=mesh, config=config)
    step = TrainStepBuilder(module, opt, config,
                            mesh=mesh).make_train_step(state)
    return step, state


def _batch(mesh=None):
    rng = np.random.default_rng(3)
    b, m = 8, 6
    arrays = (rng.integers(2, 50, (b, m)).astype(np.int32),
              rng.integers(2, 40, (b, m)).astype(np.int32),
              rng.integers(2, 50, (b, m)).astype(np.int32),
              np.ones((b, m), np.float32),
              rng.integers(2, 30, (b,)).astype(np.int32),
              np.ones((b,), bool))
    if mesh is None:
        return tuple(jnp.asarray(a) for a in arrays)
    import collections

    from code2vec_tpu.training.step import device_put_batch
    Batch = collections.namedtuple("Batch", [
        "source_token_indices", "path_indices", "target_token_indices",
        "context_valid_mask", "target_index", "example_valid"])
    return device_put_batch(Batch(*arrays), mesh)


def _run_parity(mesh, steps=3, in_backward=False):
    step_ref, s_ref = _build(False, mesh)
    step_ov, s_ov = _build(True, mesh, in_backward=in_backward)
    assert step_ov.overlap_buckets >= 2, step_ov.overlap_description
    arrays = _batch(mesh)
    key = jax.random.PRNGKey(7)
    for i in range(steps):
        s_ref, l_ref = step_ref(s_ref, *arrays, key)
        s_ov, l_ov = step_ov(s_ov, *arrays, key)
        if in_backward:
            # the loss comes from bucket 0's restricted backward, whose
            # program fuses differently — same math, not bit-pinned
            np.testing.assert_allclose(float(l_ref), float(l_ov),
                                       rtol=1e-6, err_msg=f"step {i}")
        else:
            assert float(l_ref) == float(l_ov), \
                f"step {i}: loss {float(l_ref)} != {float(l_ov)}"
    for k in s_ref.params:
        np.testing.assert_allclose(
            np.asarray(s_ov.params[k]), np.asarray(s_ref.params[k]),
            rtol=PARITY_RTOL, atol=PARITY_ATOL, err_msg=k)
    # optimizer state advanced identically: shared count, all moment
    # leaves present and matching within the same tolerance
    assert int(np.asarray(s_ov.opt_state[0].count)) == steps
    for k in s_ref.params:
        np.testing.assert_allclose(
            np.asarray(s_ov.opt_state[0].mu[k], dtype=np.float32),
            np.asarray(s_ref.opt_state[0].mu[k], dtype=np.float32),
            rtol=1e-3, atol=1e-6, err_msg=f"mu/{k}")  # bf16 storage
    return s_ref, s_ov


def test_overlap_parity_single_device():
    """mesh=None: pure apply pipelining — loss bit-equal to the
    unbucketed step, params within the documented tolerance."""
    _run_parity(None)


def test_overlap_parity_dp2_mesh():
    """dp=2 mesh: the per-shard backward + per-bucket psum computes the
    same step as the in-program all-reduce."""
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    mesh = make_mesh(MeshPlan(dp=2))
    _run_parity(mesh)


def test_overlap_parity_in_backward_single_device():
    """overlap_in_backward: per-bucket backwards (one extra forward per
    bucket, shared dropout draw) produce the same update as the
    whole-model backward."""
    _run_parity(None, in_backward=True)


def test_overlap_parity_in_backward_dp2_mesh():
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    mesh = make_mesh(MeshPlan(dp=2))
    step, _ = _build(True, mesh, in_backward=True)
    assert step.overlap_in_backward
    assert "in-backward" in step.overlap_description
    _run_parity(mesh, in_backward=True)


def _build_manual(overlap, mesh, *, in_backward=False, dropout_keep=1.0):
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.training.state import create_train_state, make_optimizer
    from code2vec_tpu.training.step import TrainStepBuilder
    config = Config(train_data_path_prefix="<t>", train_batch_size=8,
                    max_contexts=6, compute_dtype="float32",
                    dropout_keep_rate=dropout_keep,
                    dp=2, tp=2, use_manual_tp_kernels=True,
                    overlap_grad_allreduce=overlap,
                    overlap_in_backward=in_backward,
                    overlap_bucket_mb=0.003)
    config.verify()
    # vocab sizes divisible by tp=2, so no target padding in play
    dims = ModelDims(token_vocab_size=50, path_vocab_size=40,
                     target_vocab_size=30, token_dim=8, path_dim=8)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=dropout_keep)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               mesh=mesh, config=config)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)
    assert builder.manual
    return builder.make_train_step(state), state


def test_overlap_parity_manual_tp_mesh():
    """The manual-kernel tp/cp backward through the overlap builder
    computes the same step as the monolithic manual shard_map step
    (identical dropout folding discipline, so losses line up too)."""
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    mesh = make_mesh(MeshPlan(dp=2, tp=2))
    arrays = _batch(mesh)
    key = jax.random.PRNGKey(7)
    step_ref, s_ref = _build_manual(False, mesh)
    step_ov, s_ov = _build_manual(True, mesh)
    assert step_ov.overlap_buckets >= 2, step_ov.overlap_description
    assert "manual" in step_ov.overlap_description
    for i in range(3):
        s_ref, l_ref = step_ref(s_ref, *arrays, key)
        s_ov, l_ov = step_ov(s_ov, *arrays, key)
        np.testing.assert_allclose(float(l_ref), float(l_ov),
                                   rtol=1e-6, err_msg=f"step {i}")
    for k in s_ref.params:
        np.testing.assert_allclose(
            np.asarray(s_ov.params[k]), np.asarray(s_ref.params[k]),
            rtol=PARITY_RTOL, atol=PARITY_ATOL, err_msg=k)


def test_overlap_parity_manual_in_backward():
    """Manual tp/cp x in-backward completion: still the same step."""
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    mesh = make_mesh(MeshPlan(dp=2, tp=2))
    arrays = _batch(mesh)
    key = jax.random.PRNGKey(7)
    step_ref, s_ref = _build_manual(False, mesh)
    step_ib, s_ib = _build_manual(True, mesh, in_backward=True)
    assert step_ib.overlap_in_backward
    for i in range(2):
        s_ref, l_ref = step_ref(s_ref, *arrays, key)
        s_ib, l_ib = step_ib(s_ib, *arrays, key)
        np.testing.assert_allclose(float(l_ref), float(l_ib),
                                   rtol=1e-6, err_msg=f"step {i}")
    for k in s_ref.params:
        np.testing.assert_allclose(
            np.asarray(s_ib.params[k]), np.asarray(s_ref.params[k]),
            rtol=PARITY_RTOL, atol=PARITY_ATOL, err_msg=k)


def test_overlap_parity_f32_adam_state():
    """The bucket slicing also handles the plain optax.adam state
    (nu_dtype float32 skips the custom transform)."""
    step_ref, s_ref = _build(False, nu_dtype="float32")
    step_ov, s_ov = _build(True, nu_dtype="float32")
    arrays = _batch()
    key = jax.random.PRNGKey(5)
    s_ref, l_ref = step_ref(s_ref, *arrays, key)
    s_ov, l_ov = step_ov(s_ov, *arrays, key)
    assert float(l_ref) == float(l_ov)
    for k in s_ref.params:
        np.testing.assert_allclose(
            np.asarray(s_ov.params[k]), np.asarray(s_ref.params[k]),
            rtol=PARITY_RTOL, atol=PARITY_ATOL, err_msg=k)


def test_overlap_with_dropout_trains():
    """Dropout draws differ from the unbucketed step by design (the
    mesh path folds the data-axis index); the overlapped step must
    still train — finite losses, params move, moments update."""
    step_ov, state = _build(True, dropout_keep=0.75)
    arrays = _batch()
    key = jax.random.PRNGKey(9)
    before = np.asarray(state.params["transform"]).copy()
    for _ in range(2):
        state, loss = step_ov(state, *arrays, key)
        assert np.isfinite(float(loss))
    assert not np.array_equal(before, np.asarray(state.params["transform"]))


def test_plan_buckets_order_and_bounds():
    from code2vec_tpu.parallel.overlap import plan_buckets

    class L:  # noqa: N801 — shape-only stand-in
        def __init__(self, *shape):
            self.shape = shape

    params = {"token_embedding": L(100, 8), "path_embedding": L(50, 8),
              "target_embedding": L(30, 24), "transform": L(24, 24),
              "attention": L(24, 1)}
    buckets = plan_buckets(params, bucket_bytes=3000)
    flat = [n for b in buckets for n in b]
    # backward-completion order: classifier side first, gathers last
    assert flat == ["target_embedding", "attention", "transform",
                    "path_embedding", "token_embedding"]
    # every bucket respects the byte bound unless a single leaf exceeds
    # it alone
    for b in buckets:
        nbytes = sum(int(np.prod(params[n].shape)) * 4 for n in b)
        assert nbytes <= 3000 or len(b) == 1
    # one-bucket degenerate case with a huge budget
    assert plan_buckets(params, bucket_bytes=1 << 30) == [flat]
    # a leaf larger than the budget still lands (its own bucket)
    tiny = plan_buckets(params, bucket_bytes=1)
    assert [n for b in tiny for n in b] == flat
    assert all(len(b) == 1 for b in tiny)


def test_overlap_step_exposes_plan():
    step, _ = _build(True)
    assert step.overlap_buckets >= 2
    assert "gradient bucket" in step.overlap_description


def test_config_rejects_overlap_with_sparse_or_tp():
    base = dict(train_data_path_prefix="<t>", overlap_grad_allreduce=True)
    with pytest.raises(ValueError, match="sparse"):
        Config(**base, use_sparse_embedding_update=True).verify()
    # tp/cp sharding needs the manual-kernel path (GSPMD tp/cp keeps
    # the stock fused step)
    with pytest.raises(ValueError, match="manual_tp_kernels"):
        Config(**base, tp=2, max_contexts=200,
               use_manual_tp_kernels=False).verify()
    with pytest.raises(ValueError, match="manual_tp_kernels"):
        Config(**base, cp=2, max_contexts=200,
               use_manual_tp_kernels=False).verify()
    with pytest.raises(ValueError, match="overlap_bucket_mb"):
        Config(train_data_path_prefix="<t>",
               overlap_bucket_mb=0).verify()
    with pytest.raises(ValueError, match="overlap_in_backward"):
        Config(train_data_path_prefix="<t>",
               overlap_in_backward=True).verify()
    # the supported combos pass
    Config(**base, dp=2).verify()
    Config(**base, tp=2, max_contexts=200,
           use_manual_tp_kernels=True).verify()
    Config(**base, dp=2, overlap_in_backward=True).verify()


def test_overlap_refuses_foreign_opt_state():
    """A non-Adam optax state must be refused loudly, not mis-sliced."""
    from code2vec_tpu.parallel.overlap import build_overlap_train_step

    class FakeBuilder:
        config = Config(train_data_path_prefix="<t>",
                        overlap_grad_allreduce=True)
        module = optimizer = None
        mesh = None

    class FakeState:
        params = {"transform": np.zeros((2, 2), np.float32)}
        opt_state = (object(),)

    with pytest.raises(ValueError, match="ScaleByAdamState"):
        build_overlap_train_step(FakeBuilder(), FakeState())
