"""Child for test_multiprocess.test_two_process_facade_train — NOT pytest.

Each of two OS processes joins a real `jax.distributed` runtime and runs
the PRODUCTION training entry point — `Code2VecModel.train()` — over an
actual packed dataset whose raw strided shards are UNEVEN (12 vs 8 kept
train rows; the elastic global train order equalizes the per-host batch
counts, while the eval shards stay raw-strided at 3 vs 2 local batches,
exercising the lockstep eval padding). The facade path under test is
the full composition:
vocab load -> packed dataset shard -> `agree_scalar` lockstep truncation
-> jitted collective train steps -> mid-epoch collective eval (with
lockstep eval padding: 3 vs 2 local eval batches) -> per-epoch Orbax
checkpoint saves from both processes -> final save -> restore roundtrip.

Asserted here and in the parent:
- per-step training losses bit-comparable (rtol 1e-5) to the parent's
  single-process run of the same global stream;
- final params BIT-IDENTICAL across the two hosts (digest compare);
- the multi-host-saved artifact restores bit-identically.

Usage: python mp_child_facade.py <pid> <port> <root_dir> <expect.npz>
"""

import hashlib
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# jax < 0.5 has no jax_num_cpu_devices option; the legacy XLA flag does
# the same as long as it lands before the backend initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # covered by the XLA_FLAGS fallback above
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from code2vec_tpu.parallel import distributed  # noqa: E402


def params_digest(params) -> str:
    h = hashlib.md5()
    for name in sorted(params):
        h.update(name.encode())
        h.update(np.asarray(jax.device_get(params[name])).tobytes())
    return h.hexdigest()


def main():
    pid, port, root, expect_path = (
        int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4])
    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2 and jax.device_count() == 4

    from code2vec_tpu.config import Config
    from code2vec_tpu.model_facade import Code2VecModel
    from code2vec_tpu.training import checkpoint as ckpt_mod

    expect = np.load(expect_path)
    prefix = os.path.join(root, "data")
    save_path = os.path.join(root, "model", "m")

    config = Config(
        train_data_path_prefix=prefix,
        test_data_path=prefix + ".val.c2v",
        model_save_path=save_path,
        max_contexts=8,
        train_batch_size=8, test_batch_size=8,
        num_train_epochs=2,
        num_train_batches_to_evaluate=2,   # mid-epoch collective eval
        save_every_epochs=1,               # per-epoch multi-host saves
        num_batches_to_log_progress=1000,
        compute_dtype="float32",
        dropout_keep_rate=1.0,             # bit-comparability to parent
        use_packed_data=True,
        dp=4, verbose_mode=0,
    )
    model = Code2VecModel(config)

    # Record every training step's loss through the REAL facade path.
    losses = []
    orig_make = model.builder.make_train_step

    def make_recording(state):
        step = orig_make(state)

        def wrapped(s, *a):
            s2, loss = step(s, *a)
            losses.append(float(loss))
            return s2, loss

        return wrapped

    model.builder.make_train_step = make_recording
    model.train()

    # 2 epochs x 2 global batches (elastic global order: 20 filtered
    # rows // global batch 8). rtol 1e-4, not 1e-5: losses after step 1
    # are computed on params that already absorbed cross-topology float
    # summation-order differences (see the params comment below).
    np.testing.assert_allclose(losses, expect["losses"], rtol=1e-4)

    # Hosts hold the same replicated final params, bit for bit.
    digest = params_digest(model.state.params)
    with open(os.path.join(root, f"digest{pid}.txt"), "w") as f:
        f.write(digest)

    # Parent's single-process mimic of the same global stream agrees.
    # Tolerance is cross-TOPOLOGY (4-device psum vs single-device reduce:
    # different float summation order, amplified through 4 Adam steps);
    # the bit-exact claim is the cross-HOST digest above.
    flat = np.concatenate([
        np.asarray(jax.device_get(model.state.params[k])).ravel()
        for k in sorted(model.state.params)])
    np.testing.assert_allclose(flat, expect["final_params"],
                               rtol=2e-3, atol=5e-5)

    # The artifact written collectively by BOTH processes restores
    # bit-identically into the live sharded state template.
    restored = ckpt_mod.load_model(save_path, model.state, config)
    for k in sorted(model.state.params):
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored.params[k])),
            np.asarray(jax.device_get(model.state.params[k])))
    assert int(np.asarray(restored.step)) == len(losses)

    if pid == 0:
        with open(os.path.join(root, "facade_out.json"), "w") as f:
            json.dump({"losses": losses, "digest": digest,
                       "epochs": model.initial_epoch}, f)
    print(f"mp_child_facade {pid}: OK", flush=True)


if __name__ == "__main__":
    main()
