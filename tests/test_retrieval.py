"""Retrieval-stack tests: vector store, batch embed job, IVF/brute ANN
index, /neighbors serving, embedding-space fingerprint safety.

Store/index artifacts get the PR-8 treatment (round-trip + named-field
rejection matrix); the IVF index is scored for recall@k against its own
brute-force ground truth on a synthetic clustered corpus and pinned
EXACT (identical neighbor sets) at nprobe = nlist; the serving tests
drive POST /neighbors end to end over the scripted fake extractor from
test_serving and pin that a fingerprint-mismatched hot-swap can never
serve neighbors from a stale embedding space (refuse policy) or serves
them not at all (detach policy).
"""

import dataclasses
import json
import time
import urllib.request

import numpy as np
import pytest

from code2vec_tpu import obs
from code2vec_tpu.retrieval.index import (
    BACKEND_BRUTE, BACKEND_IVF, IndexArtifactError, build_index,
    load_index, measure_recall, train_kmeans,
)
from code2vec_tpu.retrieval.store import (
    StoreError, VectorStore, VectorStoreWriter,
)

pytestmark = pytest.mark.retrieval


# ---------------------------------------------------------------- helpers


def _clustered(n_clusters=12, per=40, dim=16, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(n_clusters, dim)) * 5.0
    pts = np.concatenate(
        [c + rng.normal(size=(per, dim)) * 0.3 for c in centers])
    return pts.astype(np.float32)


def _write_store(path, vectors, fingerprint="fp:test", dtype="float32",
                 shard_rows=100, ids=None):
    w = VectorStoreWriter(str(path), dim=vectors.shape[1], dtype=dtype,
                          model_fingerprint=fingerprint,
                          shard_rows=shard_rows)
    w.append(vectors,
             ids if ids is not None
             else [f"m{i}" for i in range(len(vectors))])
    return w.finalize()


def _counter_value(name, **labels):
    fams = obs.default_registry().collect()
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    child = fams.get(name, {}).get(key)
    return child.value if child is not None else 0.0


# ------------------------------------------------------------------ store


def test_store_round_trip_across_shard_boundaries(tmp_path):
    pts = _clustered(n_clusters=3, per=50)  # 150 rows, shard_rows=100
    manifest = _write_store(tmp_path / "store", pts)
    assert manifest["rows"] == 150 and len(manifest["shards"]) == 2
    s = VectorStore.open(str(tmp_path / "store"))
    assert (s.rows, s.dim, s.dtype) == (150, 16, "float32")
    assert s.fingerprint == "fp:test"
    assert s.ids[0] == "m0" and s.ids[-1] == "m149"
    np.testing.assert_allclose(s.load(), pts)
    # per-shard memmap view sums to the whole
    assert sum(sh.shape[0] for sh in s.iter_shards()) == 150


def test_store_fp16_halves_bytes_with_bounded_error(tmp_path):
    pts = _clustered(n_clusters=2, per=30)
    _write_store(tmp_path / "s16", pts, dtype="float16")
    s = VectorStore.open(str(tmp_path / "s16"))
    assert s.dtype == "float16"
    full = s.load(np.float32)
    # fp16 has ~3 decimal digits; these values are O(5)
    np.testing.assert_allclose(full, pts, atol=5e-2)
    raw = next(iter(s.iter_shards()))
    assert raw.dtype == np.float16


def test_store_rejection_matrix(tmp_path):
    pts = _clustered(n_clusters=2, per=30)
    base = tmp_path / "sv"
    _write_store(base, pts, shard_rows=30)

    def reopen(**kw):
        return VectorStore.open(str(base), **kw)

    # fingerprint pinning (the consumer names its embedding space)
    with pytest.raises(StoreError, match="model_fingerprint"):
        reopen(expect_fingerprint="fp:other")
    # not-a-store
    with pytest.raises(StoreError, match="`kind`"):
        VectorStore.open(str(tmp_path / "nope"))
    # torn ids sidecar
    ids_file = base / "shard_00000.ids"
    good_ids = ids_file.read_text()
    ids_file.write_text("only_one_line\n")
    with pytest.raises(StoreError, match="ids.*rows|rows"):
        reopen()
    ids_file.write_text(good_ids)
    reopen()
    # wrong dtype on disk vs manifest
    shard = base / "shard_00000.npy"
    arr = np.load(shard)
    np.save(shard, arr.astype(np.float16))
    with pytest.raises(StoreError, match="dtype"):
        reopen()
    np.save(shard, arr.astype(np.float32))
    reopen()
    # truncated shard
    np.save(shard, arr[:-3])
    with pytest.raises(StoreError, match="shape"):
        reopen()
    np.save(shard, arr)
    # manifest field surgery
    mpath = base / "vector_manifest.json"
    manifest = json.loads(mpath.read_text())
    for field, value in (("kind", "garbage"), ("format", 99),
                         ("complete", False)):
        doctored = dict(manifest)
        doctored[field] = value
        mpath.write_text(json.dumps(doctored))
        with pytest.raises(StoreError, match=field):
            reopen()
    mpath.write_text(json.dumps(manifest))
    reopen()


def test_store_incomplete_readable_only_with_allow_partial(tmp_path):
    w = VectorStoreWriter(str(tmp_path / "part"), dim=4, dtype="float32",
                          model_fingerprint="fp:t", shard_rows=5)
    w.append(np.ones((5, 4), np.float32), [str(i) for i in range(5)])
    # no finalize: one committed shard, store still "building"
    with pytest.raises(StoreError, match="complete"):
        VectorStore.open(str(tmp_path / "part"))
    s = VectorStore.open(str(tmp_path / "part"), allow_partial=True)
    assert s.rows == 5


def test_writer_resume_keeps_committed_shards(tmp_path):
    path = str(tmp_path / "res")
    pts = _clustered(n_clusters=1, per=25, dim=4)  # 25 rows
    w = VectorStoreWriter(path, dim=4, dtype="float32",
                          model_fingerprint="fp:r", shard_rows=10)
    w.append(pts[:23], [f"m{i}" for i in range(23)])
    # 2 shards committed (20 rows); 3 buffered rows die with the writer
    assert w.rows_done == 20
    w2 = VectorStoreWriter(path, dim=4, dtype="float32",
                           model_fingerprint="fp:r", shard_rows=10)
    assert w2.rows_done == 20
    w2.append(pts[20:], [f"m{i}" for i in range(20, 25)])
    w2.finalize()
    s = VectorStore.open(path)
    assert s.rows == 25
    np.testing.assert_allclose(s.load(), pts)
    assert s.ids == [f"m{i}" for i in range(25)]
    # resume must never mix embedding spaces
    with pytest.raises(StoreError, match="model_fingerprint"):
        VectorStoreWriter(path, dim=4, dtype="float32",
                          model_fingerprint="fp:OTHER", shard_rows=10)
    # and a complete store refuses silent appends
    with pytest.raises(StoreError, match="complete"):
        VectorStoreWriter(path, dim=4, dtype="float32",
                          model_fingerprint="fp:r", shard_rows=10)
    # resume=False rebuilds from scratch (offline export semantics)
    w3 = VectorStoreWriter(path, dim=4, dtype="float32",
                           model_fingerprint="fp:r", shard_rows=10,
                           resume=False)
    w3.append(pts[:10], [f"x{i}" for i in range(10)])
    w3.finalize()
    assert VectorStore.open(path).rows == 10


# ------------------------------------------------------------------ index


def test_kmeans_deterministic_and_jitted():
    pts = _clustered()
    c1 = train_kmeans(pts, 12, iters=5, seed=3)
    c2 = train_kmeans(pts, 12, iters=5, seed=3)
    np.testing.assert_array_equal(c1, c2)
    assert c1.shape == (12, 16) and np.isfinite(c1).all()


def test_ivf_recall_on_clustered_corpus(tmp_path):
    pts = _clustered(n_clusters=12, per=40)
    _write_store(tmp_path / "store", pts)
    meta = build_index(str(tmp_path / "store"), str(tmp_path / "idx"),
                       nlist=12, nprobe=8, kmeans_iters=8, seed=0,
                       log=lambda m: None)
    assert meta["backend"] == BACKEND_IVF
    idx = load_index(str(tmp_path / "idx"))
    queries = pts[::17]
    # the acceptance bar: recall@10 >= 0.95 at the default nprobe
    assert measure_recall(idx, queries, 10) >= 0.95
    # identity query: a stored vector's own row is its top-1
    pos, scores = idx.search(pts[:5], 1)
    assert [idx.ids[p] for p in pos[:, 0]] == [f"m{i}" for i in range(5)]
    assert np.all(scores[:, 0] > 0.999)  # cosine of self


def test_ivf_equals_brute_force_at_full_probe(tmp_path):
    """nprobe = nlist probes every inverted list: the candidate set is
    the whole store and the two backends must return identical neighbor
    sets — the exactness contract of the acceptance criteria."""
    pts = _clustered(n_clusters=12, per=40, seed=7)
    _write_store(tmp_path / "store", pts)
    build_index(str(tmp_path / "store"), str(tmp_path / "idx"),
                nlist=12, kmeans_iters=6, log=lambda m: None)
    idx = load_index(str(tmp_path / "idx"))
    queries = pts[::11]
    approx, av = idx.search(queries, 10, nprobe=idx.nlist)
    exact, ev = idx.search(queries, 10, exact=True)
    for a, e in zip(approx, exact):
        assert set(a.tolist()) == set(e.tolist())
    # and the kept scores agree (same dot products, sorted descending)
    np.testing.assert_allclose(np.sort(av, axis=1),
                               np.sort(ev, axis=1), rtol=1e-5)
    assert measure_recall(idx, queries, 10, nprobe=idx.nlist) == 1.0


def test_small_corpus_falls_back_to_brute_force(tmp_path):
    pts = _clustered(n_clusters=2, per=20, dim=8)  # 40 < MIN_IVF_ROWS
    _write_store(tmp_path / "store", pts)
    meta = build_index(str(tmp_path / "store"), str(tmp_path / "idx"),
                       nlist=8, log=lambda m: None)
    assert meta["backend"] == BACKEND_BRUTE
    idx = load_index(str(tmp_path / "idx"))
    pos, scores = idx.search(pts[3], 5)  # 1-D query auto-batches
    assert pos.shape == (1, 5)
    assert idx.ids[pos[0, 0]] == "m3"


def test_index_carries_store_fingerprint_and_fp16(tmp_path):
    pts = _clustered(n_clusters=2, per=30, dim=8)
    _write_store(tmp_path / "store", pts, fingerprint="fp:abc",
                 dtype="float16")
    build_index(str(tmp_path / "store"), str(tmp_path / "idx"),
                log=lambda m: None)
    idx = load_index(str(tmp_path / "idx"))
    assert idx.fingerprint == "fp:abc"
    with pytest.raises(IndexArtifactError, match="model_fingerprint"):
        load_index(str(tmp_path / "idx"), expect_fingerprint="fp:zzz")
    load_index(str(tmp_path / "idx"), expect_fingerprint="fp:abc")


def test_index_rejection_matrix(tmp_path):
    pts = _clustered(n_clusters=12, per=40)
    _write_store(tmp_path / "store", pts)
    base = tmp_path / "idx"
    build_index(str(tmp_path / "store"), str(base), nlist=12,
                log=lambda m: None)
    load_index(str(base))
    with pytest.raises(IndexArtifactError, match="`kind`"):
        load_index(str(tmp_path / "nothere"))
    # truncated vectors payload
    vecs = np.load(base / "vectors.npy")
    np.save(base / "vectors.npy", vecs[:-1])
    with pytest.raises(IndexArtifactError, match="vectors.shape"):
        load_index(str(base))
    np.save(base / "vectors.npy", vecs)
    # torn ids
    ids_text = (base / "ids.txt").read_text()
    (base / "ids.txt").write_text("just_one\n")
    with pytest.raises(IndexArtifactError, match="ids"):
        load_index(str(base))
    (base / "ids.txt").write_text(ids_text)
    # inconsistent offsets
    offsets = np.load(base / "list_offsets.npy")
    np.save(base / "list_offsets.npy", offsets[:-1])
    with pytest.raises(IndexArtifactError, match="list_offsets"):
        load_index(str(base))
    np.save(base / "list_offsets.npy", offsets)
    # meta surgery
    mpath = base / "index_meta.json"
    meta = json.loads(mpath.read_text())
    for field, value in (("kind", "junk"), ("format", 99),
                         ("backend", "hnsw"), ("metric", "hamming")):
        doctored = dict(meta)
        doctored[field] = value
        mpath.write_text(json.dumps(doctored))
        with pytest.raises(IndexArtifactError, match=field):
            load_index(str(base))
    doctored = dict(meta)
    del doctored["nprobe"]
    mpath.write_text(json.dumps(doctored))
    with pytest.raises(IndexArtifactError, match="nprobe"):
        load_index(str(base))
    mpath.write_text(json.dumps(meta))
    load_index(str(base))


# -------------------------------------------------------------- embed job


@pytest.fixture(scope="module")
def retrieval_model(tmp_path_factory):
    import test_serving as ts
    from code2vec_tpu.model_facade import Code2VecModel
    tmp_path = tmp_path_factory.mktemp("retrieval-model")
    ts._write_synthetic_dataset(tmp_path)
    config = ts._serving_config(tmp_path, embed_shard_rows=8)
    config.test_data_path = str(tmp_path / "synthetic.train.c2v")
    return Code2VecModel(config)


def test_embed_job_end_to_end(retrieval_model, tmp_path):
    from code2vec_tpu.retrieval.embed_job import run_embed_job
    model = retrieval_model
    out = str(tmp_path / "vecs")
    summary = run_embed_job(model, out_dir=out)
    s = VectorStore.open(out)
    assert s.rows == summary["rows"] == 32  # every synthetic row embeds
    assert s.dim == model.config.code_vector_size
    assert s.fingerprint == model.model_fingerprint()
    assert all(i.startswith("name|") for i in s.ids)  # targets sidecar
    vecs = s.load()
    assert np.isfinite(vecs).all() and np.abs(vecs).sum() > 0
    assert summary["resumed_rows"] == 0
    assert _counter_value("retrieval_embed_rows_total") >= 32


def test_embed_job_resumes_past_committed_shards(retrieval_model,
                                                 tmp_path, monkeypatch):
    from code2vec_tpu.retrieval.embed_job import run_embed_job
    model = retrieval_model
    out = str(tmp_path / "vecs-resume")
    real_step, real_params = model.eval_callable()
    calls = {"n": 0, "fail_after": 2}

    def wrapped(params, *arrays):
        calls["n"] += 1
        if calls["fail_after"] and calls["n"] > calls["fail_after"]:
            raise RuntimeError("injected mid-job crash")
        return real_step(params, *arrays)

    monkeypatch.setattr(model, "eval_callable",
                        lambda: (wrapped, real_params))
    # first run dies after 2 device batches (16 rows = 2 full shards at
    # embed_shard_rows=8, test_batch_size=8)
    with pytest.raises(RuntimeError, match="injected"):
        run_embed_job(model, out_dir=out)
    committed = VectorStore.open(out, allow_partial=True).rows
    assert committed == 16
    # second run resumes: only the REMAINING batches touch the device
    calls.update(n=0, fail_after=0)
    summary = run_embed_job(model, out_dir=out)
    assert summary["resumed_rows"] == committed
    assert calls["n"] == 2  # 4 batches total, 2 were already committed
    s = VectorStore.open(out)
    assert s.rows == 32
    # resumed store is byte-identical to a single-pass embed
    fresh = str(tmp_path / "vecs-fresh")
    run_embed_job(model, out_dir=fresh)
    np.testing.assert_array_equal(s.load(), VectorStore.open(fresh).load())
    assert s.ids == VectorStore.open(fresh).ids


# ----------------------------------------------------- offline exports


def test_export_code_vectors_writes_store_format(retrieval_model):
    model = retrieval_model
    config = model.config
    config.export_code_vectors = True
    config.vectors_text = False
    try:
        model.evaluate()
    finally:
        config.export_code_vectors = False
    store_path = config.test_data_path + ".vectors"
    s = VectorStore.open(store_path)
    assert s.rows == 32 and s.dim == config.code_vector_size
    assert s.fingerprint == model.model_fingerprint()


def test_export_code_vectors_text_compat(retrieval_model):
    model = retrieval_model
    config = model.config
    config.export_code_vectors = True
    config.vectors_text = True
    try:
        model.evaluate()
    finally:
        config.export_code_vectors = False
        config.vectors_text = False
    vectors_path = config.test_data_path + ".vectors"
    with open(vectors_path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 32
    assert all(len(line.split()) == config.code_vector_size
               for line in lines)


def test_export_embeddings_word2vec_format(retrieval_model, tmp_path):
    from code2vec_tpu.vocab import VocabType
    model = retrieval_model
    out = str(tmp_path / "emb")
    paths = model.export_embeddings(out)
    for vocab_type, key in ((VocabType.Token, "tokens"),
                            (VocabType.Target, "targets")):
        matrix = model._get_vocab_embedding_as_np_array(vocab_type)
        with open(paths[key]) as f:
            header = f.readline().split()
            assert [int(x) for x in header] == list(matrix.shape)
            first = f.readline().split()
            assert first[0] == model.vocabs.get(
                vocab_type).index_to_word[0]
            np.testing.assert_allclose(
                np.array(first[1:], dtype=np.float64), matrix[0],
                rtol=1e-6)
            assert sum(1 for _ in f) == matrix.shape[0] - 1


# -------------------------------------------------------- /neighbors


@pytest.fixture(scope="module")
def fake_extractor_module(tmp_path_factory):
    import os
    import test_serving as ts
    path = tmp_path_factory.mktemp("fakex") / "fake-c2v-extract"
    path.write_text(ts.FAKE_EXTRACTOR)
    path.chmod(0o755)
    old = os.environ.get("C2V_NATIVE_EXTRACTOR")
    os.environ["C2V_NATIVE_EXTRACTOR"] = str(path)
    yield str(path)
    if old is None:
        os.environ.pop("C2V_NATIVE_EXTRACTOR", None)
    else:
        os.environ["C2V_NATIVE_EXTRACTOR"] = old


def _snippet(name, nctx):
    return f"class A {{ int {name}() {{ return 1; }} }} NCTX{nctx}"


@pytest.fixture(scope="module")
def neighbor_server(retrieval_model, fake_extractor_module,
                    tmp_path_factory):
    """Corpus rows built from the fake extractor's own output for known
    snippets -> querying the same snippet must find its row as the
    nearest neighbor (an identical vector)."""
    from code2vec_tpu.retrieval.embed_job import run_embed_job
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    from code2vec_tpu.serving.server import PredictionServer
    model = retrieval_model
    tmp = tmp_path_factory.mktemp("neigh")
    names = [f"corpusMethod{i}" for i in range(6)]
    with ExtractorPool(model.config, size=1) as pool:
        rows = []
        for i, name in enumerate(names):
            lines, _ = pool.extract_source(_snippet(name, 2 + i % 4))
            rows.append(lines[0].rstrip("\n"))
    corpus = tmp / "neigh.test.c2v"
    corpus.write_text("\n".join(rows) + "\n")
    store_dir, idx_dir = str(tmp / "store"), str(tmp / "idx")
    run_embed_job(model, corpus_path=str(corpus), out_dir=store_dir)
    build_index(store_dir, idx_dir, log=lambda m: None)
    config = model.config
    config.retrieval_index = idx_dir
    srv = PredictionServer(model, config, log=lambda m: None)
    srv.start(port=0)
    yield srv
    srv.drain(timeout=10)
    config.retrieval_index = None


def _post(port, endpoint, body, ctype="text/plain"):
    import urllib.error
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}", data=body.encode(),
        method="POST", headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_neighbors_http_end_to_end(neighbor_server):
    srv = neighbor_server
    # the same snippet corpusMethod2 was embedded from: identical
    # contexts -> identical vector -> the near-duplicate is FIRST
    status, body = _post(srv.port, "neighbors",
                         _snippet("corpusMethod2", 4))
    assert status == 200
    payload = json.loads(body)
    assert payload["embedding_fingerprint"] == \
        srv.retrieval.index.fingerprint
    assert payload["model_fingerprint"] == srv.model_fingerprint
    [method] = payload["methods"]
    assert method["original_name"] == "corpusMethod2"
    top = method["neighbors"][0]
    assert top["id"] == "corpusMethod2"
    assert top["score"] > 0.999 and top["distance"] < 1e-3
    assert {"id", "store_row", "score", "distance"} <= set(top)
    # scores sorted descending, distances consistent with the metric
    scores = [n["score"] for n in method["neighbors"]]
    assert scores == sorted(scores, reverse=True)
    # k override via JSON body
    status, body = _post(
        srv.port, "neighbors",
        json.dumps({"code": _snippet("corpusMethod0", 2), "k": 2}),
        "application/json")
    assert status == 200
    [method] = json.loads(body)["methods"]
    assert len(method["neighbors"]) == 2
    assert method["neighbors"][0]["id"] == "corpusMethod0"
    # bad knobs are a 400, not a search
    status, _ = _post(srv.port, "neighbors",
                      json.dumps({"code": "class A {}", "k": "lots"}),
                      "application/json")
    assert status == 400
    # healthz advertises the mount
    with urllib.request.urlopen(
            f"http://127.0.0.1:{srv.port}/healthz", timeout=30) as r:
        hz = json.loads(r.read())
    assert hz["retrieval"]["status"] == "attached"
    assert hz["retrieval"]["fingerprint"] == \
        srv.retrieval.index.fingerprint
    assert hz["retrieval"]["rows"] == 6


def test_neighbors_carries_trace_ids_and_ann_span(neighbor_server,
                                                  monkeypatch):
    """Satellite pin: /neighbors rides the same request-scoped tracing
    as /predict — inbound traceparent honored and echoed in X-Trace-Id,
    and the debug tree includes the ann_search span."""
    import urllib.error
    srv = neighbor_server
    monkeypatch.setattr(srv.config, "serve_debug_trace", True)
    inbound_trace, inbound_span = "ef" * 16, "12" * 8

    def post(query="", headers=None):
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/neighbors{query}",
            data=_snippet("corpusMethod3", 5).encode(), method="POST",
            headers=dict({"Content-Type": "text/plain"},
                         **(headers or {})))
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                return r.status, r.read(), dict(r.headers)
        except urllib.error.HTTPError as e:
            return e.code, e.read(), dict(e.headers)

    status, body, headers = post(
        query="?debug=trace",
        headers={"traceparent":
                 f"00-{inbound_trace}-{inbound_span}-01"})
    assert status == 200
    assert headers["X-Trace-Id"] == inbound_trace
    trace = json.loads(body)["trace"]
    assert trace["trace_id"] == inbound_trace
    by_name = {s["name"]: s for s in trace["spans"]}
    # the whole pipeline plus the retrieval-specific search span
    assert {"request", "cache_lookup", "extract", "batch", "device",
            "ann_search", "render"} <= set(by_name)
    assert by_name["ann_search"]["attrs"]["rows"] == 6
    assert by_name["ann_search"]["attrs"]["queries"] == 1
    assert by_name["request"]["parent_id"] == inbound_span
    # minted ids when no header; the debug field is gated off by default
    monkeypatch.setattr(srv.config, "serve_debug_trace", False)
    status, body, headers = post(query="?debug=trace")
    assert status == 200
    assert "trace" not in json.loads(body)
    tid = headers["X-Trace-Id"]
    assert len(tid) == 32 and tid != inbound_trace


def test_neighbors_zero_methods_is_empty_not_500(neighbor_server):
    """A snippet extracting to zero methods must render an empty
    neighbor list, never crash the search on a (0, ?) batch."""
    srv = neighbor_server
    payload = srv._render("neighbors", [], {}, srv.model_fingerprint,
                          knobs={})
    assert payload["methods"] == []
    assert payload["embedding_fingerprint"] == \
        srv.retrieval.index.fingerprint


def test_neighbors_knobs_bucketed_to_bounded_compiles(neighbor_server):
    """Client k/nprobe values bucket to powers of two before the jitted
    search: a knob sweep must not compile one function per value (the
    serving compilation-budget discipline), while the response still
    honors the exact requested k."""
    srv = neighbor_server
    idx = srv.retrieval.index
    code = _snippet("corpusMethod4", 5)
    fns_before = len(idx._search_fns)
    for k in (3, 4):  # both bucket to k_eff=4
        status, body = _post(
            srv.port, "neighbors",
            json.dumps({"code": code, "k": k}), "application/json")
        assert status == 200
        [method] = json.loads(body)["methods"]
        assert len(method["neighbors"]) == k
    assert len(idx._search_fns) - fns_before <= 1


def test_neighbors_cache_hit_is_byte_equal(neighbor_server):
    srv = neighbor_server
    code = _snippet("corpusMethod1", 3)
    _, body1 = _post(srv.port, "neighbors", code)
    hits0 = _counter_value("serving_cache_hits_total")
    _, body2 = _post(srv.port, "neighbors", code)
    assert body2 == body1
    assert _counter_value("serving_cache_hits_total") == hits0 + 1
    # a different k is a different answer -> different cache entry
    _, body3 = _post(srv.port, "neighbors",
                     json.dumps({"code": code, "k": 1}),
                     "application/json")
    assert body3 != body1


def test_neighbors_404_without_mount(retrieval_model,
                                     fake_extractor_module):
    from code2vec_tpu.serving.server import PredictionServer
    config = retrieval_model.config
    saved = config.retrieval_index
    config.retrieval_index = None
    srv = PredictionServer(retrieval_model, config, log=lambda m: None)
    try:
        status, body, _ = srv.handle_request("neighbors", "class A {}")
        assert status == 404
        assert b"retrieval_index" in body
    finally:
        srv.drain(timeout=5)
        config.retrieval_index = saved


def test_mount_refuses_foreign_fingerprint(retrieval_model, tmp_path):
    from code2vec_tpu.retrieval.api import RetrievalHandle
    pts = _clustered(n_clusters=2, per=20, dim=retrieval_model.config
                     .code_vector_size)
    _write_store(tmp_path / "store", pts, fingerprint="fp:foreign")
    build_index(str(tmp_path / "store"), str(tmp_path / "idx"),
                log=lambda m: None)
    with pytest.raises(IndexArtifactError, match="model_fingerprint"):
        RetrievalHandle.mount(str(tmp_path / "idx"),
                              retrieval_model.model_fingerprint())


class _FakeSwapModel:
    """Stands in for a validated new model whose weights (fingerprint)
    differ from the mounted index's embedding space."""

    def __init__(self, schema, buckets, fingerprint="ckpt:swapped"):
        self._schema = dict(schema)
        self.context_buckets = tuple(buckets)
        self._fp = fingerprint
        self._predict_steps = {}

    def model_fingerprint(self):
        return self._fp

    def smoke_schema(self):
        return dict(self._schema)

    def predict_compile_count(self):
        return 0


def _wait_swap(server, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        state = server.swap.status()["state"]
        if state in ("ready", "failed"):
            return state
        time.sleep(0.05)
    raise AssertionError(f"swap stuck in {server.swap.status()}")


def test_swap_refused_on_embedding_fingerprint_mismatch(neighbor_server):
    """Default policy: a hot-swap whose weights mismatch the mounted
    index is REJECTED — old model keeps serving, /neighbors stays
    consistent, reason lands in swap_status."""
    srv = neighbor_server
    old_fp = srv.model_fingerprint
    schema = srv.model.smoke_schema()
    fake = _FakeSwapModel(schema, srv.model.context_buckets)
    from code2vec_tpu.serving.swap import SwapManager
    srv.swap = SwapManager(srv, build_model=lambda d: fake)
    srv.swap.request_reload("/fake/new-artifact")
    assert _wait_swap(srv) == "failed"
    assert "embedding space" in srv.swap.status()["error"]
    assert srv.model_fingerprint == old_fp
    assert srv.retrieval.attached
    status, _ = _post(srv.port, "neighbors", _snippet("corpusMethod3", 5))
    assert status == 200


def test_swap_detach_policy_never_serves_stale_space(neighbor_server):
    """Policy detach: the swap commits but the index detaches ATOMICALLY
    with the model flip — /neighbors answers 503 with the reason in
    /healthz, never neighbors from the old embedding space."""
    srv = neighbor_server
    schema = srv.model.smoke_schema()
    old_model, old_fp = srv._model_ref
    fake = _FakeSwapModel(schema, srv.model.context_buckets)
    from code2vec_tpu.serving.swap import SwapManager
    srv.config.retrieval_swap_policy = "detach"
    detached0 = _counter_value("serving_retrieval_detached_total",
                               reason="fingerprint_mismatch")
    try:
        srv.swap = SwapManager(srv, build_model=lambda d: fake)
        srv.swap.request_reload("/fake/new-artifact")
        assert _wait_swap(srv) == "ready"
        assert srv.model_fingerprint == "ckpt:swapped"
        assert not srv.retrieval.attached
        st = srv.retrieval.status()
        assert st["status"] == "detached"
        assert "rebuild the index" in st["detach_reason"]
        assert _counter_value("serving_retrieval_detached_total",
                              reason="fingerprint_mismatch") == \
            detached0 + 1
        status, body = _post(srv.port, "neighbors",
                             _snippet("corpusMethod3", 5))
        assert status == 503
        assert b"detached" in body
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/healthz",
                timeout=30) as r:
            hz = json.loads(r.read())
        assert hz["retrieval"]["status"] == "detached"
    finally:
        # restore the real model/index pairing for any later test
        srv.config.retrieval_swap_policy = "refuse"
        srv._model_ref = (old_model, old_fp)
        srv.retrieval._attached = True
        srv.retrieval._detach_reason = None


# --------------------------------------------------------------- CLI


def test_cli_subcommand_contracts():
    from code2vec_tpu.cli import config_from_args
    config = config_from_args(
        ["index-build", "--vectors", "/tmp/v", "--index_out", "/tmp/i",
         "--nlist", "32", "--nprobe", "4"])
    config.verify()
    assert (config.index_vectors, config.index_out) == ("/tmp/v", "/tmp/i")
    assert (config.index_nlist, config.index_nprobe) == (32, 4)
    with pytest.raises(SystemExit):
        config_from_args(["embed", "--load", "/tmp/m"])
    with pytest.raises(SystemExit):
        config_from_args(["index-build", "--vectors", "/tmp/v"])
    with pytest.raises(SystemExit):
        config_from_args(["export-embeddings", "--load", "/tmp/m"])
    config = config_from_args(
        ["embed", "--load", "/tmp", "--test", "corpus.c2v",
         "--embed_out", "/tmp/vecs", "--embed_dtype", "float16"])
    assert config.embed_out == "/tmp/vecs"
    assert config.embed_dtype == "float16"
    with pytest.raises(ValueError, match="retrieval_index"):
        config_from_args(["--load", "/tmp",
                          "--retrieval_index", "/tmp/i"]).verify()
