"""Child process for tests/test_multiprocess.py — NOT a pytest module.

Each of two OS processes runs this script: joins a real
`jax.distributed` runtime (CPU backend, gloo collectives, 2 local
devices -> 4 global), then checks the three multi-host contracts of
parallel/distributed.py against expectations the parent computed
single-process:

1. `allreduce_host_scalars` sums across processes;
2. `global_batch_arrays` (via `device_put_batch`) assembles per-host
   row shards into the right global array — verified end-to-end by
   running the REAL jitted train/eval step on a dp=4 mesh and matching
   the parent's single-device loss (any row scrambling or bad layout
   changes the loss);
3. the Evaluator reports GLOBAL metrics from per-host data shards
   (counter allreduce + host-local row extraction), matching the
   parent's single-process evaluation of the same data bit-for-bit.

Usage: python mp_child.py <process_id> <port> <data.npz> <out.json>
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# jax < 0.5 has no jax_num_cpu_devices option; the legacy XLA flag does
# the same as long as it lands before the backend initializes.
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # covered by the XLA_FLAGS fallback above
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from code2vec_tpu.parallel import distributed  # noqa: E402


def main():
    pid, port, data_path, out_path = (
        int(sys.argv[1]), sys.argv[2], sys.argv[3], sys.argv[4])

    # 1. join the runtime through the framework's own wrapper
    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert jax.device_count() == 4, jax.device_count()
    assert jax.local_device_count() == 2

    # 2. host-scalar allreduce
    reduced = distributed.allreduce_host_scalars(
        np.array([1.0 + pid, 10.0 * (1 + pid)]))
    np.testing.assert_allclose(reduced, [3.0, 30.0])

    import jax.numpy as jnp
    from code2vec_tpu.config import Config
    from code2vec_tpu.data.reader import RowBatch
    from code2vec_tpu.evaluation.evaluator import Evaluator
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    from code2vec_tpu.training.state import create_train_state, make_optimizer
    from code2vec_tpu.training.step import TrainStepBuilder, device_put_batch
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

    data = np.load(data_path, allow_pickle=True)
    B = int(data["B"])
    local = slice(pid * B // 2, (pid + 1) * B // 2)

    # dropout off: the loss must be bit-comparable to the parent's
    # single-device run independent of RNG partitioning details
    config = Config(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=B, test_batch_size=B, max_contexts=8,
                    dp=4, tp=1, cp=1, dropout_keep_rate=1.0)
    dims = ModelDims(token_vocab_size=24, path_vocab_size=16,
                     target_vocab_size=16, token_dim=4, path_dim=4)
    mesh = make_mesh(MeshPlan(dp=4))
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=config.dropout_keep_rate)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(7), mesh=mesh)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)

    local_batch = RowBatch(
        source_token_indices=data["src"][local],
        path_indices=data["pth"][local],
        target_token_indices=data["tgt"][local],
        context_valid_mask=data["mask"][local],
        target_index=data["labels"][local],
        example_valid=data["valid"][local],
        target_strings=list(data["names"][local]))

    # 3a. real eval step over the assembled global batch: loss must match
    # the parent's single-device computation on the full batch.
    arrays = device_put_batch(local_batch, mesh)
    eval_step = builder.make_eval_step(state, k=3)
    out = eval_step(state.params, *arrays)
    loss_sum = float(out.loss_sum)
    np.testing.assert_allclose(loss_sum, float(data["expected_loss_sum"]),
                               rtol=1e-5)

    # 3b. Evaluator end-to-end: per-host data shards -> global metrics.
    # (Before the train step: it donates the state's buffers.)
    freq = WordFreqDicts(
        token_to_count={"foo": 10, "bar": 8, "baz": 5, "qux": 2},
        path_to_count={"P1": 9, "P2": 7, "P3": 3},
        target_to_count={f"w{i}": 20 - i for i in range(12)},
        num_train_examples=100)
    vocabs = Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=30, max_path_vocab_size=20,
        max_target_vocab_size=20)
    evaluator = Evaluator(config, vocabs, eval_step, mesh=mesh,
                          log_path=os.path.join(
                              os.path.dirname(out_path), f"log{pid}.txt"))
    results = evaluator.evaluate(state.params, [local_batch])

    # 3b-uneven. THE lockstep case VERDICT flagged: hosts whose
    # post-filter shards yield DIFFERENT batch counts. 18 real rows split
    # 10/8 -> host 0 builds 3 local batches, host 1 only 2; the agreed
    # max (3) pads host 1 with an invalid batch so both hosts drive the
    # same number of collective eval steps, and the global metrics must
    # still match the parent's single-process evaluation of all 18 rows.
    from code2vec_tpu.data.reader import _pad_rows, _select_rows, invalid_batch

    lo, hi = (0, 10) if pid == 0 else (10, 18)
    uneven_local = RowBatch(
        source_token_indices=data["u_src"][lo:hi],
        path_indices=data["u_pth"][lo:hi],
        target_token_indices=data["u_tgt"][lo:hi],
        context_valid_mask=data["u_mask"][lo:hi],
        target_index=data["u_labels"][lo:hi],
        example_valid=np.ones((hi - lo,), bool),
        target_strings=list(data["u_names"][lo:hi]))
    local_bs = B // 2
    local_batches = [
        _pad_rows(_select_rows(uneven_local,
                               np.arange(s, min(s + local_bs, hi - lo))),
                  local_bs)
        for s in range(0, hi - lo, local_bs)]
    assert len(local_batches) == (3 if pid == 0 else 2)
    agreed_eval = distributed.agree_scalar(len(local_batches), "max")
    assert agreed_eval == 3, agreed_eval
    stream = distributed.lockstep_eval_stream(
        iter(local_batches), agreed_eval, lambda: invalid_batch(local_bs, 8))
    ev_uneven = Evaluator(config, vocabs, eval_step, mesh=mesh,
                          log_path=os.path.join(
                              os.path.dirname(out_path), f"log_u{pid}.txt"))
    res_u = ev_uneven.evaluate(state.params, stream)
    np.testing.assert_allclose(res_u.topk_acc, data["u_topk"], atol=1e-12)
    np.testing.assert_allclose(res_u.subtoken_precision,
                               float(data["u_precision"]), atol=1e-12)
    np.testing.assert_allclose(res_u.subtoken_recall,
                               float(data["u_recall"]), atol=1e-12)
    np.testing.assert_allclose(res_u.subtoken_f1, float(data["u_f1"]),
                               atol=1e-12)
    np.testing.assert_allclose(res_u.loss, float(data["u_loss"]), rtol=1e-5)

    # 3c. real train step: parameters update collectively; the returned
    # loss is the same global mean on every host.
    train_step = builder.make_train_step(state)
    _, tr_loss = train_step(state, *arrays, jax.random.PRNGKey(0))
    np.testing.assert_allclose(float(tr_loss),
                               float(data["expected_train_loss"]), rtol=1e-5)

    # 4. preemption agreement: SIGTERM is delivered ONLY to process 0,
    # but both hosts must leave the collective step loop at the same
    # reduce boundary (training/loop.py preemption_agreed) — a lone
    # host breaking out would deadlock the other.
    import signal as _signal
    from code2vec_tpu.data.reader import EpochEnd
    from code2vec_tpu.training.loop import Trainer

    cfg2 = Config(train_data_path_prefix="unused", train_batch_size=B,
                  max_contexts=8, num_train_epochs=1, dp=4)
    steps2, saves2 = [], []

    def stream2():
        for b in range(40):
            if b == 5 and pid == 0:
                os.kill(os.getpid(), _signal.SIGTERM)
            yield local_batch
        yield EpochEnd(1)

    def fake_step(s, *a):
        steps2.append(1)
        return s, np.float32(1.0)

    class _S:
        step = np.zeros((), np.int32)

    tr = Trainer(cfg2, fake_step,
                 save_fn=lambda s, e, suffix="": saves2.append((e, suffix)))
    tr.train(_S(), stream2(), rng=np.zeros((2,), np.uint32))
    assert tr.preempted, f"pid {pid}: no preemption agreement reached"
    assert len(steps2) < 40, f"pid {pid}: ran the whole stream"
    assert saves2 == [(0, "_preempt")], saves2

    # 5. UNEVEN train shards through the full Trainer loop: host 0's
    # post-filter stream yields 7 batches/epoch, host 1 only 5. The
    # agreed minimum truncates both to 5; the step, the mid-epoch eval
    # (every 3 batches) and the preemption OR-reduce (every 10) each run
    # a real host collective, so any residual count divergence hangs the
    # pod (and trips the parent's timeout) instead of passing silently.
    local_steps = 7 if pid == 0 else 5
    agreed_train = distributed.agree_scalar(local_steps, "min")
    assert agreed_train == 5, agreed_train

    def uneven_stream():
        for epoch in (1, 2):
            for _ in range(local_steps):
                yield local_batch
            yield EpochEnd(epoch)

    steps5, evals5 = [], []

    def collective_step(s, *a):
        got = distributed.allreduce_host_scalars(np.ones(1))
        assert got[0] == 2.0
        steps5.append(1)
        return s, np.float32(0.5)

    def collective_eval(state):
        evals5.append(float(distributed.allreduce_host_scalars(
            np.array([2.0]))[0]))
        return None

    cfg5 = Config(train_data_path_prefix="unused", train_batch_size=B,
                  max_contexts=8, num_train_epochs=2, dp=4,
                  num_train_batches_to_evaluate=3)
    tr5 = Trainer(cfg5, collective_step, evaluate_fn=collective_eval,
                  steps_per_epoch_hint=agreed_train)
    tr5.train(_S(), distributed.lockstep_train_stream(
        uneven_stream(), agreed_train), rng=np.zeros((2,), np.uint32))
    # 5 lockstep batches x 2 epochs; 1 mid-epoch + 1 epoch-end eval each
    assert len(steps5) == 10, len(steps5)
    assert len(evals5) == 4 and all(v == 4.0 for v in evals5), evals5

    if pid == 0:
        with open(out_path, "w") as f:
            json.dump({
                "loss_sum": loss_sum,
                "train_loss": float(tr_loss),
                "eval": {
                    "topk_acc": [float(x) for x in results.topk_acc],
                    "precision": float(results.subtoken_precision),
                    "recall": float(results.subtoken_recall),
                    "f1": float(results.subtoken_f1),
                    "loss": float(results.loss),
                },
            }, f)
    print(f"mp_child {pid}: OK", flush=True)


if __name__ == "__main__":
    main()
