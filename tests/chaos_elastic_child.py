"""Child process for tests/test_elastic_resume.py — NOT a pytest module.

Each invocation runs the PRODUCTION facade (`Code2VecModel`) over a
pre-packed dataset the parent built, as one member of an N-process pod
(N=1 joins no distributed runtime; N=2 joins a real jax.distributed
pair with gloo collectives, 2 local CPU devices each). The parent
composes invocations into elastic-resume scenarios: train on N, kill
the whole pod mid-run, resume on M != N (or on a reshaped mesh) from
the last committed artifact.

Subcommands (shared argv prefix: `<cmd> <pid> <nprocs> <port> <data_prefix>
<save_base> <dp> <tp> <epochs>`):

- `train [fault_spec]` — facade training with per-epoch checkpoints.
  Every `save_model` call first prints `ELASTIC_SAVED <pid> <epoch>
  digest=<md5-of-params>` — the parent's bit-equality oracle for what
  each committed artifact must restore to. `fault_spec` (e.g.
  `callback_crash@2=exit`) arms a hard kill: with save-per-epoch, hit 2
  fires inside the SECOND save's post-commit window, so the whole pod
  dies mid-run with `_iter2` committed — the canonical "preempted pod"
  fixture. A clean run (no spec) prints `ELASTIC_LOSSES <pid> <json>`
  and serves as the uninterrupted-trajectory reference.

- `resume` — facade construction with `--load <save_base>` (collective
  resolve on a pod), printing `ELASTIC_RESUMED <pid> mode=<resume_mode>
  step=<restored step> epoch=<epoch> digest=<md5-of-params>`; then
  trains the remaining epoch budget and prints `ELASTIC_LOSSES`.
  The parent asserts digest(resumed on M) == digest(saved on N) —
  the restored GLOBAL parameter tree is bit-equal across topologies —
  and that the loss trajectory continues the reference run's.

- `preempt <kill_batch> [load]` — single-process only: trains until the
  wrapped train step SIGTERMs the process at batch `kill_batch` (counted
  from this run's start); the preemption path writes `_iter<E>_preempt`
  with the data cursor (manifest v3), and the run exits cleanly.
  Resuming it (same or other topology) must continue the epoch mid-pass
  via the cursor. With `load`, the run first RESUMES from `save_base` —
  the preempt-again-while-resumed drill, whose recorded cursor must
  accumulate the restored skip plus the newly consumed rows.
"""

import hashlib
import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # covered by the XLA_FLAGS fallback above

import numpy as np  # noqa: E402

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from code2vec_tpu.parallel import distributed  # noqa: E402

# Short commit-barrier timeout: a dead peer must fail a pod save in
# seconds, inside the parent's subprocess timeout.
BARRIER_TIMEOUT_S = 8.0


def params_digest(params) -> str:
    h = hashlib.md5()
    for name in sorted(params):
        h.update(name.encode())
        h.update(np.asarray(jax.device_get(params[name])).tobytes())
    return h.hexdigest()


def build_config(data_prefix: str, save_base: str, dp: int, tp: int,
                 epochs: int, load: bool):
    from code2vec_tpu.config import Config
    return Config(
        train_data_path_prefix=data_prefix,
        model_save_path=save_base,
        model_load_path=save_base if load else None,
        max_contexts=8,
        train_batch_size=8, test_batch_size=8,
        num_train_epochs=epochs,
        save_every_epochs=1,
        num_batches_to_log_progress=10 ** 6,
        compute_dtype="float32",
        dropout_keep_rate=1.0,   # determinism: trajectories comparable
        use_packed_data=True,
        dp=dp, tp=tp, cp=1,
        save_barrier_timeout_s=BARRIER_TIMEOUT_S,
        seed=7,
        verbose_mode=0,
    )


def init_pod(pid: int, nprocs: int, port: str) -> None:
    if nprocs > 1:
        # gloo collectives need the distributed client; the config must
        # land before the (lazy) CPU backend initializes, and must NOT
        # be set for single-process children (no client to hand gloo).
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nprocs, process_id=pid)
        assert jax.process_count() == nprocs


def install_save_recorder(pid: int) -> None:
    """Print a params digest immediately before every checkpoint save:
    the parent's oracle for what each committed artifact must restore
    to, bit-equal, on any later topology."""
    from code2vec_tpu.training import checkpoint as ckpt_mod
    orig_save = ckpt_mod.save_model

    def recording_save(path, state, vocabs, config, **kw):
        print(f"ELASTIC_SAVED {pid} {kw.get('epoch', 0)} "
              f"digest={params_digest(state.params)}", flush=True)
        return orig_save(path, state, vocabs, config, **kw)

    ckpt_mod.save_model = recording_save


def install_loss_recorder(model, losses, on_step=None):
    orig_make = model.builder.make_train_step

    def make_recording(state):
        step = orig_make(state)

        def wrapped(s, *a):
            s2, loss = step(s, *a)
            losses.append(float(loss))
            if on_step is not None:
                on_step(len(losses))
            return s2, loss

        return wrapped

    model.builder.make_train_step = make_recording


def cmd_train(pid, nprocs, port, data_prefix, save_base, dp, tp, epochs,
              fault_spec):
    from code2vec_tpu.model_facade import Code2VecModel
    from code2vec_tpu.utils import faults

    init_pod(pid, nprocs, port)
    install_save_recorder(pid)
    if fault_spec:
        faults.reset(fault_spec)
    model = Code2VecModel(build_config(data_prefix, save_base, dp, tp,
                                       epochs, load=False))
    losses = []
    install_loss_recorder(model, losses)
    model.train()
    print(f"ELASTIC_LOSSES {pid} {json.dumps(losses)}", flush=True)
    print(f"ELASTIC_DONE {pid}", flush=True)


def cmd_resume(pid, nprocs, port, data_prefix, save_base, dp, tp, epochs):
    from code2vec_tpu.model_facade import Code2VecModel

    init_pod(pid, nprocs, port)
    install_save_recorder(pid)
    model = Code2VecModel(build_config(data_prefix, save_base, dp, tp,
                                       epochs, load=True))
    report = model.resume_report
    print(f"ELASTIC_RESUMED {pid} mode={report['resume_mode']} "
          f"step={report['restored_step']} epoch={model.initial_epoch} "
          f"digest={params_digest(model.state.params)}", flush=True)
    losses = []
    install_loss_recorder(model, losses)
    model.train()
    print(f"ELASTIC_LOSSES {pid} {json.dumps(losses)}", flush=True)
    print(f"ELASTIC_DONE {pid}", flush=True)


def cmd_preempt(pid, nprocs, port, data_prefix, save_base, dp, tp, epochs,
                kill_batch, load=False):
    import signal

    from code2vec_tpu.model_facade import Code2VecModel

    assert nprocs == 1, "preempt drill is single-process"
    install_save_recorder(pid)
    model = Code2VecModel(build_config(data_prefix, save_base, dp, tp,
                                       epochs, load=load))
    losses = []

    def sigterm_at(step_count):
        if step_count == kill_batch:
            os.kill(os.getpid(), signal.SIGTERM)

    install_loss_recorder(model, losses, on_step=sigterm_at)
    model.train()
    print(f"ELASTIC_PREEMPTED {pid} after={len(losses)}", flush=True)
    print(f"ELASTIC_LOSSES {pid} {json.dumps(losses)}", flush=True)
    print(f"ELASTIC_DONE {pid}", flush=True)


def main() -> None:
    cmd = sys.argv[1]
    pid, nprocs, port = int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
    data_prefix, save_base = sys.argv[5], sys.argv[6]
    dp, tp, epochs = int(sys.argv[7]), int(sys.argv[8]), int(sys.argv[9])
    if cmd == "train":
        cmd_train(pid, nprocs, port, data_prefix, save_base, dp, tp, epochs,
                  sys.argv[10] if len(sys.argv) > 10 else "")
    elif cmd == "resume":
        cmd_resume(pid, nprocs, port, data_prefix, save_base, dp, tp, epochs)
    elif cmd == "preempt":
        cmd_preempt(pid, nprocs, port, data_prefix, save_base, dp, tp,
                    epochs, int(sys.argv[10]),
                    load=(len(sys.argv) > 11 and sys.argv[11] == "load"))
    else:
        raise SystemExit(f"unknown chaos_elastic_child command: {cmd!r}")


if __name__ == "__main__":
    main()
