"""Golden-output regression tests for the native extractors.

The unit tests (test_extractor.py / test_cs_extractor.py) pin individual
grammar rules; these pin the COMPLETE byte-level output of both
extractors over committed fixture sources, so any grammar or
normalization change — intended or not — shows up as a reviewable diff
of `tests/goldens/*.c2v`.

Fixtures:
- `Input.java` (repo root) — the REPL quickstart fixture;
- `tests/goldens/src/*.java` — two javagen-generated classes (committed
  as static sources; regenerating javagen does not move them);
- `tests/goldens/src/Golden.cs` — hand-written C# exercising variable
  pairing, loops, lambdas, nested types.

To intentionally re-bless after a deliberate extractor change:
    C2V_REGEN_GOLDENS=1 python -m pytest tests/test_goldens.py
then review and commit the diff.
"""

import os
import subprocess

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GOLDEN_DIR = os.path.join(REPO_ROOT, "tests", "goldens")
SRC_DIR = os.path.join(GOLDEN_DIR, "src")
JAVA_BIN = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract")
CS_BIN = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract-cs")

CASES = [
    # (golden file, binary, source path, extra flags)
    ("Input.java.c2v", JAVA_BIN, os.path.join(REPO_ROOT, "Input.java"), ()),
    ("PriceService.java.c2v", JAVA_BIN,
     os.path.join(SRC_DIR, "PriceService.java"), ()),
    ("UserStore.java.c2v", JAVA_BIN,
     os.path.join(SRC_DIR, "UserStore.java"), ()),
    # no_hash keeps one Java golden human-readable (paths as node strings)
    ("Input.java.nohash.c2v", JAVA_BIN,
     os.path.join(REPO_ROOT, "Input.java"), ("--no_hash",)),
    ("Golden.cs.c2v", CS_BIN, os.path.join(SRC_DIR, "Golden.cs"), ()),
    ("Golden.cs.nohash.c2v", CS_BIN,
     os.path.join(SRC_DIR, "Golden.cs"), ("--no_hash",)),
]


def _ensure_built():
    if not (os.path.exists(JAVA_BIN) and os.path.exists(CS_BIN)):
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr


def _extract(binary, source, extra):
    if binary is CS_BIN:
        # mirrors the reference CSharpExtractor CLI (--path, --max_length)
        cmd = [binary, "--path", source, "--max_length", "8",
               "--max_width", "2", *extra]
    else:
        cmd = [binary, "--max_path_length", "8", "--max_path_width", "2",
               "--file", source, *extra]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    return proc.stdout


@pytest.mark.parametrize("golden_name,binary,source,extra", CASES,
                         ids=[c[0] for c in CASES])
def test_extractor_matches_golden(golden_name, binary, source, extra):
    _ensure_built()
    got = _extract(binary, source, extra)
    golden_path = os.path.join(GOLDEN_DIR, golden_name)
    if os.environ.get("C2V_REGEN_GOLDENS"):
        with open(golden_path, "w") as f:
            f.write(got)
    assert os.path.exists(golden_path), (
        f"{golden_name} missing; run with C2V_REGEN_GOLDENS=1 to bless")
    with open(golden_path) as f:
        want = f.read()
    assert got == want, (
        f"extractor output for {os.path.basename(source)} diverged from "
        f"{golden_name}; if the change is deliberate, re-bless with "
        f"C2V_REGEN_GOLDENS=1 and commit the diff")
    # non-triviality guard: a silently empty extraction must not pass
    assert want.strip(), f"golden {golden_name} is empty"
