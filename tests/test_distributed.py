"""Single-process semantics of the multi-host helpers
(parallel/distributed.py); true multi-host needs a pod, but the
single-process path must be exactly equivalent to plain device_put."""

import jax
import numpy as np
import pytest

from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.parallel import distributed
from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh


def _batch(b, m):
    rng = np.random.default_rng(0)
    return RowBatch(
        source_token_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        path_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        target_token_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        context_valid_mask=np.ones((b, m), np.float32),
        target_index=rng.integers(0, 9, (b,)).astype(np.int32),
        example_valid=np.ones((b,), bool))


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    distributed.initialize()  # must not raise or try to connect
    assert distributed.host_shard() == (0, 1)


def test_local_batch_size():
    assert distributed.local_batch_size(1024) == 1024
    with pytest.raises(ValueError):
        # fake a 3-host world
        orig = jax.process_count
        jax.process_count = lambda: 3
        try:
            distributed.local_batch_size(1024)
        finally:
            jax.process_count = orig


def test_global_batch_arrays_matches_device_put():
    from code2vec_tpu.training.step import device_put_batch
    mesh = make_mesh(MeshPlan(dp=2, tp=2, cp=2))
    batch = _batch(4, 4)
    via_helper = distributed.global_batch_arrays(batch, mesh)
    via_put = device_put_batch(batch, mesh)
    for a, b in zip(via_helper, via_put):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding


def test_gather_host_array_exact_above_2pow24():
    # Values above 2**24 are not f32-representable; the byte-exact gather
    # must keep them exact even with jax_enable_x64 off.
    big = np.array([2.0**24 + 1, 2.0**53 - 1, 3.5])
    out = distributed.gather_host_array(big)
    assert out.shape == (1, 3)
    np.testing.assert_array_equal(out[0], big)
    np.testing.assert_array_equal(distributed.allreduce_host_scalars(big), big)


def test_agree_scalar_and_assert_single_process():
    assert distributed.agree_scalar(17, "min") == 17
    assert distributed.agree_scalar(17, "max") == 17
    distributed.assert_host_agreement(42, "anything")  # never raises solo


def test_lockstep_train_stream_truncates_each_epoch():
    from code2vec_tpu.data.reader import EpochEnd

    def stream():
        for epoch in (1, 2):
            for i in range(7 if epoch == 1 else 6):
                yield ("batch", epoch, i)
            yield EpochEnd(epoch)

    items = list(distributed.lockstep_train_stream(stream(), 5))
    batches = [x for x in items if not isinstance(x, EpochEnd)]
    markers = [x for x in items if isinstance(x, EpochEnd)]
    assert len(batches) == 10 and [m.epoch for m in markers] == [1, 2]
    # truncation keeps the FIRST agreed-many batches of each epoch
    assert batches[:5] == [("batch", 1, i) for i in range(5)]
    assert batches[5:] == [("batch", 2, i) for i in range(5)]


def test_lockstep_train_stream_short_epoch_raises():
    from code2vec_tpu.data.reader import EpochEnd

    def stream():
        yield "b0"
        yield EpochEnd(1)

    with pytest.raises(RuntimeError, match="only 1 local batches"):
        list(distributed.lockstep_train_stream(stream(), 3))


def test_lockstep_eval_stream_pads_with_invalid_batches():
    from code2vec_tpu.data.reader import invalid_batch
    real = [_batch(4, 3), _batch(4, 3)]
    out = list(distributed.lockstep_eval_stream(
        iter(real), 5, lambda: invalid_batch(4, 3)))
    assert len(out) == 5
    assert out[0] is real[0] and out[1] is real[1]
    for pad in out[2:]:
        assert not pad.example_valid.any()
        assert pad.context_valid_mask.sum() == 0
        assert pad.target_strings == [""] * 4
    # already-long-enough stream is passed through untouched
    assert list(distributed.lockstep_eval_stream(
        iter(real), 2, lambda: 1 / 0)) == real
