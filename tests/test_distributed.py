"""Single-process semantics of the multi-host helpers
(parallel/distributed.py); true multi-host needs a pod, but the
single-process path must be exactly equivalent to plain device_put."""

import jax
import numpy as np
import pytest

from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.parallel import distributed
from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh


def _batch(b, m):
    rng = np.random.default_rng(0)
    return RowBatch(
        source_token_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        path_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        target_token_indices=rng.integers(0, 9, (b, m)).astype(np.int32),
        context_valid_mask=np.ones((b, m), np.float32),
        target_index=rng.integers(0, 9, (b,)).astype(np.int32),
        example_valid=np.ones((b,), bool))


def test_initialize_noop_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("TPU_WORKER_HOSTNAMES", raising=False)
    distributed.initialize()  # must not raise or try to connect
    assert distributed.host_shard() == (0, 1)


def test_local_batch_size():
    assert distributed.local_batch_size(1024) == 1024
    with pytest.raises(ValueError):
        # fake a 3-host world
        orig = jax.process_count
        jax.process_count = lambda: 3
        try:
            distributed.local_batch_size(1024)
        finally:
            jax.process_count = orig


def test_global_batch_arrays_matches_device_put():
    from code2vec_tpu.training.step import device_put_batch
    mesh = make_mesh(MeshPlan(dp=2, tp=2, cp=2))
    batch = _batch(4, 4)
    via_helper = distributed.global_batch_arrays(batch, mesh)
    via_put = device_put_batch(batch, mesh)
    for a, b in zip(via_helper, via_put):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert a.sharding == b.sharding
