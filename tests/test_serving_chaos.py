"""Serving resilience chaos suite: admission control + deadlines,
circuit breakers, health-gated hot-swap, and supervised multi-replica
serving under injected faults and real SIGKILLs.

The contract under test, end to end: a serving stack under overload or
partial failure must degrade GRACEFULLY and HONESTLY — excess load is
shed as 503 + Retry-After (never queued unboundedly), an expired
request is a 504 that never occupies a device slot, a dead dependency
fails fast behind a breaker while cache hits keep serving, a bad model
swap leaves the old model serving with the failure visible, a
SIGKILLed replica yields zero malformed responses and the supervisor
converges back to N live replicas. Fast in-process tests run in tier-1;
the multi-process supervisor drills are marked `slow` and run via
scripts/run_chaos.sh with their own timeout budget.

Builds on the PR-7 scripted fake extractor (test_serving.FAKE_EXTRACTOR)
plus a FakeModel so failures are injectable at every pipeline stage,
and on the `admission_enqueue` / `swap_validate` / `replica_heartbeat`
fault points (utils/faults.py).
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from code2vec_tpu import obs
from code2vec_tpu.utils import faults

from test_serving import FAKE_EXTRACTOR, _counter_value, _serving_config

pytestmark = [pytest.mark.serving, pytest.mark.serving_chaos]

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "chaos_serving_child.py")


@pytest.fixture()
def fake_extractor(tmp_path, monkeypatch):
    path = tmp_path / "fake-c2v-extract"
    path.write_text(FAKE_EXTRACTOR)
    path.chmod(0o755)
    monkeypatch.setenv("C2V_NATIVE_EXTRACTOR", str(path))
    monkeypatch.delenv("C2V_FAKE_NO_SERVER", raising=False)
    return str(path)


# --------------------------------------------------------- fake model


class _FakeResult:
    def __init__(self, name, contexts, topk, vec_size, finite):
        self.original_name = name
        self.topk_predicted_words = [f"predicted|w{i}"
                                     for i in range(topk)]
        self.topk_predicted_words_scores = [
            (0.5 / (i + 1)) if finite else float("nan")
            for i in range(topk)]
        self.attention_per_context = {}
        for i, ctx in enumerate(contexts):
            bits = ctx.split(",")
            if len(bits) == 3:
                self.attention_per_context[tuple(bits)] = 1.0 / (i + 1)
        self.code_vector = [0.25] * vec_size


class FakeModel:
    """The surface PredictionServer + SwapManager need, with every
    failure mode injectable: `fail_with` poisons the device step,
    `predict_delay_s` wedges it, `scores_finite=False` and a mismatched
    `topk`/`vec_size` make a swap candidate fail validation."""

    def __init__(self, config, fingerprint="fpA", topk=3, vec_size=8,
                 predict_delay_s=0.0, scores_finite=True):
        self.config = config
        self._fp = fingerprint
        self.topk = topk
        self.vec_size = vec_size
        self.predict_delay_s = predict_delay_s
        self.scores_finite = scores_finite
        self.fail_with = None
        self.context_buckets = (4, 8, config.max_contexts)
        self._predict_steps = {}

        class _SpecialWords:
            oov = "<OOV>"

        class _TargetVocab:
            special_words = _SpecialWords()

        class _Vocabs:
            target_vocab = _TargetVocab()

        self.vocabs = _Vocabs()

    def model_fingerprint(self):
        return self._fp

    def predict_compile_count(self):
        return 0

    def predict(self, lines, batch_size=None, with_code_vectors=False):
        if self.fail_with is not None:
            raise self.fail_with
        if self.predict_delay_s:
            time.sleep(self.predict_delay_s)
        out = []
        for line in lines:
            parts = line.split()
            out.append(_FakeResult(parts[0], parts[1:], self.topk,
                                   self.vec_size, self.scores_finite))
        return out

    def smoke_schema(self):
        import math
        [r] = self.predict(["swapsmoke a,b,c"], batch_size=1,
                           with_code_vectors=True)
        return {"topk": len(r.topk_predicted_words),
                "code_vector_size": len(r.code_vector),
                "scores_finite": all(
                    math.isfinite(s)
                    for s in r.topk_predicted_words_scores)}


def _chaos_config(tmp_path, **overrides):
    kwargs = dict(
        serve_breaker_min_requests=2,
        serve_breaker_cooldown_s=0.4,
        serve_breaker_window_s=30.0,
        extractor_retries=0,
        serve_deadline_ms=0.0,  # tests opt into deadlines explicitly
    )
    kwargs.update(overrides)
    return _serving_config(tmp_path, **kwargs)


@pytest.fixture()
def chaos_server(tmp_path, fake_extractor):
    """Factory: PredictionServer on a FakeModel + real warm fake-extractor
    pool, drained at teardown."""
    from code2vec_tpu.serving.server import PredictionServer

    made = []

    def make(**overrides):
        config = _chaos_config(tmp_path, **overrides)
        model = FakeModel(config)
        srv = PredictionServer(model, config, log=lambda m: None)
        srv.start(port=0)
        made.append(srv)
        return srv, model

    yield make
    for srv in made:
        srv.drain(timeout=10)


def _post(port, endpoint, body, headers=None):
    hdrs = {"Content-Type": "text/plain"}
    hdrs.update(headers or {})
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}", data=body.encode(),
        method="POST", headers=hdrs)
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def _hist_count(name, **labels):
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    child = obs.default_registry().collect().get(name, {}).get(key)
    return child.count if child is not None else 0


# ----------------------------------------------- admission + deadlines


def test_overload_sheds_queue_full_503_with_retry_after(
        chaos_server, monkeypatch):
    """serve_queue_depth=1 + one slow in-flight request: the next
    cache-miss request is SHED — an honest 503 + Retry-After + counted
    shed reason, not an unbounded queue entry."""
    monkeypatch.setenv("C2V_FAKE_SLEEP", "1.5")
    srv, _ = chaos_server(serve_queue_depth=1)
    shed0 = _counter_value("serving_requests_shed_total",
                           reason="queue_full")
    slow_result = {}

    def slow_post():
        slow_result["r"] = _post(
            srv.port, "predict",
            "class S { int slowOne() { return 1; } } SLOW_MARKER")

    t = threading.Thread(target=slow_post)
    t.start()
    deadline = time.time() + 5
    while srv.admission.depth == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert srv.admission.depth == 1
    t0 = time.perf_counter()
    status, body, headers = _post(
        srv.port, "predict", "class Q { int quick() { return 2; } }")
    shed_latency = time.perf_counter() - t0
    assert status == 503
    payload = json.loads(body)
    assert payload["shed"] == "queue_full"
    assert int(headers["Retry-After"]) >= 1
    # shed BEFORE any pipeline work: immediate, not behind the slow one
    assert shed_latency < 0.5
    assert _counter_value("serving_requests_shed_total",
                          reason="queue_full") == shed0 + 1
    # the slow request itself still finishes fine
    t.join(timeout=30)
    assert slow_result["r"][0] == 200
    # satellite: the 503 is IN the total-latency histogram (status label)
    assert _hist_count("serving_request_seconds",
                       phase="total", status="503") >= 1


def test_deadline_expiry_is_504_and_never_blocks_past_budget(
        chaos_server, monkeypatch):
    """X-Deadline-Ms propagates into the extractor as the per-request
    timeout: a 200ms-deadline request against a 2s-hang extractor gets
    its 504 in well under the hang time."""
    monkeypatch.setenv("C2V_FAKE_SLEEP", "2.0")
    srv, _ = chaos_server()
    exp0 = _counter_value("serving_requests_expired_total",
                          stage="extract")
    t0 = time.perf_counter()
    status, body, _ = _post(
        srv.port, "predict",
        "class D { int deadlined() { return 3; } } SLOW_MARKER",
        headers={"X-Deadline-Ms": "200"})
    elapsed = time.perf_counter() - t0
    assert status == 504
    assert "deadline" in json.loads(body)["error"]
    assert elapsed < 1.5, f"blocked {elapsed:.2f}s past a 200ms deadline"
    assert _counter_value("serving_requests_expired_total",
                          stage="extract") == exp0 + 1
    assert _hist_count("serving_request_seconds",
                       phase="total", status="504") >= 1


def test_admission_estimated_wait_sheds_doomed_requests():
    """Once the EWMA knows a request costs ~0.5s, a request with a
    100ms budget behind a queued pipeline is refused up front."""
    from code2vec_tpu.serving.admission import (
        AdmissionController, Deadline, Shed,
    )
    gate = AdmissionController(max_depth=8, concurrency=1)
    gate.admit()
    gate.finish(0.5)  # seed the EWMA
    gate.admit()      # one request in flight
    with pytest.raises(Shed) as exc:
        gate.admit(Deadline(0.1))
    assert exc.value.reason == "deadline"
    # an unbounded-deadline request is still admitted
    gate.admit(Deadline(0.0))
    gate.finish(0.5)
    gate.finish(0.5)


def test_batcher_refuses_infeasible_deadline_and_expires_waiters():
    """The batcher's two deadline duties: refuse a request whose budget
    cannot cover its bucket's observed p95 device time (503 shed, no
    device slot), and settle a request that expires while coalescing as
    504 before dispatch."""
    from code2vec_tpu.serving.admission import (
        Deadline, DeadlineExceeded, DeadlineInfeasible,
    )
    from code2vec_tpu.serving.batcher import DynamicBatcher

    batcher = DynamicBatcher(lambda lines: [l for l in lines],
                             max_batch_rows=64, max_delay_s=5.0)
    try:
        # seed the p95 estimate: 0.5s device calls
        for _ in range(4):
            batcher.device_times.record(None, 0.5)
        f = batcher.submit(["line a,b,c"], deadline=Deadline(0.1))
        with pytest.raises(DeadlineInfeasible):
            f.result(timeout=5)
        # feasible budget but a 5s coalescing window: the deadline
        # forces early dispatch instead of a 504 (slack-aware collect)
        t0 = time.perf_counter()
        f2 = batcher.submit(["line a,b,c"], deadline=Deadline(1.0))
        assert f2.result(timeout=5) == ["line a,b,c"]
        assert time.perf_counter() - t0 < 2.0
    finally:
        batcher.drain()
    # expiry while waiting for batch-mates -> 504 without dispatch
    batcher2 = DynamicBatcher(lambda lines: [l for l in lines],
                              max_batch_rows=64, max_delay_s=10.0)
    try:
        t0 = time.perf_counter()
        f3 = batcher2.submit(["line a,b,c"], deadline=Deadline(0.05))
        with pytest.raises(DeadlineExceeded):
            f3.result(timeout=5)
        assert time.perf_counter() - t0 < 2.0
        assert batcher2.batches_dispatched == 0
    finally:
        batcher2.drain()


def test_admission_fault_point_surfaces_as_honest_error(chaos_server):
    """An armed fault in the admission layer itself must surface as a
    well-formed JSON error response — never a hang or a torn body."""
    srv, _ = chaos_server()
    faults.reset("admission_enqueue=raise")
    try:
        status, body, _ = _post(
            srv.port, "predict",
            "class F { int faulty() { return 4; } }")
    finally:
        faults.reset(None)
    assert status == 500
    assert "FaultInjected" in json.loads(body)["error"]


# ------------------------------------------------- flight recorder


def test_breaker_open_under_load_dumps_shed_trace_ids(chaos_server,
                                                      tmp_path):
    """Acceptance pin: kicking a breaker open under load produces a
    flight-recorder dump containing the shed requests' trace ids — the
    incident dump is DELAYED so the black box captures both the
    failures that opened the breaker and the shed storm it caused."""
    flight_dir = tmp_path / "flight"
    srv, _ = chaos_server(serve_flight_dir=str(flight_dir),
                          serve_cache_entries=0,
                          serve_breaker_cooldown_s=10.0)
    srv.flight.configure(dump_delay_s=0.6)
    # extractor crash storm (retries=0, min_requests=2) opens the breaker
    for i in range(2):
        status, _, _ = _post(
            srv.port, "predict",
            f"class C{i} {{ int crash{i}() {{ return 1; }} }} "
            f"CRASH_ALWAYS")
        assert status == 503
    assert srv.extractor_breaker.state == "open"
    # load against the open breaker: fail-fast sheds, each with its id
    shed_ids = []
    for i in range(3):
        status, body, headers = _post(
            srv.port, "predict",
            f"class S{i} {{ int shed{i}() {{ return 1; }} }}")
        assert status == 503
        payload = json.loads(body)
        assert payload["shed"] == "breaker"
        assert payload["trace_id"] == headers["X-Trace-Id"]
        shed_ids.append(headers["X-Trace-Id"])
    deadline = time.time() + 10
    files = []
    while time.time() < deadline:
        files = sorted(flight_dir.glob("flight-*.json"))
        if files:
            break
        time.sleep(0.05)
    assert files, "a breaker open must produce a flight dump"
    doc = json.loads(files[0].read_text())
    assert doc["reason"] == "breaker_open"
    recorded = {r["trace_id"]: r for r in doc["requests"]}
    for tid in shed_ids:
        assert tid in recorded, "shed request missing from the dump"
        assert recorded[tid]["status"] == 503
        assert recorded[tid]["reason"] == "breaker"
        assert recorded[tid]["endpoint"] == "predict"
    assert any(e["kind"] == "breaker_open" and e.get("incident")
               and e["breaker"] == "extractor" for e in doc["events"])


def test_admin_dump_endpoint_writes_flight_file(chaos_server, tmp_path):
    flight_dir = tmp_path / "dumps"
    srv, _ = chaos_server(serve_flight_dir=str(flight_dir))
    status, _, headers = _post(
        srv.port, "predict", "class D { int dumped() { return 1; } }")
    assert status == 200
    wanted = headers["X-Trace-Id"]
    status, body, _ = _post(srv.port, "admin/dump", "")
    assert status == 200
    payload = json.loads(body)
    assert os.path.dirname(payload["path"]) == str(flight_dir)
    doc = json.loads(open(payload["path"]).read())
    assert doc["reason"] == "admin"
    assert payload["requests"] == len(doc["requests"]) >= 1
    assert wanted in {r["trace_id"] for r in doc["requests"]}


def test_drain_timeout_incident_dumps_synchronously(
        chaos_server, tmp_path, monkeypatch):
    """A drain timeout is an exit-path incident: the dump must land
    BEFORE the process would exit (no delayed timer), with the
    abandoned request still in the ring."""
    monkeypatch.setenv("C2V_FAKE_SLEEP", "2.0")
    flight_dir = tmp_path / "drainflight"
    srv, _ = chaos_server(serve_flight_dir=str(flight_dir))
    result = {}

    def slow_post():
        result["r"] = _post(
            srv.port, "predict",
            "class A { int abandoned() { return 1; } } SLOW_MARKER")

    t = threading.Thread(target=slow_post)
    t.start()
    deadline = time.time() + 5
    while srv._inflight == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert srv.drain(timeout=0.2) is False
    files = list(flight_dir.glob("flight-*drain_timeout.json"))
    assert len(files) == 1, "exit-path incidents dump synchronously"
    doc = json.loads(files[0].read_text())
    assert any(e["kind"] == "drain_timeout" and e["abandoned"] == 1
               for e in doc["events"])
    t.join(timeout=30)


# ------------------------------------------------------------ breakers


def test_extractor_crash_storm_opens_breaker_cache_still_serves(
        chaos_server, tmp_path):
    """The acceptance scenario: an extractor crash storm opens the
    breaker (fail-fast 503s, no extractor work), cache hits still serve
    (graceful degradation), and the half-open probe closes it again."""
    srv, _ = chaos_server()
    good = "class G { int golden() { return 1; } }"
    status, cached_body, _ = _post(srv.port, "predict", good)
    assert status == 200

    for i in range(2):
        status, _, _ = _post(
            srv.port, "predict",
            f"class C{i} {{ int crash{i}() {{ return 1; }} }} "
            f"CRASH_ALWAYS")
        assert status == 503
    assert srv.extractor_breaker.state == "open"

    # open breaker: a NEW request fails fast without touching the pool
    reqs0 = _counter_value("extractor_pool_requests_total")
    shed0 = _counter_value("serving_requests_shed_total",
                           reason="breaker")
    status, body, headers = _post(
        srv.port, "predict", "class N { int nope() { return 2; } }")
    assert status == 503
    assert json.loads(body)["shed"] == "breaker"
    assert "Retry-After" in headers
    assert _counter_value("extractor_pool_requests_total") == reqs0
    assert _counter_value("serving_requests_shed_total",
                          reason="breaker") == shed0 + 1

    # ... but the cache hit path is untouched: byte-equal 200
    status, body, _ = _post(srv.port, "predict", good)
    assert status == 200
    assert body == cached_body

    # half-open after the cooldown: one good probe closes the breaker
    time.sleep(srv.config.serve_breaker_cooldown_s + 0.1)
    assert srv.extractor_breaker.state == "half_open"
    status, _, _ = _post(srv.port, "predict",
                         "class R { int recovered() { return 3; } }")
    assert status == 200
    assert srv.extractor_breaker.state == "closed"
    assert _counter_value("serving_breaker_transitions_total",
                          breaker="extractor", to="open") >= 1
    assert _counter_value("serving_breaker_transitions_total",
                          breaker="extractor", to="closed") >= 1


def test_device_failure_storm_opens_device_breaker(chaos_server):
    srv, model = chaos_server()
    model.fail_with = RuntimeError("device wedged")
    for i in range(2):
        status, _, _ = _post(
            srv.port, "predict",
            f"class D{i} {{ int dev{i}() {{ return 1; }} }}")
        assert status == 500
    assert srv.device_breaker.state == "open"
    status, body, _ = _post(
        srv.port, "predict", "class D9 { int dev9() { return 1; } }")
    assert status == 503
    assert json.loads(body)["shed"] == "breaker"
    # recovery: dependency healthy again, half-open probe closes it
    model.fail_with = None
    time.sleep(srv.config.serve_breaker_cooldown_s + 0.1)
    status, _, _ = _post(
        srv.port, "predict", "class D8 { int dev8() { return 1; } }")
    assert status == 200
    assert srv.device_breaker.state == "closed"


def test_aborted_half_open_probe_rearms_instead_of_wedging():
    """Regression: a half-open probe that ends without a dependency
    verdict (the REQUEST's deadline expired mid-call) must re-arm the
    probe slot — not leave _probe_inflight stuck so the breaker sheds
    forever after the dependency recovered."""
    from code2vec_tpu.serving.breaker import CircuitBreaker

    t = [0.0]
    b = CircuitBreaker("x", window_s=10, failure_ratio=0.5,
                       min_requests=2, cooldown_s=5,
                       clock=lambda: t[0])
    for _ in range(2):
        assert b.allow()
        b.record(ok=False)
    assert b.state == "open"
    t[0] = 5.1
    assert b.allow()        # the half-open probe slot
    b.abort()               # probe ended with no verdict
    assert b.allow()        # slot re-armed: next request probes again
    b.record(ok=True)
    assert b.state == "closed"
    b.abort()               # no-op outside half-open
    assert b.state == "closed" and b.allow()


def test_client_parse_errors_do_not_open_the_breaker(chaos_server):
    """A storm of bad client input (deterministic 422 rejections) is a
    HEALTHY extractor answering; it must never open the breaker and
    shed good clients."""
    srv, _ = chaos_server()
    for _ in range(4):
        status, _, _ = _post(srv.port, "predict", "BOOM_ALWAYS")
        assert status == 422
    assert srv.extractor_breaker.state == "closed"
    status, _, _ = _post(srv.port, "predict",
                         "class K { int keeps() { return 1; } }")
    assert status == 200


# ------------------------------------------------------------ hot swap


def _wait_swap_state(srv, states, timeout=10.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        state = srv.swap.status()["state"]
        if state in states:
            return state
        time.sleep(0.02)
    raise AssertionError(
        f"swap never reached {states}; status={srv.swap.status()}")


def test_hot_swap_under_live_traffic_single_fingerprint_responses(
        chaos_server):
    """Every response during a live swap is attributable to exactly ONE
    model fingerprint (old or new, never a mix), and traffic after the
    swap serves the new weights."""
    from code2vec_tpu.serving.swap import SwapManager

    srv, model_a = chaos_server(serve_cache_entries=0)

    def build_b(artifact_dir):
        assert artifact_dir == "artifact-b"
        time.sleep(0.3)  # overlap the load: old model keeps serving
        return FakeModel(srv.config, fingerprint="fpB")

    srv.swap = SwapManager(srv, build_model=build_b)
    seen = []
    stop_load = threading.Event()

    def load(ci):
        i = 0
        while not stop_load.is_set():
            status, body, _ = _post(
                srv.port, "predict",
                f"class L{ci}x{i} {{ int m{ci}x{i}() {{ return 1; }} }}")
            assert status == 200
            seen.append(json.loads(body)["model_fingerprint"])
            i += 1

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)
        status, body, _ = _post(srv.port, "admin/reload",
                                json.dumps({"artifact": "artifact-b"}),
                                headers={"Content-Type":
                                         "application/json"})
        assert status == 202
        assert _wait_swap_state(srv, {"ready"}) == "ready"
        time.sleep(0.2)  # post-swap traffic
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
    assert set(seen) <= {"fpA", "fpB"}, f"mixed fingerprints: {set(seen)}"
    assert seen[-1] == "fpB" and "fpB" in seen
    status, body, _ = _post(srv.port, "predict",
                            "class Z { int after() { return 9; } }")
    assert json.loads(body)["model_fingerprint"] == "fpB"
    hz = json.loads(_get(srv.port, "/healthz")[1])
    assert hz["model"]["fingerprint"] == "fpB"
    assert hz["model"]["swap_status"]["state"] == "ready"
    assert hz["model"]["swap_status"]["swapped_fingerprint"] == "fpB"


def test_hot_swap_under_continuous_batching_single_fingerprint(
        chaos_server):
    """The continuous dispatcher (--serve_continuous) under a live
    hot-swap: every response still carries exactly ONE model
    fingerprint (old or new, never a mix) and none is malformed.
    FakeModel lacks the zero-copy slot surface, so the backend's
    supports_rows guard degrades every slot to the lines path — the
    slot/chaining machinery is exercised end to end and the
    one-fingerprint law must hold either way."""
    from code2vec_tpu.serving.batcher import ContinuousBatcher
    from code2vec_tpu.serving.swap import SwapManager

    srv, _ = chaos_server(serve_cache_entries=0, serve_continuous=True,
                          serve_inflight_steps=2)
    assert isinstance(srv.batcher, ContinuousBatcher)

    def build_b(artifact_dir):
        assert artifact_dir == "artifact-b"
        time.sleep(0.3)  # overlap the load: old model keeps serving
        return FakeModel(srv.config, fingerprint="fpB")

    srv.swap = SwapManager(srv, build_model=build_b)
    seen, malformed = [], []
    stop_load = threading.Event()

    def load(ci):
        i = 0
        while not stop_load.is_set():
            status, body, _ = _post(
                srv.port, "predict",
                f"class C{ci}x{i} {{ int m{ci}x{i}() {{ return 1; }} }}")
            assert status == 200
            try:
                seen.append(json.loads(body)["model_fingerprint"])
            except Exception:
                malformed.append(body)
            i += 1

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.1)
        status, _, _ = _post(srv.port, "admin/reload",
                             json.dumps({"artifact": "artifact-b"}),
                             headers={"Content-Type":
                                      "application/json"})
        assert status == 202
        assert _wait_swap_state(srv, {"ready"}) == "ready"
        time.sleep(0.2)  # post-swap traffic
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
    assert not malformed, malformed[:3]
    assert set(seen) <= {"fpA", "fpB"}, f"mixed fingerprints: {set(seen)}"
    assert seen[-1] == "fpB" and "fpB" in seen


def test_swap_validation_failure_leaves_old_model_serving(chaos_server):
    """A candidate with a mismatched output schema (narrower top-k) is
    REJECTED: swap status failed + visible in /healthz, old fingerprint
    keeps serving, failure counted."""
    from code2vec_tpu.serving.swap import SwapManager

    srv, _ = chaos_server()
    failed0 = _counter_value("serving_swap_total", outcome="failed")
    srv.swap = SwapManager(
        srv, build_model=lambda d: FakeModel(srv.config,
                                             fingerprint="fpBad",
                                             topk=5))
    status, _, _ = _post(srv.port, "admin/reload",
                         json.dumps({"artifact": "bad"}),
                         headers={"Content-Type": "application/json"})
    assert status == 202
    assert _wait_swap_state(srv, {"failed"}) == "failed"
    swap_status = srv.swap.status()
    assert "topk" in swap_status["error"]
    assert srv.model_fingerprint == "fpA"
    status, body, _ = _post(srv.port, "predict",
                            "class V { int still() { return 1; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fpA"
    hz = json.loads(_get(srv.port, "/healthz")[1])
    assert hz["model"]["swap_status"]["state"] == "failed"
    assert _counter_value("serving_swap_total",
                          outcome="failed") == failed0 + 1


def test_swap_rejects_nonfinite_scores(chaos_server):
    from code2vec_tpu.serving.swap import SwapManager

    srv, _ = chaos_server()
    srv.swap = SwapManager(
        srv, build_model=lambda d: FakeModel(srv.config,
                                             fingerprint="fpNaN",
                                             scores_finite=False))
    srv.swap.request_reload("nan-artifact")
    assert _wait_swap_state(srv, {"failed"}) == "failed"
    assert "non-finite" in srv.swap.status()["error"]
    assert srv.model_fingerprint == "fpA"


def test_swap_fault_injection_leaves_old_model(chaos_server):
    """The `swap_validate` chaos drill: a fault at the top of the
    load+validate worker fails the swap visibly; never a torn
    half-swapped server."""
    from code2vec_tpu.serving.swap import SwapManager

    srv, _ = chaos_server()
    srv.swap = SwapManager(
        srv, build_model=lambda d: FakeModel(srv.config,
                                             fingerprint="fpC"))
    faults.reset("swap_validate=raise")
    try:
        srv.swap.request_reload("fault-artifact")
        assert _wait_swap_state(srv, {"failed"}) == "failed"
    finally:
        faults.reset(None)
    assert "FaultInjected" in srv.swap.status()["error"]
    assert srv.model_fingerprint == "fpA"
    status, _, _ = _post(srv.port, "predict",
                         "class W { int works() { return 1; } }")
    assert status == 200


def test_swap_adopts_new_model_bucket_grid(chaos_server):
    """Regression: after a hot swap the batcher's deadline-feasibility
    math must run against the NEW model's context-bucket grid, with the
    old grid's device-time samples dropped."""
    srv, _ = chaos_server()
    old_tracker = srv.batcher.device_times
    new = FakeModel(srv.config, fingerprint="fpGrid")
    new.context_buckets = (2, srv.config.max_contexts)
    srv.swap_model(new)
    assert srv.batcher.buckets == (2, srv.config.max_contexts)
    assert srv.batcher.device_times is not old_tracker
    status, body, _ = _post(srv.port, "predict",
                            "class G { int grid() { return 1; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fpGrid"


def test_swap_concurrent_reload_conflicts_409_and_bad_body_400(
        chaos_server):
    from code2vec_tpu.serving.swap import SwapManager

    srv, _ = chaos_server()

    def slow_build(d):
        time.sleep(0.5)
        return FakeModel(srv.config, fingerprint="fpS")

    srv.swap = SwapManager(srv, build_model=slow_build)
    jhdr = {"Content-Type": "application/json"}
    assert _post(srv.port, "admin/reload",
                 json.dumps({"artifact": "s"}), headers=jhdr)[0] == 202
    status, body, _ = _post(srv.port, "admin/reload",
                            json.dumps({"artifact": "t"}), headers=jhdr)
    assert status == 409
    assert "in flight" in json.loads(body)["error"]
    # no target / malformed JSON are 400s, not 500s
    assert _post(srv.port, "admin/reload", "{}", headers=jhdr)[0] == 400
    assert _post(srv.port, "admin/reload", "{nope", headers=jhdr)[0] == 400
    _wait_swap_state(srv, {"ready"})


# --------------------------------------------- drain + SLO accounting


def test_healthz_flips_503_draining_the_moment_sigterm_lands(
        chaos_server, monkeypatch):
    """The load-balancer eviction contract: while a drain waits on
    in-flight work the listener must answer /healthz with 503 +
    status=draining, and new predicts are refused as draining sheds."""
    monkeypatch.setenv("C2V_FAKE_SLEEP", "1.2")
    srv, _ = chaos_server()
    slow_result = {}

    def slow_post():
        slow_result["r"] = _post(
            srv.port, "predict",
            "class S { int slowDrain() { return 1; } } SLOW_MARKER")

    t = threading.Thread(target=slow_post)
    t.start()
    deadline = time.time() + 5
    while srv._inflight == 0 and time.time() < deadline:
        time.sleep(0.01)
    drain_thread = threading.Thread(target=srv.drain,
                                    kwargs={"timeout": 30})
    drain_thread.start()
    deadline = time.time() + 5
    while not srv._draining and time.time() < deadline:
        time.sleep(0.005)
    status, body = _get(srv.port, "/healthz")
    assert status == 503
    hz = json.loads(body)
    assert hz["status"] == "draining"
    assert hz["inflight"] >= 1
    # intake refused with the draining shed reason while the in-flight
    # request is allowed to finish
    shed0 = _counter_value("serving_requests_shed_total",
                           reason="draining")
    status, _, _ = _post(srv.port, "predict",
                         "class N { int newReq() { return 2; } }")
    assert status == 503
    assert _counter_value("serving_requests_shed_total",
                          reason="draining") == shed0 + 1
    drain_thread.join(timeout=30)
    t.join(timeout=30)
    assert slow_result["r"][0] == 200


def test_drain_timeout_exits_nonzero_with_abandoned_count(
        tmp_path, fake_extractor, monkeypatch):
    """A drain that exceeds serve_drain_timeout_s exits nonzero with the
    abandoned-request count in the final heartbeat."""
    from code2vec_tpu.serving.server import serve_main

    monkeypatch.setenv("C2V_FAKE_SLEEP", "5.0")
    hb_path = tmp_path / "serve.heartbeat.json"
    config = _chaos_config(tmp_path, serve_port=0,
                           serve_drain_timeout_s=0.3,
                           serve_heartbeat_interval_s=0.1,
                           heartbeat_file=str(hb_path))
    model = FakeModel(config)
    stop = threading.Event()
    rc_holder = {}

    def run():
        rc_holder["rc"] = serve_main(config, model=model, stop=stop,
                                     install_signals=False)

    serve_thread = threading.Thread(target=run)
    serve_thread.start()
    try:
        deadline = time.time() + 10
        port = None
        while port is None and time.time() < deadline:
            try:
                port = json.loads(hb_path.read_text()).get("port")
            except (OSError, ValueError):
                time.sleep(0.02)
        assert port, "server heartbeat never reported a port"
        slow = threading.Thread(target=_post, args=(
            port, "predict",
            "class S { int abandoned() { return 1; } } SLOW_MARKER"))
        slow.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                if json.loads(hb_path.read_text()).get("inflight", 0):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.02)
    finally:
        stop.set()
    serve_thread.join(timeout=30)
    slow.join(timeout=30)
    assert rc_holder["rc"] == 1
    deadline = time.time() + 2
    hb = json.loads(hb_path.read_text())
    while hb.get("status") != "error" and time.time() < deadline:
        time.sleep(0.05)
        hb = json.loads(hb_path.read_text())
    assert hb["status"] == "error"
    assert hb["abandoned_requests"] >= 1


def test_sigterm_drain_under_continuous_batching_exits_zero(
        tmp_path, fake_extractor, monkeypatch):
    """serve_main with --serve_continuous: SIGTERM (the stop event the
    signal handler sets) lands while a request is in flight — the drain
    flushes the dispatcher's forming slots and in-flight steps, the
    in-flight response completes well-formed, and the exit code is 0."""
    from code2vec_tpu.serving.server import serve_main

    monkeypatch.setenv("C2V_FAKE_SLEEP", "0.4")
    hb_path = tmp_path / "serve.heartbeat.json"
    config = _chaos_config(tmp_path, serve_port=0,
                           serve_continuous=True,
                           serve_inflight_steps=2,
                           serve_drain_timeout_s=15.0,
                           serve_heartbeat_interval_s=0.1,
                           heartbeat_file=str(hb_path))
    model = FakeModel(config)
    stop = threading.Event()
    rc_holder, results = {}, {}

    def run():
        rc_holder["rc"] = serve_main(config, model=model, stop=stop,
                                     install_signals=False)

    serve_thread = threading.Thread(target=run)
    serve_thread.start()
    slow = None
    try:
        deadline = time.time() + 10
        port = None
        while port is None and time.time() < deadline:
            try:
                port = json.loads(hb_path.read_text()).get("port")
            except (OSError, ValueError):
                time.sleep(0.02)
        assert port, "server heartbeat never reported a port"

        def slow_post():
            results["slow"] = _post(
                port, "predict",
                "class S { int inflight() { return 1; } } SLOW_MARKER")

        slow = threading.Thread(target=slow_post)
        slow.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            try:
                if json.loads(hb_path.read_text()).get("inflight", 0):
                    break
            except (OSError, ValueError):
                pass
            time.sleep(0.02)
    finally:
        stop.set()
    serve_thread.join(timeout=30)
    if slow is not None:
        slow.join(timeout=30)
    assert rc_holder["rc"] == 0
    status, body, _ = results["slow"]
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fpA"


def test_total_phase_histogram_records_every_terminal_status(
        chaos_server):
    """Satellite bugfix pin: errored and shed requests land in
    serving_request_seconds{phase=total,status=...} — the tail is
    measured, not invisible."""
    srv, _ = chaos_server()
    cases = {
        "200": ("class H { int histOk() { return 1; } }", 200),
        "400": ("", 400),
        "422": ("BOOM_ALWAYS", 422),
        "503": ("class H2 { int histCrash() { return 1; } } "
                "CRASH_ALWAYS", 503),
    }
    before = {s: _hist_count("serving_request_seconds",
                             phase="total", status=s) for s in cases}
    for s, (code, want) in cases.items():
        status, _, _ = _post(srv.port, "predict", code)
        assert status == want
    for s in cases:
        assert _hist_count("serving_request_seconds", phase="total",
                           status=s) == before[s] + 1, f"status {s}"


def test_watchdog_timer_cancelled_thread_count_stable(
        fake_extractor, tmp_path):
    """Satellite bugfix pin: the pool's per-request watchdog Timer is
    cancelled on the fast path — sustained traffic must not accumulate
    idle Timer threads waiting out the 30s extractor timeout."""
    from code2vec_tpu.serving.extractor_pool import ExtractorPool

    config = _serving_config(tmp_path, extractor_timeout_s=30.0)
    with ExtractorPool(config, size=1) as pool:
        assert pool.warm
        pool.extract_source("class W { int warm() { return 1; } }")
        time.sleep(0.2)
        baseline = threading.active_count()
        for i in range(25):
            pool.extract_source(
                f"class T{i} {{ int t{i}() {{ return 1; }} }}")
        time.sleep(0.3)  # cancelled timers wind down
        after = threading.active_count()
    assert after <= baseline + 1, (
        f"{after - baseline} threads accumulated over 25 requests "
        f"(uncancelled watchdog timers)")


# ------------------------------------------------- supervisor (slow)


def _write_child_overrides(tmp_path, fake_extractor, **extra):
    overrides = dict(
        serve_host="127.0.0.1",
        max_contexts=16,
        serve_batch_size=4,
        serve_buckets="4,8",
        serve_max_delay_ms=2.0,
        serve_cache_entries=0,
        extractor_pool_size=1,
        serve_drain_timeout_s=5.0,
        serve_heartbeat_interval_s=0.2,
    )
    overrides.update(extra)
    path = tmp_path / "child-config.json"
    path.write_text(json.dumps(overrides))
    return str(path)


def _supervisor_config(tmp_path, **overrides):
    kwargs = dict(
        serve=True,
        serve_host="127.0.0.1",
        serve_port=0,
        serve_replicas=2,
        serve_max_restarts=5,
        serve_heartbeat_interval_s=0.2,
        serve_drain_timeout_s=5.0,
        heartbeat_file=str(tmp_path / "supervisor.heartbeat.json"),
        verbose_mode=0,
    )
    kwargs.update(overrides)
    from code2vec_tpu.config import Config
    return Config(**kwargs)


def _wait_live_replicas(sup, n, timeout=30.0):
    """Poll the supervisor heartbeat until n replicas are alive with
    known ports; returns the heartbeat dict."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            hb = json.loads(open(sup.heartbeat_path).read())
        except (OSError, ValueError):
            hb = None
        if hb:
            live = [r for r in hb["replicas"]
                    if r["alive"] and r["port"]]
            if len(live) >= n:
                return hb
        time.sleep(0.05)
    raise AssertionError(f"never reached {n} live replicas; last={hb}")


@pytest.fixture()
def run_supervisor(tmp_path, fake_extractor, monkeypatch):
    """Factory: a Supervisor on lightweight fake-model replica children
    (tests/chaos_serving_child.py), run on a daemon thread, torn down at
    test end."""
    from code2vec_tpu.serving.supervisor import Supervisor

    running = []

    def start(config, child_args=(), force_proxy=True):
        if force_proxy:
            monkeypatch.setenv("C2V_SERVE_FORCE_PROXY", "1")
        else:
            monkeypatch.delenv("C2V_SERVE_FORCE_PROXY", raising=False)
        child_command = [sys.executable, CHILD] + list(child_args)
        sup = Supervisor(config, child_command=child_command)
        rc_holder = {}
        thread = threading.Thread(
            target=lambda: rc_holder.update(rc=sup.run()), daemon=True)
        thread.start()
        running.append((sup, thread))
        return sup, thread, rc_holder

    yield start
    for sup, thread in running:
        sup._stop.set()
        thread.join(timeout=40)


@pytest.mark.slow
@pytest.mark.chaos
def test_supervisor_sigkill_under_load_no_corrupt_responses_converges(
        tmp_path, fake_extractor, run_supervisor):
    """THE serving chaos proof: SIGKILL one of two replicas under
    concurrent load. Zero malformed responses (every body is valid JSON
    with either a result or an honest error), the supervisor restores
    2 live replicas, and a coordinated SIGTERM drain exits 0."""
    overrides = _write_child_overrides(tmp_path, fake_extractor)
    config = _supervisor_config(tmp_path)
    sup, thread, rc_holder = run_supervisor(config, (overrides,))
    hb = _wait_live_replicas(sup, 2)
    port = sup.port

    responses = []
    resp_lock = threading.Lock()
    stop_load = threading.Event()
    malformed = []

    def load(ci):
        i = 0
        while not stop_load.is_set():
            try:
                status, body, _ = _post(
                    port, "predict",
                    f"class K{ci}x{i} {{ int m{ci}x{i}() "
                    f"{{ return 1; }} }}")
            except Exception as e:  # noqa: BLE001 — proxied kill window
                # a torn TCP connection counts as a failure to retry,
                # not a corrupt response; record it separately
                with resp_lock:
                    responses.append(("conn_error", str(e)))
                i += 1
                continue
            try:
                payload = json.loads(body)
                ok = (("methods" in payload)
                      if status == 200 else ("error" in payload))
                if not ok:
                    raise ValueError(f"incomplete payload: {payload}")
            except ValueError as e:
                malformed.append((status, body[:200], str(e)))
            with resp_lock:
                responses.append((status, None))
            i += 1

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(4)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.5)
        victim = next(r for r in hb["replicas"] if r["alive"])
        os.kill(victim["pid"], signal.SIGKILL)
        # convergence: the supervisor restarts the victim with backoff
        deadline = time.time() + 30
        while time.time() < deadline:
            hb2 = json.loads(open(sup.heartbeat_path).read())
            entry = next(r for r in hb2["replicas"]
                         if r["index"] == victim["index"])
            if (entry["alive"] and entry["port"]
                    and entry["pid"] != victim["pid"]
                    and entry["restarts"] >= 1):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"victim never restarted: {hb2}")
        _wait_live_replicas(sup, 2)
        time.sleep(0.5)  # post-recovery traffic
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
    assert not malformed, f"corrupt responses: {malformed[:3]}"
    statuses = [s for s, _ in responses]
    assert statuses.count(200) > 0
    # post-recovery the service is fully back: a fresh request succeeds
    status, body, _ = _post(port, "predict",
                            "class A { int after() { return 1; } }")
    assert status == 200
    assert json.loads(body)["methods"][0]["original_name"] == "after"
    # coordinated drain: SIGTERM fan-out, every replica exits 0
    sup._stop.set()
    thread.join(timeout=40)
    assert rc_holder["rc"] == 0
    final = json.loads(open(sup.heartbeat_path).read())
    assert final["status"] == "done"


@pytest.mark.slow
@pytest.mark.chaos
def test_supervisor_reuseport_replicas_share_one_port(
        tmp_path, fake_extractor, run_supervisor):
    """SO_REUSEPORT mode: both replicas bind the SAME port and traffic
    is served through it (kernel load-balancing)."""
    import socket as socket_mod
    if not hasattr(socket_mod, "SO_REUSEPORT"):
        pytest.skip("platform lacks SO_REUSEPORT")
    overrides = _write_child_overrides(tmp_path, fake_extractor)
    config = _supervisor_config(tmp_path)
    sup, thread, rc_holder = run_supervisor(config, (overrides,),
                                            force_proxy=False)
    assert sup.reuseport
    hb = _wait_live_replicas(sup, 2)
    ports = {r["port"] for r in hb["replicas"]}
    assert ports == {sup.port}
    # in reuseport mode replica.port is assigned at spawn, before the
    # child has bound the socket: wait for actual readiness
    deadline = time.time() + 30
    while time.time() < deadline:
        try:
            if _get(sup.port, "/healthz")[0] == 200:
                break
        except (urllib.error.URLError, OSError):
            time.sleep(0.1)
    for i in range(4):
        status, body, _ = _post(
            sup.port, "predict",
            f"class R{i} {{ int rp{i}() {{ return 1; }} }}")
        assert status == 200
    sup._stop.set()
    thread.join(timeout=40)
    assert rc_holder["rc"] == 0


@pytest.mark.slow
@pytest.mark.chaos
def test_supervisor_escalates_when_restart_budget_exhausted(
        tmp_path, monkeypatch):
    """A replica that cannot stay up is a deploy problem: after
    serve_max_restarts the supervisor kills everything and exits
    nonzero with the escalation in its heartbeat."""
    from code2vec_tpu.serving.supervisor import Supervisor

    monkeypatch.setenv("C2V_SERVE_FORCE_PROXY", "1")
    config = _supervisor_config(tmp_path, serve_replicas=1,
                                serve_max_restarts=1)
    sup = Supervisor(config, child_command=[
        sys.executable, "-c", "import sys; sys.exit(7)"])
    rc = sup.run()
    assert rc == 1
    hb = json.loads(open(sup.heartbeat_path).read())
    assert hb["status"] == "error"
    assert hb["escalated"] is True
    assert hb["replicas"][0]["restarts"] == 1


@pytest.mark.slow
@pytest.mark.chaos
def test_supervisor_restarts_replica_with_stale_heartbeat(
        tmp_path, fake_extractor, run_supervisor, monkeypatch):
    """The hung-replica drill (`replica_heartbeat` fault point): a
    replica whose heartbeat ticker dies keeps its process alive but
    goes stale; the supervisor kills and restarts it."""
    faults.reset(None)  # keep the fault env out of THIS process
    monkeypatch.setenv("C2V_FAULTS", "replica_heartbeat@2=raise")
    overrides = _write_child_overrides(tmp_path, fake_extractor)
    config = _supervisor_config(tmp_path, serve_replicas=1)
    sup, thread, rc_holder = run_supervisor(config, (overrides,))
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            try:
                hb = json.loads(open(sup.heartbeat_path).read())
            except (OSError, ValueError):
                hb = {"replicas": [{"restarts": 0}]}
            if hb["replicas"][0]["restarts"] >= 1:
                break
            time.sleep(0.1)
        else:
            raise AssertionError(f"stale replica never restarted: {hb}")
        assert _counter_value("serving_replica_restarts_total") >= 1
    finally:
        faults.reset("")  # back to lazy env re-read for other tests


# ----------------------------------------------------------- CLI seam


def test_serve_resilience_cli_flags_parse():
    from code2vec_tpu.cli import config_from_args

    config = config_from_args([
        "serve", "--load", "/tmp/nonexistent-model",
        "--serve_deadline_ms", "1500", "--serve_deadline_max_ms", "9000",
        "--serve_queue_depth", "32", "--serve_breaker_window", "20",
        "--serve_breaker_failure_ratio", "0.25",
        "--serve_breaker_min_requests", "8",
        "--serve_breaker_cooldown", "2.5",
        "--replicas", "3", "--serve_max_restarts", "7",
        "--serve_heartbeat_interval", "1.5"])
    assert config.serve_deadline_ms == 1500
    assert config.serve_deadline_max_ms == 9000
    assert config.serve_queue_depth == 32
    assert config.serve_breaker_window_s == 20
    assert config.serve_breaker_failure_ratio == 0.25
    assert config.serve_breaker_min_requests == 8
    assert config.serve_breaker_cooldown_s == 2.5
    assert config.serve_replicas == 3
    assert config.serve_max_restarts == 7
    assert config.serve_heartbeat_interval_s == 1.5
    config.verify()


def test_replicas_rejected_outside_serve():
    from code2vec_tpu.cli import config_from_args

    config = config_from_args(["--data", "/tmp/x", "--replicas", "2"])
    with pytest.raises(ValueError, match="serve subcommand"):
        config.verify()


def test_deadline_default_must_not_exceed_max():
    from code2vec_tpu.cli import config_from_args

    config = config_from_args([
        "serve", "--load", "/tmp/nonexistent-model",
        "--serve_deadline_ms", "5000", "--serve_deadline_max_ms", "1000"])
    with pytest.raises(ValueError, match="serve_deadline_max_ms"):
        config.verify()


def test_scale_down_prefers_coldest_cache_replica(tmp_path):
    """Cache-warmth-aware scale-down (PR-13 follow-on, roofline PR):
    the victim is the replica with the fewest serving_cache_hits_total
    over the CURRENT warmth window (hits since the last baseline
    sample — lifetime counters measure uptime, not warmth); missing/
    unreadable snapshots count 0; counter resets clamp to 0; all-equal
    windows fall back to newest-first."""
    from code2vec_tpu.serving.supervisor import Supervisor

    config = _supervisor_config(tmp_path, serve_replicas=3)
    sup = Supervisor(config, child_command=["true"])

    def write_metrics(replica, hits):
        with open(replica.metrics_path, "w") as f:
            f.write("# TYPE serving_cache_hits_total counter\n"
                    f"serving_cache_hits_total {hits}\n")

    r0, r1, r2 = sup.replicas
    write_metrics(r0, 50)
    write_metrics(r1, 3)
    write_metrics(r2, 90)
    assert sup._scale_down_victims(sup.replicas, 1) == [r1]
    # two victims: the two coldest caches, coldest first
    assert sup._scale_down_victims(sup.replicas, 2) == [r1, r0]
    # WINDOWED, not lifetime: baseline the counters, then give the
    # lifetime-richest replica (r2) the QUIETEST window — it must be
    # the victim despite its big historical count
    sup._sample_warmth_baselines()
    write_metrics(r0, 80)    # +30 this window
    write_metrics(r1, 60)    # +57
    write_metrics(r2, 91)    # +1  <- coldest window, biggest lifetime
    assert sup._scale_down_victims(sup.replicas, 1) == [r2]
    # a restarted replica's counter reset clamps to 0 (fresh cache IS
    # cold), never a negative that would wrap the ordering
    write_metrics(r2, 2)
    assert sup._scale_down_victims(sup.replicas, 1) == [r2]
    sup._sample_warmth_baselines()
    # replica without a snapshot (still starting) = coldest of all
    os.remove(r2.metrics_path)
    assert sup._scale_down_victims(sup.replicas, 1) == [r2]
    # unreadable garbage parses to 0 samples -> counts 0 hits
    with open(r2.metrics_path, "wb") as f:
        f.write(b"\x00\xff garbage")
    assert sup._scale_down_victims(sup.replicas, 1) == [r2]
    # all-equal warmth: newest-first (the pre-roofline policy)
    for r in sup.replicas:
        r.warmth_prev = 0.0
        write_metrics(r, 7)
    assert sup._scale_down_victims(sup.replicas, 1) == [r2]
    assert sup._scale_down_victims(sup.replicas, 2) == [r2, r1]
