"""The composed pod lifecycle on the 8-device CPU mesh.

`__graft_entry__.dryrun_multichip` is the driver's multi-chip validation
entry; since round 5 it runs the whole lifecycle — train(3) -> sharded
eval (tp_top_k + host metrics) -> sharded checkpoint save -> restore into
a freshly built mesh/state -> resume(2) — and asserts the post-restore
losses bit-equal an uninterrupted 5-step run, for dense Adam and
touched-rows sparse Adam on dp2 tp2 cp2. This test keeps that composition
exercised in CI, not just at driver time.

Spec being matched (composed + sharded): the reference's save/restore
lifecycle tensorflow_model.py:369-376 and its eval graph :266-308.
"""

import __graft_entry__ as graft


def test_composed_pod_lifecycle_8dev():
    # conftest.py pins jax to 8 virtual CPU devices, so this runs
    # in-process (no subprocess fallback); every assertion lives inside.
    graft.dryrun_multichip(8)
