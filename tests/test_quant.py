"""Quantized release-artifact suite: blockwise top-k parity, int8
round-trip error bounds, artifact save/load (+ named-field rejection),
AOT serve lowerings, eval-step blockwise parity, cache fingerprinting.

The blockwise merge's exactness claim (ops/topk.py docstring: identical
indices AND values to full `lax.top_k`, ties included) is pinned here
across block sizes, including ties from a coarse value grid, k larger
than a block, and block larger than the vocab.
"""

import dataclasses
import json
import os
import pickle
import random

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.config import Config

pytestmark = pytest.mark.quant


# ----------------------------------------------------- blockwise top-k


@pytest.mark.parametrize("block", [1, 3, 7, 16, 64, 100, 1000])
def test_blockwise_from_logits_matches_lax_top_k(block):
    from code2vec_tpu.ops.topk import blockwise_top_k_from_logits
    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.standard_normal((9, 97)), jnp.float32)
    k = 10
    fv, fi = jax.lax.top_k(logits, k)
    bv, bi = blockwise_top_k_from_logits(logits, k, block)
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(bi))
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(bv))


@pytest.mark.parametrize("block", [2, 5, 16, 41])
def test_blockwise_tie_breaking_matches(block):
    """Ties everywhere: logits drawn from 4 distinct values, so every
    top-k selection is decided by lax.top_k's lower-index-first rule —
    the merge must reproduce it exactly."""
    from code2vec_tpu.ops.topk import blockwise_top_k_from_logits
    rng = np.random.default_rng(1)
    logits = jnp.asarray(
        rng.choice([-1.0, 0.0, 0.5, 2.0], size=(6, 83)), jnp.float32)
    for k in (1, 5, 64):
        fv, fi = jax.lax.top_k(logits, k)
        bv, bi = blockwise_top_k_from_logits(logits, k, block)
        np.testing.assert_array_equal(np.asarray(fi), np.asarray(bi),
                                      err_msg=f"k={k} block={block}")
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(bv))


@pytest.mark.parametrize("v,block,k", [
    (1000, 96, 10),     # clamped last block (1000 % 96 != 0)
    (1000, 1024, 10),   # block > vocab: degenerates to one full block
    (50, 8, 20),        # k larger than a block
    (7, 3, 7),          # k == vocab
])
def test_blockwise_matmul_matches_full(v, block, k):
    from code2vec_tpu.ops.topk import blockwise_matmul_top_k
    rng = np.random.default_rng(2)
    cv = jnp.asarray(rng.standard_normal((5, 24)), jnp.float32)
    tbl = jnp.asarray(rng.standard_normal((v, 24)), jnp.float32)
    full = jnp.einsum("bd,vd->bv", cv, tbl,
                      preferred_element_type=jnp.float32)
    fv, fi = jax.lax.top_k(full, k)
    out = jax.jit(lambda c, t: blockwise_matmul_top_k(c, t, k, block))(
        cv, tbl)
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(out.indices))
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(out.values))
    # the streamed logsumexp must agree with the full-row one
    ref_lse = jax.scipy.special.logsumexp(full, axis=-1)
    np.testing.assert_allclose(np.asarray(out.lse), np.asarray(ref_lse),
                               rtol=1e-5)


def test_blockwise_matmul_bf16_and_valid_rows():
    """bf16 compute parity with the full bf16 einsum, and padded
    classifier rows (valid_rows) never selected."""
    from code2vec_tpu.ops.topk import blockwise_matmul_top_k
    rng = np.random.default_rng(3)
    v, real = 128, 119
    cv = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    tbl = jnp.asarray(rng.standard_normal((v, 16)), jnp.float32)
    full = jnp.einsum("bd,vd->bv", cv.astype(jnp.bfloat16),
                      tbl.astype(jnp.bfloat16),
                      preferred_element_type=jnp.float32)
    full = jnp.where(jnp.arange(v)[None, :] < real, full, -jnp.inf)
    fv, fi = jax.lax.top_k(full, 8)
    out = jax.jit(lambda c, t: blockwise_matmul_top_k(
        c, t, 8, 48, valid_rows=real, compute_dtype=jnp.bfloat16))(cv, tbl)
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(out.indices))
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(out.values))
    assert int(np.asarray(out.indices).max()) < real


def test_blockwise_int8_scales_match_dequantized_full():
    """The fused-dequant block matmul selects the same top-k as a full
    matmul against the explicitly dequantized table."""
    from code2vec_tpu.ops.quant import quantize_rows
    from code2vec_tpu.ops.topk import blockwise_matmul_top_k
    rng = np.random.default_rng(4)
    tbl = rng.standard_normal((300, 24)).astype(np.float32)
    q, s = quantize_rows(tbl)
    deq = q.astype(np.float32) * s
    cv = jnp.asarray(rng.standard_normal((6, 24)), jnp.float32)
    full = jnp.einsum("bd,vd->bv", cv, jnp.asarray(deq),
                      preferred_element_type=jnp.float32)
    fv, fi = jax.lax.top_k(full, 7)
    out = jax.jit(lambda c, t, sc: blockwise_matmul_top_k(
        c, t, 7, 64, scales=sc))(cv, jnp.asarray(q), jnp.asarray(s))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(out.indices))
    np.testing.assert_allclose(np.asarray(fv), np.asarray(out.values),
                               rtol=1e-6)


def test_gathered_label_logits_match_full_column():
    from code2vec_tpu.ops.topk import gathered_label_logits
    rng = np.random.default_rng(5)
    cv = jnp.asarray(rng.standard_normal((8, 12)), jnp.float32)
    tbl = jnp.asarray(rng.standard_normal((40, 12)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 40, 8), jnp.int32)
    full = jnp.einsum("bd,vd->bv", cv, tbl,
                      preferred_element_type=jnp.float32)
    want = np.take_along_axis(np.asarray(full),
                              np.asarray(labels)[:, None], axis=1)[:, 0]
    got = np.asarray(gathered_label_logits(cv, tbl, labels))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_blockwise_nonfinite_logits_keep_loss_finite():
    """CE-guard parity with the full eval path: a weight blow-up that
    produces Inf/NaN logits must leave the blockwise lse and the label
    logit finite — the full path substitutes -1e30 (safe_logits in
    training/step.py) before CE, and a poisoned eval-loss gauge would
    break best-checkpoint-by-loss comparisons and TB scalars."""
    from code2vec_tpu.ops.topk import (
        blockwise_matmul_top_k, gathered_label_logits,
    )
    rng = np.random.default_rng(8)
    tbl = rng.standard_normal((60, 12)).astype(np.float32)
    tbl[7, :] = np.inf      # blown-up row: its logits are Inf or NaN
    cv = jnp.asarray(rng.standard_normal((4, 12)), jnp.float32)
    tblj = jnp.asarray(tbl)
    out = jax.jit(lambda c, t: blockwise_matmul_top_k(c, t, 5, 16))(
        cv, tblj)
    assert np.isfinite(np.asarray(out.lse)).all()
    # the streamed lse equals the full path's safe-substituted one
    full = jnp.einsum("bd,vd->bv", cv, tblj,
                      preferred_element_type=jnp.float32)
    safe = jnp.where(jnp.isfinite(full), full, -1e30)
    ref_lse = jax.scipy.special.logsumexp(safe, axis=-1)
    np.testing.assert_allclose(np.asarray(out.lse), np.asarray(ref_lse),
                               rtol=1e-5)
    # a nonfinite label logit clamps exactly as safe_logits[label] would
    labels = jnp.asarray([7, 0, 7, 3], jnp.int32)
    ll = np.asarray(gathered_label_logits(cv, tblj, labels))
    assert np.isfinite(ll).all()
    np.testing.assert_array_equal(ll[[0, 2]], np.float32(-1e30))
    want = np.take_along_axis(np.asarray(safe),
                              np.asarray(labels)[:, None], axis=1)[:, 0]
    np.testing.assert_allclose(ll, want, rtol=1e-6)


# ----------------------------------------------------------- int8 ops


def test_int8_round_trip_error_bound():
    """Per-row symmetric absmax: |x - dequant(quant(x))| <= scale/2 =
    max|row| / 254 elementwise, and the row absmax survives exactly
    (it quantizes to +-127 by construction)."""
    from code2vec_tpu.ops.quant import dequantize_rows, quantize_rows
    rng = np.random.default_rng(6)
    tbl = (rng.standard_normal((64, 48))
           * rng.lognormal(0, 2, (64, 1))).astype(np.float32)
    tbl[13, :] = 0.0  # all-zero row (untouched vocab tail)
    q, s = quantize_rows(tbl)
    assert q.dtype == np.int8 and s.shape == (64, 1)
    deq = dequantize_rows(q, s)
    err = np.abs(deq - tbl)
    bound = np.abs(tbl).max(axis=1, keepdims=True) / 254 + 1e-9
    assert (err <= bound).all(), float((err / bound).max())
    np.testing.assert_array_equal(deq[13], np.zeros(48, np.float32))
    # absmax element is exactly representable
    flat_amax = np.abs(tbl).argmax(axis=1)
    rows = np.arange(64)
    np.testing.assert_allclose(np.abs(deq[rows, flat_amax]),
                               np.abs(tbl[rows, flat_amax]), rtol=1e-6)


def test_dequant_gather_matches_host_dequant():
    from code2vec_tpu.ops.quant import dequant_gather, quantize_rows
    rng = np.random.default_rng(7)
    tbl = rng.standard_normal((30, 8)).astype(np.float32)
    q, s = quantize_rows(tbl)
    ids = jnp.asarray(rng.integers(0, 30, (4, 5)), jnp.int32)
    got = np.asarray(dequant_gather(jnp.asarray(q), jnp.asarray(s), ids))
    want = (q.astype(np.float32) * s)[np.asarray(ids)]
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ------------------------------------------------- eval-step blockwise


def _tiny_model(tmp_path, **config_overrides):
    from code2vec_tpu.model_facade import Code2VecModel
    rng = random.Random(0)
    tokens = [f"tok{i}" for i in range(6)]
    paths = [f"p{i}" for i in range(4)]
    targets = [f"name|x{i}" for i in range(40)]
    rows = []
    for _ in range(48):
        t = rng.randrange(len(targets))
        ctxs = [f"{tokens[t % 6]},{rng.choice(paths)},{tokens[t % 6]}"
                for _ in range(rng.randint(2, 6))]
        rows.append(f"{targets[t]} " + " ".join(ctxs)
                    + " " * (16 - len(ctxs)))
    prefix = str(tmp_path / "synthetic")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump({w: 10 for w in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({t: 10 for t in targets}, f)
        pickle.dump(len(rows), f)
    kwargs = dict(train_data_path_prefix=prefix, max_contexts=16,
                  train_batch_size=8, test_batch_size=8,
                  compute_dtype="float32", verbose_mode=0,
                  serve_batch_size=4, serve_buckets="4,8",
                  num_train_epochs=1, save_every_epochs=1000)
    kwargs.update(config_overrides)
    return Code2VecModel(Config(**kwargs))


def _rand_batch_arrays(model, b=8):
    rng = np.random.default_rng(11)
    d = model.dims
    m = model.config.max_contexts
    return (jnp.asarray(rng.integers(0, d.token_vocab_size, (b, m)), jnp.int32),
            jnp.asarray(rng.integers(0, d.path_vocab_size, (b, m)), jnp.int32),
            jnp.asarray(rng.integers(0, d.token_vocab_size, (b, m)), jnp.int32),
            jnp.asarray((rng.random((b, m)) > 0.3), jnp.float32),
            jnp.asarray(rng.integers(2, d.real_target_vocab_size, (b,)),
                        jnp.int32),
            jnp.asarray(np.ones(b, bool)))


def test_eval_step_blockwise_matches_full(tmp_path):
    """The production eval step with topk_block_size engaged returns
    identical top-k indices/values and a matching CE sum vs the
    full-logits path (target vocab 40+specials, block 8 -> 6 blocks)."""
    model = _tiny_model(tmp_path)
    arrays = _rand_batch_arrays(model)
    full_cfg = dataclasses.replace(model.config, topk_block_size=0)
    from code2vec_tpu.training.step import TrainStepBuilder
    full_step = TrainStepBuilder(model.module, model.optimizer, full_cfg,
                                 mesh=None).make_eval_step(model.state)
    block_cfg = dataclasses.replace(model.config, topk_block_size=8)
    builder = TrainStepBuilder(model.module, model.optimizer, block_cfg,
                               mesh=None)
    assert builder._eval_topk_block() == 8
    block_step = builder.make_eval_step(model.state)
    fo = full_step(model.state.params, *arrays)
    bo = block_step(model.state.params, *arrays)
    np.testing.assert_array_equal(np.asarray(fo.topk_indices),
                                  np.asarray(bo.topk_indices))
    np.testing.assert_array_equal(np.asarray(fo.topk_values),
                                  np.asarray(bo.topk_values))
    np.testing.assert_allclose(np.asarray(fo.code_vectors),
                               np.asarray(bo.code_vectors), rtol=1e-6)
    np.testing.assert_allclose(float(fo.loss_sum), float(bo.loss_sum),
                               rtol=1e-5)


def test_eval_topk_block_gates(tmp_path):
    """Blockwise disengages when it cannot help: block 0, block >= vocab,
    tp-sharded tables."""
    from code2vec_tpu.training.step import TrainStepBuilder
    model = _tiny_model(tmp_path)
    mk = lambda **kw: TrainStepBuilder(  # noqa: E731
        model.module, model.optimizer,
        dataclasses.replace(model.config, **kw),
        mesh=None)._eval_topk_block()
    assert mk(topk_block_size=0) == 0
    assert mk(topk_block_size=100_000) == 0     # >= vocab: full path
    assert mk(topk_block_size=8) == 8
    assert mk(topk_block_size=8, tp=2) == 0


# ------------------------------------------------- artifact round trip


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("quant-artifact")
    model = _tiny_model(tmp)
    from code2vec_tpu.release.artifact import export_artifact
    art_dir = str(tmp / "artifact")
    meta = export_artifact(model, art_dir, log=lambda m: None)
    return model, art_dir, meta


def test_artifact_save_load_round_trip(exported):
    from code2vec_tpu.release.artifact import load_artifact
    model, art_dir, meta = exported
    art = load_artifact(art_dir)
    assert art.meta["fingerprint"] == meta["fingerprint"]
    assert art.scheme == "int8_rowwise_symmetric"
    # quantized tables carry scales shaped (V, 1); dense params are f32
    for name in ("token_embedding", "path_embedding", "target_embedding"):
        assert art.tables[name].dtype == np.int8
        assert art.tables[f"{name}.scale"].shape == \
            (art.tables[name].shape[0], 1)
    assert art.tables["transform"].dtype == np.float32
    # >= 3x smaller tables than fp32 (int8 + one f32 scale per row)
    tb = meta["table_bytes"]
    assert tb["fp32"] / tb["artifact"] >= 3.0
    # vocabularies round-trip through the artifact's dictionaries.bin
    from code2vec_tpu.vocab import Code2VecVocabs
    v = Code2VecVocabs.load(art.dictionaries_path)
    assert v.target_vocab.size == model.vocabs.target_vocab.size


def test_artifact_fp32_consumer_rejected_with_named_field(exported):
    from code2vec_tpu.release.artifact import ArtifactError, load_artifact
    _, art_dir, _ = exported
    with pytest.raises(ArtifactError, match="quantization.scheme") as ei:
        load_artifact(art_dir, expect_scheme="float32")
    assert ei.value.field == "quantization.scheme"


def test_artifact_dtype_mismatch_rejected(exported, tmp_path):
    """A tampered bundle (meta says int8, file holds f32) must fail
    naming the table, not dequantize garbage."""
    import shutil

    from code2vec_tpu.release.artifact import ArtifactError, load_artifact
    _, art_dir, _ = exported
    broken = str(tmp_path / "broken")
    shutil.copytree(art_dir, broken)
    q = np.load(os.path.join(broken, "token_embedding.npy"))
    np.save(os.path.join(broken, "token_embedding.npy"),
            q.astype(np.float32))
    with pytest.raises(ArtifactError, match="token_embedding.dtype"):
        load_artifact(broken)


@pytest.mark.parametrize("field", ["topk", "buckets", "compute_dtype",
                                   "serve_batch_size", "max_contexts"])
def test_artifact_missing_meta_field_rejected(exported, tmp_path, field):
    """A torn or hand-edited meta that lost a runtime-consumed field
    must fail at LOAD with the field named (ArtifactError), not as a
    bare KeyError later in ReleaseModel/make_release_step."""
    import shutil

    from code2vec_tpu.release.artifact import ArtifactError, load_artifact
    _, art_dir, _ = exported
    broken = str(tmp_path / f"missing_{field}")
    shutil.copytree(art_dir, broken)
    mp = os.path.join(broken, "release_meta.json")
    with open(mp) as f:
        meta = json.load(f)
    del meta[field]
    with open(mp, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ArtifactError, match=field) as ei:
        load_artifact(broken)
    assert ei.value.field == field


def test_artifact_missing_runtime_dims_rejected(exported, tmp_path):
    """dims fields only the runtime reads (real_target_vocab_size,
    target_oov_floor) are part of the load-time contract too."""
    import shutil

    from code2vec_tpu.release.artifact import ArtifactError, load_artifact
    _, art_dir, _ = exported
    broken = str(tmp_path / "missing_dims")
    shutil.copytree(art_dir, broken)
    mp = os.path.join(broken, "release_meta.json")
    with open(mp) as f:
        meta = json.load(f)
    del meta["dims"]["real_target_vocab_size"]
    with open(mp, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ArtifactError, match="real_target_vocab_size"):
        load_artifact(broken)


def test_artifact_non_artifact_dir_rejected(tmp_path):
    from code2vec_tpu.release.artifact import ArtifactError, load_artifact
    with pytest.raises(ArtifactError, match="not a release artifact"):
        load_artifact(str(tmp_path))


def test_facade_load_rejects_artifact(exported, tmp_path):
    """--load pointed at a release artifact fails up front with the
    quantization field named (never reaches the Orbax restore)."""
    from code2vec_tpu.model_facade import Code2VecModel
    _, art_dir, _ = exported
    config = Config(model_load_path=art_dir, verbose_mode=0)
    with pytest.raises(ValueError, match="quantization.scheme"):
        Code2VecModel(config)


def test_export_requires_load():
    with pytest.raises(ValueError, match="artifact_out.*requires --load"):
        Config(train_data_path_prefix="x",
               export_artifact_path="/tmp/nope").verify()


# --------------------------------------------------- release runtime


def test_release_model_predictions_and_aot(exported, tmp_path):
    """ReleaseModel serves the artifact: predictions match the facade's
    (int8 quantization of this tiny model preserves the ranking), AOT
    lowerings are used for exported shapes, jit fallback covers others,
    and quality flows through the standard Evaluator."""
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir)
    rm = ReleaseModel(config, log=lambda m: None)
    lines = ["alpha tok0,p0,tok0 tok0,p1,tok0", "beta tok1,p2,tok1"]
    base = model.predict(lines, batch_size=4)
    rel = rm.predict(lines, batch_size=4)
    assert [r.topk_predicted_words for r in rel] == \
        [r.topk_predicted_words for r in base]
    assert rm.aot_loads["aot"] == 1 and rm.aot_loads["jit_error"] == 0
    # un-exported shape -> jit fallback, same answers
    rel2 = rm.predict(lines, batch_size=2)
    assert [r.topk_predicted_words for r in rel2] == \
        [r.topk_predicted_words for r in base]
    assert rm.aot_loads["jit_fallback"] == 1
    # distinct fingerprints: facade vs artifact (cache-key separation)
    assert rm.model_fingerprint() != model.model_fingerprint()
    assert rm.model_fingerprint().startswith("artifact:")


def test_release_predict_defaults_to_serve_batch_size(exported):
    """predict() without an explicit batch_size must chunk at the
    artifact's serve_batch_size — not the facade's test_batch_size
    (1024 default) — so `--predict --artifact` and offline predict hit
    the shipped AOT lowerings instead of tracing unseen shapes."""
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir)
    rm = ReleaseModel(config, log=lambda m: None)
    assert rm._default_predict_batch_size() == int(meta["serve_batch_size"])
    rm.predict(["alpha tok0,p0,tok0 tok0,p1,tok0"])
    assert rm.aot_loads["aot"] == 1 and rm.aot_loads["jit_fallback"] == 0
    rows = {shape[0] for shape in rm._predict_steps}
    assert rows == {int(meta["serve_batch_size"])}


def test_release_eval_step_close_to_fp32(exported):
    """EvalOutputs from the release runtime (int8 + blockwise) track the
    fp32 eval step on random batches: identical top-1 for this model,
    loss within the quantization tolerance."""
    model, art_dir, _ = exported
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir)
    rm = ReleaseModel(config, log=lambda m: None)
    arrays = _rand_batch_arrays(model)
    fo = model._get_eval_step()(model.state.params, *arrays)
    ro = rm.eval_step(None, *arrays)
    assert np.asarray(ro.topk_indices).shape == \
        np.asarray(fo.topk_indices).shape
    np.testing.assert_allclose(np.asarray(ro.code_vectors),
                               np.asarray(fo.code_vectors),
                               rtol=0.1, atol=0.05)
    np.testing.assert_allclose(float(ro.loss_sum), float(fo.loss_sum),
                               rtol=0.1)


def test_aot_export_round_trip_exact(exported):
    """Deserialized AOT lowering == jit of the same step, bitwise, on
    the same platform."""
    from jax import export as jax_export

    from code2vec_tpu.release.artifact import load_artifact
    from code2vec_tpu.release.runtime import make_release_step
    model, art_dir, meta = exported
    art = load_artifact(art_dir)
    rows = int(meta["serve_batch_size"])
    m = int(meta["buckets"][0])
    path = art.aot_path(rows, m)
    assert path is not None
    with open(path, "rb") as f:
        exported_fn = jax_export.deserialize(bytearray(f.read()))
    params = {k.replace(".scale", "_scale"): jnp.asarray(v)
              for k, v in art.tables.items()}
    rng = np.random.default_rng(13)
    d = meta["dims"]
    batch = (jnp.asarray(rng.integers(0, d["token_vocab_size"], (rows, m)),
                         jnp.int32),
             jnp.asarray(rng.integers(0, d["path_vocab_size"], (rows, m)),
                         jnp.int32),
             jnp.asarray(rng.integers(0, d["token_vocab_size"], (rows, m)),
                         jnp.int32),
             jnp.ones((rows, m), jnp.float32),
             jnp.asarray(rng.integers(0, d["real_target_vocab_size"],
                                      (rows,)), jnp.int32),
             jnp.asarray(np.ones(rows, bool)))
    aot_out = exported_fn.call(params, *batch)
    jit_out = jax.jit(make_release_step(meta))(params, *batch)
    for a, b in zip(aot_out, jit_out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_release_fp32_forward_matches_facade(exported, tmp_path):
    """Drift guard for the hand-mirrored forward in make_release_step:
    on an fp32 artifact the release eval outputs must match the facade
    eval step tightly — identical top-k indices, values/code vectors/
    loss to float tolerance. Any change to the canonical forward in
    models/code2vec.py that is not mirrored in release/runtime.py
    fails here."""
    import dataclasses as dc

    from code2vec_tpu.release.artifact import export_artifact
    from code2vec_tpu.release.runtime import ReleaseModel
    model, _, _ = exported
    art_dir = str(tmp_path / "fp32_parity")
    export_artifact(model, art_dir, quantize=False, aot=False,
                    log=lambda m: None)
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir)
    rm = ReleaseModel(config, log=lambda m: None)
    arrays = _rand_batch_arrays(model)
    fo = model._get_eval_step()(model.state.params, *arrays)
    ro = rm.eval_step(None, *arrays)
    np.testing.assert_array_equal(np.asarray(fo.topk_indices),
                                  np.asarray(ro.topk_indices))
    np.testing.assert_allclose(np.asarray(ro.topk_values),
                               np.asarray(fo.topk_values), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ro.code_vectors),
                               np.asarray(fo.code_vectors), rtol=1e-6,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(ro.attention),
                               np.asarray(fo.attention), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(float(ro.loss_sum), float(fo.loss_sum),
                               rtol=1e-5)


def test_release_model_topk_artifact_authoritative(exported):
    """A serve-time --topk override cannot change the baked step: the
    artifact's exported k wins (silent truncation bugfix)."""
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir,
                        top_k_words_considered_during_prediction=3)
    rm = ReleaseModel(config, log=lambda m: None)
    assert rm.config.top_k_words_considered_during_prediction == \
        int(meta["topk"])


def test_release_model_explicit_serve_batch_size_respected(exported):
    """An EXPLICIT --serve_batch_size is honored even when it equals the
    Config default: only an unset knob adopts the artifact's
    AOT-exported size (the operator may be bounding per-request
    latency/memory on a small replica)."""
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported
    default_rows = Config.__dataclass_fields__["serve_batch_size"].default
    assert default_rows != int(meta["serve_batch_size"])
    base = dc.replace(model.config, train_data_path_prefix=None,
                      serve_artifact=art_dir,
                      serve_batch_size=default_rows)
    # unset: the artifact's exported size is adopted (AOT lowerings win)
    implicit = dc.replace(base, explicit_knobs=())
    rm = ReleaseModel(implicit, log=lambda m: None)
    assert rm.config.serve_batch_size == int(meta["serve_batch_size"])
    # explicitly typed, even at the default value: the flag wins
    explicit = dc.replace(base, explicit_knobs=("serve_batch_size",))
    rm = ReleaseModel(explicit, log=lambda m: None)
    assert rm.config.serve_batch_size == default_rows


def test_config_rejects_artifact_plus_training():
    with pytest.raises(ValueError, match="inference-only"):
        Config(train_data_path_prefix="x",
               serve_artifact="/tmp/somewhere").verify()


def test_config_rejects_export_combined_with_serve_or_test(tmp_path):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    with pytest.raises(ValueError, match="one-shot job"):
        Config(model_load_path=str(ckpt),
               export_artifact_path="/tmp/out",
               test_data_path="x.c2v").verify()


def test_config_rejects_export_combined_with_training(tmp_path):
    """--data + --artifact_out would train nothing (main() exports the
    loaded checkpoint and exits) — must fail loudly, not skip the run."""
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    with pytest.raises(ValueError, match="combined with training"):
        Config(model_load_path=str(ckpt),
               export_artifact_path="/tmp/out",
               train_data_path_prefix="corpus").verify()


def test_aot_exec_failure_degrades_to_jit(exported, monkeypatch):
    """A lowering that deserializes but fails at first EXECUTION (version
    skew surfacing at run time, not deserialize time) must degrade to the
    jit fallback — counted as jit_error — instead of erroring every
    request on that bucket."""
    import dataclasses as dc

    from jax import export as jax_export

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported

    class _Poisoned:
        def call(self, *a, **kw):
            raise RuntimeError("custom call target not registered")

    monkeypatch.setattr(jax_export, "deserialize",
                        lambda data: _Poisoned())
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir)
    rm = ReleaseModel(config, log=lambda m: None)
    lines = ["alpha tok0,p0,tok0 tok0,p1,tok0"]
    rel = rm.predict(lines, batch_size=int(meta["serve_batch_size"]))
    assert [r.topk_predicted_words for r in rel] == \
        [r.topk_predicted_words for r in model.predict(lines, batch_size=4)]
    assert rm.aot_loads["jit_error"] == 1 and rm.aot_loads["aot"] == 0


def test_release_step_honors_block_zero(exported, monkeypatch):
    """meta topk_block_size=0 (exporter pinned the full-logits path) must
    reach the blockwise kernel as one block spanning the table — not be
    coerced back to the 4096 default by a falsy-0 check. Absent key
    (older meta) still defaults to 4096."""
    import code2vec_tpu.release.runtime as runtime_mod
    from code2vec_tpu.release.runtime import (
        batch_specs, make_release_step, param_specs,
    )
    model, art_dir, meta = exported
    seen = []
    real = runtime_mod.blockwise_matmul_top_k

    def spy(q, table, k, block_rows, **kw):
        seen.append(block_rows)
        return real(q, table, k, block_rows, **kw)

    monkeypatch.setattr(runtime_mod, "blockwise_matmul_top_k", spy)
    rows, m = 2, int(meta["buckets"][0])
    for pinned, want in ((0, int(meta["dims"]["target_vocab_size"])),
                        (None, 4096)):
        meta2 = dict(meta, topk_block_size=pinned)
        if pinned is None:
            del meta2["topk_block_size"]
        seen.clear()
        jax.eval_shape(make_release_step(meta2), param_specs(meta2),
                       *batch_specs(rows, m))
        assert seen == [want], (pinned, seen)


def test_release_model_evaluate_via_test_surface(exported, tmp_path):
    """`--artifact DIR --test data.c2v`: ReleaseModel.evaluate() scores
    the artifact with the standard Evaluator — same metric surface as
    the facade's --test (the CLI wiring's backing method)."""
    import dataclasses as dc

    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, _ = exported
    test_path = model.config.train_data_path_prefix + ".train.c2v"
    config = dc.replace(model.config, train_data_path_prefix=None,
                        serve_artifact=art_dir, test_data_path=test_path,
                        test_batch_size=16)
    rm = ReleaseModel(config, log=lambda m: None)
    results = rm.evaluate()
    assert 0.0 <= float(results.subtoken_f1) <= 1.0
    assert results.topk_acc.shape == \
        (model.config.top_k_words_considered_during_prediction,)


def test_reexport_into_same_dir_drops_stale_files(exported, tmp_path):
    """fp32 re-export over a prior int8 export must fingerprint the
    same as a clean fp32 export (stale scale files and AOT lowerings
    must not survive into — or be hashed into — the new bundle)."""
    from code2vec_tpu.release.artifact import export_artifact, load_artifact
    model, _, _ = exported
    clean = str(tmp_path / "clean_fp32")
    reused = str(tmp_path / "reused")
    meta_clean = export_artifact(model, clean, quantize=False, aot=False,
                                 log=lambda m: None)
    export_artifact(model, reused, quantize=True, aot=True,
                    log=lambda m: None)
    meta_reused = export_artifact(model, reused, quantize=False, aot=False,
                                  log=lambda m: None)
    assert meta_reused["fingerprint"] == meta_clean["fingerprint"]
    assert not os.path.exists(
        os.path.join(reused, "token_embedding.scale.npy"))
    assert not os.path.isdir(os.path.join(reused, "aot"))
    art = load_artifact(reused)
    assert art.scheme == "float32"


@pytest.mark.parametrize("backend,platforms,want", [
    ("cpu", ["cpu"], True),
    ("tpu", ["tpu"], True),
    ("gpu", ["cuda"], True),        # jax.export says cuda, backend says gpu
    ("gpu", ["rocm"], True),
    ("cpu", ["cuda"], False),
    ("tpu", ["cpu"], False),
    ("cpu", [None], False),         # torn meta: no platform recorded
])
def test_backend_matches_aot_platform_vocabulary(backend, platforms, want):
    from code2vec_tpu.release.runtime import _backend_matches
    assert _backend_matches(backend, platforms) is want


def test_serving_cache_key_includes_model_fingerprint(exported):
    """Two servers over different weights never share cache entries:
    the key embeds model_fingerprint() (the PR-8 cache bugfix)."""
    from code2vec_tpu.serving.cache import cache_key
    model, art_dir, _ = exported
    code = "class A { int get() { return 1; } }"
    k_ckpt = cache_key(code, endpoint="predict", topk=10,
                       model=model.model_fingerprint())
    k_art = cache_key(code, endpoint="predict", topk=10,
                      model=f"artifact:deadbeefdeadbeef")
    assert k_ckpt != k_art
    # same fingerprint + reformatted source still hits
    assert cache_key("class A {\n  int get() {\n    return 1; } }",
                     endpoint="predict", topk=10,
                     model=model.model_fingerprint()) == k_ckpt


# ------------------------------- sub-byte / fp8 schemes (roofline PR)


roofline = pytest.mark.roofline


@roofline
@pytest.mark.parametrize("fmt,mbits,sub_half", [
    ("e4m3", 3, 2.0 ** -9),
    ("e5m2", 2, 2.0 ** -16),
])
def test_fp8_round_trip_error_bound(fmt, mbits, sub_half):
    """fp8 rounding is RELATIVE: err <= |w| * 2^-(mantissa+1) for
    normals, <= scale * half-subnormal-step near zero. All-zero rows
    reproduce exactly."""
    from code2vec_tpu.ops.quant import (
        dequantize_rows_fp8, quantize_rows_fp8,
    )
    rng = np.random.default_rng(5)
    t = (rng.standard_normal((200, 33))
         * rng.gamma(1.5, 2, (200, 1))).astype(np.float32)
    t[7] = 0
    q, s = quantize_rows_fp8(t, fmt)
    assert q.dtype == np.uint8 and q.shape == t.shape
    assert s.shape == (200, 1) and float(s[7, 0]) == 0.0
    r = dequantize_rows_fp8(q, s, fmt)
    err = np.abs(r - t)
    bound = np.maximum(np.abs(t) * 2.0 ** -(mbits + 1), s * sub_half)
    assert (err <= bound + 1e-12).all()
    assert (r[7] == 0).all()


@roofline
def test_fp8_rejects_unknown_format():
    from code2vec_tpu.ops.quant import quantize_rows_fp8
    with pytest.raises(ValueError, match="fp8 format"):
        quantize_rows_fp8(np.zeros((2, 2), np.float32), "e3m4")


@roofline
@pytest.mark.parametrize("d", [16, 33])   # even and odd widths
def test_int4_round_trip_error_bound_and_packing(d):
    """int4 worst-case round-trip error is s_r/2 (s_r = absmax/7); the
    payload is two nibbles per byte with odd widths padded by an
    encoded zero."""
    from code2vec_tpu.ops.quant import (
        dequantize_rows_int4, quantize_rows_int4, unpack_int4_host,
    )
    rng = np.random.default_rng(6)
    t = (rng.standard_normal((100, d))
         * rng.gamma(2, 1, (100, 1))).astype(np.float32)
    t[4] = 0
    q, s = quantize_rows_int4(t)
    assert q.dtype == np.uint8 and q.shape == (100, (d + 1) // 2)
    r = dequantize_rows_int4(q, s, d)
    assert (np.abs(r - t) <= s / 2 + 1e-9).all()
    assert (r[4] == 0).all()
    # nibble values stay in the signed [-7, 7] code book
    u = unpack_int4_host(q, d)
    assert u.min() >= -7 and u.max() <= 7
    # at production table widths the packed payload+scales are >= 1.8x
    # smaller than int8's (narrow test rows amortize the per-row scale
    # worse): 128-wide rows -> (128+4)/(64+4) = 1.94x
    assert (128 + 4) / ((128 + 1) // 2 + 4) >= 1.8


@roofline
def test_int4_device_gather_and_blockwise_match_dequantized():
    """The packed-gather + in-kernel unpack and the int4 blockwise
    top-k both equal the same ops over the host-dequantized table."""
    from code2vec_tpu.ops.quant import (
        dequant_gather_int4, dequantize_rows_int4, quantize_rows_int4,
    )
    from code2vec_tpu.ops.topk import (
        blockwise_matmul_top_k, gathered_label_logits,
    )
    rng = np.random.default_rng(7)
    v, d = 300, 24
    t = rng.standard_normal((v, d)).astype(np.float32)
    q, s = quantize_rows_int4(t)
    deq = dequantize_rows_int4(q, s, d)
    ids = jnp.asarray(rng.integers(0, v, (5, 4)))
    g = dequant_gather_int4(jnp.asarray(q), jnp.asarray(s), ids, d)
    np.testing.assert_allclose(np.asarray(g),
                               deq[np.asarray(ids)], rtol=1e-6)
    cv = jnp.asarray(rng.standard_normal((6, d)), jnp.float32)
    full = jnp.einsum("bd,vd->bv", cv, jnp.asarray(deq),
                      preferred_element_type=jnp.float32)
    fv, fi = jax.lax.top_k(full, 7)
    out = jax.jit(lambda c, tb, sc: blockwise_matmul_top_k(
        c, tb, 7, 64, scales=sc, int4_dim=d))(
        cv, jnp.asarray(q), jnp.asarray(s))
    np.testing.assert_array_equal(np.asarray(fi), np.asarray(out.indices))
    np.testing.assert_allclose(np.asarray(fv), np.asarray(out.values),
                               rtol=1e-6)
    labels = jnp.asarray(rng.integers(0, v, (6,)), jnp.int32)
    ll = gathered_label_logits(cv, jnp.asarray(q), labels,
                               scales=jnp.asarray(s), int4_dim=d)
    ref = np.einsum("bd,bd->b", np.asarray(cv),
                    deq[np.asarray(labels)])
    np.testing.assert_allclose(np.asarray(ll), ref, rtol=1e-5)


@roofline
@pytest.mark.parametrize("knob,scheme,dtype", [
    ("fp8_e4m3", "fp8_e4m3_rowwise", np.uint8),
    ("fp8_e5m2", "fp8_e5m2_rowwise", np.uint8),
    ("int4", "int4_rowwise_packed", np.uint8),
])
def test_scheme_artifact_round_trip(exported, tmp_path, knob, scheme,
                                    dtype):
    """Every sub-int8 scheme exports, validates on load, and its
    ReleaseModel step matches the fp32 release step over the
    host-dequantized tables (the fused dequant is where the bytes are
    saved, not where the math changes)."""
    from code2vec_tpu.ops import quant
    from code2vec_tpu.release.artifact import (
        export_artifact, load_artifact,
    )
    from code2vec_tpu.release.runtime import ReleaseModel
    model, _, _ = exported
    art_dir = str(tmp_path / f"art_{knob}")
    meta = export_artifact(model, art_dir, scheme=scheme, aot=False,
                           log=lambda m: None)
    assert meta["quantization"]["scheme"] == scheme
    art = load_artifact(art_dir)
    for name in ("token_embedding", "path_embedding",
                 "target_embedding"):
        assert art.tables[name].dtype == dtype
        assert art.tables[f"{name}.scale"].dtype == np.float32
    if knob == "int4":
        d = model.dims.token_dim
        assert art.tables["token_embedding"].shape[1] == (d + 1) // 2
        # >= 1.8x smaller than the int8 flavor of the same tables
        tb = meta["table_bytes"]
        int8_bytes = sum(
            np.asarray(jax.device_get(
                model.state.params[n])).size
            + 4 * model.state.params[n].shape[0]
            for n in ("token_embedding", "path_embedding",
                      "target_embedding"))
        assert int8_bytes / tb["artifact"] >= 1.8
    cfg = dataclasses.replace(model.config, train_data_path_prefix=None,
                              model_load_path=None,
                              serve_artifact=art_dir)
    rm = ReleaseModel(cfg, log=lambda m: None)
    arrays = _rand_batch_arrays(model, b=4)
    out = rm.eval_step(None, *arrays)
    assert np.isfinite(np.asarray(out.topk_values)).all()
    assert np.isfinite(float(out.loss_sum))
    # fp32 reference over explicitly dequantized tables: same math,
    # different byte layout
    fp32_dir = str(tmp_path / f"art_{knob}_fp32ref")
    export_artifact(model, fp32_dir, scheme="float32", aot=False,
                    log=lambda m: None)
    for name in ("token_embedding", "path_embedding",
                 "target_embedding"):
        q = np.load(os.path.join(art_dir, f"{name}.npy"))
        s = np.load(os.path.join(art_dir, f"{name}.scale.npy"))
        if knob == "int4":
            d = {"token_embedding": model.dims.token_dim,
                 "path_embedding": model.dims.path_dim,
                 "target_embedding": model.dims.code_dim
                 if hasattr(model.dims, "code_dim")
                 else model.dims.path_dim + 2 * model.dims.token_dim}[name]
            deq = quant.dequantize_rows_int4(q, s, d)
        else:
            fmt = "e4m3" if "e4m3" in knob else "e5m2"
            deq = quant.dequantize_rows_fp8(q, s, fmt)
        np.save(os.path.join(fp32_dir, f"{name}.npy"),
                deq.astype(np.float32))
    cfg_ref = dataclasses.replace(cfg, serve_artifact=fp32_dir)
    rm_ref = ReleaseModel(cfg_ref, log=lambda m: None)
    ref = rm_ref.eval_step(None, *arrays)
    np.testing.assert_array_equal(np.asarray(out.topk_indices),
                                  np.asarray(ref.topk_indices))
    np.testing.assert_allclose(np.asarray(out.topk_values),
                               np.asarray(ref.topk_values), rtol=1e-4,
                               atol=1e-5)


@roofline
def test_scheme_rejection_matrix(exported, tmp_path):
    """The loader's named-field validation across the new schemes: a
    tampered dtype, a truncated int4 payload, a missing scale file, an
    unknown scheme and an expect_scheme mismatch all fail naming the
    offending field."""
    import shutil

    from code2vec_tpu.release.artifact import (
        ArtifactError, export_artifact, load_artifact,
    )
    model, _, _ = exported
    base = str(tmp_path / "int4")
    export_artifact(model, base, scheme="int4_rowwise_packed", aot=False,
                    log=lambda m: None)

    def corrupt(name, fn):
        broken = str(tmp_path / f"broken_{np.random.randint(1 << 30)}")
        shutil.copytree(base, broken)
        fn(broken)
        return broken

    # int4 meta with an f32 payload -> dtype named
    b = corrupt("dtype", lambda d: np.save(
        os.path.join(d, "token_embedding.npy"),
        np.zeros_like(np.load(os.path.join(d, "token_embedding.npy")),
                      dtype=np.float32)))
    with pytest.raises(ArtifactError, match="token_embedding.dtype"):
        load_artifact(b)
    # truncated packed payload -> shape named (packed width checked)
    b = corrupt("shape", lambda d: np.save(
        os.path.join(d, "path_embedding.npy"),
        np.load(os.path.join(d, "path_embedding.npy"))[:, :-1]))
    with pytest.raises(ArtifactError, match="path_embedding.shape"):
        load_artifact(b)
    # missing scale -> scale named
    b = corrupt("scale", lambda d: os.remove(
        os.path.join(d, "target_embedding.scale.npy")))
    with pytest.raises(ArtifactError, match="target_embedding.scale"):
        load_artifact(b)
    # unknown scheme -> quantization.scheme named

    def bad_scheme(d):
        with open(os.path.join(d, "release_meta.json")) as f:
            meta = json.load(f)
        meta["quantization"]["scheme"] = "int2_hypothetical"
        with open(os.path.join(d, "release_meta.json"), "w") as f:
            json.dump(meta, f)

    b = corrupt("scheme", bad_scheme)
    with pytest.raises(ArtifactError, match="quantization.scheme"):
        load_artifact(b)
    # expect_scheme mismatch (an int8-only consumer handed int4)
    with pytest.raises(ArtifactError, match="quantization.scheme"):
        load_artifact(base, expect_scheme="int8_rowwise_symmetric")


@roofline
def test_release_scheme_knob_drives_export(exported, tmp_path):
    """config.release_scheme picks the scheme; --no_quantize still
    forces fp32 regardless of the knob."""
    from code2vec_tpu.release.artifact import export_artifact
    model, _, _ = exported
    cfg = dataclasses.replace(model.config, release_scheme="int4")
    old_cfg = model.config
    model.config = cfg
    try:
        meta = export_artifact(model, str(tmp_path / "a"), aot=False,
                               log=lambda m: None)
        assert meta["quantization"]["scheme"] == "int4_rowwise_packed"
        meta = export_artifact(model, str(tmp_path / "b"), aot=False,
                               quantize=False, log=lambda m: None)
        assert meta["quantization"]["scheme"] == "float32"
    finally:
        model.config = old_cfg


@roofline
def test_config_release_scheme_validation():
    with pytest.raises(ValueError, match="release_scheme"):
        Config(train_data_path_prefix="<t>",
               release_scheme="int2").verify()


# -------------------------------------- approximate-MIPS head pins


@roofline
@pytest.mark.parametrize("scheme", ["f32", "int8", "int4"])
def test_mips_full_probe_matches_blockwise_exact(scheme):
    """nprobe = nlist searches every row: the MIPS head must return the
    exact blockwise head's top-k (indices and values) for every table
    flavor."""
    from code2vec_tpu.ops import quant
    from code2vec_tpu.ops.topk import blockwise_matmul_top_k
    from code2vec_tpu.retrieval.mips import MipsHead
    rng = np.random.default_rng(11)
    v, d, b, k, real = 500, 24, 6, 7, 470
    t = rng.standard_normal((v, d)).astype(np.float32)
    cv = rng.standard_normal((b, d)).astype(np.float32)
    if scheme == "f32":
        head = MipsHead.build(t, None, real_vocab=real, nlist=16, seed=0)
        ref = blockwise_matmul_top_k(jnp.asarray(cv), jnp.asarray(t), k,
                                     128, valid_rows=real)
    elif scheme == "int8":
        q, s = quant.quantize_rows(t)
        head = MipsHead.build(q, s, real_vocab=real, nlist=16, seed=0)
        ref = blockwise_matmul_top_k(jnp.asarray(cv), jnp.asarray(q), k,
                                     128, scales=jnp.asarray(s),
                                     valid_rows=real)
    else:
        q, s = quant.quantize_rows_int4(t)
        head = MipsHead.build(q, s, real_vocab=real, int4_dim=d,
                              nlist=16, seed=0)
        ref = blockwise_matmul_top_k(jnp.asarray(cv), jnp.asarray(q), k,
                                     128, scales=jnp.asarray(s),
                                     valid_rows=real, int4_dim=d)
    vals, idx = head.search(cv, k, nprobe=head.nlist)
    np.testing.assert_array_equal(idx, np.asarray(ref.indices))
    np.testing.assert_allclose(vals, np.asarray(ref.values), rtol=1e-5)


@roofline
def test_mips_agreement_on_clustered_table():
    """On clustered data (what trained name embeddings look like,
    BENCH_RETRIEVAL.md) a small nprobe already recovers the exact
    top-1: agreement >= 0.95 at nprobe 4 of 20."""
    from code2vec_tpu.ops.topk import blockwise_matmul_top_k
    from code2vec_tpu.retrieval.mips import MipsHead
    rng = np.random.default_rng(12)
    centers = rng.standard_normal((20, 16)).astype(np.float32) * 4
    t = np.repeat(centers, 40, axis=0) + \
        rng.standard_normal((800, 16)).astype(np.float32) * 0.3
    queries = centers[rng.integers(0, 20, 50)] + \
        rng.standard_normal((50, 16)).astype(np.float32) * 0.3
    head = MipsHead.build(t, None, real_vocab=800, nlist=20, seed=0)
    _, approx = head.search(queries, 1, nprobe=4)
    exact = blockwise_matmul_top_k(jnp.asarray(queries), jnp.asarray(t),
                                   1, 256)
    agreement = float((approx[:, 0]
                       == np.asarray(exact.indices)[:, 0]).mean())
    assert agreement >= 0.95, agreement


@roofline
def test_release_model_mips_matches_exact_at_full_probe(exported,
                                                        tmp_path):
    """serve_mips_nprobe = nlist through the real ReleaseModel predict
    surface returns the exact model's predictions."""
    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, _ = exported
    lines = ["name|x1 tok1,p1,tok1 tok2,p2,tok2" + " " * 14,
             "name|x2 tok3,p3,tok3" + " " * 15]
    cfg = dataclasses.replace(model.config, train_data_path_prefix=None,
                              model_load_path=None,
                              serve_artifact=art_dir)
    exact = ReleaseModel(cfg, log=lambda m: None).predict(lines)
    cfg_mips = dataclasses.replace(cfg, serve_mips_nprobe=10_000,
                                   serve_mips_nlist=8)
    rm = ReleaseModel(cfg_mips, log=lambda m: None)
    assert rm.mips_head is not None
    # the dominant table is device-resident exactly once: the head
    # holds the reordered copy, the original-order table is never
    # transferred
    assert "target_embedding" not in rm.params
    assert "target_embedding_scale" not in rm.params
    approx = rm.predict(lines)
    for e, a in zip(exact, approx):
        assert e.topk_predicted_words == a.topk_predicted_words
        np.testing.assert_allclose(a.topk_predicted_words_scores,
                                   e.topk_predicted_words_scores,
                                   rtol=1e-4)


@roofline
def test_facade_mips_predict_matches_exact_at_full_probe(tmp_path):
    """The facade predict path honors serve_mips_nprobe too (serve
    --load without an artifact): full probe == exact facade predict."""
    (tmp_path / "exact").mkdir()
    (tmp_path / "mips").mkdir()
    model = _tiny_model(tmp_path / "exact")
    lines = ["name|x1 tok1,p1,tok1 tok2,p2,tok2" + " " * 14]
    exact = model.predict(lines)
    mips_model = _tiny_model(tmp_path / "mips", predict=True,
                             serve_mips_nprobe=10_000,
                             serve_mips_nlist=8)
    approx = mips_model.predict(lines)
    assert mips_model.mips_head is not None
    assert exact[0].topk_predicted_words == approx[0].topk_predicted_words


@roofline
def test_config_rejects_mips_misuse():
    with pytest.raises(ValueError, match="serve_mips_nprobe"):
        Config(train_data_path_prefix="<t>",
               serve_mips_nprobe=4).verify()     # neither serve nor predict
    with pytest.raises(ValueError, match="exact blockwise head"):
        Config(train_data_path_prefix="<t>", serve=True,
               test_data_path="x.c2v", serve_mips_nprobe=4).verify()
    Config(train_data_path_prefix="<t>", serve=True,
           serve_mips_nprobe=4).verify()


@roofline
def test_config_rejects_crossover_misuse():
    with pytest.raises(ValueError, match="serve_mips_crossover"):
        Config(train_data_path_prefix="<t>", serve=True,
               serve_mips_nprobe=4, serve_mips_crossover=-2).verify()
    with pytest.raises(ValueError, match="no MIPS head"):
        Config(train_data_path_prefix="<t>", serve=True,
               serve_mips_crossover=2).verify()  # nprobe unset
    Config(train_data_path_prefix="<t>", serve=True,
           serve_mips_nprobe=4, serve_mips_crossover=2).verify()
    # 0 (exact-only) is legal with or without a probe budget
    Config(train_data_path_prefix="<t>", serve=True,
           serve_mips_nprobe=4, serve_mips_crossover=0).verify()


@roofline
def test_release_hybrid_dispatch_parity_at_crossover(exported):
    """Per-batch-shape head dispatch at the crossover boundary: with
    --serve_mips_crossover 1 a single-row predict routes to the MIPS
    head compiled at the crossover shape while a bulk predict takes the
    exact blockwise head at the serve shape — and at full probe both
    sides of the boundary must agree with the exact-only model (the
    PR-14 agreement bar is exact equality at nprobe = nlist)."""
    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, meta = exported
    single = ["name|x1 tok1,p1,tok1 tok2,p2,tok2" + " " * 14]
    bulk = ["name|x1 tok1,p1,tok1" + " " * 15,
            "name|x2 tok3,p3,tok3" + " " * 15,
            "name|x3 tok1,p2,tok2" + " " * 15]
    cfg = dataclasses.replace(model.config, train_data_path_prefix=None,
                              model_load_path=None,
                              serve_artifact=art_dir)
    exact = ReleaseModel(cfg, log=lambda m: None)
    hybrid_cfg = dataclasses.replace(cfg, serve_mips_nprobe=10_000,
                                     serve_mips_nlist=8,
                                     serve_mips_crossover=1)
    rm = ReleaseModel(hybrid_cfg, log=lambda m: None)
    assert rm.mips_rows == 1 and not rm._mips_all
    # hybrid keeps the original-order table device-resident: the exact
    # head serves every bulk batch (all-MIPS skips it)
    assert "target_embedding" in rm.params
    for mine, ref in zip(rm.predict(single), exact.predict(single)):
        assert mine.topk_predicted_words == ref.topk_predicted_words
        np.testing.assert_allclose(mine.topk_predicted_words_scores,
                                   ref.topk_predicted_words_scores,
                                   rtol=1e-4)
    # the single row compiled/ran the MIPS step at the crossover shape,
    # cached apart from the exact serve-shape steps
    assert rm._mips_predict_steps and \
        all(rows == 1 for rows, _ in rm._mips_predict_steps)
    for mine, ref in zip(rm.predict(bulk), exact.predict(bulk)):
        assert mine.topk_predicted_words == ref.topk_predicted_words
        np.testing.assert_allclose(mine.topk_predicted_words_scores,
                                   ref.topk_predicted_words_scores,
                                   rtol=1e-4)
    assert all(rows == int(meta["serve_batch_size"])
               for rows, _ in rm._predict_steps)


@roofline
def test_release_crossover_zero_restores_exact_bitforbit(exported):
    """--serve_mips_crossover 0 with a probe budget set must be
    bit-for-bit the nprobe=0 path: no head built, no reordered device
    copy, byte-identical scores."""
    from code2vec_tpu.release.runtime import ReleaseModel
    model, art_dir, _ = exported
    lines = ["name|x1 tok1,p1,tok1 tok2,p2,tok2" + " " * 14,
             "name|x2 tok3,p3,tok3" + " " * 15]
    cfg = dataclasses.replace(model.config, train_data_path_prefix=None,
                              model_load_path=None,
                              serve_artifact=art_dir)
    exact = ReleaseModel(cfg, log=lambda m: None)
    off = dataclasses.replace(cfg, serve_mips_nprobe=4,
                              serve_mips_nlist=8, serve_mips_crossover=0)
    rm = ReleaseModel(off, log=lambda m: None)
    assert rm.mips_head is None and rm._mips_step is None
    assert rm.mips_rows == 0 and not rm._mips_all
    assert "target_embedding" in rm.params
    for mine, ref in zip(rm.predict(lines), exact.predict(lines)):
        assert mine.topk_predicted_words == ref.topk_predicted_words
        np.testing.assert_array_equal(
            np.asarray(mine.topk_predicted_words_scores),
            np.asarray(ref.topk_predicted_words_scores))


@roofline
def test_export_calibration_records_crossover(tmp_path):
    """An exporter configured with a MIPS head runs the head-crossover
    calibration pass: meta gains mips_crossover (largest MIPS-winning
    row count) + the timing table, on disk and in the returned dict —
    and the content fingerprint is unchanged vs an uncalibrated export
    of the same tables (the fingerprint core excludes calibration)."""
    from code2vec_tpu.release.artifact import export_artifact
    model = _tiny_model(tmp_path)
    plain = export_artifact(model, str(tmp_path / "plain"), aot=False,
                            log=lambda m: None)
    assert "mips_crossover" not in plain
    old_cfg = model.config
    model.config = dataclasses.replace(old_cfg, serve_mips_nprobe=4,
                                       serve_mips_nlist=4)
    try:
        cal = export_artifact(model, str(tmp_path / "cal"), aot=False,
                              log=lambda m: None)
    finally:
        model.config = old_cfg
    assert isinstance(cal["mips_crossover"], int)
    assert 0 <= cal["mips_crossover"] <= int(cal["serve_batch_size"])
    assert cal["mips_calibration"]
    for timing in cal["mips_calibration"].values():
        assert set(timing) == {"exact", "mips"}
    assert cal["fingerprint"] == plain["fingerprint"]
    with open(os.path.join(tmp_path, "cal", "release_meta.json")) as f:
        on_disk = json.load(f)
    assert on_disk["mips_crossover"] == cal["mips_crossover"]
