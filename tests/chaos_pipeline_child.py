"""Pipeline-supervisor child for the pipeline chaos suite
(tests/test_pipeline.py).

Runs the REAL PipelineSupervisor (manifest, journal, fault points,
terminal verdicts) over cheap scripted stage bodies, so the
SIGKILL-at-every-boundary drill runs in milliseconds per attempt: the
parent arms `C2V_FAULTS=pipeline_stage@N=exit` in the environment,
this process dies with the distinctive fault exit code mid-machine,
and the rerun must resume from the last committed stage.

Each stage body appends one `<stage>` line to `LEDGER` (append-mode —
survives the kill) and writes a deterministic `out-<stage>.txt` into
the run dir, so the parent can prove (a) committed stages never re-ran
and (b) every kill matrix converges to the same terminal manifest.

Usage: python tests/chaos_pipeline_child.py PIPELINE_DIR LEDGER
"""

import os
import sys

os.environ.setdefault("C2V_HOST_WORKER", "1")  # no jax in the drill

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


def main() -> int:
    pipeline_dir, ledger = sys.argv[1], sys.argv[2]

    from code2vec_tpu.config import Config
    from code2vec_tpu.pipeline.supervisor import PipelineSupervisor
    from code2vec_tpu.utils.faults import fault_point

    def stage(name, extra_fault=None):
        def body(ctx):
            if extra_fault:
                fault_point(extra_fault)
            with open(ledger, "a") as f:
                f.write(name + "\n")
            out = os.path.join(ctx.run_dir, f"out-{name}.txt")
            tmp = out + ".tmp"
            with open(tmp, "w") as f:
                f.write(f"{name}: deterministic output\n")
            os.replace(tmp, out)
            return {"stage": name, "out": out}
        return (name, body)

    stages = [
        stage("ingest"),
        stage("finetune"),
        stage("export"),
        stage("shadow_eval", extra_fault="shadow_eval"),
        stage("promote", extra_fault="promote"),
        stage("retrieval_refresh"),
    ]
    config = Config(pipeline=True, pipeline_dir=pipeline_dir,
                    verbose_mode=0)
    supervisor = PipelineSupervisor(config, stages=stages,
                                    log=lambda m: None)
    return supervisor.run()


if __name__ == "__main__":
    sys.exit(main())
