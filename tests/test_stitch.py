"""Cross-process trace stitching (obs/stitch.py): span files from
several processes — each on its own perf_counter epoch and pid — come
back as ONE wall-clock-rebased Chrome trace for a trace id, with the
coalesced batch span shared into every member's trace and torn files
skipped, not fatal."""

import json
import os

import pytest

from code2vec_tpu.obs import stitch

TID = "a" * 32
OTHER = "b" * 32


def _trace_file(path, epoch_s, events, producer="proc"):
    payload = {
        "traceEvents": [
            {"name": "process_name", "ph": "M", "pid": 1,
             "args": {"name": producer}},
            {"name": "thread_name", "ph": "M", "pid": 1, "tid": 7,
             "args": {"name": "worker"}},
        ] + events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_epoch_unix_s": epoch_s,
                      "producer": producer},
    }
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(payload, f)


def _span(name, ts_us, dur_us, trace_id=TID, tid=7, **attrs):
    return {"name": name, "ph": "X", "ts": ts_us, "dur": dur_us,
            "pid": 1, "tid": tid,
            "args": dict({"trace_id": trace_id, "span_id": "s" + name,
                          "parent_id": None}, **attrs)}


def test_stitch_rebases_onto_one_wall_clock_axis(tmp_path):
    root = str(tmp_path)
    # the router booted at epoch 1000 and forwarded at its local 5ms
    # (wall 1000.005..1000.015); the replica booted at epoch 1000.006
    # and handled at its local 1ms (wall 1000.007..1000.011) — on the
    # wall clock the forward CONTAINS the handler
    _trace_file(os.path.join(root, "router.trace.json"), 1000.0,
                [_span("router.forward /predict", 5_000, 10_000),
                 _span("noise", 0, 1, trace_id=OTHER)],
                producer="router")
    _trace_file(os.path.join(root, "run", "replica0.trace.json"),
                1000.006,
                [_span("request", 1_000, 4_000)], producer="replica")
    out = stitch.stitch_dir(root, TID)
    spans = [ev for ev in out["traceEvents"] if ev["ph"] == "X"]
    assert [s["name"] for s in spans] == ["router.forward /predict",
                                         "request"]
    fwd, req = spans
    # rebased: ts is wall-clock microseconds, and the hop nests
    assert fwd["ts"] == pytest.approx(1000.0 * 1e6 + 5_000)
    assert req["ts"] == pytest.approx(1000.006 * 1e6 + 1_000)
    assert fwd["ts"] <= req["ts"]
    assert req["ts"] + req["dur"] <= fwd["ts"] + fwd["dur"]
    # one display lane per source file, labeled file · producer
    assert fwd["pid"] != req["pid"]
    names = {ev["args"]["name"] for ev in out["traceEvents"]
             if ev["ph"] == "M" and ev["name"] == "process_name"}
    assert "router.trace.json · router" in names
    assert os.path.join("run", "replica0.trace.json") + " · replica" \
        in names
    other = out["otherData"]
    assert other["trace_id"] == TID and other["spans"] == 2
    assert {s["file"]: s["spans"] for s in other["sources"]} == {
        "router.trace.json": 1,
        os.path.join("run", "replica0.trace.json"): 1}


def test_batch_span_is_shared_into_member_traces(tmp_path):
    # the batcher records the coalesced device batch ONCE, with no
    # trace id of its own — only the member list. It must appear in
    # EVERY member's stitched trace.
    root = str(tmp_path)
    batch = {"name": "serving_batch", "ph": "X", "ts": 10, "dur": 5,
             "pid": 1, "tid": 7,
             "args": {"span_id": "sb", "parent_id": None,
                      "member_trace_ids": [TID, OTHER]}}
    _trace_file(os.path.join(root, "replica0.trace.json"), 0.0,
                [_span("request", 0, 20), batch])
    for tid in (TID, OTHER):
        out = stitch.stitch_dir(root, tid)
        kept = {ev["name"] for ev in out["traceEvents"]
                if ev["ph"] == "X"}
        assert "serving_batch" in kept
    assert stitch.stitch_dir(root, "c" * 32)["otherData"]["spans"] == 0


def test_torn_and_foreign_files_are_skipped_not_fatal(tmp_path):
    root = str(tmp_path)
    _trace_file(os.path.join(root, "ok.trace.json"), 0.0,
                [_span("request", 0, 1)])
    with open(os.path.join(root, "torn.trace.json"), "w") as f:
        f.write('{"traceEvents": [half')
    with open(os.path.join(root, "foreign.trace.json"), "w") as f:
        json.dump({"not": "a trace"}, f)
    out = stitch.stitch_dir(root, TID)
    assert out["otherData"]["spans"] == 1
    by_file = {s["file"]: s for s in out["otherData"]["sources"]}
    assert by_file["torn.trace.json"]["error"] == "unreadable or torn"
    assert by_file["foreign.trace.json"]["spans"] == 0
    # a heartbeat json next to the traces is not a trace file at all
    with open(os.path.join(root, "heartbeat.json"), "w") as f:
        f.write("{}")
    assert [os.path.basename(p) for p in stitch.trace_files(root)] == [
        "foreign.trace.json", "ok.trace.json", "torn.trace.json"]


def test_stitch_main_offline_dir_mode(tmp_path, capsys):
    root = str(tmp_path)
    _trace_file(os.path.join(root, "router.trace.json"), 0.0,
                [_span("router.forward /predict", 0, 10)])

    class Cfg:
        fleet_trace_id = TID
        fleet_trace_dir = root
        fleet_control = ""

    assert stitch.stitch_main(Cfg()) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["otherData"]["spans"] == 1
    # unknown id: still a valid (empty) trace, rc 1 so scripts notice
    Cfg.fleet_trace_id = "d" * 32
    assert stitch.stitch_main(Cfg()) == 1
    # neither a dir nor a control plane: usage error
    Cfg.fleet_trace_dir = ""
    assert stitch.stitch_main(Cfg()) == 2
