"""Tier-1 gate for scripts/check_slo_doc.py: every SLO objective the
engine declares (obs/slo.py objectives_from_config) must have a row in
the README SLO reference table and vice versa, and every BURN_WINDOWS
severity must be mentioned in the marked section — a new objective
cannot ship undocumented, and the table cannot keep objectives the
engine dropped."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO_ROOT, "scripts", "check_slo_doc.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_slo_doc",
                                                  CHECKER)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_declared_slo_is_documented_and_vice_versa():
    checker = _load_checker()
    problems = checker.check()
    assert problems == [], "\n".join(problems)


def test_checker_extracts_the_objective_and_severity_sets():
    """The AST walk must actually see the engine: the two shipped
    objectives and the two burn severities, so a silently-broken walk
    cannot turn the doc check vacuous."""
    checker = _load_checker()
    assert {"availability", "latency"} <= checker.declared_slos()
    assert checker.declared_severities() == {"page", "ticket"}


def test_checker_flags_undocumented_stale_and_missing_severity(
        tmp_path, monkeypatch):
    """The check fails in all three directions: a declared-but-
    undocumented objective, a documented-but-undeclared one, and a
    burn severity absent from the section."""
    checker = _load_checker()
    readme = tmp_path / "README.md"
    readme.write_text(
        "# x\n<!-- slo-table:begin -->\n"
        "| `availability` | x | x |\n"
        "| `made_up_slo` | x | x |\n"
        "severities: `ticket`\n"
        "<!-- slo-table:end -->\n")
    monkeypatch.setattr(checker, "README", str(readme))
    problems = checker.check()
    assert any("UNDOCUMENTED: SLO 'latency'" in p for p in problems)
    assert any("STALE DOC: SLO 'made_up_slo'" in p for p in problems)
    assert any("severity 'page'" in p for p in problems)


def test_checker_rejects_non_literal_objective_names(tmp_path,
                                                     monkeypatch):
    import pytest

    checker = _load_checker()
    slo = tmp_path / "slo.py"
    slo.write_text('name = "dyn"\nSloObjective(name=name, target=0.9)\n')
    monkeypatch.setattr(checker, "SLO_PATH", str(slo))
    with pytest.raises(SystemExit, match="non-literal"):
        checker.declared_slos()
