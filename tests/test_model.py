"""Model math tests: attention/softmax numerics vs hand-computed numpy
(the spec is tensorflow_model.py:235-264)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.ops.attention import masked_single_query_attention


def _numpy_reference_forward(params, src, pth, tgt, mask):
    """Direct numpy transcription of the reference math
    (tensorflow_model.py:237-262), no dropout."""
    tok = params["token_embedding"]
    path = params["path_embedding"]
    ctx = np.concatenate([tok[src], path[pth], tok[tgt]], axis=-1)
    transformed = np.tanh(ctx @ params["transform"])
    scores = transformed @ params["attention"][:, 0]
    scores = scores + np.log(mask)          # log(0) = -inf on invalid
    scores = scores - scores.max(axis=1, keepdims=True)
    e = np.exp(scores)
    attn = e / e.sum(axis=1, keepdims=True)
    code = (transformed * attn[..., None]).sum(axis=1)
    logits = code @ params["target_embedding"].T
    return code, attn, logits


@pytest.fixture
def small_module_and_params():
    dims = ModelDims(token_vocab_size=11, path_vocab_size=7,
                     target_vocab_size=5, token_dim=4, path_dim=4)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    dummy = jnp.zeros((1, 1), jnp.int32)
    params = module.init({"params": rng}, dummy, dummy, dummy,
                         jnp.zeros((1, 1)))["params"]
    return module, params


def test_forward_matches_numpy_reference(small_module_and_params):
    module, params = small_module_and_params
    rng = np.random.default_rng(0)
    B, M = 3, 6
    src = rng.integers(0, 11, (B, M)).astype(np.int32)
    pth = rng.integers(0, 7, (B, M)).astype(np.int32)
    tgt = rng.integers(0, 11, (B, M)).astype(np.int32)
    mask = (rng.random((B, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0  # every row has a valid context

    logits, code, attn = module.apply({"params": params}, src, pth, tgt, mask,
                                      deterministic=True)
    np_params = jax.tree.map(np.asarray, params)
    ref_code, ref_attn, ref_logits = _numpy_reference_forward(
        np_params, src, pth, tgt, mask)

    np.testing.assert_allclose(np.asarray(code), ref_code, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(attn), ref_attn, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(logits), ref_logits, rtol=1e-4, atol=1e-4)


def test_attention_invalid_contexts_get_zero_weight():
    B, M, D = 2, 4, 3
    transformed = jnp.ones((B, M, D))
    att = jnp.ones((D,))
    mask = jnp.array([[1, 1, 0, 0], [1, 0, 0, 0]], jnp.float32)
    code, attn = masked_single_query_attention(transformed, att, mask)
    np.testing.assert_allclose(np.asarray(attn[0]), [0.5, 0.5, 0, 0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(attn[1]), [1, 0, 0, 0], atol=1e-6)
    np.testing.assert_allclose(np.asarray(code), np.ones((B, D)), atol=1e-6)


def test_attention_all_invalid_row_is_finite():
    # Padded eval rows have no valid context; weights must be 0 (not NaN)
    # so downstream psums stay finite.
    transformed = jnp.ones((1, 4, 3))
    mask = jnp.zeros((1, 4), jnp.float32)
    code, attn = masked_single_query_attention(transformed, jnp.ones((3,)), mask)
    assert np.isfinite(np.asarray(attn)).all()
    np.testing.assert_allclose(np.asarray(attn), 0.0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(code), 0.0, atol=1e-6)


def test_dropout_scales_and_zeroes(small_module_and_params):
    module, params = small_module_and_params
    B, M = 2, 5
    src = np.zeros((B, M), np.int32)
    pth = np.zeros((B, M), np.int32)
    tgt = np.zeros((B, M), np.int32)
    mask = np.ones((B, M), np.float32)
    out1 = module.apply({"params": params}, src, pth, tgt, mask,
                        deterministic=False,
                        rngs={"dropout": jax.random.PRNGKey(1)})
    out2 = module.apply({"params": params}, src, pth, tgt, mask,
                        deterministic=True)
    # stochastic forward differs from deterministic one
    assert not np.allclose(np.asarray(out1[0]), np.asarray(out2[0]))


def test_padded_target_dims_mask_logits():
    dims = ModelDims(token_vocab_size=8, path_vocab_size=8,
                     target_vocab_size=8, token_dim=4, path_dim=4,
                     real_target_vocab_size=5)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32)
    rng = jax.random.PRNGKey(0)
    dummy = jnp.zeros((1, 2), jnp.int32)
    params = module.init({"params": rng}, dummy, dummy, dummy,
                         jnp.ones((1, 2)))["params"]
    logits, _, _ = module.apply({"params": params}, dummy, dummy, dummy,
                                jnp.ones((1, 2)), deterministic=True)
    assert np.asarray(logits)[:, 5:].max() == -np.inf
    assert np.isfinite(np.asarray(logits)[:, :5]).all()


def test_padded_to_rounds_up():
    dims = ModelDims(token_vocab_size=10, path_vocab_size=9,
                     target_vocab_size=7, token_dim=4, path_dim=4)
    p = dims.padded_to(4)
    assert (p.token_vocab_size, p.path_vocab_size, p.target_vocab_size) == (12, 12, 8)
    assert p.real_target_vocab_size == 7
    assert p.has_padded_targets
