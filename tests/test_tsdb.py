"""Telemetry history store (obs/tsdb.py) + SLO burn-rate engine
(obs/slo.py): segment-ring crash safety (torn segments skipped with a
counter, never a 500), retention/size sweep bounds, query-equals-replay
after restart, reset-aware range queries, and multi-window burn-rate
alert semantics (latching, page -> flight dump, no fleet stop)."""

import json
import math
import os

import pytest

from code2vec_tpu.obs import slo as slo_mod
from code2vec_tpu.obs import tsdb as tsdb_mod
from code2vec_tpu.obs.slo import SloEngine, SloObjective, count_below, \
    objectives_from_config
from code2vec_tpu.obs.tsdb import TsdbStore
from code2vec_tpu.serving import telemetry


def _requests_text(by_status, endpoint="/predict"):
    lines = ["# TYPE serving_requests_total counter"]
    for status, n in sorted(by_status.items()):
        lines.append(
            f'serving_requests_total{{endpoint="{endpoint}",'
            f'status="{status}"}} {n}')
    return "\n".join(lines) + "\n"


def _latency_text(buckets, phase="total"):
    lines = ["# TYPE serving_request_seconds histogram"]
    for le, n in buckets.items():
        lines.append(
            f'serving_request_seconds_bucket{{le="{le}",'
            f'phase="{phase}"}} {n}')
    return "\n".join(lines) + "\n"


def _store(tmp_path, **kw):
    kw.setdefault("retention_s", 3600.0)
    kw.setdefault("max_mb", 64.0)
    return TsdbStore(str(tmp_path / "tsdb"), **kw)


# ----------------------------------------------------------- queries


def test_increase_rate_and_by_status_across_sources(tmp_path):
    store = _store(tmp_path)
    for i, t in enumerate((100.0, 110.0, 120.0)):
        store.append({
            "host:a": _requests_text({"200": 10.0 * (i + 1),
                                      "500": 1.0 * i}),
            "host:b": _requests_text({"200": 5.0 * (i + 1)}),
        }, now=t)
    # summed across sources; window defaults `now` to the last tick
    assert store.increase("serving_requests_total",
                          window_s=30.0) == pytest.approx(30.0 + 2.0)
    assert store.rate("serving_requests_total",
                      window_s=30.0) == pytest.approx(32.0 / 20.0)
    by = store.increase_by("serving_requests_total", "status",
                           window_s=30.0)
    assert by == {"200": pytest.approx(30.0), "500": pytest.approx(2.0)}
    # per-source filter
    assert store.increase("serving_requests_total", window_s=30.0,
                          source="host:b") == pytest.approx(10.0)
    # label filter falls through to the sample labels
    assert store.increase("serving_requests_total", window_s=30.0,
                          status="500") == pytest.approx(2.0)


def test_counter_reset_mid_window_counts_restart_in_full(tmp_path):
    store = _store(tmp_path)
    for t, v in ((100.0, 50.0), (110.0, 60.0), (120.0, 4.0)):
        store.append({"host:a": _requests_text({"200": v})}, now=t)
    # 50 -> 60 (+10) then restart to 4 (+4), never negative
    assert store.increase("serving_requests_total",
                          window_s=30.0) == pytest.approx(14.0)


def test_windowed_quantile_and_buckets(tmp_path):
    store = _store(tmp_path)
    store.append({"host:a": _latency_text(
        {"0.1": 0.0, "0.5": 0.0, "+Inf": 0.0})}, now=100.0)
    store.append({"host:a": _latency_text(
        {"0.1": 90.0, "0.5": 99.0, "+Inf": 100.0})}, now=110.0)
    buckets = store.window_buckets("serving_request_seconds",
                                   window_s=30.0, phase="total")
    assert buckets == {"0.1": pytest.approx(90.0),
                       "0.5": pytest.approx(99.0),
                       "+Inf": pytest.approx(100.0)}
    p50 = store.quantile("serving_request_seconds", 0.5,
                         window_s=30.0, phase="total")
    assert p50 is not None and p50 <= 0.1
    # empty window holds no samples
    assert store.quantile("serving_request_seconds", 0.5,
                          window_s=30.0, now=10.0,
                          phase="total") is None


def test_quantile_from_buckets_inf_only_mass_is_inf():
    # the hardened central helper: a histogram whose only populated
    # bucket is +Inf has no finite bound — the honest read is +inf
    # (trips any threshold), not None and not a made-up number
    assert telemetry.quantile_from_buckets(
        {"+Inf": 10.0}, None, 0.5) == math.inf
    assert telemetry.quantile_from_buckets({}, None, 0.5) is None


def test_query_range_ops_and_validation(tmp_path):
    store = _store(tmp_path)
    store.append({"host:a": _requests_text({"200": 0.0})}, now=100.0)
    store.append({"host:a": _requests_text({"200": 30.0})}, now=130.0)
    out = store.query_range({"op": "increase",
                             "name": "serving_requests_total",
                             "window": "60", "status": "200"})
    assert out["value"] == pytest.approx(30.0)
    assert out["labels"] == {"status": "200"}
    out = store.query_range({"op": "rate",
                             "name": "serving_requests_total"})
    assert out["value"] == pytest.approx(1.0)
    stats = store.query_range({"op": "stats"})["stats"]
    assert stats["ticks"] == 2 and stats["torn_segments"] == 0
    for bad in ({"op": "nope", "name": "x"},
                {"op": "rate"},  # no name
                {"op": "rate", "name": "x", "window": "abc"},
                {"op": "quantile", "name": "x", "q": "abc"}):
        with pytest.raises(ValueError):
            store.query_range(bad)


# ------------------------------------------------- crash-safe ring


def test_segments_seal_and_query_equals_replay(tmp_path):
    """The replay pin: reopen the dir cold and the SAME query returns
    the SAME number (burn rates are reproducible after a control-plane
    restart)."""
    store = _store(tmp_path, ticks_per_segment=3)
    for i in range(8):
        store.append({"host:a": _requests_text(
            {"200": 10.0 * i, "500": float(i)})}, now=100.0 + 10.0 * i)
    segs = [p for _, p in store._segment_files()]
    assert len(segs) == 3  # 3 + 3 + 2-tick head
    want = store.increase("serving_requests_total", window_s=1000.0)
    by_want = store.increase_by("serving_requests_total", "status",
                                window_s=1000.0)
    reopened = TsdbStore(store.dir)
    assert reopened.stats()["ticks"] == 8
    assert reopened.increase("serving_requests_total",
                             window_s=1000.0) == pytest.approx(want)
    assert reopened.increase_by(
        "serving_requests_total", "status", window_s=1000.0
    ) == {k: pytest.approx(v) for k, v in by_want.items()}


def test_kill_at_every_boundary_never_500s(tmp_path):
    """Crash drill: after every append, take the on-disk state as a
    kill point, additionally tear the newest segment (truncate) or
    drop in a stale tmp file, and prove a cold reopen (a) never
    raises, (b) skips the torn segment with the counter, (c) still
    answers queries from the surviving ticks."""
    import shutil

    src = _store(tmp_path, ticks_per_segment=2)
    kill_points = []
    for i in range(5):
        src.append({"host:a": _requests_text({"200": float(i)})},
                   now=100.0 + i)
        point = tmp_path / f"kill{i}"
        shutil.copytree(src.dir, str(point))
        kill_points.append((i, point))
    for i, point in kill_points:
        # clean kill: rename is atomic, every appended tick survives
        store = TsdbStore(str(point))
        assert store.stats()["ticks"] == i + 1
        assert store.torn_segments == 0
        store.query_range({"op": "rate",
                           "name": "serving_requests_total"})
        # torn newest segment (half a write that dodged the rename
        # protocol, e.g. disk corruption): skipped + counted, older
        # sealed segments still serve
        segs = sorted(p for p in os.listdir(str(point))
                      if p.startswith("seg-"))
        newest = os.path.join(str(point), segs[-1])
        with open(newest, "r+") as f:
            body = f.read()
            f.seek(0)
            f.truncate()
            f.write(body[:max(1, len(body) // 2)])
        # plus a stale tmp file from a kill mid-write
        with open(os.path.join(str(point),
                               "seg-99999999.json.tmp-123"), "w") as f:
            f.write("{half")
        store = TsdbStore(str(point))
        assert store.torn_segments == 1
        assert store.stats()["torn_segments"] == 1
        # the torn segment's ticks are lost; every sealed one survives
        assert store.stats()["ticks"] == (i + 1) - (i % 2 + 1)
        store.query_range({"op": "increase",
                           "name": "serving_requests_total"})
        # the stale tmp file was swept
        assert not [p for p in os.listdir(str(point)) if ".tmp-" in p]


def test_foreign_and_schema_torn_segments_are_skipped(tmp_path):
    store = _store(tmp_path)
    store.append({"host:a": _requests_text({"200": 1.0})}, now=100.0)
    # foreign format marker
    with open(os.path.join(store.dir, "seg-00000099.json"), "w") as f:
        json.dump({"format": "someone-elses", "ticks": []}, f)
    # not even JSON
    with open(os.path.join(store.dir, "seg-00000098.json"), "w") as f:
        f.write("not json")
    # foreign NAME is not a segment at all — untouched, uncounted
    with open(os.path.join(store.dir, "notes.json"), "w") as f:
        f.write("keep me")
    reopened = TsdbStore(store.dir)
    assert reopened.torn_segments == 2
    assert reopened.stats()["ticks"] == 1
    assert os.path.exists(os.path.join(store.dir, "notes.json"))


def test_retention_sweep_prunes_old_sealed_segments(tmp_path):
    store = _store(tmp_path, retention_s=50.0, ticks_per_segment=2)
    for i in range(6):
        store.append({"host:a": _requests_text({"200": float(i)})},
                     now=100.0 + 20.0 * i)
    # now=200; cutoff=150 — ticks 100,120,140 (the first two sealed
    # segments' newest ticks are 120 and 160) -> first segment pruned
    stats = store.stats()
    assert stats["oldest_ts"] >= 150.0
    files = store._segment_files()
    assert all(seq >= 2 for seq, _ in files)
    # in-memory window agrees with the sweep
    assert store.series_len("serving_requests_total",
                            window_s=1e9) == 3


def test_size_sweep_evicts_oldest_but_never_the_head(tmp_path):
    store = _store(tmp_path, max_mb=0.0005, ticks_per_segment=1)
    pruned0 = tsdb_mod._c_pruned("size").value
    for i in range(20):
        store.append({"host:a": _requests_text({"200": float(i)})},
                     now=100.0 + i)
    files = store._segment_files()
    assert files, "the head segment must never be evicted"
    assert store._disk_bytes() <= 2 * store.max_bytes
    assert tsdb_mod._c_pruned("size").value > pruned0
    # newest segments survive, oldest were evicted (the head seals
    # the moment it fills at ticks_per_segment=1, so the newest FILE
    # is the just-sealed predecessor of the empty head sequence)
    assert files[-1][0] >= store._head_seq - 1
    assert files[0][0] > 1


# -------------------------------------------------------- SLO engine


class _Flight:
    def __init__(self):
        self.incidents = []

    def incident(self, reason, immediate=False, **detail):
        self.incidents.append((reason, immediate, detail))


def _slo_tsdb(tmp_path, by_status_per_tick):
    store = _store(tmp_path)
    for i, by_status in enumerate(by_status_per_tick):
        store.append({"host:a": _requests_text(by_status)},
                     now=100.0 + 10.0 * i)
    return store


def test_slo_healthy_traffic_fires_nothing(tmp_path):
    store = _slo_tsdb(tmp_path, [{"200": 100.0 * i} for i in range(4)])
    flight = _Flight()
    engine = SloEngine([SloObjective("availability", "availability",
                                     0.99)], flight=flight)
    results = engine.evaluate(store)
    (avail,) = results
    assert avail["slo"] == "availability"
    assert avail["error_budget_remaining"] == pytest.approx(1.0)
    assert all(not a["firing"] for a in avail["alerts"])
    assert flight.incidents == []
    assert engine.status()["objectives"] == results


def test_slo_burn_pages_dumps_flight_and_latches(tmp_path):
    # every request 5xx: error ratio 1.0 / budget 0.01 = 100x burn —
    # over page (14.4x) AND ticket (6x) on both windows
    store = _slo_tsdb(tmp_path,
                      [{"500": 50.0 * i} for i in range(4)])
    flight = _Flight()
    logs = []
    alerts0 = slo_mod._c_alerts("availability", "page").value
    # 10s-apart ticks sit inside even the 5m short window at scale 1
    engine = SloEngine([SloObjective("availability", "availability",
                                     0.99)],
                       flight=flight, log=logs.append)
    (avail,) = engine.evaluate(store)
    assert avail["error_budget_remaining"] < 0  # blown
    by_sev = {a["severity"]: a for a in avail["alerts"]}
    assert by_sev["page"]["firing"] and by_sev["ticket"]["firing"]
    assert by_sev["page"]["burn_long"] == pytest.approx(100.0)
    # page dumps the flight ring immediately — and ONLY page
    assert [(r, imm) for r, imm, _ in flight.incidents] \
        == [("slo_burn", True)]
    assert flight.incidents[0][2]["severity"] == "page"
    # latching: a second burning tick is the SAME alert
    engine.evaluate(store)
    assert slo_mod._c_alerts("availability",
                             "page").value == alerts0 + 1
    assert len(flight.incidents) == 1
    assert len([m for m in logs if "page burn alert" in m]) == 1
    # recovery resets the latch; a fresh burn counts again
    healthy = _slo_tsdb(tmp_path / "h",
                        [{"200": 100.0 * i} for i in range(4)])
    engine.evaluate(healthy)
    engine.evaluate(store)
    assert slo_mod._c_alerts("availability",
                             "page").value == alerts0 + 2


def test_slo_latency_objective_reads_windowed_buckets(tmp_path):
    store = _store(tmp_path)
    store.append({"host:a": _latency_text(
        {"0.1": 0.0, "+Inf": 0.0})}, now=100.0)
    # 95% of requests over the 100ms threshold
    store.append({"host:a": _latency_text(
        {"0.1": 5.0, "+Inf": 100.0})}, now=110.0)
    flight = _Flight()
    # budget 0.05, error ratio 0.95 -> 19x burn, over the 14.4x page bar
    engine = SloEngine([SloObjective("latency", "latency", 0.95,
                                     threshold_ms=100.0)],
                       flight=flight)
    (lat,) = engine.evaluate(store)
    assert lat["threshold_ms"] == 100.0
    assert {a["severity"] for a in lat["alerts"]
            if a["firing"]} == {"page", "ticket"}
    assert flight.incidents
    # no traffic burns no budget
    empty = _store(tmp_path / "e")
    (lat,) = engine.evaluate(empty)
    assert lat["error_budget_remaining"] == pytest.approx(1.0)


def test_count_below_edges():
    buckets = {"0.1": 90.0, "0.5": 99.0, "+Inf": 100.0}
    assert count_below(buckets, 0.1) == pytest.approx(90.0)
    # interpolates inside a finite span
    assert count_below(buckets, 0.3) == pytest.approx(94.5)
    # the +Inf mass is never provably good
    assert count_below(buckets, 10.0) == pytest.approx(99.0)
    assert count_below({}, 0.1) == 0.0
    assert count_below({"+Inf": 10.0}, 0.1) == 0.0


def test_objectives_from_config_disables_and_validates():
    class Cfg:
        fleet_slo_availability = 0.999
        fleet_slo_latency_target = 0.95
        fleet_slo_latency_ms = 500.0

    objs = {o.name: o for o in objectives_from_config(Cfg())}
    assert set(objs) == {"availability", "latency"}
    assert objs["latency"].threshold_ms == 500.0
    Cfg.fleet_slo_availability = 0.0
    Cfg.fleet_slo_latency_ms = 0.0
    assert objectives_from_config(Cfg()) == []
    with pytest.raises(ValueError, match="target"):
        SloObjective("x", "availability", 1.0)
