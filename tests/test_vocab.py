"""Vocab semantics tests, cross-checked against the reference's documented
behavior (vocabularies.py:22-106, preprocess.py:12-20)."""

import io
import pickle

from code2vec_tpu.vocab import (
    Code2VecVocabs, SpecialWords, Vocab, VocabType, WordFreqDicts,
    load_word_freq_dicts, special_words_for, PAD_OR_OOV, PAD, OOV,
)


def test_joined_pad_oov_is_index_zero(tiny_vocabs):
    for vocab in (tiny_vocabs.token_vocab, tiny_vocabs.path_vocab,
                  tiny_vocabs.target_vocab):
        assert vocab.pad_index == 0
        assert vocab.oov_index == 0
        assert vocab.index_to_word[0] == PAD_OR_OOV


def test_separate_pad_oov_scheme():
    sw_token = special_words_for(VocabType.Token, separate_oov_and_pad=True)
    assert sw_token.pad == PAD and sw_token.oov == OOV
    vocab = Vocab(VocabType.Token, ["a", "b"], sw_token)
    assert vocab.pad_index == 0 and vocab.oov_index == 1
    assert vocab.lookup_index("a") == 2
    # Target vocab: only OOV (reference: vocabularies.py:204-209).
    sw_target = special_words_for(VocabType.Target, separate_oov_and_pad=True)
    assert sw_target.unique == [OOV]


def test_freq_dict_truncation_keeps_top_n():
    counts = {"w%d" % i: i for i in range(1, 21)}
    vocab = Vocab.create_from_freq_dict(
        VocabType.Token, counts, max_size=5,
        special_words=special_words_for(VocabType.Token, False))
    # top-5 by count: w20..w16, plus 1 special word
    assert vocab.size == 6
    for w in ("w20", "w19", "w18", "w17", "w16"):
        assert w in vocab.word_to_index
    assert "w15" not in vocab.word_to_index


def test_oov_lookup(tiny_vocabs):
    assert tiny_vocabs.token_vocab.lookup_index("nonexistent") == 0
    assert tiny_vocabs.token_vocab.lookup_index("foo") != 0


def test_dictionaries_bin_roundtrip(tiny_vocabs, tmp_path):
    path = str(tmp_path / "dictionaries.bin")
    tiny_vocabs.save(path)
    loaded = Code2VecVocabs.load(path)
    for orig, new in ((tiny_vocabs.token_vocab, loaded.token_vocab),
                      (tiny_vocabs.path_vocab, loaded.path_vocab),
                      (tiny_vocabs.target_vocab, loaded.target_vocab)):
        assert orig.word_to_index == new.word_to_index
        assert orig.size == new.size


def test_dictionaries_bin_format_matches_reference_layout(tiny_vocabs, tmp_path):
    """The file must be a sequence of raw pickles, specials excluded,
    token/target/path order (reference: vocabularies.py:57-66, 211-218)."""
    path = str(tmp_path / "dictionaries.bin")
    tiny_vocabs.save(path)
    with open(path, "rb") as f:
        tok_w2i = pickle.load(f)
        tok_i2w = pickle.load(f)
        tok_size = pickle.load(f)
        tgt_w2i = pickle.load(f)
        _ = pickle.load(f)
        _ = pickle.load(f)
        path_w2i = pickle.load(f)
    assert "foo" in tok_w2i and PAD_OR_OOV not in tok_w2i
    assert min(tok_i2w) == 1  # specials stripped -> min index == nr specials
    assert tok_size == tiny_vocabs.token_vocab.size - 1
    assert "get|name" in tgt_w2i
    assert "P1" in path_w2i


def test_dict_c2v_pickle_roundtrip(tmp_path):
    p = tmp_path / "data.dict.c2v"
    with open(p, "wb") as f:
        pickle.dump({"tok": 3}, f)
        pickle.dump({"path": 2}, f)
        pickle.dump({"tgt": 1}, f)
        pickle.dump(42, f)
    freq = load_word_freq_dicts(str(p))
    assert freq.token_to_count == {"tok": 3}
    assert freq.path_to_count == {"path": 2}
    assert freq.target_to_count == {"tgt": 1}
    assert freq.num_train_examples == 42
