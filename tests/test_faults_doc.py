"""Tier-1 gate for scripts/check_faults_doc.py: every fault point
crossed under code2vec_tpu/ must appear in the utils/faults.py registry
docstring and vice versa — a new chaos hook cannot ship undocumented,
and the registry cannot keep names the code dropped (an armed stale
point silently injects nothing, invalidating the drill that armed
it)."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO_ROOT, "scripts", "check_faults_doc.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_faults_doc",
                                                  CHECKER)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_crossed_fault_point_is_documented_and_vice_versa():
    checker = _load_checker()
    problems = checker.check()
    assert problems == [], "\n".join(problems)


def test_checker_extracts_a_plausible_call_site_set():
    """The AST walk must actually see the hooks: spot-check names from
    different layers (checkpointing, serving, resume, pipeline) so a
    silently-broken walk cannot turn the doc check vacuous."""
    checker = _load_checker()
    names = set(checker.crossed_fault_points())
    assert len(names) >= 10
    for expected in ("save", "checkpoint_commit", "swap_validate",
                     "cursor_remap", "replica_heartbeat",
                     "pipeline_stage", "shadow_eval", "promote"):
        assert expected in names, f"{expected} missing from the walk"


def test_checker_flags_undocumented_and_stale(tmp_path, monkeypatch):
    """The check fails in BOTH directions: a crossed-but-undocumented
    point and a documented-but-never-crossed point each produce a
    problem."""
    checker = _load_checker()
    crossed = sorted(checker.crossed_fault_points())
    assert "save" in crossed
    rows = "\n".join(f"- `{n}` — x" for n in crossed if n != "save")
    registry = tmp_path / "faults.py"
    registry.write_text(
        '"""Registry.\n\n'
        f"{rows}\n- `made_up_point` — x\n"
        '"""\n')
    monkeypatch.setattr(checker, "REGISTRY", str(registry))
    problems = checker.check()
    assert any("UNDOCUMENTED: fault point save" in p for p in problems)
    assert any("STALE DOC: fault point made_up_point" in p
               for p in problems)
