"""Replica child for the serving chaos suite (tests/test_serving_chaos.py).

One supervised replica: a full PredictionServer + warm fake-extractor
pool + batcher + admission/breaker/swap stack, serving a FAKE model so
the process starts in well under a second (no jax import — see the
C2V_HOST_WORKER gate below). The supervisor spawns N of these via its
`child_command` seam and appends `--heartbeat_file PATH` and
`--serve_port N` exactly as it would for the production
`python -m code2vec_tpu.cli serve` re-exec, so the heartbeat/monitor/
drain protocol under test is the real one.

Usage: python tests/chaos_serving_child.py OVERRIDES_JSON \
           [--heartbeat_file PATH] [--serve_port N]
"""

import json
import os
import sys

# Must precede the package import: replicas serve a fake model, so the
# multi-second jax initialization is pure startup-latency noise in the
# supervisor restart-convergence timings the chaos suite asserts on.
os.environ.setdefault("C2V_HOST_WORKER", "1")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)


class _FakeResult:
    def __init__(self, name, contexts, topk):
        self.original_name = name
        self.topk_predicted_words = [f"predicted|w{i}"
                                     for i in range(topk)]
        self.topk_predicted_words_scores = [0.5 / (i + 1)
                                            for i in range(topk)]
        self.attention_per_context = {}
        for i, ctx in enumerate(contexts):
            bits = ctx.split(",")
            if len(bits) == 3:
                self.attention_per_context[tuple(bits)] = 1.0 / (i + 1)
        self.code_vector = [0.25] * 8


class FakeModel:
    """The minimal surface PredictionServer needs: deterministic
    predictions derived from the extractor lines, instant."""

    def __init__(self, config, fingerprint, topk=3):
        self.config = config
        self._fp = fingerprint
        self.topk = topk
        self.context_buckets = (4, 8, config.max_contexts)
        self._predict_steps = {}

        class _SpecialWords:
            oov = "<OOV>"

        class _TargetVocab:
            special_words = _SpecialWords()

        class _Vocabs:
            target_vocab = _TargetVocab()

        self.vocabs = _Vocabs()

    def model_fingerprint(self):
        return self._fp

    def predict_compile_count(self):
        return 0

    def predict(self, lines, batch_size=None, with_code_vectors=False):
        out = []
        for line in lines:
            parts = line.split()
            out.append(_FakeResult(parts[0], parts[1:], topk=self.topk))
        return out

    def smoke_schema(self):
        [r] = self.predict(["swapsmoke a,b,c"], batch_size=1,
                           with_code_vectors=True)
        return {"topk": len(r.topk_predicted_words),
                "code_vector_size": len(r.code_vector),
                "scores_finite": True}


def main() -> int:
    argv = sys.argv[1:]
    overrides = json.loads(open(argv[0]).read())
    # the flags the supervisor appends to every child command
    if "--heartbeat_file" in argv:
        overrides["heartbeat_file"] = argv[argv.index(
            "--heartbeat_file") + 1]
    if "--metrics_file" in argv:
        overrides["metrics_file"] = argv[argv.index(
            "--metrics_file") + 1]
    if "--serve_port" in argv:
        overrides["serve_port"] = int(argv[argv.index("--serve_port") + 1])
    # fleet-drill extensions (non-Config keys): a deterministic
    # fingerprint (cross-host swap convergence is asserted on it), a
    # fake swap builder ("fake_swap": fingerprint = "fp-" + the target
    # dir's basename), and target basenames whose swap candidate must
    # FAIL validation on THIS replica ("swap_fail_targets" — the
    # rollback drills break one host's rollout this way).
    fingerprint = overrides.pop(
        "fingerprint", f"fake-replica-model-pid{os.getpid()}")
    fake_swap = overrides.pop("fake_swap", False)
    fake_retrieval = overrides.pop("fake_retrieval", False)
    swap_fail_targets = set(overrides.pop("swap_fail_targets", ()))

    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.server import serve_main

    config = Config(serve=True, verbose_mode=0, **overrides)
    model = FakeModel(config, fingerprint=fingerprint)

    build_model = None
    if fake_swap:
        def build_model(artifact_dir):
            name = os.path.basename(str(artifact_dir).rstrip("/"))
            new = FakeModel(config, fingerprint=f"fp-{name}")
            if name in swap_fail_targets:
                # schema mismatch: SwapManager validation rejects it
                new.topk = 5
            return new

    # The (artifact, retrieval_index) reconciliation drills ride a
    # retrieval_index through the reload path; the real
    # _mount_retrieval_index would reject the fake index dirs, so a
    # fake mounter builds the minimal handle surface SwapManager and
    # /healthz touch (fingerprint/attached/detach/status/default_topk).
    mount_index = None
    if fake_retrieval:
        class _FakeRetrievalHandle:
            def __init__(self, index_dir):
                name = os.path.basename(str(index_dir).rstrip("/"))
                self.fingerprint = f"idx-{name}"
                self.attached = True
                self.default_topk = 3

            def detach(self, reason=""):
                self.attached = False

            def status(self):
                return {"attached": self.attached,
                        "fingerprint": self.fingerprint}

        def mount_index(index_dir, model=None):
            return _FakeRetrievalHandle(index_dir)

    return serve_main(config, model=model,
                      swap_build_model=build_model,
                      swap_mount_index=mount_index)


if __name__ == "__main__":
    sys.exit(main())
