"""Wire-format lock for the dependency-free TensorBoard writer
(utils/tb.py): an independent reader re-parses the TFRecord framing with
its own bitwise CRC32C (not the writer's table), verifies BOTH masked
CRCs of every record, and fully decodes the hand-encoded Event protos
(wall_time / step / file_version / Summary tag+simple_value) — so any
change to the framing or the proto field encoding shows up as a test
diff, not as a TensorBoard that silently stops loading our files. Plus
the writer lifecycle: context manager, idempotent close, flush-after-
close harmless."""

import math
import struct

import pytest

from code2vec_tpu.utils.tb import ScalarWriter


# ------------------------- independent CRC32C (bitwise, no lookup table)

def _crc32c_bitwise(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


def _masked_crc_independent(data: bytes) -> int:
    crc = _crc32c_bitwise(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ------------------------------------------- minimal protobuf wire decoder

def _read_varint(data: bytes, i: int):
    shift = 0
    out = 0
    while True:
        b = data[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _decode_summary_value(data: bytes) -> dict:
    """Summary.Value: tag=1 (len-delim), simple_value=2 (32-bit float)."""
    out = {}
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        fnum, wire = key >> 3, key & 7
        if fnum == 1 and wire == 2:
            ln, i = _read_varint(data, i)
            out["tag"] = data[i:i + ln].decode()
            i += ln
        elif fnum == 2 and wire == 5:
            out["simple_value"] = struct.unpack("<f", data[i:i + 4])[0]
            i += 4
        else:
            pytest.fail(f"unexpected Summary.Value field {fnum} wire {wire}")
    return out


def _decode_event(data: bytes) -> dict:
    """Event: wall_time=1 (double), step=2 (varint), file_version=3
    (string), summary=5 (message of repeated Value=1)."""
    ev = {"values": []}
    i = 0
    while i < len(data):
        key, i = _read_varint(data, i)
        fnum, wire = key >> 3, key & 7
        if fnum == 1 and wire == 1:
            ev["wall_time"] = struct.unpack("<d", data[i:i + 8])[0]
            i += 8
        elif fnum == 2 and wire == 0:
            ev["step"], i = _read_varint(data, i)
        elif fnum == 3 and wire == 2:
            ln, i = _read_varint(data, i)
            ev["file_version"] = data[i:i + ln].decode()
            i += ln
        elif fnum == 5 and wire == 2:
            ln, i = _read_varint(data, i)
            summary = data[i:i + ln]
            i += ln
            j = 0
            while j < len(summary):
                skey, j = _read_varint(summary, j)
                assert skey >> 3 == 1 and skey & 7 == 2, \
                    "Summary must only carry repeated Value (field 1)"
                vlen, j = _read_varint(summary, j)
                ev["values"].append(
                    _decode_summary_value(summary[j:j + vlen]))
                j += vlen
        else:
            pytest.fail(f"unexpected Event field {fnum} wire {wire}")
    return ev


def _read_events(path: str) -> list:
    """Re-parse the TFRecord stream, verifying length-header and payload
    masked CRCs with the independent CRC32C implementation."""
    events = []
    with open(path, "rb") as f:
        blob = f.read()
    i = 0
    while i < len(blob):
        header = blob[i:i + 8]
        assert len(header) == 8, "truncated record header"
        (length,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", blob[i + 8:i + 12])
        assert hcrc == _masked_crc_independent(header), "header CRC mismatch"
        payload = blob[i + 12:i + 12 + length]
        assert len(payload) == length, "truncated record payload"
        (pcrc,) = struct.unpack("<I",
                                blob[i + 12 + length:i + 16 + length])
        assert pcrc == _masked_crc_independent(payload), \
            "payload CRC mismatch"
        events.append(_decode_event(payload))
        i += 16 + length
    return events


# ----------------------------------------------------------------- tests

def test_event_stream_roundtrip_decodes_tags_values_steps(tmp_path):
    w = ScalarWriter(str(tmp_path / "tb"))
    w.scalar("train/loss", 1.5, step=7)
    w.scalar("eval/f1", -0.25, step=300)          # multi-byte varint step
    w.scalar("obs/x", 3.0e-9, step=2**33)         # >32-bit step
    w.close()

    events = _read_events(w.path)
    assert len(events) == 4

    head = events[0]
    assert head["file_version"] == "brain.Event:2"
    assert head["step"] == 0
    assert head["values"] == []

    tags = [(e["values"][0]["tag"], e["values"][0]["simple_value"],
             e["step"]) for e in events[1:]]
    assert tags[0][0] == "train/loss"
    assert tags[0][1] == pytest.approx(1.5)
    assert tags[0][2] == 7
    assert tags[1][0] == "eval/f1"
    assert tags[1][1] == pytest.approx(-0.25)
    assert tags[1][2] == 300
    assert tags[2][0] == "obs/x"
    assert tags[2][1] == pytest.approx(3.0e-9, rel=1e-6)  # f32 rounding
    assert tags[2][2] == 2**33

    # every event carries a plausible wall clock
    for e in events:
        assert 1.7e9 < e["wall_time"] < 4e9
        assert not math.isnan(e["wall_time"])


def test_writer_is_a_context_manager_with_idempotent_close(tmp_path):
    with ScalarWriter(str(tmp_path / "tb")) as w:
        w.scalar("a", 1.0, step=1)
        assert not w.closed
    assert w.closed
    w.close()          # second close: harmless
    w.flush()          # flush after close: harmless (trainer finally path)
    events = _read_events(w.path)
    assert len(events) == 2            # file_version + the scalar


def test_close_flushes_buffered_tail(tmp_path):
    """The trainer closes the writer in its `finally`; that close must
    flush OS-buffered records so a crash right after loses nothing."""
    w = ScalarWriter(str(tmp_path / "tb"))
    for i in range(50):
        w.scalar("t", float(i), step=i)
    w.close()
    events = _read_events(w.path)
    assert len(events) == 51
    assert [e["values"][0]["simple_value"] for e in events[1:]] == \
        [float(i) for i in range(50)]
