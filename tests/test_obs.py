"""Observability subsystem (code2vec_tpu/obs): registry semantics,
Prometheus text rendering, span tracer + Chrome trace export, the
atomic file exporters and the /metrics HTTP endpoint — plus a tier-1
smoke test that runs a tiny train loop and asserts the heartbeat file,
Prometheus snapshot, TB event file and Chrome trace all appear with sane
contents, and regression tests for the per-batch non-finite-loss guard
(windows that the old average-only sentinel discarded unchecked)."""

import json
import os
import struct
import threading
import urllib.request

import numpy as np
import pytest

from code2vec_tpu import obs
from code2vec_tpu.data.reader import EpochEnd, RowBatch
from code2vec_tpu.obs import exporters
from code2vec_tpu.obs.metrics import MetricsRegistry
from code2vec_tpu.obs.tracer import SpanTracer, span
from code2vec_tpu.training.loop import NonFiniteLossError, Trainer


# ------------------------------------------------------------- registry

def test_counter_gauge_histogram_basics():
    reg = MetricsRegistry()
    c = reg.counter("c_total")
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("g")
    g.set(4.0)
    g.inc()
    g.dec(2)
    assert g.value == 3.0
    h = reg.histogram("h_seconds", buckets=(0.1, 1.0))
    for v in (0.05, 0.1, 0.5, 2.0):
        h.observe(v)
    assert h.count == 4
    assert h.sum == pytest.approx(2.65)
    # le is INCLUSIVE (Prometheus semantics): the 0.1 observation counts
    # in the 0.1 bucket
    assert h.cumulative_counts() == [2, 3]


def test_registration_is_idempotent_and_type_checked():
    reg = MetricsRegistry()
    a = reg.counter("x_total", point="save")
    b = reg.counter("x_total", point="save")
    assert a is b                       # same (name, labels) -> same child
    other = reg.counter("x_total", point="load")
    assert other is not a               # different labels -> sibling
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("x_total")


def test_prometheus_render_format():
    reg = MetricsRegistry()
    reg.counter("req_total", "requests", method="get").inc(3)
    reg.gauge("temp").set(1.5)
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render_prometheus()
    assert "# HELP req_total requests" in text
    assert "# TYPE req_total counter" in text
    assert 'req_total{method="get"} 3' in text
    assert "temp 1.5" in text
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 2' in text
    assert "lat_seconds_sum 5.05" in text
    assert "lat_seconds_count 2" in text
    assert text.endswith("\n")


def test_prometheus_label_escaping():
    reg = MetricsRegistry()
    reg.counter("c_total", path='we"ird\\name\n').inc()
    text = reg.render_prometheus()
    assert 'path="we\\"ird\\\\name\\n"' in text


def test_tb_scalars_flatten_histograms_and_labels():
    reg = MetricsRegistry()
    reg.counter("c_total", kind="a").inc(2)
    h = reg.histogram("h_seconds", buckets=(1.0,))
    h.observe(0.5)
    h.observe(1.5)
    tags = dict(reg.tb_scalars())
    assert tags["c_total.kind.a"] == 2.0
    assert tags["h_seconds/count"] == 2.0
    assert tags["h_seconds/sum"] == pytest.approx(2.0)
    assert tags["h_seconds/mean"] == pytest.approx(1.0)


def test_registry_thread_safety():
    reg = MetricsRegistry()
    c = reg.counter("n_total")
    h = reg.histogram("h_seconds", buckets=(0.5,))

    def work():
        for _ in range(5000):
            c.inc()
            h.observe(0.1)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40000
    assert h.count == 40000
    assert h.cumulative_counts() == [40000]


def test_default_registry_module_helpers():
    c = obs.counter("obs_selftest_total", "test counter")
    before = c.value
    obs.counter("obs_selftest_total").inc()
    assert obs.counter("obs_selftest_total").value == before + 1
    assert "obs_selftest_total" in obs.default_registry().render_prometheus()


# --------------------------------------------------------------- tracer

def test_span_times_and_feeds_histogram_even_when_tracer_disabled():
    reg = MetricsRegistry()
    tracer = SpanTracer()
    assert not tracer.enabled
    h = reg.histogram("s_seconds", buckets=(10.0,))
    with span("work", hist=h, tracer=tracer) as s:
        pass
    assert h.count == 1
    assert s.seconds >= 0
    assert len(tracer) == 0            # disabled: nothing buffered


def test_tracer_ring_buffer_bounded_and_exports_chrome_trace(tmp_path):
    tracer = SpanTracer(capacity=8)
    tracer.enable()
    for i in range(20):
        with span(f"s{i}", tracer=tracer):
            pass
    assert len(tracer) == 8            # ring buffer: newest 8 kept
    out = tracer.export_chrome_trace(str(tmp_path / "trace.json"))
    doc = json.load(open(out))
    names = [e["name"] for e in doc["traceEvents"] if e["ph"] == "X"]
    assert names == [f"s{i}" for i in range(12, 20)]
    for e in doc["traceEvents"]:
        if e["ph"] == "X":             # Perfetto-required complete-event keys
            assert {"name", "ph", "ts", "dur", "pid", "tid"} <= set(e)
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in doc["traceEvents"])
    assert doc["otherData"]["trace_epoch_unix_s"] > 0


def test_span_records_on_exception():
    tracer = SpanTracer()
    tracer.enable()
    with pytest.raises(RuntimeError):
        with span("failing", tracer=tracer):
            raise RuntimeError("boom")
    assert len(tracer) == 1            # the span still closed + recorded


def test_tracer_counts_dropped_spans_and_high_water():
    """The ring drops oldest spans silently from the FILE's point of
    view — the drops must be first-class metrics so a truncated Chrome
    trace is detectable from /metrics alone (and from the trace file's
    otherData.spans_dropped)."""
    dropped_before = obs.counter("obs_spans_dropped_total").value
    tracer = SpanTracer(capacity=4)
    tracer.enable()
    for i in range(10):
        tracer.record(f"s{i}", 0.0, 0.001)
    assert len(tracer) == 4
    assert tracer.dropped == 6
    assert tracer.high_water == 4
    assert obs.counter("obs_spans_dropped_total").value \
        == dropped_before + 6
    assert obs.gauge("obs_span_ring_high_water").value >= 4
    doc = tracer.chrome_trace()
    assert doc["otherData"]["spans_dropped"] == 6
    # under capacity: nothing dropped, high-water tracks the fill level
    small = SpanTracer(capacity=16)
    small.enable()
    small.record("only", 0.0, 0.001)
    assert small.dropped == 0 and small.high_water == 1


def test_tracer_id_tagged_spans_export_args():
    tracer = SpanTracer()
    tracer.enable()
    tracer.record("tagged", 0.0, 0.002, trace_id="a" * 32,
                  span_id="b" * 16, parent_id="c" * 16,
                  attrs={"endpoint": "predict"})
    tracer.record("plain", 0.0, 0.001)
    doc = tracer.chrome_trace()
    by_name = {e["name"]: e for e in doc["traceEvents"]
               if e["ph"] == "X"}
    args = by_name["tagged"]["args"]
    assert args["trace_id"] == "a" * 32
    assert args["span_id"] == "b" * 16
    assert args["parent_id"] == "c" * 16
    assert args["endpoint"] == "predict"
    assert "args" not in by_name["plain"]


# ------------------------------------------------------------- reqtrace

def test_traceparent_parse_and_format():
    from code2vec_tpu.obs import reqtrace
    parsed = reqtrace.parse_traceparent(
        "00-" + "a1" * 16 + "-" + "b2" * 8 + "-01")
    assert parsed == {"trace_id": "a1" * 16,
                      "parent_span_id": "b2" * 8}
    # malformed / absent / all-zero headers are ignored, never fatal
    for bad in (None, "", "garbage", "00-xyz-abc-01",
                "00-" + "0" * 32 + "-" + "b2" * 8 + "-01",
                "00-" + "a1" * 16 + "-" + "0" * 16 + "-01"):
        assert reqtrace.parse_traceparent(bad) is None
    out = reqtrace.format_traceparent("a1" * 16, "b2" * 8)
    assert reqtrace.parse_traceparent(out) == parsed
    tid, sid = reqtrace.mint_trace_id(), reqtrace.mint_span_id()
    assert len(tid) == 32 and len(sid) == 16
    assert tid != reqtrace.mint_trace_id()  # 128-bit: never collides


def test_request_trace_span_tree_and_ring_forwarding():
    from code2vec_tpu.obs import reqtrace
    from code2vec_tpu.obs.reqtrace import RequestTrace
    ring = SpanTracer()
    ring.enable()
    rt = RequestTrace(tracer=ring)
    assert rt.minted and len(rt.trace_id) == 32
    with rt.span("request", endpoint="predict") as root:
        with rt.span("cache_lookup") as sp:
            sp.attrs["hit"] = False
        # a shareable id is minted by the CALLER (the batcher's idiom
        # for the shared batch span) — add_span itself defers minting
        # to export time
        shared = reqtrace.mint_span_id()
        rt.add_span("batch", 0.0, 0.005, span_id=shared,
                    attrs={"batch_id": 7}, forward=False)
        rt.add_span("device", 0.0, 0.005, parent_id=shared)
        root.attrs["status"] = 200
    doc = rt.to_dict()
    assert doc["trace_id"] == rt.trace_id
    by_name = {s["name"]: s for s in doc["spans"]}
    assert set(by_name) == {"request", "cache_lookup", "batch", "device"}
    root_id = doc["root_span_id"]
    assert by_name["request"]["span_id"] == root_id
    assert by_name["request"]["parent_id"] is None
    assert by_name["cache_lookup"]["parent_id"] == root_id
    assert by_name["batch"]["parent_id"] == root_id
    assert by_name["device"]["parent_id"] == by_name["batch"]["span_id"]
    assert by_name["request"]["attrs"]["status"] == 200
    # the ring got every span EXCEPT the forward=False batch copy,
    # tagged with the trace id
    ring_events = [e for e in ring.chrome_trace()["traceEvents"]
                   if e["ph"] == "X"]
    ring_names = {e["name"] for e in ring_events}
    assert ring_names == {"request", "cache_lookup", "device"}
    for e in ring_events:
        assert e["args"]["trace_id"] == rt.trace_id


def test_request_trace_honors_inbound_parent():
    from code2vec_tpu.obs.reqtrace import RequestTrace
    header = "00-" + "ab" * 16 + "-" + "cd" * 8 + "-01"
    rt = RequestTrace.from_headers(header)
    assert rt.trace_id == "ab" * 16
    assert not rt.minted
    with rt.span("request"):
        pass
    doc = rt.to_dict()
    # the root hangs under the CALLER's span: distributed tracing
    assert doc["spans"][0]["parent_id"] == "cd" * 8
    assert doc["remote_parent"] == "cd" * 8
    echoed = rt.traceparent()
    assert echoed.split("-")[1] == "ab" * 16
    assert echoed.split("-")[2] == doc["root_span_id"]
    # malformed header -> minted id, not an error
    rt2 = RequestTrace.from_headers("not-a-traceparent")
    assert rt2.minted and rt2.trace_id != rt.trace_id


# ------------------------------------------------------- flight recorder

def test_flight_recorder_rings_bounded_and_dump_schema(tmp_path):
    from code2vec_tpu.obs.flight import FlightRecorder
    rec = FlightRecorder(capacity=4, events_capacity=8)
    rec.configure(dump_dir=str(tmp_path))
    for i in range(10):
        rec.record_request(trace_id=f"t{i}", endpoint="predict",
                           status=200, duration_s=0.01,
                           phases={"extract": 0.002},
                           fingerprint="fp1")
    rec.event("swap_start", target="/x")
    path = rec.dump(reason="manual")
    doc = json.load(open(path))
    assert doc["schema_version"] == 1
    assert doc["reason"] == "manual"
    assert doc["requests_recorded"] == 10
    # ring: only the newest 4 survive
    assert [r["trace_id"] for r in doc["requests"]] \
        == ["t6", "t7", "t8", "t9"]
    req = doc["requests"][-1]
    assert req["status"] == 200
    assert req["phases_ms"]["extract"] == pytest.approx(2.0)
    assert req["fingerprint"] == "fp1"
    assert doc["events"] == [{"t": doc["events"][0]["t"],
                              "kind": "swap_start", "target": "/x"}]


def test_flight_incident_schedules_one_coalesced_dump(tmp_path):
    import time as _time
    from code2vec_tpu.obs.flight import FlightRecorder
    dumps_before = obs.counter("flight_dumps_total").value
    rec = FlightRecorder(capacity=8)
    rec.configure(dump_dir=str(tmp_path), dump_delay_s=0.15)
    rec.incident("breaker_open", breaker="extractor")
    # the delay window captures the FALLOUT: sheds recorded after the
    # incident still make the dump
    rec.record_request(trace_id="shed1", endpoint="predict", status=503,
                       duration_s=0.0, reason="breaker")
    rec.incident("breaker_open", breaker="device")  # coalesces
    deadline = _time.time() + 5
    files = []
    while _time.time() < deadline:
        files = list(tmp_path.glob("flight-*.json"))
        if files:
            break
        _time.sleep(0.02)
    assert len(files) == 1, "exactly one coalesced dump"
    doc = json.load(open(files[0]))
    assert doc["reason"] == "breaker_open"
    assert [r["trace_id"] for r in doc["requests"]] == ["shed1"]
    kinds = [e["kind"] for e in doc["events"]]
    assert kinds.count("breaker_open") == 2
    assert all(e["incident"] for e in doc["events"])
    assert doc["incidents_coalesced"] == 1
    assert obs.counter("flight_dumps_total").value == dumps_before + 1
    assert obs.counter("flight_incidents_total",
                       kind="breaker_open").value >= 2


def test_flight_incident_immediate_dumps_synchronously(tmp_path):
    from code2vec_tpu.obs.flight import FlightRecorder
    rec = FlightRecorder()
    rec.configure(dump_dir=str(tmp_path), dump_delay_s=30.0)
    rec.record_request(trace_id="a1", endpoint="predict", status=504,
                       duration_s=2.0, reason="deadline_expired")
    rec.incident("drain_timeout", immediate=True, abandoned=1)
    files = list(tmp_path.glob("flight-*drain_timeout.json"))
    assert len(files) == 1  # no timer wait: exit paths dump NOW
    doc = json.load(open(files[0]))
    assert doc["requests"][0]["trace_id"] == "a1"


def test_flight_no_dump_dir_records_but_never_dumps(tmp_path):
    from code2vec_tpu.obs.flight import FlightRecorder
    rec = FlightRecorder()
    rec.incident("breaker_open", breaker="x")
    snap = rec.snapshot()
    assert snap["events"][0]["kind"] == "breaker_open"
    assert not list(tmp_path.iterdir())


# ------------------------------------------------------------ exporters

def test_write_prometheus_is_atomic_and_complete(tmp_path):
    reg = MetricsRegistry()
    reg.counter("a_total").inc(7)
    path = str(tmp_path / "sub" / "metrics.prom")
    exporters.write_prometheus(path, registry=reg)
    assert open(path).read() == reg.render_prometheus()
    # no tmp litter left behind
    assert os.listdir(tmp_path / "sub") == ["metrics.prom"]


def test_heartbeat_schema(tmp_path):
    path = str(tmp_path / "hb.json")
    exporters.write_heartbeat(path, status="running", step=12, epoch=3,
                              last_loss=1.25)
    hb = json.load(open(path))
    assert hb["schema_version"] == exporters.HEARTBEAT_SCHEMA_VERSION
    assert hb["step"] == 12 and hb["epoch"] == 3
    assert hb["last_loss"] == 1.25
    assert hb["status"] == "running"
    assert hb["pid"] == os.getpid()
    assert hb["wall_time"] > 1.7e9     # a real unix timestamp
    # rewrite replaces, never appends
    exporters.write_heartbeat(path, status="done", step=13)
    hb2 = json.load(open(path))
    assert hb2["step"] == 13 and hb2["status"] == "done"


def test_metrics_http_server_serves_prometheus_text():
    reg = MetricsRegistry()
    reg.counter("served_total").inc(5)
    server = exporters.start_metrics_server(0, registry=reg)
    try:
        port = server.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
            assert resp.status == 200
            assert "text/plain" in resp.headers["Content-Type"]
            body = resp.read().decode()
        assert "served_total 5" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope",
                                   timeout=10)
    finally:
        exporters.stop_metrics_server(server)


# ------------------------------------------------ checkpoint-layer metrics

def test_verify_failure_counts_into_registry(tmp_path):
    from code2vec_tpu.training import checkpoint as ckpt_mod
    c = obs.counter("checkpoint_verify_failures_total")
    before = c.value
    with pytest.raises(ckpt_mod.CheckpointIntegrityError):
        ckpt_mod.verify_checkpoint(str(tmp_path / "nonexistent"))
    assert c.value == before + 1
    text = obs.default_registry().render_prometheus()
    assert "checkpoint_verify_seconds_bucket" in text


def test_fault_fire_counts_into_registry():
    from code2vec_tpu.utils import faults
    faults.reset("obs_probe=raise")
    try:
        with pytest.raises(faults.FaultInjected):
            faults.fault_point("obs_probe")
    finally:
        faults.reset(None)
    c = obs.counter("fault_injected_total", point="obs_probe",
                    action="raise")
    assert c.value == 1


# ------------------------------------------------------ train-loop smoke

def _fake_batch(n=2, m=4):
    return RowBatch(
        source_token_indices=np.ones((n, m), np.int32),
        path_indices=np.ones((n, m), np.int32),
        target_token_indices=np.ones((n, m), np.int32),
        context_valid_mask=np.ones((n, m), np.float32),
        target_index=np.ones((n,), np.int32),
        example_valid=np.ones((n,), bool))


def _marker_stream(batches_per_epoch, epochs):
    for e in range(epochs):
        for _ in range(batches_per_epoch):
            yield _fake_batch()
        yield EpochEnd(e + 1)


class _State:
    step = np.zeros((), np.int32)


def test_train_loop_emits_heartbeat_snapshot_tb_and_trace(tiny_config,
                                                          tmp_path):
    """Tier-1 smoke for the whole export surface: one tiny train run with
    every sink configured produces (a) a JSON heartbeat with step/epoch/
    loss, (b) a Prometheus snapshot with the step-breakdown histograms,
    (c) a TB event file carrying the obs/ tags, (d) a Perfetto-loadable
    Chrome trace with the per-batch host spans."""
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 2
    tiny_config.verbose_mode = 0
    tiny_config.use_tensorboard = True
    tiny_config.model_save_path = str(tmp_path / "model")
    tiny_config.metrics_file = str(tmp_path / "metrics.prom")
    tiny_config.heartbeat_file = str(tmp_path / "heartbeat.json")
    tiny_config.trace_export = str(tmp_path / "trace.json")

    def train_step(state, *args):
        return state, np.float32(2.0)

    saves = []
    trainer = Trainer(tiny_config, train_step,
                      save_fn=lambda s, e, suffix="": saves.append(e))
    try:
        trainer.train(_State(), _marker_stream(6, 1),
                      rng=np.zeros((2,), np.uint32))
    finally:
        obs.default_tracer().disable()

    # (a) heartbeat: final state says the run finished cleanly
    hb = json.load(open(tiny_config.heartbeat_file))
    assert hb["status"] == "done"
    assert hb["step"] == 6
    assert hb["epoch"] == 1
    assert hb["last_loss"] == pytest.approx(2.0)
    assert hb["rss_bytes"] > 0

    # (b) Prometheus snapshot: step-time breakdown + loop counters
    prom = open(tiny_config.metrics_file).read()
    assert "train_data_wait_seconds_bucket" in prom
    assert "train_step_dispatch_seconds_bucket" in prom
    assert "train_loss_sync_seconds_bucket" in prom
    assert "train_last_avg_loss 2" in prom
    assert "train_epochs_total" in prom

    # (c) TB event file exists and carries both the classic train/ tags
    # and the registry dump under obs/
    tb_dir = tiny_config.tensorboard_dir
    events = [f for f in os.listdir(tb_dir) if "tfevents" in f]
    assert len(events) == 1
    blob = open(os.path.join(tb_dir, events[0]), "rb").read()
    assert b"train/loss" in blob
    assert b"obs/train_batches_total" in blob

    # (d) Chrome trace: per-batch host spans, Perfetto-loadable JSON
    doc = json.load(open(tiny_config.trace_export))
    names = {e["name"] for e in doc["traceEvents"]}
    assert "step_dispatch" in names
    assert "data_wait" in names
    assert "loss_sync" in names

    assert saves == [1]                # the loop itself behaved normally


def test_train_loop_with_obs_disabled_writes_nothing(tiny_config, tmp_path):
    """Default config: no heartbeat/snapshot/trace files appear and the
    loop runs exactly as before (the instrumentation is passive)."""
    tiny_config.num_train_epochs = 1
    tiny_config.verbose_mode = 0

    def train_step(state, *args):
        return state, np.float32(1.0)

    trainer = Trainer(tiny_config, train_step)
    trainer.train(_State(), _marker_stream(3, 1),
                  rng=np.zeros((2,), np.uint32))
    assert not any(p.name.endswith((".prom", ".json"))
                   for p in tmp_path.iterdir())


# ----------------------------------- per-batch non-finite guard (ROADMAP)

def test_nan_batch_caught_when_eval_reset_would_discard_it(tiny_config):
    """Regression for the average-only sentinel's blind spot: a poisoned
    batch in a window that a mid-epoch eval drains used to be DISCARDED
    unchecked (the eval reset cleared pending_losses). The per-batch
    guard must trip the halt policy there."""
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 100   # no log boundary
    tiny_config.num_train_batches_to_evaluate = 2   # eval at batch 2
    tiny_config.verbose_mode = 0
    tiny_config.on_nonfinite_loss = "halt"
    steps, saves, evals = [], [], []

    def train_step(state, *args):
        steps.append(1)
        return state, (np.float32("nan") if len(steps) == 1
                       else np.float32(1.0))

    trainer = Trainer(tiny_config, train_step,
                      evaluate_fn=lambda s: evals.append(1),
                      save_fn=lambda s, e, suffix="": saves.append(suffix))
    with pytest.raises(NonFiniteLossError, match="nan"):
        trainer.train(_State(), _marker_stream(8, 1),
                      rng=np.zeros((2,), np.uint32))
    assert len(steps) == 2             # tripped at the eval-boundary drain
    assert evals == []                 # BEFORE the eval ran
    assert saves == ["_nanhalt"]
    assert trainer.preempted


def test_nan_batch_caught_at_epoch_boundary_before_clean_save(tiny_config):
    """Same blind spot at the epoch boundary: the poisoned tail window
    must halt BEFORE the end-of-epoch clean save (which would otherwise
    become the newest resume candidate with poisoned params)."""
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 100
    tiny_config.verbose_mode = 0
    tiny_config.on_nonfinite_loss = "halt"
    saves = []

    def train_step(state, *args):
        return state, np.float32("inf")

    trainer = Trainer(tiny_config, train_step,
                      save_fn=lambda s, e, suffix="": saves.append(suffix))
    with pytest.raises(NonFiniteLossError):
        trainer.train(_State(), _marker_stream(3, 1),
                      rng=np.zeros((2,), np.uint32))
    assert saves == ["_nanhalt"]       # no clean epoch save happened


def test_nan_window_halts_instead_of_preempt_checkpointing(tiny_config):
    """A preemption landing inside a NaN-poisoned window must NOT save
    the poisoned params as a resume-ELIGIBLE `_preempt` artifact: the
    drain runs first, the halt policy wins, and the state goes under
    `_nanhalt` (invisible to resume) — otherwise an auto-restarting
    scheduler would crash-loop on the NaN checkpoint."""
    import os as _os
    import signal as _signal
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 100   # no log boundary
    tiny_config.verbose_mode = 0
    tiny_config.on_nonfinite_loss = "halt"
    saves, steps = [], []

    def train_step(state, *args):
        steps.append(1)
        if len(steps) == 2:
            _os.kill(_os.getpid(), _signal.SIGTERM)
        return state, np.float32("nan")

    trainer = Trainer(tiny_config, train_step,
                      save_fn=lambda s, e, suffix="": saves.append(suffix))
    with pytest.raises(NonFiniteLossError):
        trainer.train(_State(), _marker_stream(8, 1),
                      rng=np.zeros((2,), np.uint32))
    assert saves == ["_nanhalt"]       # never a plain "_preempt"
    assert trainer.preempted


def test_nonfinite_batches_counted(tiny_config):
    tiny_config.num_train_epochs = 1
    tiny_config.num_batches_to_log_progress = 4
    tiny_config.verbose_mode = 0
    tiny_config.on_nonfinite_loss = "warn"
    c = obs.counter("train_nonfinite_loss_batches_total")
    before = c.value
    steps = []

    def train_step(state, *args):
        steps.append(1)
        return state, (np.float32("nan") if len(steps) in (2, 3)
                       else np.float32(1.0))

    trainer = Trainer(tiny_config, train_step)
    trainer.train(_State(), _marker_stream(4, 1),
                  rng=np.zeros((2,), np.uint32))
    assert c.value == before + 2       # each poisoned batch counted
