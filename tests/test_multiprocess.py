"""Real 2-process `jax.distributed` test (CPU backend, gloo collectives).

Unlike tests/test_distributed.py (which unit-tests the helpers'
single-process semantics), this spawns TWO actual OS processes that join
one distributed runtime — 2 local CPU devices each, 4 global — and runs
the production multi-host path end to end: `distributed.initialize`,
`allreduce_host_scalars`, `global_batch_arrays` feeding the real jitted
train/eval steps over a dp=4 mesh, and the Evaluator's global-metric
reduction. The parent computes every expected number single-process
first; the children must reproduce them exactly (see tests/mp_child.py).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.evaluation.evaluator import Evaluator
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.training.state import create_train_state, make_optimizer
from code2vec_tpu.training.step import TrainStepBuilder, device_put_batch
from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

HERE = os.path.dirname(os.path.abspath(__file__))
B, M = 8, 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _full_batch():
    rng = np.random.default_rng(11)
    dims = ModelDims(token_vocab_size=24, path_vocab_size=16,
                     target_vocab_size=16, token_dim=4, path_dim=4)
    src = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (B, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    mask = (rng.random((B, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(2, dims.real_target_vocab_size, (B,)).astype(np.int32)
    valid = np.ones((B,), bool)
    # Mix of in-vocab, multi-subtoken and never-predictable names so the
    # evaluator's tp/fp/fn and top-k counters are all non-trivial.
    names = ["w0", "w1", "w2|w3", "w4", "nosuchname", "w5", "w6|w0", "w7"]
    return dims, RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels, example_valid=valid,
        target_strings=names)


def _vocabs():
    freq = WordFreqDicts(
        token_to_count={"foo": 10, "bar": 8, "baz": 5, "qux": 2},
        path_to_count={"P1": 9, "P2": 7, "P3": 3},
        target_to_count={f"w{i}": 20 - i for i in range(12)},
        num_train_examples=100)
    return Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=30, max_path_vocab_size=20,
        max_target_vocab_size=20)


def _uneven_rows(dims, n):
    """N eval rows for the uneven-shard lockstep phase: hosts will split
    these 10/8, giving host 0 three local batches and host 1 two — the
    exact post-filter divergence VERDICT flagged as a pod deadlock."""
    rng = np.random.default_rng(23)
    src = rng.integers(0, dims.token_vocab_size, (n, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (n, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (n, M)).astype(np.int32)
    mask = (rng.random((n, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(2, dims.real_target_vocab_size, (n,)).astype(np.int32)
    pool = ["w0", "w1", "w2|w3", "w4", "nosuchname", "w5", "w6|w0", "w7",
            "w8", "w1|w9"]
    names = [pool[i % len(pool)] for i in range(n)]
    return RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels,
        example_valid=np.ones((n,), bool), target_strings=names)


def test_two_process_distributed(tmp_path):
    dims, batch = _full_batch()

    # ---- parent: single-device expected values on the full batch
    config = Config(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=B, test_batch_size=B, max_contexts=M,
                    dropout_keep_rate=1.0)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=1.0)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(7))
    builder = TrainStepBuilder(module, opt, config, mesh=None)
    arrays = device_put_batch(batch, None)
    eval_step = builder.make_eval_step(state, k=3)
    out = eval_step(state.params, *arrays)
    expected_loss_sum = float(out.loss_sum)

    evaluator = Evaluator(config, _vocabs(), eval_step, mesh=None,
                          log_path=str(tmp_path / "log_single.txt"))
    expected_eval = evaluator.evaluate(state.params, [batch])

    # ---- parent: single-process expected metrics over the UNEVEN rows
    # (18 rows; children split 10/8 -> 3 vs 2 local batches). Row-wise
    # metrics are grouping-invariant, so the parent batches them 8+8+2pad.
    from code2vec_tpu.data.reader import _pad_rows, _select_rows
    uneven = _uneven_rows(dims, 18)
    ev2 = Evaluator(config, _vocabs(), eval_step, mesh=None,
                    log_path=str(tmp_path / "log_single_uneven.txt"))
    uneven_batches = [
        _pad_rows(_select_rows(uneven, np.arange(s, min(s + B, 18))), B)
        for s in range(0, 18, B)]
    expected_uneven = ev2.evaluate(state.params, uneven_batches)

    # last: the train step donates its state buffers
    train_step = builder.make_train_step(state)
    _, expected_train_loss = train_step(state, *arrays, jax.random.PRNGKey(0))
    expected_train_loss = float(expected_train_loss)

    data_path = tmp_path / "mp_data.npz"
    np.savez(data_path, B=B, src=batch.source_token_indices,
             pth=batch.path_indices, tgt=batch.target_token_indices,
             mask=batch.context_valid_mask, labels=batch.target_index,
             valid=batch.example_valid, names=np.array(batch.target_strings),
             expected_loss_sum=expected_loss_sum,
             expected_train_loss=expected_train_loss,
             u_src=uneven.source_token_indices, u_pth=uneven.path_indices,
             u_tgt=uneven.target_token_indices,
             u_mask=uneven.context_valid_mask, u_labels=uneven.target_index,
             u_names=np.array(uneven.target_strings),
             u_topk=np.array(expected_uneven.topk_acc),
             u_precision=expected_uneven.subtoken_precision,
             u_recall=expected_uneven.subtoken_recall,
             u_f1=expected_uneven.subtoken_f1,
             u_loss=expected_uneven.loss)

    # ---- children: 2 processes, one distributed runtime
    port = _free_port()
    out_path = tmp_path / "mp_out.json"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "mp_child.py"),
         str(pid), str(port), str(data_path), str(out_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"child {pid} failed:\n{text}"
        assert f"mp_child {pid}: OK" in text

    with open(out_path) as f:
        got = json.load(f)

    # global loss / train loss already asserted inside each child against
    # the parent's numbers; re-check the reported copies here too
    np.testing.assert_allclose(got["loss_sum"], expected_loss_sum, rtol=1e-5)
    np.testing.assert_allclose(got["train_loss"], expected_train_loss,
                               rtol=1e-5)
    # the distributed Evaluator (per-host shards + counter allreduce) must
    # report exactly the single-process metrics
    np.testing.assert_allclose(got["eval"]["topk_acc"],
                               expected_eval.topk_acc, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["precision"],
                               expected_eval.subtoken_precision, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["recall"],
                               expected_eval.subtoken_recall, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["f1"],
                               expected_eval.subtoken_f1, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["loss"], expected_eval.loss,
                               rtol=1e-6)
