"""Real 2-process `jax.distributed` test (CPU backend, gloo collectives).

Unlike tests/test_distributed.py (which unit-tests the helpers'
single-process semantics), this spawns TWO actual OS processes that join
one distributed runtime — 2 local CPU devices each, 4 global — and runs
the production multi-host path end to end: `distributed.initialize`,
`allreduce_host_scalars`, `global_batch_arrays` feeding the real jitted
train/eval steps over a dp=4 mesh, and the Evaluator's global-metric
reduction. The parent computes every expected number single-process
first; the children must reproduce them exactly (see tests/mp_child.py).
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.evaluation.evaluator import Evaluator
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.training.state import create_train_state, make_optimizer
from code2vec_tpu.training.step import TrainStepBuilder, device_put_batch
from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts

HERE = os.path.dirname(os.path.abspath(__file__))
B, M = 8, 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _full_batch():
    rng = np.random.default_rng(11)
    dims = ModelDims(token_vocab_size=24, path_vocab_size=16,
                     target_vocab_size=16, token_dim=4, path_dim=4)
    src = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (B, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    mask = (rng.random((B, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(2, dims.real_target_vocab_size, (B,)).astype(np.int32)
    valid = np.ones((B,), bool)
    # Mix of in-vocab, multi-subtoken and never-predictable names so the
    # evaluator's tp/fp/fn and top-k counters are all non-trivial.
    names = ["w0", "w1", "w2|w3", "w4", "nosuchname", "w5", "w6|w0", "w7"]
    return dims, RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels, example_valid=valid,
        target_strings=names)


def _vocabs():
    freq = WordFreqDicts(
        token_to_count={"foo": 10, "bar": 8, "baz": 5, "qux": 2},
        path_to_count={"P1": 9, "P2": 7, "P3": 3},
        target_to_count={f"w{i}": 20 - i for i in range(12)},
        num_train_examples=100)
    return Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=30, max_path_vocab_size=20,
        max_target_vocab_size=20)


def _uneven_rows(dims, n):
    """N eval rows for the uneven-shard lockstep phase: hosts will split
    these 10/8, giving host 0 three local batches and host 1 two — the
    exact post-filter divergence VERDICT flagged as a pod deadlock."""
    rng = np.random.default_rng(23)
    src = rng.integers(0, dims.token_vocab_size, (n, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (n, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (n, M)).astype(np.int32)
    mask = (rng.random((n, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(2, dims.real_target_vocab_size, (n,)).astype(np.int32)
    pool = ["w0", "w1", "w2|w3", "w4", "nosuchname", "w5", "w6|w0", "w7",
            "w8", "w1|w9"]
    names = [pool[i % len(pool)] for i in range(n)]
    return RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels,
        example_valid=np.ones((n,), bool), target_strings=names)


def test_two_process_distributed(tmp_path):
    dims, batch = _full_batch()

    # ---- parent: single-device expected values on the full batch
    config = Config(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=B, test_batch_size=B, max_contexts=M,
                    dropout_keep_rate=1.0)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=1.0)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(7))
    builder = TrainStepBuilder(module, opt, config, mesh=None)
    arrays = device_put_batch(batch, None)
    eval_step = builder.make_eval_step(state, k=3)
    out = eval_step(state.params, *arrays)
    expected_loss_sum = float(out.loss_sum)

    evaluator = Evaluator(config, _vocabs(), eval_step, mesh=None,
                          log_path=str(tmp_path / "log_single.txt"))
    expected_eval = evaluator.evaluate(state.params, [batch])

    # ---- parent: single-process expected metrics over the UNEVEN rows
    # (18 rows; children split 10/8 -> 3 vs 2 local batches). Row-wise
    # metrics are grouping-invariant, so the parent batches them 8+8+2pad.
    from code2vec_tpu.data.reader import _pad_rows, _select_rows
    uneven = _uneven_rows(dims, 18)
    ev2 = Evaluator(config, _vocabs(), eval_step, mesh=None,
                    log_path=str(tmp_path / "log_single_uneven.txt"))
    uneven_batches = [
        _pad_rows(_select_rows(uneven, np.arange(s, min(s + B, 18))), B)
        for s in range(0, 18, B)]
    expected_uneven = ev2.evaluate(state.params, uneven_batches)

    # last: the train step donates its state buffers
    train_step = builder.make_train_step(state)
    _, expected_train_loss = train_step(state, *arrays, jax.random.PRNGKey(0))
    expected_train_loss = float(expected_train_loss)

    data_path = tmp_path / "mp_data.npz"
    np.savez(data_path, B=B, src=batch.source_token_indices,
             pth=batch.path_indices, tgt=batch.target_token_indices,
             mask=batch.context_valid_mask, labels=batch.target_index,
             valid=batch.example_valid, names=np.array(batch.target_strings),
             expected_loss_sum=expected_loss_sum,
             expected_train_loss=expected_train_loss,
             u_src=uneven.source_token_indices, u_pth=uneven.path_indices,
             u_tgt=uneven.target_token_indices,
             u_mask=uneven.context_valid_mask, u_labels=uneven.target_index,
             u_names=np.array(uneven.target_strings),
             u_topk=np.array(expected_uneven.topk_acc),
             u_precision=expected_uneven.subtoken_precision,
             u_recall=expected_uneven.subtoken_recall,
             u_f1=expected_uneven.subtoken_f1,
             u_loss=expected_uneven.loss)

    # ---- children: 2 processes, one distributed runtime
    port = _free_port()
    out_path = tmp_path / "mp_out.json"
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "mp_child.py"),
         str(pid), str(port), str(data_path), str(out_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outputs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"child {pid} failed:\n{text}"
        assert f"mp_child {pid}: OK" in text

    with open(out_path) as f:
        got = json.load(f)

    # global loss / train loss already asserted inside each child against
    # the parent's numbers; re-check the reported copies here too
    np.testing.assert_allclose(got["loss_sum"], expected_loss_sum, rtol=1e-5)
    np.testing.assert_allclose(got["train_loss"], expected_train_loss,
                               rtol=1e-5)
    # the distributed Evaluator (per-host shards + counter allreduce) must
    # report exactly the single-process metrics
    np.testing.assert_allclose(got["eval"]["topk_acc"],
                               expected_eval.topk_acc, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["precision"],
                               expected_eval.subtoken_precision, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["recall"],
                               expected_eval.subtoken_recall, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["f1"],
                               expected_eval.subtoken_f1, atol=1e-12)
    np.testing.assert_allclose(got["eval"]["loss"], expected_eval.loss,
                               rtol=1e-6)


# --------------------------------------------------------------------------
# Production-facade 2-process training (VERDICT r4 weak #2): the children
# run Code2VecModel.train() itself over a real packed dataset whose
# per-host post-filter shards are UNEVEN; the parent runs the same global
# stream single-process and the losses/params must agree.

def _write_facade_dataset(root: str):
    """24 train rows / 17 val rows, max_contexts=8. Targets w0..w7 are
    in-vocab; 'zzz' maps to OOV and is dropped by the TRAIN filter.
    OOV rows sit at strided positions 1,3,5,7 (all on host 1's raw
    stride), which under the elastic GLOBAL train order still yields
    equal per-host batch counts; the EVAL shards stay raw-strided and
    uneven (9 vs 8 rows -> 3 vs 2 local eval batches), keeping the
    lockstep eval padding exercised through the facade."""
    import pickle
    import random
    rng = random.Random(3)
    tokens = [f"tok{i}" for i in range(12)]
    paths = [f"path{i}" for i in range(6)]

    def row(target):
        n_ctx = rng.randint(3, 8)
        ctx = [f"{rng.choice(tokens)},{rng.choice(paths)},{rng.choice(tokens)}"
               for _ in range(n_ctx)]
        return f"{target} " + " ".join(ctx) + " " * (8 - n_ctx)

    train_rows = [row("zzz" if i in (1, 3, 5, 7) else f"w{i % 8}")
                  for i in range(24)]
    val_rows = [row("zzz" if i % 7 == 5 else f"w{i % 8}") for i in range(17)]

    prefix = os.path.join(root, "data")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(train_rows) + "\n")
    with open(prefix + ".val.c2v", "w") as f:
        f.write("\n".join(val_rows) + "\n")
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump({t: 10 for t in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({f"w{i}": 10 for i in range(8)}, f)
        pickle.dump(len(train_rows), f)
    return prefix


def test_two_process_facade_train(tmp_path):
    from code2vec_tpu.data.reader import _concat_batches
    from code2vec_tpu.data.packed import PackedDataset, pack_c2v
    from code2vec_tpu.data.reader import EpochEnd, EstimatorAction
    from code2vec_tpu.models.code2vec import ModelDims as MD
    from code2vec_tpu.parallel.distributed import lockstep_train_stream
    from code2vec_tpu.vocab import Code2VecVocabs as CV

    root = str(tmp_path)
    prefix = _write_facade_dataset(root)

    # Single-process mimic of the exact global stream the two hosts will
    # assemble: per-host strided shards, per-epoch seeded shuffle,
    # lockstep-min truncation (2 batches/epoch though host 0 has 3),
    # global batch = [host0 rows, host1 rows]
    # (make_array_from_process_local_data fills process blocks in order).
    config = Config(
        train_data_path_prefix=prefix, max_contexts=8,
        train_batch_size=8, test_batch_size=8, num_train_epochs=2,
        compute_dtype="float32", dropout_keep_rate=1.0,
        use_packed_data=True, verbose_mode=0)
    vocabs = CV.load_or_create(config)
    for role in ("train", "val"):
        pack_c2v(f"{prefix}.{role}.c2v", vocabs, 8)  # pre-pack: children race

    dims = MD.from_config_and_vocabs(config, vocabs)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=1.0)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(config.seed))
    builder = TrainStepBuilder(module, opt, config, mesh=None)
    train_step = builder.make_train_step(state)

    shards = [PackedDataset(prefix + ".train.c2vb", vocabs,
                            shard_index=i, num_shards=2) for i in (0, 1)]
    # Elastic global order: the train filter and permutation are global,
    # so per-host batch counts are EQUAL by construction (20 filtered
    # rows // global batch 8 = 2 per host) even though the raw strided
    # shards hold 12 vs 8 kept rows. The uneven-shard lockstep machinery
    # itself stays covered by mp_child.py's hand-built streams.
    assert [s.steps_per_epoch(4, EstimatorAction.Train)
            for s in shards] == [2, 2]
    streams = [
        lockstep_train_stream(
            s.iter_batches(4, EstimatorAction.Train, num_epochs=2,
                           seed=config.seed, yield_epoch_markers=True), 2)
        for s in shards]
    losses = []
    for item0, item1 in zip(*streams):
        assert isinstance(item0, EpochEnd) == isinstance(item1, EpochEnd)
        if isinstance(item0, EpochEnd):
            continue
        arrays = device_put_batch(_concat_batches([item0, item1]), None)
        state, loss = train_step(state, *arrays, jax.random.PRNGKey(0))
        losses.append(float(loss))
    assert len(losses) == 4  # 2 epochs x agreed-min 2

    final_params = np.concatenate([
        np.asarray(jax.device_get(state.params[k])).ravel()
        for k in sorted(state.params)])
    expect_path = tmp_path / "facade_expect.npz"
    np.savez(expect_path, losses=np.array(losses), final_params=final_params)

    port = _free_port()
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    # cwd=root: the facade's Evaluator writes its per-example log.txt to
    # the working directory; keep child side-effect files in tmp_path.
    procs = [subprocess.Popen(
        [sys.executable, os.path.join(HERE, "mp_child_facade.py"),
         str(pid), str(port), root, str(expect_path)],
        env=env, cwd=root, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
        for pid in (0, 1)]
    outputs = [p.communicate(timeout=420)[0] for p in procs]
    for pid, (p, text) in enumerate(zip(procs, outputs)):
        assert p.returncode == 0, f"facade child {pid} failed:\n{text}"
        assert f"mp_child_facade {pid}: OK" in text

    # Final params bit-identical across hosts.
    digests = [open(os.path.join(root, f"digest{i}.txt")).read()
               for i in (0, 1)]
    assert digests[0] == digests[1], digests

    with open(os.path.join(root, "facade_out.json")) as f:
        got = json.load(f)
    np.testing.assert_allclose(got["losses"], losses, rtol=1e-4)
    assert got["epochs"] == 2
