"""Touched-rows (lazy) Adam parity: sparse update vs dense optax.

The sparse path (training/sparse_adam.py + the sparse train steps in
training/step.py) must agree with a dense optax Adam update exactly on
touched rows, and deviate only in the documented lazy-Adam way on
untouched rows (their moments neither decay nor drive an update).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import optax
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
from code2vec_tpu.training.sparse_adam import (
    HybridOptState, combine_duplicate_rows, sparse_adam_rows,
)
from code2vec_tpu.training.state import create_train_state, make_optimizer
from code2vec_tpu.training.step import TrainStepBuilder, device_put_batch

LR, B1, B2, EPS = 1e-3, 0.9, 0.999, 1e-8


def _np_lazy_adam(table, mu, nu, ids, grads, t):
    """Numpy reference: sum duplicate grads, lazy-update touched rows."""
    table, mu, nu = table.copy(), mu.copy(), nu.copy()
    uniq = np.unique(ids)
    for row in uniq:
        if not (0 <= row < table.shape[0]):
            continue
        g = grads[ids == row].sum(axis=0)
        mu[row] = B1 * mu[row] + (1 - B1) * g
        nu[row] = B2 * nu[row] + (1 - B2) * g * g
        mu_hat = mu[row] / (1 - B1 ** t)
        nu_hat = nu[row] / (1 - B2 ** t)
        table[row] -= LR * mu_hat / (np.sqrt(nu_hat) + EPS)
    return table, mu, nu


def test_combine_duplicate_rows():
    ids = jnp.array([3, 1, 3, 0, 1, 3], jnp.int32)
    grads = jnp.arange(6, dtype=jnp.float32)[:, None] * jnp.ones((6, 2))
    ids_s, g_u, first = jax.jit(combine_duplicate_rows)(ids, grads)
    np.testing.assert_array_equal(np.asarray(ids_s), [0, 1, 1, 3, 3, 3])
    # representative rows carry the duplicate-summed grad, others zero
    rep = np.asarray(first)
    got = np.asarray(g_u)[:, 0]
    np.testing.assert_array_equal(rep, [True, True, False, True, False, False])
    np.testing.assert_allclose(got, [3.0, 1 + 4, 0.0, 0 + 2 + 5, 0.0, 0.0])


@pytest.mark.parametrize("steps", [1, 4])
def test_sparse_adam_rows_matches_numpy_lazy(steps):
    rng = np.random.default_rng(0)
    V, d, N = 13, 5, 9
    table = rng.standard_normal((V, d)).astype(np.float32)
    mu = np.zeros((V, d), np.float32)
    nu = np.zeros((V, d), np.float32)
    jt, jmu, jnu = jnp.asarray(table), jnp.asarray(mu), jnp.asarray(nu)

    step = jax.jit(lambda t_, s_, i_, g_, tt: sparse_adam_rows(
        t_, s_, i_, g_, t=tt, lr=LR, b1=B1, b2=B2, eps=EPS))

    from code2vec_tpu.training.sparse_adam import RowAdamSlots
    slots = RowAdamSlots(mu=jmu, nu=jnu)
    for t in range(1, steps + 1):
        ids = rng.integers(0, V, (N,)).astype(np.int32)
        grads = rng.standard_normal((N, d)).astype(np.float32)
        jt, slots = step(jt, slots, jnp.asarray(ids), jnp.asarray(grads),
                         jnp.asarray(t, jnp.int32))
        table, mu, nu = _np_lazy_adam(table, mu, nu, ids, grads, t)

    np.testing.assert_allclose(np.asarray(jt), table, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(slots.mu), mu, rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(slots.nu), nu, rtol=1e-5, atol=1e-7)


def test_sparse_adam_rows_drops_out_of_range():
    """Out-of-range ids (the TP foreign-row sentinel) change nothing."""
    table = jnp.ones((4, 3))
    from code2vec_tpu.training.sparse_adam import init_slots
    slots = init_slots(table)
    ids = jnp.array([4, 4, 7], jnp.int32)    # all foreign
    grads = jnp.ones((3, 3))
    new_table, new_slots = jax.jit(
        lambda: sparse_adam_rows(table, slots, ids, grads,
                                 t=jnp.asarray(1), lr=LR, b1=B1, b2=B2,
                                 eps=EPS))()
    np.testing.assert_array_equal(np.asarray(new_table), np.asarray(table))
    np.testing.assert_array_equal(np.asarray(new_slots.mu),
                                  np.asarray(slots.mu))


def test_sparse_adam_first_step_matches_dense_optax():
    """From zero moments, one sparse update == one dense optax.adam update
    on the scatter-added gradient (untouched rows move in neither: their
    dense update is -lr*0/(sqrt(0)+eps) = 0)."""
    rng = np.random.default_rng(1)
    V, d, N = 11, 4, 20
    table = rng.standard_normal((V, d)).astype(np.float32)
    ids = rng.integers(0, 7, (N,)).astype(np.int32)   # rows 7..10 untouched
    grads = rng.standard_normal((N, d)).astype(np.float32)

    dense_grad = np.zeros((V, d), np.float32)
    np.add.at(dense_grad, ids, grads)
    tx = optax.adam(LR, b1=B1, b2=B2, eps=EPS)
    opt_state = tx.init(jnp.asarray(table))
    updates, _ = tx.update(jnp.asarray(dense_grad), opt_state)
    dense_new = np.asarray(optax.apply_updates(jnp.asarray(table), updates))

    from code2vec_tpu.training.sparse_adam import init_slots
    sparse_new, _ = sparse_adam_rows(
        jnp.asarray(table), init_slots(jnp.asarray(table)),
        jnp.asarray(ids), jnp.asarray(grads), t=jnp.asarray(1),
        lr=LR, b1=B1, b2=B2, eps=EPS)

    np.testing.assert_allclose(np.asarray(sparse_new), dense_new,
                               rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------- steps

DIMS = ModelDims(token_vocab_size=24, path_vocab_size=16,
                 target_vocab_size=16, token_dim=4, path_dim=4)


def _config(**kw):
    defaults = dict(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=8, test_batch_size=8, max_contexts=8,
                    adam_mu_dtype="float32", dropout_keep_rate=1.0)
    defaults.update(kw)
    return Config(**defaults)


def _batch(rng, B, M, dims):
    # every token/path id appears somewhere => lazy == dense Adam even
    # over multiple steps (all rows touched every step)
    src = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    src.reshape(-1)[:dims.token_vocab_size] = np.arange(dims.token_vocab_size)
    pth = rng.integers(0, dims.path_vocab_size, (B, M)).astype(np.int32)
    pth.reshape(-1)[:dims.path_vocab_size] = np.arange(dims.path_vocab_size)
    tgt = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    mask = np.ones((B, M), np.float32)
    labels = rng.integers(1, dims.real_target_vocab_size, (B,)).astype(np.int32)
    return RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels,
        example_valid=np.ones((B,), bool))


def _state_and_step(config, dims, mesh=None, sparse=True):
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=config.dropout_keep_rate)
    opt = make_optimizer(config)
    cfg = dataclasses.replace(config, use_sparse_embedding_update=sparse)
    state = create_train_state(module, opt, jax.random.PRNGKey(7), mesh=mesh,
                               config=cfg)
    builder = TrainStepBuilder(module, opt, cfg, mesh=mesh)
    return state, builder.make_train_step(state)


@pytest.mark.parametrize("mu_dtype", ["float32", "bfloat16"])
def test_sparse_step_matches_dense_step_all_rows_touched(mu_dtype):
    """Single-device: 3 steps of the sparse train step == 3 steps of the
    dense train step when every embedding row is touched every step
    (dropout off; same rng). bfloat16 mu (the shipped default,
    config.py) exercises the upcast/compute/downcast-delta scatter in
    sparse_adam_rows with correspondingly looser tolerances."""
    config = _config(adam_mu_dtype=mu_dtype)
    batch = _batch(np.random.default_rng(2), 8, 8, DIMS)
    arrays = device_put_batch(batch, None)
    rng = jax.random.PRNGKey(3)

    state_d, step_d = _state_and_step(config, DIMS, sparse=False)
    state_s, step_s = _state_and_step(config, DIMS, sparse=True)
    assert isinstance(state_s.opt_state, HybridOptState)
    assert state_s.opt_state.slots["token_embedding"].mu.dtype == jnp.dtype(mu_dtype)

    for _ in range(3):
        state_d, loss_d = step_d(state_d, *arrays, rng)
        state_s, loss_s = step_s(state_s, *arrays, rng)
    np.testing.assert_allclose(float(loss_d), float(loss_s), rtol=1e-5)
    loose = mu_dtype == "bfloat16"
    for name in state_d.params:
        np.testing.assert_allclose(
            np.asarray(state_d.params[name]), np.asarray(state_s.params[name]),
            rtol=1e-2 if loose else 1e-4, atol=2e-5 if loose else 1e-6,
            err_msg=f"param {name} diverged")


def test_sparse_lazy_leaves_untouched_rows_alone():
    """Rows absent from the batch must not move under the sparse path
    (the documented lazy-Adam deviation from dense Adam)."""
    config = _config()
    rng_np = np.random.default_rng(4)
    B, M = 8, 8
    # restrict ids to the lower half of each vocab
    src = rng_np.integers(0, DIMS.token_vocab_size // 2, (B, M)).astype(np.int32)
    pth = rng_np.integers(0, DIMS.path_vocab_size // 2, (B, M)).astype(np.int32)
    tgt = rng_np.integers(0, DIMS.token_vocab_size // 2, (B, M)).astype(np.int32)
    batch = RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=np.ones((B, M), np.float32),
        target_index=rng_np.integers(1, 16, (B,)).astype(np.int32),
        example_valid=np.ones((B,), bool))
    arrays = device_put_batch(batch, None)

    state, step = _state_and_step(config, DIMS, sparse=True)
    tok0 = np.asarray(state.params["token_embedding"]).copy()
    for t in range(3):
        state, _ = step(state, *arrays, jax.random.PRNGKey(t))
    tok3 = np.asarray(state.params["token_embedding"])
    half = DIMS.token_vocab_size // 2
    np.testing.assert_array_equal(tok3[half:], tok0[half:])
    assert np.abs(tok3[:half] - tok0[:half]).max() > 0


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=8, tp=1, cp=1),
    MeshPlan(dp=2, tp=2, cp=2),
])
def test_gspmd_sparse_step_matches_single_device(plan):
    config = _config(dp=plan.dp, tp=plan.tp, cp=plan.cp,
                     use_manual_tp_kernels=False)
    dims = DIMS.padded_to(plan.tp) if plan.tp > 1 else DIMS
    batch = _batch(np.random.default_rng(5), 8, 8, dims)
    rng = jax.random.PRNGKey(6)

    state1, step1 = _state_and_step(_config(), dims, sparse=True)
    new1, loss1 = step1(state1, *device_put_batch(batch, None), rng)

    mesh = make_mesh(plan)
    stateN, stepN = _state_and_step(config, dims, mesh=mesh, sparse=True)
    newN, lossN = stepN(stateN, *device_put_batch(batch, mesh), rng)

    np.testing.assert_allclose(float(loss1), float(lossN), rtol=1e-5)
    for name in new1.params:
        np.testing.assert_allclose(
            np.asarray(new1.params[name]), np.asarray(newN.params[name]),
            rtol=2e-4, atol=2e-5, err_msg=f"param {name} diverged")


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=2, tp=2, cp=2),
    MeshPlan(dp=1, tp=8, cp=1),
    MeshPlan(dp=2, tp=1, cp=4),
])
def test_manual_sparse_step_matches_single_device(plan):
    """shard_map sparse path (sparse grad exchange via all_gather +
    per-shard row-range updates) == single-device sparse step."""
    config = _config(dp=plan.dp, tp=plan.tp, cp=plan.cp,
                     use_manual_tp_kernels=True)
    dims = DIMS.padded_to(plan.tp) if plan.tp > 1 else DIMS
    batch = _batch(np.random.default_rng(7), 8, 8, dims)
    rng = jax.random.PRNGKey(8)

    state1, step1 = _state_and_step(_config(), dims, sparse=True)
    new1, loss1 = step1(state1, *device_put_batch(batch, None), rng)

    mesh = make_mesh(plan)
    stateN, stepN = _state_and_step(config, dims, mesh=mesh, sparse=True)
    assert (plan.tp > 1 or plan.cp > 1)  # manual kernels engaged
    newN, lossN = stepN(stateN, *device_put_batch(batch, mesh), rng)

    np.testing.assert_allclose(float(loss1), float(lossN), rtol=1e-5)
    for name in new1.params:
        np.testing.assert_allclose(
            np.asarray(new1.params[name]), np.asarray(newN.params[name]),
            rtol=2e-4, atol=2e-5, err_msg=f"param {name} diverged")


def test_adam_kwargs_single_source_with_dense_optimizer():
    """_adam_kwargs (the sparse rows' hyperparameters) must describe
    exactly the transform make_optimizer builds for the dense subtree:
    apply both to the same grads for several steps and require
    bit-identical parameters. Guards against the two drifting apart if
    make_optimizer ever gains a schedule/clipping wrapper. (Pinned to
    the f32-nu path, which routes to stock optax.adam; the bf16-nu
    default path is covered by the nu_dtype test below.)"""
    config = _config(adam_nu_dtype="float32")
    kw = TrainStepBuilder._adam_kwargs(
        type("B", (), {"config": config})())
    reference = optax.adam(learning_rate=kw["lr"], b1=kw["b1"],
                           b2=kw["b2"], eps=kw["eps"],
                           mu_dtype=jnp.dtype(config.adam_mu_dtype))
    production = make_optimizer(config)

    params = {"w": jnp.linspace(-1.0, 1.0, 12).reshape(3, 4)}
    s_ref = reference.init(params)
    s_prod = production.init(params)
    rng = np.random.default_rng(0)
    p_ref, p_prod = params, params
    for _ in range(3):
        g = {"w": jnp.asarray(rng.standard_normal((3, 4)), jnp.float32)}
        u_ref, s_ref = reference.update(g, s_ref, p_ref)
        u_prod, s_prod = production.update(g, s_prod, p_prod)
        p_ref = optax.apply_updates(p_ref, u_ref)
        p_prod = optax.apply_updates(p_prod, u_prod)
    np.testing.assert_array_equal(np.asarray(p_ref["w"]),
                                  np.asarray(p_prod["w"]))


def test_adam_nu_dtype_f32_path_is_stock_and_bf16_tracks_it():
    """adam_nu_dtype='float32' must route to stock optax.adam (bit
    parity, already covered above); the bf16-nu transform must track the
    f32 trajectory within bf16 rounding of the second moment."""
    cfg32 = _config(adam_nu_dtype="float32")
    cfg16 = _config(adam_nu_dtype="bfloat16")
    opt32, opt16 = make_optimizer(cfg32), make_optimizer(cfg16)

    params = {"w": jnp.linspace(-1.0, 1.0, 12).reshape(3, 4)}
    s32, s16 = opt32.init(params), opt16.init(params)
    # nu stored in bf16 on the new path
    leaf16 = jax.tree.leaves(s16)
    assert any(getattr(l, "dtype", None) == jnp.bfloat16 for l in leaf16)
    rng = np.random.default_rng(1)
    p32, p16 = params, params
    for _ in range(5):
        g = {"w": jnp.asarray(rng.standard_normal((3, 4)), jnp.float32)}
        u32, s32 = opt32.update(g, s32, p32)
        u16, s16 = opt16.update(g, s16, p16)
        p32 = optax.apply_updates(p32, u32)
        p16 = optax.apply_updates(p16, u16)
    np.testing.assert_allclose(np.asarray(p32["w"]), np.asarray(p16["w"]),
                               rtol=2e-2, atol=2e-4)
