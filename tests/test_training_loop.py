"""Epoch-accurate training-loop behavior: EpochEnd markers at data-pass
boundaries, per-epoch save/eval scheduling, mid-epoch evaluation cadence
(reference: keras_model.py:326-369), resume epoch numbering (reference:
keras_model.py:264-274), the eval-loss OOV exclusion, and the native
TensorBoard scalar writer."""

import os
import pickle
import struct

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.packed import PackedDataset, pack_c2v
from code2vec_tpu.data.reader import (
    EpochEnd, EstimatorAction, PathContextReader, RowBatch,
)
from code2vec_tpu.training.loop import Trainer


def _write_c2v(path, lines):
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


@pytest.fixture
def packed_ds(tiny_config, tiny_vocabs, tmp_path):
    # 5 rows; one has an unknown target -> filtered from training.
    lines = ["get|name foo,P1,bar baz,P2,foo  ",
             "set|value bar,P3,baz   ",
             "run foo,P2,foo bar,P1,bar  ",
             "get|name baz,P1,foo   ",
             "unknowntarget foo,P1,bar   "]
    _write_c2v(tiny_config.train_data_path, lines)
    packed = pack_c2v(tiny_config.train_data_path, tiny_vocabs,
                      tiny_config.max_contexts)
    return PackedDataset(packed, tiny_vocabs)


def test_packed_epoch_markers_and_steps(packed_ds):
    # 4 trainable rows, batch 2 -> 2 full batches/epoch.
    assert packed_ds.steps_per_epoch(2, EstimatorAction.Train) == 2
    items = list(packed_ds.iter_batches(2, EstimatorAction.Train,
                                        num_epochs=3,
                                        yield_epoch_markers=True))
    markers = [x for x in items if isinstance(x, EpochEnd)]
    assert [m.epoch for m in markers] == [1, 2, 3]
    # each epoch: exactly 2 batches then its marker
    shape = [isinstance(x, EpochEnd) for x in items]
    assert shape == [False, False, True] * 3
    # default: no markers (back-compat for non-trainer consumers)
    plain = list(packed_ds.iter_batches(2, EstimatorAction.Train,
                                        num_epochs=1))
    assert not any(isinstance(x, EpochEnd) for x in plain)


def test_reader_epoch_markers(tiny_config, tiny_vocabs):
    lines = [f"get|name foo,P1,bar baz,P2,foo  " for _ in range(8)]
    _write_c2v(tiny_config.train_data_path, lines)
    tiny_config.num_train_epochs = 2
    tiny_config.shuffle_buffer_size = 4
    reader = PathContextReader(tiny_vocabs, tiny_config,
                               EstimatorAction.Train,
                               yield_epoch_markers=True)
    items = list(reader)
    markers = [x for x in items if isinstance(x, EpochEnd)]
    assert [m.epoch for m in markers] == [1, 2]
    batches = [x for x in items if not isinstance(x, EpochEnd)]
    # 16 filtered rows over 2 epochs, batch 2 -> 8 batches total
    assert sum(b.target_index.shape[0] for b in batches) == 16


def _fake_batch(n=2, m=4):
    return RowBatch(
        source_token_indices=np.ones((n, m), np.int32),
        path_indices=np.ones((n, m), np.int32),
        target_token_indices=np.ones((n, m), np.int32),
        context_valid_mask=np.ones((n, m), np.float32),
        target_index=np.ones((n,), np.int32),
        example_valid=np.ones((n,), bool))


def _marker_stream(batches_per_epoch, epochs):
    for e in range(epochs):
        for _ in range(batches_per_epoch):
            yield _fake_batch()
        yield EpochEnd(e + 1)


class _State:
    step = np.zeros((), np.int32)


def _run_trainer(config, stream, **kw):
    saves, evals = [], []

    def train_step(state, *args):
        return state, np.float32(1.0)

    trainer = Trainer(config, train_step,
                      evaluate_fn=lambda s: evals.append(1),
                      save_fn=lambda s, e: saves.append(e), **kw)
    trainer.train(_State(), stream, rng=np.zeros((2,), np.uint32))
    return saves, evals


def test_trainer_saves_and_evals_once_per_epoch(tiny_config):
    tiny_config.num_train_epochs = 3
    tiny_config.verbose_mode = 0
    saves, evals = _run_trainer(tiny_config, _marker_stream(5, 3))
    assert saves == [1, 2, 3]
    assert len(evals) == 3  # exactly one per data pass, incl. the final


def test_trainer_resume_continues_epoch_numbering(tiny_config):
    tiny_config.num_train_epochs = 2
    tiny_config.verbose_mode = 0
    saves, _ = _run_trainer(tiny_config, _marker_stream(3, 2),
                            initial_epoch=5)
    assert saves == [6, 7]


def test_trainer_final_epoch_always_evaluated(tiny_config):
    # save_every_epochs=2 with 3 epochs: boundary epochs 2 and (forced) 3.
    tiny_config.num_train_epochs = 3
    tiny_config.save_every_epochs = 2
    tiny_config.verbose_mode = 0
    saves, evals = _run_trainer(tiny_config, _marker_stream(4, 3))
    assert saves == [2, 3]
    assert len(evals) == 2


def test_trainer_mid_epoch_eval_cadence(tiny_config):
    # reference: NUM_TRAIN_BATCHES_TO_EVALUATE (keras_model.py:326-369).
    tiny_config.num_train_epochs = 1
    tiny_config.num_train_batches_to_evaluate = 3
    tiny_config.verbose_mode = 0
    saves, evals = _run_trainer(tiny_config, _marker_stream(8, 1))
    # batches 3 and 6 mid-epoch, plus the epoch-end eval
    assert len(evals) == 3
    assert saves == [1]


# ------------------------------------------------------------ tb writer

def _read_tb_events(path):
    """Minimal TFRecord/Event parser validating the framing CRCs."""
    from code2vec_tpu.utils.tb import _masked_crc
    events = []
    with open(path, "rb") as f:
        while True:
            header = f.read(8)
            if len(header) < 8:
                break
            (length,) = struct.unpack("<Q", header)
            (hcrc,) = struct.unpack("<I", f.read(4))
            assert hcrc == _masked_crc(header)
            data = f.read(length)
            (dcrc,) = struct.unpack("<I", f.read(4))
            assert dcrc == _masked_crc(data)
            events.append(data)
    return events


def test_tb_writer_roundtrip(tmp_path):
    from code2vec_tpu.utils.tb import ScalarWriter
    w = ScalarWriter(str(tmp_path / "tb"))
    w.scalar("train/loss", 1.5, step=7)
    w.scalar("eval/f1", 0.25, step=7)
    w.close()
    events = _read_tb_events(w.path)
    assert len(events) == 3  # file_version + 2 scalars
    assert b"brain.Event:2" in events[0]
    assert b"train/loss" in events[1]
    # float 1.5 little-endian must appear in the first scalar event
    assert struct.pack("<f", 1.5) in events[1]
    assert b"eval/f1" in events[2]


# ------------------------------------------------- eval-loss OOV exclusion

def test_eval_loss_excludes_oov_targets(tiny_vocabs, tiny_config):
    import jax
    import jax.numpy as jnp
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.training.state import create_train_state, make_optimizer
    from code2vec_tpu.training.step import TrainStepBuilder

    tiny_config.compute_dtype = "float32"
    dims = ModelDims.from_config_and_vocabs(tiny_config, tiny_vocabs)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32)
    opt = make_optimizer(tiny_config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               config=tiny_config)
    builder = TrainStepBuilder(module, opt, tiny_config)
    eval_step = builder.make_eval_step(state)

    n, m = 4, tiny_config.max_contexts
    src = jnp.ones((n, m), jnp.int32)
    pth = jnp.ones((n, m), jnp.int32)
    tgt = jnp.ones((n, m), jnp.int32)
    mask = jnp.ones((n, m), jnp.float32)
    valid = jnp.array([True, True, True, False])
    oov = tiny_vocabs.target_vocab.oov_index
    labels_all_known = jnp.array([2, 3, 2, 2], jnp.int32)
    labels_one_oov = jnp.array([2, 3, oov, 2], jnp.int32)

    out_known = eval_step(state.params, src, pth, tgt, mask,
                          labels_all_known, valid)
    out_oov = eval_step(state.params, src, pth, tgt, mask,
                        labels_one_oov, valid)
    # the OOV row contributes nothing; the padded-invalid row never does
    assert float(out_oov.loss_sum) < float(out_known.loss_sum)
    two_rows = eval_step(state.params, src, pth, tgt, mask,
                         labels_all_known,
                         jnp.array([True, True, False, False]))
    np.testing.assert_allclose(float(out_oov.loss_sum),
                               float(two_rows.loss_sum), rtol=1e-6)


# ------------------------------------------- checkpoint mode mismatch

def test_checkpoint_mode_mismatch_is_a_clear_error(tmp_path, tiny_vocabs,
                                                   tiny_config):
    import dataclasses
    import jax
    import jax.numpy as jnp
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.training import checkpoint as ckpt_mod
    from code2vec_tpu.training.state import create_train_state, make_optimizer

    tiny_config.compute_dtype = "float32"
    dims = ModelDims.from_config_and_vocabs(tiny_config, tiny_vocabs)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32)
    opt = make_optimizer(tiny_config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               config=tiny_config)
    path = str(tmp_path / "model")
    ckpt_mod.save_model(path, state, tiny_vocabs, tiny_config, epoch=4)

    meta = ckpt_mod.load_model_meta(path)
    assert meta["epoch"] == 4
    assert meta["use_sparse_embedding_update"] is False

    sparse_config = dataclasses.replace(tiny_config,
                                        use_sparse_embedding_update=True)
    with pytest.raises(ValueError, match="use_sparse_embedding_update"):
        ckpt_mod.load_model(path, state, config=sparse_config)
    # released artifacts are mode-agnostic
    rel = ckpt_mod.save_model(path, state, tiny_vocabs, tiny_config,
                              released=True)
    restored = ckpt_mod.load_model(rel, state, config=sparse_config)
    assert int(np.asarray(restored.step)) == int(np.asarray(state.step))


def test_preemption_sigterm_saves_and_stops(tiny_config):
    """SIGTERM mid-epoch -> one checkpoint of the in-flight state, clean
    early exit (PreemptionWatcher; SURVEY §5 failure detection)."""
    import os as _os
    import signal as _signal

    tiny_config.num_train_epochs = 3
    saves, steps = [], []

    def stream():
        for e in range(3):
            for b in range(4):
                yield _fake_batch()
            yield EpochEnd(e + 1)

    def train_step(state, *args):
        steps.append(1)
        # SIGTERM from the CONSUMER side at a fixed consumed step
        # (epoch 2, batch 2): deterministic regardless of how far the
        # prefetch worker has raced ahead of consumption.
        if len(steps) == 6:
            _os.kill(_os.getpid(), _signal.SIGTERM)
        return state, np.float32(1.0)

    def save_fn(state, epoch, suffix=""):
        saves.append((epoch, suffix))

    trainer = Trainer(tiny_config, train_step, save_fn=save_fn)
    trainer.train(_State(), stream(), rng=np.zeros((2,), np.uint32))

    # stopped early: well short of the 12 batches in the stream
    assert len(steps) < 12
    assert trainer.preempted
    # the preemption checkpoint gets a distinct suffixed name so the
    # clean end-of-epoch-1 artifact is never clobbered
    assert saves[0] == (1, "")            # normal end-of-epoch-1 save
    assert saves[-1] == (1, "_preempt")   # preemption save during epoch 2
    assert len(saves) == 2
    # handler restored: a later SIGTERM must not set any stale flag
    assert _signal.getsignal(_signal.SIGTERM) in (
        _signal.SIG_DFL, _signal.default_int_handler, None)


def test_preemption_disabled_by_config(tiny_config):
    """save_on_preemption=False: no handler installed, the run ignores
    the watcher entirely (SIGTERM would kill the process as before)."""
    import signal as _signal
    tiny_config.num_train_epochs = 1
    tiny_config.save_on_preemption = False
    prev = _signal.getsignal(_signal.SIGTERM)
    saves, _ = _run_trainer(tiny_config, _marker_stream(2, 1))
    assert _signal.getsignal(_signal.SIGTERM) is prev
    assert saves == [1]


def test_profiler_hook_writes_trace(tiny_config, tmp_path):
    """--profile_dir captures a jax.profiler trace between batches 10 and
    20 (§5 tracing; loop.py profiler hook)."""
    tiny_config.num_train_epochs = 1
    profile_dir = str(tmp_path / "trace")

    def train_step(state, *args):
        return state, np.float32(1.0)

    trainer = Trainer(tiny_config, train_step, profile_dir=profile_dir)
    trainer.train(_State(), _marker_stream(25, 1), rng=np.zeros((2,), np.uint32))

    import glob as _glob
    written = _glob.glob(profile_dir + "/**", recursive=True)
    assert any(os.path.isfile(p) for p in written), written


def test_release_loads_params_only_across_optimizer_mismatch(
        tmp_path, tiny_vocabs, tiny_config):
    """--release is the advertised escape hatch for every optimizer
    layout/dtype mismatch error, so its load path must not run those
    guards: a params-only load succeeds across both a sparse-mode and an
    Adam-dtype mismatch, and the released artifact then loads anywhere."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.training import checkpoint as ckpt_mod
    from code2vec_tpu.training.state import create_train_state, make_optimizer

    tiny_config.compute_dtype = "float32"
    tiny_config.adam_mu_dtype = "float32"
    dims = ModelDims.from_config_and_vocabs(tiny_config, tiny_vocabs)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32)
    opt = make_optimizer(tiny_config)
    state = create_train_state(module, opt, jax.random.PRNGKey(0),
                               config=tiny_config)
    path = str(tmp_path / "model")
    ckpt_mod.save_model(path, state, tiny_vocabs, tiny_config, epoch=2)

    mismatched = dataclasses.replace(tiny_config, adam_mu_dtype="bfloat16",
                                     use_sparse_embedding_update=True)
    # the guarded (resume) path rejects it...
    with pytest.raises(ValueError):
        ckpt_mod.load_model(path, state, config=mismatched)
    # ...while the release path loads params-only and re-saves weights-only
    rel = ckpt_mod.release_model(path, str(tmp_path / "out"), state,
                                 tiny_vocabs, mismatched)
    assert ckpt_mod.load_model_meta(rel)["released"] is True
    restored = ckpt_mod.load_model(rel, state, config=mismatched)
    tok = "token_embedding"
    np.testing.assert_array_equal(np.asarray(restored.params[tok]),
                                  np.asarray(state.params[tok]))


def test_rss_limit_checkpoints_and_stops(tiny_config):
    """Peak RSS over config.rss_limit_gb -> same clean checkpoint-and-
    stop as a SIGTERM preemption (host-memory watchdog; turns a kernel
    OOM kill into a resumable stop)."""
    tiny_config.num_train_epochs = 3
    # any real process has peak RSS far above 1 MB: trips immediately
    tiny_config.rss_limit_gb = 0.001
    saves, steps = [], []

    def stream():
        for e in range(3):
            for b in range(4):
                yield _fake_batch()
            yield EpochEnd(e + 1)

    def train_step(state, *args):
        steps.append(1)
        return state, np.float32(1.0)

    def save_fn(state, epoch, suffix=""):
        saves.append((epoch, suffix))

    logs = []
    tiny_config.log = logs.append
    trainer = Trainer(tiny_config, train_step, save_fn=save_fn)
    trainer.train(_State(), stream(), rng=np.zeros((2,), np.uint32))

    assert len(steps) == 1  # tripped at the first step boundary
    assert trainer.preempted
    assert saves == [(0, "_preempt")]
    assert any("exceeds rss_limit_gb" in m for m in logs)


def test_rss_limit_disabled_by_default(tiny_config):
    """rss_limit_gb=0 (default): the watchdog never fires."""
    tiny_config.num_train_epochs = 1
    steps = []

    def stream():
        for b in range(4):
            yield _fake_batch()
        yield EpochEnd(1)

    def train_step(state, *args):
        steps.append(1)
        return state, np.float32(1.0)

    trainer = Trainer(tiny_config, train_step)
    trainer.train(_State(), stream(), rng=np.zeros((2,), np.uint32))
    assert len(steps) == 4
    assert not trainer.preempted
