"""Reader semantics tests vs the reference's row parse/filter
(path_context_reader.py:153-228)."""

import numpy as np
import pytest

from code2vec_tpu.data.reader import (
    EstimatorAction, PathContextReader, parse_context_lines, row_filter_mask,
)
from code2vec_tpu.data.packed import pack_c2v, PackedDataset


def _write_c2v(path, lines):
    with open(path, "w") as f:
        for line in lines:
            f.write(line + "\n")


def test_parse_basic(tiny_vocabs):
    lines = ["get|name foo,P1,bar baz,P2,foo  "]
    batch = parse_context_lines(lines, tiny_vocabs, max_contexts=4,
                                estimator_action=EstimatorAction.Train)
    tv, pv = tiny_vocabs.token_vocab, tiny_vocabs.path_vocab
    assert batch.target_index[0] == tiny_vocabs.target_vocab.lookup_index("get|name")
    np.testing.assert_array_equal(
        batch.source_token_indices[0],
        [tv.lookup_index("foo"), tv.lookup_index("baz"), 0, 0])
    np.testing.assert_array_equal(
        batch.path_indices[0],
        [pv.lookup_index("P1"), pv.lookup_index("P2"), 0, 0])
    np.testing.assert_array_equal(batch.context_valid_mask[0], [1, 1, 0, 0])


def test_parse_oov_parts_counted_valid_only_if_any_nonpad(tiny_vocabs):
    # all-OOV context: in the joined PAD/OOV scheme the indices are all 0
    # == PAD, so the context is INVALID (reference FIXME at
    # path_context_reader.py:209-214 resolved as 'just no padding').
    lines = ["run unknown,UNKNOWNPATH,unknown   "]
    batch = parse_context_lines(lines, tiny_vocabs, 4, EstimatorAction.Train)
    np.testing.assert_array_equal(batch.context_valid_mask[0], [0, 0, 0, 0])
    # partially-known context stays valid
    lines = ["run foo,UNKNOWNPATH,unknown   "]
    batch = parse_context_lines(lines, tiny_vocabs, 4, EstimatorAction.Train)
    np.testing.assert_array_equal(batch.context_valid_mask[0], [1, 0, 0, 0])


def test_row_filter_train_drops_oov_target_and_invalid_rows(tiny_vocabs):
    lines = [
        "get|name foo,P1,bar   ",        # keep
        "unknowntarget foo,P1,bar   ",   # drop in train (OOV target), keep in eval
        "run unk,UNK,unk   ",            # drop everywhere (no valid context)
    ]
    batch = parse_context_lines(lines, tiny_vocabs, 4, EstimatorAction.Train)
    train_mask = row_filter_mask(batch, tiny_vocabs, EstimatorAction.Train)
    eval_mask = row_filter_mask(batch, tiny_vocabs, EstimatorAction.Evaluate)
    np.testing.assert_array_equal(train_mask, [True, False, False])
    np.testing.assert_array_equal(eval_mask, [True, True, False])


def test_malformed_context_parts_are_pad(tiny_vocabs):
    lines = ["run foo,P1 bar   "]  # 2-field and 1-field contexts
    batch = parse_context_lines(lines, tiny_vocabs, 4, EstimatorAction.Train)
    tv = tiny_vocabs.token_vocab
    assert batch.source_token_indices[0, 0] == tv.lookup_index("foo")
    assert batch.target_token_indices[0, 0] == tv.pad_index
    assert batch.source_token_indices[0, 1] == tv.lookup_index("bar")


def test_reader_end_to_end_train_batches(tiny_vocabs, tiny_config, tmp_path):
    lines = ["get|name foo,P1,bar baz,P2,foo  ",
             "set|value bar,P3,baz   ",
             "run foo,P2,qux   ",
             "unknowntarget foo,P1,bar   ",  # filtered in train
             "get|name qux,P1,foo   "]
    _write_c2v(tiny_config.train_data_path, lines)
    reader = PathContextReader(tiny_vocabs, tiny_config, EstimatorAction.Train)
    batches = list(reader)
    # 4 valid rows, batch size 2 -> 2 full batches
    assert len(batches) == 2
    for b in batches:
        assert b.source_token_indices.shape == (2, 4)
        assert b.num_valid == 2


def test_reader_eval_pads_tail(tiny_vocabs, tiny_config, tmp_path):
    lines = ["get|name foo,P1,bar   ",
             "unknowntarget bar,P2,foo   ",
             "run baz,P3,qux   "]
    test_path = str(tmp_path / "data.val.c2v")
    _write_c2v(test_path, lines)
    tiny_config.test_data_path = test_path
    reader = PathContextReader(tiny_vocabs, tiny_config, EstimatorAction.Evaluate)
    batches = list(reader)
    assert len(batches) == 2
    assert batches[0].num_valid == 2
    assert batches[1].num_valid == 1          # padded tail
    assert batches[1].example_valid.tolist() == [True, False]
    assert batches[1].target_strings[0] == "run"


def test_host_sharding_disjoint(tiny_vocabs, tiny_config):
    lines = ["get|name foo,P1,bar   " for _ in range(10)]
    _write_c2v(tiny_config.train_data_path, lines)
    r0 = PathContextReader(tiny_vocabs, tiny_config, EstimatorAction.Train,
                           shard_index=0, num_shards=2)
    r1 = PathContextReader(tiny_vocabs, tiny_config, EstimatorAction.Train,
                           shard_index=1, num_shards=2)
    n0 = sum(b.num_valid for b in r0)
    n1 = sum(b.num_valid for b in r1)
    assert n0 == n1 == 4  # 5 rows each, batch 2, tail dropped


def test_packed_roundtrip_matches_text_parse(tiny_vocabs, tiny_config):
    lines = ["get|name foo,P1,bar baz,P2,foo  ",
             "set|value bar,P3,baz   ",
             "unknowntarget foo,P1,bar   ",
             "run unk,UNK,unk   "]
    _write_c2v(tiny_config.train_data_path, lines)
    packed_path = pack_c2v(tiny_config.train_data_path, tiny_vocabs,
                           tiny_config.max_contexts)
    ds = PackedDataset(packed_path, tiny_vocabs)
    assert ds.num_rows_total == 4
    text = parse_context_lines(lines, tiny_vocabs, 4, EstimatorAction.Evaluate)
    packed = ds.gather(np.arange(4), with_target_strings=True)
    np.testing.assert_array_equal(packed.source_token_indices,
                                  text.source_token_indices)
    np.testing.assert_array_equal(packed.path_indices, text.path_indices)
    np.testing.assert_array_equal(packed.target_token_indices,
                                  text.target_token_indices)
    np.testing.assert_array_equal(packed.context_valid_mask,
                                  text.context_valid_mask)
    np.testing.assert_array_equal(packed.target_index, text.target_index)
    assert packed.target_strings == text.target_strings


def test_packed_iter_filters_and_batches(tiny_vocabs, tiny_config):
    lines = ["get|name foo,P1,bar   ",
             "set|value bar,P3,baz   ",
             "unknowntarget foo,P1,bar   ",  # train-filtered
             "run unk,UNK,unk   ",           # always filtered
             "run foo,P2,qux   "]
    _write_c2v(tiny_config.train_data_path, lines)
    packed_path = pack_c2v(tiny_config.train_data_path, tiny_vocabs, 4)
    ds = PackedDataset(packed_path, tiny_vocabs)
    train_batches = list(ds.iter_batches(2, EstimatorAction.Train, num_epochs=1))
    assert len(train_batches) == 1  # 3 valid rows -> 1 full batch, tail dropped
    eval_batches = list(ds.iter_batches(2, EstimatorAction.Evaluate))
    assert sum(b.num_valid for b in eval_batches) == 4


def test_packed_vocab_fingerprint_mismatch(tiny_vocabs, tiny_config):
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts
    _write_c2v(tiny_config.train_data_path, ["get|name foo,P1,bar   "])
    packed_path = pack_c2v(tiny_config.train_data_path, tiny_vocabs, 4)
    other = Code2VecVocabs.create_from_freq_dicts(
        WordFreqDicts({"zzz": 1}, {"Q": 1}, {"t": 1}, 1),
        max_token_vocab_size=5, max_path_vocab_size=5, max_target_vocab_size=5)
    with pytest.raises(ValueError, match="different vocabularies"):
        PackedDataset(packed_path, other)


# -------------------------------------------- resume cursor (text reader)
#
# The text reader honors the checkpoint data cursor like the packed
# dataset does (PR-6 residue closed): the epoch-keyed shuffled order is
# deterministic, so skipping the first `skip_rows` post-filter rows of
# the resumed epoch obeys the packed reader's cursor laws — the resumed
# stream is EXACTLY the uninterrupted stream minus its first skip_rows
# rows, and later epochs are untouched.


def _epoch_targets(batches):
    """[per-epoch concatenated target_index arrays] from a marker
    stream."""
    from code2vec_tpu.data.reader import EpochEnd
    epochs, current = [], []
    for item in batches:
        if isinstance(item, EpochEnd):
            epochs.append(np.concatenate([b.target_index for b in current])
                          if current else np.empty((0,), np.int32))
            current = []
        else:
            current.append(item)
    return epochs


def _cursor_lines(n=14):
    targets = ["get|name", "set|value", "run"]
    ctxs = ["foo,P1,bar", "baz,P2,foo", "qux,P3,baz"]
    return [f"{targets[i % 3]} {ctxs[i % 3]} {ctxs[(i + 1) % 3]}  "
            for i in range(n)]


def _text_reader(tiny_vocabs, tiny_config, skip_rows=0,
                 parse_chunk_lines=3):
    return PathContextReader(tiny_vocabs, tiny_config,
                             EstimatorAction.Train,
                             yield_epoch_markers=True,
                             skip_rows=skip_rows,
                             parse_chunk_lines=parse_chunk_lines)


def test_text_reader_cursor_is_exact_stream_suffix(tiny_vocabs,
                                                   tiny_config):
    """batches(skip=k) == batches(skip=0) minus the first k rows of
    epoch 0 — the packed reader's cursor law, on the text path. The
    tiny parse_chunk_lines makes the skip span chunk boundaries."""
    _write_c2v(tiny_config.train_data_path, _cursor_lines())
    tiny_config.num_train_epochs = 2
    # small buffer: the shuffle-boundary smear must not eat the whole
    # first epoch (the law below is about the STREAM, not the marker)
    tiny_config.shuffle_buffer_size = 2
    full = _epoch_targets(list(_text_reader(tiny_vocabs, tiny_config)))
    assert len(full) == 2 and len(full[0]) >= 8
    for skip in (2, 4, 6):  # multiples of the batch size (the facade
        # rounds the cursor down to a global batch multiple)
        resumed = _epoch_targets(
            list(_text_reader(tiny_vocabs, tiny_config,
                              skip_rows=skip)))
        np.testing.assert_array_equal(resumed[0], full[0][skip:])
        np.testing.assert_array_equal(resumed[1], full[1])


def test_text_reader_cursor_clears_at_epoch_boundary(tiny_vocabs,
                                                     tiny_config):
    """A stale over-long cursor consumes at most the first epoch —
    the boundary marker clears it, so epoch 2 streams in full."""
    _write_c2v(tiny_config.train_data_path, _cursor_lines())
    tiny_config.num_train_epochs = 2
    tiny_config.shuffle_buffer_size = 2
    full = _epoch_targets(list(_text_reader(tiny_vocabs, tiny_config)))
    resumed = _epoch_targets(
        list(_text_reader(tiny_vocabs, tiny_config, skip_rows=10 ** 6)))
    assert len(resumed[0]) == 0
    np.testing.assert_array_equal(resumed[1], full[1])


def test_text_reader_cursor_matches_packed_law_shape(tiny_vocabs,
                                                     tiny_config):
    """Same skip, same law on the packed reader — pinning that the two
    pipelines agree on what a cursor MEANS (a count of post-filter
    rows consumed off the epoch's deterministic order)."""
    lines = _cursor_lines()
    _write_c2v(tiny_config.train_data_path, lines)
    packed_path = pack_c2v(tiny_config.train_data_path, tiny_vocabs, 4)
    ds = PackedDataset(packed_path, tiny_vocabs)
    full = _epoch_targets(list(ds.iter_batches(
        2, EstimatorAction.Train, num_epochs=1, seed=0,
        yield_epoch_markers=True)))
    resumed = _epoch_targets(list(ds.iter_batches(
        2, EstimatorAction.Train, num_epochs=1, seed=0,
        yield_epoch_markers=True, skip_rows=4)))
    np.testing.assert_array_equal(resumed[0], full[0][4:])
