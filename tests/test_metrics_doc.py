"""Tier-1 gate for scripts/check_metrics_doc.py: every metric name
registered under code2vec_tpu/ must appear in the README "Telemetry"
metrics reference table and vice versa — a new metric cannot ship
undocumented, and the table cannot keep names the code dropped."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO_ROOT, "scripts", "check_metrics_doc.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_metrics_doc",
                                                  CHECKER)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_registered_metric_is_documented_and_vice_versa():
    checker = _load_checker()
    problems = checker.check()
    assert problems == [], "\n".join(problems)


def test_checker_extracts_a_plausible_registration_set():
    """The AST walk must actually see the registry: spot-check names
    from different layers (training, checkpointing, serving, obs) so a
    silently-broken walk cannot turn the doc check vacuous."""
    checker = _load_checker()
    names = set(checker.registered_metric_names())
    assert len(names) >= 80
    for expected in ("train_batches_total", "checkpoint_save_seconds",
                     "serving_requests_total", "obs_spans_dropped_total",
                     "flight_incidents_total", "retrieval_search_seconds",
                     "eval_topk_acc"):
        assert expected in names, f"{expected} missing from the walk"


def test_checker_flags_undocumented_and_stale(tmp_path, monkeypatch):
    """The check fails in BOTH directions: a registered-but-undocumented
    name and a documented-but-unregistered name each produce a
    problem."""
    checker = _load_checker()
    readme = tmp_path / "README.md"
    documented = sorted(checker.registered_metric_names())
    rows = "\n".join(f"| `{n}` | x |" for n in documented
                     if n != "serving_requests_total")
    readme.write_text(
        "# x\n<!-- metrics-table:begin -->\n"
        f"{rows}\n| `made_up_metric_total` | x |\n"
        "<!-- metrics-table:end -->\n")
    monkeypatch.setattr(checker, "README", str(readme))
    problems = checker.check()
    assert any("UNDOCUMENTED: serving_requests_total" in p
               for p in problems)
    assert any("STALE DOC: made_up_metric_total" in p for p in problems)
