"""Child process + shared fixtures for tests/test_chaos.py — NOT a pytest
module.

Subcommands (parent runs `python chaos_child.py <cmd> ...` and inspects
the exit code, stdout markers, and the on-disk checkpoint state):

- `save-seq <base> <n> [fault_spec]` — save deterministic artifacts
  `<base>_iter1..n`; when `fault_spec` is given it is armed immediately
  before the LAST save, so saves 1..n-1 commit cleanly and save n dies
  at the injected point (`exit` action = os._exit, the in-process
  stand-in for SIGKILL landing mid-save). The parent then asserts the
  resume chain falls back to `_iter<n-1>` with bit-equal params.
  A fault can also arrive via the C2V_FAULTS env var (then it counts
  hits from the very first save — used with n=1).

- `train <workdir> <save_base>` — real facade training on a tiny
  synthetic dataset with per-epoch checkpoints, running until killed.
  Prints `CHAOS_TRAIN_STARTED` once training begins. The parent waits
  for the first committed artifact, sends SIGTERM, and expects the
  preemption path to write `_iter<N>_preempt` and exit 0.

The deterministic-state builders live here (not in the test module) so
both the child process and the in-process tests construct bit-identical
pytrees from the same code.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

import numpy as np  # noqa: E402


def build_vocabs():
    from code2vec_tpu.vocab import Code2VecVocabs, WordFreqDicts
    freq = WordFreqDicts(
        token_to_count={"foo": 10, "bar": 8, "baz": 5, "qux": 2},
        path_to_count={"P1": 9, "P2": 7, "P3": 3},
        target_to_count={"get|name": 6, "set|value": 4, "run": 2},
        num_train_examples=100,
    )
    return Code2VecVocabs.create_from_freq_dicts(
        freq, max_token_vocab_size=10, max_path_vocab_size=10,
        max_target_vocab_size=10)


def build_config():
    from code2vec_tpu.config import Config
    return Config(max_contexts=4, default_embeddings_size=8)


def build_state(epoch: int):
    """A tiny TrainState whose every leaf is a pure function of `epoch`,
    so the parent can reconstruct the exact arrays any artifact must
    restore to (the bit-equality oracle for the resume chain)."""
    from code2vec_tpu.training.state import TrainState
    rng = np.random.RandomState(1000 + epoch)
    params = {
        "token_embedding": rng.randn(6, 8).astype(np.float32),
        "path_embedding": rng.randn(5, 8).astype(np.float32),
        "target_embedding": rng.randn(4, 24).astype(np.float32),
    }
    opt_state = {
        "mu": {k: (0.1 * v).astype(np.float32) for k, v in params.items()},
        "nu": {k: (v * v).astype(np.float32) for k, v in params.items()},
        "count": np.asarray(epoch * 7, np.int32),
    }
    return TrainState(step=np.asarray(epoch * 10, np.int32),
                      params=params, opt_state=opt_state)


def cmd_save_seq(base: str, n: int, fault_spec: str) -> None:
    from code2vec_tpu.training import checkpoint as ckpt_mod
    from code2vec_tpu.utils import faults
    vocabs = build_vocabs()
    config = build_config()
    for epoch in range(1, n + 1):
        if fault_spec and epoch == n:
            faults.reset(fault_spec)
        ckpt_mod.save_model(f"{base}_iter{epoch}", build_state(epoch),
                            vocabs, config, epoch=epoch)
        print(f"CHAOS_SAVED {epoch}", flush=True)


def make_synthetic_dataset(dirname: str, n_rows: int = 64,
                           max_contexts: int = 8, seed: int = 0) -> str:
    """Tiny learnable dataset in the .c2v text layout (same shape as
    tests/test_end_to_end.py's, smaller)."""
    import pickle
    import random
    rng = random.Random(seed)
    tokens = [f"tok{i}" for i in range(8)]
    paths = [f"path{i}" for i in range(4)]
    targets = [f"name|t{i}" for i in range(4)]
    rows = []
    for _ in range(n_rows):
        t = rng.randrange(len(targets))
        contexts = [f"{tokens[t * 2 + rng.randrange(2)]},{rng.choice(paths)},"
                    f"{tokens[t * 2]}"
                    for _ in range(rng.randint(3, max_contexts))]
        pad = " " * (max_contexts - len(contexts))
        rows.append(f"{targets[t]} " + " ".join(contexts) + pad)
    prefix = os.path.join(dirname, "chaos")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump({w: 10 for w in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({t: 10 for t in targets}, f)
        pickle.dump(len(rows), f)
    return prefix


def cmd_train(workdir: str, save_base: str) -> None:
    from code2vec_tpu.config import Config
    from code2vec_tpu.model_facade import Code2VecModel
    prefix = make_synthetic_dataset(workdir)
    config = Config(
        train_data_path_prefix=prefix,
        model_save_path=save_base,
        max_contexts=8,
        default_embeddings_size=16,
        train_batch_size=16,
        num_train_epochs=100000,   # run until SIGTERMed
        num_batches_to_log_progress=1000000,
        compute_dtype="float32",
        use_packed_data=False,
        shuffle_buffer_size=64,
        save_every_epochs=1,
        verbose_mode=0,
    )
    model = Code2VecModel(config)
    print("CHAOS_TRAIN_STARTED", flush=True)
    model.train()
    print("CHAOS_TRAIN_DONE", flush=True)


def main() -> None:
    cmd = sys.argv[1]
    if cmd == "save-seq":
        cmd_save_seq(sys.argv[2], int(sys.argv[3]),
                     sys.argv[4] if len(sys.argv) > 4 else "")
    elif cmd == "train":
        cmd_train(sys.argv[2], sys.argv[3])
    else:
        raise SystemExit(f"unknown chaos_child command: {cmd!r}")
    print("CHAOS_CHILD_OK", flush=True)


if __name__ == "__main__":
    main()
