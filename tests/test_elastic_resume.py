"""Elastic topology-change resume: any committed checkpoint restores on
any host count / mesh shape, and the input pipeline continues without
skipping or double-reading rows.

Three layers of proof:

1. In-process data-order laws (exact, row-id level): the packed
   training order is a GLOBAL epoch-keyed permutation strided per host,
   so global batch b consumes the same row SET at any host count, a
   resumed run continues the exact permutation sequence of an
   uninterrupted one, and a saved cursor remaps onto a different host
   count with no row skipped or double-read.

2. In-process restore laws: manifest v3 records topology + the global
   parameter tree; frozen v2/v1 manifests stay loadable; a real
   dp=2-sharded state saved and restored into a tp=2 mesh template is
   bit-equal with `resume_mode == "resharded"`; mismatched trees fail
   naming the offending leaf; degraded resumes are reported loudly
   (facade resume_report + heartbeat).

3. Real-process chaos (tests/chaos_elastic_child.py): a pod trains on N
   processes, the whole pod is HARD-KILLED mid-run (post-commit fault
   point), and the run resumes on M != N — 2->1 and 1->2 — plus a
   single-host dp=2 -> tp=2 mesh reshape; the restored global parameter
   tree is asserted bit-equal (params digest) to the pre-kill commit
   and the loss trajectory continues the uninterrupted reference run's.
   A SIGTERM preemption drill proves the data cursor: the resumed run's
   losses continue the reference's mid-epoch, exactly.
"""

import json
import os
import shutil
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.packed import (
    _HEADER, _MAGIC, _VERSION, PackedDataset, pack_c2v,
)
from code2vec_tpu.data.reader import EstimatorAction
from code2vec_tpu.training import checkpoint as ckpt_mod
from code2vec_tpu.utils import faults
from code2vec_tpu.vocab import Code2VecVocabs

HERE = os.path.dirname(os.path.abspath(__file__))
if HERE not in sys.path:
    sys.path.insert(0, HERE)

import chaos_child  # noqa: E402  (deterministic state builders)

CHILD = os.path.join(HERE, "chaos_elastic_child.py")
GROUP_TIMEOUT_S = 300

pytestmark = [pytest.mark.chaos, pytest.mark.elastic]


# ============================ layer 1: data-order laws (in-process) =====

def _write_packed(path: str, vocabs, n_rows: int, m: int = 4) -> None:
    """A synthetic .c2vb whose row identity is readable back from the
    batches: source_token_indices[:, 0] = 1000 + row_id (non-pad, so
    every row passes the filter); targets are all in-vocab."""
    tgt_ok = vocabs.target_vocab.oov_index + 1
    rec = np.zeros((n_rows, 1 + 3 * m), dtype=np.int32)
    rec[:, 0] = tgt_ok
    rec[:, 1] = 1000 + np.arange(n_rows)
    with open(path, "wb") as f:
        f.write(_HEADER.pack(_MAGIC, _VERSION, n_rows, m))
        f.write(rec.tobytes())


def _row_ids(batch) -> np.ndarray:
    return batch.source_token_indices[:, 0] - 1000


def _global_epoch_batches(path, vocabs, num_hosts, global_bs, num_epochs,
                          seed=5, start_epoch=0, skip_rows=0):
    """Drive one PackedDataset per simulated host; regroup the per-host
    streams into (epoch, batch) -> global row-id set."""
    local_bs = global_bs // num_hosts
    hosts = [PackedDataset(path, vocabs, shard_index=h, num_shards=num_hosts)
             for h in range(num_hosts)]
    streams = [list(h.iter_batches(local_bs, EstimatorAction.Train,
                                   num_epochs=num_epochs, seed=seed,
                                   start_epoch=start_epoch,
                                   skip_rows=skip_rows))
               for h in hosts]
    assert len({len(s) for s in streams}) == 1, "hosts out of lockstep"
    per_host_ids = [[_row_ids(b) for b in s] for s in streams]
    n_batches = len(per_host_ids[0])
    return [frozenset(int(i) for h in range(num_hosts)
                      for i in per_host_ids[h][b])
            for b in range(n_batches)]


def test_global_batches_invariant_across_host_counts(tiny_vocabs, tmp_path):
    """Global batch b consumes the SAME row set whether the pod has 1, 2
    or 4 hosts — the invariant that makes a data cursor meaningful
    across topology changes — and each epoch is one full pass: no row
    skipped, none double-read."""
    path = str(tmp_path / "d.c2vb")
    _write_packed(path, tiny_vocabs, n_rows=48)
    per_m = {m: _global_epoch_batches(path, tiny_vocabs, m, global_bs=8,
                                      num_epochs=2) for m in (1, 2, 4)}
    assert per_m[1] == per_m[2] == per_m[4]
    steps = 48 // 8
    assert len(per_m[1]) == steps * 2
    for e in range(2):
        epoch_sets = per_m[1][e * steps:(e + 1) * steps]
        union = set().union(*epoch_sets)
        assert len(union) == steps * 8  # disjoint batches: no double-read
    # epochs shuffle differently (epoch-keyed permutation, not a rerun)
    assert per_m[1][:steps] != per_m[1][steps:]


def test_start_epoch_continues_exact_sequence(tiny_vocabs, tmp_path):
    """A resumed run (start_epoch=k) draws exactly the batches the
    uninterrupted run would have drawn from epoch k on — byte-equal
    arrays, not just equal sets (same host count here)."""
    path = str(tmp_path / "d.c2vb")
    _write_packed(path, tiny_vocabs, n_rows=40)
    ds = PackedDataset(path, tiny_vocabs)
    full = list(ds.iter_batches(8, EstimatorAction.Train, num_epochs=3,
                                seed=9))
    resumed = list(ds.iter_batches(8, EstimatorAction.Train, num_epochs=2,
                                   seed=9, start_epoch=1))
    steps = 40 // 8
    assert len(resumed) == 2 * steps
    for got, want in zip(resumed, full[steps:]):
        np.testing.assert_array_equal(got.source_token_indices,
                                      want.source_token_indices)


def test_cursor_remaps_across_host_counts(tiny_vocabs, tmp_path):
    """Interrupt a 2-host epoch after k global batches; resuming on 1
    host (and on 4) with skip_rows = k * global_batch continues with
    exactly the not-yet-consumed row sets of that epoch."""
    path = str(tmp_path / "d.c2vb")
    _write_packed(path, tiny_vocabs, n_rows=48)
    full = _global_epoch_batches(path, tiny_vocabs, 2, global_bs=8,
                                 num_epochs=1)
    k = 2  # global batches consumed before the kill
    for new_hosts in (1, 4):
        cont = _global_epoch_batches(path, tiny_vocabs, new_hosts,
                                     global_bs=8, num_epochs=1,
                                     skip_rows=k * 8)
        assert cont == full[k:], f"cursor remap broken for M={new_hosts}"
        consumed_before = set().union(*full[:k])
        consumed_after = set().union(*cont)
        assert not consumed_before & consumed_after  # no double-read
        assert len(consumed_before | consumed_after) == len(full) * 8


def test_steps_per_epoch_equal_on_every_host_and_cursor_aware(tiny_vocabs,
                                                              tmp_path):
    path = str(tmp_path / "d.c2vb")
    _write_packed(path, tiny_vocabs, n_rows=43)  # ragged: 43 // 8 = 5
    for m in (1, 2, 4):
        counts = {PackedDataset(path, tiny_vocabs, shard_index=h,
                                num_shards=m).steps_per_epoch(
                      8 // m, EstimatorAction.Train) for h in range(m)}
        assert counts == {5}
    ds = PackedDataset(path, tiny_vocabs, shard_index=0, num_shards=2)
    assert ds.steps_per_epoch(4, EstimatorAction.Train, skip_rows=16) == 3


def test_lockstep_stream_accepts_short_first_epoch():
    from code2vec_tpu.data.reader import EpochEnd
    from code2vec_tpu.parallel.distributed import lockstep_train_stream

    def stream(counts):
        for e, c in enumerate(counts, 1):
            for i in range(c):
                yield ("batch", e, i)
            yield EpochEnd(e)

    out = list(lockstep_train_stream(stream([2, 4]), 4, first_epoch_steps=2))
    batches = [x for x in out if not hasattr(x, "epoch")]
    assert len(batches) == 6  # short first epoch + full second, no raise
    # without the override, a short first epoch is (rightly) a desync
    with pytest.raises(RuntimeError, match="produced only 2"):
        list(lockstep_train_stream(stream([2, 4]), 4))


def test_trainer_records_cursor_into_preemption_save():
    """The preemption save carries the data cursor: global rows the
    interrupted epoch consumed (batch_in_epoch * global batch size)."""
    import signal

    from code2vec_tpu.data.reader import RowBatch
    from code2vec_tpu.training.loop import Trainer

    def batch(n=2, m=4):
        return RowBatch(
            source_token_indices=np.ones((n, m), np.int32),
            path_indices=np.ones((n, m), np.int32),
            target_token_indices=np.ones((n, m), np.int32),
            context_valid_mask=np.ones((n, m), np.float32),
            target_index=np.ones((n,), np.int32),
            example_valid=np.ones((n,), bool))

    def stream():
        for _ in range(10):
            yield batch()

    calls = []

    def fake_step(s, *a):
        calls.append(1)
        if len(calls) == 3:
            os.kill(os.getpid(), signal.SIGTERM)
        return s, np.float32(0.5)

    saves = []

    def save_fn(state, epoch, suffix="", cursor_rows=0):
        saves.append((epoch, suffix, cursor_rows))

    class _S:
        step = np.zeros((), np.int32)

    cfg = Config(train_data_path_prefix="x", max_contexts=4,
                 train_batch_size=4, num_train_epochs=1, verbose_mode=0)
    tr = Trainer(cfg, fake_step, save_fn=save_fn)
    tr.train(_S(), stream(), rng=np.zeros((2,), np.uint32))
    assert tr.preempted
    assert saves == [(0, "_preempt", 3 * 4)]


# ============================ layer 2: restore laws (in-process) ========

def test_manifest_v3_records_topology_and_cursor(tmp_path):
    base = str(tmp_path / "m_iter1")
    vocabs, config = chaos_child.build_vocabs(), chaos_child.build_config()
    ckpt_mod.save_model(base, chaos_child.build_state(1), vocabs, config,
                        epoch=1, data_cursor={"epoch": 1,
                                              "global_row_ordinal": 16,
                                              "global_batch_size": 8})
    man = ckpt_mod.load_manifest(base)
    assert man["format"] == 3
    assert man["mesh_plan"] == {"dp": 1, "tp": 1, "cp": 1}
    assert man["data_cursor"]["global_row_ordinal"] == 16
    tree = man["param_tree"]
    leaf = tree["['params']['token_embedding']"]
    assert leaf == {"shape": [6, 8], "dtype": "float32"}
    assert any(k.startswith("['opt_state']") for k in tree)


def test_frozen_v2_manifest_still_verifies_and_restores(tmp_path):
    """Forward-compat regression: an artifact written by CURRENT code
    whose manifest is rewritten to the frozen format-2 schema (exactly
    the PR-5 field set) must verify, classify, and restore bit-equal."""
    base = str(tmp_path / "m_iter1")
    vocabs, config = chaos_child.build_vocabs(), chaos_child.build_config()
    ckpt_mod.save_model(base, chaos_child.build_state(1), vocabs, config,
                        epoch=1)
    man_path = os.path.join(base, ckpt_mod.MANIFEST_NAME)
    with open(man_path) as f:
        man = json.load(f)
    frozen_v2 = {  # the exact PR-5 schema: no topology fields
        "format": 2,
        "epoch": man["epoch"],
        "released": man["released"],
        "orbax_complete": True,
        "process_count": man["process_count"],
        "commit_acks": man["commit_acks"],
        "files": man["files"],
    }
    with open(man_path, "w") as f:
        json.dump(frozen_v2, f, indent=2)
    meta = ckpt_mod.verify_checkpoint(base)
    assert meta["epoch"] == 1
    report = {}
    restored = ckpt_mod.load_model(base, chaos_child.build_state(0),
                                   report=report)
    assert report["resume_mode"] == "exact"  # no topology record to differ
    expected = chaos_child.build_state(1)
    for name, arr in expected.params.items():
        np.testing.assert_array_equal(np.asarray(restored.params[name]), arr)


def test_classify_restore_routes_topology_changes():
    cfg = Config(train_data_path_prefix="x", dp=2, tp=1, cp=1)
    man = {"process_count": 1, "mesh_plan": {"dp": 2, "tp": 1, "cp": 1}}
    assert ckpt_mod.classify_restore(man, cfg) == "exact"
    assert ckpt_mod.classify_restore({"process_count": 2,
                                      "mesh_plan": {"dp": 2}}, cfg) \
        == "resharded"
    assert ckpt_mod.classify_restore({"process_count": 1,
                                      "mesh_plan": {"dp": 1, "tp": 2}},
                                     cfg) == "resharded"
    assert ckpt_mod.classify_restore(None, cfg) == "exact"   # legacy
    assert ckpt_mod.classify_restore({}, cfg) == "exact"


def test_param_tree_mismatch_names_offending_leaf(tmp_path):
    base = str(tmp_path / "m_iter1")
    vocabs, config = chaos_child.build_vocabs(), chaos_child.build_config()
    ckpt_mod.save_model(base, chaos_child.build_state(1), vocabs, config,
                        epoch=1)
    man_path = os.path.join(base, ckpt_mod.MANIFEST_NAME)
    with open(man_path) as f:
        man = json.load(f)
    man["param_tree"]["['params']['path_embedding']"]["shape"] = [99, 8]
    with open(man_path, "w") as f:
        json.dump(man, f)
    with pytest.raises(ValueError, match=r"path_embedding.*99"):
        ckpt_mod.load_model(base, chaos_child.build_state(0))


def test_inprocess_mesh_reshape_dp2_to_tp2_restores_bit_equal(tmp_path,
                                                              tiny_vocabs):
    """A REAL dp=2-sharded train state (params + Adam state on an 8-CPU
    device mesh) saved with mesh_plan dp=2, restored into a tp=2 mesh
    template: resume_mode == resharded, every leaf bit-equal, and the
    restored leaves carry the CURRENT (tp=2) shardings."""
    from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
    from code2vec_tpu.parallel.mesh import MeshPlan, make_mesh
    from code2vec_tpu.training.state import create_train_state, make_optimizer

    dims = ModelDims(token_vocab_size=24, path_vocab_size=16,
                     target_vocab_size=16, token_dim=4, path_dim=4)
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=1.0)
    cfg_save = Config(train_data_path_prefix="x", dp=2,
                      compute_dtype="float32")
    opt = make_optimizer(cfg_save)
    state = create_train_state(module, opt, jax.random.PRNGKey(3),
                               mesh=make_mesh(MeshPlan(dp=2)),
                               config=cfg_save)
    path = ckpt_mod.save_model(str(tmp_path / "m_iter1"), state,
                               tiny_vocabs, cfg_save, epoch=1)
    assert ckpt_mod.load_manifest(path)["mesh_plan"]["dp"] == 2

    cfg_load = Config(train_data_path_prefix="x", tp=2,
                      compute_dtype="float32")
    state_like = create_train_state(module, opt, jax.random.PRNGKey(11),
                                    mesh=make_mesh(MeshPlan(tp=2)),
                                    config=cfg_load)
    report = {}
    restored = ckpt_mod.load_model(path, state_like, config=cfg_load,
                                   report=report)
    assert report["resume_mode"] == "resharded"
    for name in state.params:
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(restored.params[name])),
            np.asarray(jax.device_get(state.params[name])))
        assert (restored.params[name].sharding
                == state_like.params[name].sharding), name
    got_leaves = jax.tree.leaves(restored.opt_state)
    want_leaves = jax.tree.leaves(state.opt_state)
    assert len(got_leaves) == len(want_leaves)
    for got, want in zip(got_leaves, want_leaves):
        np.testing.assert_array_equal(np.asarray(jax.device_get(got)),
                                      np.asarray(jax.device_get(want)))


def _write_facade_corpus(dirpath: str, n_rows: int = 40) -> str:
    """Tiny packed-trainable corpus: 7 in-vocab targets, ~10% OOV rows
    (train-filtered), max_contexts=8. Word counts are chosen so every
    vocab size (13+1, 7+1, 7+1) is EVEN: table rows are padded to a
    multiple of tp, so this keeps the global param shapes identical
    under tp=1 and tp=2 — the precondition of the mesh-reshape resume
    scenario (a tp whose padding changes the global shapes is correctly
    rejected with the offending leaf named)."""
    import pickle
    import random
    rng = random.Random(5)
    tokens = [f"tok{i}" for i in range(13)]
    paths = [f"path{i}" for i in range(7)]

    def row(target):
        n_ctx = rng.randint(3, 8)
        ctx = [f"{rng.choice(tokens)},{rng.choice(paths)},"
               f"{rng.choice(tokens)}" for _ in range(n_ctx)]
        return f"{target} " + " ".join(ctx) + " " * (8 - n_ctx)

    rows = [row("zzz" if i % 10 == 9 else f"w{i % 7}")
            for i in range(n_rows)]
    prefix = os.path.join(dirpath, "data")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(prefix + ".train.c2v.num_examples", "w") as f:
        f.write(str(n_rows))
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump({t: 10 for t in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({f"w{i}": 10 for i in range(7)}, f)
        pickle.dump(n_rows, f)
    config = Config(train_data_path_prefix=prefix, max_contexts=8,
                    verbose_mode=0)
    vocabs = Code2VecVocabs.load_or_create(config)
    pack_c2v(prefix + ".train.c2v", vocabs, 8)
    return prefix


def test_facade_degraded_resume_is_loud_and_in_heartbeat(tmp_path):
    """Corrupt the newest artifact: resume must fall back, REPORT the
    rejected candidate (resume_report + log + metrics), and stamp
    resume_mode/restored_step into the heartbeat — never a silent
    fresh start."""
    from code2vec_tpu.model_facade import Code2VecModel

    prefix = _write_facade_corpus(str(tmp_path))
    base = str(tmp_path / "run" / "m")
    cfg = Config(train_data_path_prefix=prefix, model_save_path=base,
                 max_contexts=8, train_batch_size=8, test_batch_size=8,
                 num_train_epochs=2, save_every_epochs=1,
                 num_batches_to_log_progress=10 ** 6,
                 compute_dtype="float32", use_packed_data=True,
                 verbose_mode=0)
    model = Code2VecModel(cfg)
    assert model.resume_report["resume_mode"] == "fresh"
    model.train()
    assert os.path.isdir(f"{base}_iter2")
    # kill the final full-path artifact so --load <base> takes the walk,
    # and corrupt _iter2 so the walk must fall back to _iter1
    shutil.rmtree(base)
    victim = os.path.join(f"{base}_iter2", "dictionaries.bin")
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 1)

    hb = str(tmp_path / "hb.json")
    cfg2 = Config(train_data_path_prefix=prefix, model_save_path=base,
                  model_load_path=base, max_contexts=8, train_batch_size=8,
                  test_batch_size=8, num_train_epochs=3,
                  save_every_epochs=1, num_batches_to_log_progress=10 ** 6,
                  compute_dtype="float32", use_packed_data=True,
                  heartbeat_file=hb, verbose_mode=0)
    model2 = Code2VecModel(cfg2)
    rep = model2.resume_report
    assert rep["resume_mode"] == "exact"
    assert rep["restored_epoch"] == 1
    assert len(rep["rejected"]) == 1
    assert rep["rejected"][0]["path"].endswith("_iter2")
    assert "dictionaries.bin" in rep["rejected"][0]["reason"]
    model2.train()
    with open(hb) as f:
        beat = json.load(f)
    assert beat["resume_mode"] == "exact"
    assert beat["restored_step"] == rep["restored_step"]
    assert beat["status"] == "done"
    # Cursor remap rounds DOWN to a multiple of the CURRENT global
    # batch (8): a batch-size change across the resume must re-read a
    # few rows, never leave the epoch's tail batch-misaligned (which
    # would silently drop unseen rows at the ragged-tail truncation).
    model2._resume_cursor = {"epoch": 1, "global_row_ordinal": 19,
                             "global_batch_size": 6}
    assert model2._cursor_skip_rows() == 16


# ============================ layer 3: real-process chaos ===============

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_group(nprocs, args_for_pid, timeout=GROUP_TIMEOUT_S,
               env_extra=None):
    """Spawn `nprocs` chaos_elastic_child processes as one pod; returns
    ([rc...], [stdout...]). Hung pods are killed and fail the test."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", faults.FAULTS_ENV)}
    if env_extra:
        env.update(env_extra)
    port = str(_free_port())
    procs = [subprocess.Popen(
        [sys.executable, CHILD, *args_for_pid(pid, port)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in range(nprocs)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout)[0])
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        partial = [p.communicate()[0] for p in procs]
        pytest.fail(f"elastic chaos pod hung past {timeout}s:\n"
                    f"{outs + partial}")
    return [p.returncode for p in procs], outs


def _saved_digests(out: str, pid: int = 0) -> dict:
    """{epoch: digest} from ELASTIC_SAVED markers; later saves of the
    same epoch (preemption artifacts) win, matching resume preference."""
    digests = {}
    for line in out.splitlines():
        if line.startswith(f"ELASTIC_SAVED {pid} "):
            _, _, epoch, dig = line.split()
            digests[int(epoch)] = dig.split("=", 1)[1]
    return digests


def _losses(out: str, pid: int = 0):
    for line in out.splitlines():
        if line.startswith(f"ELASTIC_LOSSES {pid} "):
            return json.loads(line.split(" ", 2)[2])
    return None


def _parse_resumed(out: str, pid: int = 0):
    for line in out.splitlines():
        if line.startswith(f"ELASTIC_RESUMED {pid} "):
            fields = dict(kv.split("=", 1) for kv in line.split()[2:])
            return (fields["mode"], int(fields["step"]),
                    int(fields["epoch"]), fields["digest"])
    return None


EPOCHS = 4          # total budget; pods are killed after the epoch-2 commit
STEPS = 4           # 36 filtered rows // global batch 8
KILL = "callback_crash@2=exit"  # hard-kill inside save #2's post-commit


@pytest.fixture(scope="session")
def elastic_world(tmp_path_factory):
    """Phase-1 fixture shared by the resume scenarios: one dataset, a
    2-process pod and a 1-process run both hard-killed right after the
    `_iter2` commit, and an uninterrupted single-process reference run
    providing the loss trajectory ground truth."""
    root = tmp_path_factory.mktemp("elastic_world")
    data_prefix = _write_facade_corpus(str(root))
    world = {"data": data_prefix}

    for name, nprocs, dp in (("pod2", 2, 4), ("pod1", 1, 2)):
        save_dir = os.path.join(str(root), name)
        os.makedirs(save_dir)
        base = os.path.join(save_dir, "m")
        rcs, outs = _run_group(nprocs, lambda pid, port: [
            "train", str(pid), str(nprocs), port, data_prefix, base,
            str(dp), "1", str(EPOCHS), KILL])
        assert rcs == [faults.FAULT_EXIT_CODE] * nprocs, (
            f"{name} was not killed at the fault point:\n{outs}")
        digests = _saved_digests(outs[0])
        assert set(digests) == {1, 2}, outs[0]
        man = ckpt_mod.load_manifest(f"{base}_iter2")
        assert man["format"] == 3
        assert man["process_count"] == nprocs
        assert man["mesh_plan"]["dp"] == dp
        world[name] = {"dir": save_dir, "digests": digests}

    ref_base = os.path.join(str(root), "ref", "m")
    os.makedirs(os.path.dirname(ref_base))
    rcs, outs = _run_group(1, lambda pid, port: [
        "train", str(pid), "1", port, data_prefix, ref_base, "2", "1",
        str(EPOCHS)])
    assert rcs == [0], outs
    world["ref_losses"] = _losses(outs[0])
    assert len(world["ref_losses"]) == EPOCHS * STEPS
    return world


def _clone_pod(world_entry, tmp_path) -> str:
    """Fresh copy of a phase-1 save dir (resume runs write new
    artifacts; scenarios must not contaminate each other)."""
    dst = str(tmp_path / "save")
    shutil.copytree(world_entry["dir"], dst)
    return os.path.join(dst, "m")


@pytest.mark.multihost
def test_kill_pod_resume_2_to_1_bit_equal_and_reshard_fault(elastic_world,
                                                            tmp_path):
    """2-process pod killed post-commit; resume SINGLE-process. First
    with the `reshard_restore` fault armed: the kill mid-reshard must
    leave the artifact untouched and re-restorable. Then for real: the
    restored params are bit-equal to the pre-kill commit, resume_mode is
    resharded, and the loss trajectory continues the reference run's."""
    w = elastic_world
    base = _clone_pod(w["pod2"], tmp_path)
    man_before = ckpt_mod.load_manifest(f"{base}_iter2")

    rcs, outs = _run_group(1, lambda pid, port: [
        "resume", "0", "1", port, w["data"], base, "2", "1", str(EPOCHS)],
        env_extra={faults.FAULTS_ENV: "reshard_restore=exit"})
    assert rcs == [faults.FAULT_EXIT_CODE], outs[0]
    assert "ELASTIC_RESUMED" not in outs[0]
    ckpt_mod.verify_checkpoint(f"{base}_iter2")  # untouched
    assert ckpt_mod.load_manifest(f"{base}_iter2") == man_before

    rcs, outs = _run_group(1, lambda pid, port: [
        "resume", "0", "1", port, w["data"], base, "2", "1", str(EPOCHS)])
    assert rcs == [0], outs[0]
    mode, step, epoch, digest = _parse_resumed(outs[0])
    assert mode == "resharded"
    assert epoch == 2 and step == 2 * STEPS
    assert digest == w["pod2"]["digests"][2], (
        "restored params differ from the pre-kill commit")
    losses = _losses(outs[0])
    assert len(losses) == 2 * STEPS
    np.testing.assert_allclose(losses, w["ref_losses"][2 * STEPS:],
                               rtol=5e-3, atol=1e-5)


@pytest.mark.multihost
def test_kill_pod_resume_1_to_2_bit_equal(elastic_world, tmp_path):
    """1-process run killed post-commit; resume on a 2-process pod: the
    collective resolve agrees on the artifact AND the reshard decision,
    both hosts restore the same bit-equal tree, and the trajectory
    continues the reference's."""
    w = elastic_world
    base = _clone_pod(w["pod1"], tmp_path)
    rcs, outs = _run_group(2, lambda pid, port: [
        "resume", str(pid), "2", port, w["data"], base, "4", "1",
        str(EPOCHS)])
    for pid in (0, 1):
        assert rcs[pid] == 0, f"resume child {pid} failed:\n{outs[pid]}"
        mode, step, epoch, digest = _parse_resumed(outs[pid], pid)
        assert mode == "resharded"
        assert epoch == 2 and step == 2 * STEPS
        assert digest == w["pod1"]["digests"][2], (
            f"host {pid} restored params differ from the pre-kill commit")
    l0, l1 = _losses(outs[0], 0), _losses(outs[1], 1)
    assert l0 == l1  # both hosts saw the same global loss
    np.testing.assert_allclose(l0, w["ref_losses"][2 * STEPS:],
                               rtol=5e-3, atol=1e-5)


def test_kill_pod_resume_mesh_reshape_dp2_to_tp2(elastic_world, tmp_path):
    """Same host count, different mesh: the dp=2 artifact restores into
    a dp=1/tp=2 (row-sharded tables) template bit-equal, classified as
    resharded."""
    w = elastic_world
    base = _clone_pod(w["pod1"], tmp_path)
    # epochs budget == epochs trained: restore-only (the reshaped mesh
    # is proven by the restore; trajectory is the other tests' job)
    rcs, outs = _run_group(1, lambda pid, port: [
        "resume", "0", "1", port, w["data"], base, "1", "2", "2"])
    assert rcs == [0], outs[0]
    mode, step, epoch, digest = _parse_resumed(outs[0])
    assert mode == "resharded"
    assert epoch == 2 and step == 2 * STEPS
    assert digest == w["pod1"]["digests"][2]


@pytest.fixture(scope="session")
def preempt_world(tmp_path_factory):
    """A single-process run preempted (SIGTERM) at global batch 5 — one
    batch into epoch 2: the `_iter1_preempt` artifact must carry
    data_cursor epoch=1, ordinal=1*8."""
    root = tmp_path_factory.mktemp("elastic_preempt")
    data_prefix = _write_facade_corpus(str(root))
    save_dir = os.path.join(str(root), "save")
    os.makedirs(save_dir)
    base = os.path.join(save_dir, "m")
    rcs, outs = _run_group(1, lambda pid, port: [
        "preempt", "0", "1", port, data_prefix, base, "2", "1",
        str(EPOCHS), "5"])
    assert rcs == [0], outs[0]
    assert "ELASTIC_PREEMPTED 0 after=5" in outs[0], outs[0]
    man = ckpt_mod.load_manifest(f"{base}_iter1_preempt")
    assert man["data_cursor"] == {"epoch": 1, "global_row_ordinal": 8,
                                  "global_batch_size": 8}
    return {"data": data_prefix, "dir": save_dir,
            "digests": _saved_digests(outs[0]),
            "losses": _losses(outs[0])}


def test_preempt_cursor_resume_continues_mid_epoch(preempt_world,
                                                   elastic_world, tmp_path):
    """Resume the preempted run (same topology): first a kill at the
    `cursor_remap` fault point (artifact must stay restorable), then for
    real — the restored tree is bit-equal to the preemption commit and
    the losses continue the uninterrupted reference EXACTLY from batch
    8 on: the interrupted epoch's remaining batch plus two full epochs,
    no row skipped or double-read."""
    w = preempt_world
    base = _clone_pod(w, tmp_path)

    rcs, outs = _run_group(1, lambda pid, port: [
        "resume", "0", "1", port, w["data"], base, "2", "1", str(EPOCHS)],
        env_extra={faults.FAULTS_ENV: "cursor_remap=exit"})
    assert rcs == [faults.FAULT_EXIT_CODE], outs[0]
    ckpt_mod.verify_checkpoint(f"{base}_iter1_preempt")  # untouched

    rcs, outs = _run_group(1, lambda pid, port: [
        "resume", "0", "1", port, w["data"], base, "2", "1", str(EPOCHS)])
    assert rcs == [0], outs[0]
    mode, step, epoch, digest = _parse_resumed(outs[0])
    assert mode == "exact"
    assert epoch == 1 and step == 5
    assert digest == w["digests"][1], (
        "restored params differ from the preemption commit")
    losses = _losses(outs[0])
    # 3 remaining batches of the interrupted epoch + 2 full epochs
    assert len(losses) == 3 + 2 * STEPS
    ref = elastic_world["ref_losses"]
    np.testing.assert_allclose(w["losses"], ref[:5], rtol=1e-6)
    np.testing.assert_allclose(losses, ref[5:], rtol=1e-6)


def test_second_preemption_accumulates_cursor(preempt_world, tmp_path):
    """Preempt AGAIN while still inside the cursor-resumed epoch: the
    recorded cursor must be the restored skip PLUS the newly consumed
    rows — the trainer's batch counter restarted at zero on resume, so
    an unadjusted cursor would double-read the difference on the next
    resume."""
    w = preempt_world
    base = _clone_pod(w, tmp_path)
    # resume (skips 8 rows = 1 batch of the interrupted epoch), then
    # SIGTERM after 2 more batches — still inside that epoch (3 remain)
    rcs, outs = _run_group(1, lambda pid, port: [
        "preempt", "0", "1", port, w["data"], base, "2", "1", str(EPOCHS),
        "2", "load"])
    assert rcs == [0], outs[0]
    assert "ELASTIC_PREEMPTED 0 after=2" in outs[0], outs[0]
    man = ckpt_mod.load_manifest(f"{base}_iter1_preempt")
    assert man["data_cursor"] == {"epoch": 1,
                                  "global_row_ordinal": 8 + 2 * 8,
                                  "global_batch_size": 8}
