"""Helpers parity tests (reference: common.py)."""

import io

import numpy as np

from code2vec_tpu.common import (
    filter_impossible_names, get_first_match_word_from_top_predictions,
    get_subtokens, is_legal_method_name, java_string_hashcode, normalize_word,
    save_word2vec_file,
)


def test_normalize_word():
    # reference: common.py:12-18
    assert normalize_word("getName") == "getname"
    assert normalize_word("get_name2") == "getname"
    assert normalize_word("123") == "123"       # all-stripped falls back to lower
    assert normalize_word("A_B") == "ab"


def test_legal_method_names():
    # reference: common.py:122-124
    oov = "<PAD_OR_OOV>"
    assert is_legal_method_name("get|name", oov)
    assert not is_legal_method_name(oov, oov)
    assert not is_legal_method_name("get2", oov)
    assert not is_legal_method_name("", oov)
    assert filter_impossible_names([oov, "a|b", "x9", "run"], oov) == ["a|b", "run"]


def test_first_match():
    # reference: common.py:180-187 — index is within the FILTERED list.
    oov = "<PAD_OR_OOV>"
    res = get_first_match_word_from_top_predictions(
        "getName", [oov, "bad2", "set|name", "get|name"], oov)
    assert res == (1, "get|name")
    assert get_first_match_word_from_top_predictions("getName", ["foo"], oov) is None


def test_subtokens():
    assert get_subtokens("get|name") == ["get", "name"]
    assert get_subtokens("run") == ["run"]


def test_java_string_hashcode():
    # Known Java values: "".hashCode()==0, "a".hashCode()==97,
    # "hello".hashCode()==99162322, "polygenelubricants" is famously negative.
    assert java_string_hashcode("") == 0
    assert java_string_hashcode("a") == 97
    assert java_string_hashcode("hello") == 99162322
    assert java_string_hashcode("polygenelubricants") == -2147483648


def test_w2v_format():
    # reference: common.py:82-91
    buf = io.StringIO()
    mat = np.array([[1.0, 2.0], [3.5, 4.25]])
    save_word2vec_file(buf, {0: "a", 1: "b"}, mat)
    lines = buf.getvalue().splitlines()
    assert lines[0] == "2 2"
    assert lines[1].startswith("a 1.0 2.0")
    assert lines[2].startswith("b 3.5 4.25")
