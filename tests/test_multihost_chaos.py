"""Multi-process chaos matrix for the cross-host checkpoint commit
protocol (training/checkpoint.py + parallel/distributed.py).

Contract under test: with async checkpointing on, killing EITHER host at
every stage of the commit protocol — pre-barrier (`async_commit`),
in-barrier (`barrier_enter`), post-barrier pre-rename
(`checkpoint_commit`), and mid-callback post-rename (`callback_crash`)
— leaves the surviving host's fallback walk on ONE well-defined valid
artifact that restores bit-equal and is trainable. Plus: the loud
desync contract (hosts that diverge raise on every host instead of
deadlocking the pod) and the clean-path collective resume agreement.

Every child pair runs under a hard subprocess timeout: a protocol hang
fails the test in ~2 minutes with the children's stdout attached,
instead of eating the tier-1 time budget.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from code2vec_tpu.training import checkpoint as ckpt_mod
from code2vec_tpu.utils import faults

HERE = os.path.dirname(os.path.abspath(__file__))
if HERE not in sys.path:
    sys.path.insert(0, HERE)

import chaos_child  # noqa: E402

CHILD = os.path.join(HERE, "chaos_mh_child.py")
PAIR_TIMEOUT_S = 150

pytestmark = [pytest.mark.chaos, pytest.mark.multihost]

# (fault point, victim host) -> the artifact every survivor must land
# on. Stages before the rename leave `_iter2` manifest-less (staging
# only), so the fallback is `_iter1`; `callback_crash` fires after the
# committing host's rename, so `_iter2` is already the valid newest.
# `checkpoint_commit` is only crossed by the committing host (process
# 0), hence no victim-1 case for it.
KILL_MATRIX = [
    ("async_commit", 0, 1),
    ("async_commit", 1, 1),
    ("barrier_enter", 0, 1),
    ("barrier_enter", 1, 1),
    ("checkpoint_commit", 0, 1),
    ("callback_crash", 0, 2),
    ("callback_crash", 1, 2),
]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_pair(args_for_pid, timeout=PAIR_TIMEOUT_S):
    """Spawn the two-process child pair; returns ([rc0, rc1], [out0,
    out1]). Children that hang are killed and fail the test with their
    partial output."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS", faults.FAULTS_ENV)}
    procs = [subprocess.Popen(
        [sys.executable, CHILD, *args_for_pid(pid)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for pid in (0, 1)]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout)[0])
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
        partial = [p.communicate()[0] for p in procs]
        pytest.fail(f"multi-host chaos child pair hung past {timeout}s "
                    f"(protocol deadlock?):\n--- child 0 ---\n"
                    f"{outs + partial}")
    return [p.returncode for p in procs], outs


def _manifest(artifact: str) -> dict:
    with open(os.path.join(artifact, ckpt_mod.MANIFEST_NAME)) as f:
        return json.load(f)


def _marker(out: str, prefix: str):
    for line in out.splitlines():
        if line.startswith(prefix):
            return line[len(prefix):].strip()
    return None


@pytest.mark.parametrize("point,victim,expect_epoch", KILL_MATRIX,
                         ids=[f"{p}-victim{v}" for p, v, _ in KILL_MATRIX])
def test_kill_one_host_at_every_protocol_stage(tmp_path, point, victim,
                                               expect_epoch):
    """Kill one host at a protocol stage; the survivor (and the on-disk
    truth) must converge on the expected artifact, bit-equal.

    Two survivor outcomes are legitimate, and both must converge:
    - victim is a WORKER (process 1): the survivor's barrier times out,
      it raises BarrierTimeout from the save, reports the artifact its
      fallback walk lands on, and exits cleanly;
    - victim is the LEADER (process 0, which also hosts the jax
      coordination service): the service dies with it and the jax
      runtime hard-kills the survivor from its error-polling thread —
      exactly what happens on a real pod when the task-0 host dies. The
      convergence contract then applies to the RESTARTED pod, which the
      parent models below with a fresh-process fallback walk over the
      shared store."""
    base = str(tmp_path / "m")
    port = _free_port()
    rcs, outs = _run_pair(
        lambda pid: ["matrix", str(pid), str(port), base, point,
                     str(victim), "1"])
    survivor = 1 - victim
    assert rcs[victim] == faults.FAULT_EXIT_CODE, (
        f"victim did not die at the fault point:\n{outs[victim]}")
    expected = f"{base}_iter{expect_epoch}"
    if rcs[survivor] == 0:
        # survivor outlived the runtime: its own walk must have
        # converged on the expected artifact before it exited
        got = _marker(outs[survivor], f"CHAOS_MH_LATEST {survivor} ")
        assert got == expected, (f"survivor landed on {got}, expected "
                                 f"{expected}:\n{outs[survivor]}")
    else:
        # leader death: the runtime killed the survivor before it could
        # report — legal only when the victim was the coordination
        # leader, never for a worker death
        assert victim == 0, (
            f"survivor of a worker death must exit cleanly, got "
            f"rc={rcs[survivor]}:\n{outs[survivor]}")
    # On-disk truth from a fresh process: same artifact, verifies, and
    # restores bit-equal to the state its epoch must carry.
    found = ckpt_mod.latest_valid_checkpoint(base, collective=False)
    assert found == expected
    meta = ckpt_mod.verify_checkpoint(found)
    assert meta["epoch"] == expect_epoch
    manifest = _manifest(found)
    assert manifest["process_count"] == 2
    assert manifest["commit_acks"] == [0, 1]
    restored = ckpt_mod.load_model(found, chaos_child.build_state(0))
    expected_state = chaos_child.build_state(expect_epoch)
    for name, arr in expected_state.params.items():
        np.testing.assert_array_equal(np.asarray(restored.params[name]), arr)


@pytest.mark.parametrize("use_async", [0, 1], ids=["sync", "async"])
def test_clean_pod_save_collective_agreement_and_resume(tmp_path,
                                                        use_async):
    """No faults: both hosts commit both artifacts through the barrier
    protocol (sync and async commit pipelines), the COLLECTIVE resume
    agreement hands both hosts the same newest path, and the artifact
    resumes training single-process."""
    base = str(tmp_path / "m")
    port = _free_port()
    rcs, outs = _run_pair(
        lambda pid: ["matrix", str(pid), str(port), base, "none", "0",
                     str(use_async)])
    for pid in (0, 1):
        assert rcs[pid] == 0, f"child {pid} failed:\n{outs[pid]}"
        assert f"CHAOS_MH_OK {pid}" in outs[pid]
        assert (_marker(outs[pid], f"CHAOS_MH_AGREED {pid} ")
                == f"{base}_iter2"), outs[pid]
    ckpt_mod.verify_checkpoint(f"{base}_iter2")
    manifest = _manifest(f"{base}_iter2")
    assert manifest["process_count"] == 2
    assert manifest["commit_acks"] == [0, 1]
    # both hosts' ack files are inside the committed artifact
    for i in (0, 1):
        assert os.path.isfile(
            os.path.join(f"{base}_iter2", f"{ckpt_mod.ACK_PREFIX}{i}"))
    # resume: restore bit-equal, then the restored state drives a
    # training loop (fake step: the point is that the artifact loads
    # into a live trainer and the loop runs from it)
    restored = ckpt_mod.load_model(f"{base}_iter2",
                                   chaos_child.build_state(0))
    expected_state = chaos_child.build_state(2)
    for name, arr in expected_state.params.items():
        np.testing.assert_array_equal(np.asarray(restored.params[name]), arr)

    from code2vec_tpu.config import Config
    from code2vec_tpu.data.reader import EpochEnd, RowBatch
    from code2vec_tpu.training.loop import Trainer

    def batch(n=2, m=4):
        return RowBatch(
            source_token_indices=np.ones((n, m), np.int32),
            path_indices=np.ones((n, m), np.int32),
            target_token_indices=np.ones((n, m), np.int32),
            context_valid_mask=np.ones((n, m), np.float32),
            target_index=np.ones((n,), np.int32),
            example_valid=np.ones((n,), bool))

    def stream():
        for _ in range(4):
            yield batch()
        yield EpochEnd(1)

    steps = []

    def train_step(state, *args):
        steps.append(1)
        return state, np.float32(0.5)

    cfg = Config(train_data_path_prefix="x", max_contexts=4,
                 train_batch_size=2, num_train_epochs=1, verbose_mode=0)
    Trainer(cfg, train_step).train(restored, stream(),
                                   rng=np.zeros((2,), np.uint32))
    assert len(steps) == 4


def test_desync_paths_raise_loudly_on_every_host(tmp_path):
    """Hosts that intentionally diverge must get the loud desync error
    on BOTH hosts — assert_host_agreement, the Trainer's epoch-boundary
    check, and the collective fallback walk with a host-local veto —
    never a silent hang (the pair runs under a hard timeout)."""
    port = _free_port()
    rcs, outs = _run_pair(
        lambda pid: ["desync", str(pid), str(port), str(tmp_path)])
    for pid in (0, 1):
        assert rcs[pid] == 0, f"child {pid} failed:\n{outs[pid]}"
        for marker in ("CHAOS_MH_DESYNC_ASSERT_OK",
                       "CHAOS_MH_DESYNC_EPOCH_OK",
                       "CHAOS_MH_DESYNC_FALLBACK_OK",
                       "CHAOS_MH_OK"):
            assert f"{marker} {pid}" in outs[pid], (
                f"missing {marker} from child {pid}:\n{outs[pid]}")
