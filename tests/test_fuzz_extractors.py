"""Bounded mutation-fuzz smoke test for both native extractors.

The full campaign (thousands of mutated inputs, byte flips + span
deletes/duplications + quote/comment injection) runs offline; this
seeded, bounded version keeps the no-crash property pinned in CI:
whatever bytes arrive, the extractor must exit cleanly (rc >= 0, no
signal) within the timeout — a crashed worker loses its whole
extraction batch, a clean failure loses one file.
"""

import random
import subprocess

import pytest

from tests.test_extractor import BINARY as JAVA_BIN
from tests.test_cs_extractor import BINARY as CS_BIN

SEEDS_JAVA = [
    'public class A { int f(int n) { return n > 0 ? f(n-1) : 0; } }',
    'public class B { String s = "esc\\"\\n tail"; int[] a = {1, 2}; }',
    ('public class C<T extends Comparable<? super T>> '
     '{ java.util.Map<String, java.util.List<int[]>> m; '
     'void f() { l: for (;;) break l; } }'),
    ('sealed interface S permits R {} '
     'record R(int a, String b) implements S { '
     'R { if (a < 0) { a = 0; } } int twice() { return a * 2; } }'),
]
SEEDS_CS = [
    'class A { string S = $"interp {1+1} tail"; int F() => 2; }',
    ('class A2 { string G(User u) => $"x {u.Name,-8:F2} y '
     '{(u.Ok ? $@"in ""{u.Id}"" {{esc}}" : "no")} z"; }'),
    ('class A3 { string R() => """raw "q" body"""; '
     'string S(User u) => $$"""t {b} {{u.Id}} e"""; }'),
    ('class B<T> where T : struct { event System.EventHandler E; '
     'public static implicit operator int(B<T> b) => 0; }'),
    'class D { string V = @"verbatim ""q"" here"; int this[int i] => i; }',
    ('class E { object Q(int[] xs, int[] ys) => from x in xs '
     'join y in ys on x equals y into g orderby x descending '
     'let z = x + 1 group z by x into h select h.Key; }'),
    ('record Base(string N); record Kid(string N, int A) : Base(N) '
     '{ public int Twice() => A * 2; } record struct P(int X);'),
]


def _mutate(s: str, rng: random.Random) -> bytes:
    b = bytearray(s.encode())
    for _ in range(rng.randint(1, 40)):
        if not b:
            break
        op = rng.randrange(4)
        i = rng.randrange(len(b))
        if op == 0:
            b[i] = rng.randrange(256)
        elif op == 1:
            del b[i:i + rng.randint(1, 40)]
        elif op == 2:
            b[i:i] = bytes(rng.choices(
                b'(){}[]<>;,."\'\\@#$%&*-=+?:', k=rng.randint(1, 20)))
        else:
            j = rng.randrange(len(b))
            b[i:i] = b[j:j + rng.randint(1, 60)]
    return bytes(b)


@pytest.mark.parametrize("language", ["java", "cs"])
def test_mutated_inputs_never_crash(language, tmp_path):
    rng = random.Random(1234 if language == "java" else 5678)
    seeds = SEEDS_JAVA if language == "java" else SEEDS_CS
    path = tmp_path / f"fuzz.{language if language == 'cs' else 'java'}"
    for it in range(40):
        path.write_bytes(_mutate(rng.choice(seeds), rng))
        if language == "java":
            args = [JAVA_BIN, "--max_path_length", "8",
                    "--max_path_width", "2", "--file", str(path)]
        else:
            args = [CS_BIN, "--path", str(path)]
        proc = subprocess.run(args, capture_output=True, timeout=30)
        assert proc.returncode >= 0, (
            f"iter {it}: extractor died on signal {-proc.returncode}; "
            f"input saved at {path}")


# ---- structure-aware interpolated-string fuzz (bounded CI version) ----
#
# Unlike the byte-mutation fuzz above (no-crash only), this generates
# VALID nested $-strings — holes with member accesses, calls, ternaries,
# alignments, format clauses, verbatim/raw nesting — and requires them
# to PARSE (both generated methods extracted). The offline 12K-case
# campaign of this generator found two real parser bugs in round 5
# (tuple-element declaration speculation eating `(c ? x : y)`, and
# `@$"""` misread as a raw string), so the full-parse property is pinned
# here, not just crash-freedom.

def _gen_expr(rng, depth):
    c = rng.randrange(6 if depth < 3 else 4)
    if c == 0:
        return rng.choice(["x", "user.Name", "a.B.C", "f(x)", "xs[i]"])
    if c == 1:
        return str(rng.randrange(100))
    if c == 2:
        return f"({_gen_expr(rng, depth + 1)} + {_gen_expr(rng, depth + 1)})"
    if c == 3:
        return '"lit"'
    if c == 4:
        return _gen_interp(rng, depth + 1)
    return f"(c ? {_gen_expr(rng, depth + 1)} : {_gen_expr(rng, depth + 1)})"


def _gen_interp(rng, depth):
    verbatim = rng.random() < 0.25
    q = ('$@"' if (verbatim and rng.random() < 0.5)
         else ('@$"' if verbatim else '$"'))
    parts = []
    for _ in range(rng.randrange(4)):
        parts.append(rng.choice(
            ["txt", "a b", "{{", "}}", '""' if verbatim else "\\n", ""]))
        hole = _gen_expr(rng, depth)
        if rng.random() < 0.3:
            hole += f",{rng.randrange(20)}"
        if rng.random() < 0.3:
            hole += ":" + rng.choice(["F2", "000", "N}}q", "x{{y"])
        parts.append("{" + hole + "}")
    parts.append(rng.choice(["tail", ""]))
    return q + "".join(parts) + '"'


def _gen_java_expr(rng, d):
    c = rng.randrange(8 if d < 3 else 5)
    if c == 0:
        return rng.choice(["x", "this.a", "u.name", "f(x)", "xs[i]", "A.B.c"])
    if c == 1:
        return str(rng.randrange(100))
    if c == 2:
        return f"({_gen_java_expr(rng, d + 1)} + {_gen_java_expr(rng, d + 1)})"
    if c == 3:
        return '"lit"'
    if c == 4:
        return (f"(c ? {_gen_java_expr(rng, d + 1)} : "
                f"{_gen_java_expr(rng, d + 1)})")
    if c == 5:
        return (f"((java.util.List<String>) "
                f"{_gen_java_expr(rng, d + 1)}).size()")
    if c == 6:
        return (f"switch (k) {{ case 1 -> {_gen_java_expr(rng, d + 1)}; "
                f"default -> {_gen_java_expr(rng, d + 1)}; }}")
    return f"xs.stream().map(v -> {_gen_java_expr(rng, d + 1)}).count()"


def _gen_java_stmt(rng, d):
    c = rng.randrange(7)
    if c == 0:
        return f"int q{d} = (int) ({_gen_java_expr(rng, d)});"
    if c == 1:
        return f"if (o instanceof String s{d}) {{ use(s{d}); }}"
    if c == 2:
        return (f"for (int i{d} = 0; i{d} < 3; i{d}++) "
                f"{{ use({_gen_java_expr(rng, d)}); }}")
    if c == 3:
        return f"var t{d} = {_gen_java_expr(rng, d)};"
    if c == 4:
        return ('String tb = """\n        text block "quoted"\n'
                '        """;')
    if c == 5:
        return (f"int r{d} = switch (k) {{ case 1: yield (int) "
                f"({_gen_java_expr(rng, d)}); default: yield 0; }};")
    return f"use({_gen_java_expr(rng, d)});"


def test_generated_java_methods_parse(tmp_path):
    """Structure-aware Java fuzz, full-parse property: generated methods
    mix casts, ternaries, switch expressions (incl. as cast operands and
    with colon+yield bodies), instanceof patterns, text blocks, lambdas
    and generic casts — every method must extract. The offline 8K-case
    campaign of this generator found the cast-of-switch-expression gap
    in round 5 (tests/test_extractor.py::test_cast_of_switch_expression)."""
    rng = random.Random(99)
    path = tmp_path / "gen.java"
    for it in range(200):
        body = "\n        ".join(
            _gen_java_stmt(rng, 0) for _ in range(rng.randint(1, 4)))
        code = ("public class C {\n"
                "    int k; Object o; int[] xs; U u; boolean c; int x;\n"
                f"    void m() {{\n        {body}\n    }}\n"
                "    int keep() { return 1; }\n}\n")
        path.write_text(code)
        proc = subprocess.run(
            [JAVA_BIN, "--max_path_length", "8", "--max_path_width", "2",
             "--file", str(path), "--no_hash"],
            capture_output=True, timeout=30, text=True)
        assert proc.returncode == 0, (it, code, proc.stderr)
        names = [ln.split(" ", 1)[0]
                 for ln in proc.stdout.splitlines() if ln.strip()]
        assert names == ["m", "keep"], (it, code, names, proc.stderr[:200])


def test_generated_interpolations_parse(tmp_path):
    rng = random.Random(424)
    path = tmp_path / "interp.cs"
    for it in range(300):
        s = _gen_interp(rng, 0)
        code = (f"class C {{ string M() {{ return {s}; }} "
                f"int K() {{ return 1; }} }}")
        path.write_text(code)
        proc = subprocess.run([CS_BIN, "--path", str(path), "--no_hash"],
                              capture_output=True, timeout=30, text=True)
        assert proc.returncode == 0, (it, code, proc.stderr)
        names = [ln.split(" ", 1)[0]
                 for ln in proc.stdout.splitlines() if ln.strip()]
        assert names == ["m", "k"], (it, code, names, proc.stderr[:200])
