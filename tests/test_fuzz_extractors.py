"""Bounded mutation-fuzz smoke test for both native extractors.

The full campaign (thousands of mutated inputs, byte flips + span
deletes/duplications + quote/comment injection) runs offline; this
seeded, bounded version keeps the no-crash property pinned in CI:
whatever bytes arrive, the extractor must exit cleanly (rc >= 0, no
signal) within the timeout — a crashed worker loses its whole
extraction batch, a clean failure loses one file.
"""

import random
import subprocess

import pytest

from tests.test_extractor import BINARY as JAVA_BIN
from tests.test_cs_extractor import BINARY as CS_BIN

SEEDS_JAVA = [
    'public class A { int f(int n) { return n > 0 ? f(n-1) : 0; } }',
    'public class B { String s = "esc\\"\\n tail"; int[] a = {1, 2}; }',
    ('public class C<T extends Comparable<? super T>> '
     '{ java.util.Map<String, java.util.List<int[]>> m; '
     'void f() { l: for (;;) break l; } }'),
    ('sealed interface S permits R {} '
     'record R(int a, String b) implements S { '
     'R { if (a < 0) { a = 0; } } int twice() { return a * 2; } }'),
]
SEEDS_CS = [
    'class A { string S = $"interp {1+1} tail"; int F() => 2; }',
    ('class A2 { string G(User u) => $"x {u.Name,-8:F2} y '
     '{(u.Ok ? $@"in ""{u.Id}"" {{esc}}" : "no")} z"; }'),
    ('class B<T> where T : struct { event System.EventHandler E; '
     'public static implicit operator int(B<T> b) => 0; }'),
    'class D { string V = @"verbatim ""q"" here"; int this[int i] => i; }',
    ('class E { object Q(int[] xs, int[] ys) => from x in xs '
     'join y in ys on x equals y into g orderby x descending '
     'let z = x + 1 group z by x into h select h.Key; }'),
    ('record Base(string N); record Kid(string N, int A) : Base(N) '
     '{ public int Twice() => A * 2; } record struct P(int X);'),
]


def _mutate(s: str, rng: random.Random) -> bytes:
    b = bytearray(s.encode())
    for _ in range(rng.randint(1, 40)):
        if not b:
            break
        op = rng.randrange(4)
        i = rng.randrange(len(b))
        if op == 0:
            b[i] = rng.randrange(256)
        elif op == 1:
            del b[i:i + rng.randint(1, 40)]
        elif op == 2:
            b[i:i] = bytes(rng.choices(
                b'(){}[]<>;,."\'\\@#$%&*-=+?:', k=rng.randint(1, 20)))
        else:
            j = rng.randrange(len(b))
            b[i:i] = b[j:j + rng.randint(1, 60)]
    return bytes(b)


@pytest.mark.parametrize("language", ["java", "cs"])
def test_mutated_inputs_never_crash(language, tmp_path):
    rng = random.Random(1234 if language == "java" else 5678)
    seeds = SEEDS_JAVA if language == "java" else SEEDS_CS
    path = tmp_path / f"fuzz.{language if language == 'cs' else 'java'}"
    for it in range(40):
        path.write_bytes(_mutate(rng.choice(seeds), rng))
        if language == "java":
            args = [JAVA_BIN, "--max_path_length", "8",
                    "--max_path_width", "2", "--file", str(path)]
        else:
            args = [CS_BIN, "--path", str(path)]
        proc = subprocess.run(args, capture_output=True, timeout=30)
        assert proc.returncode >= 0, (
            f"iter {it}: extractor died on signal {-proc.returncode}; "
            f"input saved at {path}")
