"""Metric-definition tests vs hand-computed cases
(spec: tensorflow_model.py:449-512, common.py:122-187)."""

import numpy as np

from code2vec_tpu.evaluation.metrics import (
    SubtokensEvaluationMetric, TargetWordTables, TopKAccuracyEvaluationMetric,
    first_match_rank,
)
from code2vec_tpu.vocab import (
    Vocab, VocabType, special_words_for,
)


def _vocab(words):
    return Vocab(VocabType.Target, words,
                 special_words_for(VocabType.Target, False))


def test_topk_accuracy_filtered_rank_semantics():
    # vocab: 0=<PAD_OR_OOV>, 1=get|name, 2=bad2name, 3=set|name, 4=run
    vocab = _vocab(["get|name", "bad2name", "set|name", "run"])
    tables = TargetWordTables(vocab)
    metric = TopKAccuracyEvaluationMetric(3, tables)
    # top-3 = [OOV, bad2name, get|name]: OOV + illegal are skipped, so
    # get|name is the FIRST filtered candidate -> correct at rank 1.
    metric.update_batch_from_indices(["getName"], np.array([[0, 2, 1]]))
    np.testing.assert_array_equal(metric.topk_correct_predictions, [1, 1, 1])
    # top-3 = [set|name, get|name, run] vs getName: match at filtered idx 1.
    metric.update_batch_from_indices(["getName"], np.array([[3, 1, 4]]))
    np.testing.assert_array_equal(metric.topk_correct_predictions,
                                  [0.5, 1, 1])
    # no match anywhere
    metric.update_batch_from_indices(["zzz"], np.array([[1, 3, 4]]))
    np.testing.assert_allclose(metric.topk_correct_predictions,
                               [1 / 3, 2 / 3, 2 / 3])


def test_subtoken_metric_counter_semantics():
    vocab = _vocab(["get|name", "get|get|name", "set|value", "run"])
    tables = TargetWordTables(vocab)
    metric = SubtokensEvaluationMetric(tables)
    # original getName -> subtokens Counter(getname: 1)?? No: original name
    # comes as the raw target string 'get|name' in .c2v data.
    # prediction get|get|name: tp counts duplicates (2x 'get' both count
    # since 'get' in original), fn for nothing, fp for nothing extra.
    metric.update_batch_from_indices(["get|name"], np.array([[2]]))
    assert metric.nr_true_positives == 3   # get,get,name all in original
    assert metric.nr_false_positives == 0
    assert metric.nr_false_negatives == 0

    metric2 = SubtokensEvaluationMetric(tables)
    # prediction set|value vs original get|name: 0 tp, 2 fp, 2 fn
    metric2.update_batch_from_indices(["get|name"], np.array([[3]]))
    assert (metric2.nr_true_positives, metric2.nr_false_positives,
            metric2.nr_false_negatives) == (0, 2, 2)
    assert metric2.precision == 0 and metric2.recall == 0 and metric2.f1 == 0


def test_subtoken_metric_no_legal_prediction_counts_fn():
    vocab = _vocab(["bad2name"])
    tables = TargetWordTables(vocab)
    metric = SubtokensEvaluationMetric(tables)
    # top-k contains only OOV and an illegal name: reference would crash
    # (tensorflow_model.py:459); we count all original subtokens as FN.
    metric.update_batch_from_indices(["get|name"], np.array([[0, 1]]))
    assert (metric.nr_true_positives, metric.nr_false_positives,
            metric.nr_false_negatives) == (0, 0, 2)


def test_first_match_rank():
    vocab = _vocab(["get|name", "bad2name", "set|name"])
    tables = TargetWordTables(vocab)
    assert first_match_rank(tables, "getName", [0, 2, 3, 1]) == (1, "get|name")
    assert first_match_rank(tables, "nope", [1, 3]) is None


def test_batch_prediction_info_matches_per_row_reference():
    """Differential: the vectorized batch pass must reproduce the naive
    per-row walk (the pre-vectorization implementation, which is the
    reference's literal semantics) on random batches that hit every edge
    case — no legal prediction, no match, match at every rank, OOV
    names, and out-of-vocab (padded-logit-column) indices."""
    from code2vec_tpu.common import normalize_word
    from code2vec_tpu.evaluation.metrics import batch_prediction_info

    words = ["get|name", "setvalue", "BAD_NAME!", "run", "x|y|z", "Get|Name",
             "a", "b|c", "Weird$", "go"]
    vocab = _vocab(words)
    tables = TargetWordTables(vocab)
    v = vocab.size
    rng = np.random.default_rng(9)
    names = ["getName", "setValue", "nosuch", "x|y|z", "GO", "b|c", "zzz"]
    for trial in range(50):
        b, k = int(rng.integers(1, 6)), int(rng.integers(1, 8))
        # sprinkle out-of-vocab indices (padded logit columns)
        topk = rng.integers(0, v + 2, (b, k))
        batch_names = [names[i] for i in rng.integers(0, len(names), b)]
        info = batch_prediction_info(tables, batch_names, topk)
        for i in range(b):
            # naive reference walk
            rank, midx, first_legal = -1, -1, -1
            filtered = 0
            for idx in topk[i]:
                idx = int(idx)
                if idx >= v or not tables.legal(idx):
                    continue
                if first_legal < 0:
                    first_legal = idx
                if tables.normalized(idx) == normalize_word(batch_names[i]):
                    rank, midx = filtered, idx
                    break
                filtered += 1
            assert info.match_rank[i] == rank, (trial, i)
            assert info.match_idx[i] == midx, (trial, i)
            assert info.first_legal_idx[i] == first_legal, (trial, i)
