"""Tests for the native C# path-context extractor (cpp/c2v-extract-cs).

Pinned against the reference C# pipeline's semantics: variable-centric
contexts (Extractor.cs:111-138), Roslyn-kind path strings with truncated
childIds (Extractor.cs:46-99), NUM masking and the C# normalizeName
quirks (Utilities.cs:103-154), comment contexts (Extractor.cs:204-218),
and classic .NET string hashing (Extractor.cs:224-233).
"""

import ctypes
import os
import subprocess

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract-cs")

TEMP_CS = """\
namespace Extractor
{
    class Temp
    {
        class NestedClass
        {
            void fooBar()
            {
                a.b = c;
            }
        }
    }
}
"""


def dotnet_string_hashcode(s: str) -> int:
    """Classic .NET Framework 32-bit String.GetHashCode."""
    h1 = ctypes.c_int32((5381 << 16) + 5381).value
    h2 = h1
    for i in range(0, len(s), 2):
        h1 = ctypes.c_int32(((h1 << 5) + h1) ^ ord(s[i])).value
        if i + 1 < len(s):
            h2 = ctypes.c_int32(((h2 << 5) + h2) ^ ord(s[i + 1])).value
    return ctypes.c_int32(h1 + ctypes.c_int32(h2 * 1566083941).value).value


@pytest.fixture(scope="module")
def extractor():
    if not os.path.exists(BINARY):
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr
    def run(path, *extra):
        proc = subprocess.run([BINARY, "--path", path, *extra],
                              capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout.splitlines()
    return run


@pytest.fixture()
def cs_file(tmp_path):
    def write(code, name="Input.cs"):
        p = tmp_path / name
        p.write_text(code)
        return str(p)
    return write


def test_temp_cs_golden(extractor, cs_file):
    """The reference's shipped Temp.cs fixture."""
    lines = extractor(cs_file(TEMP_CS), "--no_hash")
    assert len(lines) == 1
    parts = lines[0].split(" ")
    assert parts[0] == "foo|bar"
    contexts = [c.split(",") for c in parts[1:] if c]
    # Roslyn-kind paths, no parentheses; childIds under member access
    assert ["a", "IdentifierName0^SimpleMemberAccessExpression_IdentifierName1",
            "b"] in contexts
    # METHOD_NAME masking of the method's identifier token
    assert any("METHOD_NAME" in (c[0], c[2]) for c in contexts)
    # the void return type is a PredefinedType-token leaf
    assert any(c[0] == "void" and c[1].startswith("PredefinedType")
               for c in contexts)


def test_hashed_mode_matches_dotnet_hash(extractor, cs_file):
    plain = extractor(cs_file(TEMP_CS), "--no_hash")
    hashed = extractor(cs_file(TEMP_CS))
    for raw, enc in zip(plain[0].split(" ")[1:], hashed[0].split(" ")[1:]):
        if not raw:
            continue
        w1, path, w2 = raw.split(",")
        h1, phash, h2 = enc.split(",")
        assert (w1, w2) == (h1, h2)
        if path == "COMMENT":
            assert phash == "COMMENT"  # comment contexts are never hashed
        else:
            assert str(dotnet_string_hashcode(path)) == phash


def test_num_masking_and_whitelist(extractor, cs_file):
    """NUM replaces out-of-whitelist integers; {0,1,2,3,4,5,10} kept
    (Utilities.cs:37,136-148) — unlike the Java side, this is printed."""
    code = "class A { int F(int x) { return x + 37 + 5 + 10 + 1234; } }"
    line = extractor(cs_file(code), "--no_hash")[0]
    tokens = {c.split(",")[i] for c in line.split(" ")[1:] if c for i in (0, 2)}
    assert "NUM" in tokens
    assert "5" in tokens and "10" in tokens
    assert "37" not in tokens and "1234" not in tokens


def test_variable_grouping_groups_same_name(extractor, cs_file):
    """All occurrences of one name form one Variable; self-pairs give
    occurrence-to-occurrence paths (Extractor.cs:115-116)."""
    code = """
class A {
  int Sum(int[] data) {
    int total = 0;
    total = total + data[0];
    return total;
  }
}
"""
    line = extractor(cs_file(code), "--no_hash")[0]
    contexts = [c.split(",") for c in line.split(" ")[1:] if c]
    # self-pair: total <-> total across distinct occurrences
    assert any(c[0] == "total" and c[2] == "total" for c in contexts)
    # element access childId: BracketedArgumentList parents add ids
    assert any("BracketedArgumentList" in c[1] for c in contexts)


def test_comment_contexts(extractor, cs_file):
    code = """
class A {
  // reads the frobnicator index quickly for caching purposes extra words
  int F(int x) { return x; }
  /* block note */
  int G(int y) { return y; }
}
"""
    lines = extractor(cs_file(code), "--no_hash")
    assert len(lines) == 2
    for line in lines:  # whole-file comments attach to EVERY method
        ctxs = [c for c in line.split(" ")[1:] if ",COMMENT," in c]
        assert len(ctxs) >= 3  # 2 batches from the long comment + block
        first = ctxs[0].split(",")
        assert first[0] == first[2]
        assert len(first[0].split("|")) <= 5  # 5-subtoken batches
    doc = "class B { /// doc excluded\n int H(int z) { return z; } }"
    doc_lines = extractor(cs_file(doc, "B.cs"), "--no_hash")
    assert not any("COMMENT" in ln for ln in doc_lines)


def test_var_keyword_excluded(extractor, cs_file):
    code = "class A { int F() { var count = 1; return count; } }"
    line = extractor(cs_file(code), "--no_hash")[0]
    tokens = {c.split(",")[i] for c in line.split(" ")[1:] if c for i in (0, 2)}
    assert "var" not in tokens
    assert "count" in tokens


def test_string_literal_subtokens(extractor, cs_file):
    code = 'class A { string F() { return "hello worldPeace"; } }'
    line = extractor(cs_file(code), "--no_hash")[0]
    tokens = {c.split(",")[i] for c in line.split(" ")[1:] if c for i in (0, 2)}
    assert "hello|world|peace" in tokens


def test_methods_without_bodies_still_extracted(extractor, cs_file):
    """No body filter in the C# pipeline (Extractor.cs:172-178):
    interface methods produce (possibly context-light) lines too."""
    code = "interface I { int Size(); }"
    lines = extractor(cs_file(code), "--no_hash")
    assert len(lines) == 1
    assert lines[0].startswith("size ")


def test_parse_failure_skips_file(tmp_path, extractor):
    good = tmp_path / "Good.cs"
    good.write_text("class G { int Ok() { return 1; } }")
    bad = tmp_path / "Bad.cs"
    bad.write_text("class ]]] not csharp {{{")
    proc = subprocess.run([BINARY, "--path", str(tmp_path), "--no_hash"],
                          capture_output=True, text=True)
    assert proc.returncode == 0
    assert proc.stdout.startswith("ok ")
    assert "Bad.cs" in proc.stderr


def test_ofile_append_mode(tmp_path, extractor, cs_file):
    src = cs_file(TEMP_CS)
    out = tmp_path / "out.txt"
    for _ in range(2):
        subprocess.run([BINARY, "--path", src, "--no_hash",
                        "--ofile_name", str(out)], check=True,
                       capture_output=True)
    content = out.read_text().splitlines()
    assert len(content) == 2  # append semantics, like the reference


# --------------------------------------------------------- C#7/8 syntax
# The reference parses with Roslyn (Extractor.cs:170), which accepts all
# modern C#; these pin the from-scratch parser's coverage of the C#7/8
# constructs real corpora hit: patterns, switch expressions, tuples,
# local functions, using declarations — plus per-member recovery for
# anything still unsupported.

MODERN_CS = """
using System;
using System.Collections.Generic;
namespace N
{
    public class Modern
    {
        public int MatchShape(object o)
        {
            switch (o)
            {
                case int i when i > 0: return i;
                case string s: return s.Length;
                case 42: return 424;
                case null: return -1;
                default: return 0;
            }
        }

        public string GradeScore(int x) => x switch
        {
            < 0 => "invalid",
            0 => "zero",
            _ => "positive"
        };

        public (int, string) SplitPair(string joined)
        {
            var idx = joined.Length / 2;
            return (idx, joined);
        }

        public int SumViaHelper(int x)
        {
            int Helper(int y) { return y + 1; }
            return Helper(x) + Helper(x * 2);
        }

        public void FlushBuffer()
        {
            using var stream = new System.IO.MemoryStream();
            stream.Flush();
        }

        public (int count, string name) NamePair(string joined)
        {
            (int half, int rest) = (joined.Length / 2, 1);
            return (count: half + rest, name: joined);
        }

        public int DoubleViaLocal(int x)
        {
            static int Twice(int y) { return y * 2; }
            T Id<T>(T v) { return v; }
            return Id(Twice(x)) + x switch { 0 => 1, _ => 2 };
        }

        public int FirstVar(object o)
        {
            switch (o) { case var x: return 1; }
        }
    }
}
"""


def test_modern_csharp_constructs(extractor, cs_file):
    lines = extractor(cs_file(MODERN_CS), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["match|shape", "grade|score", "split|pair",
                     "sum|via|helper", "flush|buffer", "name|pair",
                     "double|via|local", "first|var"]
    by_name = dict(zip(names, lines))
    # pattern variables and constants feed path contexts
    assert "DeclarationPattern" in by_name["match|shape"]
    assert "WhenClause" in by_name["match|shape"]
    assert "SwitchExpression" in by_name["grade|score"]
    assert "RelationalPattern" in by_name["grade|score"]
    assert "TupleType" in by_name["split|pair"]
    assert "LocalFunctionStatement" in by_name["sum|via|helper"]
    # plain constant labels keep the legacy node (goldens pin this)
    assert "CaseSwitchLabel" in by_name["match|shape"]
    # named tuples + deconstruction (Roslyn NameColon/DeclarationExpression)
    assert "NameColon" in by_name["name|pair"]
    assert "DeclarationExpression" in by_name["name|pair"]
    # static + generic local functions; switch expr binds tighter than `+`
    assert "LocalFunctionStatement" in by_name["double|via|local"]
    assert "AddExpression^SwitchExpression" not in by_name["double|via|local"]
    assert "SwitchExpression" in by_name["double|via|local"]
    # `case var x` is Roslyn's VarPattern, not DeclarationPattern
    assert "VarPattern" in by_name["first|var"]


def test_per_member_recovery_skips_only_the_bad_member(cs_file):
    # An unparsable member must cost one member, not the file (the
    # reference's Roslyn never hard-fails).
    code = """
using System;
using System.Linq;
namespace N
{
    public class Mixed
    {
        public int CountItems(int[] xs)
        {
            return xs.Length;
        }

        public object BrokenItems(int[] xs)
        {
            var q = xs |> ??! select;
            return q;
        }

        public int SumItems(int[] xs)
        {
            int acc = 0;
            foreach (int x in xs) { acc += x; }
            return acc;
        }
    }
}
"""
    proc = subprocess.run([BINARY, "--path", cs_file(code), "--no_hash"],
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    names = [ln.split(" ", 1)[0] for ln in proc.stdout.splitlines()]
    assert "count|items" in names
    assert "sum|items" in names
    assert "warning: skipped unparsable member" in proc.stderr


LINQ_CS = """
using System;
using System.Linq;
using System.Collections.Generic;
public class Queries
{
    public List<string> AdultNames(List<Person> people)
    {
        var names = from p in people
                    where p.Age >= 18
                    orderby p.Name ascending, p.Age descending
                    select p.Name;
        return names.ToList();
    }
    public IEnumerable<int> JoinTotals(List<Item> items, List<Price> prices)
    {
        return from Item i in items
               join Price pr in prices on i.Id equals pr.ItemId into g
               from pp in g
               let twice = pp.Value * 2
               select twice + 1;
    }
    public object ByCity(List<Person> people)
    {
        return from p in people
               group p by p.City into cityGroup
               select cityGroup.Key;
    }
    public int NotAQuery(int from)
    {
        int x = from + 1;
        return from - x;
    }
    public List<int> ParenQuery(List<int> xs)
    {
        return (from v in xs where v < 10 select v).ToList();
    }
}
"""


def test_linq_query_expressions(extractor, cs_file):
    """Query expressions parse whole into Roslyn-kind nodes (reference
    consumes full Roslyn trees, CSharpExtractor/Extractor/Tree.cs:100-204);
    an identifier merely named `from` must not trigger the query path."""
    lines = extractor(cs_file(LINQ_CS), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["adult|names", "join|totals", "by|city", "not|a|query",
                     "paren|query"]
    by_name = dict(zip(names, lines))
    for kind in ("QueryExpression", "FromClause", "QueryBody",
                 "WhereClause", "OrderByClause", "AscendingOrdering",
                 "DescendingOrdering", "SelectClause"):
        assert kind in by_name["adult|names"], kind
    for kind in ("JoinClause", "JoinIntoClause", "LetClause"):
        assert kind in by_name["join|totals"], kind
    for kind in ("GroupClause", "QueryContinuation"):
        assert kind in by_name["by|city"], kind
    # range variables are identifier leaves: `p` pairs into contexts
    assert ",p " in by_name["adult|names"] or " p," in by_name["adult|names"]
    # `from` used as a plain identifier stays an ordinary expression
    assert "QueryExpression" not in by_name["not|a|query"]
    assert "SubtractExpression" in by_name["not|a|query"]
    # `(from v in ...)` must survive the declaration-expression
    # speculation in the parenthesized/tuple argument path
    assert "QueryExpression" in by_name["paren|query"]


def test_csharp_records(extractor, cs_file):
    """C#9/10 record types parse whole (Roslyn RecordDeclaration /
    RecordStructDeclaration with primary-constructor ParameterList);
    `record` stays usable as an ordinary identifier."""
    code = """
using System;
public record Person(string Name, int Age)
{
    public string Display() { return Name + ":" + Age; }
}
public record Student(string Name, int Age, string School)
    : Person(Name, Age)
{
    public string Tag() { return School + "/" + Display(); }
}
public record struct Pt(int X, int Y)
{
    public int Dot(Pt o) { return X * o.X + Y * o.Y; }
}
public record Empty(int Value);
public class Keep
{
    int record = 1;
    int UseIt(int record) { return record + 1; }
}
public class Edge
{
    // `record r;` is AMBIGUOUS for pre-C#9 sources that had a type
    // named `record`; C#9+ compilers resolve the ambiguity toward the
    // contextual keyword (declaring a type named `record` is itself a
    // C#9 warning), so this parses as a body-less nested record named
    // `r`, not a field — pinned here, entry in cpp/DEVIATIONS.md.
    record r;
    // ...while an initializer makes it unambiguous: a field again.
    record q = null;
    int After() { return 2; }
}
"""
    lines = extractor(cs_file(code), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["display", "tag", "dot", "use|it", "after"]
    by_name = dict(zip(names, lines))
    # component identifiers used in bodies feed contexts as usual
    assert ",name " in by_name["display"] or " name," in by_name["display"]
    assert "school" in by_name["tag"]


def test_parenthesized_conditional_with_bare_ident(extractor, cs_file):
    """`(c ? x : y)` — a bare-identifier condition must not be eaten by
    the tuple-element declaration speculation (`c?` nullable type +
    designation `x`), which used to fail the member at the `:`. Found by
    the round-5 structure-aware interpolation fuzzer; the fix requires
    the designation to END the tuple element (follow set `,`/`)`), same
    rule as the `out T x` path."""
    code = """
public class C
{
    object A(bool c, User user) { return (c ? user.Name : 61); }
    string B(bool c, int x, int y) { return $"{(c ? x : y),4}"; }
    void D() { (int a, string b) = GetPair(); Use(a, b); }
}
"""
    lines = extractor(cs_file(code), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["a", "b", "d"]
    by_name = dict(zip(names, lines))
    assert "ConditionalExpression" in by_name["a"]
    assert "name" in by_name["a"]
    assert "ConditionalExpression" in by_name["b"]
    assert "DeclarationExpression" in by_name["d"]  # real deconstruction


def test_interpolated_string_holes(extractor, cs_file):
    """$-string holes are REAL sub-expressions (Roslyn: Interpolation
    nodes under InterpolatedStringExpression, with alignment/format
    clauses), not one opaque token — `$"{user.Name}"` must feed `name`
    into path contexts. Covers: member-access holes, alignment+format
    (`{x,8:F2}`), `{{`/`}}` escapes, nested $-strings inside holes, and
    verbatim-interpolated `$@"..."` with `""` escapes."""
    code = """
public class C
{
    string Greet(User user) { return $"hi {user.Name}, owe {user.Balance,8:F2}"; }
    string Nested(Order o) { return $"n {(o.Fine ? $"ok {o.Id}" : "bad")}"; }
    string Esc(int n) { return $"{{lit}} {n:000} t"; }
    string Verb(string p) { return $@"pre ""{p}"" post"; }
}
"""
    lines = extractor(cs_file(code), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["greet", "nested", "esc", "verb"]
    by_name = dict(zip(names, lines))
    # hole leaves reach contexts with Roslyn-shaped path nodes
    assert "Interpolation" in by_name["greet"]
    assert "InterpolatedStringExpression" in by_name["greet"]
    assert ",name " in by_name["greet"] or " name," in by_name["greet"]
    assert "balance" in by_name["greet"]
    assert "InterpolationAlignmentClause" in by_name["greet"]
    assert "InterpolationFormatClause" in by_name["greet"]
    # nested $-string inside a hole: inner hole's leaf present
    assert ",id " in by_name["nested"] or " id," in by_name["nested"]
    # {{...}} stays literal text; format text is a leaf, not parsed code
    assert "lit" in by_name["esc"]
    # verbatim-interpolated: "" escapes survive, hole leaf present
    assert ",p " in by_name["verb"] or " p," in by_name["verb"]


def test_raw_string_literals(extractor, cs_file):
    """C#11 raw strings: `\"\"\"...\"\"\"` (no escapes, inner quotes
    legal, multi-line with closing-line dedent) and interpolated raw
    `$\"\"\"`/`$$\"\"\"` where the dollar count sets the hole's brace
    count; shorter brace runs stay literal text."""
    code = '''
public class C
{
    string Plain() { return """hello "quoted" raw"""; }
    string Multi()
    {
        return """
            line one
            line two
            """;
    }
    string Interp(User u) { return $"""val {u.Name} end"""; }
    string Dollar(User u) { return $$"""lit {brace} hole {{u.Id}} end"""; }
    int After() { return 7; }
}
'''
    lines = extractor(cs_file(code), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["plain", "multi", "interp", "dollar", "after"]
    by_name = dict(zip(names, lines))
    assert "hello|quoted|raw" in by_name["plain"]
    # dedent: closing-line indentation stripped, inner newline kept
    assert "line|one|line|two" in by_name["multi"]
    # interpolated raw: hole leaves reach contexts
    assert "Interpolation" in by_name["interp"]
    assert ",name " in by_name["interp"] or " name," in by_name["interp"]
    # $$: single-brace runs are TEXT, double-brace runs are holes
    assert ",id " in by_name["dollar"] or " id," in by_name["dollar"]
    assert "brace" in by_name["dollar"]


def test_adversarial_nesting_fails_cleanly(cs_file):
    """Pathological nesting -> clean error or per-member skip, never a
    SIGSEGV (parser DepthGuard + iterative CsCheckAstDepth)."""
    cases = {
        "deep_parens": ("class C { int Keep(int x){return x;} int M() "
                        "{ return " + "(" * 20000 + "1" + ")" * 20000
                        + "; } }"),
        "long_chain": ("class C { int M() { int y = " + "1+" * 100000
                       + "1; return y; } }"),
        "deep_ifs": ("class C { void M() { " + "if (true) {" * 10000
                     + "}" * 10000 + " } }"),
        "nested_classes": ("class A {" + " class B {" * 50000
                           + "}" * 50000 + " }"),
        "ctor_chain": ("class C { C() { int y = " + "1+" * 100000
                       + "1; } int Keep(){return 1;} }"),
        # each `into` recurses ParseQueryBody once; must trip the
        # DepthGuard, not the native stack
        "query_into_chain": ("class C { object M(int[] xs) { var q = "
                             "from x in xs select x "
                             + "into a select a " * 100000
                             + "; return q; } int Keep(){return 1;} }"),
    }
    for name, src in cases.items():
        proc = subprocess.run([BINARY, "--path", cs_file(src, f"{name}.cs")],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode >= 0, f"{name}: died on signal {-proc.returncode}"
    proc = subprocess.run(
        [BINARY, "--path", cs_file(cases["deep_parens"], "again.cs")],
        capture_output=True, text=True, timeout=60)
    assert "keep" in proc.stdout
