"""Tier-1 gate for scripts/check_knobs_doc.py: every long CLI flag
registered in code2vec_tpu/cli.py must appear in the README "CLI knob
reference" table and vice versa, and every flag's dest must land in a
Config field (or the checker's closed _ARGS_ONLY allowlist) — a new
knob cannot ship undocumented or silently unwired, and the table
cannot keep flags the CLI dropped."""

import importlib.util
import os

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKER = os.path.join(REPO_ROOT, "scripts", "check_knobs_doc.py")


def _load_checker():
    spec = importlib.util.spec_from_file_location("check_knobs_doc",
                                                  CHECKER)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


def test_every_registered_flag_is_documented_wired_and_vice_versa():
    checker = _load_checker()
    problems = checker.check()
    assert problems == [], "\n".join(problems)


def test_checker_extracts_a_plausible_flag_set():
    """The AST walk must actually see the parser: spot-check flags
    from different layers (training, serving, fleet, edge, pipeline)
    so a silently-broken walk cannot turn the doc check vacuous."""
    checker = _load_checker()
    flags = set(checker.registered_flags())
    assert len(flags) >= 100
    for expected in ("--load", "--serve_port", "--fleet_hosts",
                     "--fleet_routers", "--fleet_control",
                     "--fleet_no_affinity", "--fleet_launcher",
                     "--fleet_addresses", "--pipeline_dir",
                     "--retrieval_topk"):
        assert expected in flags, f"{expected} missing from the walk"
    # and the Config-field side of the wiring check
    fields = checker.config_fields()
    assert {"serve_port", "fleet_routers", "fleet_cache_affinity",
            "fleet_launcher"} <= fields


def test_checker_flags_undocumented_stale_and_unwired(tmp_path,
                                                      monkeypatch):
    """The check fails in ALL THREE directions: an
    unregistered-but-documented flag, a registered-but-undocumented
    flag, and a flag whose dest lands nowhere."""
    checker = _load_checker()
    readme = tmp_path / "README.md"
    rows = "\n".join(f"| `{f}` | x | x |"
                     for f in sorted(checker.registered_flags())
                     if f != "--serve_port")
    readme.write_text(
        "# x\n<!-- knobs-table:begin -->\n"
        f"{rows}\n| `--made_up_flag` | x | x |\n"
        "<!-- knobs-table:end -->\n")
    monkeypatch.setattr(checker, "README", str(readme))
    problems = checker.check()
    assert any("UNDOCUMENTED: --serve_port" in p for p in problems)
    assert any("STALE DOC: --made_up_flag" in p for p in problems)
    # unwired: a parser whose flag's dest is not a Config field
    cli = tmp_path / "cli.py"
    cli.write_text('parser.add_argument("--ghost_knob", type=int)\n')
    monkeypatch.setattr(checker, "CLI_PATH", str(cli))
    problems = checker.check()
    assert any("UNWIRED: --ghost_knob" in p and "ghost_knob" in p
               for p in problems)


def test_checker_rejects_non_literal_option_strings(tmp_path,
                                                    monkeypatch):
    import pytest

    checker = _load_checker()
    cli = tmp_path / "cli.py"
    cli.write_text('name = "--dyn"\nparser.add_argument(name)\n')
    monkeypatch.setattr(checker, "CLI_PATH", str(cli))
    with pytest.raises(SystemExit, match="non-literal"):
        checker.registered_flags()
