"""End-to-end slice on a tiny synthetic dataset: preprocess -> vocab ->
train (loss decreases) -> evaluate (model memorizes) -> save/load -> predict.
This is BASELINE.json config #1's shape (java-small, CPU-runnable) in
miniature."""

import os
import pickle
import random

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.model_facade import Code2VecModel
from code2vec_tpu.vocab import VocabType


def _make_synthetic_dataset(tmp_path, n_rows=96, max_contexts=8, seed=0):
    """Learnable synthetic data: target determined by which tokens appear."""
    rng = random.Random(seed)
    # NB: targets must match the legality filter ^[a-zA-Z|]+$
    # (common.py:122-124) or every prediction is filtered out.
    letters = ["alpha", "beta", "gamma", "delta"]
    tokens = [f"tok{i}" for i in range(12)]
    paths = [f"path{i}" for i in range(6)]
    targets = [f"name|{letters[i]}" for i in range(4)]
    rows = []
    for _ in range(n_rows):
        t = rng.randrange(len(targets))
        contexts = []
        for _ in range(rng.randint(3, max_contexts)):
            # token identity leaks the target -> memorizable
            tok = tokens[t * 3 + rng.randrange(3)]
            contexts.append(f"{tok},{rng.choice(paths)},{tok}")
        pad = " " * (max_contexts - len(contexts))
        rows.append(f"{targets[t]} " + " ".join(contexts) + pad)

    token_counts = {w: 10 for w in tokens}
    path_counts = {p: 10 for p in paths}
    target_counts = {t: 10 for t in targets}

    prefix = str(tmp_path / "synthetic")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(prefix + ".val.c2v", "w") as f:
        f.write("\n".join(rows[:32]) + "\n")
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump(token_counts, f)
        pickle.dump(path_counts, f)
        pickle.dump(target_counts, f)
        pickle.dump(len(rows), f)
    return prefix


@pytest.mark.parametrize("use_packed", [True, False])
def test_train_eval_save_load_predict(tmp_path, use_packed):
    prefix = _make_synthetic_dataset(tmp_path)
    save_path = str(tmp_path / "model" / "saved_model")
    config = Config(
        train_data_path_prefix=prefix,
        test_data_path=prefix + ".val.c2v",
        model_save_path=save_path,
        max_contexts=8,
        train_batch_size=16, test_batch_size=16,
        num_train_epochs=30,
        num_batches_to_log_progress=1000,
        compute_dtype="float32",
        use_packed_data=use_packed,
        shuffle_buffer_size=64,
        save_every_epochs=1000,  # don't checkpoint mid-test
        verbose_mode=0,
    )
    model = Code2VecModel(config)
    model.train()

    results = model.evaluate()
    # memorizable dataset: near-perfect top-1 after 30 epochs
    assert results.topk_acc[0] > 0.9, str(results)
    assert results.subtoken_f1 > 0.9, str(results)

    # w2v export
    w2v_path = str(tmp_path / "tokens.w2v")
    model.save_word2vec_format(w2v_path, VocabType.Token)
    with open(w2v_path) as f:
        header = f.readline().split()
    assert int(header[0]) == model.vocabs.token_vocab.size
    assert int(header[1]) == config.token_embeddings_size

    # load into a fresh model and check eval matches; also exercise the
    # code-vector export — by default the sharded retrieval store
    # format (retrieval/store.py; --vectors_text restores the
    # reference's text layout, pinned in tests/test_retrieval.py)
    load_config = Config(
        model_load_path=save_path,
        test_data_path=prefix + ".val.c2v",
        max_contexts=8, test_batch_size=16,
        compute_dtype="float32",
        use_packed_data=use_packed,
        export_code_vectors=True,
        verbose_mode=0,
    )
    loaded = Code2VecModel(load_config)
    results2 = loaded.evaluate()
    np.testing.assert_allclose(results2.topk_acc, results.topk_acc, atol=1e-6)
    vectors_path = load_config.test_data_path + ".vectors"
    assert os.path.exists(vectors_path)
    from code2vec_tpu.retrieval.store import VectorStore
    store = VectorStore.open(vectors_path)
    assert store.rows == load_config.num_test_examples
    assert store.dim == 3 * load_config.token_embeddings_size
    assert store.fingerprint == loaded.model_fingerprint()
    assert np.isfinite(store.load()).all()

    # predict on a raw line (no filtering)
    line = "unknownname tok0,path0,tok0 tok1,path1,tok1" + " " * 6
    preds = loaded.predict([line])
    assert len(preds) == 1
    assert preds[0].original_name == "unknownname"
    # k is clamped to the target vocab size (reference:
    # tensorflow_model.py:298-299)
    assert len(preds[0].topk_predicted_words) == min(
        config.top_k_words_considered_during_prediction,
        loaded.vocabs.target_vocab.size)
    assert abs(sum(preds[0].topk_predicted_words_scores) - 1.0) < 1e-5
    assert ("tok0", "path0", "tok0") in preds[0].attention_per_context
    # name|alpha should be the top prediction for tok0/tok1 contexts
    assert preds[0].topk_predicted_words[0] == "name|alpha"


def test_release_roundtrip(tmp_path):
    prefix = _make_synthetic_dataset(tmp_path, n_rows=32)
    save_path = str(tmp_path / "model" / "m")
    config = Config(
        train_data_path_prefix=prefix, model_save_path=save_path,
        max_contexts=8, train_batch_size=16, num_train_epochs=2,
        compute_dtype="float32", verbose_mode=0, save_every_epochs=1000,
        num_batches_to_log_progress=1000)
    model = Code2VecModel(config)
    model.train()

    release_config = Config(
        model_load_path=save_path, release=True, max_contexts=8,
        compute_dtype="float32", verbose_mode=0)
    releaser = Code2VecModel(release_config)
    assert releaser.evaluate() is None  # release mode returns None
    released_path = save_path + ".release"
    assert os.path.isdir(released_path)

    # released artifact loads (without optimizer state)
    from code2vec_tpu.training.checkpoint import load_model_meta
    assert load_model_meta(released_path)["released"] is True
    load_config = Config(
        model_load_path=released_path, test_data_path=prefix + ".val.c2v",
        max_contexts=8, test_batch_size=16, compute_dtype="float32",
        verbose_mode=0)
    loaded = Code2VecModel(load_config)
    results = loaded.evaluate()
    assert results is not None


def test_repl_pipeline_on_input_java(tmp_path):
    """The interactive REPL's loop body, non-interactively: native
    extractor bridge over the shipped Input.java -> model.predict ->
    parse_prediction_results (predictions + attention display rows).
    reference flow: interactive_predict.py:39-72."""
    import os
    import subprocess
    from code2vec_tpu.serving.extractor_bridge import PathExtractor
    from code2vec_tpu.serving.interactive import parse_prediction_results

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    binary = os.path.join(repo_root, "cpp", "build", "c2v-extract")
    if not os.path.exists(binary):
        rc = subprocess.run(["make", "-C", os.path.join(repo_root, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr

    prefix = _make_synthetic_dataset(tmp_path)
    config = Config(
        train_data_path_prefix=prefix,
        max_contexts=8, train_batch_size=16, test_batch_size=16,
        num_train_epochs=1, compute_dtype="float32",
        num_batches_to_log_progress=1000, shuffle_buffer_size=64,
        save_every_epochs=1000)
    model = Code2VecModel(config)
    model.train()

    extractor = PathExtractor(config, max_path_length=8, max_path_width=2)
    lines, hash_to_string = extractor.extract_paths(
        os.path.join(repo_root, "Input.java"))
    assert lines, "no methods extracted from Input.java"

    raw = model.predict(lines)
    oov = model.vocabs.target_vocab.special_words.oov
    methods = parse_prediction_results(raw, hash_to_string, oov, topk=5)
    assert len(methods) == len(lines)
    m = methods[0]
    # the shipped Input.java defines `sumValues` (subtokens sum|values)
    assert m.original_name == "sum|values"
    assert m.predictions, "no top-k predictions surfaced"
    assert all(0.0 <= p["probability"] <= 1.0 for p in m.predictions)
    # attention rows must display READABLE paths (hash inverted)
    assert m.attention_paths
    for att in m.attention_paths:
        assert att["path"].startswith("("), att  # node-string form
        assert 0.0 <= att["score"] <= 1.0
