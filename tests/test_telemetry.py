"""Fleet telemetry suite (serving/telemetry.py + supervisor wiring):
exposition-text parse/merge semantics (counters + histograms summed,
gauges labeled per replica), the /fleet view math, and the 2-replica
supervisor e2e acceptance pin — merged /metrics request counters equal
the sum of the per-replica counters under concurrent load, fixing the
PR-9 reuseport one-replica-scrape gap."""

import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from code2vec_tpu.obs.metrics import MetricsRegistry
from code2vec_tpu.serving import telemetry

from test_serving import FAKE_EXTRACTOR

pytestmark = pytest.mark.telemetry

CHILD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "chaos_serving_child.py")


@pytest.fixture()
def fake_extractor(tmp_path, monkeypatch):
    path = tmp_path / "fake-c2v-extract"
    path.write_text(FAKE_EXTRACTOR)
    path.chmod(0o755)
    monkeypatch.setenv("C2V_NATIVE_EXTRACTOR", str(path))
    return str(path)


# ------------------------------------------------------ parse + merge


def _registry_text(requests=0, shed=0, depth=0.0, lat=()):
    reg = MetricsRegistry()
    if requests:
        reg.counter("serving_requests_total", "reqs",
                    endpoint="predict", status="200").inc(requests)
    if shed:
        reg.counter("serving_requests_shed_total", "sheds",
                    reason="breaker").inc(shed)
    reg.gauge("serving_admission_depth", "depth").set(depth)
    h = reg.histogram("serving_device_seconds", "lat", buckets=(0.1, 1.0))
    for v in lat:
        h.observe(v)
    return reg.render_prometheus()


def test_parse_prometheus_text_roundtrips_obs_render():
    text = _registry_text(requests=3, shed=1, depth=2.0,
                          lat=(0.05, 0.5, 5.0))
    fams = telemetry.parse_prometheus_text(text)
    assert fams["serving_requests_total"].kind == "counter"
    assert fams["serving_requests_total"].samples[
        "serving_requests_total"][
        (("endpoint", "predict"), ("status", "200"))] == 3.0
    assert fams["serving_admission_depth"].kind == "gauge"
    hist = fams["serving_device_seconds"]
    assert hist.kind == "histogram"
    # bucket samples attach to the DECLARING family, le labels parsed
    buckets = hist.samples["serving_device_seconds_bucket"]
    assert buckets[(("le", "0.1"),)] == 1.0
    assert buckets[(("le", "1"),)] == 2.0
    assert buckets[(("le", "+Inf"),)] == 3.0
    assert hist.samples["serving_device_seconds_count"][()] == 3.0
    assert hist.samples["serving_device_seconds_sum"][()] \
        == pytest.approx(5.55)
    # garbage lines are skipped, not fatal
    assert telemetry.parse_prometheus_text(
        "!!!\nnot a line\n# weird\n") == {}


def test_merge_sums_counters_and_histograms_labels_gauges():
    merged = telemetry.merge_prometheus_snapshots({
        "0": _registry_text(requests=3, shed=1, depth=2.0,
                            lat=(0.05, 0.5)),
        "1": _registry_text(requests=4, depth=5.0, lat=(5.0,)),
    })
    # counters summed across replicas by (name, labels)
    assert ('serving_requests_total{endpoint="predict",status="200"} 7'
            in merged)
    assert 'serving_requests_shed_total{reason="breaker"} 1' in merged
    # histogram buckets/sum/count summed
    assert 'serving_device_seconds_bucket{le="0.1"} 1' in merged
    assert 'serving_device_seconds_bucket{le="1"} 2' in merged
    assert 'serving_device_seconds_bucket{le="+Inf"} 3' in merged
    assert 'serving_device_seconds_count 3' in merged
    # gauges NOT summed: one sample per replica, replica label added
    assert 'serving_admission_depth{replica="0"} 2' in merged
    assert 'serving_admission_depth{replica="1"} 5' in merged
    # and the merged text re-parses (it is valid exposition format)
    fams = telemetry.parse_prometheus_text(merged)
    assert telemetry.sum_family(fams, "serving_requests_total") == 7.0
    assert fams["serving_device_seconds"].kind == "histogram"


def test_sum_family_with_label_filter():
    text = _registry_text(requests=5, shed=2)
    assert telemetry.sum_family(text, "serving_requests_total") == 5.0
    assert telemetry.sum_family(text, "serving_requests_total",
                                status="200") == 5.0
    assert telemetry.sum_family(text, "serving_requests_total",
                                status="503") == 0.0
    assert telemetry.sum_family(text, "nope_total") == 0.0


def test_fleet_replica_view_staleness_and_shed_rate():
    now = time.time()
    hb = {"wall_time": now - 1.5, "status": "serving",
          "model_fingerprint": "fp-a",
          "breakers": {"extractor": "closed", "device": "open"},
          "requests_total": 50, "requests_shed_total": 10,
          "requests_expired_total": 2, "swap_state": "idle",
          "inflight": 1}
    view = telemetry.fleet_replica_view(hb, now)
    assert view["heartbeat_age_s"] == pytest.approx(1.5, abs=0.05)
    assert view["shed_rate"] == pytest.approx(0.2)
    assert view["breakers"]["device"] == "open"
    assert view["model_fingerprint"] == "fp-a"
    # zero traffic: rate 0.0, not a division error
    assert telemetry.fleet_replica_view(
        {"wall_time": now, "requests_total": 0}, now)["shed_rate"] == 0.0
    # no heartbeat yet: nulls, never a crash
    empty = telemetry.fleet_replica_view(None, now)
    assert empty["status"] is None and empty["shed_rate"] is None


def test_merge_survives_torn_and_garbage_snapshots():
    """A torn or mid-rewrite snapshot must never crash the merge: bad
    lines are skipped per line, full-garbage text merges to nothing."""
    good = _registry_text(requests=3)
    torn = good[: len(good) // 2]  # truncated mid-line
    merged = telemetry.merge_prometheus_snapshots(
        {"0": good, "1": torn, "2": "\x00\xff not prometheus {{{",
         "3": ""})
    fams = telemetry.parse_prometheus_text(merged)
    # the intact replica's counters survive; the torn one contributes
    # only its complete lines; garbage contributes nothing
    assert telemetry.sum_family(fams, "serving_requests_total") >= 3.0


def test_supervisor_scrape_skips_and_counts_bad_replica_snapshot(
        tmp_path):
    """Satellite pin: a replica metrics file caught torn/garbled must
    be SKIPPED AND COUNTED — the supervisor /metrics scrape stays 200
    on the surviving replicas' truth, never a 500."""
    from code2vec_tpu import obs
    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.supervisor import Supervisor
    from code2vec_tpu.serving.telemetry import TelemetryServer

    config = Config(
        serve=True, serve_host="127.0.0.1", serve_port=0,
        serve_replicas=2, verbose_mode=0,
        heartbeat_file=str(tmp_path / "supervisor.heartbeat.json"))
    sup = Supervisor(config, child_command=["true"])  # never spawned
    # replica 0: binary garbage (a torn rewrite / disk corruption);
    # replica 1: a valid snapshot
    with open(sup.replicas[0].metrics_path, "wb") as f:
        f.write(b"\x00\xffgarbage{{{ 7\n===")
    with open(sup.replicas[1].metrics_path, "w") as f:
        f.write(_registry_text(requests=5))

    def skipped():
        return sum(
            child.value for labels, child in obs.default_registry()
            .collect().get("serving_telemetry_snapshots_skipped_total",
                           {}).items())

    before = skipped()
    merged = sup.merged_metrics()
    assert telemetry.sum_family(
        merged, "serving_requests_total") >= 5.0
    assert skipped() == before + 1
    # and over HTTP: 200, never a 500, repeat scrapes keep counting
    telem = TelemetryServer(sup.merged_metrics, sup.fleet_view,
                            host="127.0.0.1", port=0)
    try:
        status, body = _get("127.0.0.1", telem.port, "/metrics")
        assert status == 200
        assert telemetry.sum_family(
            body.decode(), "serving_requests_total") >= 5.0
        assert skipped() == before + 2
    finally:
        telem.close()


# --------------------------------------------------- supervisor e2e


def _get(host, port, path):
    with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                timeout=30) as r:
        return r.status, r.read()


def _post(port, endpoint, body, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}", data=body.encode(),
        method="POST", headers=dict({"Content-Type": "text/plain"},
                                    **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


def _wait_live_replicas(sup, n, timeout=30.0):
    deadline = time.time() + timeout
    hb = None
    while time.time() < deadline:
        try:
            hb = json.loads(open(sup.heartbeat_path).read())
        except (OSError, ValueError):
            hb = None
        if hb:
            live = [r for r in hb["replicas"] if r["alive"] and r["port"]]
            if len(live) >= n:
                return hb
        time.sleep(0.05)
    raise AssertionError(f"never reached {n} live replicas; last={hb}")


def test_supervisor_merged_metrics_equal_replica_sum_and_fleet(
        tmp_path, fake_extractor, monkeypatch):
    """Acceptance pin: a 2-replica supervisor serves merged /metrics
    whose request counters equal the sum of the per-replica counters
    under concurrent load — plus the /fleet JSON view (breaker state,
    shed rate, staleness, fingerprints) and the proxy-port /metrics
    interception (never round-robined to one replica)."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.supervisor import Supervisor
    monkeypatch.setenv("C2V_SERVE_FORCE_PROXY", "1")
    overrides = dict(
        serve_host="127.0.0.1", max_contexts=16, serve_batch_size=4,
        serve_buckets="4,8", serve_max_delay_ms=2.0,
        serve_cache_entries=0, extractor_pool_size=1,
        serve_drain_timeout_s=5.0, serve_heartbeat_interval_s=0.2)
    overrides_path = tmp_path / "child-config.json"
    overrides_path.write_text(json.dumps(overrides))
    config = Config(
        serve=True, serve_host="127.0.0.1", serve_port=0,
        serve_replicas=2, serve_max_restarts=5,
        serve_heartbeat_interval_s=0.2, serve_drain_timeout_s=5.0,
        serve_telemetry_port=0,
        heartbeat_file=str(tmp_path / "supervisor.heartbeat.json"),
        verbose_mode=0)
    sup = Supervisor(config, child_command=[
        sys.executable, CHILD, str(overrides_path)])
    rc_holder = {}
    thread = threading.Thread(
        target=lambda: rc_holder.update(rc=sup.run()), daemon=True)
    thread.start()
    try:
        hb = _wait_live_replicas(sup, 2)
        assert hb["telemetry_port"] == sup._telemetry.port
        tport = hb["telemetry_port"]

        # concurrent load through the public (proxy) port
        n_requests, n_threads = 12, 4
        statuses = []
        lock = threading.Lock()

        def load(ci):
            for i in range(n_requests // n_threads):
                status, _, _ = _post(
                    sup.port, "predict",
                    f"class L{ci}x{i} {{ int m{ci}x{i}() "
                    f"{{ return 1; }} }}")
                with lock:
                    statuses.append(status)

        threads = [threading.Thread(target=load, args=(ci,))
                   for ci in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert statuses == [200] * n_requests

        # the proxy must carry trace headers BOTH ways: an inbound
        # traceparent reaches the replica (same trace id end to end)
        # and the replica's X-Trace-Id/traceparent reach the client
        inbound = "ab" * 16
        status, _, hdrs = _post(
            sup.port, "predict",
            "class P { int proxied() { return 1; } }",
            headers={"traceparent":
                     f"00-{inbound}-{'cd' * 8}-01"})
        assert status == 200
        assert hdrs["X-Trace-Id"] == inbound
        assert hdrs["traceparent"].split("-")[1] == inbound
        expected_total = n_requests + 1  # the traced request counts too

        # The supervisor folds its OWN process registry into the merge
        # (as replica="supervisor") — in this test the supervisor runs
        # IN the pytest process, whose registry carries counts from
        # earlier serving tests, so the acceptance equality is on the
        # merge MINUS the supervisor-process contribution (constant
        # here: nothing serves in-process during this test).
        from code2vec_tpu import obs
        sup_own = telemetry.sum_family(
            obs.default_registry().render_prometheus(),
            "serving_requests_total")
        # replica snapshots are rewritten every 0.2s: poll the MERGED
        # endpoint until every request is visible
        deadline = time.time() + 20
        merged_total = per_replica = None
        while time.time() < deadline:
            _, merged_body = _get("127.0.0.1", tport, "/metrics")
            merged_total = telemetry.sum_family(
                merged_body.decode(),
                "serving_requests_total") - sup_own
            per_replica = []
            for r in sup.replicas:
                try:
                    text = open(r.metrics_path).read()
                except OSError:
                    text = ""
                per_replica.append(telemetry.sum_family(
                    text, "serving_requests_total"))
            if merged_total >= expected_total:
                break
            time.sleep(0.1)
        # THE acceptance equality: merged == sum over replicas == load
        assert merged_total == expected_total
        assert sum(per_replica) == expected_total
        # the proxy spread load over BOTH replicas (round-robin), so a
        # one-replica scrape would undercount — the gap being fixed
        assert all(v > 0 for v in per_replica)
        # gauges export per replica, not summed
        merged_text = merged_body.decode()
        assert 'extractor_pool_size{replica="0"}' in merged_text
        assert 'extractor_pool_size{replica="1"}' in merged_text
        # public (proxy) port serves the SAME merged view
        _, pub_body = _get("127.0.0.1", sup.port, "/metrics")
        assert telemetry.sum_family(
            pub_body.decode(), "serving_requests_total") >= n_requests

        # /fleet: the ROADMAP fleet item's signal set
        _, fleet_body = _get("127.0.0.1", tport, "/fleet")
        fleet = json.loads(fleet_body)
        assert fleet["mode"] == "proxy"
        assert fleet["replica_count"] == 2 and not fleet["escalated"]
        assert len(fleet["replicas"]) == 2
        fingerprints = set()
        for r in fleet["replicas"]:
            assert r["alive"] and r["restarts"] == 0
            assert r["status"] == "serving"
            assert r["heartbeat_age_s"] < fleet["stale_after_s"]
            assert r["breakers"] == {"extractor": "closed",
                                     "device": "closed"}
            assert r["shed_rate"] == 0.0
            assert r["requests_total"] > 0
            fingerprints.add(r["model_fingerprint"])
        assert len(fingerprints) == 2  # per-pid fake fingerprints
        assert sum(r["requests_total"]
                   for r in fleet["replicas"]) == expected_total
        # /fleet on the public proxy port too
        _, pub_fleet = _get("127.0.0.1", sup.port, "/fleet")
        assert json.loads(pub_fleet)["replica_count"] == 2
    finally:
        sup._stop.set()
        thread.join(timeout=40)
    assert rc_holder.get("rc") == 0
