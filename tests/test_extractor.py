"""Tests for the native C++ Java path-context extractor (cpp/).

Golden behavior is pinned against the reference extractor's documented
semantics (FeatureExtractor.java:120-191 path grammar,
Property.java:26-77 node naming, Common.java:36-76 normalization,
ProgramRelation.java:18 Java-hashCode path hashing).
"""

import os
import re
import subprocess

import pytest

from code2vec_tpu.common import java_string_hashcode

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BINARY = os.path.join(REPO_ROOT, "cpp", "build", "c2v-extract")

FACTORIAL = """\
int f(int n) {
    if (n == 0) {
        return 1;
    } else {
        return n * f(n-1);
    }
}
"""


@pytest.fixture(scope="module")
def extractor():
    if not os.path.exists(BINARY):
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr
    def run(path, *extra):
        cmd = [BINARY, "--max_path_length", "8", "--max_path_width", "2",
               "--file", path, *extra]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
        return proc.stdout.splitlines()
    return run


@pytest.fixture()
def java_file(tmp_path):
    def write(code, name="Input.java"):
        p = tmp_path / name
        p.write_text(code)
        return str(p)
    return write


def test_factorial_golden(extractor, java_file):
    """The snippet from the reference's shipped Input.java: bare method,
    wrapped by the parse retries (FeatureExtractor.java:51-75)."""
    lines = extractor(java_file(FACTORIAL), "--no_hash")
    assert len(lines) == 1
    parts = lines[0].split(" ")
    assert parts[0] == "f"
    contexts = [c.split(",") for c in parts[1:]]
    # every context is a (token, path, token) triple
    assert all(len(c) == 3 for c in contexts)
    # the method-name leaf is masked (Property.java:66-68)
    assert any(c[0] == "METHOD_NAME" or c[2] == "METHOD_NAME"
               for c in contexts)
    # known context: return type leaf <-> masked name leaf with the
    # alpha.4 MethodDeclaration child ids (type=0, nameExpr=1)
    assert ["int", "(PrimitiveType0)^(MethodDeclaration)_(NameExpr1)",
            "METHOD_NAME"] in contexts
    # recursion: n-1 argument context with operator-suffixed type
    assert ["n", "(NameExpr0)^(BinaryExpr:minus1)_(IntegerLiteralExpr1)",
            "1"] in contexts
    # path length cap: no path has more than 8 up/down hops + 1 node
    for _, path, _ in contexts:
        assert len(re.findall(r"[\^_]", path)) <= 8


def test_hashed_mode_matches_java_hashcode(extractor, java_file):
    plain = extractor(java_file(FACTORIAL), "--no_hash")
    hashed = extractor(java_file(FACTORIAL))
    for raw, enc in zip(plain[0].split(" ")[1:], hashed[0].split(" ")[1:]):
        w1, path, w2 = raw.split(",")
        h1, phash, h2 = enc.split(",")
        assert (w1, w2) == (h1, h2)
        assert str(java_string_hashcode(path)) == phash


def test_label_subtokenization(extractor, java_file):
    code = "class A { void setMaxHTTPRetries2Go(int x) { x++; } }"
    lines = extractor(java_file(code), "--no_hash")
    # Common.java:71-76 split: camelCase, acronym boundary, digits removed
    assert lines[0].split(" ")[0] == "set|max|http|retries|go"


def test_method_name_masking_and_tokens_lowercase(extractor, java_file):
    code = """
class A {
  int addItem(String itemName) { return itemName.length() + MAX_SIZE; }
}
"""
    line = extractor(java_file(code), "--no_hash")[0]
    tokens = set()
    for ctx in line.split(" ")[1:]:
        w1, _, w2 = ctx.split(",")
        tokens.add(w1)
        tokens.add(w2)
    # normalizeName lowercases and strips non-alpha (Common.java:36-53)
    assert "itemname" in tokens
    assert "maxsize" in tokens
    assert "METHOD_NAME" in tokens
    assert not any(t != "METHOD_NAME" and t.lower() != t for t in tokens)


def test_boxed_type_rewrite(extractor, java_file):
    """Integer leaf: type becomes PrimitiveType, name the unboxed type
    (Property.java:29-31,62-64)."""
    code = "class A { Integer box(Integer v) { return v; } }"
    line = extractor(java_file(code), "--no_hash")[0]
    assert "(PrimitiveType" in line
    assert "ClassOrInterfaceType" not in line
    tokens = {c.split(",")[i] for c in line.split(" ")[1:] for i in (0, 2)}
    assert "int" in tokens and "integer" not in tokens


def test_numeric_literals_keep_digits(extractor, java_file):
    """Out-of-whitelist ints keep digits in the printed token: the <NUM>
    masking touches only the never-printed SplitName (Property.java:70-76,
    ProgramRelation.java:31-34)."""
    code = "class A { int f() { return 37 + 64; } }"
    line = extractor(java_file(code), "--no_hash")[0]
    tokens = {c.split(",")[i] for c in line.split(" ")[1:] for i in (0, 2)}
    assert "37" in tokens and "64" in tokens


def test_empty_methods_filtered(extractor, java_file):
    """MinCodeLength=1 drops empty bodies (FeatureExtractor.java:79-82)."""
    code = "class A { void empty() {} int real() { return 1; } }"
    lines = extractor(java_file(code), "--no_hash")
    assert [ln.split(" ")[0] for ln in lines] == ["real"]


def test_interface_and_abstract_methods_skipped(extractor, java_file):
    code = """
interface I { int size(); }
abstract class B implements I { abstract void g(); int h() { return 2; } }
"""
    lines = extractor(java_file(code), "--no_hash")
    assert [ln.split(" ")[0] for ln in lines] == ["h"]


def test_nested_and_anonymous_methods(extractor, java_file):
    """Methods of anonymous classes are separate examples AND their
    leaves appear in the enclosing method (FunctionVisitor.java:18-23)."""
    code = """
class A {
  Runnable outer() {
    return new Runnable() {
      public void run() { int innerVar = 5; innerVar++; }
    };
  }
}
"""
    lines = extractor(java_file(code), "--no_hash")
    labels = [ln.split(" ")[0] for ln in lines]
    assert labels == ["outer", "run"]
    # inner leaf participates in outer method's contexts
    assert "innervar" in lines[0]


def test_dir_mode_and_parse_failure_resilience(tmp_path, extractor):
    good = tmp_path / "Good.java"
    good.write_text("class G { int ok() { return 1; } }")
    bad = tmp_path / "Bad.java"
    bad.write_text("class { this is not java ]]]")
    proc = subprocess.run(
        [BINARY, "--max_path_length", "8", "--max_path_width", "2",
         "--dir", str(tmp_path), "--no_hash"],
        capture_output=True, text=True)
    assert proc.returncode == 0
    assert proc.stdout.startswith("ok ")
    assert "Bad.java" in proc.stderr


def test_path_width_prunes_distant_siblings(extractor, java_file):
    """max_path_width limits sibling distance at the common ancestor
    (FeatureExtractor.java:145-151)."""
    code = "class A { int f(int a, int b, int c, int d) { return a; } }"
    wide = subprocess.run(
        [BINARY, "--max_path_length", "8", "--max_path_width", "99",
         "--file", java_file(code), "--no_hash"],
        capture_output=True, text=True).stdout
    narrow = subprocess.run(
        [BINARY, "--max_path_length", "8", "--max_path_width", "1",
         "--file", java_file(code), "--no_hash"],
        capture_output=True, text=True).stdout
    assert len(wide.split(" ")) > len(narrow.split(" "))


def test_extractor_bridge_prefers_native(tmp_path, extractor):
    """serving/extractor_bridge.py drives the native binary end-to-end."""
    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.extractor_bridge import PathExtractor

    src = tmp_path / "Input.java"
    src.write_text(FACTORIAL)
    config = Config(train_data_path_prefix="<t>", max_contexts=200)
    lines, hash_to_path = PathExtractor(config).extract_paths(str(src))
    assert len(lines) == 1
    first = lines[0].rstrip().split(" ")
    assert first[0] == "f"
    # bridge re-hashes readable paths; mapping must invert
    w1, hashed, w2 = first[1].split(",")
    assert hash_to_path[hashed].startswith("(")


# ----------------------------------------------------- modern Java (14+)
# The reference's JavaParser 3.0.0-alpha.4 predates these constructs and
# hard-fails such files; real corpora contain them, so the from-scratch
# parser covers arrow switches, switch expressions with yield, text
# blocks, instanceof patterns — and degrades per-member (skip + warning)
# on anything else instead of losing the file.

def test_modern_java_constructs(extractor, java_file):
    code = """
public class Modern {
    public String gradeOf(int x) {
        return switch (x) {
            case 0, 1 -> "low";
            case 2 -> "mid";
            default -> "high";
        };
    }
    public int viaYield(int x) {
        int base = 2;
        return switch (x) { case 0: yield base; default: yield x * base; };
    }
    public void arrowStmt(int x) {
        switch (x) { case 0 -> System.out.println("z");
                     default -> System.out.println("o"); }
    }
    public int patternBind(Object o) {
        if (o instanceof String s) { return s.length(); }
        return 0;
    }
    public String block() {
        return \"\"\"
            hello
            \"\"\";
    }
}
"""
    lines = extractor(java_file(code))
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["grade|of", "via|yield", "arrow|stmt", "pattern|bind",
                     "block"]
    # the pattern binding variable feeds contexts
    assert any(",s " in ln or " s," in ln for ln in lines) or "s," in lines[3]


def test_records_and_sealed_types(extractor, java_file):
    """Records (Java 16) and sealed types (Java 17) parse whole — the
    reference's JavaParser alpha.4 predates both, so kinds follow modern
    JavaParser (RecordDeclaration, CompactConstructorDeclaration) like
    the other beyond-alpha.4 constructs; `record`/`sealed` stay usable
    as plain identifiers."""
    code = """
public sealed interface Shape permits Circle, Square {
    double area();
}

record Point(int x, int y) implements Comparable<Point> {
    Point {
        if (x < 0) { throw new IllegalArgumentException("x"); }
    }
    public int manhattan() { return Math.abs(x) + Math.abs(y); }
    public int compareTo(Point other) {
        return this.manhattan() - other.manhattan();
    }
}

final class Keeper {
    int record = 3;
    int useRecordAsName(int sealed) { int non = record - sealed; return non; }
}
"""
    lines = extractor(java_file(code))
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["manhattan", "compare|to", "use|record|as|name"]
    # record component identifiers participate in contexts
    assert any(",x " in ln or " x," in ln for ln in lines)


def test_nested_record_in_class(extractor, java_file):
    code = """
public class Outer {
    private record Pair(String key, int value) {
        public String render() { return key + "=" + value; }
    }
    public String show() { return new Pair("a", 1).render(); }
}
"""
    lines = extractor(java_file(code))
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["render", "show"]


def test_local_record_in_method_body(extractor, java_file):
    """A local record (Java 16) must not cost the enclosing method."""
    code = """
public class C {
    public int useLocal() {
        record Local(int x, int y) { int sum() { return x + y; } }
        return new Local(1, 2).sum();
    }
    int keep() { return 1; }
}
"""
    lines = extractor(java_file(code))
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["use|local", "sum", "keep"]


def test_record_inside_annotation_decl(extractor, java_file):
    code = """
@interface Outer {
    record R(int x) { int half() { return x / 2; } }
}
"""
    lines = extractor(java_file(code))
    assert [ln.split(" ", 1)[0] for ln in lines] == ["half"]


def test_yield_with_parenthesized_expression(extractor, java_file):
    """`yield (a + b);` inside a switch body is a YieldStmt (JLS 14.21:
    a statement starting with `yield` is a yield statement there), while
    `yield(x)` outside any switch stays a call to a method named yield —
    the contextual-keyword split JavaParser implements."""
    code = """
public class YieldParen {
    public int parens(int x) {
        int base = 2;
        return switch (x) { case 0: yield (x + base); default: yield base; };
    }
    public int callOutside(int x) { return yield(x); }
    public int yield(int v) { return v; }
}
"""
    import subprocess as sp
    proc = sp.run([BINARY, "--max_path_length", "12", "--max_path_width",
                   "3", "--file", java_file(code), "--no_hash"],
                  capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    lines = proc.stdout.splitlines()
    assert [ln.split(" ", 1)[0] for ln in lines] == \
        ["parens", "call|outside", "yield"]
    assert "(YieldStmt)_(EnclosedExpr)" in lines[0]
    assert "MethodCallExpr" not in lines[0].replace("METHOD_NAME", "")
    assert "(MethodCallExpr0)_(NameExpr0),yield" in lines[1]
    assert "YieldStmt" not in lines[1]


def test_cast_of_switch_expression(extractor, java_file):
    """`(int) switch (k) {...}` — a switch EXPRESSION is a legal cast
    operand (Java 14); TryParseCast's operand-start set must admit the
    `switch` keyword. Found by the round-5 structure-aware Java fuzzer
    (438/8000 generated methods previously lost to skip recovery)."""
    code = """
public class CastSwitch {
    int k;
    int prim() { return (int) switch (k) { case 1 -> 1; default -> 0; }; }
    Object ref() { return (Object) switch (k) { case 1 -> "a"; default -> "b"; }; }
    int keep() { return 1; }
}
"""
    lines = extractor(java_file(code), "--no_hash")
    names = [ln.split(" ", 1)[0] for ln in lines]
    assert names == ["prim", "ref", "keep"]
    assert "CastExpr" in lines[0] and "SwitchExpr" in lines[0]


def test_java_per_member_recovery(java_file, extractor, tmp_path):
    import subprocess as sp
    # the middle method uses a Java 21 type-pattern switch case, which
    # the parser does not cover
    code = """
public class Mixed {
    public int keep(int x) { return x + 1; }
    public int bad(Object o) {
        return switch (o) { case String s -> 1; default -> 0; };
    }
    public int keepToo(int y) { return y * 2; }
}
"""
    p = tmp_path / "Mixed.java"
    p.write_text(code)
    proc = sp.run([BINARY, "--max_path_length", "8", "--max_path_width", "2",
                   "--file", str(p)], capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    names = [ln.split(" ", 1)[0] for ln in proc.stdout.splitlines()]
    assert names == ["keep", "keep|too"]
    assert "warning: skipped unparsable member" in proc.stderr


def test_adversarial_nesting_fails_cleanly(tmp_path):
    """Pathological nesting must produce a clean error (or per-member
    skip), never a stack-overflow SIGSEGV: a crashed worker loses its
    whole extraction batch, a clean failure loses one file or member
    (parser DepthGuard + iterative CheckAstDepth)."""
    import subprocess as sp
    cases = {
        "deep_parens": ("public class C { int keep(int x){return x;} "
                        "int m() { return " + "(" * 20000 + "1"
                        + ")" * 20000 + "; } }"),
        "deep_blocks": ("public class C { void m() { " + "{" * 20000
                        + "}" * 20000 + " } }"),
        "long_chain": ("public class C { int m() { int y = "
                       + "1+" * 100000 + "1; return y; } }"),
        "deep_lambda": ("public class C { Object f = " + "x -> " * 50000
                        + "null; }"),
        "nested_classes": ("public class A {" + " class B {" * 50000
                           + "}" * 50000 + " }"),
        "field_chain": ("public class C { int x = " + "1+" * 100000
                        + "1; int keep(){return 1;} }"),
    }
    for name, src in cases.items():
        p = tmp_path / f"{name}.java"
        p.write_text(src)
        proc = sp.run([BINARY, "--max_path_length", "8",
                       "--max_path_width", "2", "--file", str(p)],
                      capture_output=True, text=True, timeout=60)
        assert proc.returncode >= 0, f"{name}: died on signal {-proc.returncode}"
    # the recoverable cases salvage the good methods: a too-deep member
    # costs itself, not the file
    proc = sp.run([BINARY, "--max_path_length", "8", "--max_path_width", "2",
                   "--file", str(tmp_path / "deep_parens.java")],
                  capture_output=True, text=True, timeout=60)
    assert "keep" in proc.stdout
    mixed = ("public class C { int keep(int x){return x;} int m() { int y = "
             + "1+" * 100000 + "1; return y; } int keepToo(int z){return z;} }")
    p = tmp_path / "mixed.java"
    p.write_text(mixed)
    proc = sp.run([BINARY, "--max_path_length", "8", "--max_path_width", "2",
                   "--file", str(p)], capture_output=True, text=True,
                  timeout=60)
    names = [ln.split(" ", 1)[0] for ln in proc.stdout.splitlines()]
    # the deep method's SHALLOW part still extracts (subtree truncated at
    # the depth cap), and the good methods are untouched
    assert names == ["keep", "m", "keep|too"], names
    assert "truncated" in proc.stderr
    # a deep FIELD initializer must not cost the file's methods either
    proc = sp.run([BINARY, "--max_path_length", "8", "--max_path_width", "2",
                   "--file", str(tmp_path / "field_chain.java")],
                  capture_output=True, text=True, timeout=60)
    assert "keep" in proc.stdout
