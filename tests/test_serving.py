"""Serving subsystem tests: warm extractor pool, dynamic batcher,
prediction cache, HTTP server, REPL rewire.

A FAKE extractor binary (a small Python script speaking both the
one-shot `--file` CLI and the warm `--server` protocol, installed via
the C2V_NATIVE_EXTRACTOR env hook) stands in for the real parser, so
these tests pin the SERVING machinery — pooling, requeue-on-crash,
coalescing, bucketed compilation, cache byte-equality, SIGTERM drain —
independent of the cpp build. Behaviors are driven by markers in the
"Java" source: NCTX<n> (emit n contexts), SLOW_MARKER (sleep),
CRASH_ONCE (die with SIGKILL-ish 137 exactly once per stamp file),
BOOM_ALWAYS (deterministic parse rejection).
"""

import json
import os
import pickle
import re
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from code2vec_tpu import obs
from code2vec_tpu.config import Config

pytestmark = pytest.mark.serving

FAKE_EXTRACTOR = r'''#!/usr/bin/env python3
"""Fake c2v extractor: deterministic output derived from the source."""
import os, re, sys, time


def extract(src):
    if "SLOW_MARKER" in src:
        time.sleep(float(os.environ.get("C2V_FAKE_SLEEP", "1.0")))
    if "CRASH_ALWAYS" in src:
        os._exit(137)
    if "CRASH_ONCE" in src:
        stamp = os.environ.get("C2V_FAKE_STAMP", "")
        if stamp and not os.path.exists(stamp):
            open(stamp, "w").close()
            os._exit(137)  # looks like an OOM SIGKILL exit
    if "BOOM_ALWAYS" in src:
        raise ValueError("fake deterministic parse error")
    m = re.search(r"NCTX(\d+)", src)
    nctx = int(m.group(1)) if m else 3
    names = re.findall(r"(\w+)\s*\(", src) or ["m"]
    lines = []
    for name in names:
        ctxs = " ".join("tok%d,(P%d)^(Q)_(R%d),tok%d" % (i, i, i, i)
                        for i in range(nctx))
        lines.append("%s %s" % (name, ctxs))
    return lines


def main():
    argv = sys.argv[1:]
    if os.environ.get("C2V_FAKE_NO_SERVER") and "--server" in argv:
        sys.stderr.write("unknown flag: --server\n")
        sys.exit(2)
    if "--server" not in argv:
        path = argv[argv.index("--file") + 1]
        try:
            with open(path) as f:
                lines = extract(f.read())
        except ValueError as e:
            sys.stderr.write(str(e) + "\n")
            sys.exit(1)
        sys.stdout.write("".join(l + "\n" for l in lines))
        return
    out = sys.stdout
    out.write("READY\n")
    out.flush()
    stdin = sys.stdin.buffer
    while True:
        header = stdin.readline()
        if not header:
            return
        header = header.decode().strip()
        try:
            if header.startswith("FILE "):
                with open(header[5:]) as f:
                    src = f.read()
            elif header.startswith("SRC "):
                n = int(header[4:])
                src = stdin.read(n).decode()
                stdin.readline()  # frame terminator
            elif not header:
                continue
            else:
                raise ValueError("bad request: " + header)
            lines = extract(src)
        except ValueError as e:
            out.write("ERR %s\n" % e)
            out.flush()
            continue
        out.write("OK %d\n" % len(lines))
        for l in lines:
            out.write(l + "\n")
        out.flush()


if __name__ == "__main__":
    main()
'''


@pytest.fixture()
def fake_extractor(tmp_path, monkeypatch):
    path = tmp_path / "fake-c2v-extract"
    path.write_text(FAKE_EXTRACTOR)
    path.chmod(0o755)
    monkeypatch.setenv("C2V_NATIVE_EXTRACTOR", str(path))
    monkeypatch.delenv("C2V_FAKE_NO_SERVER", raising=False)
    return str(path)


def _serving_config(tmp_path, **overrides) -> Config:
    kwargs = dict(
        train_data_path_prefix=str(tmp_path / "synthetic"),
        max_contexts=16,
        train_batch_size=8, test_batch_size=8,
        num_train_epochs=1,
        compute_dtype="float32",
        verbose_mode=0,
        serve_batch_size=4,
        serve_buckets="4,8",
        serve_max_delay_ms=5.0,
        serve_cache_entries=16,
        extractor_pool_size=1,
        num_batches_to_log_progress=1000,
        shuffle_buffer_size=64,
        save_every_epochs=1000,
    )
    kwargs.update(overrides)
    return Config(**kwargs)


def _write_synthetic_dataset(tmp_path, n_rows=32, max_contexts=16):
    import random
    rng = random.Random(0)
    tokens = [f"tok{i}" for i in range(6)]
    paths = [f"p{i}" for i in range(4)]
    targets = ["name|alpha", "name|beta"]
    rows = []
    for _ in range(n_rows):
        t = rng.randrange(len(targets))
        ctxs = [f"{tokens[t]},{rng.choice(paths)},{tokens[t]}"
                for _ in range(rng.randint(2, 6))]
        rows.append(f"{targets[t]} " + " ".join(ctxs)
                    + " " * (max_contexts - len(ctxs)))
    prefix = str(tmp_path / "synthetic")
    with open(prefix + ".train.c2v", "w") as f:
        f.write("\n".join(rows) + "\n")
    with open(prefix + ".dict.c2v", "wb") as f:
        pickle.dump({w: 10 for w in tokens}, f)
        pickle.dump({p: 10 for p in paths}, f)
        pickle.dump({t: 10 for t in targets}, f)
        pickle.dump(n_rows, f)
    return prefix


@pytest.fixture(scope="module")
def served_model(tmp_path_factory):
    """One untrained tiny model shared by the module: serving tests pin
    machinery (batching, caching, drain), not model quality."""
    from code2vec_tpu.model_facade import Code2VecModel
    tmp_path = tmp_path_factory.mktemp("serving-model")
    _write_synthetic_dataset(tmp_path)
    return Code2VecModel(_serving_config(tmp_path))


def _counter_value(name, **labels):
    fams = obs.default_registry().collect()
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    child = fams.get(name, {}).get(key)
    return child.value if child is not None else 0.0


# ------------------------------------------------------------- pool


def test_pool_warm_extract_and_postprocess(fake_extractor, tmp_path):
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    config = _serving_config(tmp_path)
    with ExtractorPool(config, size=2) as pool:
        assert pool.warm, "fake extractor advertises --server"
        phases = {}
        lines, h2s = pool.extract_source(
            "class A { int f(int n) { return n; } } NCTX2", phases=phases)
        assert len(lines) == 1
        parts = lines[0].rstrip().split(" ")
        assert parts[0] == "f"
        # bridge semantics preserved: paths re-hashed, mapping inverts,
        # line padded to max_contexts
        w1, hashed, w2 = parts[1].split(",")
        assert h2s[hashed] == "(P0)^(Q)_(R0)"
        assert len(lines[0]) - len(lines[0].rstrip()) == 16 - 2
        assert phases["queue_wait"] >= 0 and phases["extract"] > 0
        # same worker serves a second request (no respawn)
        java_file = tmp_path / "Second.java"
        java_file.write_text("class B { int g() { return 2; } }")
        pid_before = {w.proc.pid for w in pool._idle}
        lines2, _ = pool.extract_file(str(java_file))
        assert lines2[0].split(" ")[0] == "g"
        assert {w.proc.pid for w in pool._idle} == pid_before


def test_pool_cold_fallback_when_no_server_mode(fake_extractor, tmp_path,
                                                monkeypatch):
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    monkeypatch.setenv("C2V_FAKE_NO_SERVER", "1")
    config = _serving_config(tmp_path)
    with ExtractorPool(config, size=1) as pool:
        assert not pool.warm
        lines, _ = pool.extract_source("class A { int g() { return 1; } }")
        assert lines[0].split(" ")[0] == "g"


def test_pool_requeues_crashed_worker_without_double_count(
        fake_extractor, tmp_path, monkeypatch):
    """A worker killed mid-request (exit 137 = OOM-style) requeues the
    request onto a fresh worker; extractor_failures_total counts the
    failed attempt EXACTLY once, labeled retried=yes."""
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    stamp = tmp_path / "crash-stamp"
    monkeypatch.setenv("C2V_FAKE_STAMP", str(stamp))
    config = _serving_config(tmp_path)
    before_yes = _counter_value("extractor_failures_total", retried="yes")
    before_no = _counter_value("extractor_failures_total", retried="no")
    before_rq = _counter_value("extractor_pool_requeues_total")
    with ExtractorPool(config, size=1) as pool:
        lines, _ = pool.extract_source(
            "class A { int h() { return 1; } } CRASH_ONCE")
        assert lines[0].split(" ")[0] == "h"
        assert stamp.exists()
        # the pool still has one LIVE worker after the replacement
        assert len(pool._idle) == 1 and pool._idle[0].alive
    assert _counter_value("extractor_failures_total",
                          retried="yes") == before_yes + 1
    assert _counter_value("extractor_failures_total",
                          retried="no") == before_no
    assert _counter_value("extractor_pool_requeues_total") == before_rq + 1


def test_pool_crash_exhausts_retries(fake_extractor, tmp_path,
                                     monkeypatch):
    from code2vec_tpu.serving.extractor_bridge import ExtractorCrash
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    config = _serving_config(tmp_path, extractor_retries=1)
    before_no = _counter_value("extractor_failures_total", retried="no")
    with ExtractorPool(config, size=1) as pool:
        with pytest.raises(ExtractorCrash):
            pool.extract_source("class A { int h() { return 1; } } "
                                "CRASH_ALWAYS")
    # final attempt counted retried=no (surfaced to the caller)
    assert _counter_value("extractor_failures_total",
                          retried="no") == before_no + 1


def test_pool_deterministic_rejection_not_retried(fake_extractor,
                                                  tmp_path):
    from code2vec_tpu.serving.extractor_pool import ExtractorPool
    config = _serving_config(tmp_path)
    before_rq = _counter_value("extractor_pool_requeues_total")
    with ExtractorPool(config, size=1) as pool:
        with pytest.raises(ValueError, match="deterministic parse error"):
            pool.extract_source("BOOM_ALWAYS")
        # rejection must not kill the warm worker
        assert pool._idle[0].alive
    assert _counter_value("extractor_pool_requeues_total") == before_rq


# ---------------------------------------------------------- batcher


def test_batcher_coalesces_concurrent_requests():
    from code2vec_tpu.serving.batcher import DynamicBatcher
    calls = []

    def predict_fn(lines):
        calls.append(list(lines))
        return [f"r:{l}" for l in lines]

    batcher = DynamicBatcher(predict_fn, max_batch_rows=4,
                             max_delay_s=2.0)
    futures = []

    def submit(i):
        futures.append(batcher.submit([f"line{i}"]))

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    results = [f.result(timeout=10) for f in futures]
    assert sorted(r[0] for r in results) == [f"r:line{i}"
                                             for i in range(4)]
    # 4 rows hit max_batch_rows -> ONE device batch, not four
    assert batcher.batches_dispatched == 1
    assert sorted(len(c) for c in calls) == [4]
    batcher.drain()


def test_batcher_flushes_on_delay_and_preserves_order():
    from code2vec_tpu.serving.batcher import DynamicBatcher
    batcher = DynamicBatcher(lambda lines: [l.upper() for l in lines],
                             max_batch_rows=100, max_delay_s=0.02)
    f = batcher.submit(["a", "b", "c"])
    assert f.result(timeout=10) == ["A", "B", "C"]
    batcher.drain()


def test_batcher_error_propagates_and_drain_refuses():
    from code2vec_tpu.serving.batcher import DynamicBatcher

    def boom(lines):
        raise RuntimeError("device on fire")

    batcher = DynamicBatcher(boom, max_batch_rows=2, max_delay_s=0.01)
    f = batcher.submit(["x"])
    with pytest.raises(RuntimeError, match="device on fire"):
        f.result(timeout=10)
    batcher.drain()
    f2 = batcher.submit(["y"])
    with pytest.raises(RuntimeError, match="draining"):
        f2.result(timeout=10)


def test_device_time_tracker_caches_sorted_view():
    """p95 runs per admission, samples land per batch: the sorted view
    must be cached between records (O(1) no-new-sample path) and
    invalidated by record()."""
    from code2vec_tpu.serving.batcher import _DeviceTimeTracker
    tr = _DeviceTimeTracker()
    for v in (0.4, 0.1, 0.3, 0.2):
        tr.record(7, v)
    assert tr.p95(7) == 0.4
    cached = tr._sorted[7]
    assert tr.p95(7) == 0.4
    assert tr._sorted[7] is cached, "no-new-sample path re-sorted"
    tr.record(7, 0.05)
    assert 7 not in tr._sorted, "record() must invalidate the view"
    assert tr.p95(7) == 0.4
    assert tr._sorted[7] is not cached


def test_batch_span_attrs_shared_and_thread_count_stable():
    """The dispatch thread builds ONE batch-span attrs dict per batch —
    every member trace holds the same object by reference, not a
    per-member dict construction; and the classic batcher runs exactly
    one dispatcher thread."""
    from code2vec_tpu.obs.reqtrace import RequestTrace
    from code2vec_tpu.serving.batcher import DynamicBatcher
    before = threading.active_count()
    batcher = DynamicBatcher(lambda lines: [l for l in lines],
                             max_batch_rows=3, max_delay_s=2.0)
    assert threading.active_count() == before + 1
    traces = [RequestTrace() for _ in range(3)]
    futures = []

    def submit(i):
        futures.append(batcher.submit([f"line{i}"], trace=traces[i]))

    threads = [threading.Thread(target=submit, args=(i,))
               for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for f in list(futures):
        f.result(timeout=10)
    assert batcher.batches_dispatched == 1
    batch_attrs = [attrs for tr in traces
                   for (name, _, _, _, _, attrs) in tr._spans
                   if name == "batch"]
    assert len(batch_attrs) == 3
    assert batch_attrs[0] is batch_attrs[1] is batch_attrs[2], \
        "batch-span attrs must be one shared dict per batch"
    assert batch_attrs[0]["requests"] == 3
    batcher.drain()


# ------------------------------------------------ continuous batcher


def test_continuous_row_rides_step_n_plus_1():
    """A row admitted while step N is on device rides step N+1 the
    moment the worker frees — never a fresh max_delay_s window, never
    step N+2 when a slot is free."""
    from code2vec_tpu.serving.batcher import ContinuousBatcher
    calls = []

    def predict(lines):
        calls.append(list(lines))
        time.sleep(0.25)
        return [l.upper() for l in lines]

    batcher = ContinuousBatcher(predict, max_batch_rows=4,
                                max_delay_s=2.0, inflight_steps=1)
    # four rows fill the slot -> step N dispatches immediately
    f1 = batcher.submit(["a1", "a2", "a3", "a4"])
    time.sleep(0.1)                      # step N is on device now
    t0 = time.perf_counter()
    f2 = batcher.submit(["b"])           # admitted mid-step-N
    assert f1.result(timeout=10) == ["A1", "A2", "A3", "A4"]
    assert f2.result(timeout=10) == ["B"]
    waited = time.perf_counter() - t0
    # rode step N+1 (~0.15s left of N + 0.25s of N+1) instead of
    # opening a fresh 2s delay window or waiting for step N+2
    assert waited < 1.0, waited
    assert batcher.batches_dispatched == 2
    assert calls == [["a1", "a2", "a3", "a4"], ["b"]]
    assert batcher.rides == 1
    batcher.drain()


def test_continuous_refusal_against_inflight_eta():
    """Deadline-infeasible refusal is re-expressed against the
    in-flight step's ETA: a budget that covers the bucket p95 alone but
    NOT eta + p95 is refused while a step occupies the only worker, and
    admitted once the worker is free."""
    from code2vec_tpu.serving.admission import (
        Deadline, DeadlineInfeasible,
    )
    from code2vec_tpu.serving.batcher import ContinuousBatcher
    release = threading.Event()

    def predict(lines):
        release.wait(10)
        return list(lines)

    batcher = ContinuousBatcher(predict, max_batch_rows=1,
                                max_delay_s=0.0, inflight_steps=1)
    for _ in range(4):
        batcher.device_times.record(None, 0.5)   # p95 = 0.5s
    f1 = batcher.submit(["x"])                   # occupies the worker
    deadline_waited = time.perf_counter() + 2.0
    while batcher._inflight == 0:
        assert time.perf_counter() < deadline_waited
        time.sleep(0.005)
    # 0.8s budget > p95 0.5s (the classic check would admit), but the
    # in-flight step needs ~0.5s more before a worker frees: refused.
    f2 = batcher.submit(["y"], deadline=Deadline(0.8))
    with pytest.raises(DeadlineInfeasible):
        f2.result(timeout=5)
    release.set()
    f1.result(timeout=10)
    while batcher._inflight:
        time.sleep(0.005)
    # worker free -> eta 0 -> the same budget is feasible again
    f3 = batcher.submit(["z"], deadline=Deadline(0.8))
    assert f3.result(timeout=10) == ["z"]
    batcher.drain()


def test_continuous_drain_flushes_partial_slot():
    from code2vec_tpu.serving.batcher import ContinuousBatcher
    batcher = ContinuousBatcher(lambda lines: [l * 2 for l in lines],
                                max_batch_rows=100, max_delay_s=30.0)
    f = batcher.submit(["q"])
    batcher.drain(timeout=10)
    assert f.result(timeout=1) == ["qq"]
    f2 = batcher.submit(["z"])
    with pytest.raises(RuntimeError, match="draining"):
        f2.result(timeout=5)


def test_continuous_serial_client_byte_identical(served_model,
                                                 fake_extractor,
                                                 tmp_path):
    """For a serial client (no concurrency, so continuous batching has
    nothing to chain) the zero-copy slot path must answer byte-for-byte
    what collect-then-dispatch answers."""
    import dataclasses
    from code2vec_tpu.serving.server import PredictionServer
    codes = [
        "class A { int f(int n) { return n; } } NCTX2",
        "class B { int g() { return 2; } int h() { return 3; } NCTX5 }",
        "class C { void noop() { } } NCTX1",
    ]
    classic = PredictionServer(served_model, served_model.config,
                               log=lambda m: None)
    continuous = PredictionServer(
        served_model,
        dataclasses.replace(served_model.config, serve_continuous=True,
                            serve_inflight_steps=2),
        log=lambda m: None)
    try:
        from code2vec_tpu.serving.batcher import ContinuousBatcher
        assert isinstance(continuous.batcher, ContinuousBatcher)
        assert not isinstance(classic.batcher, ContinuousBatcher)
        for endpoint in ("predict", "embed"):
            for code in codes:
                s1, b1, _ = classic.handle_request(endpoint, code)
                s2, b2, _ = continuous.handle_request(endpoint, code)
                assert (s1, s2) == (200, 200)
                assert b1 == b2, (endpoint, code)
        # the continuous arm really took the zero-copy rows path: its
        # batches dispatched without a single lines-mode fallback
        assert continuous.batcher.batches_dispatched >= len(codes)
    finally:
        classic.drain(timeout=10)
        continuous.drain(timeout=10)


def test_continuous_stale_parse_falls_back_to_lines_path():
    """A slot whose rows were parsed under a fingerprint that is no
    longer live (the model hot-swapped between parse and dispatch) must
    be re-dispatched through predict_lines under the CURRENT model —
    results settle normally, every response from one batch carries one
    fingerprint, no error surfaces to the caller."""
    from code2vec_tpu.serving.batcher import ContinuousBatcher, StaleParse

    calls = {"rows": 0, "lines": 0}

    class _Buf:
        def __init__(self, rows):
            self.context_valid_mask = np.zeros((rows, 4), np.float32)
            self.example_valid = np.zeros((rows,), bool)

    class _Backend:
        def supports_rows(self):
            return True

        def alloc(self, rows):
            return _Buf(rows)

        def parse_into(self, lines, buffer, row_offset):
            return "fpOLD"

        def predict_rows(self, buffer, n_rows, fingerprint):
            calls["rows"] += 1
            raise StaleParse("model swapped after parse")

        def predict_lines(self, lines):
            calls["lines"] += 1
            return [f"fpNEW:{ln}" for ln in lines]

    b = ContinuousBatcher(max_batch_rows=4, max_delay_s=0.005,
                          backend=_Backend(), inflight_steps=1)
    try:
        futs = [b.submit([f"l{i}"]) for i in range(2)]
        out = [f.result(timeout=5) for f in futs]
    finally:
        b.drain(timeout=5)
    assert calls["rows"] >= 1, "rows path never attempted"
    assert calls["lines"] >= 1, "StaleParse did not fall back to lines"
    assert out == [["fpNEW:l0"], ["fpNEW:l1"]]


def test_parse_buckets_and_bucket_for():
    from code2vec_tpu.serving.batcher import bucket_for, parse_buckets
    assert parse_buckets("32,64,128", 200) == (32, 64, 128, 200)
    # >= max_contexts dropped, max always appended, duplicates collapse
    assert parse_buckets("8,8,300", 200) == (8, 200)
    assert parse_buckets("", 200) == (200,)
    # cp filtering: buckets must stay divisible by the ctx-parallel degree
    assert parse_buckets("30,32,64", 200, cp=4) == (32, 64, 200)
    buckets = (32, 64, 200)
    assert bucket_for(1, buckets) == 32
    assert bucket_for(32, buckets) == 32
    assert bucket_for(33, buckets) == 64
    assert bucket_for(200, buckets) == 200


# ------------------------------------------- facade bucketed predict


def test_predict_bucket_bound_compilation_count(served_model):
    """Distinct request shapes map onto the configured bucket list: the
    compiled-step cache stays <= number of buckets no matter how many
    context counts traffic brings."""
    model = served_model
    buckets = model.context_buckets
    assert buckets == (4, 8, 16)
    start = model.predict_compile_count()

    def line(nctx):
        ctxs = " ".join(f"tok0,p0,tok0" for _ in range(nctx))
        return "somename " + ctxs + " " * (16 - nctx)

    for nctx in (1, 2, 3, 4, 5, 7, 9, 12, 16, 2, 6, 11):
        model.predict([line(nctx)], batch_size=4)
    assert model.predict_compile_count() - start <= len(buckets)
    # and the shapes actually bucketed (not one giant shape): a 2-context
    # request must NOT have compiled the 16-context shape alone
    assert (4, 4) in model._predict_steps


def test_predict_accepts_lazy_iterable(served_model):
    model = served_model
    lines = ["somename tok0,p0,tok0 tok1,p1,tok1" + " " * 14
             for _ in range(10)]
    consumed = []

    def gen():
        for l in lines:
            consumed.append(l)
            yield l

    out = model.predict(gen(), batch_size=4)
    assert len(out) == 10
    assert len(consumed) == 10
    # chunked (3 batches of <=4) results identical to one-shot list
    out2 = model.predict(lines, batch_size=16)
    for a, b in zip(out, out2):
        assert a.topk_predicted_words == b.topk_predicted_words
        np.testing.assert_allclose(a.topk_predicted_words_scores,
                                   b.topk_predicted_words_scores,
                                   rtol=1e-5)
        assert a.attention_per_context.keys() == \
            b.attention_per_context.keys()


# ------------------------------------------------------------- http


@pytest.fixture()
def server(served_model, fake_extractor):
    from code2vec_tpu.serving.server import PredictionServer
    srv = PredictionServer(served_model, served_model.config,
                           log=lambda m: None)
    srv.start(port=0)
    yield srv
    srv.drain(timeout=10)


def _post(port, endpoint, body, ctype="text/plain"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}", data=body.encode(),
        method="POST", headers={"Content-Type": ctype})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_http_end_to_end(server):
    code = "class A { int addOne(int n) { return n + 1; } }"
    status, body = _post(server.port, "predict", code)
    assert status == 200
    payload = json.loads(body)
    assert payload["model"] == "code2vec_tpu"
    [method] = payload["methods"]
    assert method["original_name"] == "addOne"
    assert method["predictions"], "top-k predictions missing"
    for p in method["predictions"]:
        assert 0.0 <= p["probability"] <= 1.0
    assert method["attention_paths"]
    for att in method["attention_paths"]:
        assert att["path"].startswith("(")  # hash inverted for display

    # JSON body form + /embed (vectors forced on)
    status, body = _post(server.port, "embed",
                         json.dumps({"code": code}), "application/json")
    assert status == 200
    embed_payload = json.loads(body)
    vectors = embed_payload["vectors"]
    assert len(vectors) == 1
    assert len(vectors[0]) == server.config.code_vector_size
    # the embedding-space identity rides every /embed response (the
    # same field /neighbors stamps) so clients can detect cross-model
    # vector mixing
    assert embed_payload["embedding_fingerprint"] == \
        server.model_fingerprint
    assert embed_payload["embedding_fingerprint"] == \
        embed_payload["model_fingerprint"]

    # healthz + metrics ride the same listener
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/healthz", timeout=30) as r:
        hz = json.loads(r.read())
    assert hz["status"] == "serving"
    assert hz["compiled_predict_steps"] <= len(hz["buckets"])
    with urllib.request.urlopen(
            f"http://127.0.0.1:{server.port}/metrics", timeout=30) as r:
        metrics = r.read().decode()
    assert "serving_request_seconds_bucket" in metrics
    assert 'phase="total"' in metrics

    # error surface: empty body, parse rejection, unknown endpoint,
    # and crash-through-every-retry = infra 503 (NOT a client 422:
    # ExtractorCrash subclasses ValueError, the mapping must not lump
    # dead workers in with rejected sources)
    assert _post(server.port, "predict", "")[0] == 400
    assert _post(server.port, "predict", "BOOM_ALWAYS")[0] == 422
    assert _post(server.port, "nope", "x")[0] == 404
    assert _post(server.port, "predict", "CRASH_ALWAYS f(")[0] == 503


def test_http_coalesces_concurrent_requests(server):
    before = server.batcher.batches_dispatched
    codes = [f"class A{i} {{ int f{i}(int n) {{ return n; }} }}"
             for i in range(4)]
    results = [None] * 4

    def post(i):
        results[i] = _post(server.port, "predict", codes[i])

    threads = [threading.Thread(target=post, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r[0] == 200 for r in results)
    for i, (_, body) in enumerate(results):
        assert json.loads(body)["methods"][0]["original_name"] == f"f{i}"
    # 4 single-method requests, serve_batch_size=4, 5ms delay window:
    # strictly fewer device batches than requests proves coalescing
    assert server.batcher.batches_dispatched - before < 4


def test_cache_hit_is_byte_equal_and_normalized(server):
    code = "class B { int mul(int a, int b) { return a * b; } }"
    hits0 = _counter_value("serving_cache_hits_total")
    status, body1 = _post(server.port, "predict", code)
    assert status == 200
    # same method, different formatting -> same cache entry, byte-equal
    reformatted = code.replace("{ ", "{\n    ").replace("; ", ";\n")
    status, body2 = _post(server.port, "predict", reformatted)
    assert status == 200
    assert body2 == body1
    assert _counter_value("serving_cache_hits_total") == hits0 + 1
    # a real edit (here: one that changes the extracted contexts) misses
    # the cache and re-predicts
    misses0 = _counter_value("serving_cache_misses_total")
    status, body3 = _post(server.port, "predict",
                          code.replace("a * b", "a + b") + " NCTX5")
    assert body3 != body1
    assert _counter_value("serving_cache_misses_total") == misses0 + 1


def test_cache_lru_eviction():
    from code2vec_tpu.serving.cache import PredictionCache, cache_key
    ev0 = _counter_value("serving_cache_evictions_total")
    cache = PredictionCache(capacity=2)
    k = [cache_key(f"code{i}") for i in range(3)]
    cache.put(k[0], b"0")
    cache.put(k[1], b"1")
    assert cache.get(k[0]) == b"0"  # touch: k[1] is now LRU
    cache.put(k[2], b"2")
    assert cache.get(k[1]) is None
    assert cache.get(k[0]) == b"0" and cache.get(k[2]) == b"2"
    assert _counter_value("serving_cache_evictions_total") == ev0 + 1
    # capacity 0 disables cleanly
    off = PredictionCache(capacity=0)
    off.put(k[0], b"x")
    assert off.get(k[0]) is None


def test_sigterm_drain_finishes_inflight(served_model, fake_extractor,
                                         monkeypatch):
    """The preemption-grace pattern: a drain racing an in-flight request
    lets it finish (200), refuses everything after, and tears the
    listener down."""
    from code2vec_tpu.serving.server import PredictionServer
    monkeypatch.setenv("C2V_FAKE_SLEEP", "1.0")
    srv = PredictionServer(served_model, served_model.config,
                           log=lambda m: None)
    srv.start(port=0)
    slow_result = {}

    def slow_post():
        slow_result["r"] = _post(
            srv.port, "predict",
            "class S { int slow() { return 1; } } SLOW_MARKER")

    t = threading.Thread(target=slow_post)
    t.start()
    # let the request enter the extractor before draining
    deadline = time.time() + 5
    while srv._inflight == 0 and time.time() < deadline:
        time.sleep(0.01)
    assert srv._inflight == 1
    assert srv.drain(timeout=30) is True
    t.join(timeout=30)
    status, body = slow_result["r"]
    assert status == 200
    assert json.loads(body)["methods"][0]["original_name"] == "slow"
    # the listener is gone: a new request cannot even connect
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/healthz",
                               timeout=5)


# ---------------------------------------------------- request tracing


def _post_full(port, endpoint, body, ctype="text/plain", headers=None,
               query=""):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}{query}", data=body.encode(),
        method="POST", headers=dict({"Content-Type": ctype},
                                    **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.fixture()
def traced_server(served_model, fake_extractor):
    """A server with --serve_debug_trace on (the ?debug=trace gate)."""
    import dataclasses
    from code2vec_tpu.serving.server import PredictionServer
    config = dataclasses.replace(served_model.config,
                                 serve_debug_trace=True)
    srv = PredictionServer(served_model, config, log=lambda m: None)
    srv.start(port=0)
    yield srv
    srv.drain(timeout=10)


def test_trace_id_minted_and_debug_tree_names_every_phase(traced_server):
    """Acceptance pin: a request through the real HTTP server returns an
    X-Trace-Id whose span tree (via the debug-trace knob) names every
    pipeline phase it crossed, including the batch it rode."""
    status, body, headers = _post_full(
        traced_server.port, "predict",
        "class T { int traced(int n) { return n; } }",
        query="?debug=trace")
    assert status == 200
    trace_id = headers["X-Trace-Id"]
    assert len(trace_id) == 32 and int(trace_id, 16)
    payload = json.loads(body)
    trace = payload["trace"]
    assert trace["trace_id"] == trace_id
    by_name = {}
    for s in trace["spans"]:
        by_name.setdefault(s["name"], s)
    # every pipeline phase the request crossed, as a tree
    assert {"request", "cache_lookup", "admission", "extract_wait",
            "extract", "batch_wait", "batch", "device",
            "render"} <= set(by_name)
    root = by_name["request"]
    assert root["span_id"] == trace["root_span_id"]
    assert root["attrs"] == {"endpoint": "predict", "status": 200}
    for child in ("cache_lookup", "admission", "extract_wait",
                  "extract", "batch_wait", "batch", "render"):
        assert by_name[child]["parent_id"] == root["span_id"], child
    # the device span hangs under the SHARED batch span
    batch = by_name["batch"]
    assert by_name["device"]["parent_id"] == batch["span_id"]
    assert trace_id in batch["attrs"]["members"]
    assert batch["attrs"]["rows"] == 1
    assert by_name["cache_lookup"]["attrs"]["hit"] is False
    assert by_name["extract"]["attrs"]["mode"] == "warm"
    assert by_name["extract"]["attrs"]["worker_pid"] > 0
    # the traceparent response header names the root span
    version, tid, sid, flags = headers["traceparent"].split("-")
    assert (version, flags) == ("00", "01")
    assert tid == trace_id and sid == trace["root_span_id"]
    # the normal (non-debug) response stays trace-free
    status, body2, headers2 = _post_full(
        traced_server.port, "predict",
        "class T { int traced(int n) { return n; } }")
    assert "trace" not in json.loads(body2)
    assert headers2["X-Trace-Id"] != trace_id  # fresh id per request


def test_inbound_traceparent_honored_and_echoed(traced_server):
    """A caller-supplied W3C traceparent joins ITS trace: same trace id
    end to end, the server's root span parented under the caller's
    span, and the echoed traceparent naming the server's root span."""
    inbound_trace, inbound_span = "ab" * 16, "cd" * 8
    status, body, headers = _post_full(
        traced_server.port, "predict",
        "class I { int inbound() { return 1; } }",
        headers={"traceparent":
                 f"00-{inbound_trace}-{inbound_span}-01"},
        query="?debug=trace")
    assert status == 200
    assert headers["X-Trace-Id"] == inbound_trace
    trace = json.loads(body)["trace"]
    assert trace["trace_id"] == inbound_trace
    assert trace["remote_parent"] == inbound_span
    [root] = [s for s in trace["spans"] if s["name"] == "request"]
    assert root["parent_id"] == inbound_span
    assert headers["traceparent"] == \
        f"00-{inbound_trace}-{root['span_id']}-01"
    # malformed traceparent: minted id, not a 400
    status, _, headers = _post_full(
        traced_server.port, "predict",
        "class I { int inbound2() { return 1; } }",
        headers={"traceparent": "zz-garbage"})
    assert status == 200
    assert headers["X-Trace-Id"] != inbound_trace


def test_minted_ids_unique_across_coalesced_batch(traced_server):
    """Concurrent requests coalesced into one device batch each keep
    their OWN trace id; the shared batch span id ties the trees
    together and its `members` attr lists exactly the requests that
    rode it."""
    codes = [f"class B{i} {{ int rode{i}(int n) {{ return n; }} }}"
             for i in range(4)]
    results = [None] * 4

    def post(i):
        results[i] = _post_full(traced_server.port, "predict", codes[i],
                                query="?debug=trace")

    threads = [threading.Thread(target=post, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r[0] == 200 for r in results)
    trace_ids = [r[2]["X-Trace-Id"] for r in results]
    assert len(set(trace_ids)) == 4, "minted ids must be unique"
    batches = {}  # batch span id -> (members attr, rider trace ids)
    for (_, body, headers) in results:
        trace = json.loads(body)["trace"]
        assert trace["trace_id"] == headers["X-Trace-Id"]
        [batch] = [s for s in trace["spans"] if s["name"] == "batch"]
        [device] = [s for s in trace["spans"] if s["name"] == "device"]
        assert device["parent_id"] == batch["span_id"]
        members, riders = batches.setdefault(
            batch["span_id"], (batch["attrs"]["members"], set()))
        assert batch["attrs"]["members"] == members
        riders.add(trace["trace_id"])
    # each batch span's members list is EXACTLY the requests that rode
    # it — no request missing, none from another batch
    for members, riders in batches.values():
        assert set(members) == riders
    assert {t for _, r in batches.values() for t in r} == set(trace_ids)


def test_cache_hit_fast_path_carries_trace_id(traced_server):
    code = "class H { int hits(int n) { return n * 2; } }"
    status, _, h1 = _post_full(traced_server.port, "predict", code)
    assert status == 200
    hits0 = _counter_value("serving_cache_hits_total")
    status, body, h2 = _post_full(traced_server.port, "predict", code,
                                  query="?debug=trace")
    assert status == 200
    assert _counter_value("serving_cache_hits_total") == hits0 + 1
    # the hit got its own fresh id...
    assert h2["X-Trace-Id"] != h1["X-Trace-Id"]
    trace = json.loads(body)["trace"]
    assert trace["trace_id"] == h2["X-Trace-Id"]
    by_name = {s["name"]: s for s in trace["spans"]}
    # ...and an honest short tree: cache hit, no pipeline phases
    assert by_name["cache_lookup"]["attrs"]["hit"] is True
    assert "extract" not in by_name and "device" not in by_name
    # error paths carry the id too (here: 400 empty body)
    status, _, h3 = _post_full(traced_server.port, "predict", "   ")
    assert status == 400 and len(h3["X-Trace-Id"]) == 32


def test_debug_trace_gated_off_by_default(server):
    """Security gate: without --serve_debug_trace the ?debug=trace query
    is ignored — the span tree exposes internals (worker pids, batch
    composition) that must not leak from a production endpoint."""
    assert not server.config.serve_debug_trace
    status, body, headers = _post_full(
        server.port, "predict",
        "class G { int gated() { return 1; } }", query="?debug=trace")
    assert status == 200
    assert "trace" not in json.loads(body)
    assert "X-Trace-Id" in headers  # the id itself still rides


def test_telemetry_cli_flags_parse():
    from code2vec_tpu.cli import config_from_args
    config = config_from_args([
        "serve", "--load", "/tmp/nonexistent-model",
        "--serve_debug_trace", "--serve_flight_dir", "/tmp/fl",
        "--serve_flight_records", "64", "--serve_telemetry_port", "0"])
    assert config.serve_debug_trace is True
    assert config.serve_flight_dir == "/tmp/fl"
    assert config.serve_flight_records == 64
    assert config.serve_telemetry_port == 0
    # defaults: debug trace OFF, flight dir/telemetry port unset
    config2 = config_from_args(["--serve", "--load", "/tmp/x"])
    assert config2.serve_debug_trace is False
    assert config2.serve_flight_dir is None
    assert config2.serve_telemetry_port is None


# -------------------------------------------------------------- REPL


def test_repl_golden_output_format(served_model, fake_extractor,
                                   tmp_path, monkeypatch, capsys):
    """The rewired REPL (warm pool underneath) keeps the reference's
    exact display format (interactive_predict.py:39-72): Original name /
    tab-indented (prob) predicted rows / Attention: score<TAB>context
    triples."""
    from code2vec_tpu.serving.interactive import InteractivePredictor
    input_file = tmp_path / "Input.java"
    input_file.write_text(
        "class A { int addOne(int n) { return n + 1; } }")
    answers = iter(["", "q"])
    monkeypatch.setattr("builtins.input", lambda *a: next(answers))
    predictor = InteractivePredictor(served_model.config, served_model)
    assert predictor.extractor_pool.size == 1
    predictor.predict(str(input_file))
    out = capsys.readouterr().out
    assert "Starting interactive prediction..." in out
    assert "Exiting..." in out
    lines = out.splitlines()
    assert "Original name:\taddOne" in lines
    pred_re = re.compile(r"^\t\(\d\.\d{6}\) predicted: (\[.*\]|.+)$")
    att_re = re.compile(r"^\d\.\d{6}\tcontext: .+,\(.+\),.+$")
    assert any(pred_re.match(l) for l in lines), lines
    assert "Attention:" in lines
    assert any(att_re.match(l) for l in lines), lines
    # the pool is torn down when the REPL exits
    assert predictor.extractor_pool._closed


def test_serve_cli_flags_parse():
    from code2vec_tpu.cli import config_from_args
    config = config_from_args([
        "serve", "--load", "/tmp/nonexistent-model", "--serve_port", "0",
        "--serve_batch_size", "32", "--serve_buckets", "16,32",
        "--serve_max_delay_ms", "2.5", "--serve_cache_entries", "128",
        "--extractor_pool_size", "3"])
    assert config.serve is True
    assert config.serve_port == 0
    assert config.serve_batch_size == 32
    assert config.serve_buckets == "16,32"
    assert config.serve_max_delay_ms == 2.5
    assert config.serve_cache_entries == 128
    assert config.extractor_pool_size == 3
    # --serve flag form equals the subcommand form
    config2 = config_from_args(["--serve", "--load", "/tmp/x"])
    assert config2.serve is True
