"""Tenant-fair serving suite (code2vec_tpu/serving/tenancy.py + the
tenant threading through admission, batchers, server and fleet):

- weight/qps spec parsing laws and their Config-validation surfacing;
- deterministic token-bucket refill against an injected clock, and the
  BUGFIX pin: a tenant_quota shed's Retry-After derives from THAT
  tenant's bucket refill time, never the fleet-wide EWMA estimate;
- admission share laws: a lone tenant owns the whole queue (work
  conservation ⇒ tenancy on for one tenant == tenancy off), contending
  tenants converge to weighted shares (1:2:4 ⇒ accepted ratios within
  10% under saturation), per-tenant depth bounds sum to <= max_depth,
  an idle tenant keeps its share inside the active window and releases
  it after;
- `other`-bucket label collapse + the bounded-cardinality registration
  guard (the registry can never grow unbounded tenant label values);
- dwrr_take interleave laws (single tenant ⇒ None: the byte-identical
  FIFO path);
- end-to-end byte-equality: a single tenant's responses with tenancy
  ON equal the tenancy-OFF bytes;
- satellite pins: the pipeline manifest records its promote model
  group, FleetSwapDriver refuses an unmapped group naming the fleet's
  known groups;
- the slow tenant-overload chaos drill: a hot tenant floods a real
  HTTP server while an in-share tenant keeps serving (run via
  scripts/run_chaos.sh under TENANCY_BUDGET).
"""

import dataclasses
import json
import threading
import urllib.error
import urllib.request

import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.serving.tenancy import (
    DEFAULT_TENANT, OTHER_LABEL, TENANT_HEADER, TenantPolicy,
    TokenBucket, dwrr_take, parse_tenant_qps, parse_tenant_weights,
    tenant_metric,
)

from test_serving import (  # noqa: F401 — fixtures
    _serving_config, fake_extractor, served_model,
)

pytestmark = pytest.mark.tenancy


class _Clock:
    """Injectable monotonic clock: tests advance it explicitly so
    bucket refill and active-window behavior are exact, not timing."""

    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------ spec parsing


def test_parse_tenant_weights_laws():
    assert parse_tenant_weights("") == {}
    assert parse_tenant_weights(None) == {}
    assert parse_tenant_weights("acme") == {"acme": 1.0}
    assert parse_tenant_weights(" acme=4, dev=1.5 ,ci ") == {
        "acme": 4.0, "dev": 1.5, "ci": 1.0}
    for bad in ("=2", "acme=0", "acme=-1", "acme=x", "a=1,a=2"):
        with pytest.raises(ValueError):
            parse_tenant_weights(bad)


def test_parse_tenant_qps_laws():
    assert parse_tenant_qps("") == {}
    assert parse_tenant_qps("5") == {"*": 5.0}
    assert parse_tenant_qps("acme=50,dev=0") == {"acme": 50.0,
                                                 "dev": 0.0}
    for bad in ("acme=-1", "acme=x", "a=1,a=2", "=3"):
        with pytest.raises(ValueError):
            parse_tenant_qps(bad)


def test_config_validates_tenancy_knobs():
    # a typo'd share spec fails at startup, not silently in production
    with pytest.raises(ValueError, match="serve_tenants"):
        Config(train_data_path_prefix="x",
               serve_tenants="acme=0").verify()
    with pytest.raises(ValueError, match="serve_tenant_qps"):
        Config(train_data_path_prefix="x",
               serve_tenant_qps="acme=-2").verify()
    with pytest.raises(ValueError, match="serve_tenant_default_weight"):
        Config(train_data_path_prefix="x", serve_tenants="acme=1",
               serve_tenant_default_weight=0.0).verify()
    Config(train_data_path_prefix="x", serve_tenants="acme=4,dev=1",
           serve_tenant_qps="acme=50").verify()


def test_policy_from_config_off_means_none():
    assert TenantPolicy.from_config(Config()) is None
    pol = TenantPolicy.from_config(Config(serve_tenants="a=2"))
    assert pol is not None and pol.weight("a") == 2.0


# -------------------------------------------------- identity collapse


def test_resolve_and_label_collapse():
    pol = TenantPolicy({"acme": 4.0, "dev": 1.0})
    assert TenantPolicy.resolve(None) == DEFAULT_TENANT
    assert TenantPolicy.resolve("  ") == DEFAULT_TENANT
    assert TenantPolicy.resolve(" acme ") == "acme"
    assert pol.label("acme") == "acme"
    assert pol.label(None) == DEFAULT_TENANT
    # every unconfigured tenant collapses into ONE bucket: the label
    # set is closed no matter what clients put in X-Tenant
    assert pol.label("fuzz-1") == OTHER_LABEL
    assert pol.label("fuzz-2") == OTHER_LABEL
    assert pol.labels == ("acme", "dev", DEFAULT_TENANT, OTHER_LABEL)


def test_tenant_metric_cardinality_guard():
    pol = TenantPolicy({"acme": 1.0})
    # the registry refuses unbounded tenant label values ...
    with pytest.raises(ValueError, match="outside the configured"):
        tenant_metric("counter", "serving_requests_total", "h",
                      "fuzz-1", pol.labels)
    # ... and any metric name outside the closed tenant-family set
    with pytest.raises(ValueError, match="not a tenant-labeled"):
        tenant_metric("counter", "bogus_total", "h", "acme",
                      pol.labels)
    c = tenant_metric("counter", "serving_requests_shed_total",
                      "requests shed before the model ran, by reason",
                      "acme", pol.labels, reason="test_guard")
    before = c.value
    c.inc()
    assert c.value == before + 1


def test_dynamic_registration_allowlist_mirrors_tenant_metrics():
    """scripts/check_metrics_doc.py's closed allowlist and tenancy.py's
    guard set must stay the same tuple — the doc gate is only as
    honest as this mirror."""
    import importlib.util
    import os
    spec = importlib.util.spec_from_file_location(
        "check_metrics_doc",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts",
            "check_metrics_doc.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from code2vec_tpu.serving import tenancy
    declared = mod._DYNAMIC_REGISTRATIONS[
        os.path.join("serving", "tenancy.py")]
    assert tuple(declared) == tenancy._TENANT_METRICS


# ------------------------------------------------------- token bucket


def test_token_bucket_refill_is_deterministic():
    clock = _Clock()
    b = TokenBucket(2.0, clock=clock)  # burst = max(1, 2) = 2
    assert b.try_take() and b.try_take()
    assert not b.try_take()
    assert b.retry_after_s() == pytest.approx(0.5)  # (1-0)/2 qps
    clock.advance(0.5)
    assert b.try_take()
    assert not b.try_take()
    clock.advance(0.25)
    assert b.retry_after_s() == pytest.approx(0.25)
    # refill caps at burst: a long idle gap is not a storm credit
    clock.advance(100.0)
    assert b.try_take() and b.try_take() and not b.try_take()


def test_zero_rate_bucket_blocks_hard():
    pol = TenantPolicy({"a": 1.0}, qps={"a": 0.0})
    assert pol.bucket("a") is None  # 0 = uncapped, not blocked
    b = TokenBucket(0.0, burst=0.0, clock=_Clock())
    assert not b.try_take()
    assert b.retry_after_s() == 60.0


def test_shared_star_qps_and_per_label_buckets():
    pol = TenantPolicy({"a": 1.0, "b": 1.0}, qps={"*": 5.0, "b": 1.0})
    assert pol.bucket("a").rate == 5.0
    assert pol.bucket("b").rate == 1.0
    assert pol.bucket("a") is pol.bucket("a")  # one bucket per label


# ------------------------------------------------- admission fairness


def _policy_controller(weights, max_depth, clock=None, qps=None,
                       concurrency=1):
    from code2vec_tpu.serving.admission import AdmissionController
    pol = TenantPolicy(weights, qps=qps, clock=clock or _Clock())
    return AdmissionController(max_depth=max_depth,
                               concurrency=concurrency,
                               tenancy=pol), pol


def test_lone_tenant_owns_the_whole_queue():
    """Work conservation: with no contention the share bound IS the
    global bound — tenancy on with one tenant == tenancy off."""
    ac, _ = _policy_controller({"a": 1.0, "b": 2.0}, max_depth=8)
    for _ in range(8):
        ac.admit(tenant="a")
    from code2vec_tpu.serving.admission import Shed
    with pytest.raises(Shed) as e:
        ac.admit(tenant="a")
    # the 9th refusal is the GLOBAL queue, not a share cap
    assert e.value.reason == "queue_full"


def test_contending_tenants_get_weighted_bounds():
    clock = _Clock()
    ac, _ = _policy_controller({"a": 1.0, "b": 2.0, "c": 5.0},
                               max_depth=16, clock=clock)
    from code2vec_tpu.serving.admission import Shed
    # all three probe: each lands in the active set
    for t in ("a", "b", "c"):
        ac.admit(tenant=t)
    # bounds are floor(depth * w / total): 2, 4, 10 — summing <= 16,
    # so an in-share tenant can never be refused by the global gate
    assert ac.tenant_bound("a") == 2
    assert ac.tenant_bound("b") == 4
    assert ac.tenant_bound("c") == 10
    # c floods to its bound, then sheds tenant_quota — while a still
    # admits (the most-over-share tenant is always the first refused)
    for _ in range(9):
        ac.admit(tenant="c")
    with pytest.raises(Shed) as e:
        ac.admit(tenant="c")
    assert e.value.reason == "tenant_quota"
    assert "fair share" in str(e.value)
    ac.admit(tenant="a")  # in-share tenant keeps admitting


def test_idle_tenant_releases_share_after_active_window():
    clock = _Clock()
    ac, pol = _policy_controller({"a": 1.0, "b": 1.0}, max_depth=8,
                                 clock=clock)
    ac.admit(tenant="b")
    ac.finish(0.01, tenant="b")
    # inside the window b still reserves half the queue ...
    assert ac.tenant_bound("a") == 4
    # ... and after it (with zero in flight) the queue is a's again
    clock.advance(pol.active_window_s + 1.0)
    assert ac.tenant_bound("a") == 8


def test_saturated_shares_converge_to_weights():
    """The fairness law the drill measures: under saturation with
    equal service times, accepted throughput converges to the 1:2:4
    weights within 10%."""
    from code2vec_tpu.serving.admission import Shed
    clock = _Clock()
    ac, _ = _policy_controller({"a": 1.0, "b": 2.0, "c": 4.0},
                               max_depth=14, clock=clock)
    tenants = ("a", "b", "c")
    accepted = {t: 0 for t in tenants}
    inflight = []
    for i in range(4000):
        clock.advance(0.001)
        for t in tenants:  # every tenant has infinite backlog
            try:
                ac.admit(tenant=t)
                inflight.append(t)
                accepted[t] += 1
            except Shed:
                pass
        if inflight:  # equal service time: complete the oldest
            done = inflight.pop(0)
            ac.finish(0.01, tenant=done)
    total = sum(accepted.values())
    shares = {t: accepted[t] / total for t in tenants}
    assert shares["a"] == pytest.approx(1 / 7, rel=0.10), shares
    assert shares["b"] == pytest.approx(2 / 7, rel=0.10), shares
    assert shares["c"] == pytest.approx(4 / 7, rel=0.10), shares


def test_rate_quota_retry_after_is_the_buckets_not_the_ewma():
    """THE BUGFIX PIN: an over-quota tenant's Retry-After derives from
    its own token-bucket refill time. A fleet under heavy load has a
    huge queue-wait EWMA; leaking that into a quota shed would tell a
    blocked tenant to back off for the whole fleet's drain time."""
    from code2vec_tpu.serving.admission import Shed
    clock = _Clock()
    ac, _ = _policy_controller({"a": 1.0}, max_depth=64, clock=clock,
                               qps={"a": 0.25})
    # poison the fleet-wide estimate: 50s EWMA, deep queue
    ac._ewma_s = 50.0
    ac.admit(tenant="a")  # burst token
    with pytest.raises(Shed) as e:
        ac.admit(tenant="a")
    assert e.value.reason == "tenant_quota"
    assert "rate quota" in str(e.value)
    # bucket: rate 0.25 ⇒ a whole token in 4s — NOT 50s * depth
    assert e.value.retry_after_s == pytest.approx(4.0, abs=0.1)


def test_share_shed_retry_after_is_tenant_scoped():
    """A share shed waits for the TENANT's in-flight work to drain,
    not the whole queue's."""
    from code2vec_tpu.serving.admission import Shed
    clock = _Clock()
    ac, _ = _policy_controller({"a": 1.0, "b": 1.0}, max_depth=8,
                               clock=clock, concurrency=1)
    ac._ewma_s = 2.0
    ac.admit(tenant="b")  # contention: a's bound becomes 4
    for _ in range(4):
        ac.admit(tenant="a")
    with pytest.raises(Shed) as e:
        ac.admit(tenant="a")
    assert e.value.reason == "tenant_quota"
    # 2s EWMA * 4 held / 1 concurrency = 8s; the GLOBAL estimate would
    # be 2 * 8 = 16s
    assert e.value.retry_after_s == pytest.approx(8.0)


def test_admission_without_tenant_is_unchanged():
    """tenancy=None (or tenant=None) keeps the PR-9 gate bit-for-bit:
    same reasons, same bookkeeping."""
    from code2vec_tpu.serving.admission import (
        AdmissionController, Shed,
    )
    ac = AdmissionController(max_depth=2)
    ac.admit()
    ac.admit()
    with pytest.raises(Shed) as e:
        ac.admit()
    assert e.value.reason == "queue_full"
    ac.finish(0.01)
    ac.admit()


# ---------------------------------------------------------- DWRR laws


class _Row:
    def __init__(self, tenant, n=1):
        self.tenant = tenant
        self.lines = ["x"] * n


def test_dwrr_single_tenant_returns_none():
    # one tenant pending ⇒ the caller keeps its FIFO path (the
    # byte-equality mechanism for the tenancy-on single-tenant case)
    assert dwrr_take([_Row("a"), _Row("a")], 4, lambda t: 1.0, {}) \
        is None
    assert dwrr_take([], 4, lambda t: 1.0, {}) is None


def test_dwrr_interleaves_by_weight():
    pol = TenantPolicy({"a": 1.0, "b": 3.0})
    pending = [_Row("a") for _ in range(8)] + \
              [_Row("b") for _ in range(8)]
    state = {}
    picked = dwrr_take(pending, 4, pol.weight, state)
    assert picked is not None and len(picked) == 4
    by_tenant = [pending[i].tenant for i in picked]
    # weight 1:3 over a 4-row batch ⇒ 1 a-row, 3 b-rows
    assert by_tenant.count("a") == 1 and by_tenant.count("b") == 3
    # FIFO within a tenant
    a_rows = [i for i in picked if pending[i].tenant == "a"]
    assert a_rows == sorted(a_rows)


def test_dwrr_oversized_head_dispatches_alone():
    pol = TenantPolicy({"a": 1.0, "b": 1.0})
    pending = [_Row("a", n=10), _Row("b", n=1)]
    picked = dwrr_take(pending, 4, pol.weight, {})
    # the first take is always allowed (an oversized request must not
    # deadlock), and nothing else fits after it
    assert picked == [0]


def test_dwrr_carries_deficit_across_batches():
    pol = TenantPolicy({"a": 1.0, "b": 1.0})
    state = {}
    pending = [_Row("a") for _ in range(6)] + \
              [_Row("b") for _ in range(6)]
    first = dwrr_take(pending, 4, pol.weight, state)
    remaining = [p for i, p in enumerate(pending) if i not in first]
    second = dwrr_take(remaining, 4, pol.weight, state)
    counts = {"a": 0, "b": 0}
    for idx_set, pool in ((first, pending), (second, remaining)):
        for i in idx_set:
            counts[pool[i].tenant] += 1
    # equal weights ⇒ equal service over two batches
    assert counts["a"] == counts["b"] == 4


def test_classic_batcher_dwrr_under_two_tenants():
    """With two tenants backed up, a filled batch carries both in
    weighted proportion instead of one tenant's FIFO run."""
    import time as _time

    from code2vec_tpu.serving.batcher import DynamicBatcher
    pol = TenantPolicy({"a": 1.0, "b": 1.0})
    seen = []
    gate = threading.Event()

    def predict(lines):
        if list(lines) == ["warm"]:
            gate.wait(timeout=5)  # hold the dispatcher: backlogs build
        seen.append(list(lines))
        return [f"r:{ln}" for ln in lines]

    b = DynamicBatcher(max_batch_rows=4, max_delay_s=0.01,
                       predict_fn=predict, tenancy=pol)
    try:
        warm = b.submit(["warm"], tenant="a")
        _time.sleep(0.2)  # dispatcher is now blocked inside predict
        futs = [b.submit([f"a{i}"], tenant="a") for i in range(4)]
        futs += [b.submit([f"b{i}"], tenant="b") for i in range(4)]
        gate.set()
        assert warm.result(timeout=5)
        for f in futs:
            assert f.result(timeout=5)
    finally:
        gate.set()
        b.drain(timeout=5)
    first_full = next(batch for batch in seen
                      if len(batch) == 4 and "warm" not in batch)
    tenants = ["a" if ln.startswith("a") else "b" for ln in first_full]
    assert tenants.count("a") == 2 and tenants.count("b") == 2, seen


# ----------------------------------------- satellite pins: fleet/pipe


def test_manifest_records_promote_model_group(tmp_path):
    from code2vec_tpu.pipeline.manifest import PipelineManifest
    m = PipelineManifest.load_or_create(str(tmp_path), "fp1",
                                        ["ingest"], model="prod")
    assert m.data["model"] == "prod"
    # survives reload (a postmortem reads it off the file)
    m2 = PipelineManifest.load_or_create(str(tmp_path), "fp1",
                                         ["ingest"])
    assert m2.data["model"] == "prod"


def test_fleet_swap_refuses_unmapped_model_group_naming_known():
    """A promote for a model group the router's --fleet_models map
    does not know fails EARLY with the known groups in the message,
    not ambiguously at canary convergence."""
    from code2vec_tpu.serving.fleet.swap import FleetSwapDriver

    class _Control:
        models = ["default", "prod"]

        def swap_hosts(self, model):
            return None if model not in self.models else []

    driver = FleetSwapDriver(_Control())
    with pytest.raises(ValueError) as e:
        driver.request("artifact-dir", model="staging")
    msg = str(e.value)
    assert "staging" in msg
    assert "default" in msg and "prod" in msg
    assert "--fleet_models" in msg


def test_x_tenant_rides_the_forwarding_contract():
    from code2vec_tpu.serving.forwarding import REQUEST_FORWARD_HEADERS
    assert TENANT_HEADER in REQUEST_FORWARD_HEADERS
    assert "X-Model" in REQUEST_FORWARD_HEADERS
    assert "X-Deadline-Ms" in REQUEST_FORWARD_HEADERS


# ------------------------------------------- end-to-end byte equality


def test_single_tenant_bytes_equal_tenancy_off(served_model,
                                               fake_extractor):
    """The zero-behavior-change contract, end to end: one tenant's
    responses with tenancy ON are byte-identical to tenancy OFF, for
    the named tenant, the default tenant and an unconfigured one."""
    from code2vec_tpu.serving.server import PredictionServer
    codes = [
        "class A { int f(int n) { return n; } } NCTX2",
        "class B { int g() { return 2; } } NCTX1",
    ]
    off = PredictionServer(served_model, served_model.config,
                           log=lambda m: None)
    on = PredictionServer(
        served_model,
        dataclasses.replace(served_model.config,
                            serve_tenants="acme=4,dev=1",
                            serve_tenant_qps="acme=1000"),
        log=lambda m: None)
    try:
        assert off.tenancy is None and on.tenancy is not None
        for tenant in (None, "acme", "unconfigured-tenant"):
            for endpoint in ("predict", "embed"):
                for code in codes:
                    s1, b1, _ = off.handle_request(endpoint, code,
                                                   tenant=tenant)
                    s2, b2, _ = on.handle_request(endpoint, code,
                                                  tenant=tenant)
                    assert (s1, s2) == (200, 200)
                    assert b1 == b2, (tenant, endpoint, code)
        # healthz: the tenancy block appears ONLY when the policy is on
        assert "tenancy" not in off.healthz()
        hz = on.healthz()["tenancy"]
        assert hz["tenants"]["acme"]["weight"] == 4.0
        assert hz["tenants"]["acme"]["qps"] == 1000.0
    finally:
        off.drain(timeout=10)
        on.drain(timeout=10)


# --------------------------------------------- chaos: overload drill


def _http_post(port, endpoint, body, headers=None, timeout=30):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/{endpoint}", data=body.encode(),
        method="POST", headers=dict({"Content-Type": "text/plain"},
                                    **(headers or {})))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, r.read(), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, e.read(), dict(e.headers)


@pytest.mark.slow
@pytest.mark.chaos
def test_tenant_overload_drill(served_model, fake_extractor):
    """A hot tenant hammering a rate quota sheds tenant_quota with a
    per-tenant Retry-After while an in-share tenant keeps serving with
    ZERO sheds — the in-process version of the fleet drill."""
    from code2vec_tpu.serving.server import PredictionServer
    srv = PredictionServer(
        served_model,
        dataclasses.replace(served_model.config,
                            serve_tenants="hot=1,cold=1",
                            serve_tenant_qps="hot=2",
                            serve_queue_depth=32),
        log=lambda m: None)
    srv.start(port=0)
    hot_results = []

    def flood():
        for i in range(20):
            status, body, headers = _http_post(
                srv.port, "predict",
                f"class H {{ int f{i}() {{ return {i}; }} }}",
                headers={TENANT_HEADER: "hot"})
            hot_results.append((status, body, headers))

    try:
        threads = [threading.Thread(target=flood) for _ in range(3)]
        for t in threads:
            t.start()
        cold = []
        for i in range(10):
            cold.append(_http_post(
                srv.port, "predict",
                f"class C {{ int g{i}() {{ return {i}; }} }}",
                headers={TENANT_HEADER: "cold"}))
        for t in threads:
            t.join(timeout=60)
        # the in-share tenant never shed
        assert all(s == 200 for s, _, _ in cold), \
            [(s, b[:80]) for s, b, _ in cold]
        sheds = [(s, b, h) for s, b, h in hot_results if s == 503]
        oks = [s for s, _, _ in hot_results if s == 200]
        assert oks, "the hot tenant must still get its quota through"
        assert sheds, "60 rapid-fire requests at 2 qps must shed"
        for s, body, headers in sheds:
            payload = json.loads(body)
            assert payload["shed"] == "tenant_quota", payload
            # honest, per-tenant retry hint (jittered int >= 1)
            assert int(headers["Retry-After"]) >= 1
        # no malformed responses: every answer parsed as JSON with a
        # terminal status
        for s, body, _ in hot_results + cold:
            assert s in (200, 503), (s, body[:120])
            json.loads(body)
    finally:
        srv.drain(timeout=15)
