"""Fleet "host" child for the fleet chaos suite (tests/test_fleet.py).

One fleet host = one REAL serving Supervisor (the full heartbeat /
monitor / restart / telemetry / scale / reload-fan-out machinery)
whose replicas are the lightweight fake-model children
(tests/chaos_serving_child.py) — so a multi-"host" fleet starts in a
couple of seconds and the control-plane / router / coordinated-swap
protocol under test is the production one.

Usage (the fleet ControlPlane appends `--heartbeat_file PATH`; the
test builds the rest of the command):

    python tests/chaos_fleet_host.py HOST_CONFIG_JSON \
        REPLICA_OVERRIDES_JSON [--heartbeat_file PATH] \
        [--serve_port N] [--serve_telemetry_port N]
"""

import json
import os
import sys

# No jax in a supervisor parent: keep host startup at subprocess speed.
os.environ.setdefault("C2V_HOST_WORKER", "1")

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

HERE = os.path.dirname(os.path.abspath(__file__))


def main() -> int:
    argv = sys.argv[1:]
    overrides = json.loads(open(argv[0]).read())
    replica_overrides_path = argv[1]
    if "--heartbeat_file" in argv:
        overrides["heartbeat_file"] = argv[argv.index(
            "--heartbeat_file") + 1]
    if "--serve_port" in argv:
        overrides["serve_port"] = int(
            argv[argv.index("--serve_port") + 1])
    if "--serve_telemetry_port" in argv:
        overrides["serve_telemetry_port"] = int(
            argv[argv.index("--serve_telemetry_port") + 1])

    from code2vec_tpu.config import Config
    from code2vec_tpu.serving.supervisor import supervisor_main

    config = Config(serve=True, verbose_mode=0, **overrides)
    child_command = [
        sys.executable, os.path.join(HERE, "chaos_serving_child.py"),
        replica_overrides_path]
    return supervisor_main(config, child_command=child_command)


if __name__ == "__main__":
    sys.exit(main())
