"""Child process for tests/test_multihost_chaos.py — NOT a pytest module.

Each of two OS processes joins a real `jax.distributed` runtime (CPU
backend, gloo collectives) and exercises the multi-host checkpoint
commit protocol (training/checkpoint.py) under fault injection.

Subcommands:

- `matrix <pid> <port> <base> <kill_point> <victim> <async>` — the kill
  matrix. Both hosts save `_iter1` cleanly, then save `_iter2` with the
  named fault point armed (action `exit`) on the victim host only. The
  victim dies with FAULT_EXIT_CODE mid-protocol; the survivor's commit
  barrier times out, it prints the artifact its LOCAL fallback walk
  lands on (`CHAOS_MH_LATEST`), and exits 0 via os._exit (the normal
  interpreter exit would hang in jax.distributed's shutdown barrier
  against the dead peer). `kill_point=none` runs the protocol clean:
  both hosts commit both artifacts, run the COLLECTIVE resume
  agreement, and print the agreed artifact.

- `desync <pid> <port> <workdir>` — the loud-desync contract: hosts
  intentionally diverge and every path must raise the named desync
  error on EVERY host instead of hanging the pod:
  1. `assert_host_agreement` with per-host values;
  2. the Trainer's epoch-boundary agreement check with per-host batch
     counts (3 vs 2);
  3. the collective `latest_valid_checkpoint` walk with one host
     locally rejecting the newest artifact — both hosts must converge
     on the SAME older artifact.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # covered by the XLA_FLAGS fallback above
jax.config.update("jax_cpu_collectives_implementation", "gloo")

import numpy as np  # noqa: E402

HERE = os.path.dirname(os.path.abspath(__file__))
REPO_ROOT = os.path.dirname(HERE)
for p in (REPO_ROOT, HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

import chaos_child  # noqa: E402  (deterministic state builders)

# Short barrier timeout: a dead peer must fail the save in seconds, well
# inside both the parent's subprocess timeout and the coordination
# service's own missed-heartbeat kill (~100s).
BARRIER_TIMEOUT_S = 8.0


def _die(code: int) -> None:
    """Exit WITHOUT running jax.distributed's shutdown barrier — after a
    peer died mid-protocol that barrier can only time out."""
    sys.stdout.flush()
    os._exit(code)


def cmd_matrix(pid: int, port: str, base: str, kill_point: str,
               victim: int, use_async: bool) -> None:
    import dataclasses

    from code2vec_tpu.parallel import distributed
    from code2vec_tpu.training import checkpoint as ckpt_mod
    from code2vec_tpu.utils import faults

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    vocabs = chaos_child.build_vocabs()
    config = dataclasses.replace(chaos_child.build_config(),
                                 save_barrier_timeout_s=BARRIER_TIMEOUT_S,
                                 async_checkpointing=use_async)
    committer = (ckpt_mod.AsyncCommitter(max_in_flight=2)
                 if use_async else None)

    def save(epoch: int) -> None:
        ckpt_mod.save_model(f"{base}_iter{epoch}",
                            chaos_child.build_state(epoch), vocabs, config,
                            epoch=epoch, committer=committer)
        if committer is not None:
            committer.drain()

    save(1)
    print(f"CHAOS_MH_SAVED {pid} 1", flush=True)

    if kill_point != "none" and pid == victim:
        faults.reset(f"{kill_point}=exit")
    try:
        save(2)
    except Exception as e:
        # Survivor path: the victim died mid-protocol and this host's
        # barrier timed out (or its commit errored behind the dead
        # peer). Report what the LOCAL fallback walk finds — the
        # collective walk needs a live pod — and leave fast.
        print(f"CHAOS_MH_SURVIVOR {pid} {type(e).__name__}", flush=True)
        latest = ckpt_mod.latest_valid_checkpoint(base, collective=False)
        print(f"CHAOS_MH_LATEST {pid} {latest}", flush=True)
        _die(0)
    print(f"CHAOS_MH_SAVED {pid} 2", flush=True)

    if kill_point != "none":
        # The victim's armed fault never fired an exception HERE (exit
        # action kills the process); a victim reaching this line means
        # the fault point was never crossed — fail loudly.
        if pid == victim:
            print(f"CHAOS_MH_FAULT_NOT_HIT {pid} {kill_point}", flush=True)
            _die(9)
        # Survivor of a post-commit kill (callback_crash on the other
        # host can leave this host's save fully successful when the
        # victim was a non-committing peer that died after this host
        # passed every barrier). Report and leave like any survivor.
        print(f"CHAOS_MH_SURVIVOR {pid} CleanSave", flush=True)
        latest = ckpt_mod.latest_valid_checkpoint(base, collective=False)
        print(f"CHAOS_MH_LATEST {pid} {latest}", flush=True)
        _die(0)

    # Clean run: both hosts committed both artifacts; the COLLECTIVE
    # resume agreement must hand every host the same newest path.
    agreed = ckpt_mod.latest_valid_checkpoint(base)
    print(f"CHAOS_MH_AGREED {pid} {agreed}", flush=True)
    meta = ckpt_mod.verify_checkpoint(agreed)
    assert meta["epoch"] == 2, meta
    print(f"CHAOS_MH_OK {pid}", flush=True)


def cmd_desync(pid: int, port: str, workdir: str) -> None:
    from code2vec_tpu.config import Config
    from code2vec_tpu.data.reader import EpochEnd
    from code2vec_tpu.parallel import distributed
    from code2vec_tpu.training import checkpoint as ckpt_mod
    from code2vec_tpu.training.loop import Trainer

    distributed.initialize(coordinator_address=f"localhost:{port}",
                           num_processes=2, process_id=pid)
    assert jax.process_count() == 2

    # agree_scalar handles divergence by construction (that is its job)
    assert distributed.agree_scalar(10 + pid, "min") == 10
    assert distributed.agree_scalar(10 + pid, "max") == 11

    # 1. assert_host_agreement: divergent values must raise the loud
    # desync error on EVERY host (the gather completes collectively
    # before any host raises, so nobody hangs).
    try:
        distributed.assert_host_agreement(7 + pid, "intentional divergence")
        print(f"CHAOS_MH_DESYNC_ASSERT_MISSED {pid}", flush=True)
        _die(9)
    except RuntimeError as e:
        assert "multi-host desync" in str(e), e
        print(f"CHAOS_MH_DESYNC_ASSERT_OK {pid}", flush=True)

    # 2. the Trainer's epoch-boundary agreement: hosts cross the same
    # epoch boundary after DIFFERENT batch counts (3 vs 2) — the
    # lockstep precondition every collective in the loop relies on —
    # and every host must get the loud error, not a hang.
    class _S:
        step = np.zeros((), np.int32)

    from code2vec_tpu.data.reader import RowBatch

    def _fake_batch(n=2, m=4):
        return RowBatch(
            source_token_indices=np.ones((n, m), np.int32),
            path_indices=np.ones((n, m), np.int32),
            target_token_indices=np.ones((n, m), np.int32),
            context_valid_mask=np.ones((n, m), np.float32),
            target_index=np.ones((n,), np.int32),
            example_valid=np.ones((n,), bool))

    def stream():
        for _ in range(3 if pid == 0 else 2):
            yield _fake_batch()
        yield EpochEnd(1)

    def fake_step(s, *a):
        return s, np.float32(1.0)

    cfg = Config(train_data_path_prefix="unused", train_batch_size=4,
                 max_contexts=4, num_train_epochs=1, verbose_mode=0,
                 save_on_preemption=False)
    try:
        Trainer(cfg, fake_step).train(_S(), stream(),
                                      rng=np.zeros((2,), np.uint32))
        print(f"CHAOS_MH_DESYNC_EPOCH_MISSED {pid}", flush=True)
        _die(9)
    except RuntimeError as e:
        assert "multi-host desync" in str(e), e
        print(f"CHAOS_MH_DESYNC_EPOCH_OK {pid}", flush=True)

    # 3. collective fallback agreement: host 1 locally rejects the
    # newest artifact (simulating per-host verification divergence);
    # BOTH hosts must converge on the same older artifact.
    base = os.path.join(workdir, "m")
    vocabs = chaos_child.build_vocabs()
    config = chaos_child.build_config()
    for epoch in (1, 2):
        # save_model is a collective on a pod: BOTH hosts call it
        ckpt_mod.save_model(f"{base}_iter{epoch}",
                            chaos_child.build_state(epoch), vocabs,
                            config, epoch=epoch)
    if pid == 1:
        real_verify = ckpt_mod._verify_checkpoint_inner

        def biased_verify(path, check_content=False):
            if path.rstrip(os.sep).endswith("_iter2"):
                raise ckpt_mod.CheckpointIntegrityError(
                    f"{path}: injected host-local rejection")
            return real_verify(path, check_content)

        ckpt_mod._verify_checkpoint_inner = biased_verify
    agreed = ckpt_mod.latest_valid_checkpoint(base)
    assert agreed == f"{base}_iter1", agreed
    print(f"CHAOS_MH_DESYNC_FALLBACK_OK {pid} {agreed}", flush=True)
    print(f"CHAOS_MH_OK {pid}", flush=True)


def main() -> None:
    cmd = sys.argv[1]
    if cmd == "matrix":
        cmd_matrix(int(sys.argv[2]), sys.argv[3], sys.argv[4], sys.argv[5],
                   int(sys.argv[6]), bool(int(sys.argv[7])))
    elif cmd == "desync":
        cmd_desync(int(sys.argv[2]), sys.argv[3], sys.argv[4])
    else:
        raise SystemExit(f"unknown chaos_mh_child command: {cmd!r}")


if __name__ == "__main__":
    main()
