"""Parity tests: native C++ data core (cpp/src/dataloader.cc) vs the
pure-Python reference path in data/reader.py.

Both implement the reference pipeline semantics
(path_context_reader.py:184-228): empty field = PAD, unknown word = OOV,
context valid iff any part != PAD.
"""

import os
import subprocess

import numpy as np
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data import native, packed
from code2vec_tpu.data import reader as reader_mod
from code2vec_tpu.data.reader import EstimatorAction
from code2vec_tpu.vocab import Code2VecVocabs, Vocab, VocabType, special_words_for

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module", autouse=True)
def built_library():
    if native.load_library() is None:
        rc = subprocess.run(["make", "-C", os.path.join(REPO_ROOT, "cpp")],
                            capture_output=True, text=True)
        assert rc.returncode == 0, rc.stderr
        native._lib_checked = False  # re-probe after building
    assert native.load_library() is not None


@pytest.fixture()
def vocabs():
    def build(vocab_type, words):
        return Vocab(vocab_type, words,
                     special_words_for(vocab_type, separate_oov_and_pad=False))
    return Code2VecVocabs(
        token_vocab=build(VocabType.Token, ["foo", "bar", "baz", "n"]),
        path_vocab=build(VocabType.Path, ["111", "222", "-333"]),
        target_vocab=build(VocabType.Target, ["get|x", "set|y"]),
    )


LINES = [
    "get|x foo,111,bar bar,222,baz n,-333,foo",
    "set|y foo,111,foo",
    "unknown|target foo,111,bar",          # OOV target
    "get|x zzz,999,qqq",                   # all-OOV context: still valid
    "get|x ,,",                            # all-empty context: invalid
    "get|x",                               # no contexts at all
    "",                                    # empty line
    "get|x foo,111,bar  bar,222,baz",      # double space: empty field skipped
    "get|x malformed_no_commas",
    "get|x a,b,c,d,e extra,222,parts",     # >3 comma parts ignored
    "set|y foo,111,bar\n",                 # trailing newline kept by caller
    "\n",                                  # blank line (must still be a row)
]


def _python_parse(lines, vocabs, m, action):
    """Force the pure-Python path regardless of the native library."""
    lib = native._lib
    native._lib = None
    try:
        return reader_mod.parse_context_lines(lines, vocabs, m, action)
    finally:
        native._lib = lib


def test_parse_parity_all_fields(vocabs):
    m = 4
    action = EstimatorAction.Evaluate
    py = _python_parse(LINES, vocabs, m, action)
    nat = reader_mod.parse_context_lines(LINES, vocabs, m, action)
    np.testing.assert_array_equal(py.source_token_indices,
                                  nat.source_token_indices)
    np.testing.assert_array_equal(py.path_indices, nat.path_indices)
    np.testing.assert_array_equal(py.target_token_indices,
                                  nat.target_token_indices)
    np.testing.assert_array_equal(py.context_valid_mask,
                                  nat.context_valid_mask)
    np.testing.assert_array_equal(py.target_index, nat.target_index)
    assert py.target_strings == nat.target_strings


def test_parse_parity_fuzz(vocabs):
    rng = np.random.default_rng(0)
    tokens = ["foo", "bar", "baz", "n", "zzz", ""]
    paths = ["111", "222", "-333", "999", ""]
    targets = ["get|x", "set|y", "nope", ""]
    lines = []
    for _ in range(300):
        n_ctx = int(rng.integers(0, 8))
        parts = [str(rng.choice(targets))]
        for _ in range(n_ctx):
            parts.append(",".join([str(rng.choice(tokens)),
                                   str(rng.choice(paths)),
                                   str(rng.choice(tokens))]))
        lines.append(" ".join(parts))
    m = 5
    action = EstimatorAction.Train
    py = _python_parse(lines, vocabs, m, action)
    nat = reader_mod.parse_context_lines(lines, vocabs, m, action)
    for field in ("source_token_indices", "path_indices",
                  "target_token_indices", "context_valid_mask",
                  "target_index"):
        np.testing.assert_array_equal(getattr(py, field), getattr(nat, field),
                                      err_msg=field)


def test_native_pack_matches_python_pack(tmp_path, vocabs):
    c2v = tmp_path / "data.test.c2v"
    c2v.write_text("\n".join(LINES) + "\n")
    m = 4
    native_out = packed.pack_c2v(str(c2v), vocabs, m,
                                 out_path=str(tmp_path / "native.c2vb"))
    lib = native._lib
    native._lib = None
    try:
        python_out = packed.pack_c2v(str(c2v), vocabs, m,
                                     out_path=str(tmp_path / "python.c2vb"))
    finally:
        native._lib = lib
    with open(native_out, "rb") as f:
        native_bytes = f.read()
    with open(python_out, "rb") as f:
        python_bytes = f.read()
    assert native_bytes == python_bytes
    with open(native_out + ".targets") as f:
        native_targets = f.read()
    with open(python_out + ".targets") as f:
        python_targets = f.read()
    assert native_targets == python_targets


def test_from_tables_and_parse_rows_match_vocab_tables(vocabs):
    """The worker-side table constructor (raw bytes->id dicts, no vocab
    object) and the interleaved-row parse entry point must agree with
    the vocab-built tables + separate-array parse."""
    m = 4
    ref = native.NativeTables(vocabs)
    worker = native.NativeTables.from_tables(
        {w.encode(): i for w, i in vocabs.token_vocab.word_to_index.items()},
        {w.encode(): i for w, i in vocabs.path_vocab.word_to_index.items()},
        {w.encode(): i for w, i in vocabs.target_vocab.word_to_index.items()},
        token_pad=vocabs.token_vocab.pad_index,
        token_oov=vocabs.token_vocab.oov_index,
        path_pad=vocabs.path_vocab.pad_index,
        path_oov=vocabs.path_vocab.oov_index,
        target_oov=vocabs.target_vocab.oov_index)
    lines = [ln.rstrip("\n") for ln in LINES]
    blob = ("\n".join(lines) + "\n").encode()
    n = len(lines)
    src, pth, tgt, label, _mask = ref.parse_blob(blob, n, m)
    rec = worker.parse_rows_blob(blob, n, m)
    np.testing.assert_array_equal(rec[:, 0], label)
    np.testing.assert_array_equal(rec[:, 1:1 + m], src)
    np.testing.assert_array_equal(rec[:, 1 + m:1 + 2 * m], pth)
    np.testing.assert_array_equal(rec[:, 1 + 2 * m:], tgt)


def test_native_histogram_range_matches_python(tmp_path):
    """`c2v_histogram_range` (the map step of the multiprocess histogram
    build) must reproduce the Python serial loop exactly, including the
    skip rules for empty names/fields and non-3-piece contexts."""
    from code2vec_tpu.data import preprocess as pp
    raw = tmp_path / "raw.txt"
    raw.write_text(
        "get|x foo,111,bar foo,111,bar bar,222,baz\n"
        "\n"                                  # blank line skipped
        " t,1,t\n"                            # empty name: line skipped
        "set|y  foo,111,foo ,, a,b\n"         # empty field, 3-empty, 2-piece
        "get|x a,b,c,d e,111,f\n"             # 4-piece skipped, 3-piece kept
        "solo\n"
        "last f,222,g")                       # unterminated final line
    serial = pp.build_histograms(str(raw))
    assert native.has_histogram_range()
    sharded = pp.build_histograms(str(raw), num_workers=2)
    assert tuple(sharded) == tuple(serial)


def test_fused_pack_native_matches_python(tmp_path, vocabs):
    """pack_raw with the native worker core vs the pure-Python memo path:
    identical `.c2vb` bytes and sidecar (sampling engaged)."""
    raw = tmp_path / "raw.txt"
    rng = np.random.default_rng(3)
    tokens = ["foo", "bar", "baz", "n", "zzz"]
    paths = ["111", "222", "-333", "999"]
    with open(raw, "w") as f:
        for i in range(200):
            k = int(rng.integers(1, 9))  # m=4 -> plenty over budget
            ctxs = [",".join([str(rng.choice(tokens)), str(rng.choice(paths)),
                              str(rng.choice(tokens))]) for _ in range(k)]
            f.write(f"get|x {' '.join(ctxs)}\n")
    w2c = {"foo": 5, "bar": 4, "baz": 3, "n": 2}
    p2c = {"111": 5, "222": 4, "-333": 3}
    native_out = str(tmp_path / "native.c2vb")
    packed.pack_raw(str(raw), native_out, vocabs, w2c, p2c, 4, seed=11,
                    num_workers=1)
    lib = native._lib
    native._lib = None
    try:
        python_out = str(tmp_path / "python.c2vb")
        packed.pack_raw(str(raw), python_out, vocabs, w2c, p2c, 4, seed=11,
                        num_workers=1)
    finally:
        native._lib = lib
    with open(native_out, "rb") as a, open(python_out, "rb") as b:
        assert a.read() == b.read()
    with open(native_out + ".targets", "rb") as a, \
            open(python_out + ".targets", "rb") as b:
        assert a.read() == b.read()


def test_packed_dataset_roundtrip_native(tmp_path, vocabs):
    c2v = tmp_path / "data.train.c2v"
    c2v.write_text("\n".join(LINES) + "\n")
    out = packed.pack_c2v(str(c2v), vocabs, 4)
    ds = packed.PackedDataset(out, vocabs)
    batches = list(ds.iter_batches(2, EstimatorAction.Train, num_epochs=1))
    # valid train rows: known target AND >=1 valid context
    total = sum(b.num_valid for b in batches)
    assert total == 4  # lines 0,1,7,9 survive; ragged tail dropped -> pairs
