"""Continuous-training pipeline suite (code2vec_tpu/pipeline/):
journaled manifest resume, the SIGKILL-at-every-stage-boundary chaos
matrix, the shadow-eval quality gate (verdict matrix + exported
metrics), the promote/retrieval-refresh fleet drivers, the
retrieval-index remount plumbing, delta ingest against a frozen vocab,
and the live-traffic sampler.

Fast tests run in tier-1 on scripted stages/stubs; the subprocess kill
matrix and the end-to-end fleet promotion drill (real Supervisor
subprocesses running fake-model replicas) are marked `slow` and run
via scripts/run_chaos.sh with their own budget.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from code2vec_tpu import obs
from code2vec_tpu.config import Config
from code2vec_tpu.pipeline.manifest import (
    PipelineManifest, PipelineStateError,
)
from code2vec_tpu.pipeline.shadow_eval import (
    GateBars, gate_verdict, sample_traffic, topk_agreement,
)
from code2vec_tpu.pipeline.stages import (
    GateRefused, PipelineContext, PromoteFailed, StageFailed,
    StageSkipped, run_ingest, run_promote,
)
from code2vec_tpu.pipeline.supervisor import PipelineSupervisor
from code2vec_tpu.utils.faults import FAULT_EXIT_CODE, FaultInjected
from code2vec_tpu.utils import faults

from test_serving import FAKE_EXTRACTOR, _counter_value

pytestmark = pytest.mark.pipeline

HERE = os.path.dirname(os.path.abspath(__file__))
PIPELINE_CHILD = os.path.join(HERE, "chaos_pipeline_child.py")
FLEET_HOST = os.path.join(HERE, "chaos_fleet_host.py")


def _gauge_value(name, **labels):
    fams = obs.default_registry().collect()
    key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
    child = fams.get(name, {}).get(key)
    return child.value if child is not None else None


# ------------------------------------------------------------ manifest


def test_manifest_create_commit_and_resume(tmp_path):
    m = PipelineManifest.load_or_create(str(tmp_path), "fp1",
                                        ["a", "b"])
    assert m.stage("a") is None and m.terminal is None
    m.commit_stage("a", {"x": 1}, duration_s=0.5)
    # a fresh load sees the committed stage (the resume path)
    m2 = PipelineManifest.load_or_create(str(tmp_path), "fp1",
                                         ["a", "b"])
    assert m2.stage("a")["outputs"] == {"x": 1}
    assert m2.stage("a")["status"] == "committed"
    assert m2.stage("b") is None
    m2.set_terminal("committed", {"ok": True})
    m3 = PipelineManifest.load_or_create(str(tmp_path), "fp1",
                                         ["a", "b"])
    assert m3.terminal["outcome"] == "committed"
    # the journal recorded the transitions, newest last
    events = [e["event"] for e in m3.data["journal"]]
    assert events[-1] == "terminal"


def test_manifest_refuses_different_run_inputs(tmp_path):
    PipelineManifest.load_or_create(str(tmp_path), "fp1", ["a"])
    with pytest.raises(PipelineStateError, match="different inputs"):
        PipelineManifest.load_or_create(str(tmp_path), "fp2", ["a"])


def test_manifest_refuses_future_schema(tmp_path):
    m = PipelineManifest.load_or_create(str(tmp_path), "fp1", ["a"])
    m.data["schema_version"] = 99
    m._write()
    with pytest.raises(PipelineStateError, match="schema_version"):
        PipelineManifest.load_or_create(str(tmp_path), "fp1", ["a"])


# ------------------------------------------- shadow-eval comparator


class _Res:
    """Scripted ModelEvaluationResults stand-in."""

    def __init__(self, top1, topk, f1, loss=1.0):
        self.topk_acc = np.array([top1, topk])
        self.subtoken_f1 = f1
        self.subtoken_precision = f1
        self.subtoken_recall = f1
        self.loss = loss


def test_gate_passes_better_and_equal_candidates():
    inc = _Res(0.40, 0.60, 0.50)
    for cand in (_Res(0.45, 0.65, 0.55),   # better
                 _Res(0.40, 0.60, 0.50),   # equal
                 _Res(0.395, 0.595, 0.495)):  # within the 0.01 bar
        v = gate_verdict(inc, cand, bars=GateBars())
        assert v["passed"], v["reasons"]
    assert _gauge_value("pipeline_gate_top1_delta") is not None


def test_gate_refuses_worse_than_bar_with_named_metric():
    inc = _Res(0.40, 0.60, 0.50)
    v = gate_verdict(inc, _Res(0.40, 0.60, 0.44), bars=GateBars())
    assert not v["passed"]
    assert any("f1 regressed" in r for r in v["reasons"])
    assert v["numbers"]["f1_delta"] == pytest.approx(-0.06)
    # the refusal is visible from a scrape alone
    assert _gauge_value("pipeline_gate_f1_delta") == \
        pytest.approx(-0.06)
    assert _counter_value("pipeline_gate_total", verdict="fail") >= 1


def test_gate_refuses_nan_poisoned_candidate_fail_closed():
    inc = _Res(0.40, 0.60, 0.50)
    v = gate_verdict(inc, _Res(float("nan"), 0.60, 0.50),
                     bars=GateBars())
    assert not v["passed"]
    assert any("non-finite" in r for r in v["reasons"])
    # NaN loss alone also refuses (the metrics can look fine while the
    # model is diverging)
    v = gate_verdict(inc, _Res(0.41, 0.61, 0.51, loss=float("nan")),
                     bars=GateBars())
    assert not v["passed"]


def test_gate_agreement_bar_only_when_traffic_was_replayed():
    inc = _Res(0.40, 0.60, 0.50)
    cand = _Res(0.40, 0.60, 0.50)
    low = {"samples": 50, "topk_agreement": 0.5,
           "top1_agreement": 0.5}
    v = gate_verdict(inc, cand, agreement=low, bars=GateBars())
    assert not v["passed"]
    assert any("agreement" in r for r in v["reasons"])
    assert _gauge_value("pipeline_gate_topk_agreement") == \
        pytest.approx(0.5)
    # no traffic -> the agreement bar cannot trip
    v = gate_verdict(inc, cand, agreement={"samples": 0,
                                           "topk_agreement": None,
                                           "top1_agreement": None},
                     bars=GateBars())
    assert v["passed"]


class _PredModel:
    def __init__(self, words_per_line):
        self._words = words_per_line

    def predict(self, lines, batch_size=None, with_code_vectors=False):
        class _R:
            def __init__(self, words):
                self.topk_predicted_words = words
        return [_R(self._words[i % len(self._words)])
                for i in range(len(lines))]


def test_topk_agreement_scripted_models():
    lines = ["m1 a,P,b", "m2 c,P,d"]
    same = _PredModel([["x", "y", "z"]])
    assert topk_agreement(same, same, lines)["topk_agreement"] == 1.0
    disjoint = _PredModel([["p", "q", "r"]])
    out = topk_agreement(same, disjoint, lines)
    assert out["topk_agreement"] == 0.0
    assert out["top1_agreement"] == 0.0
    half = _PredModel([["x", "y", "w"]])
    out = topk_agreement(same, half, lines)
    assert out["topk_agreement"] == pytest.approx(2 / 3)
    assert out["top1_agreement"] == 1.0
    assert topk_agreement(same, same, [])["topk_agreement"] is None


def test_sample_traffic_deterministic_and_bounded():
    lines = [f"m{i} a,P,b" for i in range(100)] + ["", "  "]
    a = sample_traffic(lines, 10, seed=7)
    b = sample_traffic(lines, 10, seed=7)
    assert a == b and len(a) == 10
    assert sample_traffic(lines, 1000, seed=7) == \
        [ln for ln in lines if ln.strip()]
    # 0 disables the replay (gate on the accuracy harness alone)
    assert sample_traffic(lines, 0, seed=7) == []


# --------------------------------------------------- supervisor core


def _scripted_stages(ledger, overrides=None):
    overrides = overrides or {}

    def make(name):
        def body(ctx):
            if name in overrides:
                return overrides[name](ctx)
            ledger.append(name)
            return {"stage": name}
        return (name, body)

    return [make(n) for n in ("ingest", "finetune", "export",
                              "shadow_eval", "promote",
                              "retrieval_refresh")]


def _cfg(tmp_path, sub="pipe", **kw):
    return Config(pipeline=True,
                  pipeline_dir=str(tmp_path / sub),
                  verbose_mode=0, **kw)


def test_supervisor_runs_all_stages_once_and_is_idempotent(tmp_path):
    ledger = []
    config = _cfg(tmp_path)
    sup = PipelineSupervisor(config, stages=_scripted_stages(ledger),
                             log=lambda m: None)
    assert sup.run() == 0
    assert ledger == ["ingest", "finetune", "export", "shadow_eval",
                      "promote", "retrieval_refresh"]
    assert sup.manifest.terminal["outcome"] == "committed"
    # rerun of a committed manifest re-reports without re-driving
    sup2 = PipelineSupervisor(config,
                              stages=_scripted_stages(ledger),
                              log=lambda m: None)
    assert sup2.run() == 0
    assert len(ledger) == 6


def test_supervisor_resumes_from_last_committed_at_every_boundary(
        tmp_path):
    """THE resume law, in process: arm `pipeline_stage@N=raise` for
    every N (two boundary crossings per stage), crash there, rerun
    with faults disarmed — the rerun completes, committed stages never
    re-ran, and every kill matrix converges to the same terminal
    manifest."""
    names = ["ingest", "finetune", "export", "shadow_eval", "promote",
             "retrieval_refresh"]
    # baseline outputs to converge to
    base_ledger = []
    base_cfg = _cfg(tmp_path, "baseline")
    PipelineSupervisor(base_cfg, stages=_scripted_stages(base_ledger),
                       log=lambda m: None).run()
    baseline = json.loads(open(os.path.join(
        base_cfg.pipeline_dir, "pipeline_manifest.json")).read())
    try:
        for n in range(1, 2 * len(names) + 1):
            ledger = []
            config = _cfg(tmp_path, f"kill{n}")
            faults.reset(f"pipeline_stage@{n}=raise")
            sup = PipelineSupervisor(
                config, stages=_scripted_stages(ledger),
                log=lambda m: None)
            with pytest.raises(FaultInjected):
                sup.run()
            committed_at_kill = [s for s in names
                                 if sup.manifest.stage(s)]
            # hit 2k-1 = stage k's start, hit 2k = its commit window:
            # exactly floor((n-1)/2) stages were committed
            assert len(committed_at_kill) == (n - 1) // 2
            faults.reset(None)
            ledger_at_kill = list(ledger)
            sup2 = PipelineSupervisor(
                config, stages=_scripted_stages(ledger),
                log=lambda m: None)
            assert sup2.run() == 0
            # committed stages never re-ran
            for s in committed_at_kill:
                assert ledger.count(s) == 1
            # a stage killed AFTER its work but BEFORE its commit ran
            # again (idempotent), everything else exactly once
            for s in names:
                assert 1 <= ledger.count(s) <= 2
                if s not in ledger_at_kill:
                    assert ledger.count(s) == 1
            # convergence: same terminal manifest as the baseline
            final = sup2.manifest.data
            assert final["terminal"]["outcome"] == "committed"
            assert {k: v["outputs"] for k, v in
                    final["stages"].items()} == \
                   {k: v["outputs"] for k, v in
                    baseline["stages"].items()}
    finally:
        faults.reset(None)


def test_gate_refusal_is_terminal_with_numbers_everywhere(tmp_path):
    numbers = {"f1_delta": -0.2, "top1_delta": -0.1,
               "topk_agreement": 0.4}
    ledger = []

    def refuse(ctx):
        raise GateRefused("shadow_eval", "f1 regressed", numbers)

    config = _cfg(tmp_path)
    stages = _scripted_stages(ledger, {"shadow_eval": refuse})
    sup = PipelineSupervisor(config, stages=stages, log=lambda m: None)
    assert sup.run() == 1
    # terminal verdict in the manifest, numbers included
    term = sup.manifest.terminal
    assert term["outcome"] == "gate_refused"
    assert term["detail"]["f1_delta"] == -0.2
    # the incumbent was never touched: promote never ran
    assert "promote" not in ledger
    assert sup.manifest.stage("promote") is None
    # gate numbers in the heartbeat (the runbook's first stop)
    hb = json.loads(open(sup.heartbeat_path).read())
    assert hb["status"] == "gate_refused"
    assert hb["gate"]["f1_delta"] == -0.2
    # a flight dump was written (immediate incident)
    assert any(f.startswith("flight-") for f in
               os.listdir(config.pipeline_dir))
    # rerun converges to the same refusal without re-driving stages
    before = len(ledger)
    sup2 = PipelineSupervisor(config, stages=stages,
                              log=lambda m: None)
    assert sup2.run() == 1
    assert len(ledger) == before
    assert _counter_value("pipeline_runs_total",
                          outcome="gate_refused") >= 1


def test_promote_failure_is_terminal_rollback_recorded(tmp_path):
    def fail(ctx):
        raise PromoteFailed("promote", "fleet rollout rolled_back",
                            outcome="rolled_back",
                            numbers={"swap_error": "host default-1"})

    config = _cfg(tmp_path)
    sup = PipelineSupervisor(
        config, stages=_scripted_stages([], {"promote": fail}),
        log=lambda m: None)
    assert sup.run() == 1
    term = sup.manifest.terminal
    assert term["outcome"] == "promote_failed"
    assert term["detail"]["rollout_outcome"] == "rolled_back"


def test_stage_failure_is_retryable_not_terminal(tmp_path):
    attempts = []

    def flaky(ctx):
        attempts.append(1)
        if len(attempts) == 1:
            raise StageFailed("finetune", "transient: child OOM")
        return {"ok": True}

    config = _cfg(tmp_path)
    stages = _scripted_stages([], {"finetune": flaky})
    sup = PipelineSupervisor(config, stages=stages, log=lambda m: None)
    assert sup.run() == 1
    assert sup.manifest.terminal is None  # NOT terminal
    assert sup.manifest.stage("finetune") is None
    sup2 = PipelineSupervisor(config, stages=stages,
                              log=lambda m: None)
    assert sup2.run() == 0
    assert len(attempts) == 2
    assert sup2.manifest.stage("finetune")["outputs"] == {"ok": True}


def test_unexpected_exception_is_a_recorded_stage_failure(tmp_path):
    """A stage body raising OUTSIDE the StageFailed family (corrupt
    artifact ValueError, disk-full OSError) must not leave a dead
    supervisor behind a forever-'running' heartbeat: it is a failed,
    retryable attempt recorded in heartbeat + metrics + flight."""
    def boom(ctx):
        raise ValueError("release_meta.json: tampered")

    config = _cfg(tmp_path)
    stages = _scripted_stages([], {"export": boom})
    sup = PipelineSupervisor(config, stages=stages, log=lambda m: None)
    assert sup.run() == 1
    assert sup.manifest.terminal is None       # retryable, not a verdict
    assert sup.manifest.stage("export") is None
    hb = json.loads(open(sup.heartbeat_path).read())
    assert hb["status"] == "error"
    assert "ValueError" in hb["error"]
    assert _counter_value("pipeline_stages_total", stage="export",
                          outcome="failed") >= 1


def test_skipped_stage_committed_as_skipped(tmp_path):
    def skip(ctx):
        raise StageSkipped("no fleet configured")

    config = _cfg(tmp_path)
    sup = PipelineSupervisor(
        config, stages=_scripted_stages([], {"promote": skip}),
        log=lambda m: None)
    assert sup.run() == 0
    rec = sup.manifest.stage("promote")
    assert rec["status"] == "skipped"
    assert "no fleet" in rec["outputs"]["reason"]


def test_supervisor_refuses_resumed_dir_with_different_inputs(
        tmp_path):
    config = _cfg(tmp_path)
    PipelineSupervisor(config, stages=_scripted_stages([]),
                       log=lambda m: None)
    changed = _cfg(tmp_path, pipeline_finetune_epochs=7)
    with pytest.raises(PipelineStateError, match="different inputs"):
        PipelineSupervisor(changed, stages=_scripted_stages([]),
                           log=lambda m: None)


# --------------------------------------------- promote stage (stub fleet)


class _ScriptedRouter:
    """Stub fleet router: POST /admin/reload records the payload and
    arms a scripted swap-state sequence; GET /fleet steps through it."""

    def __init__(self, states, error=None):
        import http.server
        self.reloads = []
        self.states = list(states)
        self.error = error
        self._seq = []
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(length) or b"{}")
                outer.reloads.append((self.path, payload))
                outer._seq = list(outer.states)
                self._reply(202, {"accepted": True})

            def do_GET(self):
                if not outer.reloads:
                    self._reply(200, {"swap": {"state": "idle",
                                               "target": None}})
                    return
                state = (outer._seq.pop(0) if len(outer._seq) > 1
                         else outer._seq[0])
                artifact = outer.reloads[-1][1]["artifact"]
                fp = "fp-" + os.path.basename(artifact)
                self._reply(200, {
                    "swap": {"state": state, "target": artifact,
                             "target_fingerprint": fp,
                             "error": (outer.error if state in
                                       ("failed", "rolled_back")
                                       else None)},
                    "models": {"default": {
                        "fingerprints": [fp],
                        "mixed_fingerprints": False}},
                })

        self.httpd = http.server.HTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _promote_ctx(tmp_path, port, artifact):
    config = _cfg(tmp_path, pipeline_fleet=f"127.0.0.1:{port}",
                  pipeline_promote_timeout_s=15.0)
    manifest = PipelineManifest.load_or_create(
        config.pipeline_dir, "fp", ["export", "promote"])
    manifest.commit_stage("export", {"artifact": artifact,
                                     "fingerprint":
                                     "fp-" + os.path.basename(artifact)})
    return PipelineContext(config, manifest, config.pipeline_dir,
                           lambda m: None)


def test_promote_stage_commits_through_scripted_fleet(tmp_path):
    router = _ScriptedRouter(["canary", "rolling", "committed"])
    try:
        ctx = _promote_ctx(tmp_path, router.port, "/artifacts/v2")
        out = run_promote(ctx)
        assert out["outcome"] == "committed"
        assert out["fingerprint"] == "fp-v2"
        assert router.reloads[0][1] == {"artifact": "/artifacts/v2",
                                        "model": "default"}
        assert _counter_value("pipeline_promotions_total",
                              outcome="committed") >= 1
    finally:
        router.close()


def test_promote_stage_rolled_back_is_promote_failed(tmp_path):
    router = _ScriptedRouter(["canary", "rolling", "rolled_back"],
                             error="default-1: replica rejected")
    try:
        ctx = _promote_ctx(tmp_path, router.port, "/artifacts/v3")
        with pytest.raises(PromoteFailed) as e:
            run_promote(ctx)
        assert e.value.outcome == "rolled_back"
        assert "incumbent is serving everywhere" in str(e.value)
        assert _counter_value("pipeline_promotions_total",
                              outcome="rolled_back") >= 1
    finally:
        router.close()


def test_promote_stage_skips_without_fleet(tmp_path):
    ctx = _promote_ctx(tmp_path, 1, "/artifacts/v2")
    ctx.config.pipeline_fleet = ""
    with pytest.raises(StageSkipped, match="pipeline_fleet"):
        run_promote(ctx)


def test_refresh_reload_carries_retrieval_index(tmp_path):
    from code2vec_tpu.pipeline.stages import drive_fleet_swap
    router = _ScriptedRouter(["canary", "committed"])
    try:
        ctx = _promote_ctx(tmp_path, router.port, "/artifacts/v2")
        result = drive_fleet_swap(ctx, "retrieval_refresh",
                                  "/artifacts/v2",
                                  retrieval_index="/idx/new")
        assert result["swap"]["state"] == "committed"
        assert router.reloads[0][1]["retrieval_index"] == "/idx/new"
    finally:
        router.close()


# ------------------------------------ retrieval-index remount plumbing


class _SwapStubModel:
    def __init__(self, fp, topk=3):
        self._fp = fp
        self.topk = topk
        self.context_buckets = (4, 8)

    def model_fingerprint(self):
        return self._fp

    def smoke_schema(self):
        return {"topk": self.topk, "code_vector_size": 8,
                "scores_finite": True}


class _SwapStubServer:
    def __init__(self):
        self.config = Config(verbose_mode=0)
        self.log = lambda m: None
        self.model = _SwapStubModel("fp-old")
        self.model_fingerprint = "fp-old"
        self.retrieval = None
        self.swapped = []

    def swap_model(self, new_model, retrieval_handle=None):
        self.swapped.append((new_model, retrieval_handle))
        return new_model.model_fingerprint()


def _wait_swap(manager, timeout=10.0):
    deadline = time.time() + timeout
    while manager.status()["state"] in ("loading", "validating"):
        if time.time() > deadline:
            raise AssertionError(f"swap wedged: {manager.status()}")
        time.sleep(0.01)
    return manager.status()


def test_swap_manager_mounts_index_atomically_with_flip():
    from code2vec_tpu.serving.swap import SwapManager

    server = _SwapStubServer()
    mounted = []

    class _Handle:
        fingerprint = "fp-new"
        attached = True

    def mount(path, new_model):
        mounted.append((path, new_model.model_fingerprint()))
        return _Handle()

    manager = SwapManager(server,
                          build_model=lambda d: _SwapStubModel("fp-new"),
                          mount_index=mount)
    manager.request_reload("/artifacts/v2", retrieval_index="/idx/new")
    status = _wait_swap(manager)
    assert status["state"] == "ready"
    assert status["retrieval_index"] == "/idx/new"
    # the index was fingerprint-checked against the NEW model and
    # handed to swap_model for the atomic flip
    assert mounted == [("/idx/new", "fp-new")]
    [(model, handle)] = server.swapped
    assert model.model_fingerprint() == "fp-new"
    assert handle.fingerprint == "fp-new"


def test_swap_manager_mount_failure_fails_whole_swap():
    from code2vec_tpu.serving.swap import SwapManager

    server = _SwapStubServer()

    def mount(path, new_model):
        raise ValueError("index fingerprint mismatch: fp-stale")

    manager = SwapManager(server,
                          build_model=lambda d: _SwapStubModel("fp-new"),
                          mount_index=mount)
    manager.request_reload("/artifacts/v2", retrieval_index="/idx/bad")
    status = _wait_swap(manager)
    assert status["state"] == "failed"
    assert "mismatch" in status["error"]
    # old model + old index untouched: nothing swapped
    assert server.swapped == []


def test_plain_swap_without_index_keeps_stale_index_policy():
    """A reload WITHOUT a riding index still runs the PR-10 refuse
    policy against a mounted mismatching index."""
    from code2vec_tpu.serving.swap import SwapManager

    server = _SwapStubServer()

    class _Mounted:
        fingerprint = "fp-old"
        attached = True

    server.retrieval = _Mounted()
    server.config.retrieval_swap_policy = "refuse"
    manager = SwapManager(server,
                          build_model=lambda d: _SwapStubModel("fp-new"))
    manager.request_reload("/artifacts/v2")
    status = _wait_swap(manager)
    assert status["state"] == "failed"
    assert "stale embedding space" in status["error"]
    assert server.swapped == []


def test_reload_target_info_roundtrip(tmp_path):
    from code2vec_tpu.serving.server import (
        RELOAD_TARGET_FILENAME, reload_target_info,
    )
    hb = tmp_path / "hb.json"
    config = Config(verbose_mode=0, heartbeat_file=str(hb))
    assert reload_target_info(config) is None
    target = tmp_path / RELOAD_TARGET_FILENAME
    target.write_text(json.dumps({"artifact": "/a/v2",
                                  "retrieval_index": "/idx/n"}))
    info = reload_target_info(config)
    assert info == {"artifact": "/a/v2", "retrieval_index": "/idx/n"}
    target.write_text(json.dumps({"artifact": "/a/v2"}))
    assert reload_target_info(config)["retrieval_index"] is None


def test_fleet_swap_driver_keys_on_retrieval_index(tmp_path):
    """A replica still showing the PROMOTE rollout's ready state for
    the SAME artifact (swap_retrieval_index None) must not satisfy a
    retrieval-refresh rollout carrying an index — the driver waits for
    the post-reload state."""
    from code2vec_tpu.serving.fleet.swap import FleetSwapDriver

    class _Host:
        id = "h0"

        def __init__(self):
            # stale state from the committed promote rollout
            self.swap_target = "/artifacts/v2"
            self.swap_state = "ready"
            self.swap_retrieval_index = None
            self.fingerprint = "fp-v2"
            self.reload_applied = False

    host = _Host()

    class _Control:
        class config:
            fleet_swap_timeout_s = 10.0

        flight = obs.default_flight_recorder()
        log = staticmethod(lambda m: None)

        def swap_hosts(self, model):
            return [host]

        def host_reload(self, h, artifact, retrieval_index=None,
                        traceparent=None):
            # apply DELAYED: the window where the stale promote state
            # is all the driver can see
            def later():
                time.sleep(0.4)
                h.reload_applied = True
                h.swap_retrieval_index = retrieval_index
            threading.Thread(target=later, daemon=True).start()
            return True, ""

        def host_fleet(self, h):
            return {"replicas": [{
                "model_fingerprint": h.fingerprint,
                "swap_state": h.swap_state,
                "swap_target": h.swap_target,
                "swap_retrieval_index": h.swap_retrieval_index,
                "draining": False}]}

        def rollback_target(self, model):
            return "/artifacts/v1"

        def set_artifact(self, model, artifact,
                         retrieval_index=None):
            pass

    driver = FleetSwapDriver(_Control(), poll_interval_s=0.05)
    driver.request("/artifacts/v2", retrieval_index="/idx/new")
    deadline = time.time() + 10
    while driver.status()["state"] in ("canary", "rolling"):
        assert time.time() < deadline, driver.status()
        time.sleep(0.02)
    assert driver.status()["state"] == "committed"
    # convergence waited for the reload to actually land
    assert host.reload_applied


# --------------------------------------------------- ingest (real pack)


def test_ingest_packs_delta_against_frozen_vocab_with_oov(
        tmp_path, tiny_vocabs):
    ckpt = tmp_path / "ckpt_iter3"
    ckpt.mkdir()
    (ckpt / "code2vec_manifest.json").write_text("{}")
    (ckpt / "code2vec_meta.json").write_text(
        json.dumps({"epoch": 3}))
    tiny_vocabs.save(str(ckpt / "dictionaries.bin"))
    raw = tmp_path / "delta.raw.txt"
    raw.write_text("get|name foo,P1,bar baz,P2,qux\n"
                   "brandnewtarget foo,P1,bar\n"          # OOV target
                   "run nope,P9,bar\n")                   # OOV context
    config = Config(verbose_mode=0, max_contexts=4,
                    pipeline_raw=str(raw),
                    model_load_path=str(tmp_path / "ckpt"))
    ctx = PipelineContext(config, None, str(tmp_path / "run"),
                          lambda m: None)
    os.makedirs(ctx.run_dir, exist_ok=True)
    out = run_ingest(ctx)
    assert out["rows"] == 3
    assert out["train_rows"] == 2  # OOV target row is untrainable
    assert out["incumbent_ckpt"] == str(ckpt)
    assert out["target_oov_rate"] == pytest.approx(1 / 3)
    assert 0 < out["context_oov_rate"] < 1
    assert os.path.isfile(out["packed"])
    assert os.path.isfile(out["packed"] + ".targets")
    assert _gauge_value("pipeline_ingest_oov_rate",
                        kind="target") == pytest.approx(1 / 3)
    # re-run is idempotent (atomic pack overwrite)
    out2 = run_ingest(ctx)
    assert out2["rows"] == 3


def test_ingest_accumulates_manifest_and_finetune_uses_it(
        tmp_path, tiny_vocabs):
    """Manifest mode: ingest APPENDS each delta shard to the corpus
    manifest (idempotent under re-run), and fine-tune hands the child
    trainer --train_corpus_manifest instead of the delta alone."""
    from code2vec_tpu.pipeline.stages import run_finetune
    ckpt = tmp_path / "ckpt_iter3"
    ckpt.mkdir()
    (ckpt / "code2vec_manifest.json").write_text("{}")
    (ckpt / "code2vec_meta.json").write_text(json.dumps({"epoch": 3}))
    tiny_vocabs.save(str(ckpt / "dictionaries.bin"))
    raw = tmp_path / "delta.raw.txt"
    raw.write_text("get|name foo,P1,bar baz,P2,qux\n"
                   "get|name foo,P1,bar\n"
                   "run nope,P9,bar\n")
    manifest = tmp_path / "corpus.manifest.json"
    config = Config(verbose_mode=0, max_contexts=4,
                    pipeline_raw=str(raw),
                    model_load_path=str(tmp_path / "ckpt"),
                    train_corpus_manifest=str(manifest))
    ctx = PipelineContext(config, None, str(tmp_path / "run"),
                          lambda m: None)
    os.makedirs(ctx.run_dir, exist_ok=True)
    out = run_ingest(ctx)
    assert out["manifest"] == str(manifest)
    assert out["manifest_shards"] == 1
    assert out["manifest_rows"] == out["rows"] == 3
    # re-run: the same shard path is NOT appended twice
    out2 = run_ingest(ctx)
    assert out2["manifest_shards"] == 1
    # a later pipeline run (fresh run dir -> fresh shard) accumulates
    ctx2 = PipelineContext(config, None, str(tmp_path / "run2"),
                           lambda m: None)
    os.makedirs(ctx2.run_dir, exist_ok=True)
    out3 = run_ingest(ctx2)
    assert out3["manifest_shards"] == 2
    assert out3["manifest_rows"] == 6

    class _Rec:
        @staticmethod
        def stage(name):
            return {"outputs": out3}

    ctx2.manifest = _Rec()
    captured = {}

    def fake_run_cli(argv, stage, name):
        captured["argv"] = list(argv)
        cand = tmp_path / "run2" / "candidate" / "ckpt_iter4"
        cand.mkdir(parents=True, exist_ok=True)
        (cand / "code2vec_manifest.json").write_text("{}")
        (cand / "code2vec_meta.json").write_text(
            json.dumps({"epoch": 4}))

    ctx2.run_cli = fake_run_cli
    ft = run_finetune(ctx2)
    argv = captured["argv"]
    assert "--train_corpus_manifest" in argv
    assert argv[argv.index("--train_corpus_manifest") + 1] == \
        str(manifest)
    assert ft["candidate_ckpt"].endswith("ckpt_iter4")


def test_ingest_refuses_untrainable_delta(tmp_path, tiny_vocabs):
    ckpt = tmp_path / "ckpt_iter1"
    ckpt.mkdir()
    (ckpt / "code2vec_manifest.json").write_text("{}")
    (ckpt / "code2vec_meta.json").write_text(json.dumps({"epoch": 1}))
    tiny_vocabs.save(str(ckpt / "dictionaries.bin"))
    raw = tmp_path / "delta.raw.txt"
    raw.write_text("unknown1 foo,P1,bar\nunknown2 bar,P2,foo\n")
    config = Config(verbose_mode=0, max_contexts=4,
                    pipeline_raw=str(raw),
                    model_load_path=str(tmp_path / "ckpt"))
    ctx = PipelineContext(config, None, str(tmp_path / "run"),
                          lambda m: None)
    with pytest.raises(StageFailed, match="no trainable rows"):
        run_ingest(ctx)


# ------------------------------------------------------ traffic sampler


def test_traffic_sampler_every_nth_bounded_and_atomic(tmp_path):
    from code2vec_tpu.serving.traffic import TrafficSampler
    path = str(tmp_path / "traffic.txt")
    sampler = TrafficSampler(path, every=2, cap=8)
    for i in range(10):
        sampler.record([f"m{i} a,P,b"])
    sampler.flush()
    lines = open(path).read().splitlines()
    # requests 2,4,6,8,10 sampled (every 2nd)
    assert lines == [f"m{i} a,P,b" for i in (1, 3, 5, 7, 9)]
    # the cap bounds the ring: oldest evicted
    for i in range(10, 40):
        sampler.record([f"m{i} a,P,b"])
    sampler.flush()
    lines = open(path).read().splitlines()
    assert len(lines) == 8
    assert lines[-1] == "m39 a,P,b"
    assert sampler.status()["entries"] == 8
    assert _counter_value("serving_traffic_sampled_total") >= 13


# ----------------------------------------------------------- CLI/config


def test_pipeline_cli_parse_and_verify(tmp_path):
    from code2vec_tpu.cli import config_from_args
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    raw = tmp_path / "raw.txt"
    raw.write_text("x a,P,b\n")
    incumbent = tmp_path / "incumbent"
    incumbent.mkdir()
    config = config_from_args([
        "pipeline", "--pipeline_dir", str(tmp_path / "run"),
        "--load", str(ckpt), "--pipeline_raw", str(raw),
        "--pipeline_incumbent", str(incumbent),
        "--test", str(tmp_path / "val.c2v"),
        "--pipeline_fleet", "127.0.0.1:8800",
        "--pipeline_gate_f1_drop", "0.02"])
    assert config.pipeline
    assert config.pipeline_gate_f1_drop == 0.02
    config.verify()
    # the subcommand demands its state dir
    with pytest.raises(SystemExit, match="pipeline_dir"):
        config_from_args(["pipeline", "--load", str(ckpt)])


@pytest.mark.parametrize("mutate, match", [
    (dict(pipeline_dir=None), "pipeline_dir"),
    (dict(model_load_path=None), "Must train or load"),
    (dict(pipeline_raw=None), "pipeline_raw"),
    (dict(pipeline_incumbent=None), "pipeline_incumbent"),
    (dict(test_data_path=""), "requires --test"),
    (dict(serve=True), "standalone"),
    (dict(train_data_path_prefix="/x"), "standalone"),
    (dict(export_artifact_path="/x"), "one-shot"),
    (dict(pipeline_finetune_epochs=0), "finetune_epochs"),
    (dict(pipeline_gate_min_agreement=2.0), "min_agreement"),
    (dict(pipeline_promote_timeout_s=0), "promote_timeout"),
])
def test_pipeline_verify_rejection_matrix(tmp_path, mutate, match):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    kwargs = dict(pipeline=True,
                  pipeline_dir=str(tmp_path / "run"),
                  model_load_path=str(ckpt),
                  pipeline_raw=str(tmp_path / "raw.txt"),
                  pipeline_incumbent=str(tmp_path / "inc"),
                  test_data_path=str(tmp_path / "val.c2v"),
                  verbose_mode=0)
    kwargs.update(mutate)
    with pytest.raises(ValueError, match=match):
        Config(**kwargs).verify()


def test_traffic_sample_knob_verify():
    with pytest.raises(ValueError, match="serve subcommand"):
        Config(verbose_mode=0, model_load_path="./m",
               serve_traffic_sample_file="/x").verify()
    with pytest.raises(ValueError, match="sample_every"):
        Config(verbose_mode=0, model_load_path="./m", serve=True,
               serve_traffic_sample_every=0).verify()


# -------------------------------------------- chaos drills (slow)


@pytest.mark.slow
@pytest.mark.chaos
def test_pipeline_sigkill_at_every_boundary_subprocess(tmp_path):
    """ROADMAP acceptance: SIGKILL (os._exit via the armed fault — no
    handlers, no cleanup) the REAL pipeline supervisor process at every
    stage boundary; the rerun resumes from the last committed stage and
    converges to the same terminal manifest, with no committed stage's
    work repeated."""
    def run_child(run_dir, ledger, faults_spec=None):
        env = dict(os.environ)
        env.pop("C2V_FAULTS", None)
        if faults_spec:
            env["C2V_FAULTS"] = faults_spec
        return subprocess.run(
            [sys.executable, PIPELINE_CHILD, run_dir, ledger],
            env=env, capture_output=True, timeout=120)

    names = ["ingest", "finetune", "export", "shadow_eval", "promote",
             "retrieval_refresh"]
    # baseline manifest to converge to
    base_dir = str(tmp_path / "baseline")
    base_ledger = str(tmp_path / "baseline.ledger")
    assert run_child(base_dir, base_ledger).returncode == 0
    baseline = json.loads(open(
        os.path.join(base_dir, "pipeline_manifest.json")).read())

    def norm_outputs(manifest, run_dir):
        # stage outputs carry absolute paths under the run dir; two
        # runs converge when they agree modulo that root
        return {k: {kk: (vv.replace(run_dir, "<run>")
                         if isinstance(vv, str) else vv)
                    for kk, vv in v["outputs"].items()}
                for k, v in manifest["stages"].items()}

    base_outputs = norm_outputs(baseline, base_dir)

    for n in range(1, 2 * len(names) + 1):
        run_dir = str(tmp_path / f"kill{n}")
        ledger = str(tmp_path / f"kill{n}.ledger")
        killed = run_child(run_dir, ledger,
                           faults_spec=f"pipeline_stage@{n}=exit")
        assert killed.returncode == FAULT_EXIT_CODE, (
            n, killed.returncode, killed.stderr[-500:])
        manifest = json.loads(open(
            os.path.join(run_dir, "pipeline_manifest.json")).read())
        committed_at_kill = set(manifest["stages"])
        assert len(committed_at_kill) == (n - 1) // 2
        # rerun, faults disarmed: completes and converges
        rerun = run_child(run_dir, ledger)
        assert rerun.returncode == 0, rerun.stderr[-500:]
        final = json.loads(open(
            os.path.join(run_dir, "pipeline_manifest.json")).read())
        assert final["terminal"]["outcome"] == "committed"
        assert norm_outputs(final, run_dir) == base_outputs
        counts = {s: 0 for s in names}
        for line in open(ledger).read().splitlines():
            counts[line] += 1
        for s in names:
            # committed-before-kill stages ran exactly once; the stage
            # killed in its commit window ran at most twice
            assert counts[s] == (1 if s in committed_at_kill
                                 else counts[s])
            assert 1 <= counts[s] <= 2
        # every stage's deterministic output exists exactly once
        for s in names:
            out = os.path.join(run_dir, f"out-{s}.txt")
            assert open(out).read() == f"{s}: deterministic output\n"


# ------------------------------------------ fleet promotion drill (slow)


@pytest.fixture()
def fake_extractor(tmp_path, monkeypatch):
    path = tmp_path / "fake-c2v-extract"
    path.write_text(FAKE_EXTRACTOR)
    path.chmod(0o755)
    monkeypatch.setenv("C2V_NATIVE_EXTRACTOR", str(path))
    monkeypatch.delenv("C2V_FAKE_NO_SERVER", raising=False)
    return str(path)


@pytest.fixture()
def run_fleet(tmp_path, fake_extractor):
    from code2vec_tpu.serving.fleet.control import ControlPlane
    from code2vec_tpu.serving.fleet.router import FleetRouter

    running = []

    def start(config, host_specs, artifacts=None):
        control = ControlPlane(config, host_specs, log=lambda m: None)
        for model, artifact in (artifacts or {}).items():
            control.set_initial_artifact(model, artifact)
        control.router = FleetRouter(config, control, host="127.0.0.1",
                                     port=0, log=lambda m: None)
        rc_holder = {}
        thread = threading.Thread(
            target=lambda: rc_holder.update(rc=control.run()),
            daemon=True)
        thread.start()
        running.append((control, thread))
        return control, thread, rc_holder

    yield start
    for control, thread in running:
        control.stop()
        thread.join(timeout=60)


@pytest.mark.slow
@pytest.mark.chaos
def test_pipeline_promotion_drill_on_real_fleet(tmp_path, run_fleet):
    """ROADMAP acceptance, end to end on real subprocesses: (1) a good
    candidate flows through the pipeline and the canary-first rollout
    lands its fingerprint on every replica of every host under client
    load with zero malformed/mixed responses; (2) a quality-regressed
    candidate is REFUSED at shadow-eval with the fleet untouched;
    (3) a candidate that fails mid-fleet-swap rolls the whole fleet
    back — terminal promote_failed, fleet back on the prior
    fingerprint."""
    from test_fleet import (
        _all_routable, _fleet_config, _host_overrides, _post,
        _replica_overrides, _wait_fleet, _write_json,
    )
    from code2vec_tpu.serving.fleet.control import HostSpec
    from code2vec_tpu.pipeline.stages import (
        run_retrieval_refresh,
    )

    ok_replicas = _write_json(
        tmp_path, "replica-ok.json",
        _replica_overrides(fingerprint="fp-v1", fake_swap=True))
    failing_replicas = _write_json(
        tmp_path, "replica-fail-v3.json",
        _replica_overrides(fingerprint="fp-v1", fake_swap=True,
                           swap_fail_targets=["v3"]))
    host_json = _write_json(tmp_path, "host.json", _host_overrides())
    config = _fleet_config(tmp_path)
    control, thread, rc_holder = run_fleet(
        config,
        [HostSpec("default-0",
                  [sys.executable, FLEET_HOST, host_json, ok_replicas]),
         HostSpec("default-1",
                  [sys.executable, FLEET_HOST, host_json,
                   failing_replicas])],
        artifacts={"default": "/artifacts/v1"})
    _wait_fleet(control, _all_routable(2), what="2 routable hosts")
    port = control.router.port

    # -- background client load for the swap windows
    malformed, statuses = [], []
    lock = threading.Lock()
    stop_load = threading.Event()
    allowed_fps = {"fp-v1", "fp-v2", "fp-v3"}

    def load(ci):
        i = 0
        while not stop_load.is_set():
            try:
                status, body, headers = _post(
                    port, "/predict",
                    f"class P{ci}x{i} {{ int m{ci}x{i}() "
                    f"{{ return 1; }} }}", timeout=30)
            except Exception:
                i += 1
                continue  # torn TCP = client retry, not corruption
            try:
                payload = json.loads(body)
                if status == 200:
                    ok = (payload.get("model_fingerprint")
                          in allowed_fps and "methods" in payload)
                else:
                    ok = (status in (503, 504)
                          and payload.get("trace_id"))
                if not ok:
                    raise ValueError(f"dishonest: {status} {payload}")
            except ValueError as e:
                with lock:
                    malformed.append((status, body[:200], str(e)))
            with lock:
                statuses.append(status)
            i += 1

    threads = [threading.Thread(target=load, args=(ci,))
               for ci in range(3)]
    for t in threads:
        t.start()

    def pipeline_for(sub, artifact_name, shadow_fn):
        cfg = Config(pipeline=True,
                     pipeline_dir=str(tmp_path / sub),
                     pipeline_fleet=f"127.0.0.1:{port}",
                     pipeline_promote_timeout_s=120.0,
                     verbose_mode=0)
        artifact = os.path.join(str(tmp_path), "artifacts",
                                artifact_name)
        stages = [
            ("ingest", lambda ctx: {"delta_prefix": "unused"}),
            ("finetune", lambda ctx: {"save_base": "unused"}),
            ("export", lambda ctx: {"artifact": artifact,
                                    "fingerprint":
                                        f"fp-{artifact_name}"}),
            ("shadow_eval", shadow_fn),
            ("promote", run_promote),
            ("retrieval_refresh", run_retrieval_refresh),
        ]
        return PipelineSupervisor(cfg, stages=stages,
                                  log=lambda m: None)

    def shadow_pass(ctx):
        v = gate_verdict(_Res(0.40, 0.60, 0.50),
                         _Res(0.42, 0.62, 0.52), bars=GateBars())
        assert v["passed"]
        return dict(v["numbers"], gate="passed")

    def shadow_fail(ctx):
        v = gate_verdict(_Res(0.40, 0.60, 0.50),
                         _Res(0.30, 0.50, 0.40), bars=GateBars())
        assert not v["passed"]
        raise GateRefused("shadow_eval", "; ".join(v["reasons"]),
                          v["numbers"])

    try:
        # ---- (1) good candidate: ingest -> promote, fleet-wide fp-v2
        sup = pipeline_for("pipe-good", "v2", shadow_pass)
        assert sup.run() == 0
        assert sup.manifest.terminal["outcome"] == "committed"
        assert sup.manifest.stage("promote")["outputs"]["outcome"] == \
            "committed"
        # retrieval refresh not requested -> recorded skipped
        assert sup.manifest.stage("retrieval_refresh")["status"] == \
            "skipped"
        view = _wait_fleet(
            control,
            lambda v: (v["models"]["default"]["fingerprints"]
                       == ["fp-v2"]
                       and not v["models"]["default"]
                       ["mixed_fingerprints"]),
            what="every replica on fp-v2")
        for host in view["hosts"]:
            assert host["fingerprints"] == ["fp-v2"], host

        # ---- (2) regressed candidate: refused at the gate, fleet
        # untouched
        sup = pipeline_for("pipe-regressed", "v2b", shadow_fail)
        assert sup.run() == 1
        assert sup.manifest.terminal["outcome"] == "gate_refused"
        assert sup.manifest.stage("promote") is None
        view = control.fleet_view()
        assert view["models"]["default"]["fingerprints"] == ["fp-v2"]
        assert view["models"]["default"]["artifact"].endswith("v2")

        # ---- (3) mid-fleet-swap failure: host 1 rejects v3 ->
        # fleet-wide rollback, terminal promote_failed
        sup = pipeline_for("pipe-rollback", "v3", shadow_pass)
        assert sup.run() == 1
        term = sup.manifest.terminal
        assert term["outcome"] == "promote_failed"
        assert term["detail"]["rollout_outcome"] == "rolled_back"
        view = _wait_fleet(
            control,
            lambda v: v["models"]["default"]["fingerprints"]
            == ["fp-v2"],
            what="fleet rolled back to fp-v2")
        assert not view["models"]["default"]["mixed_fingerprints"]
        time.sleep(0.5)  # post-rollback traffic
    finally:
        stop_load.set()
        for t in threads:
            t.join(timeout=30)
    assert not malformed, f"dishonest responses: {malformed[:3]}"
    assert statuses.count(200) > 0
    # fresh request serves the rolled-back fingerprint
    status, body, _ = _post(port, "/predict",
                            "class A { int after() { return 1; } }")
    assert status == 200
    assert json.loads(body)["model_fingerprint"] == "fp-v2"
    control.stop()
    thread.join(timeout=60)
    assert rc_holder["rc"] == 0
