"""Sharding correctness on the 8-virtual-device CPU mesh: GSPMD and manual
shard_map train/eval steps must match the single-device computation."""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from code2vec_tpu.config import Config
from code2vec_tpu.data.reader import RowBatch
from code2vec_tpu.models.code2vec import Code2VecModule, ModelDims
from code2vec_tpu.ops import sharded as tp_ops
from code2vec_tpu.parallel.mesh import (
    MeshPlan, make_mesh, replicated_axes_for_spec, make_mesh as _mm,
)
from code2vec_tpu.training.state import (
    TrainState, create_train_state, make_optimizer,
)
from code2vec_tpu.training.step import (
    TrainStepBuilder, _shard_map, device_put_batch,
)
from jax.sharding import PartitionSpec as P


def _make_batch(rng, B, M, dims, all_valid_rows=True):
    src = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    pth = rng.integers(0, dims.path_vocab_size, (B, M)).astype(np.int32)
    tgt = rng.integers(0, dims.token_vocab_size, (B, M)).astype(np.int32)
    mask = (rng.random((B, M)) > 0.3).astype(np.float32)
    mask[:, 0] = 1.0
    labels = rng.integers(1, dims.real_target_vocab_size, (B,)).astype(np.int32)
    return RowBatch(
        source_token_indices=src, path_indices=pth, target_token_indices=tgt,
        context_valid_mask=mask, target_index=labels,
        example_valid=np.ones((B,), bool))


def _config(**kw):
    defaults = dict(train_data_path_prefix="unused", compute_dtype="float32",
                    train_batch_size=8, test_batch_size=8, max_contexts=8)
    defaults.update(kw)
    return Config(**defaults)


def _module_and_state(config, dims, mesh=None):
    module = Code2VecModule(dims=dims, compute_dtype=jnp.float32,
                            dropout_keep_rate=config.dropout_keep_rate)
    opt = make_optimizer(config)
    state = create_train_state(module, opt, jax.random.PRNGKey(7), mesh=mesh)
    return module, opt, state


DIMS = ModelDims(token_vocab_size=24, path_vocab_size=16,
                 target_vocab_size=16, token_dim=4, path_dim=4)


def test_replicated_axes_rule():
    assert replicated_axes_for_spec(P("model", None)) == ("data", "ctx")
    assert replicated_axes_for_spec(P()) == ("data", "model", "ctx")
    assert replicated_axes_for_spec(P("data", "ctx")) == ("model",)


def test_tp_ops_match_dense():
    """tp_embedding_lookup / tp_softmax_ce / tp_top_k vs dense equivalents."""
    mesh = make_mesh(MeshPlan(dp=1, tp=4, cp=1))
    rng = np.random.default_rng(0)
    table = rng.standard_normal((16, 4)).astype(np.float32)
    ids = rng.integers(0, 16, (8,)).astype(np.int32)
    logits = rng.standard_normal((8, 16)).astype(np.float32)
    labels = rng.integers(0, 16, (8,)).astype(np.int32)

    def per_shard(table_shard, ids, logits_shard, labels):
        emb = tp_ops.tp_embedding_lookup(table_shard, ids, "model")
        ce = tp_ops.tp_softmax_ce(logits_shard, labels, "model")
        vals, idx = tp_ops.tp_top_k(logits_shard, 3, "model")
        return emb, ce, vals, idx

    f = _shard_map(
        per_shard, mesh=mesh,
        in_specs=(P("model", None), P(), P(None, "model"), P()),
        out_specs=(P(), P(), P(), P()), check_vma=False)
    emb, ce, vals, idx = f(table, ids, logits, labels)

    np.testing.assert_allclose(np.asarray(emb), table[ids], atol=1e-6)
    ref_ce = (np.log(np.exp(logits - logits.max(-1, keepdims=True)).sum(-1))
              + logits.max(-1) - logits[np.arange(8), labels])
    np.testing.assert_allclose(np.asarray(ce), ref_ce, rtol=1e-5, atol=1e-5)
    ref_idx = np.argsort(-logits, axis=-1)[:, :3]
    np.testing.assert_array_equal(np.sort(np.asarray(idx)), np.sort(ref_idx))
    np.testing.assert_allclose(
        np.asarray(vals), np.take_along_axis(logits, ref_idx, -1),
        rtol=1e-6)


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=8, tp=1, cp=1),
    MeshPlan(dp=2, tp=2, cp=2),
    MeshPlan(dp=1, tp=4, cp=2),
])
def test_gspmd_train_step_matches_single_device(plan):
    config = _config(dp=plan.dp, tp=plan.tp, cp=plan.cp,
                     use_manual_tp_kernels=False)
    dims = DIMS.padded_to(plan.tp) if plan.tp > 1 else DIMS
    batch = _make_batch(np.random.default_rng(1), 8, 8, dims)
    rng = jax.random.PRNGKey(3)

    # single-device baseline (eval first: the train step donates its state)
    cfg1 = _config(use_manual_tp_kernels=False)
    module1, opt1, state1 = _module_and_state(cfg1, dims)
    builder1 = TrainStepBuilder(module1, opt1, cfg1, mesh=None)
    arrays1 = device_put_batch(batch, None)
    eval1 = builder1.make_eval_step(state1, k=3)
    out1 = eval1(state1.params, *arrays1)

    mesh = make_mesh(plan)
    module, opt, state = _module_and_state(config, dims, mesh=mesh)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)
    assert not builder.manual
    arrays = device_put_batch(batch, mesh)
    evalN = builder.make_eval_step(state, k=3)
    outN = evalN(state.params, *arrays)

    # Dropout RNG folding differs across shardings, so the stochastic train
    # losses are not bit-comparable; check finiteness of a train step on
    # each layout and exact equality of the deterministic eval forward.
    step1 = builder1.make_train_step(state1)
    new1, loss1 = step1(state1, *arrays1, rng)
    step = builder.make_train_step(state)
    new, loss = step(state, *arrays, rng)
    assert np.isfinite(float(loss1)) and np.isfinite(float(loss))
    np.testing.assert_allclose(np.asarray(out1.topk_values),
                               np.asarray(outN.topk_values), rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(out1.topk_indices),
                                  np.asarray(outN.topk_indices))
    np.testing.assert_allclose(float(out1.loss_sum), float(outN.loss_sum),
                               rtol=1e-4)


@pytest.mark.parametrize("plan", [
    MeshPlan(dp=2, tp=2, cp=2),
    MeshPlan(dp=1, tp=8, cp=1),
    MeshPlan(dp=2, tp=1, cp=4),
])
def test_manual_shard_map_matches_single_device(plan):
    config = _config(dp=plan.dp, tp=plan.tp, cp=plan.cp,
                     use_manual_tp_kernels=True)
    dims = DIMS.padded_to(plan.tp) if plan.tp > 1 else DIMS
    batch = _make_batch(np.random.default_rng(2), 8, 8, dims)
    rng = jax.random.PRNGKey(5)

    cfg1 = _config(use_manual_tp_kernels=False)
    module1, opt1, state1 = _module_and_state(cfg1, dims)
    arrays1 = device_put_batch(batch, None)
    eval1 = TrainStepBuilder(module1, opt1, cfg1, mesh=None).make_eval_step(state1, k=3)
    out1 = eval1(state1.params, *arrays1)

    mesh = make_mesh(plan)
    module, opt, state = _module_and_state(config, dims, mesh=mesh)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)
    assert builder.manual
    arrays = device_put_batch(batch, mesh)
    evalN = builder.make_eval_step(state, k=3)
    outN = evalN(state.params, *arrays)

    np.testing.assert_allclose(np.asarray(out1.topk_values),
                               np.asarray(outN.topk_values), rtol=1e-4, atol=1e-4)
    np.testing.assert_array_equal(np.asarray(out1.topk_indices),
                                  np.asarray(outN.topk_indices))
    np.testing.assert_allclose(float(out1.loss_sum), float(outN.loss_sum),
                               rtol=1e-4)

    # Manual train step runs and decreases loss over a few steps.
    step = builder.make_train_step(state)
    losses = []
    for i in range(5):
        state, loss = step(state, *arrays, jax.random.PRNGKey(0))
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_manual_grads_match_single_device_grads():
    """Deterministic (no-dropout) gradient parity: manual shard_map grads
    == single-device grads. Verifies the storage-replication psum rule."""
    plan = MeshPlan(dp=2, tp=2, cp=2)
    dims = DIMS.padded_to(plan.tp)
    config = _config(dp=plan.dp, tp=plan.tp, cp=plan.cp,
                     dropout_keep_rate=1.0)
    batch = _make_batch(np.random.default_rng(3), 8, 8, dims)
    rng = jax.random.PRNGKey(11)

    cfg1 = _config(dropout_keep_rate=1.0)
    module1, opt1, state1 = _module_and_state(cfg1, dims)
    step1 = TrainStepBuilder(module1, opt1, cfg1, mesh=None).make_train_step(state1)
    arrays1 = device_put_batch(batch, None)
    new1, loss1 = step1(state1, *arrays1, rng)

    mesh = make_mesh(plan)
    module, opt, state = _module_and_state(config, dims, mesh=mesh)
    builder = TrainStepBuilder(module, opt, config, mesh=mesh)
    step = builder.make_train_step(state)
    arrays = device_put_batch(batch, mesh)
    new, loss = step(state, *arrays, rng)

    np.testing.assert_allclose(float(loss1), float(loss), rtol=1e-5)
    for name in new1.params:
        np.testing.assert_allclose(
            np.asarray(new1.params[name]), np.asarray(new.params[name]),
            rtol=2e-4, atol=2e-5, err_msg=f"param {name} diverged")
