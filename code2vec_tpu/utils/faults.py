"""Fault-injection hook points for chaos testing (tests/test_chaos.py).

Production code calls `fault_point("name")` at the places a crash is
interesting (e.g. between each file written during a checkpoint save).
The hooks are inert — zero work beyond one dict truthiness check — unless
the `C2V_FAULTS` environment variable (or an explicit `reset(spec)` call
in-process) arms them.

Spec grammar (comma-separated):

    C2V_FAULTS="<point>[@N][=<action>][,<point2>...]"

- `<point>`  — the fault-point name passed to `fault_point`.
- `@N`       — trigger on the Nth *hit* of that point (1-based; default 1).
               Hits are counted per point name across the whole process,
               so `save@3=exit` kills the process at the third `save`
               hook crossed since arming.
- `<action>` — `raise` (default): raise `FaultInjected`, unwinding like
               an in-flight exception; `exit`: `os._exit(FAULT_EXIT_CODE)`,
               a hard kill with no cleanup handlers — the closest
               in-process stand-in for SIGKILL / power loss.

The spec is parsed lazily on the first `fault_point` call and cached;
subprocess tests set the env var before the interpreter starts, and
in-process tests use `reset("...")` / `reset(None)` to (re)arm or disarm.

Fault points in the checkpoint commit protocol (training/checkpoint.py):

- `save` (x5)        — between each staged file (1 staging created,
                       2 vocab, 3 meta, 4 Orbax flushed, 5 fully staged)
- `async_commit`     — start of the deferred commit work (post-flush,
                       pre-barrier); on the commit thread in async mode
- `barrier_enter`    — immediately before entering the cross-host
                       post-flush commit barrier (a host killed here
                       times the barrier out on every survivor)
- `checkpoint_commit`— staged + barriered, rename pending
- `checkpoint_swap`  — mid overwrite-swap (the empty-slot window)
- `callback_crash`   — committed, completion barrier / content-hash
                       pass still pending

Fault points in the elastic restore path (training/checkpoint.py
load_model, model_facade._train_batches):

- `reshard_restore`  — a topology-changed (resharded) restore is about
                       to read the artifact. Restore is read-only by
                       design, so a kill here must leave the original
                       artifact untouched and re-restorable — the
                       elastic chaos matrix arms it to prove exactly
                       that.
- `cursor_remap`     — the saved data-pipeline cursor is being remapped
                       to the current host count before the resumed
                       epoch's first batch; same untouched-artifact
                       contract as `reshard_restore`.

Fault points in the serving resilience stack (serving/admission.py,
serving/swap.py, serving/server.py; tests/test_serving_chaos.py):

- `admission_enqueue` — crossed on every admission-gate admit. An armed
                       fault here must surface as an honest JSON error
                       response (never a hang, never a torn body) —
                       the admission layer failing is itself a serving
                       fault mode.
- `swap_validate`    — top of the hot-swap load+validate worker. A kill
                       or raise mid-swap must leave the OLD model
                       serving untouched, with the failure visible in
                       /healthz `model.swap_status`.
- `replica_heartbeat`— crossed by the serving heartbeat ticker before
                       each rewrite. `raise` wedges the ticker (the
                       heartbeat goes stale -> the supervisor's
                       hung-replica detection fires); `exit` kills the
                       whole replica (the supervisor's crash-restart
                       path).

Fault points in the continuous-training pipeline
(pipeline/supervisor.py, pipeline/stages.py;
tests/test_pipeline.py):

- `pipeline_stage`   — crossed TWICE per stage of the pipeline stage
                       machine: at stage start (hit 2k-1 for stage k)
                       and again with the stage's work done but its
                       manifest commit still pending (hit 2k). Arming
                       `pipeline_stage@N=exit` therefore kills the
                       supervisor at EVERY boundary of the machine;
                       the rerun must resume from the last committed
                       stage and never repeat committed work.
- `shadow_eval`      — top of the shadow-eval stage, before either
                       model is built. A kill here must leave the
                       candidate un-judged (stage uncommitted, rerun
                       re-evaluates) and the incumbent serving.
- `promote`          — immediately before the canary-first fleet
                       rollout request is issued. A kill here must
                       leave the fleet untouched on the incumbent
                       (the rollout was never requested).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

FAULTS_ENV = "C2V_FAULTS"
# Distinctive exit code so a test can tell an injected kill from a
# genuine crash in the code under test.
FAULT_EXIT_CODE = 43

_ACTIONS = ("raise", "exit")


class FaultInjected(RuntimeError):
    """Raised by an armed `raise`-action fault point."""


class FaultSpecError(ValueError):
    """A C2V_FAULTS spec that cannot be parsed (fail loud: a typo'd spec
    silently injecting nothing would invalidate the chaos test)."""


# point name -> (trigger hit number, action); None = not parsed yet,
# {} = parsed and disarmed (the zero-cost steady state).
_spec: Optional[Dict[str, Tuple[int, str]]] = None
_hits: Dict[str, int] = {}


def _parse(raw: str) -> Dict[str, Tuple[int, str]]:
    spec: Dict[str, Tuple[int, str]] = {}
    for clause in filter(None, (c.strip() for c in raw.split(","))):
        point, _, action = clause.partition("=")
        action = action or "raise"
        if action not in _ACTIONS:
            raise FaultSpecError(
                f"bad {FAULTS_ENV} clause {clause!r}: action {action!r} "
                f"not in {_ACTIONS}")
        point, _, nth = point.partition("@")
        try:
            n = int(nth) if nth else 1
        except ValueError:
            raise FaultSpecError(
                f"bad {FAULTS_ENV} clause {clause!r}: hit count {nth!r} "
                f"is not an integer")
        if not point or n < 1:
            raise FaultSpecError(f"bad {FAULTS_ENV} clause {clause!r}")
        spec[point] = (n, action)
    return spec


def reset(spec: Optional[str] = "") -> None:
    """(Re)arm the fault points. `reset("save@2=raise")` arms in-process
    (tests); `reset()` or `reset("")` re-reads the environment on the
    next hit; `reset(None)` disarms outright."""
    global _spec
    _hits.clear()
    if spec is None:
        _spec = {}
    elif spec == "":
        _spec = None  # lazy re-read of the env var
    else:
        _spec = _parse(spec)


def fault_point(name: str) -> None:
    """Cross a named fault point. No-op (one dict check) unless armed."""
    global _spec
    if _spec is None:
        _spec = _parse(os.environ.get(FAULTS_ENV, ""))
    if not _spec:
        return
    armed = _spec.get(name)
    if armed is None:
        return
    _hits[name] = _hits.get(name, 0) + 1
    n, action = armed
    if _hits[name] != n:
        return
    # Fired faults are counted so `raise`-action drills (and anything
    # else sharing this process) can prove via the registry which fault
    # paths fired. An `exit`-action increment necessarily dies with the
    # process — os._exit runs no exporters by design, that IS the fault
    # being simulated; exit drills are observed by their distinctive
    # exit code instead. Imported lazily: the unarmed fast path above
    # must stay one dict check with no import machinery.
    from code2vec_tpu import obs
    obs.counter("fault_injected_total",
                "armed fault points that fired",
                point=name, action=action).inc()
    if action == "exit":
        os._exit(FAULT_EXIT_CODE)
    raise FaultInjected(f"injected fault at point {name!r} (hit {n})")
