"""Host->device double-buffering: overlap input parsing/transfer with step
execution (the reference gets this from tf.data's internal C++ threads,
path_context_reader.py:150; here an explicit background thread feeds a
bounded queue of device-resident, sharding-annotated batches)."""

from __future__ import annotations

import queue
import threading
from typing import Iterable, Iterator, Optional

from code2vec_tpu.data.reader import EpochEnd
from code2vec_tpu.training.step import device_put_batch


class DevicePrefetcher:
    """Wraps a RowBatch iterable; yields (device_arrays, host_batch) with up
    to `depth` batches transferred ahead of consumption. EpochEnd markers
    from the underlying iterable are passed through in order (bare, not
    wrapped in a tuple)."""

    _SENTINEL = object()

    def __init__(self, batches: Iterable, mesh, depth: int = 4,
                 keep_host_batch: bool = False):
        self.batches = batches
        self.mesh = mesh
        self.depth = max(1, depth)
        self.keep_host_batch = keep_host_batch
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _worker(self):
        try:
            for batch in self.batches:
                if isinstance(batch, EpochEnd):
                    self._queue.put(batch)
                    continue
                arrays = device_put_batch(batch, self.mesh)
                self._queue.put(
                    (arrays, batch if self.keep_host_batch else None))
        except BaseException as e:  # propagate to consumer
            self._error = e
        finally:
            self._queue.put(self._SENTINEL)

    def __iter__(self) -> Iterator:
        self._thread.start()
        while True:
            item = self._queue.get()
            if item is self._SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item
