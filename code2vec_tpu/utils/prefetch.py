"""Host->device double-buffering: overlap input parsing/packing with step
execution (the reference gets this from tf.data's internal C++ threads,
path_context_reader.py:150; here an explicit background thread feeds a
bounded queue of ready-to-transfer batches)."""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterable, Iterator, Optional

from code2vec_tpu import obs
from code2vec_tpu.data.reader import EpochEnd
from code2vec_tpu.training.step import (
    device_put_batch, fused_path_applies, pack_batch_host,
)

# Module-scope handles: these fire once per batch on the worker and
# consumer threads (registry metrics are thread-safe).
_H_PACK = obs.histogram(
    "prefetch_pack_seconds",
    "host packing of one batch's fused transfer buffer (worker thread)")
_H_DEVICE_PUT = obs.histogram(
    "prefetch_device_put_seconds",
    "host-side cost of dispatching one batch's device transfer "
    "(consumer thread; the transfer itself is async)")
_C_BATCHES = obs.counter("prefetch_batches_total",
                         "batches staged by the prefetch worker")
_G_DEPTH = obs.gauge(
    "prefetch_queue_depth",
    "ready batches queued ahead of the consumer at its last take "
    "(0 every step = the pipeline is feed-bound)")


class DevicePrefetcher:
    """Wraps a RowBatch iterable; yields (device_arrays, host_batch) with
    up to `depth` batches prepared ahead of consumption. EpochEnd markers
    from the underlying iterable are passed through in order (bare, not
    wrapped in a tuple).

    Division of labor: the worker thread runs only HOST work — iterating
    the reader (parse/filter) and packing the fused transfer buffer
    (pack_batch_host, pure numpy). The device transfer + jitted unpack
    happen on the consumer thread at yield time; transfers dispatch
    asynchronously, so the consumer is not stalled — and keeping every
    runtime interaction on one thread avoids serializing the consumer's
    step dispatches against a second thread's transfer calls inside the
    runtime client (measured 2-3x worse real-data throughput with
    device_put on the worker thread)."""

    _SENTINEL = object()

    def __init__(self, batches: Iterable, mesh, depth: int = 4,
                 keep_host_batch: bool = False,
                 double_buffer: bool = False):
        self.batches = batches
        self.mesh = mesh
        self.depth = max(1, depth)
        self.keep_host_batch = keep_host_batch
        self.double_buffer = double_buffer
        self._queue: queue.Queue = queue.Queue(maxsize=self.depth)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._worker, daemon=True)

    def _put(self, item) -> bool:
        """Bounded put that gives up when the consumer has stopped, so an
        abandoned iteration never wedges this thread on a full queue
        (pinning the upstream reader's files for the process lifetime)."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _worker(self):
        try:
            pack = fused_path_applies(self.mesh)
            for batch in self.batches:
                if isinstance(batch, EpochEnd):
                    item = batch
                elif pack:
                    # the packed buffer is all the consumer needs unless
                    # it asked for the host batch too — don't pin both
                    t0 = time.perf_counter()
                    packed = pack_batch_host(batch)
                    dur = time.perf_counter() - t0
                    _H_PACK.observe(dur)
                    obs.default_tracer().maybe_record("prefetch_pack",
                                                      t0, dur)
                    _C_BATCHES.inc()
                    item = (batch if self.keep_host_batch else None,
                            packed)
                else:
                    _C_BATCHES.inc()
                    item = (batch, None)
                if not self._put(item):
                    return
        except BaseException as e:  # propagate to consumer
            self._error = e
        finally:
            self._put(self._SENTINEL)

    def __iter__(self) -> Iterator:
        # Double-buffering (`double_buffer=True`) holds ONE transferred
        # batch back: batch N+1's device_put is dispatched before batch
        # N is handed to the step loop, so the N+1 transfer rides under
        # step N's dispatch instead of serializing after it. The
        # transfer still runs on THIS thread (see the class docstring:
        # a second runtime-client thread measured 2-3x worse) — only
        # the dispatch order changes. Costs one extra batch of device
        # memory and one batch of startup latency; EpochEnd markers
        # flush the held batch first so ordering is preserved.
        self._thread.start()
        pending = None
        try:
            while True:
                item = self._queue.get()
                if item is self._SENTINEL:
                    if self._error is not None:
                        raise self._error
                    if pending is not None:
                        yield pending
                    return
                if isinstance(item, EpochEnd):
                    if pending is not None:
                        out, pending = pending, None
                        yield out
                    yield item
                    continue
                _G_DEPTH.set(self._queue.qsize())
                batch, packed = item
                t0 = time.perf_counter()
                arrays = device_put_batch(batch, self.mesh, packed=packed)
                dur = time.perf_counter() - t0
                _H_DEVICE_PUT.observe(dur)
                obs.default_tracer().maybe_record("prefetch_device_put",
                                                  t0, dur)
                staged = (arrays, batch if self.keep_host_batch else None)
                if not self.double_buffer:
                    yield staged
                elif pending is None:
                    pending = staged  # prime: hold batch 0, put batch 1
                else:
                    out, pending = pending, staged
                    yield out
        finally:
            # consumer stopped (normally, by exception, or abandoned):
            # release the worker so it can exit and drop the reader
            self._stop.set()
