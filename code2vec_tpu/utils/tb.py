"""Dependency-free TensorBoard scalar writer.

The reference's `--tensorboard` flag attaches a Keras TensorBoard callback
(reference: config.py:42-43, keras_model.py:158-163). This framework has
no TensorFlow, so the event-file format is produced directly: a TFRecord
stream (length + masked CRC32C framing) of hand-encoded `Event` protobuf
messages containing scalar `Summary` values. Files written here load in
stock TensorBoard.

Wire format notes (protobuf encoding, stable since proto2):
  Event:   wall_time=1 (double), step=2 (int64), file_version=3 (string),
           summary=5 (message)
  Summary: value=1 (repeated message); Value: tag=1 (string),
           simple_value=2 (float)
"""

from __future__ import annotations

import os
import socket
import struct
import time
from typing import Optional

# ---------------------------------------------------------------- crc32c

_CRC_TABLE = []
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (_c >> 1) ^ (0x82F63B78 if _c & 1 else 0)
    _CRC_TABLE.append(_c)


def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc = (crc >> 8) ^ _CRC_TABLE[(crc ^ b) & 0xFF]
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# ----------------------------------------------------------- proto encode

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_bytes(num: int, payload: bytes) -> bytes:
    return _varint((num << 3) | 2) + _varint(len(payload)) + payload


def _event(wall_time: float, step: int, *, file_version: Optional[str] = None,
           scalar: Optional[tuple] = None) -> bytes:
    msg = bytearray()
    msg += _varint((1 << 3) | 1) + struct.pack("<d", wall_time)
    msg += _varint((2 << 3) | 0) + _varint(step)
    if file_version is not None:
        msg += _field_bytes(3, file_version.encode())
    if scalar is not None:
        tag, value = scalar
        val = (_field_bytes(1, tag.encode())
               + _varint((2 << 3) | 5) + struct.pack("<f", float(value)))
        msg += _field_bytes(5, _field_bytes(1, val))
    return bytes(msg)


class ScalarWriter:
    """Appends scalar events to one `events.out.tfevents.*` file.

    Lifecycle: usable as a context manager; `close()` is idempotent and
    flushes first, so the trainer can close it in a `finally` (a crash or
    the NaN-halt raise must not lose the tail of the event stream) while
    any later defensive close stays harmless."""

    def __init__(self, logdir: str):
        os.makedirs(logdir, exist_ok=True)
        name = (f"events.out.tfevents.{int(time.time())}."
                f"{socket.gethostname()}")
        self.path = os.path.join(logdir, name)
        self._f = open(self.path, "ab")
        self._write(_event(time.time(), 0, file_version="brain.Event:2"))

    def _write(self, record: bytes) -> None:
        header = struct.pack("<Q", len(record))
        self._f.write(header)
        self._f.write(struct.pack("<I", _masked_crc(header)))
        self._f.write(record)
        self._f.write(struct.pack("<I", _masked_crc(record)))

    def scalar(self, tag: str, value: float, step: int) -> None:
        self._write(_event(time.time(), int(step), scalar=(tag, value)))

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()

    @property
    def closed(self) -> bool:
        return self._f.closed

    def close(self) -> None:
        if not self._f.closed:
            self._f.flush()
            self._f.close()

    def __enter__(self) -> "ScalarWriter":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.close()
        return False
