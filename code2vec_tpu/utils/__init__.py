from code2vec_tpu.utils.prefetch import DevicePrefetcher  # noqa: F401
