# Lazy re-export (PEP 562): prefetch.py pulls training/step.py and with
# it jax + flax — seconds of import and hundreds of MB. Serving-side
# consumers of this package (admission/extractor code importing
# utils.faults) must not pay that: a supervisor-restarted fake-model
# replica's convergence time is dominated by exactly this import.
# Everything in-repo imports DevicePrefetcher from its own module;
# this keeps `from code2vec_tpu.utils import DevicePrefetcher` working
# for external callers without the eager cost.


def __getattr__(name):
    if name == "DevicePrefetcher":
        from code2vec_tpu.utils.prefetch import DevicePrefetcher
        return DevicePrefetcher
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
