"""Shadow evaluation: score a candidate model side-by-side with the
incumbent and decide — with numbers — whether it may be promoted.

Two signal sources, both replayed through BOTH models so every number
is a paired comparison, never a cross-run anecdote:

- **Accuracy harness**: the `--test` corpus through each model's
  standard `evaluate()` (the PR-8 release-runtime eval path — the
  exact head, the exact metrics the README reports). The gate compares
  top-1/top-k accuracy and subtoken F1 deltas against configurable
  regression bars.
- **Recorded live traffic**: a sampled slice of extractor lines the
  serving stack recorded (`--serve_traffic_sample`,
  serving/traffic.py) replayed through each model's bucketed predict
  path; the gate compares top-k AGREEMENT (mean overlap of the two
  top-k lists) and top-1 agreement — distribution-shift insurance the
  frozen harness cannot give.

The verdict is fail-closed: a candidate whose metrics are non-finite
(a NaN-poisoned fine-tune) is refused regardless of the bars, and any
single tripped bar refuses promotion. Every gate number is exported as
a `pipeline_gate_*` gauge and the verdict counted in
`pipeline_gate_total{verdict}` so the refusal is diagnosable from a
scrape alone.
"""

from __future__ import annotations

import dataclasses
import math
import random
from typing import Dict, Iterable, List, Optional

from code2vec_tpu import obs


@dataclasses.dataclass(frozen=True)
class GateBars:
    """Regression bars, phrased as the largest tolerated DROP (candidate
    minus incumbent; a negative delta is a regression) and the smallest
    tolerated traffic agreement."""
    max_top1_drop: float = 0.01
    max_topk_drop: float = 0.01
    max_f1_drop: float = 0.01
    min_topk_agreement: float = 0.98

    @classmethod
    def from_config(cls, config) -> "GateBars":
        return cls(
            max_top1_drop=float(getattr(config,
                                        "pipeline_gate_top1_drop", 0.01)),
            max_topk_drop=float(getattr(config,
                                        "pipeline_gate_topk_drop", 0.01)),
            max_f1_drop=float(getattr(config,
                                      "pipeline_gate_f1_drop", 0.01)),
            min_topk_agreement=float(getattr(
                config, "pipeline_gate_min_agreement", 0.98)))


def _eval_numbers(results) -> Dict[str, float]:
    """(top1, topk, f1, loss) from a ModelEvaluationResults-like object
    (duck-typed: the comparator unit tests script these)."""
    topk_acc = results.topk_acc
    return {
        "top1": float(topk_acc[0]),
        "topk": float(topk_acc[-1]),
        "f1": float(results.subtoken_f1),
        "loss": (None if getattr(results, "loss", None) is None
                 else float(results.loss)),
    }


def sample_traffic(lines: Iterable[str], limit: int,
                   seed: int = 0) -> List[str]:
    """A deterministic sample of up to `limit` recorded traffic lines
    (seeded — reruns of a killed shadow-eval stage replay the SAME
    slice). `limit <= 0` disables the replay entirely (the documented
    `--pipeline_shadow_samples 0` semantics: gate on the accuracy
    harness alone)."""
    if limit <= 0:
        return []
    pool = [ln for ln in (l.strip("\n") for l in lines) if ln.strip()]
    if len(pool) <= limit:
        return pool
    return random.Random(seed).sample(pool, limit)


def topk_agreement(incumbent, candidate, lines: List[str],
                   batch_size: int = 64) -> Dict[str, Optional[float]]:
    """Replay extractor lines through both models' predict paths and
    measure how much the answer would change for live callers:
    `topk_agreement` = mean overlap fraction of the two top-k word
    lists, `top1_agreement` = fraction of lines whose #1 word is
    unchanged. Returns None values when there is nothing to replay."""
    if not lines:
        return {"samples": 0, "topk_agreement": None,
                "top1_agreement": None}
    inc = incumbent.predict(list(lines), batch_size=batch_size)
    cand = candidate.predict(list(lines), batch_size=batch_size)
    overlap_sum = 0.0
    top1_hits = 0
    for a, b in zip(inc, cand):
        wa = list(a.topk_predicted_words)
        wb = list(b.topk_predicted_words)
        k = max(len(wa), len(wb), 1)
        overlap_sum += len(set(wa) & set(wb)) / k
        if wa and wb and wa[0] == wb[0]:
            top1_hits += 1
    n = len(lines)
    return {"samples": n,
            "topk_agreement": overlap_sum / n,
            "top1_agreement": top1_hits / n}


def gate_verdict(incumbent_eval, candidate_eval,
                 agreement: Optional[Dict] = None,
                 bars: Optional[GateBars] = None) -> Dict:
    """The promotion decision. Returns {passed, reasons, numbers};
    `numbers` carries every delta/agreement the verdict was made on
    (they also go into the heartbeat and the flight-recorder incident
    when the gate refuses). Fail-closed on non-finite candidate
    metrics."""
    bars = bars or GateBars()
    inc = _eval_numbers(incumbent_eval)
    cand = _eval_numbers(candidate_eval)
    numbers: Dict = {
        "incumbent_top1": inc["top1"], "candidate_top1": cand["top1"],
        "incumbent_topk": inc["topk"], "candidate_topk": cand["topk"],
        "incumbent_f1": inc["f1"], "candidate_f1": cand["f1"],
        "top1_delta": cand["top1"] - inc["top1"],
        "topk_delta": cand["topk"] - inc["topk"],
        "f1_delta": cand["f1"] - inc["f1"],
        "topk_agreement": (None if not agreement
                           else agreement.get("topk_agreement")),
        "top1_agreement": (None if not agreement
                           else agreement.get("top1_agreement")),
        "traffic_samples": (0 if not agreement
                            else int(agreement.get("samples") or 0)),
    }
    reasons: List[str] = []
    cand_scalars = [cand["top1"], cand["topk"], cand["f1"]]
    if cand["loss"] is not None:
        cand_scalars.append(cand["loss"])
    if agreement and agreement.get("topk_agreement") is not None:
        cand_scalars.append(agreement["topk_agreement"])
    if not all(math.isfinite(v) for v in cand_scalars):
        reasons.append(
            "candidate metrics are non-finite (NaN-poisoned "
            "fine-tune); refusing regardless of the bars")
    else:
        for key, bar in (("top1", bars.max_top1_drop),
                         ("topk", bars.max_topk_drop),
                         ("f1", bars.max_f1_drop)):
            delta = numbers[f"{key}_delta"]
            if delta < -bar:
                reasons.append(
                    f"{key} regressed {delta:+.4f} (bar: -{bar:g}); "
                    f"incumbent {inc[key]:.4f} vs candidate "
                    f"{cand[key]:.4f}")
        agr = numbers["topk_agreement"]
        if agr is not None and agr < bars.min_topk_agreement:
            reasons.append(
                f"top-k traffic agreement {agr:.4f} below "
                f"{bars.min_topk_agreement:g} over "
                f"{numbers['traffic_samples']} replayed sample(s)")
    passed = not reasons
    obs.gauge("pipeline_gate_top1_delta",
              "shadow-eval candidate-minus-incumbent top-1 accuracy "
              "delta of the latest gate decision").set(
        numbers["top1_delta"] if math.isfinite(numbers["top1_delta"])
        else -1.0)
    obs.gauge("pipeline_gate_topk_delta",
              "shadow-eval candidate-minus-incumbent top-k accuracy "
              "delta of the latest gate decision").set(
        numbers["topk_delta"] if math.isfinite(numbers["topk_delta"])
        else -1.0)
    obs.gauge("pipeline_gate_f1_delta",
              "shadow-eval candidate-minus-incumbent subtoken-F1 "
              "delta of the latest gate decision").set(
        numbers["f1_delta"] if math.isfinite(numbers["f1_delta"])
        else -1.0)
    if numbers["topk_agreement"] is not None:
        obs.gauge("pipeline_gate_topk_agreement",
                  "shadow-eval incumbent/candidate top-k agreement "
                  "over replayed live-traffic samples (latest gate "
                  "decision)").set(
            numbers["topk_agreement"]
            if math.isfinite(numbers["topk_agreement"]) else 0.0)
    obs.counter("pipeline_gate_total",
                "shadow-eval gate decisions by verdict",
                verdict="pass" if passed else "fail").inc()
    return {"passed": passed, "reasons": reasons, "numbers": numbers}


def shadow_compare(config, incumbent_artifact: str,
                   candidate_artifact: str,
                   traffic_lines: List[str],
                   bars: Optional[GateBars] = None,
                   build_model=None, log=None) -> Dict:
    """The shadow-eval stage body on REAL release artifacts: build both
    sides (PR-8 runtime; `build_model` is the test seam), run the
    accuracy harness through each, replay the traffic slice, and return
    the gate verdict. The incumbent is never mutated — both models are
    read-only artifact consumers."""
    log = log or config.log
    if build_model is None:
        def build_model(artifact_dir):
            from code2vec_tpu.release.runtime import ReleaseModel
            cfg = dataclasses.replace(config, serve_artifact=artifact_dir,
                                      serve=False, predict=False,
                                      pipeline=False)
            return ReleaseModel(cfg, log=log)
    incumbent = build_model(incumbent_artifact)
    candidate = build_model(candidate_artifact)
    log(f"Shadow eval: scoring incumbent {incumbent_artifact} vs "
        f"candidate {candidate_artifact} on {config.test_data_path} "
        f"+ {len(traffic_lines)} replayed traffic line(s)")
    incumbent_eval = incumbent.evaluate()
    candidate_eval = candidate.evaluate()
    agreement = topk_agreement(incumbent, candidate, traffic_lines)
    return gate_verdict(incumbent_eval, candidate_eval,
                        agreement=agreement,
                        bars=bars or GateBars.from_config(config))
