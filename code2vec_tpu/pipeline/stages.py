"""Stage bodies of the continuous-training pipeline.

Each stage is a function `fn(ctx) -> outputs dict` over a
PipelineContext; the supervisor owns ordering, manifest commits and
fault points. Stage work is IDEMPOTENT under re-run: outputs are
committed atomically by their writers (pack_raw's tmp+rename, the
checkpoint commit protocol, the export-dir rename below, the embed
job's per-shard resume), so a stage killed before its manifest commit
can simply run again.

Heavy lifting runs in CHILD processes re-execing this repo's own CLI
(`train`/`export`/`embed`/`index-build`) — the same crash-isolation
philosophy as the serving supervisor: the pipeline parent holds no
model, so a fine-tune OOM kills one stage attempt, not the loop's
state.
"""

from __future__ import annotations

import glob as glob_mod
import json
import os
import shutil
import subprocess
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.utils.faults import fault_point

CHECKPOINT_MANIFEST = "code2vec_manifest.json"
CHECKPOINT_META = "code2vec_meta.json"


class StageFailed(RuntimeError):
    """A stage attempt failed (crash, bad input, subprocess rc != 0).
    NOT terminal: the manifest keeps no record, so a rerun retries the
    stage from its last committed predecessor."""

    def __init__(self, stage: str, detail: str):
        super().__init__(f"pipeline stage {stage!r} failed: {detail}")
        self.stage = stage
        self.detail = detail


class StageSkipped(Exception):
    """A stage that does not apply to this run (no fleet to promote
    into, retrieval refresh not requested); committed to the manifest
    with status "skipped" so reruns don't re-decide."""


class GateRefused(StageFailed):
    """The shadow-eval quality gate refused the candidate — a TERMINAL
    verdict (the incumbent keeps serving; re-running cannot change the
    numbers)."""

    def __init__(self, stage: str, detail: str, numbers: Dict):
        super().__init__(stage, detail)
        self.numbers = numbers


class PromoteFailed(StageFailed):
    """The fleet rollout failed or rolled back — TERMINAL for this run
    (the fleet swap driver already restored the incumbent everywhere;
    the candidate needs investigation, not a blind retry)."""

    def __init__(self, stage: str, detail: str, outcome: str,
                 numbers: Optional[Dict] = None):
        super().__init__(stage, detail)
        self.outcome = outcome
        self.numbers = numbers or {}


def _c_promotions(outcome: str):
    return obs.counter(
        "pipeline_promotions_total",
        "pipeline-driven fleet promotions by outcome (committed, "
        "failed, rolled_back, timeout)", outcome=outcome)


class PipelineContext:
    """What every stage sees: config, the manifest (for committed
    predecessors' outputs), per-stage work dirs under the pipeline run
    dir, and a CLI-subprocess runner."""

    def __init__(self, config, manifest, run_dir: str, log):
        self.config = config
        self.manifest = manifest
        self.run_dir = run_dir
        self.log = log
        # set by the supervisor: the run's RequestTrace — stages that
        # cross process boundaries (drive_fleet_swap) propagate it
        self.trace = None

    def dir(self, name: str) -> str:
        path = os.path.join(self.run_dir, name)
        os.makedirs(path, exist_ok=True)
        return path

    def outputs(self, stage: str) -> Dict:
        rec = self.manifest.stage(stage)
        if rec is None:
            raise StageFailed(
                stage, f"stage ordering bug: {stage!r} has no committed "
                       f"record yet its outputs were requested")
        return rec.get("outputs") or {}

    def run_cli(self, argv: List[str], stage: str, name: str) -> None:
        """Run `python -m code2vec_tpu.cli <argv>` to completion,
        logging to `<stage dir>/<name>.log`; nonzero rc = StageFailed
        with the log path named (the child's heartbeat file, when one
        was passed, says where it stopped)."""
        from code2vec_tpu.serving.supervisor import child_env
        log_path = os.path.join(self.dir(stage), f"{name}.log")
        cmd = [sys.executable, "-m", "code2vec_tpu.cli"] + list(argv)
        self.log(f"Pipeline stage {stage}: running {name} subprocess "
                 f"({' '.join(argv[:6])}...; log: {log_path})")
        with open(log_path, "ab") as logf:
            rc = subprocess.call(cmd, stdout=logf, stderr=logf,
                                 env=child_env(os.environ))
        if rc != 0:
            raise StageFailed(
                stage, f"{name} subprocess exited rc={rc}; see "
                       f"{log_path}")


# ------------------------------------------------------------ helpers


def newest_committed_checkpoint(load_path: str
                                ) -> Tuple[Optional[str], int]:
    """(dir, epoch) of the newest committed checkpoint a `--load` path
    resolves to — a concrete artifact dir, or the newest `_iter*` under
    a save base. LIGHT probe only (manifest present + meta readable);
    the consuming subprocess's resolve path does full integrity
    verification and backward fallback."""
    base = os.path.abspath(load_path)
    candidates = ([base] if os.path.isfile(
        os.path.join(base, CHECKPOINT_MANIFEST))
        else [p for p in glob_mod.glob(base + "_iter*")
              if os.path.isfile(os.path.join(p, CHECKPOINT_MANIFEST))])
    best: Optional[str] = None
    best_key = (-1, -1.0)
    for path in candidates:
        try:
            with open(os.path.join(path, CHECKPOINT_META)) as f:
                epoch = int(json.load(f).get("epoch", 0))
            mtime = os.path.getmtime(
                os.path.join(path, CHECKPOINT_MANIFEST))
        except (OSError, ValueError):
            continue
        if (epoch, mtime) > best_key:
            best, best_key = path, (epoch, mtime)
    return best, max(best_key[0], 0)


def _frozen_vocabs(config, incumbent_dir: str):
    from code2vec_tpu.vocab import Code2VecVocabs
    path = os.path.join(incumbent_dir, "dictionaries.bin")
    if not os.path.isfile(path):
        raise StageFailed(
            "ingest", f"incumbent checkpoint {incumbent_dir} has no "
                      f"dictionaries.bin to freeze the vocab from")
    return Code2VecVocabs.load(
        path, separate_oov_and_pad=config.separate_oov_and_pad)


def measure_delta_oov(raw_path: str, ds, vocabs) -> Dict[str, float]:
    """OOV profile of an ingested delta (`ds`: its PackedDataset)
    against the frozen vocab: the 'is the vocabulary aging out' signal
    of the continuous loop. target rate = packed rows whose label fell
    to OOV (untrainable); context rate = raw token/path fields missing
    from the frozen dicts — measured on the TEXT because in the joined
    PAD/OOV scheme an OOV slot packs to the PAD index and the ints
    cannot distinguish them (one extra serial pass over the raw file;
    delta shards are small next to the base corpus)."""
    t_oov = vocabs.target_vocab.oov_index
    rows = oov_rows = 0
    for start in range(0, ds.num_rows_total, 1 << 18):
        labels = ds._rec[start:start + (1 << 18), 0]
        rows += labels.shape[0]
        oov_rows += int((labels == t_oov).sum())
    token_w2i = vocabs.token_vocab.word_to_index
    path_w2i = vocabs.path_vocab.word_to_index
    slots = oov_slots = 0
    with open(raw_path, "r", errors="surrogateescape",
              buffering=16 * 1024 * 1024) as f:
        for line in f:
            for ctx in line.split()[1:]:
                pieces = ctx.split(",")
                a = pieces[0] if pieces else ""
                b = pieces[1] if len(pieces) > 1 else ""
                c = pieces[2] if len(pieces) > 2 else ""
                for val, table in ((a, token_w2i), (c, token_w2i)):
                    if val:
                        slots += 1
                        oov_slots += val not in table
                if b:
                    slots += 1
                    oov_slots += b not in path_w2i
    return {"rows": rows,
            "target_oov_rate": oov_rows / max(rows, 1),
            "context_oov_rate": oov_slots / max(slots, 1)}


def _fleet_base(config) -> str:
    addr = str(config.pipeline_fleet).strip().rstrip("/")
    if not addr.startswith("http://") and not addr.startswith("https://"):
        addr = "http://" + addr
    return addr


def _http_json(stage: str, method: str, url: str,
               payload: Optional[Dict] = None,
               timeout: float = 15.0) -> Tuple[int, Dict]:
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"} if data else {})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            raw = r.read()
            status = r.status
    except urllib.error.HTTPError as e:
        raw = e.read()
        status = e.code
    except (OSError, ValueError) as e:
        raise StageFailed(stage, f"fleet unreachable at {url}: {e}")
    try:
        body = json.loads(raw.decode("utf-8", errors="replace") or "{}")
    except ValueError:
        body = {"raw": raw.decode("utf-8", errors="replace")[:200]}
    return status, body


def drive_fleet_swap(ctx, stage: str, artifact: str,
                     retrieval_index: Optional[str] = None) -> Dict:
    """Request a canary-first coordinated rollout through the fleet
    router and poll `GET /fleet` until THIS rollout (keyed on its
    target) reaches a terminal state. Returns the terminal swap status
    dict; the caller maps failed/rolled_back to its own verdict."""
    config = ctx.config
    base = _fleet_base(config)
    model = config.pipeline_model
    payload: Dict = {"artifact": artifact, "model": model}
    if retrieval_index:
        payload["retrieval_index"] = retrieval_index
    if getattr(ctx, "trace", None) is not None:
        # the rollout's spans (router admin, swap driver, every host's
        # reload fan-out) parent under the pipeline run's trace id
        payload["traceparent"] = ctx.trace.traceparent()
    status, body = _http_json(stage, "POST", base + "/admin/reload",
                              payload)
    if status not in (200, 202):
        raise StageFailed(
            stage, f"fleet reload request refused: HTTP {status} "
                   f"{json.dumps(body)[:300]}")
    deadline = time.monotonic() + config.pipeline_promote_timeout_s
    last: Dict = {}
    while time.monotonic() < deadline:
        time.sleep(0.25)
        status, view = _http_json(stage, "GET", base + "/fleet")
        if status != 200:
            continue
        swap = view.get("swap") or {}
        if swap.get("target") != artifact:
            continue  # an older rollout's status; ours not started yet
        last = {"swap": swap, "models": view.get("models", {})}
        if swap.get("state") in ("committed", "failed", "rolled_back"):
            return last
    _c_promotions("timeout").inc()
    raise StageFailed(
        stage, f"fleet rollout did not reach a terminal state within "
               f"{config.pipeline_promote_timeout_s:g}s "
               f"(last: {json.dumps(last)[:300]}); inspect GET /fleet")


# -------------------------------------------------------------- stages


def run_ingest(ctx: PipelineContext) -> Dict:
    """Pack new raw extractor output as a delta shard against the
    FROZEN incumbent vocab (no re-histogram, no sampling tiers — OOV
    is the designed fate of genuinely new words, and its rate is the
    exported aging signal). With --train_corpus_manifest, the shard is
    additionally APPENDED to the corpus manifest — the accumulated
    multi-shard corpus grows without ever re-packing prior data."""
    config = ctx.config
    raw = config.pipeline_raw
    if not raw or not os.path.isfile(raw):
        raise StageFailed("ingest",
                          f"--pipeline_raw {raw!r} is not a file")
    incumbent_ckpt, _epoch = newest_committed_checkpoint(
        config.model_load_path)
    if incumbent_ckpt is None:
        raise StageFailed(
            "ingest", f"no committed checkpoint under --load "
                      f"{config.model_load_path}")
    vocabs = _frozen_vocabs(config, incumbent_ckpt)
    delta_prefix = os.path.join(ctx.dir("delta"), "delta")
    packed = delta_prefix + ".train.c2vb"
    from code2vec_tpu.data.packed import PackedDataset, pack_raw
    from code2vec_tpu.data.reader import EstimatorAction
    rows = pack_raw(raw, packed, vocabs, None, None,
                    config.max_contexts, seed=config.seed,
                    num_workers=config.preprocess_workers, log=ctx.log)
    ds = PackedDataset(packed, vocabs)
    oov = measure_delta_oov(raw, ds, vocabs)
    obs.counter("pipeline_ingest_rows_total",
                "delta rows packed by pipeline ingest").inc(rows)
    for kind in ("target", "context"):
        obs.gauge("pipeline_ingest_oov_rate",
                  "OOV rate of the latest ingested delta shard against "
                  "the frozen vocab (kind=target: rows whose label is "
                  "OOV; kind=context: non-pad context slots that fell "
                  "to OOV)", kind=kind).set(oov[f"{kind}_oov_rate"])
    # post-filter trainable rows bound the fine-tune batch size
    train_rows = ds.steps_per_epoch(1, EstimatorAction.Train)
    if train_rows == 0:
        raise StageFailed(
            "ingest", f"delta shard has no trainable rows "
                      f"({rows} packed, all filtered: OOV target / no "
                      f"valid context)")
    ctx.log(f"Pipeline ingest: {rows} rows ({train_rows} trainable) "
            f"packed at {packed}; target OOV "
            f"{oov['target_oov_rate']:.4f}, context OOV "
            f"{oov['context_oov_rate']:.4f}")
    outputs = {"delta_prefix": delta_prefix, "packed": packed,
               "rows": rows, "train_rows": train_rows,
               "incumbent_ckpt": incumbent_ckpt,
               "target_oov_rate": oov["target_oov_rate"],
               "context_oov_rate": oov["context_oov_rate"]}
    manifest_path = getattr(config, "train_corpus_manifest", None)
    if manifest_path:
        # ACCUMULATE instead of re-pack: the delta shard joins the
        # corpus manifest (pure append — incumbent pack + every prior
        # delta stay byte-identical), so fine-tune trains over the
        # WHOLE accumulated corpus through ShardedCorpus rather than
        # the delta alone. Idempotent under re-run: a shard already
        # listed is left alone (pack_raw committed it atomically).
        from code2vec_tpu.data.packed import (
            _manifest_shard_path, append_manifest_shard, create_manifest,
            load_manifest,
        )
        try:
            if not os.path.isfile(manifest_path):
                manifest = create_manifest(manifest_path, [packed])
            else:
                manifest = load_manifest(manifest_path)
                listed = {os.path.abspath(
                    _manifest_shard_path(manifest_path, e))
                    for e in manifest["shards"]}
                if os.path.abspath(packed) in listed:
                    ctx.log(f"Pipeline ingest: {packed} already in "
                            f"{manifest_path} (re-run); manifest "
                            f"unchanged")
                else:
                    manifest = append_manifest_shard(manifest_path,
                                                     packed)
        except (ValueError, OSError) as e:
            # mixed vocab / drifted shard: refuse loudly — training on
            # a silently inconsistent corpus is the one unacceptable
            # outcome
            raise StageFailed("ingest",
                              f"corpus manifest accumulation refused: "
                              f"{e}")
        total = sum(int(e["rows"]) for e in manifest["shards"])
        ctx.log(f"Pipeline ingest: corpus manifest {manifest_path} now "
                f"{len(manifest['shards'])} shard(s), {total} rows")
        obs.gauge("pipeline_corpus_shards",
                  "shards in the accumulated training-corpus manifest"
                  ).set(len(manifest["shards"]))
        obs.gauge("pipeline_corpus_rows",
                  "total packed rows across the accumulated "
                  "training-corpus manifest").set(total)
        outputs.update(manifest=manifest_path,
                       manifest_shards=len(manifest["shards"]),
                       manifest_rows=total)
    return outputs


def run_finetune(ctx: PipelineContext) -> Dict:
    """Fine-tune from the latest committed checkpoint — on the delta
    shard alone, or (manifest mode) on the WHOLE accumulated corpus
    via --train_corpus_manifest — in a child CLI process
    (elastic-restore path: `--load` resolves to the newest VALID
    artifact and restores on whatever host count/mesh the child runs).
    A rerun after a mid-train kill resumes from the candidate's own
    newest committed checkpoint."""
    config = ctx.config
    ingest = ctx.outputs("ingest")
    save_base = os.path.join(ctx.dir("candidate"), "ckpt")
    # resume-aware source: a prior (killed) fine-tune attempt's own
    # committed checkpoint beats restarting from the incumbent
    prior, _ = newest_committed_checkpoint(save_base)
    load_from = save_base if prior is not None else \
        config.model_load_path
    _, incumbent_epoch = newest_committed_checkpoint(
        config.model_load_path)
    total_epochs = incumbent_epoch + config.pipeline_finetune_epochs
    # batch bounded by what the corpus can fill: the delta alone, or —
    # in manifest mode — the whole accumulated corpus (packed rows
    # upper-bound the trainable rows; with any realistic corpus the
    # configured batch wins)
    row_cap = int(ingest.get("manifest_rows") or ingest["train_rows"])
    batch = max(1, min(config.train_batch_size, row_cap))
    argv = ["--data", ingest["delta_prefix"],
            "--load", load_from,
            "--save", save_base,
            "--epochs", str(total_epochs),
            "--batch_size", str(batch),
            "--seed", str(config.seed),
            "--heartbeat_file",
            os.path.join(ctx.dir("finetune"), "train.heartbeat.json"),
            "--metrics_file",
            os.path.join(ctx.dir("finetune"), "train.metrics.prom")]
    if ingest.get("manifest"):
        # train over the accumulated multi-shard corpus, not the delta
        # re-pack (the tentpole: ingest appends, fine-tune reads the
        # manifest through ShardedCorpus)
        argv += ["--train_corpus_manifest", ingest["manifest"]]
    ctx.run_cli(argv, "finetune", "train")
    candidate, cand_epoch = newest_committed_checkpoint(save_base)
    if candidate is None:
        raise StageFailed(
            "finetune", f"train subprocess exited 0 but no committed "
                        f"checkpoint exists under {save_base}")
    return {"save_base": save_base, "candidate_ckpt": candidate,
            "epoch": cand_epoch, "batch_size": batch,
            "loaded_from": load_from}


def run_export(ctx: PipelineContext) -> Dict:
    """Export the candidate as a PR-8 release artifact (scheme from
    config), committed by directory rename so a kill mid-export leaves
    only a disposable `.tmp` dir."""
    config = ctx.config
    finetune = ctx.outputs("finetune")
    out = os.path.join(ctx.dir("candidate"), "artifact")
    tmp = out + ".tmp"
    # idempotent re-run: clear any casualty of a previous attempt
    shutil.rmtree(tmp, ignore_errors=True)
    shutil.rmtree(out, ignore_errors=True)
    argv = ["export", "--load", finetune["save_base"],
            "--artifact_out", tmp,
            "--release_scheme", config.release_scheme]
    if not config.release_quantize:
        argv.append("--no_quantize")
    if not config.release_aot:
        argv.append("--no_aot")
    ctx.run_cli(argv, "export", "export")
    meta_path = os.path.join(tmp, "release_meta.json")
    try:
        with open(meta_path) as f:
            fingerprint = json.load(f)["fingerprint"]
    except (OSError, ValueError, KeyError) as e:
        raise StageFailed("export",
                          f"exported artifact has no readable "
                          f"fingerprint ({meta_path}: {e})")
    os.rename(tmp, out)
    return {"artifact": out, "fingerprint": fingerprint,
            "scheme": config.release_scheme}


def run_shadow_eval(ctx: PipelineContext) -> Dict:
    """Candidate vs incumbent through the accuracy harness plus a
    replayed traffic slice; a tripped bar is a TERMINAL refusal."""
    fault_point("shadow_eval")
    config = ctx.config
    from code2vec_tpu.pipeline.shadow_eval import (
        GateBars, sample_traffic, shadow_compare,
    )
    export = ctx.outputs("export")
    lines: List[str] = []
    if config.pipeline_traffic:
        if not os.path.isfile(config.pipeline_traffic):
            ctx.log(f"Pipeline shadow eval: no traffic sample at "
                    f"{config.pipeline_traffic}; gating on the "
                    f"accuracy harness alone")
        else:
            with open(config.pipeline_traffic) as f:
                lines = sample_traffic(f, config.pipeline_shadow_samples,
                                       seed=config.seed)
    verdict = shadow_compare(config, config.pipeline_incumbent,
                             export["artifact"], lines,
                             bars=GateBars.from_config(config),
                             log=ctx.log)
    if not verdict["passed"]:
        raise GateRefused("shadow_eval",
                          "; ".join(verdict["reasons"]),
                          numbers=verdict["numbers"])
    ctx.log(f"Pipeline gate PASSED: "
            f"top1 {verdict['numbers']['top1_delta']:+.4f}, "
            f"f1 {verdict['numbers']['f1_delta']:+.4f}, agreement "
            f"{verdict['numbers']['topk_agreement']}")
    return dict(verdict["numbers"], gate="passed")


def run_promote(ctx: PipelineContext) -> Dict:
    """Canary-first fleet rollout of the gated candidate (the PR-13
    swap driver, through the router's admin surface). failed or
    rolled_back is TERMINAL — the driver already left/restored the
    incumbent on every host."""
    config = ctx.config
    export = ctx.outputs("export")
    if not config.pipeline_fleet:
        raise StageSkipped(
            f"no --pipeline_fleet router address; gated candidate is "
            f"ready at {export['artifact']}")
    fault_point("promote")
    result = drive_fleet_swap(ctx, "promote", export["artifact"])
    swap = result["swap"]
    outcome = swap.get("state")
    _c_promotions(outcome).inc()
    if outcome != "committed":
        raise PromoteFailed(
            "promote",
            f"fleet rollout {outcome}: {swap.get('error')} — the "
            f"incumbent is serving everywhere (driver "
            f"{'rolled the fleet back' if outcome == 'rolled_back' else 'halted at the canary'})",
            outcome=outcome,
            numbers={"swap_error": swap.get("error"),
                     "hosts": swap.get("hosts")})
    model_view = result.get("models", {}).get(config.pipeline_model, {})
    ctx.log(f"Pipeline promote committed: fleet on fingerprint "
            f"{swap.get('target_fingerprint')} "
            f"(mixed={model_view.get('mixed_fingerprints')})")
    return {"outcome": "committed",
            "fingerprint": swap.get("target_fingerprint"),
            "hosts": swap.get("hosts")}


def run_retrieval_refresh(ctx: PipelineContext) -> Dict:
    """Re-embed the delta shard with the promoted candidate, build a
    fresh ANN index carrying its fingerprint, and remount it across
    the fleet through the reload fan-out (the refuse/detach policy
    guards the transition on every replica; the model swap at promote
    detached any stale index under `detach`)."""
    config = ctx.config
    if not config.pipeline_refresh_retrieval:
        raise StageSkipped("--pipeline_refresh_retrieval not set")
    ingest = ctx.outputs("ingest")
    export = ctx.outputs("export")
    retr = ctx.dir("retrieval")
    store = os.path.join(retr, "store")
    index_out = os.path.join(retr, "index")
    corpus = ingest["delta_prefix"] + ".train.c2v"
    # embed resumes per committed shard across re-runs (PR-10)
    ctx.run_cli(["embed", "--artifact", export["artifact"],
                 "--test", corpus, "--embed_out", store,
                 "--embed_dtype", config.embed_dtype,
                 "--embed_shard_rows", str(config.embed_shard_rows)],
                "retrieval_refresh", "embed")
    tmp = index_out + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    shutil.rmtree(index_out, ignore_errors=True)
    ctx.run_cli(["index-build", "--vectors", store,
                 "--index_out", tmp,
                 "--nlist", str(config.index_nlist),
                 "--nprobe", str(config.index_nprobe),
                 "--index_metric", config.index_metric],
                "retrieval_refresh", "index-build")
    os.rename(tmp, index_out)
    outputs = {"store": store, "index": index_out,
               "fingerprint": export["fingerprint"]}
    if not config.pipeline_fleet:
        outputs["remount"] = "skipped (no fleet)"
        return outputs
    result = drive_fleet_swap(ctx, "retrieval_refresh",
                              export["artifact"],
                              retrieval_index=index_out)
    state = result["swap"].get("state")
    if state != "committed":
        raise StageFailed(
            "retrieval_refresh",
            f"index remount rollout {state}: "
            f"{result['swap'].get('error')} — prediction traffic is "
            f"unaffected; /neighbors stays on the detached/previous "
            f"index until remounted")
    outputs["remount"] = "committed"
    ctx.log(f"Pipeline retrieval refresh: index {index_out} remounted "
            f"fleet-wide behind fingerprint {export['fingerprint']}")
    return outputs


DEFAULT_STAGES = (
    ("ingest", run_ingest),
    ("finetune", run_finetune),
    ("export", run_export),
    ("shadow_eval", run_shadow_eval),
    ("promote", run_promote),
    ("retrieval_refresh", run_retrieval_refresh),
)
