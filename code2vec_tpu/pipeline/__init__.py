"""Continuous-training pipeline: delta-ingest -> fine-tune -> export ->
shadow-eval -> canary promote -> retrieval refresh, as one supervised,
crash-safe loop (the `pipeline` CLI subcommand; README "Continuous
training").

Every ingredient exists elsewhere as an island — elastic resume (PR 6),
release export (PR 8), validated hot-swap with rollback (PR 9),
fingerprint-pinned retrieval (PR 10), the coordinated fleet swap
(PR 13). This package closes them into one stage machine
(pipeline/supervisor.py) whose state lives in a journaled manifest
(pipeline/manifest.py, tmp+rename like the checkpoint protocol): a
SIGKILL at any stage boundary resumes idempotently from the last
committed stage, and a candidate that regresses the quality gate
(pipeline/shadow_eval.py) or fails its fleet rollout is REFUSED with
the incumbent left serving everywhere.
"""
