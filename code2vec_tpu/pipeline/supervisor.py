"""The pipeline stage machine: drive ingest -> fine-tune -> export ->
shadow-eval -> promote -> retrieval refresh with crash-safe,
journaled progress (the `pipeline` CLI subcommand body).

Robustness contract (README "Continuous training"):

- Every stage's outputs commit atomically and its completion is
  recorded in the journaled manifest (pipeline/manifest.py) — a
  SIGKILL at ANY stage boundary resumes idempotently from the last
  committed stage, and committed work is never repeated.
- The fault point `pipeline_stage` (utils/faults.py) is crossed TWICE
  per stage — at stage start, and again with the stage's work done but
  its manifest commit pending — so the chaos suite can kill the
  supervisor at every boundary of the machine; `shadow_eval` and
  `promote` fire inside their stages.
- A refused quality gate or a failed/rolled-back fleet rollout is
  TERMINAL: the incumbent keeps serving everywhere, the verdict (with
  its numbers) lands in the manifest, the heartbeat and a
  flight-recorder incident, and the supervisor exits nonzero. Reruns
  of a terminal manifest re-report the verdict — every rerun converges
  to the same terminal manifest.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple

from code2vec_tpu import obs
from code2vec_tpu.obs.reqtrace import RequestTrace
from code2vec_tpu.pipeline.manifest import (
    PipelineManifest, PipelineStateError,
)
from code2vec_tpu.pipeline.stages import (
    DEFAULT_STAGES, GateRefused, PipelineContext, PromoteFailed,
    StageFailed, StageSkipped,
)
from code2vec_tpu.utils.faults import fault_point


def _h_stage(stage: str):
    return obs.histogram(
        "pipeline_stage_seconds",
        "wall time of one pipeline stage attempt that reached its "
        "manifest commit", stage=stage)


def _c_stage(stage: str, outcome: str):
    return obs.counter(
        "pipeline_stages_total",
        "pipeline stage attempts by outcome (committed, skipped, "
        "refused, failed)", stage=stage, outcome=outcome)


def _c_runs(outcome: str):
    return obs.counter(
        "pipeline_runs_total",
        "pipeline runs reaching a terminal verdict (committed, "
        "gate_refused, promote_failed) or failing a stage attempt "
        "(error)", outcome=outcome)


class PipelineSupervisor:
    """One pipeline run over one state dir. `stages` is the injection
    seam: [(name, fn(ctx))] — production uses stages.DEFAULT_STAGES,
    the chaos suite scripts cheap stage bodies around the REAL
    manifest/fault/terminal machinery."""

    def __init__(self, config, stages: Optional[List[Tuple]] = None,
                 log=None, params_fingerprint: Optional[str] = None):
        self.config = config
        self.log = log or config.log
        if not config.pipeline_dir:
            raise PipelineStateError(
                "pipeline requires --pipeline_dir DIR (the journaled "
                "state root)")
        self.run_dir = os.path.abspath(config.pipeline_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.heartbeat_path = config.heartbeat_file or os.path.join(
            self.run_dir, "pipeline.heartbeat.json")
        self.stages = list(stages if stages is not None
                           else DEFAULT_STAGES)
        self.flight = obs.default_flight_recorder()
        self.flight.configure(
            dump_dir=self.run_dir,
            max_dumps=getattr(config, "serve_flight_max_dumps", 64),
            log=self.log)
        self.manifest = PipelineManifest.load_or_create(
            self.run_dir,
            params_fingerprint or self._params_fingerprint(),
            [name for name, _fn in self.stages], log=self.log,
            model=config.pipeline_model)
        self.ctx = PipelineContext(config, self.manifest, self.run_dir,
                                   self.log)
        # One trace id per pipeline run: every stage span — and,
        # through drive_fleet_swap's traceparent, the whole fleet
        # rollout it triggers — stitches under this id (`fleet trace`).
        self.trace = RequestTrace.from_headers(None)
        self.ctx.trace = self.trace
        self.trace_path = getattr(config, "trace_export", None) \
            or os.path.join(self.run_dir, "pipeline.trace.json")
        if getattr(config, "trace_export", None):
            obs.default_tracer().enable()
            self.log(f"Pipeline run trace id {self.trace.trace_id} "
                     f"(stitch with `fleet --fleet_trace_id "
                     f"{self.trace.trace_id}`)")

    # ------------------------------------------------------- identity

    def _params_fingerprint(self) -> str:
        """Identity of the run REQUEST: resuming this dir with
        different inputs/bars is refused (manifest.py). The raw delta
        file participates by path+size so a silently swapped input
        cannot graft onto a half-finished run."""
        config = self.config
        raw = config.pipeline_raw
        raw_size = None
        if raw and os.path.isfile(raw):
            raw_size = os.path.getsize(raw)
        ident = {
            "raw": os.path.abspath(raw) if raw else None,
            "raw_size": raw_size,
            "load": (os.path.abspath(config.model_load_path)
                     if config.model_load_path else None),
            "incumbent": (os.path.abspath(config.pipeline_incumbent)
                          if config.pipeline_incumbent else None),
            "test": config.test_data_path or None,
            "traffic": (os.path.abspath(config.pipeline_traffic)
                        if config.pipeline_traffic else None),
            "finetune_epochs": config.pipeline_finetune_epochs,
            "bars": [config.pipeline_gate_top1_drop,
                     config.pipeline_gate_topk_drop,
                     config.pipeline_gate_f1_drop,
                     config.pipeline_gate_min_agreement],
            "scheme": config.release_scheme,
            "fleet": config.pipeline_fleet or None,
            "model": config.pipeline_model,
            "refresh": bool(config.pipeline_refresh_retrieval),
            "seed": config.seed,
        }
        return hashlib.sha256(
            json.dumps(ident, sort_keys=True).encode()).hexdigest()[:16]

    # ------------------------------------------------------ heartbeat

    def _heartbeat(self, status: str, **extra) -> None:
        obs.exporters.write_heartbeat(
            self.heartbeat_path, status=status, role="pipeline",
            pipeline_dir=self.run_dir,
            stages_committed=[n for n, _ in self.stages
                              if self.manifest.stage(n)], **extra)

    def _export_trace(self) -> None:
        if not getattr(self.config, "trace_export", None):
            return
        if not len(obs.default_tracer()):
            return
        try:
            obs.default_tracer().export_chrome_trace(self.trace_path)
        except OSError as e:
            self.log(f"Pipeline trace export failed: {e}")

    # ------------------------------------------------------------ run

    def run(self) -> int:
        terminal = self.manifest.terminal
        if terminal is not None:
            outcome = terminal["outcome"]
            self.log(f"Pipeline manifest is already terminal "
                     f"({outcome}); re-reporting. "
                     f"{json.dumps(terminal['detail'])[:400]}")
            self._heartbeat("done" if outcome == "committed"
                            else outcome, terminal=terminal)
            return 0 if outcome == "committed" else 1
        for name, fn in self.stages:
            rec = self.manifest.stage(name)
            if rec is not None:
                self.log(f"Pipeline stage {name}: already "
                         f"{rec['status']} "
                         f"(at {rec.get('completed_at')}); skipping")
                continue
            self._heartbeat("running", stage=name)
            fault_point("pipeline_stage")  # boundary: stage start
            self.manifest.journal("stage_start", stage=name)
            self.log(f"Pipeline stage {name}: starting")
            t0 = time.monotonic()
            try:
                with self.trace.span(f"pipeline.{name}", stage=name):
                    outputs = fn(self.ctx)
                status = "committed"
            except StageSkipped as e:
                outputs = {"reason": str(e)}
                status = "skipped"
                self.log(f"Pipeline stage {name}: skipped ({e})")
            except GateRefused as e:
                return self._terminal_failure(
                    "gate_refused", name, str(e), e.numbers,
                    incident="pipeline_gate_refused")
            except PromoteFailed as e:
                return self._terminal_failure(
                    "promote_failed", name, str(e),
                    dict(e.numbers, rollout_outcome=e.outcome),
                    incident="pipeline_promote_failed")
            except StageFailed as e:
                return self._stage_failure(name, str(e))
            except Exception as e:  # noqa: BLE001 — a stage body
                # raising OUTSIDE the StageFailed family (a corrupt
                # artifact's ValueError, a disk-full OSError) is still
                # a failed ATTEMPT: record it everywhere the runbook
                # looks instead of dying with a bare traceback and a
                # forever-"running" heartbeat. Not terminal — the
                # manifest keeps no record, a rerun retries.
                return self._stage_failure(
                    name, f"{type(e).__name__}: {e}")
            duration = time.monotonic() - t0
            # boundary: work done, manifest commit pending — a kill
            # here re-runs the stage (its writers are idempotent),
            # never skips it
            fault_point("pipeline_stage")
            self.manifest.commit_stage(name, outputs,
                                       duration_s=duration,
                                       status=status)
            _h_stage(name).observe(duration)
            _c_stage(name, status).inc()
            self._export_trace()
            self.log(f"Pipeline stage {name}: {status} in "
                     f"{duration:.1f}s")
        detail = self._run_summary()
        self.manifest.set_terminal("committed", detail)
        _c_runs("committed").inc()
        self._heartbeat("done", terminal=self.manifest.terminal)
        self.log(f"Pipeline run COMMITTED: "
                 f"{json.dumps(detail)[:400]}")
        return 0

    def _run_summary(self) -> Dict:
        detail: Dict = {}
        export = self.manifest.stage("export")
        if export and export.get("outputs"):
            detail["artifact"] = export["outputs"].get("artifact")
            detail["fingerprint"] = export["outputs"].get("fingerprint")
        promote = self.manifest.stage("promote")
        if promote:
            detail["promote"] = promote["status"]
        return detail

    def _stage_failure(self, name: str, error: str) -> int:
        """A failed stage ATTEMPT (not a verdict): counted, heartbeat
        status=error, immediate flight dump, rc 1 — and the manifest
        untouched, so a rerun resumes exactly here."""
        _c_stage(name, "failed").inc()
        _c_runs("error").inc()
        self.flight.incident("pipeline_stage_failed", immediate=True,
                             stage=name, error=error)
        self._heartbeat("error", stage=name, error=error)
        self._export_trace()
        self.log(f"Pipeline stage {name} FAILED (rerun resumes here): "
                 f"{error}")
        return 1

    def _terminal_failure(self, outcome: str, stage: str, error: str,
                          numbers: Dict, incident: str) -> int:
        """A verdict rerunning cannot change: record it everywhere the
        runbook says to look — manifest (terminal), heartbeat (with the
        gate's numbers), flight recorder (immediate dump), metrics —
        and exit nonzero with the incumbent serving everywhere."""
        _c_stage(stage, "refused").inc()
        _c_runs(outcome).inc()
        safe_numbers = {k: v for k, v in (numbers or {}).items()
                        if isinstance(v, (int, float, str, bool,
                                          type(None)))}
        self.manifest.set_terminal(
            outcome, {"stage": stage, "error": error, **safe_numbers})
        self.flight.incident(incident, immediate=True, stage=stage,
                             error=error, **safe_numbers)
        self._heartbeat(outcome, stage=stage, error=error,
                        gate=safe_numbers)
        self._export_trace()
        self.log(f"Pipeline {outcome.upper()} at stage {stage}: "
                 f"{error}")
        return 1


def pipeline_main(config, argv=None) -> int:
    """`pipeline` CLI subcommand body (cli.main dispatches here before
    any model/jax state is built — stages own their heavy children)."""
    try:
        supervisor = PipelineSupervisor(config)
    except PipelineStateError as e:
        config.log(f"Pipeline refused to start: {e}")
        return 1
    return supervisor.run()
