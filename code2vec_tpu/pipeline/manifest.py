"""Journaled pipeline manifest: the stage machine's durable state.

One JSON file (`pipeline_manifest.json` in the pipeline run dir)
rewritten atomically (tmp+rename, the checkpoint commit discipline —
obs.exporters._atomic_write) at every state transition, so a reader
never observes a torn manifest and a SIGKILL between transitions loses
at most the uncommitted stage's work:

- `stages`: {name: {status, outputs, completed_at, duration_s}} — a
  stage is re-run on resume iff it has no record here. Stage OUTPUTS
  (packed delta shards, checkpoints, release artifacts, index dirs)
  are themselves committed atomically by their writers, so re-running
  an uncommitted stage is idempotent.
- `journal`: append-only event list (stage start/commit, terminal
  transitions) — the flight-recorder-style trail of what the
  supervisor was doing when it died.
- `terminal`: the run's final verdict (committed | gate_refused |
  promote_failed), set exactly once. A rerun of a terminal manifest
  re-reports the verdict instead of re-driving stages — reruns
  CONVERGE to the same terminal manifest.

The manifest records a `params_fingerprint` of the run's defining
inputs (delta file, incumbent, gate bars, ...): resuming a pipeline
dir with DIFFERENT inputs is refused loudly (PipelineStateError) —
half of run A's stages followed by half of run B's would be a silently
corrupt candidate.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional

from code2vec_tpu.obs import exporters

MANIFEST_NAME = "pipeline_manifest.json"
SCHEMA_VERSION = 1

# journal ring bound: a long retry loop must not grow the manifest
# without bound (the newest entries are the ones a postmortem needs)
_JOURNAL_CAP = 256


class PipelineStateError(ValueError):
    """A pipeline dir whose manifest cannot be resumed by this run
    (schema from the future, or different run inputs)."""


class PipelineManifest:
    def __init__(self, path: str, data: Dict):
        self.path = path
        self.data = data

    # ------------------------------------------------------------- load

    @classmethod
    def load_or_create(cls, pipeline_dir: str, params_fingerprint: str,
                       stage_names: List[str],
                       log=None,
                       model: Optional[str] = None) -> "PipelineManifest":
        path = os.path.join(os.path.abspath(pipeline_dir), MANIFEST_NAME)
        if os.path.isfile(path):
            try:
                with open(path) as f:
                    data = json.load(f)
            except ValueError as e:
                raise PipelineStateError(
                    f"{path} is unreadable ({e}); the manifest is "
                    f"written atomically, so this is not a crash "
                    f"artifact — move it aside or use a fresh "
                    f"--pipeline_dir")
            if int(data.get("schema_version", -1)) != SCHEMA_VERSION:
                raise PipelineStateError(
                    f"{path} has schema_version "
                    f"{data.get('schema_version')!r}; this build "
                    f"understands {SCHEMA_VERSION}")
            if data.get("params_fingerprint") != params_fingerprint:
                raise PipelineStateError(
                    f"{path} records a run with different inputs "
                    f"(params fingerprint "
                    f"{data.get('params_fingerprint')!r} != "
                    f"{params_fingerprint!r}). Resuming would mix two "
                    f"runs' stages into one candidate; finish/inspect "
                    f"the old run or use a fresh --pipeline_dir")
            if log is not None:
                done = [n for n in stage_names
                        if data.get("stages", {}).get(n)]
                log(f"Pipeline manifest resumed from {path}: "
                    f"{len(done)}/{len(stage_names)} stage(s) already "
                    f"committed ({', '.join(done) or 'none'})")
            return cls(path, data)
        data = {
            "schema_version": SCHEMA_VERSION,
            "params_fingerprint": params_fingerprint,
            "stage_names": list(stage_names),
            # the X-Model group this run promotes for: a postmortem of
            # a refused promote reads WHICH group the run targeted
            # straight off the manifest instead of re-deriving it from
            # flags (the group is validated against the router's
            # --fleet_models map by FleetSwapDriver.request)
            "model": model,
            "created_at": time.time(),
            "stages": {},
            "journal": [],
            "terminal": None,
        }
        manifest = cls(path, data)
        manifest._write()
        return manifest

    # ------------------------------------------------------------ state

    def stage(self, name: str) -> Optional[Dict]:
        return self.data["stages"].get(name)

    @property
    def terminal(self) -> Optional[Dict]:
        return self.data.get("terminal")

    def journal(self, event: str, **detail) -> None:
        rec = {"t": time.time(), "event": event}
        rec.update(detail)
        self.data["journal"].append(rec)
        self.data["journal"] = self.data["journal"][-_JOURNAL_CAP:]
        self._write()

    def commit_stage(self, name: str, outputs: Dict,
                     duration_s: Optional[float] = None,
                     status: str = "committed") -> None:
        self.data["stages"][name] = {
            "status": status,
            "outputs": outputs,
            "completed_at": time.time(),
            "duration_s": (None if duration_s is None
                           else round(duration_s, 3)),
        }
        # one atomic write commits record + journal entry together
        self.journal("stage_commit", stage=name, status=status)

    def set_terminal(self, outcome: str, detail: Dict) -> None:
        self.data["terminal"] = {"outcome": outcome,
                                 "completed_at": time.time(),
                                 "detail": detail}
        self.journal("terminal", outcome=outcome)

    # ------------------------------------------------------------ write

    def _write(self) -> None:
        exporters._atomic_write(
            self.path,
            json.dumps(self.data, indent=1, sort_keys=True) + "\n")
