"""Release-artifact runtime: the quantized serving/eval fast path.

`ReleaseModel` is the serving-side twin of the training facade: it
exposes the exact `predict` surface PredictionServer and the REPL drive
(BucketedPredictMixin in model_facade.py — same line parsing, context
bucketing, compiled-step cache), but is built from a release artifact
(release/artifact.py) instead of a checkpoint:

- tables live on device as int8 + per-row f32 scales (or f32 for an
  unquantized artifact); the fp32 training tables, the Adam state and
  the Orbax machinery are never materialized — a replica's RSS is the
  artifact, not the checkpoint;
- the forward fuses dequant into the gathers (ops/quant.py) and streams
  the target classifier through the blockwise top-k merge (ops/topk.py)
  — the (B, 246K) logit row never exists;
- each (rows, context-bucket) serve shape cold-starts from the
  artifact's AOT lowering (jax.export) when one matches the current
  backend, falling back to a fresh jit otherwise (counted in
  `serving_aot_loads_total{outcome=...}`).

The forward math mirrors models/code2vec.py transform_gathered/encode
with deterministic=True; eval CE comes from the blockwise logsumexp
minus the gathered label logit, so the standard Evaluator can score an
artifact directly through `ReleaseModel.eval_step`.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from code2vec_tpu import obs
from code2vec_tpu.model_facade import BucketedPredictMixin
from code2vec_tpu.ops.attention import masked_single_query_attention
from code2vec_tpu.ops.quant import table_gather
from code2vec_tpu.ops.topk import (
    blockwise_matmul_top_k, gathered_label_logits,
)
from code2vec_tpu.release.artifact import (
    QUANTIZED_SCHEMES, SCHEME_FP8_E4M3, SCHEME_FP8_E5M2, SCHEME_INT4,
    SCHEME_INT8, ReleaseArtifact, load_artifact, table_dim,
)
from code2vec_tpu.training.step import EvalOutputs
from code2vec_tpu.vocab import Code2VecVocabs


def _backend_matches(backend: str, platforms) -> bool:
    """True when the current jax backend can run an AOT lowering
    exported for `platforms`. jax.export records lowering platform
    names ('cpu', 'tpu', 'cuda', 'rocm') while jax.default_backend()
    reports the backend family ('cpu', 'tpu', 'gpu') — on GPU the two
    vocabularies differ, so a literal `in` test would send every GPU
    replica down the jit fallback."""
    names = {str(p).lower() for p in platforms if p}
    if backend in names:
        return True
    return backend == "gpu" and bool(names & {"cuda", "rocm"})


def _aot_counter(outcome: str):
    return obs.counter(
        "serving_aot_loads_total",
        "predict-step builds by source: aot (deserialized jax.export "
        "lowering), jit_fallback (no matching lowering / wrong "
        "platform), jit_error (lowering present but unusable)",
        outcome=outcome)


def make_release_step(meta: dict, mips_topk=None):
    """Pure serve/eval function over artifact params:
    (params, src, pth, tgt, mask, labels, valid) ->
    (topk_values, topk_indices, code_vectors, attention, loss_sum).

    `mips_topk` (a retrieval/mips.py `MipsHead.topk_fn` closure)
    replaces the exact blockwise classifier head with the
    approximate-MIPS candidate search — serve/predict only, never the
    accuracy-eval path (config.verify rejects the combination); its
    steps report loss_sum = 0 (no logsumexp exists over a candidate
    subset, and no serving consumer reads it).

    Returns a plain tuple (not EvalOutputs) so jax.export can serialize
    the output pytree without namedtuple registration; callers wrap.
    """
    dims = meta["dims"]
    scheme = meta["quantization"]["scheme"]
    quantized = scheme in QUANTIZED_SCHEMES
    int4 = scheme == SCHEME_INT4
    compute_dtype = jnp.dtype(meta["compute_dtype"])
    k = min(int(meta["topk"]), int(dims["real_target_vocab_size"]))
    raw_block = meta.get("topk_block_size")
    block = 4096 if raw_block is None else int(raw_block)
    if block <= 0:
        # The exporter pinned the classic full-logits path (--topk_block
        # 0): one block spanning the table computes exactly the full
        # matmul + lax.top_k, so honoring it is a block of V rows — not
        # a silent coercion back to the 4096 default.
        block = int(dims["target_vocab_size"])
    oov_floor = int(dims["target_oov_floor"])
    real_v = int(dims["real_target_vocab_size"])

    def scale(params, name):
        return params[f"{name}_scale"] if quantized else None

    def int4_dim(name):
        # int4 tables travel packed; their consumers need the unpacked
        # column count (ops/quant.py unpack_int4)
        return table_dim(dims, name) if int4 else None

    def step(params, src, pth, tgt, mask, labels, valid):
        tok, tok_s = params["token_embedding"], scale(params, "token_embedding")
        src_rows = table_gather(tok, tok_s, src,
                                int4_dim=int4_dim("token_embedding"))
        tgt_rows = table_gather(tok, tok_s, tgt,
                                int4_dim=int4_dim("token_embedding"))
        pth_rows = table_gather(params["path_embedding"],
                                scale(params, "path_embedding"), pth,
                                int4_dim=int4_dim("path_embedding"))
        # concat/cast/tanh-transform/attention exactly as
        # models/code2vec.py transform_gathered + encode (deterministic).
        # Hand-mirrored rather than routed through module.apply (the
        # flax param tree would have to bind int8 tables it never
        # reads); any drift from the canonical forward fails
        # test_release_fp32_forward_matches_facade in tests/test_quant.py.
        ctx = jnp.concatenate([src_rows, pth_rows, tgt_rows],
                              axis=-1).astype(compute_dtype)
        transformed = jnp.tanh(jnp.einsum(
            "bmc,cd->bmd", ctx, params["transform"].astype(compute_dtype),
            preferred_element_type=jnp.float32)).astype(compute_dtype)
        code_vectors, attention = masked_single_query_attention(
            transformed, params["attention"][:, 0], mask)
        code_vectors = code_vectors.astype(jnp.float32)
        if mips_topk is not None:
            values, indices = mips_topk(code_vectors)
            return (values, indices, code_vectors, attention,
                    jnp.zeros((), jnp.float32))
        target_s = scale(params, "target_embedding")
        out = blockwise_matmul_top_k(
            code_vectors, params["target_embedding"], k, block,
            scales=target_s, valid_rows=real_v, compute_dtype=compute_dtype,
            int4_dim=int4_dim("target_embedding"))
        label_logit = gathered_label_logits(
            code_vectors, params["target_embedding"], labels,
            scales=target_s, compute_dtype=compute_dtype,
            int4_dim=int4_dim("target_embedding"))
        loss_rows = valid & (labels > oov_floor)
        ce = (out.lse - label_logit) * loss_rows.astype(jnp.float32)
        return (out.values, out.indices.astype(jnp.int32), code_vectors,
                attention, jnp.sum(ce))

    return step


def _table_device_dtype(scheme: str):
    """Device dtype of the table params per scheme. fp8 payloads are
    bitcast from their on-disk uint8 patterns back to the fp8 dtype at
    load, so the step's astype decodes them; int4 stays packed uint8."""
    return {
        SCHEME_INT8: jnp.int8,
        SCHEME_FP8_E4M3: jnp.float8_e4m3fn,
        SCHEME_FP8_E5M2: jnp.float8_e5m2,
        SCHEME_INT4: jnp.uint8,
    }.get(scheme, jnp.float32)


def param_specs(meta: dict) -> Dict[str, jax.ShapeDtypeStruct]:
    """ShapeDtypeStructs of the artifact param tree (AOT export specs)."""
    dims = meta["dims"]
    scheme = meta["quantization"]["scheme"]
    quantized = scheme in QUANTIZED_SCHEMES
    d_tok, d_path = int(dims["token_dim"]), int(dims["path_dim"])
    code_dim = d_path + 2 * d_tok
    shapes = {
        "token_embedding": (int(dims["token_vocab_size"]), d_tok),
        "path_embedding": (int(dims["path_vocab_size"]), d_path),
        "target_embedding": (int(dims["target_vocab_size"]), code_dim),
    }
    if scheme == SCHEME_INT4:
        shapes = {name: (v, (d + 1) // 2)
                  for name, (v, d) in shapes.items()}
    table_dtype = _table_device_dtype(scheme)
    specs = {name: jax.ShapeDtypeStruct(shape, table_dtype)
             for name, shape in shapes.items()}
    if quantized:
        for name, shape in shapes.items():
            specs[f"{name}_scale"] = jax.ShapeDtypeStruct(
                (shape[0], 1), jnp.float32)
    specs["transform"] = jax.ShapeDtypeStruct((code_dim, code_dim),
                                              jnp.float32)
    specs["attention"] = jax.ShapeDtypeStruct((code_dim, 1), jnp.float32)
    return specs


def batch_specs(rows: int, m: int) -> Tuple[jax.ShapeDtypeStruct, ...]:
    return (jax.ShapeDtypeStruct((rows, m), jnp.int32),   # src
            jax.ShapeDtypeStruct((rows, m), jnp.int32),   # pth
            jax.ShapeDtypeStruct((rows, m), jnp.int32),   # tgt
            jax.ShapeDtypeStruct((rows, m), jnp.float32),  # mask
            jax.ShapeDtypeStruct((rows,), jnp.int32),     # labels
            jax.ShapeDtypeStruct((rows,), jnp.bool_))     # valid


def aot_export_serve_functions(out_dir: str, meta: dict, log=print) -> dict:
    """jax.export every (serve_batch_size, bucket) serve shape into
    `<out_dir>/aot/`; returns the meta["aot"] record. Lowerings are
    platform-tagged — a consumer on another backend jit-falls-back."""
    import os

    from jax import export as jax_export

    aot_dir = os.path.join(out_dir, "aot")
    os.makedirs(aot_dir, exist_ok=True)
    step = make_release_step(meta)
    specs = param_specs(meta)
    rows = int(meta["serve_batch_size"])
    entries = {}
    platforms = None
    t0 = time.perf_counter()
    for m in meta["buckets"]:
        exported = jax_export.export(jax.jit(step))(specs,
                                                    *batch_specs(rows, m))
        if platforms is None:
            platforms = list(exported.platforms)
        name = f"serve_r{rows}_m{m}.jaxexport"
        with open(os.path.join(aot_dir, name), "wb") as f:
            f.write(exported.serialize())
        entries[f"r{rows}_m{m}"] = f"aot/{name}"
    record = {
        "platform": jax.default_backend(),
        "platforms": platforms,
        "jax_version": jax.__version__,
        "entries": entries,
    }
    log(f"AOT-exported {len(entries)} serve shape(s) "
        f"(rows={rows}, buckets={list(meta['buckets'])}) for platform "
        f"{record['platform']} in {time.perf_counter() - t0:.2f}s")
    return record


class ReleaseModel(BucketedPredictMixin):
    """Serving/eval model over a release artifact — drop-in for the
    facade on the predict surface (PredictionServer, InteractivePredictor,
    offline predict, Evaluator via `eval_step`)."""

    def __init__(self, config, artifact: Optional[ReleaseArtifact] = None,
                 log=None):
        self.config = config
        self.log = log or config.log
        self.artifact = artifact or load_artifact(config.serve_artifact)
        meta = self.meta = self.artifact.meta
        self.mesh = None
        # The artifact is authoritative for everything that shapes the
        # compiled steps and the parse: a mismatched CLI override would
        # silently compile shapes the AOT store doesn't have (or parse
        # at the wrong context budget).
        config.max_contexts = int(meta["max_contexts"])
        config.separate_oov_and_pad = bool(meta["separate_oov_and_pad"])
        if config.top_k_words_considered_during_prediction != \
                int(meta["topk"]):
            # The serve step (and its AOT lowerings) are baked at the
            # export-time k; honoring a different serve-time --topk
            # would silently truncate predictions and mis-denominate
            # top-k metrics, so the artifact wins and the override is
            # visible in the log.
            self.log(
                f"topk {config.top_k_words_considered_during_prediction} "
                f"differs from the artifact's exported {meta['topk']}: "
                f"the artifact is authoritative (re-export to change k)")
            config.top_k_words_considered_during_prediction = \
                int(meta["topk"])
        self._context_buckets = tuple(int(b) for b in meta["buckets"])
        art_rows = int(meta["serve_batch_size"])
        if config.serve_batch_size != art_rows:
            fields = getattr(type(config), "__dataclass_fields__", {})
            default_rows = getattr(fields.get("serve_batch_size"),
                                   "default", None)
            explicit = "serve_batch_size" in getattr(
                config, "explicit_knobs", ())
            if config.serve_batch_size == default_rows and not explicit:
                # The consumer never asked for a batch size — it holds
                # the config default and the flag was not on the command
                # line (explicit_knobs). Adopting the artifact's exported
                # size keeps every serve shape on its AOT lowering;
                # leaving the default would silently trade the entire
                # trace-free cold start for nothing. An EXPLICIT
                # --serve_batch_size always wins, even when it equals
                # the default — the operator may be bounding per-request
                # latency/memory on a small replica.
                self.log(
                    f"adopting the artifact's AOT-exported "
                    f"serve_batch_size {art_rows} (config held the "
                    f"default {default_rows})")
                config.serve_batch_size = art_rows
            else:
                self.log(
                    f"serve_batch_size {config.serve_batch_size} differs "
                    f"from the artifact's AOT-exported {art_rows}: serve "
                    f"shapes will jit-compile instead of AOT-loading")
        self.vocabs = Code2VecVocabs.load(
            self.artifact.dictionaries_path,
            separate_oov_and_pad=config.separate_oov_and_pad)
        # Device-resident artifact params: quantized tables + f32 scales
        # (one transfer each; the mmap'd host copies are dropped after
        # this). fp8 payloads travel on disk as uint8 bit patterns
        # (numpy's npy mmap cannot represent ml_dtypes) and are viewed
        # back to their fp8 dtype here, so the step's astype decodes
        # them; int4 tables stay packed (unpacked per gathered row).
        import ml_dtypes
        fp8_np = {SCHEME_FP8_E4M3: ml_dtypes.float8_e4m3fn,
                  SCHEME_FP8_E5M2: ml_dtypes.float8_e5m2}.get(
            self.artifact.scheme)
        mips_nprobe = int(getattr(config, "serve_mips_nprobe", 0) or 0)
        # Batch-shape-aware head dispatch (--serve_mips_crossover, the
        # PR-14 residue: MIPS wins 10-56x single-row but loses at bulk):
        # batches with <= mips_rows live rows route to the MIPS head,
        # bulk shapes to the exact blockwise head. -1 adopts the
        # crossover the export calibration recorded in the artifact
        # meta (mips_crossover) and falls back to legacy all-MIPS for
        # artifacts without one; 0 disables MIPS entirely (exact-only,
        # bit-for-bit the nprobe=0 path); a crossover at or above the
        # serve batch size IS all-MIPS (every batch is below it).
        crossover = int(getattr(config, "serve_mips_crossover", -1))
        self.mips_rows = 0          # hybrid threshold; 0 = no split
        self._mips_all = False
        if mips_nprobe > 0:
            if crossover == 0:
                mips_nprobe = 0
            elif crossover < 0:
                calibrated = int(meta.get("mips_crossover", 0) or 0)
                if calibrated > 0:
                    self.mips_rows = calibrated
                else:
                    self._mips_all = True
            else:
                self.mips_rows = crossover
            if self.mips_rows >= int(config.serve_batch_size):
                self._mips_all, self.mips_rows = True, 0
        self.params = {}
        for name, arr in self.artifact.tables.items():
            if self._mips_all and name.startswith("target_embedding"):
                # all-MIPS: the head (below) holds the list-reordered
                # copy and the exact head never runs, so transferring
                # the original-order table would double the dominant
                # table's device footprint. Hybrid dispatch keeps it —
                # the exact head serves every bulk batch.
                continue
            if fp8_np is not None and not name.endswith(".scale") \
                    and arr.dtype == np.uint8:
                arr = np.asarray(arr).view(fp8_np)
            self.params[name.replace(".scale", "_scale")] = jnp.asarray(arr)
        self._step_fn = make_release_step(meta)
        # Approximate-MIPS prediction head (--serve_mips_nprobe > 0):
        # built once from the artifact's (quantized) target table; the
        # predict steps then search nprobe coarse lists instead of
        # streaming the whole classifier. AOT lowerings bake the exact
        # head, so MIPS steps always jit (logged below); the exact
        # `_step_fn` remains the fallback/eval program.
        self.mips_head = None
        self._mips_step = None
        if mips_nprobe > 0:
            from code2vec_tpu.retrieval.mips import MipsHead
            dims = meta["dims"]
            int4_dim = (int(dims["path_dim"]) + 2 * int(dims["token_dim"])
                        if self.artifact.scheme == SCHEME_INT4 else None)
            # Build from the HOST-side artifact tables (fp8 viewed to
            # its ml_dtypes type, like the device-param load above) —
            # the head holds the list-reordered quantized rows on
            # device. All-MIPS skipped the original-order table in the
            # device-param loop above (the MIPS step never reads it)
            # so the dominant table is device-resident exactly once;
            # hybrid dispatch pays for both copies because the exact
            # head still serves every bulk batch.
            tgt = np.asarray(self.artifact.tables["target_embedding"])
            if fp8_np is not None and tgt.dtype == np.uint8:
                tgt = tgt.view(fp8_np)
            tgt_scale = self.artifact.tables.get("target_embedding.scale")
            self.mips_head = MipsHead.build(
                tgt,
                None if tgt_scale is None else np.asarray(tgt_scale),
                real_vocab=int(dims["real_target_vocab_size"]),
                nlist=int(getattr(config, "serve_mips_nlist", 0) or 0),
                nprobe=mips_nprobe, int4_dim=int4_dim,
                seed=int(getattr(config, "seed", 0)), log=self.log)
            k = min(int(meta["topk"]),
                    int(dims["real_target_vocab_size"]))
            self._mips_step = make_release_step(
                meta, mips_topk=self.mips_head.topk_fn(k, mips_nprobe))
            mode = ("all batches" if self._mips_all
                    else f"batches with <= {self.mips_rows} live rows "
                         f"(exact blockwise head above)")
            self.log(f"Approximate-MIPS head active for {mode}: nprobe "
                     f"{self.mips_head.nprobe}/{self.mips_head.nlist} "
                     f"lists per prediction (MIPS steps always jit — "
                     f"the AOT lowerings bake the exact head)")
        self._predict_steps: Dict[Tuple[int, int], object] = {}
        # MIPS steps cached apart from the exact `_predict_steps` so
        # compile-count surfaces (healthz, quant_bench) keep counting
        # exact serve shapes, and each head's compile budget stays
        # <= len(buckets) per rows shape.
        self._mips_predict_steps: Dict[Tuple[int, int], object] = {}
        self.aot_loads = {"aot": 0, "jit_fallback": 0, "jit_error": 0}
        self.log(
            f"Release model loaded from {self.artifact.path}: scheme="
            f"{self.artifact.scheme}, tables "
            f"{self.artifact.table_bytes() / 1e6:.1f} MB, buckets "
            f"{list(self._context_buckets)}, fingerprint "
            f"{self.artifact.fingerprint[:12]}, aot="
            f"{'none' if not meta.get('aot') else meta['aot']['platform']}")

    @property
    def context_buckets(self) -> Tuple[int, ...]:
        return self._context_buckets

    def _default_predict_batch_size(self) -> int:
        """Default predict chunks to the serve batch size (the
        artifact's AOT-exported rows unless --serve_batch_size
        overrode it): `--predict --artifact` and offline predict then
        cold-start from the shipped lowerings instead of tracing a
        (test_batch_size, bucket) shape the AOT store never saw."""
        return int(self.config.serve_batch_size)

    def model_fingerprint(self) -> str:
        return f"artifact:{self.artifact.fingerprint[:16]}"

    # ------------------------------------------------- predict plumbing

    def _make_predict_step(self, batch_rows: int, m: int):
        if self._mips_all:
            return jax.jit(self._mips_step)
        aot = self.meta.get("aot") or {}
        path = self.artifact.aot_path(batch_rows, m)
        if path is not None and _backend_matches(
                jax.default_backend(),
                aot.get("platforms") or [aot.get("platform")]):
            try:
                from jax import export as jax_export
                with open(path, "rb") as f:
                    exported = jax_export.deserialize(bytearray(f.read()))
                # jit around .call caches the (opaque-body) executable so
                # repeat calls skip the export calling-convention shim.
                step = jax.jit(exported.call)
                # Deserializing alone does not prove the lowering runs
                # here — version/platform skew can surface at first
                # execution, which happens inside the batcher dispatch
                # where nothing catches it. Run the step once now so a
                # stale lowering lands in this except and degrades to
                # jit instead of erroring every request on this bucket.
                jax.block_until_ready(
                    step(self.params, *self._dummy_batch(batch_rows, m)))
                self.aot_loads["aot"] += 1
                _aot_counter("aot").inc()
                return step
            except Exception as e:  # noqa: BLE001 — a stale lowering
                # must degrade to jit, never take the replica down
                self.aot_loads["jit_error"] += 1
                _aot_counter("jit_error").inc()
                self.log(f"AOT lowering {path} unusable "
                         f"({type(e).__name__}: {e}); jit fallback")
        else:
            self.aot_loads["jit_fallback"] += 1
            _aot_counter("jit_fallback").inc()
        return jax.jit(self._step_fn)

    def _get_mips_predict_step(self, rows: int, m: int):
        key = (rows, m)
        step = self._mips_predict_steps.get(key)
        if step is None:
            step = self._mips_predict_steps[key] = jax.jit(self._mips_step)
            self.log(f"Compiled MIPS predict step for shape "
                     f"(rows={rows}, contexts={m})")
        return step

    def _dispatch_predict_step(self, n: int, batch_rows: int, m: int):
        """Per-batch-shape head dispatch: batches whose LIVE row count
        is at or below the resolved crossover route to the MIPS head
        compiled at the crossover shape (small batches repad down, so
        a lone interactive row never pays the bulk shape's exact
        scan); everything else takes the exact blockwise head at the
        serve shape. All-MIPS and exact-only modes degenerate to the
        single-head behaviour."""
        if self._mips_all:
            return (self._get_bucketed_predict_step(batch_rows, m),
                    batch_rows, "mips")
        if 0 < n <= self.mips_rows:
            return (self._get_mips_predict_step(self.mips_rows, m),
                    self.mips_rows, "mips")
        return (self._get_bucketed_predict_step(batch_rows, m),
                batch_rows, "exact")

    @staticmethod
    def _dummy_batch(rows: int, m: int):
        """All-padding batch of one serve shape (AOT validation, warmup)."""
        return (jnp.zeros((rows, m), jnp.int32),
                jnp.zeros((rows, m), jnp.int32),
                jnp.zeros((rows, m), jnp.int32),
                jnp.ones((rows, m), jnp.float32),
                jnp.zeros((rows,), jnp.int32),
                jnp.ones((rows,), bool))

    def _call_predict_step(self, step, arrays):
        return EvalOutputs(*step(self.params, *arrays))

    # ------------------------------------------------------------- eval

    def eval_step(self, _params_unused, *arrays) -> EvalOutputs:
        """Evaluator-compatible signature: the standard Evaluator can
        score an artifact (quality-delta benches) — params come from the
        artifact, the first argument is accepted and ignored."""
        rows, m = arrays[0].shape
        step = self._get_bucketed_predict_step(rows, m)
        return self._call_predict_step(step, arrays)

    def eval_callable(self):
        """(eval_step, params) — the facade's surface for direct eval
        drivers (Evaluator, retrieval/embed_job.py). Params are the
        artifact's, bound inside `eval_step`, so the slot is None."""
        return self.eval_step, None

    def evaluate(self):
        """Score the artifact on config.test_data_path with the
        reference-definition metrics (the facade `--test` surface for a
        release bundle; `--artifact DIR --test data.c2v` in the CLI)."""
        from code2vec_tpu.evaluation.evaluator import Evaluator
        config = self.config
        config.num_test_examples = self._count_examples(
            config.test_data_path)
        evaluator = Evaluator(config, self.vocabs, self.eval_step,
                              mesh=None)
        return evaluator.evaluate(None, self._eval_batches())

    def warmup(self, rows: Optional[int] = None) -> float:
        """Build + run every (rows, bucket) serve shape once on a dummy
        batch; returns wall seconds. This is the replica cold-start the
        AOT store exists to shrink (measured in quant_bench)."""
        rows = int(rows or self.config.serve_batch_size)
        t0 = time.perf_counter()
        for m in self.context_buckets:
            step = self._get_bucketed_predict_step(rows, m)
            out = self._call_predict_step(step, self._dummy_batch(rows, m))
            jax.block_until_ready(out.topk_indices)
            if self.mips_rows > 0:
                # hybrid dispatch: small batches take the MIPS head at
                # the crossover shape — warm it too or the first
                # interactive request pays the jit it was routed to
                # avoid
                step = self._get_mips_predict_step(self.mips_rows, m)
                out = self._call_predict_step(
                    step, self._dummy_batch(self.mips_rows, m))
                jax.block_until_ready(out.topk_indices)
        return time.perf_counter() - t0


def calibrate_mips_crossover(artifact_dir: str, config, log=print):
    """Export-time head-crossover calibration: load the just-written
    artifact, time the exact blockwise head against the MIPS head on
    dummy batches over a small rows grid (one context bucket — the
    crossover is a rows property; per-context cost scales both heads
    alike), and return `(crossover, table)` where crossover is the
    largest row count at which MIPS still wins (0 if it never does,
    scanning stops at the first exact-head win so a noisy outlier deep
    in bulk territory cannot stretch the threshold). The exporter
    records the value as meta["mips_crossover"]; serving adopts it via
    --serve_mips_crossover -1. Timings are median-of-3 after a warmup
    execution, so jit/compile cost never pollutes the comparison."""
    import dataclasses

    nprobe = int(getattr(config, "serve_mips_nprobe", 0) or 0) or 8
    cfg = dataclasses.replace(
        config, serve_artifact=artifact_dir, serve_mips_nprobe=nprobe,
        serve_mips_crossover=1)  # hybrid: both heads live + both tables
    model = ReleaseModel(cfg, log=log)
    bs = int(cfg.serve_batch_size)
    grid = sorted({r for r in (1, 2, 4, 8, 16, bs) if 1 <= r <= bs})
    m = model.context_buckets[0]
    table, crossover = {}, 0
    for rows in grid:
        batch = model._dummy_batch(rows, m)
        timing = {}
        for head, step in (
                ("exact", model._get_bucketed_predict_step(rows, m)),
                ("mips", model._get_mips_predict_step(rows, m))):
            jax.block_until_ready(
                model._call_predict_step(step, batch).topk_indices)
            samples = []
            for _ in range(3):
                t0 = time.perf_counter()
                jax.block_until_ready(
                    model._call_predict_step(step, batch).topk_indices)
                samples.append(time.perf_counter() - t0)
            timing[head] = sorted(samples)[1]
        table[str(rows)] = {k: round(v * 1e6, 1) for k, v in timing.items()}
        if timing["mips"] < timing["exact"]:
            crossover = rows
        else:
            break
    log(f"MIPS crossover calibration (nprobe {nprobe}, bucket {m}): "
        f"crossover={crossover} over rows grid {grid} "
        f"(us medians: {table})")
    return crossover, table
