"""Release artifacts: self-contained quantized inference bundles.

`artifact.py` writes/loads the on-disk bundle (int8 tables + per-row
scales, vocabularies, AOT serve lowerings, meta); `runtime.py` is the
serving/eval fast path that consumes one without ever building the fp32
training state.
"""

from code2vec_tpu.release.artifact import (  # noqa: F401
    ArtifactError, ReleaseArtifact, export_artifact, is_release_artifact,
    load_artifact,
)
