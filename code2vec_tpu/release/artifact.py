"""Release-artifact writer/loader: the deployable inference bundle.

A release artifact is a directory that carries EVERYTHING a serving
replica needs — no checkpoint, no optimizer state, no training config:

    release_meta.json       format/quantization/dims/buckets/source/
                            fingerprint (see _build_meta)
    dictionaries.bin        the three vocabularies (reference sidecar
                            format, vocab.py)
    token_embedding.npy     int8 (V, D) / uint8 fp8 bit patterns (V, D)
                            / uint8 int4-packed (V, ceil(D/2)) — or f32
                            for --no_quantize (scheme in the meta)
    token_embedding.scale.npy   f32 (V, 1) per-row symmetric scales
    path_embedding[.scale].npy
    target_embedding[.scale].npy
    transform.npy           f32 (3d, d) — small dense params stay f32
    attention.npy           f32 (d, 1)
    aot/serve_r<rows>_m<m>.jaxexport   serialized jax.export lowerings,
                            one per (serve_batch_size, context bucket)

Quantization is per-row symmetric (ops/quant.py), scheme selectable at
export (`--release_scheme int8|fp8_e4m3|fp8_e5m2|int4`): int8 drops the
three tables ~3.9x at the flagship shape (1 byte/weight + 4 bytes/row),
fp8 keeps the byte count with a relative error profile, int4 packs two
weights per byte for another ~2x — which is both the artifact's
disk/RSS footprint and, because the hot ops are bandwidth-bound
(BENCH_ROOFLINE.md), the serve step's HBM traffic. Quality deltas per
scheme are measured same-run vs fp32 in BENCH_QUANT.md.

Every load validates `kind`/`format`/table dtypes against the declared
scheme and raises ArtifactError naming the offending field; pointing
the fp32 checkpoint loader (--load) at an artifact is rejected up front
in model_facade with the same named-field treatment, so a quantized
bundle can never be silently misread as fp32 garbage.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, Optional

import numpy as np

META_NAME = "release_meta.json"
DICT_NAME = "dictionaries.bin"
AOT_DIR = "aot"
ARTIFACT_FORMAT = 1
ARTIFACT_KIND = "code2vec_release_artifact"
SCHEME_INT8 = "int8_rowwise_symmetric"
SCHEME_FP8_E4M3 = "fp8_e4m3_rowwise"
SCHEME_FP8_E5M2 = "fp8_e5m2_rowwise"
SCHEME_INT4 = "int4_rowwise_packed"
SCHEME_FP32 = "float32"
# Every scheme the loader/runtime understand; the quantized ones carry
# per-row f32 scales. fp8/int4 payloads are stored as uint8 npy files
# (fp8 = bit patterns — numpy's mmap path cannot represent ml_dtypes;
# int4 = two nibbles per byte), decoded by the runtime (ops/quant.py).
QUANTIZED_SCHEMES = (SCHEME_INT8, SCHEME_FP8_E4M3, SCHEME_FP8_E5M2,
                     SCHEME_INT4)
ALL_SCHEMES = QUANTIZED_SCHEMES + (SCHEME_FP32,)
# --release_scheme CLI vocabulary -> on-disk scheme name.
SCHEME_BY_KNOB = {
    "int8": SCHEME_INT8,
    "fp8_e4m3": SCHEME_FP8_E4M3,
    "fp8_e5m2": SCHEME_FP8_E5M2,
    "int4": SCHEME_INT4,
    "float32": SCHEME_FP32,
}

_TABLES = ("token_embedding", "path_embedding", "target_embedding")
_DENSE = ("transform", "attention")


def _quantize_table(table: "np.ndarray", scheme: str):
    """(payload, scales-or-None) for one table under `scheme`."""
    from code2vec_tpu.ops import quant
    if scheme == SCHEME_INT8:
        return quant.quantize_rows(table)
    if scheme == SCHEME_FP8_E4M3:
        return quant.quantize_rows_fp8(table, "e4m3")
    if scheme == SCHEME_FP8_E5M2:
        return quant.quantize_rows_fp8(table, "e5m2")
    if scheme == SCHEME_INT4:
        return quant.quantize_rows_int4(table)
    assert scheme == SCHEME_FP32, scheme
    return table, None


class ArtifactError(ValueError):
    """Artifact rejected with the offending meta/table field named, so a
    bad deploy fails at load with a pointer instead of serving garbage."""

    def __init__(self, field: str, message: str):
        super().__init__(f"release artifact field `{field}`: {message}")
        self.field = field


@dataclasses.dataclass
class ReleaseArtifact:
    path: str
    meta: dict
    tables: Dict[str, np.ndarray]   # name -> array; int8 tables carry a
    #                                 sibling "<name>.scale" f32 entry

    @property
    def scheme(self) -> str:
        return self.meta["quantization"]["scheme"]

    @property
    def fingerprint(self) -> str:
        return self.meta["fingerprint"]

    @property
    def dictionaries_path(self) -> str:
        return os.path.join(self.path, DICT_NAME)

    def aot_path(self, rows: int, m: int) -> Optional[str]:
        entries = (self.meta.get("aot") or {}).get("entries", {})
        rel = entries.get(f"r{rows}_m{m}")
        if rel is None:
            return None
        p = os.path.join(self.path, rel)
        return p if os.path.isfile(p) else None

    def table_bytes(self) -> int:
        return sum(a.nbytes for a in self.tables.values())


def is_release_artifact(path: str) -> bool:
    return os.path.isfile(os.path.join(path, META_NAME))


def _content_fingerprint(payloads: Dict[str, np.ndarray], meta: dict) -> str:
    """sha256 over the table payloads + the identity-bearing meta core.
    Stable across re-serialization of the json (the hash covers values,
    not formatting) and across AOT re-export (lowerings are a cache of
    the tables + dims, not independent identity). Hashes the in-memory
    arrays the exporter just wrote — the loader never recomputes this,
    so re-reading a flagship-scale bundle off disk just to hash it
    would double the export I/O for nothing."""
    h = hashlib.sha256()
    core = {k: meta[k] for k in ("kind", "format", "quantization", "dims",
                                 "max_contexts", "compute_dtype")}
    h.update(json.dumps(core, sort_keys=True).encode())
    for name in sorted(payloads):
        arr = np.ascontiguousarray(payloads[name])
        h.update(f"{name}:{arr.dtype}:{arr.shape}".encode())
        h.update(arr.data)
    return h.hexdigest()


def export_artifact(model, out_dir: str, *, quantize: Optional[bool] = None,
                    aot: Optional[bool] = None,
                    scheme: Optional[str] = None, log=None) -> dict:
    """Write a release artifact from a live facade model. Returns the
    meta dict (with the content fingerprint filled in). `scheme` is an
    on-disk scheme name (ALL_SCHEMES); unset, it follows
    config.release_scheme with `quantize`/--no_quantize forcing fp32."""
    import jax

    config = model.config
    log = log or config.log
    quantize = config.release_quantize if quantize is None else quantize
    aot = config.release_aot if aot is None else aot
    if scheme is None:
        knob = getattr(config, "release_scheme", "int8")
        if knob not in SCHEME_BY_KNOB:
            raise ValueError(f"release_scheme must be one of "
                             f"{sorted(SCHEME_BY_KNOB)}, got {knob!r}")
        scheme = SCHEME_BY_KNOB[knob] if quantize else SCHEME_FP32
    if scheme not in ALL_SCHEMES:
        raise ValueError(f"unknown artifact scheme {scheme!r} "
                         f"(one of {ALL_SCHEMES})")
    os.makedirs(out_dir, exist_ok=True)

    params = {k: np.asarray(jax.device_get(v))
              for k, v in model.state.params.items()}
    fp32_bytes = sum(params[t].nbytes for t in _TABLES)
    written = 0
    payloads: Dict[str, np.ndarray] = {}
    for name in _TABLES:
        table = params[name].astype(np.float32)
        scale_path = os.path.join(out_dir, f"{name}.scale.npy")
        q, scales = _quantize_table(table, scheme)
        np.save(os.path.join(out_dir, f"{name}.npy"), q)
        written += q.nbytes
        payloads[name] = q
        if scales is not None:
            np.save(scale_path, scales)
            written += scales.nbytes
            payloads[f"{name}.scale"] = scales
        elif os.path.exists(scale_path):
            # A prior quantized export into the same dir leaves scale
            # files behind; the loader reads whatever scale files
            # exist, so stale ones must go with the tables they
            # described.
            os.remove(scale_path)
    for name in _DENSE:
        arr = params[name].astype(np.float32)
        np.save(os.path.join(out_dir, f"{name}.npy"), arr)
        payloads[name] = arr
    # Stale lowerings from a prior export must never ride along with
    # fresh tables (meta's aot entries are rewritten below either way;
    # this keeps the on-disk bundle == what the meta describes).
    stale_aot = os.path.join(out_dir, AOT_DIR)
    if os.path.isdir(stale_aot):
        import shutil
        shutil.rmtree(stale_aot)

    model.vocabs.save(os.path.join(out_dir, DICT_NAME))

    dims = model.dims
    meta = {
        "kind": ARTIFACT_KIND,
        "format": ARTIFACT_FORMAT,
        "quantization": {"scheme": scheme},
        "dims": {
            "token_vocab_size": dims.token_vocab_size,
            "path_vocab_size": dims.path_vocab_size,
            "target_vocab_size": dims.target_vocab_size,
            "real_target_vocab_size": dims.real_target_vocab_size,
            "token_dim": dims.token_dim,
            "path_dim": dims.path_dim,
            "target_oov_floor": dims.target_oov_floor,
        },
        "separate_oov_and_pad": config.separate_oov_and_pad,
        "compute_dtype": config.compute_dtype,
        "max_contexts": config.max_contexts,
        "topk": config.top_k_words_considered_during_prediction,
        "topk_block_size": config.topk_block_size,
        "serve_batch_size": config.serve_batch_size,
        "buckets": list(model.context_buckets),
        "source": {
            "checkpoint": (os.path.abspath(config.model_load_path)
                           if config.model_load_path else None),
            "step": int(jax.device_get(model.state.step)),
            "epoch": getattr(model, "initial_epoch", None),
        },
        "table_bytes": {"fp32": fp32_bytes, "artifact": written},
        "aot": None,
    }
    meta["fingerprint"] = _content_fingerprint(payloads, meta)

    if aot:
        from code2vec_tpu.release.runtime import aot_export_serve_functions
        meta["aot"] = aot_export_serve_functions(out_dir, meta, log=log)

    with open(os.path.join(out_dir, META_NAME), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
        f.write("\n")

    # Head-crossover calibration (hybrid exact/MIPS dispatch): when the
    # exporter is configured for a MIPS head, time both heads across a
    # small rows grid and record the largest MIPS-winning row count so
    # serving replicas with --serve_mips_crossover -1 can adopt it.
    # Runs after the meta is on disk (the calibrator loads the bundle
    # like a replica would) and after the fingerprint is fixed — the
    # fingerprint core never covers mips_crossover, so calibrated and
    # uncalibrated exports of the same tables stay byte-identical in
    # identity.
    if (int(getattr(config, "serve_mips_nprobe", 0) or 0) > 0
            and int(getattr(config, "serve_mips_crossover", -1)) != 0):
        from code2vec_tpu.release.runtime import calibrate_mips_crossover
        crossover, cal_table = calibrate_mips_crossover(
            out_dir, config, log=log)
        meta["mips_crossover"] = crossover
        meta["mips_calibration"] = cal_table
        with open(os.path.join(out_dir, META_NAME), "w") as f:
            json.dump(meta, f, indent=2, sort_keys=True)
            f.write("\n")

    log(f"Exported release artifact to {out_dir}: scheme={scheme}, "
        f"tables {fp32_bytes / 1e6:.1f} MB fp32 -> {written / 1e6:.1f} MB "
        f"({fp32_bytes / max(written, 1):.2f}x smaller), "
        f"aot={'on' if meta['aot'] else 'off'}, "
        f"fingerprint {meta['fingerprint'][:12]}")
    return meta


def _expected_dtype(scheme: str, name: str) -> np.dtype:
    if name.endswith(".scale") or name in _DENSE:
        return np.dtype(np.float32)
    if scheme == SCHEME_INT8:
        return np.dtype(np.int8)
    if scheme in (SCHEME_FP8_E4M3, SCHEME_FP8_E5M2, SCHEME_INT4):
        # fp8 bit patterns / packed nibbles both travel as uint8 bytes
        return np.dtype(np.uint8)
    return np.dtype(np.float32)


def table_dim(dims: dict, name: str) -> int:
    """Unpacked (model-side) column count of one embedding table."""
    d_tok, d_path = int(dims["token_dim"]), int(dims["path_dim"])
    return {"token_embedding": d_tok, "path_embedding": d_path,
            "target_embedding": d_path + 2 * d_tok}[name]


def _expected_shape(dims: dict, name: str,
                    scheme: str = SCHEME_FP32) -> tuple:
    """Declared shape of each payload per meta["dims"]. Shape drift must
    fail at load: a truncated table would otherwise serve silently-wrong
    rows (jnp.take clamps out-of-bounds ids under jit). int4-packed
    tables store two columns per byte."""
    d_tok, d_path = int(dims["token_dim"]), int(dims["path_dim"])
    code_dim = d_path + 2 * d_tok
    shape = {
        "token_embedding": (int(dims["token_vocab_size"]), d_tok),
        "path_embedding": (int(dims["path_vocab_size"]), d_path),
        "target_embedding": (int(dims["target_vocab_size"]), code_dim),
        "transform": (code_dim, code_dim),
        "attention": (code_dim, 1),
    }[name]
    if scheme == SCHEME_INT4 and name in _TABLES:
        return (shape[0], (shape[1] + 1) // 2)
    return shape


def load_artifact(path: str,
                  expect_scheme: Optional[str] = None) -> ReleaseArtifact:
    """Load + validate a release artifact. Tables are memory-mapped (the
    flagship int8 bundle is ~100 MB; serving moves it to device once).

    `expect_scheme` lets a caller that can only consume one flavor fail
    with a named-field error instead of misreading the payload — e.g.
    an fp32-only consumer handed an int8 bundle."""
    base = os.path.abspath(path)
    meta_path = os.path.join(base, META_NAME)
    if not os.path.isfile(meta_path):
        raise ArtifactError(
            "kind", f"{base} is not a release artifact ({META_NAME} "
            f"missing); checkpoints are served via --load, artifacts "
            f"are produced by the `export` subcommand")
    with open(meta_path) as f:
        try:
            meta = json.load(f)
        except json.JSONDecodeError as e:
            raise ArtifactError("kind", f"unparseable {META_NAME}: {e}")
    if meta.get("kind") != ARTIFACT_KIND:
        raise ArtifactError("kind", f"expected {ARTIFACT_KIND!r}, "
                                    f"got {meta.get('kind')!r}")
    if int(meta.get("format", -1)) > ARTIFACT_FORMAT:
        raise ArtifactError(
            "format", f"artifact format {meta.get('format')} is newer "
            f"than this build understands (<= {ARTIFACT_FORMAT})")
    scheme = (meta.get("quantization") or {}).get("scheme")
    if scheme not in ALL_SCHEMES:
        raise ArtifactError(
            "quantization.scheme",
            f"unknown scheme {scheme!r} (this build understands "
            f"{list(ALL_SCHEMES)})")
    if expect_scheme is not None and scheme != expect_scheme:
        raise ArtifactError(
            "quantization.scheme",
            f"artifact is quantized as {scheme!r} but the caller "
            f"requires {expect_scheme!r}; re-export with "
            f"{'--no_quantize' if expect_scheme == SCHEME_FP32 else 'the matching --release_scheme'} "
            f"or use a consumer that dequantizes")
    if "fingerprint" not in meta:
        raise ArtifactError("fingerprint", "missing (torn export?)")
    # Every meta field the runtime consumes (make_release_step,
    # ReleaseModel.__init__) must be present HERE: a torn or hand-edited
    # meta otherwise passes load and dies later with a bare KeyError,
    # breaking the named-field contract in the module docstring.
    for key in ("compute_dtype", "topk", "serve_batch_size",
                "max_contexts", "separate_oov_and_pad", "buckets"):
        if key not in meta:
            raise ArtifactError(
                key, f"missing from {META_NAME} (torn or hand-edited "
                     f"export?)")
    if not os.path.isfile(os.path.join(base, DICT_NAME)):
        raise ArtifactError("dictionaries", f"{DICT_NAME} missing")
    dims = meta.get("dims") or {}
    missing = {"token_vocab_size", "path_vocab_size", "target_vocab_size",
               "real_target_vocab_size", "target_oov_floor",
               "token_dim", "path_dim"} - dims.keys()
    if missing:
        raise ArtifactError("dims", f"missing field(s) {sorted(missing)}")

    tables: Dict[str, np.ndarray] = {}
    for name in _TABLES + _DENSE:
        p = os.path.join(base, f"{name}.npy")
        if not os.path.isfile(p):
            raise ArtifactError(name, "table file missing")
        arr = np.load(p, mmap_mode="r")
        want = _expected_dtype(scheme, name)
        if arr.dtype != want:
            raise ArtifactError(
                f"{name}.dtype",
                f"expected {want} under quantization.scheme={scheme}, "
                f"file holds {arr.dtype}")
        want_shape = _expected_shape(meta.get("dims") or {}, name, scheme)
        if tuple(arr.shape) != want_shape:
            raise ArtifactError(
                f"{name}.shape",
                f"expected {want_shape} per meta dims, file holds "
                f"{tuple(arr.shape)}")
        tables[name] = arr
        if scheme in QUANTIZED_SCHEMES and name in _TABLES:
            sp = os.path.join(base, f"{name}.scale.npy")
            if not os.path.isfile(sp):
                raise ArtifactError(f"{name}.scale", "scale file missing")
            scales = np.load(sp, mmap_mode="r")
            if scales.dtype != np.float32 or scales.shape != (arr.shape[0], 1):
                raise ArtifactError(
                    f"{name}.scale",
                    f"expected float32 ({arr.shape[0]}, 1), got "
                    f"{scales.dtype} {scales.shape}")
            tables[f"{name}.scale"] = scales
    return ReleaseArtifact(path=base, meta=meta, tables=tables)
