"""Per-row symmetric int8 quantization for the embedding tables.

The flagship shape is 227-383M params dominated by three embedding
tables and the ~246K-name target classifier, and every hot op that
touches them is memory-bandwidth-bound (BENCH_ROOFLINE.md): int8 storage
moves one byte per weight instead of four through HBM, with the dequant
fused into the consuming op — gathers multiply the gathered rows by
their scales (ops below), the classifier matmul dequants its block
logits after f32 accumulation (ops/topk.py blockwise_matmul_top_k).

Scheme: per-row symmetric absmax. For row r with scale
s_r = max|w_r| / 127, q = round(w / s_r) in [-127, 127]; dequant is
q * s_r. No zero-point (embedding rows are ~zero-centered by init and
training), so the dequant stays a single fused multiply. Worst-case
round-trip error is s_r / 2 per element, pinned in tests/test_quant.py;
the end-to-end quality delta is measured on the accuracy bench by
experiments/quant_bench.py (BENCH_QUANT.md).

All-zero rows (never-touched vocab tail, padding rows) get scale 0 and
quantize to exact zeros; the dequant multiply reproduces them exactly.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

QMAX = 127


def quantize_rows(table: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side quantizer: f32 (V, D) -> (int8 (V, D), f32 scales (V, 1)).

    Runs in numpy (export is an offline host job; the tables may be
    bigger than comfortable to round-trip through the device twice).
    """
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"quantize_rows expects a 2-D table, "
                         f"got shape {table.shape}")
    absmax = np.abs(table).max(axis=1, keepdims=True)
    scales = (absmax / QMAX).astype(np.float32)
    # 0-scale rows are exact zeros; guard the divide, not the result.
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.rint(table / safe), -QMAX, QMAX).astype(np.int8)
    return q, scales


def dequantize_rows(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Host-side inverse of quantize_rows (bench/analysis utility)."""
    return q.astype(np.float32) * np.asarray(scales, np.float32)


def dequant_gather(q_table: jax.Array, scales: jax.Array,
                   ids: jax.Array) -> jax.Array:
    """Gather rows of an int8 table by id with fused dequant:
    (..., D) f32. The gather moves int8 bytes; the per-row scale
    multiply happens on the gathered (batch-sized) rows, never on the
    full table."""
    rows = jnp.take(q_table, ids, axis=0).astype(jnp.float32)
    s = jnp.take(scales[:, 0], ids, axis=0)
    return rows * s[..., None]


def table_gather(table: jax.Array, scales: Optional[jax.Array],
                 ids: jax.Array) -> jax.Array:
    """Scheme-agnostic gather: int8 tables carry scales, f32 tables
    pass scales=None (plain take). One call site serves both release
    artifact flavors (release/runtime.py)."""
    if scales is None:
        return jnp.take(table, ids, axis=0)
    return dequant_gather(table, scales, ids)
