"""Per-row quantization schemes for the embedding tables: int8, fp8
(e4m3/e5m2) and sub-byte int4 (two weights per byte).

The flagship shape is 227-383M params dominated by three embedding
tables and the ~246K-name target classifier, and every hot op that
touches them is memory-bandwidth-bound (BENCH_ROOFLINE.md): quantized
storage moves 1 byte (int8/fp8) or half a byte (int4) per weight instead
of four through HBM, with the dequant fused into the consuming op —
gathers multiply the gathered rows by their scales (ops below), the
classifier matmul dequants its block logits after f32 accumulation
(ops/topk.py blockwise_matmul_top_k).

Schemes (all per-row symmetric, no zero point — embedding rows are
~zero-centered by init and training, so dequant stays one fused
multiply; all-zero rows get scale 0 and reproduce exactly):

- **int8** (`quantize_rows`): s_r = max|w_r| / 127, q = round(w/s_r) in
  [-127, 127]. Worst-case round-trip error s_r/2 per element.
- **fp8 e4m3 / e5m2** (`quantize_rows_fp8`): s_r = max|w_r| / FP8_MAX,
  payload = (w/s_r) cast to the fp8 format. Same byte count as int8 but
  a RELATIVE error profile (~2^-3 of magnitude for e4m3, ~2^-2 for
  e5m2) instead of int8's absolute s_r/2: small-magnitude elements of a
  heavy-tailed row round proportionally instead of to a fixed grid.
  Stored on disk / moved through HBM as uint8 bit patterns (numpy's
  .npy mmap path cannot represent ml_dtypes; the bitcast is free).
- **int4 packed** (`quantize_rows_int4`): s_r = max|w_r| / 7, q =
  round(w/s_r) in [-7, 7], stored offset-binary (q+8, one nibble) two
  per uint8 byte — HALF the bytes of int8 (the ~2x the release
  artifact's int8 tables still leave on the table, BENCH_QUANT.md).
  Worst-case round-trip error s_r/2 with s_r 18x coarser than int8's;
  the end-to-end quality delta is measured same-run vs fp32 by
  experiments/quant_bench.py.

Error bounds are pinned in tests/test_quant.py; end-to-end quality
deltas live in BENCH_QUANT.md (same-run fp32 discipline).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import ml_dtypes
import numpy as np

QMAX = 127
INT4_QMAX = 7
FP8_FORMATS = {
    "e4m3": ml_dtypes.float8_e4m3fn,
    "e5m2": ml_dtypes.float8_e5m2,
}
FP8_MAX = {fmt: float(ml_dtypes.finfo(dt).max)
           for fmt, dt in FP8_FORMATS.items()}


def quantize_rows(table: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side int8 quantizer: f32 (V, D) -> (int8 (V, D), f32 scales
    (V, 1)).

    Runs in numpy (export is an offline host job; the tables may be
    bigger than comfortable to round-trip through the device twice).
    """
    table = _check_2d(table)
    scales = _row_scales(table, QMAX)
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.rint(table / safe), -QMAX, QMAX).astype(np.int8)
    return q, scales


def quantize_rows_fp8(table: np.ndarray, fmt: str = "e4m3"
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side fp8 quantizer: f32 (V, D) -> (uint8 bit patterns
    (V, D), f32 scales (V, 1)). The payload is the fp8 encoding of
    w / s_r viewed as uint8 (see module docstring for why bytes)."""
    if fmt not in FP8_FORMATS:
        raise ValueError(f"fp8 format must be one of "
                         f"{sorted(FP8_FORMATS)}, got {fmt!r}")
    table = _check_2d(table)
    scales = _row_scales(table, FP8_MAX[fmt])
    safe = np.where(scales > 0, scales, 1.0)
    q = (table / safe).astype(FP8_FORMATS[fmt])
    return q.view(np.uint8), scales


def quantize_rows_int4(table: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side packed-int4 quantizer: f32 (V, D) -> (uint8
    (V, ceil(D/2)), f32 scales (V, 1)). Nibble n of byte b holds column
    2b+n as offset-binary q+8 (q in [-7, 7]); an odd trailing column is
    padded with the encoding of 0 (decoded then sliced off by
    `unpack_int4`)."""
    table = _check_2d(table)
    scales = _row_scales(table, INT4_QMAX)
    safe = np.where(scales > 0, scales, 1.0)
    q = np.clip(np.rint(table / safe), -INT4_QMAX, INT4_QMAX)
    u = (q + 8).astype(np.uint8)
    if u.shape[1] % 2:
        u = np.concatenate(
            [u, np.full((u.shape[0], 1), 8, np.uint8)], axis=1)
    return (u[:, 0::2] | (u[:, 1::2] << 4)), scales


def _check_2d(table: np.ndarray) -> np.ndarray:
    table = np.asarray(table, np.float32)
    if table.ndim != 2:
        raise ValueError(f"row quantizers expect a 2-D table, "
                         f"got shape {table.shape}")
    return table


def _row_scales(table: np.ndarray, qmax: float) -> np.ndarray:
    absmax = np.abs(table).max(axis=1, keepdims=True)
    # 0-scale rows are exact zeros; consumers guard the divide.
    return (absmax / qmax).astype(np.float32)


def dequantize_rows(q: np.ndarray, scales: np.ndarray) -> np.ndarray:
    """Host-side inverse of quantize_rows (bench/analysis utility)."""
    return q.astype(np.float32) * np.asarray(scales, np.float32)


def dequantize_rows_fp8(q: np.ndarray, scales: np.ndarray,
                        fmt: str = "e4m3") -> np.ndarray:
    """Host-side inverse of quantize_rows_fp8 (uint8 bit patterns in)."""
    f = np.asarray(q).view(FP8_FORMATS[fmt]).astype(np.float32)
    return f * np.asarray(scales, np.float32)


def unpack_int4_host(packed: np.ndarray, dim: int) -> np.ndarray:
    """Host-side nibble unpack: uint8 (V, ceil(dim/2)) -> int8 (V, dim)
    in [-7, 7]."""
    packed = np.asarray(packed, np.uint8)
    lo = (packed & 0xF).astype(np.int8) - 8
    hi = (packed >> 4).astype(np.int8) - 8
    out = np.empty((packed.shape[0], packed.shape[1] * 2), np.int8)
    out[:, 0::2] = lo
    out[:, 1::2] = hi
    return out[:, :dim]


def dequantize_rows_int4(packed: np.ndarray, scales: np.ndarray,
                         dim: int) -> np.ndarray:
    """Host-side inverse of quantize_rows_int4."""
    return (unpack_int4_host(packed, dim).astype(np.float32)
            * np.asarray(scales, np.float32))


# ------------------------------------------------------- device (jax) side


def unpack_int4(packed: jax.Array, dim: int) -> jax.Array:
    """Nibble unpack inside a jitted consumer: uint8 (..., ceil(dim/2))
    -> f32 (..., dim). Runs on the gathered/sliced (batch- or
    block-sized) rows, never on the full table — the table moves
    through HBM packed."""
    lo = (packed & 0xF).astype(jnp.int32) - 8
    hi = (packed >> 4).astype(jnp.int32) - 8
    out = jnp.stack([lo, hi], axis=-1).reshape(
        packed.shape[:-1] + (packed.shape[-1] * 2,))
    return out[..., :dim].astype(jnp.float32)


def dequant_gather(q_table: jax.Array, scales: jax.Array,
                   ids: jax.Array) -> jax.Array:
    """Gather rows of an int8 (or fp8-viewed) table by id with fused
    dequant: (..., D) f32. The gather moves quantized bytes; the
    per-row scale multiply happens on the gathered (batch-sized) rows,
    never on the full table."""
    rows = jnp.take(q_table, ids, axis=0).astype(jnp.float32)
    s = jnp.take(scales[:, 0], ids, axis=0)
    return rows * s[..., None]


def dequant_gather_int4(packed_table: jax.Array, scales: jax.Array,
                        ids: jax.Array, dim: int) -> jax.Array:
    """int4 flavor of `dequant_gather`: gather PACKED uint8 rows (half
    the HBM bytes of int8), unpack + scale on the gathered result."""
    rows = unpack_int4(jnp.take(packed_table, ids, axis=0), dim)
    s = jnp.take(scales[:, 0], ids, axis=0)
    return rows * s[..., None]


def table_gather(table: jax.Array, scales: Optional[jax.Array],
                 ids: jax.Array, *, int4_dim: Optional[int] = None
                 ) -> jax.Array:
    """Scheme-agnostic gather: f32 tables pass scales=None (plain take);
    int8/fp8 tables carry scales; int4-packed tables additionally pass
    their unpacked `int4_dim`. One call site serves every release
    artifact flavor (release/runtime.py)."""
    if scales is None:
        return jnp.take(table, ids, axis=0)
    if int4_dim is not None:
        return dequant_gather_int4(table, scales, ids, int4_dim)
    return dequant_gather(table, scales, ids)
